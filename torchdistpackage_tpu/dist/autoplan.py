"""Auto-sharding planner — close the loop from cost models to a plan.

The repo owns three cost models that were, until now, only ever consulted
one at a time: the calibrated per-axis alpha-beta :class:`~..obs.comm_model.
CommModel` (including the int8-ring ``predict_compressed`` arms), the HLO
``cost_analysis`` FLOP count captured by the Telemetry AOT hook, and
:class:`~..obs.mem_ledger.MemoryModel` (per-leaf resident bytes from
spec x mesh math, no compile).  This module is the consumer that uses all
three at once: given a model config and a chip count it

1. **enumerates** candidate plans — mesh factorizations ``dp x tp x pp``
   of the chip count (constrained to what the model family can actually
   shard: ``tp | nheads/dim/vocab``, ``pp | nlayers``), each crossed with
   the layer layout for the data axis (``dp`` = replicated params,
   ``fsdp`` = ZeRO-3 param sharding via the same first-free-divisible-dim
   rule ``parallel.zero.zero_partition_spec`` applies) and with per-axis
   int8 compression arms (grad collectives on the data axis, SP boundary
   activations on the tensor axis — exactly the knobs
   ``DataParallel(grad_compress=...)`` / ``TransformerConfig(ag_compress=
   ...)`` expose) — MoE GPT configs additionally cross in an
   expert-parallel factor ``ep | gcd(dp, experts)`` (expert stacks
   sharded over a dedicated ``ep`` mesh axis, the batch over
   ``("data", "ep")``, the dispatch all_to_all priced per MoE layer);
2. **prunes** candidates whose modeled per-device resident bytes exceed
   the HBM budget — ``MemoryModel.estimate`` is the judge when jax is
   importable (``memory='model'``), a byte-identical pure-python mirror
   (``memory='analytic'``, pinned to the model by tests) serves the
   jax-free CLI; every pruned plan emits a ``plan_rejected_oom`` event
   **before anything compiles**;
3. **scores** the survivors with a modeled step time: an HLO-FLOP (or
   6N+12LSD formula) compute term over a sustained per-device FLOP/s
   basis, plus every per-step collective the plan implies priced through
   the CommModel (grad reduce / ZeRO param gathers over ``data``, SP
   boundary gathers+scatters over ``tensor``, pipeline p2p over ``pipe``
   with the 1F1B bubble on the compute term) — compressed arms priced by
   ``predict_compressed``, so an int8 arm can only win when the
   (calibrated) model approves it;
4. **emits** an executable plan: mesh axes, per-leaf param PartitionSpecs
   (:func:`plan_param_specs` builds the real ``jax.sharding.
   PartitionSpec`` tree for the winning candidate), the compress policy,
   and the ranked alternatives with per-term score breakdowns — plus a
   ``plan_selected`` event and the validated RUNREPORT ``autoplan``
   section (``Telemetry.record_autoplan``), so every selection is
   auditable after the fact.

Known gaps vs measured (docs/autoplan.md spells these out): the comm
terms assume zero compute/comm overlap (the same serialized convention as
the RUNREPORT comm section's ``modeled_comm_s``), the vocab-parallel
cross-entropy reductions and optimizer-update traffic are unmodeled, and
TP compute is assumed to scale perfectly.  The ranking is validated
against measured CPU-sim steps in ``tests/test_autoplan.py`` and the
``bench.py --autoplan`` arm; disagreements are disclosed in the section's
``modeled_vs_measured`` record rather than hidden.

Module scope is deliberately jax-free (``tools/autoplan.py`` is a
login-node CLI over a JSON model config, like ``bench_trend``): jax is
imported lazily and only by the executable-side helpers and the
``memory='model'`` estimator.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..obs.comm_model import CommModel
from ..obs.mem_ledger import headroom_verdict
# the schema vocabulary lives in obs (the leaf subsystem) so the RUNREPORT
# validator never has to import dist; re-exported here for callers
from ..obs.report import AUTOPLAN_SCHEMA, PLAN_VERDICTS  # noqa: F401

#: Default sustained per-device FLOP/s when nothing better is known (no
#: measured step, no recognized chip) — only relative comm terms order
#: plans in that regime, and the basis is recorded so the report says so.
ASSUMED_FLOPS = 1e12


# --------------------------------------------------------- model description


@dataclasses.dataclass(frozen=True)
class ModelDims:
    """Normalized, jax-free view of a model config — everything the shape
    table and the FLOP formula need.  Built by :func:`model_dims` from a
    ``GPTConfig``, a ``TransformerConfig``, or a plain dict (the CLI's
    JSON config)."""

    family: str  # 'gpt' (embed + stacked blocks + head) | 'transformer'
    dim: int
    nheads: int
    nlayers: int
    seq: int
    vocab: Optional[int] = None
    ffn: int = 0
    kv_heads: Optional[int] = None
    act: str = "gelu"
    norm: str = "layer"
    pos: str = "learned"
    dtype_size: int = 4
    # MoE (0 experts = dense).  Every ``moe_every``-th block's FFN is an
    # expert layer; top_k routing with the Switch capacity bound inflates
    # the expert FLOP term by ``top_k * capacity_factor / experts``.
    moe_experts: int = 0
    moe_top_k: int = 2
    moe_every: int = 2
    moe_capacity_factor: float = 1.25

    @property
    def n_moe_layers(self) -> int:
        """MoE blocks in the stack — ``is_moe_block`` places one at every
        ``moe_every``-th position, so exactly ``L // moe_every``."""
        if not self.moe_experts:
            return 0
        return self.nlayers // max(self.moe_every, 1)


def model_dims(config: Any) -> ModelDims:
    """Normalize a GPTConfig / TransformerConfig / dict into
    :class:`ModelDims`.  MoE GPT configs carry the expert dims through
    (the planner prices the EP all_to_all and the capacity-inflated
    expert FLOPs); the transformer family has no MoE variant."""
    get = (config.get if isinstance(config, dict)
           else lambda k, d=None: getattr(config, k, d))
    moe_experts = int(get("moe_experts", 0) or 0)
    if moe_experts and not get("vocab_size"):
        raise ValueError(
            "MoE planning needs the gpt family (gpt_moe) — the "
            "transformer family has no expert blocks")
    dim = int(get("dim"))
    ffn = get("ffn_hidden") or dim * int(get("ffn_mult", 4))
    dtype = get("dtype", "float32")
    try:
        dtype_size = int(np.dtype(dtype).itemsize)
    except TypeError:
        dtype_size = int(np.dtype(str(dtype).split(".")[-1]).itemsize)
    vocab = get("vocab_size")
    seq = get("max_seq") or get("seq") or 0
    kv = get("kv_heads")
    return ModelDims(
        family="gpt" if vocab else "transformer",
        dim=dim,
        nheads=int(get("nheads")),
        nlayers=int(get("nlayers")),
        seq=int(seq),
        vocab=int(vocab) if vocab else None,
        ffn=int(ffn),
        kv_heads=int(kv) if kv else None,
        act=str(get("act", "gelu")),
        norm=str(get("norm", "layer")),
        pos=str(get("pos", "learned")),
        dtype_size=dtype_size,
        moe_experts=moe_experts,
        moe_top_k=int(get("moe_top_k", 2) or 2),
        moe_every=int(get("moe_every", 2) or 2),
        moe_capacity_factor=float(get("moe_capacity_factor", 1.25) or 1.25),
    )


@dataclasses.dataclass(frozen=True)
class LeafRow:
    """One param leaf of the analytic shape table.  ``tp_dim`` /
    ``stack_dim`` name the dims the tensor / pipe axes shard (None =
    replicated on that axis); ``count`` multiplies the leaf (the
    transformer family keeps per-layer block lists where GPT stacks)."""

    path: str
    shape: Tuple[int, ...]
    tp_dim: Optional[int] = None
    stack_dim: Optional[int] = None
    count: int = 1
    matmul: bool = True  # counted by the 6N FLOP formula
    ep_dim: Optional[int] = None  # dim the expert-parallel axis shards
    #: FLOP multiplier vs a dense leaf — expert leaves carry
    #: ``top_k * capacity_factor / experts`` (each token visits top_k of
    #: E experts, padded to the Switch capacity bound).
    flop_weight: float = 1.0


def _block_rows(d: ModelDims) -> List[LeafRow]:
    """Unstacked per-block leaves with their TP dims — the analytic mirror
    of ``tensor_parallel.block_param_specs`` + ``init_block_params``."""
    D, F = d.dim, d.ffn
    rows: List[LeafRow] = []
    norm_leaves = [("scale", (D,))] + (
        [("bias", (D,))] if d.norm == "layer" else [])
    for ln in ("ln1", "ln2"):
        rows += [LeafRow(f"{ln}.{k}", s) for k, s in norm_leaves]
    if d.kv_heads and d.kv_heads != d.nheads:
        dkv = d.kv_heads * (D // d.nheads)
        rows += [
            LeafRow("attn.wq", (D, D), tp_dim=1),
            LeafRow("attn.bq", (D,), tp_dim=0),
            LeafRow("attn.wkv", (2, D, dkv), tp_dim=2),
            LeafRow("attn.bkv", (2, dkv), tp_dim=1),
        ]
    else:
        rows += [
            LeafRow("attn.wqkv", (3, D, D), tp_dim=2),
            LeafRow("attn.bqkv", (3, D), tp_dim=1),
        ]
    rows += [
        LeafRow("attn.wo", (D, D), tp_dim=0),
        LeafRow("attn.bo", (D,)),
    ]
    if d.act == "swiglu":
        rows += [
            LeafRow("mlp.w1", (2, D, F), tp_dim=2),
            LeafRow("mlp.b1", (2, F), tp_dim=1),
        ]
    else:
        rows += [
            LeafRow("mlp.w1", (D, F), tp_dim=1),
            LeafRow("mlp.b1", (F,), tp_dim=0),
        ]
    rows += [
        LeafRow("mlp.w2", (F, D), tp_dim=0),
        LeafRow("mlp.b2", (D,)),
    ]
    return rows


def _moe_rows(d: ModelDims, count: int) -> List[LeafRow]:
    """The expert-layer leaves of one MoE block — the analytic mirror of
    ``parallel.moe.init_moe_params`` / ``moe_param_specs``: router
    replicated, stacked expert arrays EP-sharded on dim 0.  Expert leaves
    carry the capacity-inflated FLOP weight (a token runs top_k of E
    experts, each padded to the Switch capacity bound)."""
    D, F, E = d.dim, d.ffn, d.moe_experts
    w = d.moe_top_k * d.moe_capacity_factor / E
    rows = [LeafRow("moe.router.w", (D, E), count=count)]
    if d.act == "swiglu":
        rows += [
            LeafRow("moe.experts.w1", (E, 2, D, F), ep_dim=0, count=count,
                    flop_weight=w),
            LeafRow("moe.experts.b1", (E, 2, F), ep_dim=0, count=count,
                    flop_weight=w),
        ]
    else:
        rows += [
            LeafRow("moe.experts.w1", (E, D, F), ep_dim=0, count=count,
                    flop_weight=w),
            LeafRow("moe.experts.b1", (E, F), ep_dim=0, count=count,
                    flop_weight=w),
        ]
    rows += [
        LeafRow("moe.experts.w2", (E, F, D), ep_dim=0, count=count,
                flop_weight=w),
        LeafRow("moe.experts.b2", (E, D), ep_dim=0, count=count,
                flop_weight=w),
    ]
    return rows


def param_table(d: ModelDims) -> List[LeafRow]:
    """The model's full analytic shape table.  GPT stacks block leaves on
    a leading [L] dim (``stack_dim=0`` — the dim ``pipe`` shards, and a
    legal FSDP dim, exactly as in the real spec tree); the transformer
    family keeps per-layer leaves (``count=nlayers``).  MoE GPT blocks
    are a heterogeneous LIST in the real tree (``init_gpt_moe_params``),
    so they are counted per-layer too: dense blocks x (L - n_moe), MoE
    blocks' attention/norm leaves + expert leaves x n_moe."""
    rows: List[LeafRow] = []
    if d.family == "gpt":
        assert d.vocab
        rows.append(LeafRow("tok_emb", (d.vocab, d.dim), tp_dim=0,
                            matmul=False))
        if d.pos == "learned":
            rows.append(LeafRow("pos_emb", (d.seq, d.dim), matmul=False))
        if d.moe_experts:
            n_moe = d.n_moe_layers
            n_dense = d.nlayers - n_moe
            brows = _block_rows(d)
            if n_dense:
                rows += [dataclasses.replace(
                    r, path=f"blocks[dense].{r.path}", count=n_dense)
                    for r in brows]
            rows += [dataclasses.replace(
                r, path=f"blocks[moe].{r.path}", count=n_moe)
                for r in brows if not r.path.startswith("mlp.")]
            rows += [dataclasses.replace(r, path=f"blocks[moe].{r.path}")
                     for r in _moe_rows(d, count=n_moe)]
        else:
            for r in _block_rows(d):
                rows.append(LeafRow(
                    f"blocks.{r.path}", (d.nlayers, *r.shape),
                    tp_dim=None if r.tp_dim is None else r.tp_dim + 1,
                    stack_dim=0))
        rows.append(LeafRow("head", (d.dim, d.vocab), tp_dim=1))
    else:
        for r in _block_rows(d):
            rows.append(dataclasses.replace(
                r, path=f"blocks.{r.path}", count=d.nlayers))
    norm_leaves = [("scale", (d.dim,))] + (
        [("bias", (d.dim,))] if d.norm == "layer" else [])
    rows += [LeafRow(f"ln_f.{k}", s) for k, s in norm_leaves]
    return rows


def flops_per_token(d: ModelDims) -> float:
    """The bench.py 6N+12LSD accounting: 6 FLOPs per matmul param per
    token (embedding tables excluded — gathers, not matmuls) plus the
    attention score/value matmuls.  ``bench.py --autoplan`` replaces this
    with the compiled step's own ``cost_analysis`` count when it has one.
    Expert leaves count at their capacity-inflated ``flop_weight`` — a
    token runs ``top_k`` of ``E`` experts, padded to capacity — so a MoE
    stack prices its *activated* FLOPs, not the full parameter count."""
    n_matmul = sum(
        r.count * r.flop_weight * int(np.prod(r.shape, dtype=np.int64))
        for r in param_table(d) if r.matmul)
    return 6.0 * n_matmul + 12.0 * d.nlayers * d.seq * d.dim


# --------------------------------------------------------------- candidates


def _divisors(n: int) -> List[int]:
    return [k for k in range(1, n + 1) if n % k == 0]


def _tp_ok(d: ModelDims, tp: int) -> bool:
    if tp == 1:
        return True
    if d.nheads % tp or d.dim % tp or d.ffn % tp:
        return False
    if d.vocab and d.vocab % tp:
        return False
    if d.kv_heads and d.kv_heads % tp:
        return False
    return True


def candidate_key(c: Dict[str, Any]) -> str:
    parts = [f"{'fsdp' if c['layout'] == 'fsdp' else 'dp'}{c['dp']}"]
    if c.get("ep", 1) > 1:
        parts.append(f"ep{c['ep']}")
    if c["tp"] > 1:
        parts.append(f"tp{c['tp']}")
    if c["pp"] > 1:
        parts.append(f"pp{c['pp']}")
    key = "·".join(parts)
    if c["compress"]["grads"]:
        key += "+gc8"
    if c["compress"]["acts"]:
        key += "+ac8"
    return key


def enumerate_candidates(
    d: ModelDims,
    n_chips: int,
    global_batch: int,
    allow_pp: bool = True,
    executable_only: bool = False,
    compression: bool = True,
    layouts: Sequence[str] = ("dp", "fsdp"),
) -> List[Dict[str, Any]]:
    """Every legal ``dp x tp x pp`` factorization of ``n_chips`` crossed
    with layer layout and compression arms — deterministic order.  Awkward
    chip counts still always yield at least pure DP (``dp = n_chips``
    divides any batch multiple of it; batch-indivisible dp values are
    skipped).  ``executable_only`` restricts to plans bench's timed
    runners can execute: compression only on the pure-dp ``pp == 1`` arm
    (``DataParallel(grad_compress='int8')`` — the GSPMD jit runner for
    tp/fsdp plans cannot express the int8 rings), and ``pp > 1`` plans
    restricted to the ``dp`` layout (bench's pipeline runner drives the
    1F1B/ZB schedules through ``DataParallel``, which replicates params
    over ``data`` — the fsdp spec insertion has no pipelined runner).

    MoE configs additionally cross each ``dp x tp`` point with an
    expert-parallel factor ``ep`` (every common divisor of ``dp`` and the
    expert count): the data axis splits into ``data = dp/ep`` x ``ep``,
    the batch shards over both, and expert stacks shard over ``ep``
    (``moe_param_specs``).  MoE candidates are restricted to ``pp == 1``
    (MoE blocks are a heterogeneous list — no stacked [L] dim for pipe to
    shard), the ``dp`` layout (the ZeRO insertion has no MoE runner), and
    no compression arms (the int8 rings have no expert-dispatch runner)."""
    out: List[Dict[str, Any]] = []
    moe = d.moe_experts > 0
    for pp in _divisors(n_chips):
        if pp > 1 and (
                not allow_pp or d.family != "gpt" or d.nlayers % pp or moe):
            continue
        for tp in _divisors(n_chips // pp):
            if not _tp_ok(d, tp):
                continue
            dp = n_chips // pp // tp
            if global_batch % dp:
                continue
            arm_layouts = [
                l for l in layouts if l == "dp" or (l == "fsdp" and dp > 1)]
            if moe or (executable_only and pp > 1):
                arm_layouts = [l for l in arm_layouts if l == "dp"]
            ep_arms = [
                e for e in _divisors(dp) if d.moe_experts % e == 0
            ] if moe else [1]
            for layout in arm_layouts:
                can_gq = compression and dp > 1 and not moe and not (
                    executable_only and (tp > 1 or pp > 1
                                         or layout == "fsdp"))
                grad_arms = (False, True) if can_gq else (False,)
                act_arms = (False, True) if (
                    compression and tp > 1 and not moe
                    and not executable_only) else (False,)
                for gq in grad_arms:
                    for aq in act_arms:
                        for ep in ep_arms:
                            c: Dict[str, Any] = {
                                "dp": dp, "tp": tp, "pp": pp,
                                "layout": layout,
                                "mesh_axes": {"pipe": pp, "data": dp,
                                              "tensor": tp},
                                "compress": {"grads": gq, "acts": aq},
                            }
                            if moe:
                                c["ep"] = ep
                                c["mesh_axes"] = {
                                    "pipe": pp, "data": dp // ep,
                                    "ep": ep, "tensor": tp}
                            out.append(c)
    for c in out:
        c["key"] = candidate_key(c)
    return out


# ----------------------------------------------------------------- sharding


def _axis_assignment(
    row: LeafRow, c: Dict[str, Any]
) -> List[Optional[str]]:
    """Per-dim mesh-axis assignment for one leaf under candidate ``c`` —
    the analytic mirror of ``plan_param_specs``: tp/pipe dims from the
    table, then (fsdp layout) the data axis on the first free dim whose
    size divides dp, exactly ``parallel.zero.zero_partition_spec``'s rule."""
    entries: List[Optional[str]] = [None] * len(row.shape)
    if c["pp"] > 1 and row.stack_dim is not None:
        entries[row.stack_dim] = "pipe"
    if c.get("ep", 1) > 1 and row.ep_dim is not None:
        entries[row.ep_dim] = "ep"
    if c["tp"] > 1 and row.tp_dim is not None:
        entries[row.tp_dim] = "tensor"
    if c["layout"] == "fsdp" and c["dp"] > 1:
        for dim, (size, used) in enumerate(zip(row.shape, entries)):
            if used is None and size > 0 and size % c["dp"] == 0:
                entries[dim] = "data"
                break
    return entries


def _leaf_shards(row: LeafRow, c: Dict[str, Any]) -> int:
    n = 1
    for axis in _axis_assignment(row, c):
        if axis is not None:
            n *= c["mesh_axes"][axis]
    return n


def _spec_str(entries: Sequence[Optional[str]]) -> str:
    trimmed = list(entries)
    while trimmed and trimmed[-1] is None:
        trimmed.pop()
    return "P(" + ", ".join(a or "None" for a in trimmed) + ")"


def spec_table(d: ModelDims, c: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Per-leaf spec rows of a candidate (rendered, audit-friendly) —
    the ``param_specs`` payload of an emitted plan."""
    rows = []
    for r in param_table(d):
        entries = _axis_assignment(r, c)
        rows.append({
            "path": r.path,
            "shape": list(r.shape),
            "spec": _spec_str(entries),
            "shard_count": _leaf_shards(r, c),
        })
    return rows


# ------------------------------------------------------------------- memory


def estimate_memory_analytic(
    d: ModelDims,
    c: Dict[str, Any],
    global_batch: int,
    seq_len: Optional[int] = None,
    capacity_bytes: Optional[int] = None,
    optimizer_slots: int = 2,
    act_factor: float = 1.0,
) -> Dict[str, Any]:
    """Pure-python per-device resident-bytes estimate — byte-identical to
    ``MemoryModel.estimate`` over the real (config, mesh, specs) triple
    (``tests/test_autoplan.py`` pins the two): per-leaf ceil over the
    spec'd shard product, grads at param sharding, f32 optimizer moments,
    the same B_local*S*D*L activation term."""
    params_bytes = 0
    elems_resident = 0
    for r in param_table(d):
        n_elems = int(np.prod(r.shape, dtype=np.int64))
        shards = _leaf_shards(r, c)
        resident = -(-n_elems // shards)
        params_bytes += r.count * resident * d.dtype_size
        elems_resident += r.count * resident
    grads_bytes = params_bytes
    opt_bytes = optimizer_slots * elems_resident * 4
    S = seq_len if seq_len is not None else d.seq
    batch_per_device = global_batch // c["dp"]
    act_bytes = int(
        batch_per_device * S * d.dim * d.nlayers * act_factor * d.dtype_size)
    total = params_bytes + grads_bytes + opt_bytes + act_bytes
    hv = headroom_verdict(total, capacity_bytes)
    return {
        "params_bytes": params_bytes,
        "grads_bytes": grads_bytes,
        "opt_bytes": opt_bytes,
        "act_bytes": act_bytes,
        "total_bytes": total,
        "capacity_bytes": capacity_bytes,
        "frac": hv["frac"],
        "headroom_frac": hv["headroom_frac"],
        "verdict": hv["verdict"],
        "basis": "analytic",
    }


class _MiniMesh:
    """Duck-typed mesh for ``MemoryModel.estimate`` (it reads only
    ``axis_names`` + ``shape``) — scores mesh shapes no device has to
    back."""

    def __init__(self, sizes: Dict[str, int]) -> None:
        self.shape = dict(sizes)
        self.axis_names = tuple(sizes)


def estimate_memory_model(
    config: Any,
    c: Dict[str, Any],
    global_batch: int,
    seq_len: Optional[int] = None,
    capacity_bytes: Optional[int] = None,
    optimizer_slots: int = 2,
    act_factor: float = 1.0,
) -> Dict[str, Any]:
    """``MemoryModel.estimate`` over the candidate's REAL spec tree — the
    acceptance path: the same model that judges compiled layouts judges
    the plan, before anything compiles."""
    from ..obs.mem_ledger import MemoryModel

    specs = plan_param_specs(c, config)
    est = MemoryModel(
        capacity_bytes=capacity_bytes,
        optimizer_slots=optimizer_slots,
        act_factor=act_factor,
    ).estimate(
        config, _MiniMesh(c["mesh_axes"]), specs,
        batch_per_device=global_batch // c["dp"],
        seq_len=seq_len,
    )
    est = {k: est[k] for k in (
        "params_bytes", "grads_bytes", "opt_bytes", "act_bytes",
        "total_bytes", "capacity_bytes", "frac", "headroom_frac",
        "verdict")}
    est["basis"] = "memory-model"
    return est


# ------------------------------------------------------------------ scoring


def _grad_payload_bytes(d: ModelDims, c: Dict[str, Any]) -> float:
    """Per-device grad bytes entering the data-axis collective: each
    leaf's bytes after the NON-data shards (tp/pp/ep — each ep shard owns
    different experts, so its grads never cross the ep boundary) — the
    fsdp data shard is the collective's OUTPUT, not its payload."""
    total = 0
    for r in param_table(d):
        n_elems = int(np.prod(r.shape, dtype=np.int64))
        shards = 1
        for axis in _axis_assignment(r, c):
            if axis in ("tensor", "pipe", "ep"):
                shards *= c["mesh_axes"][axis]
        total += r.count * -(-n_elems // shards) * d.dtype_size
    return float(total)


def comm_terms(
    d: ModelDims,
    c: Dict[str, Any],
    global_batch: int,
    model: CommModel,
    seq_len: Optional[int] = None,
    microbatches: int = 8,
) -> List[Dict[str, Any]]:
    """The per-step collectives candidate ``c`` implies, priced through
    the CommModel.  Per term: op, axes, full-payload bytes (the same
    nccl-tests convention ``CommModel.predict`` expects), op count per
    step, per-op and total predicted seconds, and — for compressed arms —
    the ``predict_compressed`` record (so the report shows whether the
    calibrated model actually approved the ring)."""
    S = seq_len if seq_len is not None else d.seq
    dp, tp, pp = c["dp"], c["tp"], c["pp"]
    terms: List[Dict[str, Any]] = []

    def price(name, op, axes, n, payload, count, compressed):
        if n <= 1 or payload <= 0 or count <= 0:
            return
        row: Dict[str, Any] = {
            "name": name, "op": op, "axes": list(axes), "n": int(n),
            "payload_bytes": int(payload), "count": int(count),
            "compressed": bool(compressed),
        }
        if compressed:
            rec = model.predict_compressed(
                op, payload, n, axes=axes, elem_bytes=d.dtype_size)
            row["per_op_s"] = rec["compressed_s"]
            row["model_approves"] = rec["compress"]
            row["basis"] = rec["basis"]
            row["exact_s"] = rec["exact_s"]
        else:
            row["per_op_s"] = model.predict(op, payload, n, axes=axes)
        row["total_s"] = row["per_op_s"] * count
        terms.append(row)

    gq = c["compress"]["grads"]
    grad_bytes = _grad_payload_bytes(d, c)
    if dp > 1:
        if c["layout"] == "fsdp":
            # ZeRO-3: param all-gather fwd + bwd re-gather, grad
            # reduce-scatter inside the backward
            price("fsdp-param-gather", "all_gather", ("data",), dp,
                  grad_bytes, 2, gq)
            price("fsdp-grad-scatter", "reduce_scatter", ("data",), dp,
                  grad_bytes, 1, gq)
        else:
            price("dp-grad-reduce", "all_reduce", ("data",), dp,
                  grad_bytes, 1, gq)
    if tp > 1:
        # SP boundaries: 2 gathers + 2 scatters per block forward, the
        # transposed pair in the backward -> 4 of each per layer per step
        act_bytes = (global_batch // dp) * S * d.dim * d.dtype_size
        n_each = 4 * d.nlayers
        aq = c["compress"]["acts"]
        price("sp-act-gather", "all_gather", ("tensor",), tp,
              act_bytes, n_each, aq)
        price("sp-act-scatter", "reduce_scatter", ("tensor",), tp,
              act_bytes, n_each, aq)
    if pp > 1:
        # 1F1B critical path: ~2(M + pp - 2) boundary transfers of one
        # microbatch's boundary activation
        micro_act = ((global_batch // dp) / microbatches) * S * d.dim \
            * d.dtype_size
        price("pp-boundary", "ppermute", ("pipe",), pp, micro_act,
              2 * (microbatches + pp - 2), False)
    ep = c.get("ep", 1)
    if ep > 1:
        # EP dispatch: each MoE layer all_to_alls the capacity-padded
        # token buffer (T_local * top_k * cf rows of D) to its experts
        # and back, forward and backward -> 4 per MoE layer per step.
        tok_local = (global_batch // dp) * S
        a2a_bytes = int(
            tok_local * d.moe_top_k * d.moe_capacity_factor
            * d.dim * d.dtype_size)
        price("moe-all-to-all", "all_to_all", ("ep",), ep, a2a_bytes,
              4 * d.n_moe_layers, False)
    return terms


def score_candidate(
    d: ModelDims,
    c: Dict[str, Any],
    global_batch: int,
    model: CommModel,
    effective_flops: float,
    fpt: float,
    seq_len: Optional[int] = None,
    microbatches: int = 8,
) -> Dict[str, Any]:
    """Modeled step time = compute term (HLO/formula FLOPs over the
    sustained per-device FLOP/s, inflated by the pipeline schedule's
    modeled wall-clock multiplier for pp plans) + the serialized comm
    terms.  Returned dict is the ranked-row payload (per-term breakdown
    included).

    pp plans are priced under BOTH pipeline schedules the executable side
    can drive — classic 1F1B and the zero-bubble split
    (``obs.aggregate.pipeline_time_inflation``, which charges zb's extra
    dgrad/wgrad recompute honestly) — and the row records the cheaper one
    as ``pp_schedule`` plus its slot-accounting ``bubble_fraction``
    (``obs.aggregate.pipeline_bubble_fraction``), so the planner's
    schedule choice is auditable against the measured pair
    ``bench.py --autoplan`` attaches."""
    from ..obs.aggregate import (
        pipeline_bubble_fraction,
        pipeline_time_inflation,
    )

    S = seq_len if seq_len is not None else d.seq
    n_chips = c["dp"] * c["tp"] * c["pp"]
    flops_step = fpt * global_batch * S
    if c["pp"] > 1:
        inflations = {
            sched: pipeline_time_inflation(microbatches, c["pp"],
                                           schedule=sched)
            for sched in ("1f1b", "zb")
        }
        pp_schedule = min(inflations, key=inflations.get)
        inflation = inflations[pp_schedule]
        bubble = pipeline_bubble_fraction(
            microbatches, c["pp"], schedule=pp_schedule)
    else:
        pp_schedule, inflation, bubble = None, 1.0, 0.0
    compute_s = flops_step / n_chips / effective_flops * inflation
    terms = comm_terms(d, c, global_batch, model, seq_len=S,
                       microbatches=microbatches)
    comm_s = sum(t["total_s"] for t in terms)
    return {
        "compute_s": compute_s,
        "comm_s": comm_s,
        "step_s": compute_s + comm_s,
        "bubble_fraction": round(bubble, 4),
        "pp_schedule": pp_schedule,
        "terms": terms,
    }


# --------------------------------------------------------------- the planner


def plan(
    config: Any,
    n_chips: int,
    global_batch: int,
    seq_len: Optional[int] = None,
    comm_model: Optional[CommModel] = None,
    capacity_bytes: Optional[int] = None,
    effective_flops: Optional[float] = None,
    fpt: Optional[float] = None,
    optimizer_slots: int = 2,
    act_factor: float = 1.0,
    microbatches: int = 8,
    allow_pp: bool = True,
    executable_only: bool = False,
    compression: bool = True,
    layouts: Sequence[str] = ("dp", "fsdp"),
    memory: str = "auto",
    device_kind: Optional[str] = None,
    top: int = 8,
    emit: bool = True,
) -> Dict[str, Any]:
    """Plan the parallelism for ``config`` on ``n_chips`` chips.

    Returns the RUNREPORT-shaped ``autoplan`` section: ``chosen`` (the
    executable winner: mesh axes, layout, compress policy, rendered
    per-leaf specs, score + memory breakdowns), ``ranked`` (top
    alternatives), ``pruned`` + ``n_pruned_oom`` (the OOM evidence), the
    scoring ``basis``, and ``verdict`` (``ok`` | ``all_oom``).

    - ``comm_model``: a calibrated :class:`CommModel` grounds the comm
      terms (and the int8 arms) in measurement; default = the
      per-generation table model for ``device_kind``.
    - ``effective_flops``: sustained per-device FLOP/s.  Feed the value a
      measured step implies (``bench.py --autoplan`` does: HLO FLOPs /
      measured step time) to close the loop; default = 40% of the chip's
      table peak when recognized, else :data:`ASSUMED_FLOPS`.
    - ``fpt``: FLOPs/token for the compute term — pass the compiled
      step's ``cost_analysis`` count when one exists; default = the
      6N+12LSD formula.
    - ``memory``: ``'model'`` (``MemoryModel.estimate`` over the real
      spec tree — needs jax importable), ``'analytic'`` (the pure-python
      mirror, for the jax-free CLI), ``'auto'`` = model when config is a
      real config object and jax imports, else analytic.
    - ``emit``: a ``plan_rejected_oom`` event per pruned candidate and one
      ``plan_selected`` event for the winner land on the default event
      timeline.
    """
    d = model_dims(config)
    if global_batch < 1:
        raise ValueError(f"global_batch must be >= 1, got {global_batch}")
    if memory not in ("auto", "model", "analytic"):
        raise ValueError(f"memory must be auto|model|analytic, got {memory!r}")
    use_model = memory == "model"
    if memory == "auto":
        use_model = not isinstance(config, dict) and _jax_importable()
    model = comm_model or CommModel.from_defaults(
        device_kind=device_kind or "unknown")
    fpt_val = float(fpt) if fpt else flops_per_token(d)
    eff, compute_basis = _resolve_effective_flops(
        effective_flops, device_kind)

    cands = enumerate_candidates(
        d, n_chips, global_batch, allow_pp=allow_pp,
        executable_only=executable_only, compression=compression,
        layouts=layouts)
    ranked: List[Dict[str, Any]] = []
    pruned: List[Dict[str, Any]] = []
    for c in cands:
        if use_model:
            mem = estimate_memory_model(
                config, c, global_batch, seq_len=seq_len,
                capacity_bytes=capacity_bytes,
                optimizer_slots=optimizer_slots, act_factor=act_factor)
        else:
            mem = estimate_memory_analytic(
                d, c, global_batch, seq_len=seq_len,
                capacity_bytes=capacity_bytes,
                optimizer_slots=optimizer_slots, act_factor=act_factor)
        if mem["verdict"] == "oom_risk":
            row = {"key": c["key"], "total_bytes": mem["total_bytes"],
                   "capacity_bytes": mem["capacity_bytes"],
                   "frac": mem["frac"]}
            pruned.append(row)
            if emit:
                from ..obs.events import emit_event

                emit_event("plan_rejected_oom", **row)
            continue
        score = score_candidate(
            d, c, global_batch, model, eff, fpt_val,
            seq_len=seq_len, microbatches=microbatches)
        ranked.append({**c, **score, "memory": mem})
    ranked.sort(key=lambda r: (r["step_s"], r["key"]))

    chosen = None
    if ranked:
        chosen = dict(ranked[0])
        chosen["param_specs"] = spec_table(d, chosen)[:64]
        if emit:
            from ..obs.events import emit_event

            emit_event(
                "plan_selected", key=chosen["key"],
                modeled_step_s=chosen["step_s"],
                n_candidates=len(cands), n_pruned_oom=len(pruned))
    return {
        "schema": AUTOPLAN_SCHEMA,
        "verdict": "ok" if chosen else "all_oom",
        "n_candidates": len(cands),
        "n_pruned_oom": len(pruned),
        "pruned": pruned[:16],
        "chosen": chosen,
        "ranked": [
            {k: v for k, v in r.items() if k != "terms"}
            if i else r  # full per-term breakdown on the winner only
            for i, r in enumerate(ranked[:top])
        ],
        "params": {
            "n_chips": n_chips, "global_batch": global_batch,
            "seq_len": seq_len if seq_len is not None else d.seq,
            "family": d.family, "microbatches": microbatches,
        },
        "basis": {
            "comm": model.source,
            "compute": compute_basis,
            "memory": ("memory-model" if use_model else "analytic"),
            "flops_per_token": fpt_val,
            "effective_flops": eff,
        },
    }


PREFILL_PLAN_SCHEMA = "autoplan-prefill-v1"


def plan_prefill_tier(
    config: Any,
    *,
    context_len: int,
    chunk: int,
    block_size: int,
    num_blocks: Optional[int] = None,
    cp_widths: Sequence[int] = (1, 2, 4, 8),
    batch: int = 1,
    comm_model: Optional[CommModel] = None,
    device_kind: Optional[str] = None,
    capacity_bytes: Optional[int] = None,
    effective_flops: Optional[float] = None,
    emit: bool = True,
) -> Dict[str, Any]:
    """Size a CP prefill tier (docs/long_context.md "CP prefill
    serving"): for each candidate ring width price the modeled TTFT of
    one ``context_len``-token prompt — the chunk compute split ``cp``
    ways plus every ring hop through the CommModel's ``ppermute`` row,
    the same per-hop payloads the engine's HLO ledger shows
    (``ring_hops_per_chunk`` / ``ring_chunk_bytes`` in
    ops/ring_paged.py) — and the per-rank memory verdict: pool slice
    (``pool/cp``) + ring working set against ``capacity_bytes``
    (``headroom_verdict``).  Ranked by modeled ``ttft_s`` among
    non-OOM arms; widths that don't divide ``chunk`` (each rank
    prefills ``chunk/cp`` rows) are skipped as non-executable.

    The hop and compute terms are summed SERIALLY — the honest model
    until the on-chip overlap round lands (ROADMAP 5c); the returned
    ``basis`` says so.  ``emit`` lands ``plan_rejected_oom`` /
    ``plan_selected`` events like :func:`autoplan`."""
    from ..obs.mem_ledger import headroom_verdict
    from ..ops.ring_paged import (
        modeled_cp_working_set_bytes,
        ring_chunk_bytes,
        ring_hops_per_chunk,
    )

    if context_len < 1 or chunk < 1 or block_size < 1:
        raise ValueError(
            f"context_len/chunk/block_size must be >= 1, got "
            f"{context_len}/{chunk}/{block_size}")
    d = model_dims(config)
    kv_heads = d.kv_heads or d.nheads
    head_dim = d.dim // d.nheads
    model = comm_model or CommModel.from_defaults(
        device_kind=device_kind or "unknown")
    eff, compute_basis = _resolve_effective_flops(
        effective_flops, device_kind)
    # forward-only prefill: the 6N+12LSD accounting is fwd+bwd, and the
    # backward is 2x the forward
    fpt = flops_per_token(d) / 3.0
    n_chunks = -(-context_len // chunk)
    nb_base = num_blocks if num_blocks is not None \
        else 1 + batch * -(-context_len // block_size)

    ranked: List[Dict[str, Any]] = []
    pruned: List[Dict[str, Any]] = []
    skipped: List[int] = []
    for cp in sorted(set(int(w) for w in cp_widths)):
        if cp < 1 or chunk % cp:
            skipped.append(cp)
            continue
        nb = -(-nb_base // cp) * cp  # the engine's rounding
        nb_local = nb // cp
        pool = 2 * d.nlayers * nb * kv_heads * block_size * head_dim \
            * d.dtype_size
        mem_bytes = pool // cp + modeled_cp_working_set_bytes(
            kv_heads=kv_heads, head_dim=head_dim, block_size=block_size,
            nb_local=nb_local, chunk=chunk, cp=cp, batch=batch,
            itemsize=d.dtype_size)
        verdict = headroom_verdict(mem_bytes, capacity_bytes)
        compute_s = fpt * context_len / (cp * eff)
        terms: List[Dict[str, Any]] = [{
            "name": "prefill-compute", "op": "matmul", "axes": [],
            "n": cp, "count": n_chunks, "total_s": compute_s,
        }]
        ring_s = 0.0
        if cp > 1:
            fresh = batch * kv_heads * (chunk // cp) * head_dim \
                * d.dtype_size
            pool_slice = nb_local * kv_heads * block_size * head_dim \
                * d.dtype_size
            for name, payload in (("cp-ring-fresh", fresh),
                                  ("cp-ring-pool", pool_slice)):
                per_op = model.predict(
                    "ppermute", payload, cp, axes=("context",))
                count = n_chunks * 2 * (cp - 1) * d.nlayers
                terms.append({
                    "name": name, "op": "ppermute", "axes": ["context"],
                    "n": cp, "payload_bytes": int(payload),
                    "count": count, "per_op_s": per_op,
                    "total_s": per_op * count,
                })
                ring_s += per_op * count
        row = {
            "key": f"cp{cp}",
            "cp": cp,
            "num_blocks": nb,
            "ttft_s": compute_s + ring_s,
            "compute_s": compute_s,
            "ring_s": ring_s,
            "ring_hops": n_chunks * ring_hops_per_chunk(d.nlayers, cp),
            "ring_bytes": n_chunks * ring_chunk_bytes(
                nlayers=d.nlayers, cp=cp, batch=batch, kv_heads=kv_heads,
                head_dim=head_dim, chunk=chunk, nb_local=nb_local,
                block_size=block_size, itemsize=d.dtype_size),
            "mem_bytes": mem_bytes,
            "memory": verdict,
            "terms": terms,
        }
        if verdict["verdict"] == "oom_risk":
            prow = {"key": row["key"], "total_bytes": mem_bytes,
                    "capacity_bytes": capacity_bytes,
                    "frac": verdict["frac"]}
            pruned.append(prow)
            if emit:
                from ..obs.events import emit_event

                emit_event("plan_rejected_oom", **prow)
            continue
        ranked.append(row)
    ranked.sort(key=lambda r: (r["ttft_s"], r["key"]))

    chosen = dict(ranked[0]) if ranked else None
    if chosen and emit:
        from ..obs.events import emit_event

        emit_event(
            "plan_selected", key=chosen["key"],
            modeled_step_s=chosen["ttft_s"],
            n_candidates=len(ranked) + len(pruned),
            n_pruned_oom=len(pruned))
    return {
        "schema": PREFILL_PLAN_SCHEMA,
        "verdict": "ok" if chosen else "all_oom",
        "n_candidates": len(ranked) + len(pruned),
        "n_pruned_oom": len(pruned),
        "skipped_widths": skipped,
        "pruned": pruned,
        "chosen": chosen,
        "ranked": [
            {k: v for k, v in r.items() if k != "terms"} if i else r
            for i, r in enumerate(ranked)
        ],
        "params": {
            "context_len": context_len, "chunk": chunk,
            "block_size": block_size, "batch": batch,
            "family": d.family,
        },
        "basis": {
            "comm": model.source,
            "compute": compute_basis,
            "flops_per_token_fwd": fpt,
            "effective_flops": eff,
            "overlap": "serial (compute + ring summed; ROADMAP 5c)",
        },
    }


def _jax_importable() -> bool:
    try:
        import jax  # noqa: F401

        return True
    except Exception:
        return False


def _resolve_effective_flops(
    effective_flops: Optional[float], device_kind: Optional[str]
) -> Tuple[float, str]:
    if effective_flops:
        return float(effective_flops), "measured"
    if device_kind:
        from ..obs.telemetry import peak_flops_for

        peak = peak_flops_for(device_kind)
        if peak:
            # sustained ~= 40% of peak: the repo's own measured MFU band
            return 0.4 * peak, "peak-table@0.4"
    return ASSUMED_FLOPS, "assumed"


def attach_measured(
    result: Dict[str, Any], rows: Sequence[Dict[str, Any]]
) -> Dict[str, Any]:
    """Record measured step times for (some of) the ranked plans into the
    section's ``modeled_vs_measured`` — the audit record the acceptance
    reads: per-plan modeled vs measured with rel err, and whether the
    measured ordering agrees with the modeled one.  ``rows``: dicts with
    ``key``, ``modeled_step_s``, ``measured_step_s``; pp rows may carry
    the bubble audit alongside (``pp_schedule``,
    ``modeled_bubble_fraction`` from the slot accounting,
    ``measured_bubble_fraction`` estimated from the timed 1F1B/ZB pair)
    — passed through verbatim so the RUNREPORT shows the bubble term's
    modeled-vs-measured agreement, not just the step time's."""
    out_rows = []
    for r in rows:
        mo, me = float(r["modeled_step_s"]), float(r["measured_step_s"])
        out_rows.append({
            "key": r["key"], "modeled_step_s": mo, "measured_step_s": me,
            "rel_err": round((mo - me) / me, 4) if me > 0 else None,
        })
        for extra in ("pp_schedule", "modeled_bubble_fraction",
                      "measured_bubble_fraction", "microbatches"):
            if extra in r:
                out_rows[-1][extra] = r[extra]
    modeled_order = [r["key"] for r in sorted(
        out_rows, key=lambda r: r["modeled_step_s"])]
    measured_order = [r["key"] for r in sorted(
        out_rows, key=lambda r: r["measured_step_s"])]
    result["modeled_vs_measured"] = {
        "rows": out_rows,
        "modeled_order": modeled_order,
        "measured_order": measured_order,
        "ordering_agrees": modeled_order == measured_order,
    }
    return result


# ---------------------------------------------------------- executable side


def build_mesh(c: Dict[str, Any], devices: Optional[Sequence[Any]] = None):
    """A real ``jax.sharding.Mesh`` for a candidate/chosen plan: the
    plan's axis sizes over the attached (or given) devices, ICI-aware via
    ``mesh_utils`` when more than one axis is non-trivial."""
    import jax
    from jax.experimental import mesh_utils
    from jax.sharding import Mesh

    sizes = c["mesh_axes"]
    names = tuple(sizes)
    shape = tuple(sizes[a] for a in names)
    devs = list(devices) if devices is not None else jax.devices()
    n = int(np.prod(shape))
    if len(devs) != n:
        raise ValueError(
            f"plan wants {n} chips ({dict(sizes)}), have {len(devs)}")
    try:
        arr = mesh_utils.create_device_mesh(shape, devices=devs)
    except Exception:
        arr = np.asarray(devs).reshape(shape)
    return Mesh(arr, axis_names=names)


def plan_param_specs(c: Dict[str, Any], config: Any):
    """The candidate's REAL per-leaf PartitionSpec tree (jax side): the
    family's TP/PP specs composed with the ZeRO first-free-divisible-dim
    data-axis insertion for the fsdp layout.  ``tests/test_autoplan.py``
    pins this against the analytic :func:`spec_table`."""
    import jax
    from jax.sharding import PartitionSpec as P

    from ..obs.mem_ledger import _shapes_for_config
    from ..parallel.zero import zero_partition_spec

    d = model_dims(config)
    tp_axis = "tensor" if c["tp"] > 1 else None
    pipe_axis = "pipe" if c["pp"] > 1 else None
    shapes = _shapes_for_config(config)
    if d.family == "gpt" and d.moe_experts:
        from ..models.gpt_moe import gpt_moe_param_specs

        base = gpt_moe_param_specs(
            config, tp_axis=tp_axis,
            ep_axis="ep" if c.get("ep", 1) > 1 else None)
    elif d.family == "gpt":
        from ..models.gpt import gpt_param_specs

        base = gpt_param_specs(config, tp_axis=tp_axis, pipe_axis=pipe_axis)
    else:
        if tp_axis:
            from ..parallel.tensor_parallel import transformer_param_specs

            base = transformer_param_specs(config, axis=tp_axis)
        else:
            base = jax.tree.map(lambda _: P(), shapes)
    if c["layout"] != "fsdp" or c["dp"] <= 1:
        return base
    flat_p, treedef = jax.tree_util.tree_flatten(shapes)
    flat_s = treedef.flatten_up_to(base)
    out = [
        zero_partition_spec(tuple(p.shape), s, "data", c["dp"])[0]
        for p, s in zip(flat_p, flat_s)
    ]
    return jax.tree_util.tree_unflatten(treedef, out)


def batch_partition_spec(c: Dict[str, Any]):
    """Batch leaves shard their leading dim over the data axis — over
    ``("data", "ep")`` for MoE plans, whose data axis splits in two (the
    batch still shards ``dp`` ways; experts shard over the ep factor)."""
    from jax.sharding import PartitionSpec as P

    if "ep" in c["mesh_axes"]:
        return P(("data", "ep")) if c["dp"] > 1 else P()
    return P("data") if c["dp"] > 1 else P()
