"""Memory observability (obs/mem_ledger.py): static buffer ledger parsed
from real CPU-sim compiled steps, donation accounting, per-leaf sharding
evidence (FSDP resident bytes scale ~1/N across shard counts), headroom
verdict math, the planner-facing MemoryModel, and the Telemetry-built
RUNREPORT ``memory`` section.

Everything compiles TINY programs (a 2-leaf train step) — the whole file
costs a handful of sub-second compiles.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from torchdistpackage_tpu.obs import (
    MEM_VERDICTS,
    MemoryModel,
    Telemetry,
    headroom_verdict,
    live_memory,
    mem_report,
    static_ledger,
    validate_runreport,
)
from torchdistpackage_tpu.obs.events import EventLog, set_default_event_log
from torchdistpackage_tpu.obs.mem_ledger import OOM_RISK_FRAC, TIGHT_FRAC


def _mesh(devices, n):
    return Mesh(np.array(devices[:n]), axis_names=("data",))


def _step_fn(lr=0.1):
    def step(p, x):
        def loss(pp):
            return jnp.mean((x @ pp["w"]) ** 2) + pp["ln"].sum()

        g = jax.grad(loss)(p)
        return jax.tree.map(lambda a, b: a - lr * b, p, g), jnp.mean(x)

    return step


def _sharded_inputs(mesh, d=64):
    params = {
        "w": jax.device_put(
            jnp.ones((d, d)), NamedSharding(mesh, P("data"))),
        "ln": jax.device_put(jnp.ones((7,)), NamedSharding(mesh, P())),
    }
    x = jax.device_put(jnp.ones((8, d)), NamedSharding(mesh, P("data")))
    return params, x


def _compile(mesh, donate=False, d=64):
    params, x = _sharded_inputs(mesh, d)
    j = jax.jit(_step_fn(), donate_argnums=(0,) if donate else ())
    return j.lower(params, x).compile()


# ------------------------------------------------------------ static ledger


def test_static_ledger_parses_real_compiled_step(devices8):
    led = static_ledger(_compile(_mesh(devices8, 8)), label="step")
    assert led is not None and led["label"] == "step"
    for key in ("argument_bytes", "output_bytes", "temp_bytes",
                "alias_bytes", "generated_code_bytes",
                "peak_estimate_bytes"):
        assert isinstance(led[key], int) and led[key] >= 0, key
    # args + outputs + temps + codegen - alias is the static upper bound
    assert led["peak_estimate_bytes"] == (
        led["argument_bytes"] + led["output_bytes"] + led["temp_bytes"]
        + led["generated_code_bytes"] - led["alias_bytes"])
    # per-leaf attribution sums to XLA's own argument accounting
    assert led["n_leaves"] == 3  # w, ln, x
    assert sum(r["resident_bytes"] for r in led["per_leaf"]) == (
        led["argument_bytes"])


def test_per_leaf_sharding_evidence(devices8):
    """The attribution must EVIDENCE the sharding: the P('data') leaves
    show global/8 resident bytes, the tiny ln leaf is flagged
    replicated."""
    led = static_ledger(_compile(_mesh(devices8, 8)))
    rows = {r["path"]: r for r in led["per_leaf"]}
    w = rows["[0]['w']"]
    assert w["global_bytes"] == 64 * 64 * 4
    assert w["resident_bytes"] == w["global_bytes"] // 8
    assert w["shard_count"] == 8 and not w["replicated"]
    ln = rows["[0]['ln']"]
    assert ln["replicated"] and ln["shard_count"] == 1
    assert ln["resident_bytes"] == ln["global_bytes"] == 7 * 4
    assert led["sharded_leaves"] == 2 and led["replicated_leaves"] == 1


@pytest.mark.parametrize("n", [2, 4, 8])
def test_fsdp_resident_bytes_scale_inverse_n(devices8, n):
    """The acceptance bar: per-leaf resident param bytes scale ~1/N
    across shard counts on the CPU sim — sharding is evidenced from the
    compiled program's own input layouts, not from caller intent."""
    led = static_ledger(_compile(_mesh(devices8, n)))
    w = next(r for r in led["per_leaf"] if r["path"] == "[0]['w']")
    assert w["resident_bytes"] == (64 * 64 * 4) // n
    assert w["shard_count"] == n


def test_donation_accounting(devices8):
    """``donate_argnums`` must SHOW UP as alias bytes: the donated param
    tree's resident bytes are aliased into the outputs; the undonated
    compile of the identical program shows zero."""
    mesh = _mesh(devices8, 8)
    plain = static_ledger(_compile(mesh, donate=False))
    donated = static_ledger(_compile(mesh, donate=True))
    assert plain["alias_bytes"] == 0
    # everything donatable: w's shard + ln (both returned updated)
    want = (64 * 64 * 4) // 8 + 7 * 4
    assert donated["alias_bytes"] == want
    # and the savings land in the static peak estimate
    assert donated["peak_estimate_bytes"] == (
        plain["peak_estimate_bytes"] - want)


# ----------------------------------------------------------- verdict math


def test_headroom_verdict_thresholds():
    cap = 10 ** 9
    assert headroom_verdict(0.5 * cap, cap)["verdict"] == "ok"
    assert headroom_verdict(TIGHT_FRAC * cap, cap)["verdict"] == "tight"
    assert headroom_verdict(0.9 * cap, cap)["verdict"] == "tight"
    assert headroom_verdict(OOM_RISK_FRAC * cap, cap)["verdict"] == "oom_risk"
    assert headroom_verdict(2 * cap, cap)["verdict"] == "oom_risk"
    hv = headroom_verdict(0.25 * cap, cap)
    assert hv["frac"] == 0.25 and hv["headroom_frac"] == 0.75
    for bad in ((None, cap), (cap, None), (cap, 0), (0, cap)):
        assert headroom_verdict(*bad)["verdict"] == "unknown"
    assert set(MEM_VERDICTS) == {"ok", "tight", "oom_risk", "unknown"}


def test_mem_report_modeled_vs_measured(devices8):
    led = static_ledger(_compile(_mesh(devices8, 8)))
    cap = 10 ** 9
    # modeled only: the static peak decides
    sec = mem_report(programs=[led], capacity_bytes=cap, emit=False)
    assert sec["modeled_peak_bytes"] == led["peak_estimate_bytes"]
    assert sec["verdict"] == "ok" and "modeled" in sec["verdict_basis"]
    # measured side wins when present (per-device frac is ground truth)
    sec = mem_report(programs=[led], measured_peak_frac=0.97,
                     capacity_bytes=cap, emit=False)
    assert sec["verdict"] == "oom_risk"
    assert "measured" in sec["verdict_basis"]
    # no capacity, no measurement -> unknown (the CPU-sim default)
    assert mem_report(programs=[led], emit=False)["verdict"] == "unknown"


def test_oom_risk_event_emitted():
    log = EventLog()
    set_default_event_log(log)
    try:
        sec = mem_report(measured_peak_frac=0.99, capacity_bytes=1)
        assert sec["verdict"] == "oom_risk"
        events = log.of_kind("oom_risk")
        assert len(events) == 1 and events[0]["peak_frac"] == 0.99
        # ok verdicts stay quiet
        mem_report(measured_peak_frac=0.5, capacity_bytes=1)
        assert len(log.of_kind("oom_risk")) == 1
    finally:
        set_default_event_log(None)


def test_kv_pool_cross_check():
    kv = {"pool_bytes": 4096, "pool_bytes_expected": 4096}
    sec = mem_report(kv_pool=kv, emit=False)
    assert sec["kv_pool"]["accounting_match"] is True
    bad = mem_report(
        kv_pool={"pool_bytes": 4096, "pool_bytes_expected": 8192},
        emit=False)
    assert bad["kv_pool"]["accounting_match"] is False


# ------------------------------------------------------------- live reader


def test_live_memory_cpu_sim_shape():
    mem = live_memory()
    # the CPU sim reports nothing — the reader must say so, not crash
    assert set(mem) == {"reported", "live_bytes", "peak_bytes",
                        "limit_bytes", "peak_frac", "per_device"}
    if not mem["reported"]:
        assert mem["per_device"] == [] and mem["peak_frac"] is None


# ---------------------------------------------------------- planner model


def test_memory_model_estimate_sharding(devices8):
    from torchdistpackage_tpu.parallel.tensor_parallel import (
        TransformerConfig,
        transformer_param_specs,
    )

    cfg = TransformerConfig(dim=32, nheads=4, nlayers=2, ffn_mult=2)
    mesh = Mesh(np.array(devices8).reshape(2, 4), axis_names=("data", "tensor"))
    specs = transformer_param_specs(cfg, axis="tensor")
    mm = MemoryModel(capacity_bytes=10 ** 9, optimizer_slots=2)
    tp = mm.estimate(cfg, mesh, specs, batch_per_device=2, seq_len=16)
    rep = mm.estimate(cfg, mesh, jax.tree.map(
        lambda s: P(), specs, is_leaf=lambda x: isinstance(x, P)),
        batch_per_device=2, seq_len=16)
    # TP sharding strictly shrinks resident params vs replicated
    assert tp["params_bytes"] < rep["params_bytes"]
    assert rep["replicated_leaves"] > 0
    # optimizer moments follow the param sharding at f32
    assert tp["opt_bytes"] == 2 * sum(
        -(-r["global_bytes"] // 4 // r["shard_count"]) * 4
        for r in tp["per_leaf"])
    assert tp["act_bytes"] > 0
    assert tp["total_bytes"] == (
        tp["params_bytes"] + tp["grads_bytes"] + tp["opt_bytes"]
        + tp["act_bytes"])
    assert tp["verdict"] in MEM_VERDICTS


def test_memory_model_verdict_against_budget(devices8):
    """The planner contract: the same layout flips ok -> oom_risk purely
    on the capacity budget."""
    from torchdistpackage_tpu.parallel.tensor_parallel import (
        TransformerConfig,
        transformer_param_specs,
    )

    cfg = TransformerConfig(dim=32, nheads=4, nlayers=2, ffn_mult=2)
    mesh = Mesh(np.array(devices8[:4]), axis_names=("tensor",))
    specs = transformer_param_specs(cfg, axis="tensor")
    roomy = MemoryModel(capacity_bytes=10 ** 9).estimate(cfg, mesh, specs)
    total = roomy["total_bytes"]
    assert roomy["verdict"] == "ok"
    squeezed = MemoryModel(capacity_bytes=int(total * 1.01)).estimate(
        cfg, mesh, specs)
    assert squeezed["verdict"] == "oom_risk"
    unknown = MemoryModel(capacity_bytes=None).estimate(cfg, mesh, specs)
    assert unknown["verdict"] == "unknown"  # CPU sim: no capacity


# ------------------------------------------------------- telemetry section


@pytest.fixture()
def _fresh_log():
    log = EventLog()
    set_default_event_log(log)
    yield log
    set_default_event_log(None)


def test_telemetry_memory_section_validates(devices8, _fresh_log):
    mesh = _mesh(devices8, 8)
    params, x = _sharded_inputs(mesh)
    tel = Telemetry(run="mem", report_path=None, mesh=mesh)
    step = tel.wrap_step(jax.jit(_step_fn(), donate_argnums=(0,)))
    for i in range(3):
        params, loss = step(params, x)
        tel.end_step(step=i, loss=loss)
    report = tel.finalize(print_summary=False)
    assert validate_runreport(report) == []
    mem = report["memory"]
    assert mem["verdict"] in MEM_VERDICTS
    assert len(mem["programs"]) == 1  # one signature, one static ledger
    prog = mem["programs"][0]
    assert prog["alias_bytes"] > 0  # donation evidenced through Telemetry
    assert prog["n_leaves"] == 3
    assert mem["modeled_peak_bytes"] == prog["peak_estimate_bytes"]
    # legacy keys intact for pre-existing consumers
    assert "peak_bytes_in_use" in mem and "reported" in mem


def test_trace_exports_hbm_counter_track():
    """Step records carrying memory samples must land in the Chrome trace
    as a counter track (ph 'C', name hbm_bytes) — the scrubbing view of
    the mem_snapshot timeline."""
    from torchdistpackage_tpu.obs.trace import chrome_trace_events

    history = [{
        "type": "step", "step": i, "t_end_s": 10.0 + i,
        "step_time_s": 0.5, "span_device_s": 0.5,
        "bytes_in_use": 1000 + i, "peak_bytes_in_use": 2000,
    } for i in range(3)]
    events = chrome_trace_events(history)
    counters = [e for e in events
                if e.get("ph") == "C" and e["name"] == "hbm_bytes"]
    assert len(counters) == 3
    assert counters[0]["args"] == {"live": 1000, "peak": 2000}


def test_serving_pool_accounting_cross_check(devices8, _fresh_log):
    """The engine's kv_pool summary must carry matching shape-math and
    device-buffer byte counts, and the Telemetry memory section must
    surface the cross-check."""
    from torchdistpackage_tpu.models import GPTConfig, init_gpt_params
    from torchdistpackage_tpu.serving import (
        ServingEngine,
        expected_pool_bytes,
        pool_bytes,
    )

    cfg = GPTConfig(vocab_size=64, dim=32, nheads=4, nlayers=2, max_seq=32)
    params = init_gpt_params(jax.random.PRNGKey(0), cfg)
    tel = Telemetry(run="serve-mem", report_path=None)
    eng = ServingEngine(params, cfg, num_slots=2, block_size=4,
                        telemetry=tel)
    assert pool_bytes(eng.cache) == expected_pool_bytes(
        cfg, eng.num_blocks, eng.block_size)
    summary = eng.serving_summary()
    assert summary["kv_pool"]["pool_bytes"] == (
        summary["kv_pool"]["pool_bytes_expected"])
    tel.record_serving(summary)
    report = tel.finalize(print_summary=False)
    assert validate_runreport(report) == []
    assert report["memory"]["kv_pool"]["accounting_match"] is True
