"""CI smoke for every example script: each runs end-to-end on the 8-device
CPU sim in a subprocess (examples configure their own platform via
TDP_CPU_SIM, so they must NOT inherit this test process's JAX).  The analogue
of the reference treating its examples/ as the de-facto test suite
(SURVEY.md §4) — but actually wired into CI."""

import os
import pathlib
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

REPO = pathlib.Path(__file__).resolve().parent.parent
EXAMPLES = sorted(p.name for p in (REPO / "examples").glob("train_*.py"))


@pytest.mark.parametrize("script", EXAMPLES)
def test_example_runs_on_cpu_sim(script):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    env["TDP_CPU_SIM"] = "8"
    env["TDP_SMOKE"] = "1"  # examples that support it shrink their step count
    env["PYTHONPATH"] = f"{REPO}{os.pathsep}" + env.get("PYTHONPATH", "")
    res = subprocess.run(
        [sys.executable, str(REPO / "examples" / script)],
        env=env,
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert res.returncode == 0, (
        f"{script} failed (rc={res.returncode})\n"
        f"--- stdout ---\n{res.stdout[-2000:]}\n--- stderr ---\n{res.stderr[-2000:]}"
    )


def test_examples_discovered():
    # guard against the glob silently matching nothing
    assert len(EXAMPLES) >= 6, EXAMPLES
