"""Shared pytree helpers."""

from __future__ import annotations


def key_str(path) -> str:
    """Render a jax key-path as 'a/b/0' — the canonical leaf name used by
    partitioning, surgery and debug tooling (one implementation so predicates
    and partition rules always agree on names)."""
    return "/".join(str(getattr(k, "key", getattr(k, "idx", k))) for k in path)
