"""End-to-end example: ZERO-BUBBLE pipelined GPT training vs classic 1F1B
at the SAME (pp, M) config — the PR-14 A/B this schedule exists for.

The zero-bubble schedule (``parallel/pipeline_parallel/zero_bubble.py``,
ZB-H1 shape per arXiv 2412.14374) splits each stage's backward into a
dgrad wavefront plus an M-tick wgrad drain, cutting the tick-accounting
bubble from ``2(P-1)/(M+2P-2)`` to ``4(P-1)/(3M+4P-4)``.  This example:

1. trains the SAME GPT from the SAME init under both schedules on a
   data x pipe mesh and asserts the per-step losses agree (the split
   backward is the same math, re-scheduled);
2. records the pipeline counters the RUNREPORT validates — schedule,
   both bubble fractions (``obs.aggregate.pipeline_bubble_fraction``,
   the tick arithmetic the acceptance measures), and the timed
   per-arm step seconds;
3. asserts the ZB bubble fraction is strictly below the 1F1B one.

The default shape (pp=4, M=4) sits in the ``M < 2(P-1)`` regime where
the split's tick savings also beat its extra recompute in wall clock
(docs/parallelism.md derives the crossover).

- real TPU chips:      python examples/train_zb_pipeline.py
- 8-device CPU sim:    TDP_CPU_SIM=8 python examples/train_zb_pipeline.py
"""

import os
import sys
import time

if os.environ.get("TDP_CPU_SIM"):
    # XLA_FLAGS handling is centralized in dist/overlap.py (test_repo_lint
    # bans direct writes); cpu_sim also pins the cpu platform.
    from torchdistpackage_tpu.dist.overlap import cpu_sim

    cpu_sim(os.environ["TDP_CPU_SIM"])

import jax

import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from torchdistpackage_tpu import setup_distributed, tpc
from torchdistpackage_tpu.obs import Telemetry, pipeline_bubble_fraction
from torchdistpackage_tpu.models import (
    GPTConfig,
    gpt_pipeline_1f1b,
    gpt_pipeline_zb,
    init_gpt_params,
)
from torchdistpackage_tpu.parallel import DataParallel
from torchdistpackage_tpu.models.gpt import gpt_param_specs


def main():
    setup_distributed()
    ndev = len(jax.devices())
    if ndev % 2 != 0:
        print("need an even device count for a pipeline; got", ndev)
        return 0
    pp = 4 if ndev % 4 == 0 else 2
    dp_size = ndev // pp
    M, mbs = 4, 2  # microbatches, per-dp-shard microbatch size
    tpc.setup_process_groups([("data", dp_size), ("pipe", pp)])
    mesh = tpc.get_view()
    print(f"mesh: {dict(mesh.shape)}  schedule A/B at (pp={pp}, M={M})")

    cfg = GPTConfig(
        vocab_size=256, dim=64, nheads=4, nlayers=8, max_seq=32, ffn_mult=2
    )
    # host-side init: both arms broadcast the SAME weights, and the
    # donated train steps cannot delete the master copy under arm A
    params0 = jax.device_get(init_gpt_params(jax.random.PRNGKey(0), cfg))
    specs = gpt_param_specs(cfg, pipe_axis="pipe")

    opt = optax.adamw(1e-3)
    dp = DataParallel(mesh=mesh)

    def make_step(sched_fn):
        def vg_fn(p, batch):
            return sched_fn(p, batch, cfg, num_microbatches=M)

        return dp.make_train_step(
            value_and_grad_fn=vg_fn,
            optimizer=opt,
            param_specs=specs,
            batch_spec={"tokens": P(None, "data"), "targets": P(None, "data")},
        )

    steps = 3 if os.environ.get("TDP_SMOKE") else 8
    key = jax.random.PRNGKey(1)
    batches = []
    for _ in range(steps):
        key, kt = jax.random.split(key)
        tokens = jax.random.randint(
            kt, (M, mbs * dp_size, cfg.max_seq), 0, cfg.vocab_size)
        # copy task: predict the previous token (learnable via attention)
        targets = jnp.concatenate(
            [tokens[:, :, :1], tokens[:, :, :-1]], axis=2)
        batches.append(jax.tree.map(
            lambda a: jax.device_put(a, NamedSharding(mesh, P(None, "data"))),
            {"tokens": tokens, "targets": targets},
        ))

    tel = Telemetry(
        run="train_zb_pipeline",
        tokens_per_step=M * mbs * dp_size * cfg.max_seq,
        mesh=mesh,
    )
    bf_zb = pipeline_bubble_fraction(M, pp, schedule="zb")
    bf_1f1b = pipeline_bubble_fraction(M, pp, schedule="1f1b")

    def run_arm(sched_fn, name, step0):
        """Train the arm from the SAME init over the SAME batches through
        the SAME Telemetry wrapper (identical dispatch machinery — the
        wall-clock pair must not compare a jit cache against an AOT
        executable); returns (per-step losses, post-compile mean step
        seconds)."""
        step = tel.wrap_step(make_step(sched_fn))
        sharded = dp.broadcast_params(params0, param_specs=specs)
        state = opt.init(sharded)
        losses, t0 = [], None
        for i, batch in enumerate(batches):
            sharded, state, loss = step(sharded, state, batch)
            rec = tel.end_step(step=step0 + i, loss=loss)
            losses.append(rec["loss"])
            if i == 0:  # step 0 pays the compile; time the rest
                t0 = time.perf_counter()
        dt = (time.perf_counter() - t0) / max(1, steps - 1)
        print(f"{name}: loss {losses[0]:.4f} -> {losses[-1]:.4f}, "
              f"{dt * 1e3:.1f} ms/step (post-compile)")
        return losses, dt

    # classic 1F1B arm first (the baseline), then the ZB arm
    losses_1f1b, dt_1f1b = run_arm(gpt_pipeline_1f1b, "1f1b", 0)
    losses_zb, dt_zb = run_arm(gpt_pipeline_zb, "zb", steps)

    # the A/B's whole point, asserted: same math (per-step losses agree
    # across schedules), smaller bubble by the schedules' own tick
    # arithmetic — the validated RUNREPORT pipeline section records both
    np.testing.assert_allclose(losses_zb, losses_1f1b, rtol=2e-4, atol=1e-5)
    assert bf_zb < bf_1f1b, (bf_zb, bf_1f1b)
    tel.record_counters(pipeline={
        "schedule": "zb",
        "pipe_size": pp,
        "num_microbatches": M,
        "bubble_fraction": bf_zb,
        "bubble_fraction_1f1b": bf_1f1b,
        "step_time_zb_s": round(dt_zb, 6),
        "step_time_1f1b_s": round(dt_1f1b, 6),
    })
    tel.finalize()
    print(f"bubble fraction: zb {bf_zb:.4f} < 1f1b {bf_1f1b:.4f} — OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
