"""Continuous-batching serving engine over the paged KV cache.

``generate()`` is a *batch* API: every sequence in a call shares one
prompt length and one decode budget, and a new request waits for the whole
batch to drain.  Serving traffic is nothing like that — requests arrive
staggered, prompts and output lengths vary wildly, and throughput comes
from keeping a fixed-size decode batch FULL (Orca/vLLM continuous
batching).  This engine is that scheduler, built TPU-first:

- **Fixed slots, compiled once.**  The decode batch is ``num_slots`` rows
  forever.  A request occupies a slot from admission to retirement; freed
  slots are refilled from the FIFO queue on the next tick.  Because every
  device-side shape is static (``[num_slots, 1]`` tokens, ``[num_slots,
  max_blocks]`` int32 tables, the block pool), the hot loop is exactly TWO
  compiled programs — one decode step, one prefill-chunk step — and host
  code between ticks only rewrites small int32 tables.  No shape ever
  depends on which requests are in flight, so there is no per-request
  retrace (``serving_summary()['decode_signatures']`` is the evidence).
- **Chunked prefill.**  Prompts enter through the same paged forward in
  ``chunk``-token slices, one slice per tick, batched across every
  prefilling slot — a long prompt never stalls in-flight decodes for more
  than one chunk's latency.  The final slice samples the first token
  (per-slot ``last_idx`` picks the true last prompt row out of the padded
  chunk), which is also when TTFT stops ticking.
- **Per-slot sampling.**  Temperature / top-k / top-p and the PRNG key are
  ``[num_slots]`` arrays, so every request keeps its own sampling policy
  and stream inside one compiled sampler (temperature 0 = greedy, exactly
  ``generate()``'s argmax).
- **Retirement.**  EOS or the request's ``max_new_tokens`` frees the slot
  and returns its blocks to the pool the same tick — no token of decode
  compute is spent on finished rows beyond the step that finished them.
- **TP/DP come from the mesh, not the code.**  With a mesh, the step runs
  inside shard_map: KV heads and the vocab-parallel head shard over
  ``axis`` (tp) exactly as in training/`generate()`, and slots + block
  pool shard over ``dp_axis`` — each data group runs its own slice of the
  slot batch against its own pool shard, so a ``tp_dp`` mesh serves with
  zero engine changes.

Observability: every lifecycle transition is a structured event
(``request_admitted`` / ``prefill_chunk`` / ``request_retired`` /
``slots_snapshot``), decode ticks are Telemetry steps when a session is
wired in, and :meth:`ServingEngine.serving_summary` is the RUNREPORT
``serving`` section — TTFT/TPOT percentiles, aggregate tokens/s, slot
occupancy, and KV-pool utilization (the serving counterpart of the
training MFU loop).
"""

from __future__ import annotations

import collections
import dataclasses
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..models.generate import _full_logits
from ..models.gpt import GPTConfig
from ..obs.aggregate import percentiles
from ..obs.events import EventLog, default_event_log
from .paged_cache import (
    BlockAllocator,
    expected_pool_bytes,
    init_paged_kv,
    paged_forward,
    paged_forward_moe,
    pool_bytes,
)

# slot lifecycle
FREE, PREFILL, DECODE = "free", "prefill", "decode"


@dataclasses.dataclass
class Request:
    """One serving request.  ``temperature=0`` is greedy (bit-identical to
    ``generate()``'s argmax); otherwise ``seed`` starts the slot's private
    sampling stream.  ``eos_id`` retires the request early — a serving-
    layer concern ``generate()`` deliberately doesn't have."""

    tokens: Sequence[int]
    max_new_tokens: int
    temperature: float = 0.0
    top_k: Optional[int] = None
    top_p: Optional[float] = None
    eos_id: Optional[int] = None
    seed: int = 0
    rid: int = -1  # assigned at submit()

    def __post_init__(self) -> None:
        if self.max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {self.max_new_tokens}")
        if self.temperature < 0.0:
            raise ValueError(
                f"temperature must be >= 0, got {self.temperature}")
        if self.top_k is not None and self.top_k < 1:
            raise ValueError(f"top_k must be >= 1, got {self.top_k}")
        if len(self.tokens) < 1:
            raise ValueError("empty prompt")


def _split_keys(keys: jnp.ndarray):
    """[B, 2] uint32 -> (carried keys, this step's sample keys)."""
    ks = jax.vmap(lambda k: jax.random.split(k, 2))(keys)
    return ks[:, 0], ks[:, 1]


def _slot_sample(
    logits: jnp.ndarray,
    keys: jnp.ndarray,
    temperature: jnp.ndarray,
    top_k: jnp.ndarray,
    top_p: jnp.ndarray,
) -> jnp.ndarray:
    """Vectorized per-slot sampler on full [B, V] logits: each row applies
    ITS OWN temperature -> top-k -> top-p filter chain (the `_sample`
    semantics, including the rank-0-always-kept nucleus edge) and draws
    from its own key; ``temperature <= 0`` rows take the plain f32 argmax
    — bitwise the ``generate()`` greedy choice."""
    x = logits.astype(jnp.float32)
    greedy = jnp.argmax(x, axis=-1).astype(jnp.int32)
    V = x.shape[-1]
    neg = jnp.float32(-jnp.inf)
    xs = x / jnp.maximum(temperature, 1e-6)[:, None]
    k = jnp.clip(top_k, 1, V)[:, None]
    sorted_x = jnp.sort(xs, axis=-1)[:, ::-1]  # ONE descending sort
    kth = jnp.take_along_axis(sorted_x, k - 1, axis=-1)
    xs = jnp.where(xs < kth, neg, xs)
    sorted_x = jnp.where(jnp.arange(V)[None, :] < k, sorted_x, neg)
    probs = jax.nn.softmax(sorted_x, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep = jnp.roll(cum, 1, axis=-1).at[:, 0].set(0.0) < top_p[:, None]
    keep = keep.at[:, 0].set(True)  # argmax always survives (top_p -> 0)
    cutoff = jnp.min(jnp.where(keep, sorted_x, jnp.inf), axis=-1,
                     keepdims=True)
    xs = jnp.where(xs < cutoff, neg, xs)
    sampled = jax.vmap(jax.random.categorical)(keys, xs).astype(jnp.int32)
    return jnp.where(temperature <= 0.0, greedy, sampled)


class _SlotState:
    """Host-side bookkeeping for one slot (device state lives in the
    engine's int32/f32 arrays; this carries the request identity)."""

    __slots__ = ("state", "rid", "req", "blocks", "prompt", "off",
                 "generated", "t_submit", "t_admit", "t_last", "ttft_s",
                 "tpot_s")

    def __init__(self) -> None:
        self.reset()

    def reset(self) -> None:
        self.state = FREE
        self.rid = -1
        self.req: Optional[Request] = None
        self.blocks: List[int] = []
        self.prompt: Optional[np.ndarray] = None
        self.off = 0
        self.generated: List[int] = []
        self.t_submit = self.t_admit = self.t_last = 0.0
        self.ttft_s: Optional[float] = None
        self.tpot_s: List[float] = []


class ServingEngine:
    """Paged-KV continuous-batching engine — see the module docstring for
    the design.  Typical driver::

        eng = ServingEngine(params, cfg, num_slots=8, block_size=16,
                            telemetry=tel)
        eng.submit(Request(prompt_ids, max_new_tokens=64))
        eng.run_until_idle()
        out = eng.finished[0]["tokens"]          # prompt + generated
        tel.record_serving(eng.serving_summary())

    Parameters
    ----------
    params: the model tree — plain arrays (serial) or device_put with the
        training TP specs when a ``mesh`` is given.
    num_slots: decode-batch width (divisible by the dp size).
    block_size: KV positions per pool block.
    num_blocks: pool blocks PER DP GROUP (incl. the reserved NULL block);
        default sizes the pool so every slot can hold ``max_ctx``.
    max_ctx: per-request ceiling on prompt + generated tokens; sets the
        block-table width.  Default ``cfg.max_seq``.
    chunk: prefill tokens per slot per tick.
    mesh / axis / dp_axis / ep_axis: the serving mesh and its tp / dp /
        expert axes; all None = single-device.  ``param_specs`` overrides
        the auto-derived (``gpt_param_specs`` family) in_specs.
    kv_quant: int8 block pool (``_kv_quant`` per-vector scales).
    telemetry: an ``obs.Telemetry`` — decode ticks become steps (recompile
        detection guards the compile-once contract) and events land on its
        timeline.
    """

    def __init__(
        self,
        params: Any,
        cfg: GPTConfig,
        *,
        num_slots: int = 4,
        block_size: int = 16,
        num_blocks: Optional[int] = None,
        max_ctx: Optional[int] = None,
        chunk: int = 16,
        mesh: Optional[Any] = None,
        axis: Optional[str] = None,
        dp_axis: Optional[str] = None,
        ep_axis: Optional[str] = None,
        param_specs: Optional[Any] = None,
        kv_quant: bool = False,
        telemetry: Optional[Any] = None,
        snapshot_every: int = 16,
    ) -> None:
        if (axis is not None or dp_axis is not None) and mesh is None:
            raise ValueError("axis/dp_axis need a mesh")
        if cfg.attn_impl in ("ring", "ulysses"):
            raise NotImplementedError(
                "context-parallel serving is not supported: the KV pool is "
                "not sequence-sharded (decode a CP-trained checkpoint with "
                "attn_impl='flash', context_axis=None)")
        if num_slots < 1 or chunk < 1 or block_size < 1:
            raise ValueError(
                f"num_slots/chunk/block_size must be >= 1, got "
                f"{num_slots}/{chunk}/{block_size}")
        self.cfg = cfg
        self.params = params
        self.num_slots = num_slots
        self.block_size = block_size
        self.chunk = chunk
        self.mesh, self.axis, self.dp_axis = mesh, axis, dp_axis
        self.ep_axis = ep_axis
        self.kv_quant = kv_quant
        self.telemetry = telemetry
        self.snapshot_every = snapshot_every
        self._ev: EventLog = (
            telemetry.events if telemetry is not None else default_event_log())

        self.max_ctx = int(max_ctx if max_ctx is not None else cfg.max_seq)
        self.max_blocks = -(-self.max_ctx // block_size)  # table width
        self.dp = int(mesh.shape[dp_axis]) if (mesh is not None and dp_axis) else 1
        if num_slots % self.dp:
            raise ValueError(
                f"num_slots {num_slots} not divisible by dp {self.dp}")
        self.slots_per_group = num_slots // self.dp
        if num_blocks is None:
            num_blocks = 1 + self.slots_per_group * self.max_blocks
        self.num_blocks = num_blocks  # per dp group
        self._allocs = [BlockAllocator(num_blocks) for _ in range(self.dp)]
        self._param_specs = param_specs

        cache = init_paged_kv(cfg, self.dp * num_blocks, block_size,
                              quantized=kv_quant)
        if mesh is not None:
            from jax.sharding import NamedSharding

            cache = jax.tree.map(
                lambda a, s: jax.device_put(a, NamedSharding(mesh, s)),
                cache, self._cache_specs(cache))
        self.cache = cache

        # host-visible device state, one row per slot
        V = cfg.vocab_size
        self._tables = np.zeros((num_slots, self.max_blocks), np.int32)
        self._lengths = np.zeros(num_slots, np.int32)
        self._last_tok = np.zeros(num_slots, np.int32)
        self._temps = np.zeros(num_slots, np.float32)
        self._top_k = np.full(num_slots, V, np.int32)
        self._top_p = np.ones(num_slots, np.float32)
        self._keys = np.zeros((num_slots, 2), np.uint32)

        self._slots = [_SlotState() for _ in range(num_slots)]
        self.queue: collections.deque = collections.deque()
        self.finished: Dict[int, Dict[str, Any]] = {}
        self._next_rid = 0
        self._step_fn = self._build_step()
        self._decode_fn = (
            telemetry.wrap_step(self._step_fn) if telemetry is not None
            else self._step_fn)
        self.reset_metrics()

    # ------------------------------------------------------------ compiled step

    def _cache_specs(self, cache):
        from jax.sharding import PartitionSpec as P

        def spec(leaf):
            lead = (None, self.dp_axis, self.axis)
            return P(*lead, *([None] * (leaf.ndim - 3)))

        return jax.tree.map(spec, cache)

    def _build_step(self) -> Callable:
        """ONE python step serves both phases: S_in=1 calls are the decode
        step, S_in=chunk calls the prefill-chunk step — two signatures of
        the same program, compiled once each."""
        cfg, axis, ep_axis = self.cfg, self.axis, self.ep_axis
        if cfg.moe_experts:
            import functools

            fwd = functools.partial(paged_forward_moe, ep_axis=ep_axis)
        else:
            fwd = paged_forward

        def step(params, cache, tokens, tables, offsets, last_idx, samp, keys):
            cache, logits = fwd(params, tokens, cfg, cache, tables, offsets,
                                axis=axis, last_idx=last_idx)
            full = _full_logits(logits, cfg, axis)
            keys, sub = _split_keys(keys)
            tok = _slot_sample(full, sub, samp["temperature"], samp["top_k"],
                               samp["top_p"])
            if axis is not None:
                # every tp shard sampled the identical token (full logits
                # are psum-assembled, keys replicated); pmax re-types it
                # axis-invariant for the replicated out_spec
                tok = jax.lax.pmax(tok, axis)
            return cache, tok, keys

        if self.mesh is None:
            return jax.jit(step)
        return self._mesh_step(step)

    def _mesh_step(self, step):
        from jax.sharding import PartitionSpec as P

        from ..compat import shard_map

        dp = self.dp_axis
        row = P(dp) if dp else P()
        in_specs = (
            self.param_specs_cached(),
            self._cache_specs(self.cache),
            row, row, row, row,
            {"temperature": row, "top_k": row, "top_p": row},
            row,
        )
        out_specs = (self._cache_specs(self.cache), row, row)
        return jax.jit(shard_map(
            step, mesh=self.mesh, in_specs=in_specs, out_specs=out_specs))

    def param_specs_cached(self):
        if getattr(self, "_param_specs", None) is None:
            from ..models import gpt_moe_param_specs, gpt_param_specs

            fn = gpt_moe_param_specs if self.cfg.moe_experts else gpt_param_specs
            kw = {"ep_axis": self.ep_axis} if (
                self.cfg.moe_experts and self.ep_axis) else {}
            self._param_specs = fn(self.cfg, tp_axis=self.axis, **kw)
        return self._param_specs

    # ---------------------------------------------------------------- lifecycle

    def submit(self, req: Request) -> int:
        """Enqueue; returns the request id.  Raises if the request can
        never fit the engine's context/pool ceilings (a too-long request
        must fail loudly at the door, not deadlock the FIFO)."""
        P, N = len(req.tokens), req.max_new_tokens
        need = -(-(P + N) // self.block_size)
        if P + N > self.max_ctx:
            raise ValueError(
                f"prompt {P} + max_new {N} exceeds max_ctx {self.max_ctx}")
        if need > self._allocs[0].n_usable:
            raise ValueError(
                f"request needs {need} blocks, pool has "
                f"{self._allocs[0].n_usable} per group")
        if self.cfg.pos == "learned" and P + N > self.cfg.max_seq:
            raise ValueError(
                f"P + max_new_tokens = {P + N} exceeds the learned position "
                f"table ({self.cfg.max_seq})")
        req = dataclasses.replace(req, rid=self._next_rid)
        self._next_rid += 1
        self.queue.append((req, time.perf_counter()))
        return req.rid

    def _admit(self) -> int:
        """FIFO admission: the head request takes the first free slot
        whose dp group can cover its blocks.  Head-of-line blocking is
        deliberate — skipping ahead would starve long requests."""
        admitted = 0
        while self.queue:
            req, t_submit = self.queue[0]
            P, N = len(req.tokens), req.max_new_tokens
            need = -(-(P + N) // self.block_size)
            slot_idx = None
            for i, s in enumerate(self._slots):
                if s.state != FREE:
                    continue
                if self._allocs[i // self.slots_per_group].n_free >= need:
                    slot_idx = i
                    break
            if slot_idx is None:
                break
            self.queue.popleft()
            blocks = self._allocs[slot_idx // self.slots_per_group].alloc(need)
            s = self._slots[slot_idx]
            s.state, s.rid, s.req, s.blocks = PREFILL, req.rid, req, blocks
            s.prompt = np.asarray(req.tokens, np.int32)
            s.off, s.generated = 0, []
            s.t_submit, s.t_admit = t_submit, time.perf_counter()
            s.ttft_s, s.tpot_s = None, []
            self._tables[slot_idx] = 0
            self._tables[slot_idx, :need] = blocks
            self._lengths[slot_idx] = 0
            self._temps[slot_idx] = req.temperature
            self._top_k[slot_idx] = (
                req.top_k if req.top_k is not None else self.cfg.vocab_size)
            self._top_p[slot_idx] = (
                req.top_p if req.top_p is not None else 1.0)
            self._keys[slot_idx] = np.asarray(
                jax.random.PRNGKey(req.seed), np.uint32)
            self._ev.emit(
                "request_admitted", rid=req.rid, slot=slot_idx,
                prompt_len=int(P), max_new_tokens=int(N), blocks=need,
                queue_wait_s=round(s.t_admit - t_submit, 6))
            admitted += 1
        return admitted

    def _masked(self, state: str) -> np.ndarray:
        """Table rows for slots NOT in ``state`` zeroed (NULL block) so a
        phase's step can never touch another phase's cache blocks."""
        m = np.array([s.state == state for s in self._slots], bool)
        t = np.where(m[:, None], self._tables, 0).astype(np.int32)
        return m, t

    def _samp(self) -> Dict[str, np.ndarray]:
        return {"temperature": self._temps, "top_k": self._top_k,
                "top_p": self._top_p}

    def _sig(self, tokens: np.ndarray) -> tuple:
        return (tokens.shape, str(tokens.dtype), self.num_slots,
                self.max_blocks)

    def _prefill_tick(self) -> int:
        """One ``chunk``-token slice for EVERY prefilling slot, batched in
        one compiled call.  Slots whose slice covers the last prompt row
        sample their first token (TTFT) and move to DECODE."""
        mask, tables = self._masked(PREFILL)
        if not mask.any():
            return 0
        B, C = self.num_slots, self.chunk
        tokens = np.zeros((B, C), np.int32)
        offsets = np.zeros(B, np.int32)
        last_idx = np.zeros(B, np.int32)
        for i, s in enumerate(self._slots):
            if s.state != PREFILL:
                continue
            sl = s.prompt[s.off:s.off + C]
            tokens[i, :len(sl)] = sl
            offsets[i] = s.off
            last_idx[i] = min(len(s.prompt) - 1 - s.off, C - 1)
        self.cache, tok, keys = self._step_fn(
            self.params, self.cache, tokens, tables, offsets, last_idx,
            self._samp(), self._keys)
        self._prefill_sigs.add(("prefill",) + self._sig(tokens))
        tok = np.asarray(tok)
        keys = np.asarray(keys)
        now = time.perf_counter()
        rids = []
        for i, s in enumerate(self._slots):
            if s.state != PREFILL:
                continue
            rids.append(s.rid)
            s.off += C
            if s.off >= len(s.prompt):  # final slice: first token sampled
                self._keys[i] = keys[i]
                s.state = DECODE
                s.ttft_s = now - s.t_submit
                s.t_last = now
                self._lengths[i] = len(s.prompt)
                self._last_tok[i] = tok[i]
                s.generated.append(int(tok[i]))
                self._maybe_retire(i, int(tok[i]), now)
        self.stats["prefill_chunks"] += 1
        self._ev.emit("prefill_chunk", rids=rids, chunk=C,
                      n_slots=len(rids))
        return len(rids)

    def _decode_tick(self) -> int:
        mask, tables = self._masked(DECODE)
        n_active = int(mask.sum())
        if n_active == 0:
            return 0
        tokens = np.where(mask, self._last_tok, 0).astype(np.int32)[:, None]
        offsets = np.where(mask, self._lengths, 0).astype(np.int32)
        last_idx = np.zeros(self.num_slots, np.int32)
        self.cache, tok, keys = self._decode_fn(
            self.params, self.cache, tokens, tables, offsets, last_idx,
            self._samp(), self._keys)
        self._decode_sigs.add(("decode",) + self._sig(tokens))
        if self.telemetry is not None:
            self.telemetry.end_step(active_slots=n_active)
        tok = np.asarray(tok)
        keys = np.asarray(keys)
        now = time.perf_counter()
        for i, s in enumerate(self._slots):
            if s.state != DECODE:
                continue
            self._keys[i] = keys[i]
            self._lengths[i] += 1
            self._last_tok[i] = tok[i]
            s.generated.append(int(tok[i]))
            s.tpot_s.append(now - s.t_last)
            s.t_last = now
            self._maybe_retire(i, int(tok[i]), now)
        self.stats["decode_steps"] += 1
        self.stats["decode_slot_steps"] += n_active
        return n_active

    def _maybe_retire(self, i: int, tok: int, now: float) -> None:
        s = self._slots[i]
        req = s.req
        done_eos = req.eos_id is not None and tok == req.eos_id
        done_len = len(s.generated) >= req.max_new_tokens
        if not (done_eos or done_len):
            return
        self.finished[s.rid] = {
            "rid": s.rid,
            "tokens": np.concatenate(
                [s.prompt, np.asarray(s.generated, np.int32)]),
            "prompt_len": int(len(s.prompt)),
            "new_tokens": len(s.generated),
            "reason": "eos" if done_eos else "max_tokens",
            "ttft_s": s.ttft_s,
            "tpot_s": list(s.tpot_s),
            "t_submit": s.t_submit,
            "t_done": now,
        }
        self._ttfts.append(s.ttft_s)
        self._tpots.extend(s.tpot_s)
        self.stats["generated_tokens"] += len(s.generated)
        self._t_first = min(self._t_first, s.t_submit)
        self._t_last_done = max(self._t_last_done, now)
        self._ev.emit(
            "request_retired", rid=s.rid, slot=i,
            reason=self.finished[s.rid]["reason"],
            new_tokens=len(s.generated),
            ttft_s=round(s.ttft_s, 6) if s.ttft_s is not None else None)
        self._allocs[i // self.slots_per_group].free(s.blocks)
        self._tables[i] = 0
        self._lengths[i] = 0
        self._last_tok[i] = 0
        self._temps[i] = 0.0
        s.reset()

    # -------------------------------------------------------------- driver API

    @property
    def n_busy(self) -> int:
        return sum(s.state != FREE for s in self._slots)

    def step(self) -> Dict[str, int]:
        """One engine tick: admit -> one prefill slice -> one decode step.
        Returns what happened (all zeros = idle)."""
        self._tick += 1
        admitted = self._admit()
        prefilled = self._prefill_tick()
        decoded = self._decode_tick()
        busy = self.n_busy
        self._occ_sum += busy / self.num_slots
        util = float(np.mean([a.utilization() for a in self._allocs]))
        self._util_sum += util
        self._occ_ticks += 1
        if self.snapshot_every and self._tick % self.snapshot_every == 0:
            self._ev.emit(
                "slots_snapshot", tick=self._tick, busy=busy,
                queued=len(self.queue), pool_utilization=round(util, 4))
        return {"admitted": admitted, "prefill_slots": prefilled,
                "decode_slots": decoded, "busy": busy}

    def run_until_idle(self, max_ticks: int = 100_000) -> None:
        """Drain the queue and every in-flight slot."""
        while self.queue or self.n_busy:
            self.step()
            if self._tick > max_ticks:
                raise RuntimeError(
                    f"engine did not drain within {max_ticks} ticks "
                    f"(queued={len(self.queue)}, busy={self.n_busy})")

    def reset_metrics(self) -> None:
        """Zero the serving metrics (the bench's warmup/measure split);
        compiled steps, pool, and queue state are untouched."""
        self.stats = {"decode_steps": 0, "prefill_chunks": 0,
                      "decode_slot_steps": 0, "generated_tokens": 0}
        self._decode_sigs: set = set()
        self._prefill_sigs: set = set()
        self._ttfts: List[float] = []
        self._tpots: List[float] = []
        self._tick = 0
        self._occ_sum = self._util_sum = 0.0
        self._occ_ticks = 0
        self._t_first = float("inf")
        self._t_last_done = 0.0
        self.finished = {}
        for a in self._allocs:
            a.peak_in_use = a.in_use

    # ------------------------------------------------------------------ report

    def serving_summary(self) -> Dict[str, Any]:
        """The RUNREPORT ``serving`` section (``Telemetry.record_serving``
        attaches it; ``validate_runreport`` checks it)."""
        span = self._t_last_done - self._t_first
        n_req = len(self.finished)
        peak_util = max(a.peak_in_use for a in self._allocs) / (
            self._allocs[0].n_usable)
        return {
            "requests": {"completed": n_req, "queued": len(self.queue),
                         "in_flight": self.n_busy},
            "generated_tokens": self.stats["generated_tokens"],
            "tokens_per_sec": (
                self.stats["generated_tokens"] / span
                if span > 0 and n_req else 0.0),
            "ttft_s": percentiles([t for t in self._ttfts if t is not None]),
            "tpot_s": percentiles(self._tpots),
            "slot_occupancy": {
                "mean": (self._occ_sum / self._occ_ticks
                         if self._occ_ticks else 0.0),
                "num_slots": self.num_slots,
            },
            "kv_pool": {
                "num_blocks": self.num_blocks,
                "block_size": self.block_size,
                "dp_groups": self.dp,
                "mean_utilization": (self._util_sum / self._occ_ticks
                                     if self._occ_ticks else 0.0),
                "peak_utilization": peak_util,
                # the obs memory section cross-checks these two: the
                # device buffer actually held vs what the shape math says
                # init_paged_kv should have allocated
                "pool_bytes": pool_bytes(self.cache),
                "pool_bytes_expected": expected_pool_bytes(
                    self.cfg, self.dp * self.num_blocks, self.block_size,
                    quantized=self.kv_quant),
            },
            "decode_steps": self.stats["decode_steps"],
            "prefill_chunks": self.stats["prefill_chunks"],
            "decode_batch_mean": (
                self.stats["decode_slot_steps"] / self.stats["decode_steps"]
                if self.stats["decode_steps"] else 0.0),
            # compile-once evidence: distinct device-call signatures the
            # engine issued (must be 1 per phase however many requests of
            # whatever shapes were served)
            "decode_signatures": len(self._decode_sigs),
            "prefill_signatures": len(self._prefill_sigs),
        }
