"""End-to-end example: SERVE a GPT with the continuous-batching engine.

The training examples show the framework learns; this one shows it serves.
A tp_dp mesh (tensor-parallel attention/head x data-parallel slot groups —
the SAME axes and param specs as training) runs the paged-KV
continuous-batching engine (`torchdistpackage_tpu.serving`) against a
fixed-seed Poisson-ish arrival schedule with mixed prompt lengths, output
budgets and per-request sampling params — the traffic `generate()`'s
fixed-shape batch API cannot express.  The whole run is two compiled
programs (one decode step, one prefill-chunk step); host code between
ticks only rewrites int32 block tables.

Telemetry wraps the decode step, so the RUNREPORT carries a ``serving``
section (TTFT/TPOT percentiles — per priority class too — aggregate
tokens/s, slot occupancy, KV-pool utilization, the
``healthy|degraded|overloaded`` verdict with its cited basis, and the
``slo`` block: per-priority deadline attainment, goodput, TTFT
calibration) and the event timeline shows every admission / prefill
chunk / retirement plus the per-tick ``engine_tick`` accounting — the
serving counterpart of the training MFU loop.  The engine additionally
streams live ``serving_metrics`` gauges through a Prometheus-textfile
sink while it runs, and the run proves every completed request's
lifecycle reconstructs from the event timeline alone
(docs/serving.md "Serving observability").  CI
(tests/test_examples.py) validates all of it.

Phase 2 demonstrates the preemption-safe drain contract (docs/serving.md
"Serving under stress"): with requests in flight, a real SIGTERM (what
SLURM sends before reclaiming the node) trips ``GracefulShutdown``,
``run_until_idle(stop=...)`` drains the engine into persisted
descriptors instead of finishing the work, and a RESTARTED engine
resumes them mid-stream — emitted prefixes replayed through chunked
prefill, carried PRNG keys continuing the sampling streams.

Phase 3 is the serving fast path (docs/serving.md "Prefix cache" /
"Speculative decoding"): shared-SYSTEM-PROMPT traffic through a
``prefix_cache=True, spec_k=2`` engine — every request after the first
maps the resident prefix blocks instead of re-prefilling them
(``prefix_hit_rate > 0`` asserted), the n-gram drafter + one compiled
verify program emit 1..k+1 tokens per tick, and the same greedy
requests through a plain engine prove BIT-parity — the speedups are
semantically free.

- real TPU chips:      python examples/serve_gpt.py
- 8-device CPU sim:    TDP_CPU_SIM=8 python examples/serve_gpt.py
"""

import os
import signal

if os.environ.get("TDP_CPU_SIM"):
    from torchdistpackage_tpu.dist.overlap import cpu_sim

    cpu_sim(os.environ["TDP_CPU_SIM"])

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding

from torchdistpackage_tpu import setup_distributed, tpc
from torchdistpackage_tpu.models import gpt_param_specs, init_gpt_params, llama_config
from torchdistpackage_tpu.obs import PrometheusTextfileSink, Telemetry
from torchdistpackage_tpu.serving import (
    Request,
    ServingEngine,
    assemble_request_timelines,
    lifecycle_phases,
)
from torchdistpackage_tpu.utils.preemption import GracefulShutdown


def main():
    setup_distributed()
    ndev = len(jax.devices())
    tp = 2 if ndev % 2 == 0 else 1
    dp = 2 if ndev >= 4 and tp == 2 else 1
    tpc.setup_process_groups(
        [("data", dp), ("tensor", tp)], devices=jax.devices()[: dp * tp])
    mesh = tpc.get_view()
    print(f"serving mesh: {dict(mesh.shape)}")

    on_cpu = jax.default_backend() == "cpu"
    smoke = bool(os.environ.get("TDP_SMOKE"))
    cfg = llama_config(
        vocab_size=256 if on_cpu else 32768,
        dim=64 if on_cpu else 512,
        nheads=4 if on_cpu else 8,
        kv_heads=2 if on_cpu else 4,  # GQA: kv_heads % tp == 0
        nlayers=2 if on_cpu else 8,
        max_seq=128 if on_cpu else 1024,
        dtype=jnp.float32 if on_cpu else jnp.bfloat16,
        attn_impl="naive" if on_cpu else "flash",
    )
    params = init_gpt_params(jax.random.PRNGKey(0), cfg)
    specs = gpt_param_specs(cfg, tp_axis="tensor")
    params = jax.tree.map(
        lambda a, s: jax.device_put(a, NamedSharding(mesh, s)), params, specs)

    tel = Telemetry(run="serve_gpt", mesh=mesh, poll_memory=not on_cpu)
    num_slots = 4 if smoke else 8
    # live export: every tick's serving_metrics record lands in a
    # Prometheus-textfile gauge set an external scraper could watch
    # while the engine runs (docs/serving.md "Serving observability")
    prom_path = os.path.join(
        os.environ.get("TMPDIR", "/tmp"),
        f"serve_gpt_metrics_{os.getpid()}.prom")
    metrics_sink = PrometheusTextfileSink(
        prom_path, prefix="tdp_serving", run="serve_gpt")
    eng = ServingEngine(
        params, cfg, num_slots=num_slots, block_size=8, chunk=8,
        mesh=mesh, axis="tensor", dp_axis="data" if dp > 1 else None,
        telemetry=tel, snapshot_every=8, metrics_sink=metrics_sink)

    # fixed-seed Poisson-ish arrivals: requests land every few engine
    # ticks with mixed prompts, budgets, per-request sampling, AND mixed
    # priority classes (interactive=2 > batch=0) with generous deadlines
    # on the batch tier — the RUNREPORT serving section reports each
    # class's TTFT/TPOT percentiles separately
    rng = np.random.RandomState(0)
    n_requests = 6 if smoke else 24
    schedule = []
    tick = 0
    for i in range(n_requests):
        tick += int(rng.poisson(2))
        P = int(rng.choice([4, 8, 12]))
        prio = 2 if i % 3 == 0 else 0  # every third request is interactive
        schedule.append((tick, Request(
            tokens=rng.randint(0, cfg.vocab_size, size=P).tolist(),
            max_new_tokens=int(rng.choice([6, 10, 16])),
            temperature=float(rng.choice([0.0, 0.7, 1.0])),
            top_k=int(rng.choice([0, 8, 32])) or None,
            seed=i,
            priority=prio,
            deadline_s=None if prio else 120.0,
        )))

    t = 0
    while schedule or eng.n_busy or eng.queue:
        while schedule and schedule[0][0] <= t:
            eng.submit(schedule.pop(0)[1])
        eng.step()
        t += 1

    summary = eng.serving_summary()
    tel.record_serving(summary)
    assert summary["requests"]["completed"] == n_requests
    assert summary["decode_signatures"] == 1, "decode step retraced!"
    assert summary["verdict"] == "healthy", summary["verdict"]
    assert len(summary["priorities"]) == 2, "expected two priority classes"
    for rid in sorted(eng.finished)[:3]:
        f = eng.finished[rid]
        print(f"req {rid}: prompt {f['prompt_len']} -> +{f['new_tokens']} "
              f"tokens ({f['reason']}, prio {f['priority']}), "
              f"ttft {f['ttft_s'] * 1e3:.1f}ms")
    print(f"served {summary['requests']['completed']} requests, "
          f"{summary['generated_tokens']} tokens at "
          f"{summary['tokens_per_sec']:.1f} tok/s; "
          f"occupancy {summary['slot_occupancy']['mean']:.0%}, "
          f"pool {summary['kv_pool']['mean_utilization']:.0%}; "
          f"verdict {summary['verdict']} ({summary['verdict_basis']})")

    # ---- serving observability (PR 11): SLO/goodput, live gauges, trace
    slo = summary["slo"]
    assert slo["attainment"] is not None, "deadline traffic left no SLO"
    assert slo["goodput_tok_s"] <= summary["tokens_per_sec"] + 1e-6
    assert summary["tick_accounting"]["ticks"] > 0
    with open(prom_path) as f:
        prom = f.read()
    assert "tdp_serving_queue_depth" in prom, "live gauge export missing"
    assert "tdp_serving_phase_decode_s" in prom
    # every completed request's lifecycle reconstructs from the event
    # timeline alone — the request-flow trace the Perfetto export renders
    timelines = assemble_request_timelines(tel.events.as_list())
    retired = [r for r in timelines if r["terminal"] == "retired"]
    assert len(retired) >= n_requests, (len(retired), n_requests)
    for r in retired:
        walk = lifecycle_phases(r)
        assert walk[0] == "queued" and walk[-1] == "retired", walk
        assert "decode" in walk, walk
    cal = slo["calibration"]
    print(f"SLO: goodput {slo['goodput_tok_s']:.1f} tok/s, attainment "
          f"{slo['attainment']:.0%}; TTFT calibration: {cal['n']} "
          f"predictions resolved, bias {cal['bias'] or 1.0:.2f}; "
          f"{len(retired)} lifecycles reconstructed from the trace; "
          f"live gauges at {prom_path}")
    os.remove(prom_path)

    # ---- phase 2: preemption-safe drain (the SLURM SIGTERM contract) ----
    # Requests in flight, a REAL SIGTERM arrives, run_until_idle drains
    # into persisted descriptors, and a restarted engine resumes them.
    drain_path = os.path.join(
        os.environ.get("TMPDIR", "/tmp"), f"serve_gpt_drain_{os.getpid()}.json")
    n_drain = 4 if smoke else 8
    with GracefulShutdown(signals=("SIGTERM",)) as stop:
        for i in range(n_drain):
            eng.submit(Request(
                tokens=rng.randint(0, cfg.vocab_size,
                                   size=int(rng.choice([4, 8]))).tolist(),
                max_new_tokens=16,
                temperature=float(rng.choice([0.0, 0.8])),
                seed=100 + i,
                priority=int(rng.choice([0, 2]))))
        for _ in range(4):  # a little service before the reclaim lands
            eng.step()
        os.kill(os.getpid(), signal.SIGTERM)
        eng.run_until_idle(stop=stop, persist_path=drain_path)
        assert stop.requested, "SIGTERM did not trip GracefulShutdown"
    assert eng.n_busy == 0 and not eng.queue, "drain left work behind"

    eng2 = ServingEngine(  # the relaunched job's engine, same config
        params, cfg, num_slots=num_slots, block_size=8, chunk=8,
        mesh=mesh, axis="tensor", dp_axis="data" if dp > 1 else None,
        telemetry=tel, snapshot_every=8)
    rids = eng2.resume(drain_path)
    eng2.run_until_idle()
    resumed = [eng2.finished[r] for r in rids]
    assert len(resumed) == n_drain and not eng2.rejected
    assert all(f["reason"] in ("eos", "max_tokens") for f in resumed)
    assert eng2.serving_summary()["decode_signatures"] == 1
    n_mid = sum(f["resumed"] for f in resumed)
    print(f"SIGTERM drain: persisted {n_drain} requests "
          f"({n_mid} mid-stream), restarted engine completed all "
          f"{len(resumed)} — emitted prefixes replayed, key streams "
          f"continued")
    for p in (drain_path, drain_path + ".manifest.json"):
        if os.path.exists(p):
            os.remove(p)

    # ---- phase 3: the serving fast path — shared system prompt + spec ----
    # Every request = one system prompt + a short unique tail (the
    # few-shot traffic shape a million-user deployment actually sends).
    # The fast engine maps the resident prefix and speculates at k=2; a
    # plain engine serves the SAME greedy requests to prove bit-parity.
    sys_prompt = rng.randint(0, cfg.vocab_size, size=24).tolist()  # 3 blocks
    n_fast = 6 if smoke else 12
    fast_reqs = [
        Request(
            tokens=sys_prompt + rng.randint(
                0, cfg.vocab_size, size=int(rng.choice([2, 4]))).tolist(),
            max_new_tokens=int(rng.choice([8, 12])),
            priority=2 if i % 3 == 0 else 0,
        )
        for i in range(n_fast)
    ]
    eng_fast = ServingEngine(
        params, cfg, num_slots=num_slots, block_size=8, chunk=8,
        mesh=mesh, axis="tensor", dp_axis="data" if dp > 1 else None,
        telemetry=tel, snapshot_every=8, prefix_cache=True, spec_k=2)
    eng_plain = ServingEngine(
        params, cfg, num_slots=num_slots, block_size=8, chunk=8,
        mesh=mesh, axis="tensor", dp_axis="data" if dp > 1 else None)
    outs = {}
    for name, e in (("fast", eng_fast), ("plain", eng_plain)):
        rids = [e.submit(Request(r.tokens, r.max_new_tokens,
                                 priority=r.priority)) for r in fast_reqs]
        e.run_until_idle()
        outs[name] = [e.finished[r]["tokens"].tolist() for r in rids]
    assert outs["fast"] == outs["plain"], (
        "fast-path tokens diverged from the plain engine")
    s3 = eng_fast.serving_summary()
    assert s3["prefix_hit_rate"] > 0, "no prefix hits on shared-prompt traffic"
    assert s3["decode_signatures"] == 1, "verify step retraced!"
    assert len(s3["priorities"]) == 2
    tel.record_serving(s3)  # the RUNREPORT carries the fast-path arm
    print(f"fast path: prefix hit rate {s3['prefix_hit_rate']:.0%} "
          f"({s3['prefix_cache']['hits']} hits, "
          f"{s3['prefix_cache']['cow_copies']} COW), spec accept rate "
          f"{s3['spec_accept_rate']:.0%} at k={s3['spec']['k']}; "
          f"{n_fast} requests bit-equal to the non-speculative engine")
    tel.finalize()


if __name__ == "__main__":
    main()
