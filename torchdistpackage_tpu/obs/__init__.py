"""obs — unified run telemetry for training, serving, and benchmarks.

The framework could train and serve but not *report on itself*: throughput,
MFU, memory peaks, pipeline bubble fraction, and MoE load balance were
computed ad hoc (or not at all) in ``bench.py``, ``utils/metrics.py`` and
``tools/decode_bench.py`` with no shared schema, no cross-host view, and no
event timeline (VERDICT round 5).  This subpackage is the one shared
telemetry layer every train loop, example, and bench emits through:

- :mod:`.telemetry` — :class:`Telemetry`, a run-session object that wraps a
  jitted train/decode step, records per-step spans (data / dispatch /
  device / fetch), detects recompiles, polls ``device.memory_stats()``, and
  computes MFU + bytes-moved from XLA ``cost_analysis`` of the *compiled*
  step (compiler ground truth — cross-checked against the 6N+12LSD hand
  formula in ``bench.py``).
- :mod:`.events` — append-only structured event log (compile, checkpoint
  save/restore, preemption, NaN-watchdog trip, loss-scale change,
  straggler alert) with monotonic timestamps and process index.
- :mod:`.aggregate` — cross-host reduction of host-side step times
  (min/mean/max per host → straggler detection) plus the per-parallelism
  counters: pipeline bubble fraction, MoE expert-load imbalance.
- :mod:`.report` + :mod:`.exporters` — pluggable sinks (JSONL always;
  TensorBoard scalars and Prometheus textfile behind optional-import
  guards) and the end-of-run ``RUNREPORT.json`` + markdown summary.
- :mod:`.comm_ledger` — per-step collective ledger parsed from the
  AOT-compiled step's HLO: every all-reduce / all-gather / reduce-scatter
  / all-to-all / collective-permute with payload bytes, mapped onto mesh
  axes and classified per parallelism dimension (dp/tp/pp/moe).
- :mod:`.comm_model` — alpha–beta cost model over the ledger: per-TPU-
  generation ICI/DCN link tables, ``CommModel.calibrate(mesh)`` fitting
  measured ``dist.comm_bench`` timings, and the RUNREPORT ``comm``
  section (modeled vs measured comm time, comm-bound vs compute-bound
  verdict, overlap headroom).
- :mod:`.mem_ledger` — memory observability: the per-compiled-program
  static buffer ledger from ``memory_analysis()`` (argument / output /
  temp / donation-savings bytes, argument bytes attributed to pytree
  leaves through the compiled input shardings), the repo's ONE
  ``memory_stats()`` reader (``live_memory``), ``ok|tight|oom_risk``
  headroom verdicts, and the planner-facing ``MemoryModel.estimate``.
- :mod:`.numerics` — numerics observability: the jittable
  ``numerics_stats`` fused into the train step (per-layer-group grad/
  param/update norms, update ratio, non-finite counts, low-precision
  range fractions), the per-dtype HLO FLOP/byte ledger (what actually
  runs in bf16 vs f32 vs int8), threshold-driven ``numerics_alert``
  events, and the RUNREPORT ``numerics`` section.
- :mod:`.parity` — A/B run-parity: compare two runs' record streams /
  RUNREPORTs into an ``exact|bounded|diverged`` verdict with per-step
  drift curves and per-leaf param divergence (``tools/parity_diff.py``
  is the CLI).
- :mod:`.trace` — Perfetto-loadable Chrome-trace export of the run
  (spans, events, ledger + HBM + grad-norm counters) + ``XlaStepTrace``,
  a programmatic ``jax.profiler`` capture bracketing a chosen step
  window.

Design constraints: ``obs`` is a LEAF subsystem — it imports nothing from
the rest of the package at module scope (``utils.metrics`` shims over
``obs.exporters``, so a module-level import the other way would cycle), and
every device/backend touch is guarded so the CPU sim, a half-initialized
backend, or an old jax still produce a report instead of a crash.
"""

from .events import (
    EVENT_KINDS,
    EventLog,
    default_event_log,
    emit_event,
    set_default_event_log,
)
from .exporters import (
    JsonlSink,
    MultiSink,
    PrometheusTextfileSink,
    TensorBoardSink,
    tensorboard_available,
)
from .telemetry import Telemetry, compiled_cost, peak_flops_for
from .aggregate import (
    cross_host_step_stats,
    moe_load_stats,
    percentiles,
    pipeline_bubble_fraction,
    pipeline_time_inflation,
    step_time_stats,
)
from .report import (
    AUTOPLAN_SCHEMA,
    PLAN_VERDICTS,
    RESILIENCE_VERDICTS,
    RUNREPORT_SCHEMA,
    SERVING_VERDICTS,
    default_report_path,
    render_markdown,
    validate_runreport,
    write_runreport,
)
from .comm_ledger import (
    COMM_RECORD_SCHEMA,
    LEDGER_SCHEMA,
    comm_record,
    ledger_from_compiled,
    ledger_from_hlo,
    tp_pp_overlap,
)
from .comm_model import (
    COMPRESSION_SCHEMA,
    CommModel,
    comm_report,
    compressed_ledger_bytes,
    compressed_wire_bytes,
    compression_report,
    fit_alpha_beta,
)
from .mem_ledger import (
    MEM_LEDGER_SCHEMA,
    MEM_VERDICTS,
    MemoryModel,
    device_capacity,
    headroom_verdict,
    live_memory,
    mem_report,
    static_ledger,
)
from .numerics import (
    DEFAULT_THRESHOLDS,
    DTYPE_LEDGER_SCHEMA,
    NUMERICS_SCHEMA,
    check_alerts,
    dtype_ledger_from_compiled,
    dtype_ledger_from_hlo,
    global_grad_norm,
    numerics_report,
    numerics_stats,
)
from .parity import (
    PARITY_SCHEMA,
    PARITY_VERDICTS,
    compare_streams,
    param_divergence,
    parity_section,
    stream_of,
)
from .trace import (
    XlaStepTrace,
    build_trace,
    default_trace_path,
    export_trace,
    validate_trace,
)

__all__ = [
    "EVENT_KINDS",
    "EventLog",
    "default_event_log",
    "emit_event",
    "set_default_event_log",
    "JsonlSink",
    "MultiSink",
    "PrometheusTextfileSink",
    "TensorBoardSink",
    "tensorboard_available",
    "Telemetry",
    "compiled_cost",
    "peak_flops_for",
    "cross_host_step_stats",
    "moe_load_stats",
    "percentiles",
    "pipeline_bubble_fraction",
    "pipeline_time_inflation",
    "step_time_stats",
    "RESILIENCE_VERDICTS",
    "SERVING_VERDICTS",
    "RUNREPORT_SCHEMA",
    "default_report_path",
    "render_markdown",
    "validate_runreport",
    "write_runreport",
    "COMM_RECORD_SCHEMA",
    "LEDGER_SCHEMA",
    "comm_record",
    "ledger_from_compiled",
    "ledger_from_hlo",
    "tp_pp_overlap",
    "CommModel",
    "comm_report",
    "fit_alpha_beta",
    "MEM_LEDGER_SCHEMA",
    "MEM_VERDICTS",
    "MemoryModel",
    "device_capacity",
    "headroom_verdict",
    "live_memory",
    "mem_report",
    "static_ledger",
    "DEFAULT_THRESHOLDS",
    "DTYPE_LEDGER_SCHEMA",
    "NUMERICS_SCHEMA",
    "check_alerts",
    "dtype_ledger_from_compiled",
    "dtype_ledger_from_hlo",
    "global_grad_norm",
    "numerics_report",
    "numerics_stats",
    "PARITY_SCHEMA",
    "PARITY_VERDICTS",
    "compare_streams",
    "param_divergence",
    "parity_section",
    "stream_of",
    "XlaStepTrace",
    "build_trace",
    "default_trace_path",
    "export_trace",
    "validate_trace",
]
