from .flash_attention import flash_attention, flash_attention_with_lse, mha_reference
from .ring_attention import ring_attention, ulysses_attention
