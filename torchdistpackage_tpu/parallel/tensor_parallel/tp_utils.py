"""Tensor/sequence-parallel core ops — analogue of
``torchdistpackage/parallel/tensor_parallel/tp_utils.py`` (248 LoC).

The reference implements Megatron-style autograd regions by hand
(`_ReduceFromModelParallelRegion`, `_GatherFromSequenceParallelRegion`,
`_ReduceScatterToSequenceParallelRegion`, tp_utils.py:39-149) because eager
PyTorch needs explicit backward rules.  Under ``shard_map`` + JAX AD the
transposes come for free and *correctly*:

- ``all_gather``   (SP gather, fwd)  <-AD->  ``psum_scatter`` (bwd)
- ``psum_scatter`` (SP scatter, fwd) <-AD->  ``all_gather``   (bwd)
- replicated operand entering a per-shard matmul (``pvary``) <-AD-> ``psum``
  of its gradient — this is the Megatron "f" region whose backward all-reduce
  the reference *misses* in non-SP mode (SURVEY.md §3.4); here it cannot be
  missed.

Unlike the reference, which keeps a module-global ``TP_GROUP`` disconnected
from its own topology singleton (tp_utils.py:7-15 — an integration gap), the
default axis here is the topology's canonical ``'tensor'`` axis, overridable
per call.
"""

from __future__ import annotations

from typing import Optional

import jax

from ...compat import axis_size
import jax.numpy as jnp

from ...dist.topology import TENSOR_AXIS

# Default mesh-axis name used by TP layers; override per-call via ``axis=``.
_TP_AXIS = TENSOR_AXIS


def set_tp_axis(name: str) -> None:
    """Analogue of ``set_tp_group`` (tp_utils.py:12-15)."""
    global _TP_AXIS
    _TP_AXIS = name


def get_tp_axis() -> str:
    return _TP_AXIS


def tp_size() -> int:
    """Axis size — traced-safe inside shard_map."""
    return axis_size(_TP_AXIS)


# --------------------------------------------------------------------- regions
# All of these are *traced* ops for use inside shard_map over the TP axis.
# seq_dim defaults to 1 for [batch, seq, hidden] layout (TPU-friendly; the
# reference uses seq-first dim 0, tp_utils.py:52-108 — layout is a free choice
# here since XLA owns the memory layout anyway).


def reduce_from_tp(x: jnp.ndarray, axis: Optional[str] = None) -> jnp.ndarray:
    """Forward all-reduce over the TP axis (row-parallel output); backward is
    identity — exactly `_ReduceFromModelParallelRegion` (tp_utils.py:39-49)."""
    return jax.lax.psum(x, axis or _TP_AXIS)


def gather_from_sp(x: jnp.ndarray, axis: Optional[str] = None, seq_dim: int = 1) -> jnp.ndarray:
    """SP -> full: fwd all-gather along the sequence dim, bwd reduce-scatter
    (`_GatherFromSequenceParallelRegion`, tp_utils.py:126-149)."""
    return jax.lax.all_gather(x, axis or _TP_AXIS, axis=seq_dim, tiled=True)


def scatter_to_sp(x: jnp.ndarray, axis: Optional[str] = None, seq_dim: int = 1) -> jnp.ndarray:
    """Full -> SP: fwd reduce-scatter along the sequence dim, bwd all-gather
    (`_ReduceScatterToSequenceParallelRegion`, tp_utils.py:110-123)."""
    return jax.lax.psum_scatter(x, axis or _TP_AXIS, scatter_dimension=seq_dim, tiled=True)


def split_to_sp(x: jnp.ndarray, axis: Optional[str] = None, seq_dim: int = 1) -> jnp.ndarray:
    """Full -> SP without reduction: each shard keeps its sequence slice; bwd
    all-gathers (`_split_along_first_dim`, tp_utils.py:88-108).  Used at the
    model boundary to enter SP from a replicated activation."""
    ax = axis or _TP_AXIS
    n = axis_size(ax)
    idx = jax.lax.axis_index(ax)
    if x.shape[seq_dim] % n != 0:
        raise ValueError(f"seq dim {x.shape[seq_dim]} not divisible by TP size {n}")
    chunk = x.shape[seq_dim] // n
    return jax.lax.dynamic_slice_in_dim(x, idx * chunk, chunk, axis=seq_dim)
