"""Tests for bench.py's baseline-policy machinery — the perf-honesty rules
(VERDICT r2 item 2 / BASELINE.md "first measurement wins"): per-
(backend, config) records, never overwritten, vs_baseline against the BEST
recorded config.  Pure-python, no accelerator."""

import importlib.util
import json
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def _bench():
    spec = importlib.util.spec_from_file_location("bench", REPO / "bench.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_load_baselines_migrations(tmp_path):
    b = _bench()
    p = tmp_path / "b.json"

    # oldest layout: one flat record
    p.write_text(json.dumps(
        {"backend": "tpu", "value": 100.0, "unit": "tokens/sec/chip", "config": "cfgA"}
    ))
    out = b._load_baselines(str(p))
    assert out["tpu"]["cfgA"]["value"] == 100.0

    # legacy layout: one record per backend
    p.write_text(json.dumps(
        {"tpu": {"backend": "tpu", "value": 100.0, "config": "cfgA"}}
    ))
    out = b._load_baselines(str(p))
    assert out["tpu"]["cfgA"]["value"] == 100.0

    # current layout: {backend: {config: record}}
    p.write_text(json.dumps(
        {"tpu": {"cfgA": {"backend": "tpu", "value": 100.0, "config": "cfgA"}}}
    ))
    out = b._load_baselines(str(p))
    assert out["tpu"]["cfgA"]["value"] == 100.0

    # unreadable / missing -> empty
    assert b._load_baselines(str(tmp_path / "missing.json")) == {}
    p.write_text("not json")
    assert b._load_baselines(str(p)) == {}


def test_record_baseline_first_wins(tmp_path):
    b = _bench()
    p = str(tmp_path / "b.json")
    baselines = {}
    b._record_baseline(baselines, p, "tpu", "cfgA", 100.0)
    # a slower re-measurement of the same config must NOT overwrite
    b._record_baseline(baselines, p, "tpu", "cfgA", 50.0)
    assert baselines["tpu"]["cfgA"]["value"] == 100.0
    # a new config gets its own record without touching cfgA
    b._record_baseline(baselines, p, "tpu", "cfgB", 80.0)
    assert baselines["tpu"]["cfgA"]["value"] == 100.0
    assert baselines["tpu"]["cfgB"]["value"] == 80.0
    on_disk = json.loads(Path(p).read_text())
    assert on_disk["tpu"]["cfgA"]["value"] == 100.0

    # vs_baseline semantics: bench.py's own denominator is the BEST recorded
    # config, so a config switch can never re-base the history (the round-2
    # failure mode)
    assert b._best_recorded(baselines, "tpu", fallback=80.0) == 100.0
    assert 80.0 / b._best_recorded(baselines, "tpu", 80.0) < 1.0
    # no records for a backend -> the current measurement is its own baseline
    assert b._best_recorded(baselines, "cpu", fallback=42.0) == 42.0

    # metric scoping: different model sizes are different series — the 1b
    # config's denominator ignores 125m records and vice versa
    baselines["tpu"]["cfgA"]["metric"] = "gpt-125m-train-throughput"
    baselines["tpu"]["cfgB"]["metric"] = "gpt-125m-train-throughput"
    b._record_baseline(baselines, p, "tpu", "big1", 12.0,
                       metric="gpt-1b-train-throughput")
    assert b._best_recorded(
        baselines, "tpu", 12.0, metric="gpt-1b-train-throughput") == 12.0
    assert b._best_recorded(
        baselines, "tpu", 80.0, metric="gpt-125m-train-throughput") == 100.0


def test_only_index_parsing():
    b = _bench()
    assert b._only_index(["bench.py", "--ab", "--only", "2"]) == 2
    assert b._only_index(["bench.py", "--ab"]) is None
    assert b._only_index(["bench.py", "--only"]) is None  # missing operand


def test_peak_flops_lookup():
    b = _bench()
    assert b._peak_flops("TPU v5 lite") == 197e12
    assert b._peak_flops("TPU v4") == 275e12
    assert b._peak_flops("some future chip") is None


def test_last_good_accel_line():
    b = _bench()
    baselines = {
        "cpu": {"tiny": {"backend": "cpu", "value": 5000.0, "config": "tiny"}},
        "tpu": {
            "cfgA": {"backend": "tpu", "value": 62000.0, "config": "cfgA",
                     "chip": "TPU v5 lite", "recorded": "2026-06-01"},
            "cfgB": {"backend": "tpu", "value": 84000.0, "config": "cfgB",
                     "chip": "TPU v5 lite", "recorded": "2026-06-02"},
        },
    }
    line = b._last_good_accel_line(baselines, reason="init probes exhausted")
    # the BEST non-CPU record, never a CPU one
    assert line["value"] == 84000.0
    assert line["config"] == "cfgB"
    assert line["chip"] == "TPU v5 lite"
    # staleness is explicit and machine-readable, and the reason reports
    # what ACTUALLY failed (init vs measurement) — never hardcoded
    assert line["stale"] is True
    assert line["measured_this_run"] is False
    assert line["recorded"] == "2026-06-02"
    assert "init probes exhausted" in line["stale_reason"]
    line2 = b._last_good_accel_line(baselines, reason="measurement failed")
    assert "measurement failed" in line2["stale_reason"]
    # metric name matches the fresh accelerator series (legacy records
    # without a stored metric fall back to the accel metric name)
    assert line["metric"] == "gpt-125m-train-throughput"
    baselines["tpu"]["cfgB"]["metric"] = "custom-metric"
    assert b._last_good_accel_line(baselines)["metric"] == "custom-metric"

    # CPU-only history -> no stale line (nothing to honestly report)
    assert b._last_good_accel_line({"cpu": baselines["cpu"]}) is None
    assert b._last_good_accel_line({}) is None


def test_probe_accel_tristate(monkeypatch):
    """'accel' on a non-CPU answer; 'cpu' short-circuits retries (a CPU-only
    host is a deterministic answer, not a flake); 'hang' only after every
    attempt failed."""
    b = _bench()
    # no real sleeping; scoped so stdlib time.sleep is restored after the
    # test (b.time IS the shared stdlib module)
    monkeypatch.setattr(b.time, "sleep", lambda s: None)

    calls = []

    def fake_child(answers):
        it = iter(answers)

        def run(env, timeout, extra_args=(), capture=False, quiet=False):
            calls.append(extra_args)
            nxt = next(it)
            if nxt is None:
                return None
            failed = ["axon"] if nxt == "cpu-after-error" else []
            backend = "cpu" if nxt == "cpu-after-error" else nxt
            return json.dumps(
                {"probe_backend": backend, "probe_chip": backend,
                 "probe_n_devices": 1, "probe_failed_platforms": failed})
        return run

    b._run_child = fake_child(["tpu"])
    assert b._probe_accel(4, 1.0, 0.0) == "accel"
    assert len(calls) == 1  # first success stops

    calls.clear()
    b._run_child = fake_child(["cpu", "tpu"])
    assert b._probe_accel(4, 1.0, 0.0) == "cpu"
    assert len(calls) == 1  # cpu answer short-circuits, no retry

    calls.clear()
    b._run_child = fake_child([None, None, "tpu"])
    assert b._probe_accel(3, 1.0, 0.0) == "accel"
    assert len(calls) == 3  # hangs retry until the answer

    calls.clear()
    b._run_child = fake_child([None, None])
    assert b._probe_accel(2, 1.0, 0.0) == "hang"

    # a CPU answer caused by an accelerator-platform init ERROR is the
    # flaky tunnel, not a CPU-only host: it must keep retrying
    calls.clear()
    b._run_child = fake_child(["cpu-after-error", "tpu"])
    assert b._probe_accel(4, 1.0, 0.0) == "accel"
    assert len(calls) == 2

    calls.clear()
    b._run_child = fake_child(["cpu-after-error", "cpu-after-error"])
    assert b._probe_accel(2, 1.0, 0.0) == "hang"


def test_record_baseline_stamps_date_and_chip(tmp_path):
    b = _bench()
    p = str(tmp_path / "b.json")
    baselines = {}
    b._record_baseline(baselines, p, "tpu", "cfgA", 100.0, chip="TPU v5 lite")
    rec = baselines["tpu"]["cfgA"]
    assert rec["chip"] == "TPU v5 lite"
    assert len(rec["recorded"]) == 10  # YYYY-MM-DD
