"""TP+SP golden tests — the reference's discipline (test_tpmlp.py:11-41,
test_attn.py:11-47, test_transformer.py:13-44): same full weights, serial
model vs TP/TP+SP model, forward AND gradient parity.  Ours is stronger: the
TP gradients come back as global arrays directly comparable to serial grads
(no manual shard gathering), and the non-SP input-grad all-reduce the
reference is missing (SURVEY.md §3.4) is exercised by the grad checks."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torchdistpackage_tpu.compat import HAS_VMA

# These golden/parity compositions depend on varying-manual-axes shard_map
# semantics (jax.shard_map, jax >= 0.6-era).  The legacy
# jax.experimental.shard_map fallback (compat.py) runs check_rep=False,
# which reassociates the grad reductions — numerically fine for training,
# but the tight-tolerance serial-parity goldens here cannot hold.
requires_vma = pytest.mark.skipif(
    not HAS_VMA,
    reason="needs varying-manual-axes shard_map (jax>=0.6); legacy "
    "fallback reassociates reductions — parity goldens cannot hold",
)
from torchdistpackage_tpu.compat import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from torchdistpackage_tpu.dist import tpc
from torchdistpackage_tpu.parallel.tensor_parallel import (
    TransformerConfig,
    init_transformer_params,
    transformer_forward,
    transformer_param_specs,
)

CFG = TransformerConfig(dim=32, nheads=4, nlayers=2, ffn_mult=2, causal=True)
B, S = 2, 16


def _setup_tp(devices8, tp=4):
    tpc.setup_process_groups([("data", len(devices8) // tp), ("tensor", tp)], devices=devices8)
    return tpc.get_view()


def _sp_out_spec(sp):
    # SP output stays seq-sharded (gather_output=False); shard_map reassembles
    return P(None, "tensor", None) if sp else P()


@pytest.fixture(scope="module")
def serial_golden():
    """The serial reference, computed ONCE for the whole file as a single
    ``value_and_grad(has_aux=True)`` program: forward output, loss, and
    grads all come out of ONE compile (tier-1 budget: fwd+grad pairs fold
    into one program, ROADMAP item 1)."""
    params = init_transformer_params(jax.random.PRNGKey(0), CFG)
    x = jax.random.normal(jax.random.PRNGKey(1), (B, S, CFG.dim))

    @jax.jit
    def vg(p, xx):
        def loss_with_out(pp):
            out = transformer_forward(pp, xx, CFG)
            return jnp.mean(out**2), out

        return jax.value_and_grad(loss_with_out, has_aux=True)(p)

    (loss, out), grads = vg(params, x)
    return {
        "params": params, "x": x, "out": np.asarray(out),
        "loss": float(loss), "grads": jax.device_get(grads),
    }


@pytest.mark.parametrize("sp", [False, True])
def test_tp_transformer_matches_serial(devices8, serial_golden, sp):
    mesh = _setup_tp(devices8)
    params, x = serial_golden["params"], serial_golden["x"]

    # TP: shard the *same global arrays* by spec; shard_map sees local shards
    specs = transformer_param_specs(CFG, axis="tensor")
    sharded = jax.tree.map(
        lambda a, s: jax.device_put(a, NamedSharding(mesh, s)), params, specs
    )
    x_sh = jax.device_put(x, NamedSharding(mesh, P()))

    # forward + loss + grad parity from ONE compiled program: the shard_map
    # forward's output rides out as value_and_grad aux
    def tp_loss_with_out(p, xx):
        out = shard_map(
            functools.partial(
                transformer_forward, cfg=CFG, axis="tensor", sp=sp, gather_output=False
            ),
            mesh=mesh,
            in_specs=(specs, P()),
            out_specs=_sp_out_spec(sp),
        )(p, xx)
        return jnp.mean(out**2), out

    (tp_loss_val, tp_out), tp_grads = jax.jit(
        jax.value_and_grad(tp_loss_with_out, has_aux=True)
    )(sharded, x_sh)
    np.testing.assert_allclose(
        np.asarray(tp_out), serial_golden["out"], rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(
        float(tp_loss_val), serial_golden["loss"], rtol=1e-5)
    flat_s, _ = jax.tree_util.tree_flatten_with_path(serial_golden["grads"])
    flat_t, _ = jax.tree_util.tree_flatten_with_path(tp_grads)
    for (path, gs), (_, gt) in zip(flat_s, flat_t):
        np.testing.assert_allclose(
            np.asarray(gt), np.asarray(gs), rtol=5e-5, atol=5e-5,
            err_msg=f"grad mismatch at {jax.tree_util.keystr(path)}",
        )


@requires_vma
def test_tp_dp_composition(devices8):
    """TP=2 x DP=4 train step: grads pmean over data, TP collectives inside —
    params must follow the serial trajectory."""
    import optax

    from torchdistpackage_tpu.parallel.data_parallel import DataParallel

    tp = 2
    tpc.setup_process_groups([("data", 4), ("tensor", tp)], devices=devices8)
    mesh = tpc.get_view()
    params = init_transformer_params(jax.random.PRNGKey(0), CFG)
    specs = transformer_param_specs(CFG, axis="tensor")
    opt = optax.sgd(1e-2)

    def loss_fn(p, batch):
        out = transformer_forward(p, batch["x"], CFG, axis="tensor", sp=True)
        return jnp.mean((out - batch["y"]) ** 2)

    dp = DataParallel(mesh=mesh)
    sharded = dp.broadcast_params(params, param_specs=specs)
    state = opt.init(sharded)
    step = dp.make_train_step(loss_fn, opt, param_specs=specs)

    def serial_loss(p, batch):
        out = transformer_forward(p, batch["x"], CFG)
        return jnp.mean((out - batch["y"]) ** 2)

    sparams, sstate = params, opt.init(params)

    @jax.jit
    def serial_step(p, s, b):
        loss, g = jax.value_and_grad(serial_loss)(p, b)
        u, s = opt.update(g, s, p)
        return jax.tree.map(jnp.add, p, u), s, loss

    for i in range(3):
        kx, ky = jax.random.split(jax.random.PRNGKey(10 + i))
        batch = {
            "x": jax.random.normal(kx, (8, S, CFG.dim)),
            "y": jax.random.normal(ky, (8, S, CFG.dim)),
        }
        sparams, sstate, sloss = serial_step(sparams, sstate, batch)
        sharded, state, dloss = step(sharded, state, dp.shard_batch(batch))
        np.testing.assert_allclose(float(dloss), float(sloss), rtol=1e-4, atol=1e-5)

    w_tp = np.asarray(sharded["blocks"][0]["mlp"]["w1"])
    w_s = np.asarray(sparams["blocks"][0]["mlp"]["w1"])
    np.testing.assert_allclose(w_tp, w_s, rtol=1e-4, atol=1e-5)
