"""Flash attention as a Pallas TPU kernel (fwd + custom-VJP bwd).

The reference only *derives* this math in a single-device numpy study
(explore/flash-attn/tile_attn.py:100-212 — tiled online-softmax fwd+bwd); it
ships no kernel.  Here it is a first-class TPU kernel: blockwise online
softmax with f32 accumulators in VMEM, MXU matmuls via ``jnp.dot`` with
``preferred_element_type``, causal block skipping, and a standard flash
backward (recompute probabilities from the saved logsumexp; dq kernel loops
over KV blocks, dkv kernel loops over Q blocks).

**Blocked-KV 3D grid**: K/V are streamed through VMEM one ``block_k`` tile at
a time — the grid is ``(batch*heads, Sq/block_q, Sk/block_k)`` with the KV
dimension innermost ("arbitrary" semantics, executed sequentially per core)
and the online-softmax state ``(m, l, acc)`` carried in VMEM scratch across
KV steps.  VMEM per program is O(block), independent of sequence length, so
single-chip long-S is bounded by HBM, not VMEM; Mosaic double-buffers the KV
block DMAs against the MXU work.

The kernel also returns the per-row logsumexp **differentiably** (cotangents
on lse fold into the standard flash ``delta`` term), which is what lets ring
/ Ulysses context parallelism (ops/ring_attention.py) combine per-hop partial
outputs exactly.

On CPU (tests / CI sim) the kernels run in Pallas interpreter mode
automatically, so the same code path is exercised everywhere.
"""

from __future__ import annotations

import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30  # finite "minus infinity": avoids (-inf) - (-inf) NaNs

_LANES = 128  # m/l scratch keeps a full lane dim for layout friendliness

# Tuned (block_q, block_k) by device_kind substring, measured by the
# autotuner (tools/flash_tune.py — run it on a new chip generation and add
# a row; current data: docs/FLASH_TUNE_v5e.json).  _FALLBACK_TILES covers
# unmeasured chips and the CPU interpreter, and stays conservative on
# purpose: (1024, 1024) was measured fastest on v5e ONLY — an unmeasured
# generation gets the safe small tiles (no VMEM-pressure surprises), and
# earns larger ones the day flash_tune.py runs on it.
_TUNED_TILES = (
    ("v5 lite", (1024, 1024)),
    ("v5e", (1024, 1024)),
)
_FALLBACK_TILES = (256, 512)


@functools.lru_cache(maxsize=None)
def _tiles_for(device_kind: str) -> Tuple[int, int]:
    dk = device_kind.lower()
    for sub, tiles in _TUNED_TILES:
        if sub in dk:
            return tiles
    if jax.default_backend() != "cpu":
        # once per kind (lru_cache): a mis-tiled accelerator run must be
        # visible, or fallback-served chips silently bench below potential
        import logging

        logging.getLogger(__name__).warning(
            "flash_attention: no autotuned tile row for device_kind=%r; "
            "serving conservative fallback %s — run tools/flash_tune.py on "
            "this chip and add a _TUNED_TILES row", device_kind,
            _FALLBACK_TILES)
    return _FALLBACK_TILES


def default_tiles() -> Tuple[int, int]:
    """(block_q, block_k) for the attached chip — autotuned when measured,
    :data:`_FALLBACK_TILES` otherwise.  The device kind is re-read on every
    call (only the per-kind lookup is cached): a process can switch
    backends mid-run (bench.py's CPU fallback does exactly that), so a
    transient failure or an interpreter-mode first trace must not pin the
    wrong tiles."""
    try:
        dk = jax.devices()[0].device_kind
    except Exception:
        return _FALLBACK_TILES
    return _tiles_for(dk)


def _interpret() -> bool:
    return jax.default_backend() == "cpu"


def _compiler_params():
    if _interpret():
        return None
    return pltpu.CompilerParams(
        dimension_semantics=("parallel", "parallel", "arbitrary")
    )


def mha_reference(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    window: Optional[int] = None,
) -> jnp.ndarray:
    """Plain softmax(QK^T)V golden — [B, H, S, D] layout.  Grouped-query
    attention: ``k``/``v`` may carry fewer heads (H_q % H_kv == 0); each
    group of ``H_q // H_kv`` consecutive query heads attends to one shared
    KV head."""
    if k.shape[1] != q.shape[1]:
        g, rem = divmod(q.shape[1], k.shape[1])
        assert rem == 0, (q.shape, k.shape)
        k = jnp.repeat(k, g, axis=1)
        v = jnp.repeat(v, g, axis=1)
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * sm_scale
    if causal:
        Sq, Sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((Sq, Sk), dtype=bool), k=Sk - Sq)
        if window is not None:
            # Mistral semantics: key in (qpos - window, qpos]
            mask = mask & jnp.triu(
                jnp.ones((Sq, Sk), dtype=bool), k=Sk - Sq - window + 1)
        s = jnp.where(mask, s, NEG_INF)
    elif window is not None:
        raise ValueError("sliding window requires causal attention")
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)


def _out_struct(shape, dtype, like):
    """ShapeDtypeStruct carrying the vma of ``like`` — required for
    pallas_call under shard_map (check_vma=True)."""
    from ..compat import typeof

    vma = getattr(typeof(like), "vma", None)
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def _causal_hi(qi, block_q, block_k, num_kv):
    """Number of KV blocks a causal row-block attends to (incl. diagonal)."""
    hi = jax.lax.div((qi + 1) * block_q + block_k - 1, block_k)
    return jnp.minimum(hi, num_kv)


def _window_lo(qi, block_q, block_k, window):
    """First KV block with any in-window key for q row-block ``qi``
    (lowest needed key position = qi*block_q - window + 1)."""
    return jnp.maximum(jax.lax.div(qi * block_q - window + 1, block_k), 0)


def _window_mask(s, qi, kj, block_q, block_k, window):
    """Causal + sliding-window in-block mask: key in (qpos-window, qpos]."""
    qpos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    kpos = kj * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    keep = kpos <= qpos
    if window is not None:
        keep = keep & (kpos > qpos - window)
    return jnp.where(keep, s, NEG_INF)


# ------------------------------------------------------------------- forward


def _fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
    *, sm_scale, causal, num_kv, window=None,
):
    block_q = q_ref.shape[1]
    block_k = k_ref.shape[1]
    qi = pl.program_id(1)
    kj = pl.program_id(2)

    hi = _causal_hi(qi, block_q, block_k, num_kv) if causal else num_kv
    lo = _window_lo(qi, block_q, block_k, window) if window is not None else 0

    @pl.when(kj == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    @pl.when((kj >= lo) & (kj < hi))
    def _compute():
        q = q_ref[0]  # [Bq, D] storage dtype — MXU takes bf16 in, f32 out
        kblk = k_ref[0]
        vblk = v_ref[0]
        m = m_ref[:, :1]
        l = l_ref[:, :1]
        s = jnp.dot(q, kblk.T, preferred_element_type=jnp.float32) * sm_scale
        if causal:
            s = _window_mask(s, qi, kj, block_q, block_k, window)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * corr + jnp.dot(
            p.astype(vblk.dtype), vblk, preferred_element_type=jnp.float32
        )
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(kj == hi - 1)
    def _write():
        m = m_ref[:, :1]
        l = l_ref[:, :1]
        o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)
        lse_ref[0] = m + jnp.log(l)  # [Bq, 1]


def _fwd(q, k, v, sm_scale, causal, block_q, block_k, groups=1, window=None):
    BH, Sq, D = q.shape
    Sk = k.shape[1]
    num_kv = Sk // block_k
    grid = (BH, Sq // block_q, num_kv)
    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal, num_kv=num_kv,
        window=window,
    )
    # GQA: q is flattened [B*Hq, ...] b-major with the G q-heads of a group
    # consecutive, kv is [B*Hkv, ...] — kv block for q-program b is b//G
    # (an index_map, not a materialized repeat)
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b // groups, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b // groups, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            _out_struct((BH, Sq, D), q.dtype, q),
            _out_struct((BH, Sq, 1), jnp.float32, q),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),       # acc
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # m
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # l
        ],
        compiler_params=_compiler_params(),
        interpret=_interpret(),
    )(q, k, v)
    return o, lse


# ------------------------------------------------------------------ backward


def _bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_acc_ref,
    *, sm_scale, causal, num_kv, window=None,
):
    block_q = q_ref.shape[1]
    block_k = k_ref.shape[1]
    qi = pl.program_id(1)
    kj = pl.program_id(2)

    hi = _causal_hi(qi, block_q, block_k, num_kv) if causal else num_kv
    lo = _window_lo(qi, block_q, block_k, window) if window is not None else 0

    @pl.when(kj == 0)
    def _init():
        dq_acc_ref[...] = jnp.zeros_like(dq_acc_ref)

    @pl.when((kj >= lo) & (kj < hi))
    def _compute():
        q = q_ref[0]
        do = do_ref[0]
        lse = lse_ref[0]  # [Bq, 1]
        delta = delta_ref[0]
        kblk = k_ref[0]
        vblk = v_ref[0]
        s = jnp.dot(q, kblk.T, preferred_element_type=jnp.float32) * sm_scale
        if causal:
            s = _window_mask(s, qi, kj, block_q, block_k, window)
        p = jnp.exp(s - lse)  # [Bq, Bk]
        dp = jnp.dot(do, vblk.T, preferred_element_type=jnp.float32)
        ds = (p * (dp - delta)).astype(kblk.dtype)
        dq_acc_ref[...] = dq_acc_ref[...] + jnp.dot(
            ds, kblk, preferred_element_type=jnp.float32
        )

    @pl.when(kj == hi - 1)
    def _write():
        dq_ref[0] = (dq_acc_ref[...] * sm_scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    dk_acc_ref, dv_acc_ref,
    *, sm_scale, causal, num_q, window=None,
):
    block_q = q_ref.shape[1]
    block_k = k_ref.shape[1]
    ki = pl.program_id(1)
    qi = pl.program_id(2)

    # causal: only q blocks at or after this kv block contribute; a window
    # additionally bounds ABOVE (no q past kpos_max + window - 1 sees it)
    lo = jax.lax.div(ki * block_k, block_q) if causal else 0
    if window is not None:
        hi_q = jnp.minimum(
            jax.lax.div((ki + 1) * block_k - 1 + window - 1, block_q) + 1,
            num_q)
    else:
        hi_q = num_q

    @pl.when(qi == 0)
    def _init():
        dk_acc_ref[...] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[...] = jnp.zeros_like(dv_acc_ref)

    @pl.when((qi >= lo) & (qi < hi_q))
    def _compute():
        k = k_ref[0]
        v = v_ref[0]
        q = q_ref[0]
        do = do_ref[0]
        lse = lse_ref[0]  # [Bq, 1]
        delta = delta_ref[0]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * sm_scale  # [Bq, Bk]
        if causal:
            s = _window_mask(s, qi, ki, block_q, block_k, window)
        p = jnp.exp(s - lse)
        dv_acc_ref[...] = dv_acc_ref[...] + jnp.dot(
            p.T.astype(do.dtype), do, preferred_element_type=jnp.float32
        )
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = (p * (dp - delta)).astype(q.dtype)
        dk_acc_ref[...] = dk_acc_ref[...] + jnp.dot(
            ds.T, q, preferred_element_type=jnp.float32
        )

    @pl.when(qi == num_q - 1)
    def _write():
        dk_ref[0] = (dk_acc_ref[...] * sm_scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_acc_ref[...].astype(dv_ref.dtype)


def _bwd(sm_scale, causal, block_q, block_k, groups, window, res, cts):
    q, k, v, o, lse = res
    dout, dlse = cts
    BH, Sq, D = q.shape
    Sk = k.shape[1]
    num_q = Sq // block_q
    num_kv = Sk // block_k
    # delta is the standard flash rowsum(do * o); a cotangent on lse folds in
    # exactly here: d lse_i / d s_ij = p_ij, so ds += dlse_i * p_ij, i.e.
    # delta' = delta - dlse.
    delta = jnp.sum(
        dout.astype(jnp.float32) * o.astype(jnp.float32), axis=-1, keepdims=True
    )  # [BH, Sq, 1]
    if dlse is not None:
        delta = delta - dlse.astype(jnp.float32)

    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, sm_scale=sm_scale, causal=causal, num_kv=num_kv,
            window=window,
        ),
        grid=(BH, num_q, num_kv),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b // groups, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b // groups, j, 0)),
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
        out_shape=_out_struct(q.shape, q.dtype, q),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        compiler_params=_compiler_params(),
        interpret=_interpret(),
    )(q, k, v, dout, lse, delta)

    # GQA: the dkv kernel stays per-Q-HEAD (grid dim 0 = B*Hq, kv blocks
    # read via b//G) — G programs writing one kv output block would race,
    # so each q head writes its own partial [B*Hq, Sk, D] (f32 when G > 1)
    # and the group-sum happens outside as a fused XLA reduction.
    dkv_dtype = k.dtype if groups == 1 else jnp.float32
    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, sm_scale=sm_scale, causal=causal, num_q=num_q,
            window=window,
        ),
        grid=(BH, num_kv, num_q),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b // groups, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b // groups, j, 0)),
            pl.BlockSpec((1, block_q, D), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            _out_struct((BH, Sk, D), dkv_dtype, k),
            _out_struct((BH, Sk, D), dkv_dtype, v),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        compiler_params=_compiler_params(),
        interpret=_interpret(),
    )(q, k, v, dout, lse, delta)
    if groups > 1:
        BHkv = BH // groups
        dk = dk.reshape(BHkv, groups, Sk, D).sum(axis=1).astype(k.dtype)
        dv = dv.reshape(BHkv, groups, Sk, D).sum(axis=1).astype(v.dtype)
    return dq, dk, dv


# ------------------------------------------------------------------ public op


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash(q, k, v, sm_scale, causal, block_q, block_k, groups=1, window=None):
    return _fwd(q, k, v, sm_scale, causal, block_q, block_k, groups, window)


def _flash_fwd_rule(q, k, v, sm_scale, causal, block_q, block_k, groups=1,
                    window=None):
    o, lse = _fwd(q, k, v, sm_scale, causal, block_q, block_k, groups, window)
    # Name the kernel's residuals so rematerialization policies can elect to
    # save them: under jax.checkpoint with
    # save_only_these_names('flash_out', 'flash_lse') (scan_blocks
    # remat='flash') the backward reuses o/lse instead of re-running the
    # Pallas forward kernel — the recompute replays only the cheap qkv
    # einsum, cutting the remat recompute by the whole attention fwd at
    # [B, S, D] (+ lse) bf16 of extra saved bytes per block.  Without such a
    # policy the tags are inert identities.
    from jax.ad_checkpoint import checkpoint_name

    o = checkpoint_name(o, "flash_out")
    lse = checkpoint_name(lse, "flash_lse")
    return (o, lse), (q, k, v, o, lse)


def _flash_bwd_rule(sm_scale, causal, block_q, block_k, groups, window,
                    res, cts):
    return _bwd(sm_scale, causal, block_q, block_k, groups, window, res, cts)


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def _prep(q, k, v, sm_scale, block_q, block_k):
    B, H, Sq, D = q.shape
    Hkv = k.shape[1]
    Sk = k.shape[2]
    groups, rem = divmod(H, Hkv)
    if rem:
        raise ValueError(
            f"GQA needs q heads divisible by kv heads, got {H} vs {Hkv}")
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(D)
    if block_q is None or block_k is None:
        tq, tk = default_tiles()
        block_q = tq if block_q is None else block_q
        block_k = tk if block_k is None else block_k
    # clamp to the sequence, then shrink to an exact divisor (gcd) so any
    # shard length works — e.g. ring shards of 384 with block_q=256 use 128
    block_q = math.gcd(min(block_q, Sq), Sq)
    block_k = math.gcd(min(block_k, Sk), Sk)
    qf = q.reshape(B * H, Sq, D)
    kf = k.reshape(B * Hkv, Sk, D)
    vf = v.reshape(B * Hkv, Sk, D)
    return qf, kf, vf, float(sm_scale), int(block_q), int(block_k), int(groups)


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    window: Optional[int] = None,
) -> jnp.ndarray:
    """Blockwise (flash) attention.  [B, H, S, D] layout, differentiable.

    ``window``: sliding-window attention (Mistral semantics — query q
    attends keys in ``(q - window, q]``; requires ``causal``).  Both the
    in-block mask AND the KV block range are bounded (``_window_lo``), so
    compute drops to O(S*window) like the causal bound drops it to half.

    **Grouped-query attention**: ``k``/``v`` may carry fewer heads than
    ``q`` (``H_q % H_kv == 0`` — MQA is ``H_kv == 1``); each group of
    ``H_q // H_kv`` consecutive query heads shares one KV head.  The kv
    tiles are NEVER materialized per-group: the kernels' kv BlockSpecs
    index ``b // G``, so a KV block is DMA'd once per group, and the
    dk/dv group-sum is a fused XLA reduction outside the kernel.  Grads
    return in the kv heads' own shape.

    Block sizes are clamped to the sequence lengths and shrunk (gcd) to exact
    divisors of S, so any shard length traces; power-of-two S keeps the
    requested blocks.  Pad upstream if S is prime-ish and perf matters.

    ``block_q``/``block_k`` default to :func:`default_tiles` — the per-chip
    autotuned sizes (tools/flash_tune.py, docs/FLASH_TUNE_v5e.json): at the
    bench shape [8, 12, 2048, 64] on v5e, (1024, 1024) runs the fwd+bwd
    1.8x faster than the previous (256, 512) default — larger tiles
    amortize the per-grid-step scratch init/rescale overhead and keep the
    MXU busier; VMEM per program stays ~2 MB, well under budget at
    head_dim 64.
    """
    if window is not None and not causal:
        raise ValueError("sliding window requires causal attention")
    if window is not None and window < 1:
        raise ValueError(f"window must be >= 1, got {window}")
    B, H, Sq, D = q.shape
    qf, kf, vf, sm_scale, block_q, block_k, groups = _prep(
        q, k, v, sm_scale, block_q, block_k)
    o, _ = _flash(qf, kf, vf, sm_scale, bool(causal), block_q, block_k,
                  groups, None if window is None else int(window))
    return o.reshape(B, H, Sq, D)


def flash_attention_with_lse(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Like :func:`flash_attention` but also returns the per-row logsumexp
    ``[B, H, S]`` (f32), differentiably.

    This is the composition point for ring / Ulysses context parallelism:
    per-hop partial outputs combine exactly via
    ``o = sum_i exp(lse_i - lse_total) * o_i`` with
    ``lse_total = logaddexp_i(lse_i)`` (ops/ring_attention.py).
    """
    B, H, Sq, D = q.shape
    qf, kf, vf, sm_scale, block_q, block_k, groups = _prep(
        q, k, v, sm_scale, block_q, block_k)
    o, lse = _flash(qf, kf, vf, sm_scale, bool(causal), block_q, block_k, groups)
    return o.reshape(B, H, Sq, D), lse.reshape(B, H, Sq)
