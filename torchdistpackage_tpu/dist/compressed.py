"""Quantized collectives — an XLA-native take on EQuARX
("Efficient Quantized AllReduce in XLA", arXiv 2506.17615, PAPERS.md): cut
the bytes a grad/activation collective moves over ICI/DCN by carrying int8
payloads through manual ppermute rings, requantizing per hop exactly the
way the paper does inside XLA's all-reduce stages.

The ring family (all traced; call inside shard_map):

- :func:`int8_ring_pmean`          — mean all-reduce (DP grad sync)
- :func:`int8_ring_reduce_scatter` — sum reduce-to-owner (ZeRO / FSDP
  backward; custom VJP: its transpose is the int8 ring all-gather)
- :func:`int8_ring_all_gather`     — gather (FSDP param prefetch, TP/SP
  activation boundaries; custom VJP: transpose is the int8 reduce-scatter,
  so a compressed forward gather buys a compressed backward scatter for
  free)
- :func:`int8_psum_all_gather`     — gather with an INVARIANCE-typed
  result (masked int8 psum) for sites whose out_specs drop the axis
  (ZeRO's param re-gather)
- :func:`ef_compress`              — input-side error feedback: round-trip
  a leaf through the quantizer and return the residual, so repeated lossy
  reductions don't accumulate bias (``ZeroOptimizer(grad_compress=
  'int8_ef')`` carries the residual in the optimizer state)

Ring idiom: the hop loops are **python-unrolled** (the PR-3
``ring_ag_matmul`` idiom, tp_utils.py) rather than ``lax.scan``-rolled.
Three reasons: XLA's latency-hiding scheduler sees n-1 independent
ppermute/compute pairs instead of a serialized while-loop body; AD/
custom-VJP plumbing stays trivial; and — the observability reason — the
HLO comm ledger counts each hop's payload as its own instruction, so the
ledger's per-axis bytes account the compressed wire traffic (s8 chunks +
f32 scale sideband) **correctly** instead of undercounting a while body
by the trip count (comm_ledger.py's known loop limitation).

Quantization: symmetric per-group int8 (:data:`GROUP` elements per f32
scale — ~1.5% sideband at the f32 wire rate).  Wire cost per element vs a
4-byte payload: ~4x fewer bytes for one ring pass (reduce-scatter /
all-gather), ~2.7x for the mean-all-reduce (ring pass + invariance-typed
int8 psum gather — the psum, not a cheaper varying-typed all_gather, is
what keeps the result a legal ``pmean`` drop-in under
``shard_map(check_vma=True)`` so compression composes with TP/PP meshes).
Noise per hop is bounded by ``group_amax / 127``; the tests bound the
numeric error and the A/B parity harness (obs/parity.py) checks
end-to-end training stays ``bounded``.

The decision loop: :func:`auto_compress_policy` scores each leaf's
collective through ``CommModel.predict_compressed`` (calibrated per-axis
alpha-beta; bytes quarter, the latency term and quant FLOPs don't) into a
per-leaf compress/exact policy — ``grad_compress='auto'`` on
``DataParallel`` / ``ZeroOptimizer`` consumes it and records the choices
as a structured ``compress_policy`` event (docs/compression.md).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Sequence, Tuple

import jax

from ..compat import axis_size
import jax.numpy as jnp


GROUP = 256  # elements per quantization scale (1.5% f32-scale overhead)

#: every ``grad_compress=`` knob in the package validates against this set
#: ('int8_ef' is ZeRO-only: the residual needs persistent optimizer state)
COMPRESS_MODES = (None, "int8", "int8_ef", "auto")


def _mark_varying(x, axis: str):
    """Mark ``x`` varying over ``axis`` if it isn't already (idempotent —
    same contract as parallel.data_parallel._mark_varying, duplicated here
    to keep dist/ import-independent of parallel/)."""
    from ..compat import pvary, typeof

    if axis in getattr(typeof(x), "vma", frozenset()):
        return x
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, (axis,), to="varying")
    return pvary(x, (axis,))


def _group_size(n: int) -> int:
    """Largest power of two <= GROUP dividing n (n is a static chunk size)."""
    g = 1
    while g * 2 <= GROUP and n % (g * 2) == 0:
        g *= 2
    return g


def _quant(x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric int8 quantization with PER-GROUP scales: a single per-chunk
    scale lets a few outlier elements wash out the rest of the chunk (quant
    noise ~ amax/127 per element regardless of magnitude), which accumulates
    over the ring's n-1 requantization hops into noise comparable to typical
    gradient values.  Per-group scales keep the noise proportional to the
    LOCAL amax.  x: [c] -> (q [c] int8, scales [c/g] f32)."""
    c = x.shape[0]
    g = _group_size(c)
    grouped = x.reshape(-1, g)
    scale = jnp.maximum(jnp.max(jnp.abs(grouped), axis=1), 1e-30) / 127.0
    q = jnp.clip(jnp.round(grouped / scale[:, None]), -127, 127).astype(jnp.int8)
    return q.reshape(c), scale.astype(jnp.float32)


def _dequant(q: jnp.ndarray, scale: jnp.ndarray) -> jnp.ndarray:
    c = q.shape[0]
    g = c // scale.shape[0]
    return (q.astype(jnp.float32).reshape(-1, g) * scale[:, None]).reshape(c)


def ef_compress(x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Input-side error feedback (Karimireddy et al., "Error Feedback
    Fixes SignSGD"): round-trip ``x`` through the block-scaled int8
    quantizer and return ``(x_q, residual)`` with ``residual = x - Q(x)``
    (f32, same shape as ``x``).

    The caller adds the PREVIOUS step's residual before compressing
    (``x = g + e``) and persists the new residual — so the quantization
    error of each step is re-fed instead of discarded, and the lossy
    reduction's bias cancels over steps instead of accumulating.  The
    ring's per-hop requantization of PARTIAL SUMS adds further (unbiased,
    bounded) noise the local residual cannot see; the input-side term is
    the systematic one.  Used by ``ZeroOptimizer(grad_compress='int8_ef')``,
    which carries the residual in the optimizer state."""
    flat = x.reshape(-1).astype(jnp.float32)
    q, s = _quant(flat)
    xq = _dequant(q, s)
    return (
        xq.reshape(x.shape).astype(x.dtype),
        (flat - xq).reshape(x.shape),
    )


# ----------------------------------------------------------- ring kernels
# Raw (non-custom-vjp) implementations; python-unrolled hop loops (the
# PR-3 ring_ag_matmul idiom) so the scheduler, AD and the HLO comm ledger
# all see n-1 distinct ppermute instructions.


def _ring_perm(n: int):
    return [(i, (i + 1) % n) for i in range(n)]


def _ring_reduce_scatter(g: jnp.ndarray, axis: str, scatter_dim: int) -> jnp.ndarray:
    n = axis_size(axis)
    if g.shape[scatter_dim] % n != 0:
        raise ValueError(
            f"scatter dim {scatter_dim} of size {g.shape[scatter_dim]} must "
            f"divide by the {axis!r} axis size {n} (same contract as tiled "
            f"psum_scatter)")
    if n == 1:
        return jax.lax.psum_scatter(
            g, axis, scatter_dimension=scatter_dim, tiled=True)

    gm = jnp.moveaxis(g, scatter_dim, 0).astype(jnp.float32)
    rest = gm.shape[1:]
    tile = gm.shape[0] // n
    chunks = gm.reshape(n, -1)  # chunk c = tile c of scatter_dim (C-order)
    # the ring's payloads are axis-varying by construction (idx-indexed); an
    # invariance-typed input (e.g. a fully-replicated grad leaf) must be
    # cast up front or ppermute's operand types mismatch
    chunks = _mark_varying(chunks, axis)
    idx = jax.lax.axis_index(axis)
    fwd = _ring_perm(n)

    def chunk(c):
        return jax.lax.dynamic_index_in_dim(chunks, c, axis=0, keepdims=False)

    # Ring schedule: rank r starts by sending chunk r-1; each hop adds the
    # LOCAL value of the travelling chunk and requantizes the partial sum
    # for the next hop.  After n-1 hops rank r holds exactly chunk r fully
    # reduced — psum_scatter's tiling contract.  The accumulator stays
    # f32; only the per-hop payload is int8 (+ f32 scales).
    send_q, send_s = _quant(chunk(jnp.mod(idx - 1, n)))
    part = None
    for t in range(n - 1):
        recv_q = jax.lax.ppermute(send_q, axis, fwd)
        recv_s = jax.lax.ppermute(send_s, axis, fwd)
        part = chunk(jnp.mod(idx - t - 2, n)) + _dequant(recv_q, recv_s)
        if t < n - 2:
            send_q, send_s = _quant(part)
    out = jnp.moveaxis(part.reshape((tile,) + rest), 0, scatter_dim)
    return out.astype(g.dtype)


def _ring_all_gather(x: jnp.ndarray, axis: str, gather_dim: int) -> jnp.ndarray:
    n = axis_size(axis)
    if n == 1:
        return jax.lax.all_gather(x, axis, axis=gather_dim, tiled=True)
    xm = jnp.moveaxis(x, gather_dim, 0)
    tile, rest = xm.shape[0], xm.shape[1:]
    flat = _mark_varying(xm.reshape(-1).astype(jnp.float32), axis)
    idx = jax.lax.axis_index(axis)
    fwd = _ring_perm(n)

    # quantize the local shard ONCE; raw quantized chunks travel the ring
    # and every rank (the owner included) assembles the DEQUANTIZED values
    # — all ranks hold the identical gathered tensor, exactly as with
    # all_gather, just at quantized precision.
    cur_q, cur_s = _quant(flat)
    out = jnp.zeros((n,) + flat.shape, jnp.float32)
    for k in range(n):
        owner = jnp.mod(idx - k, n)  # ring flows +1: we hold shard idx-k's x
        out = jax.lax.dynamic_update_index_in_dim(
            out, _dequant(cur_q, cur_s), owner, axis=0)
        if k < n - 1:
            cur_q = jax.lax.ppermute(cur_q, axis, fwd)
            cur_s = jax.lax.ppermute(cur_s, axis, fwd)
    full = jnp.moveaxis(out.reshape((n * tile,) + rest), 0, gather_dim)
    return full.astype(x.dtype)


# ------------------------------------------------------- public ring ops
# reduce-scatter and all-gather are each other's transpose (exactly like
# psum_scatter <-AD-> all_gather), but AD cannot differentiate through
# round/clip — the custom VJPs pair them explicitly, so a compressed
# forward collective buys a compressed backward collective for free:
# FSDP's int8 param all-gather transposes into the int8 per-leaf grad
# reduce-scatter inside the backward; TP's int8 activation gather
# transposes into an int8 activation-grad scatter.


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def int8_ring_reduce_scatter(
    g: jnp.ndarray, axis: str, scatter_dim: int
) -> jnp.ndarray:
    """``psum_scatter(..., tiled=True)`` with int8 wire format: rank r of
    the mesh ``axis`` receives the SUM over the axis of tile r of
    ``scatter_dim`` (caller normalizes).  Traced; call inside shard_map.

    This is the ZeRO reduce-to-owner (zero_optim.py:203): grads only ever
    travel *toward* their owner shard, so the whole reduction is one ring
    pass — (n-1)/n int8 bytes per element on the wire (+ ~1.5% scales) vs
    4(n-1)/n for the f32 ``psum_scatter`` it replaces: ~4x fewer wire
    bytes, and still 2x under a hypothetical bf16 wire.  Like
    ``psum_scatter`` itself, ``scatter_dim`` must divide by the axis size
    (ZeRO's ``zero_partition_spec`` only ever picks such dims; leaves with
    no divisible dim stay replicated and never reach this path).

    Differentiable: the VJP is :func:`int8_ring_all_gather` of the
    cotangent (the transpose pairing of psum_scatter/all_gather, kept
    quantized) — so the op is legal INSIDE a forward pass (TP's
    row-parallel close into SP layout) as well as on grads."""
    return _ring_reduce_scatter(g, axis, scatter_dim)


def _rs_fwd(g, axis, scatter_dim):
    return _ring_reduce_scatter(g, axis, scatter_dim), None


def _rs_bwd(axis, scatter_dim, _res, ct):
    return (_ring_all_gather(ct, axis, scatter_dim),)


int8_ring_reduce_scatter.defvjp(_rs_fwd, _rs_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def int8_ring_all_gather(
    x: jnp.ndarray, axis: str, gather_dim: int
) -> jnp.ndarray:
    """``all_gather(..., tiled=True)`` with int8 wire format: every rank
    assembles the full array along ``gather_dim`` from quantized shard
    payloads (1 byte/elem + ~1.5% scale sideband on the wire vs 4 for
    f32).  Each rank's own shard is ALSO round-tripped through the
    quantizer, so all ranks hold the identical tensor (all_gather's
    replication contract at quantized precision).  Traced; call inside
    shard_map.  The result is varying-typed over ``axis``, like
    ``all_gather`` — for sites whose out_specs need an invariance-typed
    gather use :func:`int8_psum_all_gather`.

    VJP: :func:`int8_ring_reduce_scatter` of the cotangent — FSDP's
    quantized param gather therefore emits the quantized per-leaf grad
    reduce-scatter inside the backward, at the point the leaf's grad is
    produced (fsdp.make_overlap_train_step(grad_compress='int8'))."""
    return _ring_all_gather(x, axis, gather_dim)


def _ag_fwd(x, axis, gather_dim):
    return _ring_all_gather(x, axis, gather_dim), None


def _ag_bwd(axis, gather_dim, _res, ct):
    return (_ring_reduce_scatter(ct, axis, gather_dim),)


int8_ring_all_gather.defvjp(_ag_fwd, _ag_bwd)


def int8_psum_all_gather(x: jnp.ndarray, axis: str, gather_dim: int) -> jnp.ndarray:
    """All-gather with int8 payload and an **invariance-typed** result:
    each rank scatters its quantized shard into a zeroed [n, ...] buffer
    and a psum assembles the full tensor (every position has exactly one
    non-zero contributor, so int8 addition is exact) — the same masked-
    psum idiom as :func:`int8_ring_pmean`'s gather leg.

    Use where the consumer's out_specs DROP the axis (ZeRO's master ->
    param re-gather pins the output to the TP-only param sharding): a
    ring/all_gather result is varying-typed over the axis and would be
    rejected there under ``check_vma=True``.  Wire cost 2(n-1)/n int8
    bytes/elem — above the ring's (n-1)/n, but 2x under a bf16 all-gather
    and what invariant typing costs (see int8_ring_pmean's note)."""
    n = axis_size(axis)
    if n == 1:
        return x
    xm = jnp.moveaxis(x, gather_dim, 0)
    tile, rest = xm.shape[0], xm.shape[1:]
    flat = xm.reshape(-1).astype(jnp.float32)
    q, s = _quant(flat)
    idx = jax.lax.axis_index(axis)
    pq = jax.lax.dynamic_update_index_in_dim(
        jnp.zeros((n,) + q.shape, jnp.int8), q, idx, axis=0)
    ps_ = jax.lax.dynamic_update_index_in_dim(
        jnp.zeros((n,) + s.shape, jnp.float32), s, idx, axis=0)
    gq = jax.lax.psum(pq, axis)   # [n, c] int8, invariant over axis
    gs = jax.lax.psum(ps_, axis)  # [n, c/g] f32
    vals = jax.vmap(_dequant)(gq, gs)
    full = jnp.moveaxis(vals.reshape((n * tile,) + rest), 0, gather_dim)
    return full.astype(x.dtype)


def int8_ring_pmean(g: jnp.ndarray, axis: str) -> jnp.ndarray:
    """Mean of ``g`` over the mesh ``axis`` with int8 wire format (traced;
    call inside shard_map).  Falls back to exact ``pmean`` when the flat
    size doesn't divide by the axis size (ragged chunks) or the axis has a
    single member.

    Two legs: a ring reduce-scatter (n-1 unrolled requantizing hops — the
    :func:`int8_ring_reduce_scatter` schedule at offset 0), then a masked
    int8 **psum** of the finished owner chunks.  Why a psum rather than
    the cheaper int8 all_gather for the second leg: psum output is
    invariance-typed over the axis, so the function is a legal drop-in
    ``pmean`` under ``shard_map(check_vma=True)`` — grad compression
    therefore composes with TP/PP meshes, where the step's vma-driven
    bookkeeping (model-axis grad normalization, global-norm clip) must
    keep running.  Wire cost ~3(n-1)/n int8 bytes/elem total vs 8(n-1)/n
    for an f32 all-reduce (~2.7x; the pure all_gather variant's 4x is not
    reachable with invariant typing)."""
    n = axis_size(axis)
    if n == 1:
        # still a pmean: the caller is promised an invariance-TYPED result
        # (a bare return would stay varying-marked and fail check_vma at
        # the sharded out_specs); over a 1-member axis it's free
        return jax.lax.pmean(g, axis)
    flat = g.reshape(-1)
    if flat.shape[0] % n != 0:
        return jax.lax.pmean(g, axis)

    idx = jax.lax.axis_index(axis)
    chunks = _mark_varying(flat.reshape(n, -1).astype(jnp.float32), axis)
    fwd = _ring_perm(n)

    def chunk(c):
        return jax.lax.dynamic_index_in_dim(chunks, c, axis=0, keepdims=False)

    # ring reduce-scatter: rank r sends chunk r; after n-1 accumulate-
    # requantize hops THIS rank holds chunk (idx+1) % n fully reduced
    send_q, send_s = _quant(chunk(idx))
    part = None
    for t in range(n - 1):
        recv_q = jax.lax.ppermute(send_q, axis, fwd)
        recv_s = jax.lax.ppermute(send_s, axis, fwd)
        part = chunk(jnp.mod(idx - t - 1, n)) + _dequant(recv_q, recv_s)
        if t < n - 2:
            send_q, send_s = _quant(part)
    own_c = jnp.mod(idx + 1, n)
    owned = part / n

    # masked psum gather of the owned (mean) chunks, int8 on the wire —
    # see the docstring for why this leg is a psum, not an all_gather
    oq, os_ = _quant(owned)
    padded_q = jnp.zeros((n,) + oq.shape, jnp.int8)
    padded_q = jax.lax.dynamic_update_index_in_dim(padded_q, oq, own_c, axis=0)
    padded_s = jnp.zeros((n,) + os_.shape, jnp.float32)
    padded_s = jax.lax.dynamic_update_index_in_dim(padded_s, os_, own_c, axis=0)
    gq = jax.lax.psum(padded_q, axis)  # [n, c] int8, invariant over axis
    gs = jax.lax.psum(padded_s, axis)  # [n, c/g] f32
    out = jax.vmap(_dequant)(gq, gs)
    return out.reshape(g.shape).astype(g.dtype)


# ------------------------------------------------------------ auto policy


def auto_compress_policy(
    named_leaves: Sequence[Tuple[str, Tuple[int, ...], int]],
    op: str,
    axes: Sequence[str],
    mesh,
    model=None,
    min_size: int = 65536,
    group: int = GROUP,
) -> Tuple[Dict[str, bool], List[Dict[str, Any]]]:
    """Per-leaf compress/exact decisions from the alpha-beta cost model.

    ``named_leaves``: ``[(name, shape, dtype_itemsize)]`` — the grad
    leaves a step will reduce (names in the ``_key_str`` convention the
    reducers match on).  ``op``: the exact collective being replaced
    (``'all_reduce'`` for the DP pmean, ``'reduce_scatter'`` for ZeRO's
    reduce-to-owner).  Each leaf is scored through
    ``CommModel.predict_compressed`` (``model`` defaults to the table
    model for ``mesh``; pass ``CommModel.calibrate(...)`` for
    measurement-grounded decisions); the choice is *compressed predicted
    faster AND the leaf clears* ``min_size`` (tiny leaves stay exact —
    the scale sideband and ring latency dominate there, and a leaf whose
    flat size doesn't divide the axis would fall back anyway).

    Returns ``(policy, records)``: ``policy[name] -> bool`` for the
    reducers, and one record per leaf (bytes, both predictions, the
    choice) — the payload of the ``compress_policy`` event and the
    RUNREPORT ``compression`` section
    (``obs.comm_model.compression_report``)."""
    from ..obs.comm_model import CommModel

    if model is None:
        model = CommModel.from_defaults(mesh=mesh)
    n = 1
    for a in axes:
        n *= int(mesh.shape[a])
    policy: Dict[str, bool] = {}
    records: List[Dict[str, Any]] = []
    for name, shape, itemsize in named_leaves:
        size = 1
        for d in shape:
            size *= int(d)
        payload = size * itemsize
        pred = model.predict_compressed(
            op, payload, n, axes=tuple(axes), elem_bytes=itemsize, group=group)
        choose = bool(pred["compress"]) and size >= min_size
        policy[name] = choose
        records.append({
            "leaf": name,
            "elems": size,
            "bytes": payload,
            "op": op,
            "axes": list(axes),
            "compress": choose,
            "pred_exact_s": pred["exact_s"],
            "pred_compressed_s": pred["compressed_s"],
            "ledger_bytes_exact": pred["ledger_bytes_exact"],
            "ledger_bytes_compressed": pred["ledger_bytes_compressed"],
        })
    return policy, records
