"""Hang detection + cross-host consistency guards.

Two failure modes no amount of checkpointing fixes, because the run never
*crashes* — it just stops making progress or silently computes the wrong
thing:

- **Hangs**: a collective whose participant died blocks forever (the
  default ICI/DCN timeout is minutes-to-infinite); the SLURM babysitter
  sees a live process and never relaunches.  :class:`Watchdog` runs a
  daemon heartbeat thread: the loop calls :meth:`Watchdog.beat` each step,
  and a beat gap over ``timeout_s`` escalates ``hang_suspected`` →
  (optionally, after a further grace) a hard ``os._exit`` so the
  babysitter *can* relaunch.
- **Silent desync**: replicas that should be bit-identical drift apart
  (a host loaded stale code, a data loader double-served a shard, a
  collective was dropped) and training continues producing garbage.
  :func:`check_consistency` allgathers a cheap per-host fingerprint —
  step counter, config hash, code hash, RNG key, a low-cost param-tree
  checksum — and turns any disagreement into a loud ``desync_detected``
  event.  Run it at startup and every N steps
  (``ResilientLoop(consistency_every=N)``).

Both guards are collective-free on single-process runs and cost one small
``process_allgather`` per check on pods.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

# --------------------------------------------------------------- watchdog


class Watchdog:
    """Heartbeat-gap detector (daemon thread; context manager).

    ::

        with Watchdog(timeout_s=300, abort=True) as dog:
            for step in range(start, total):
                dog.beat(step)
                ...

    - gap > ``timeout_s``    → ``hang_suspected`` event (once per episode)
    - beat arrives after one → ``hang_resolved`` event
    - gap > ``timeout_s + abort_grace_s`` with ``abort=True`` →
      ``hang_abort`` event then ``os._exit(exit_code)`` — the process
      must *die*, not unwind: the stuck collective would swallow any
      exception, and the babysitter's relaunch is the recovery.
    """

    def __init__(
        self,
        timeout_s: float = 300.0,
        poll_s: Optional[float] = None,
        abort: bool = False,
        abort_grace_s: Optional[float] = None,
        exit_code: int = 87,
        _exit: Optional[Callable[[int], None]] = None,  # test seam
    ) -> None:
        self.timeout_s = float(timeout_s)
        self.poll_s = poll_s if poll_s is not None else max(0.05, timeout_s / 4.0)
        self.abort = abort
        self.abort_grace_s = (
            float(abort_grace_s) if abort_grace_s is not None else self.timeout_s
        )
        self.exit_code = exit_code
        self._exit = _exit or os._exit
        self._last_beat = time.perf_counter()
        self._last_step: Optional[int] = None
        self._suspected = False
        self._stalled_since = self._last_beat
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.n_suspected = 0

    def beat(self, step: Optional[int] = None) -> None:
        """The loop is alive; call once per iteration (thread-safe)."""
        self._last_beat = time.perf_counter()
        if step is not None:
            self._last_step = int(step)

    def start(self) -> "Watchdog":
        if self._thread is not None:
            return self
        self._last_beat = time.perf_counter()
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._watch, name="tdp-watchdog", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def __enter__(self) -> "Watchdog":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _watch(self) -> None:
        from ..obs.events import emit_event

        while not self._stop.wait(self.poll_s):
            age = time.perf_counter() - self._last_beat
            if not self._suspected and age > self.timeout_s:
                self._suspected = True
                self.n_suspected += 1
                self._stalled_since = self._last_beat
                emit_event(
                    "hang_suspected", age_s=round(age, 3),
                    timeout_s=self.timeout_s, last_step=self._last_step,
                    will_abort=self.abort,
                )
            elif self._suspected and age <= self.timeout_s:
                self._suspected = False
                emit_event(
                    "hang_resolved", last_step=self._last_step,
                    stalled_for_s=round(self._last_beat - self._stalled_since, 3),
                )
            if (
                self.abort and self._suspected
                and age > self.timeout_s + self.abort_grace_s
            ):
                emit_event(
                    "hang_abort", age_s=round(age, 3),
                    last_step=self._last_step, exit_code=self.exit_code,
                )
                self._exit(self.exit_code)
                return  # only reached with an injected test _exit


# ----------------------------------------------------- consistency guards


def config_fingerprint(obj: Any) -> str:
    """Stable SHA-256 of any config-ish object (dict/dataclass/str) — the
    cross-host 'are we even running the same experiment' check."""
    try:
        blob = json.dumps(obj, sort_keys=True, default=repr)
    except TypeError:
        blob = repr(obj)
    return hashlib.sha256(blob.encode()).hexdigest()


def code_fingerprint(root: Optional[str] = None) -> str:
    """SHA-256 over the package's ``.py`` sources (sorted relpath +
    contents) — catches a host running stale code after a partial deploy.
    Computed once per process and cached (~70 small files)."""
    global _CODE_FP
    if root is None and _CODE_FP is not None:
        return _CODE_FP
    base = root or os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    h = hashlib.sha256()
    paths = []
    for dirpath, _dirs, files in os.walk(base):
        if "__pycache__" in dirpath:
            continue
        for f in files:
            if f.endswith(".py"):
                paths.append(os.path.join(dirpath, f))
    for p in sorted(paths):
        h.update(os.path.relpath(p, base).encode())
        with open(p, "rb") as f:
            h.update(f.read())
    digest = h.hexdigest()
    if root is None:
        _CODE_FP = digest
    return digest


_CODE_FP: Optional[str] = None


def param_checksum(tree: Any) -> float:
    """Low-cost host-side checksum of a param pytree: sum of ``|x|`` over
    every leaf's *locally addressable* shards.  On symmetric meshes (every
    host holds the same shard layout) in-sync hosts produce bit-identical
    sums; a replica whose weights drifted produces a different one.  Not a
    cryptographic digest — a cheap tripwire run every N steps."""
    import jax

    total = 0.0
    for leaf in jax.tree_util.tree_leaves(tree):
        if hasattr(leaf, "addressable_shards"):
            for sh in leaf.addressable_shards:
                total += float(np.sum(np.abs(np.asarray(sh.data, np.float64))))
        elif hasattr(leaf, "dtype") or np.isscalar(leaf):
            total += float(np.sum(np.abs(np.asarray(leaf, np.float64))))
    return total


def _hash_parts(hexdigest: str) -> List[float]:
    """Two 16-bit chunks of a hash as exact small floats — one chunk alone
    would collide too easily (the gather itself is exact: fingerprints
    travel bit-cast as int32 lanes, see :func:`_f64_to_lanes`)."""
    v = int(hexdigest[:16], 16)
    return [float(v % 65521), float((v // 65521) % 65521)]


def _f64_to_lanes(values: "Sequence[float]") -> np.ndarray:
    """Bit-cast a float64 vector to int32 lane pairs for the allgather.
    ``process_allgather`` is exact on int32, while a float32 gather would
    round step counters above 2**24 and param-checksum sums — small real
    drifts would compare equal and desyncs go unseen.  (Assumes one
    endianness across the pod, which any homogeneous slice satisfies.)"""
    return np.ascontiguousarray(np.asarray(values, np.float64)).view(np.int32)


def _lanes_to_f64(lanes: np.ndarray, n_components: int) -> np.ndarray:
    """Inverse of :func:`_f64_to_lanes` over a gathered ``(n_hosts, 2n)``
    int32 array → exact ``(n_hosts, n)`` float64 values."""
    arr = np.ascontiguousarray(np.asarray(lanes, np.int32))
    return arr.view(np.float64).reshape(arr.shape[0], n_components)


def consistency_fingerprint(
    step: Optional[int] = None,
    config: Any = None,
    params: Any = None,
    rng_key: Any = None,
    code: bool = False,
) -> "tuple[List[str], List[float]]":
    """(labels, values) — the per-host vector :func:`check_consistency`
    allgathers.  Only the components you pass are included, so the check
    costs exactly what you ask for (``params=`` walks the local shards;
    ``code=True`` hashes the package sources once per process)."""
    labels: List[str] = []
    values: List[float] = []
    if step is not None:
        labels.append("step")
        values.append(float(int(step)))
    if config is not None:
        labels += ["config_a", "config_b"]
        values += _hash_parts(config_fingerprint(config))
    if code:
        labels += ["code_a", "code_b"]
        values += _hash_parts(code_fingerprint())
    if rng_key is not None:
        labels.append("rng")
        try:
            import jax

            data = jax.random.key_data(rng_key)
        except (AttributeError, TypeError):
            data = rng_key
        values.append(float(np.asarray(data, np.float64).sum()))
    if params is not None:
        labels.append("params")
        values.append(param_checksum(params))
    return labels, values


def check_consistency(
    step: Optional[int] = None,
    config: Any = None,
    params: Any = None,
    rng_key: Any = None,
    code: bool = False,
    event_log=None,
    _gathered: Optional[np.ndarray] = None,
) -> Dict[str, Any]:
    """Cross-host agreement check; **collective** — call on every process.

    Returns ``{"ok", "n_hosts", "labels", "mismatched", "per_host"}``.  Any
    component on which hosts disagree lands in ``mismatched`` and emits one
    ``desync_detected`` event (on ``event_log`` or the process default)
    carrying the per-host values — silent desync becomes a loud artifact.

    ``_gathered`` is a test seam: a pre-gathered ``(n_hosts, n_components)``
    array standing in for the ``process_allgather``.
    """
    labels, values = consistency_fingerprint(
        step=step, config=config, params=params, rng_key=rng_key, code=code)
    if not labels:
        raise ValueError("check_consistency: nothing to check "
                         "(pass step/config/params/rng_key/code)")
    if _gathered is not None:
        gathered = np.asarray(_gathered, np.float64).reshape(-1, len(labels))
    else:
        try:
            import jax

            n_proc = jax.process_count()
        except Exception:  # backend not up: single-host semantics
            n_proc = 1
        if n_proc <= 1:
            gathered = np.asarray([values], np.float64)
        else:
            import jax.numpy as jnp
            from jax.experimental import multihost_utils

            # exact gather: float64 fingerprints travel bit-cast as int32
            # lanes (a float32 gather would round steps > 2**24 and the
            # float64 param checksums, hiding small real drifts)
            lanes = _f64_to_lanes(values)
            gathered = _lanes_to_f64(
                np.asarray(
                    multihost_utils.process_allgather(jnp.asarray(lanes))
                ).reshape(n_proc, lanes.size),
                len(labels),
            )

    mismatched = [
        labels[i] for i in range(len(labels))
        if not np.all(gathered[:, i] == gathered[0, i])
    ]
    out = {
        "ok": not mismatched,
        "n_hosts": int(gathered.shape[0]),
        "labels": labels,
        "mismatched": mismatched,
        "per_host": gathered.tolist(),
    }
    if mismatched:
        from ..obs.events import default_event_log

        (event_log or default_event_log()).emit(
            "desync_detected",
            step=step,
            mismatched=mismatched,
            per_host={
                lab: [gathered[h, labels.index(lab)] for h in range(out["n_hosts"])]
                for lab in mismatched
            },
        )
    return out
