"""ViT-MoE: the encoder MoE family (V-MoE style — Riquelme et al. 2021;
expert-choice routing per Zhou et al. 2022).

The reference has no vision-MoE model; this family exists because the
package's own causal guard makes the GPT family reject ``expert_choice``
routing — an encoder is where EC legitimately lives (each expert ranks the
whole patch sequence; there is no autoregressive order to leak).  Every
``moe_every``-th ViT block's FFN is the expert layer from
``parallel/moe.py`` (shared with GPT-MoE: same routing, same EP
all_to_alls, same dispatch materializations); causality is taken from
``cfg.block.causal`` — False for ViT, so both routers are available.

Reference capability provenance: MoE machinery analogue of
``torchdistpackage/ddp/naive_ddp.py:233-441`` + ``process_topo.py:118-143``
applied to the vision tower the reference pipelines in
``examples/model_parallel/test_pipeline.py:54-123``.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from ..parallel.moe import init_moe_params
from ..parallel.tensor_parallel import (
    RematMode,
    init_block_params,
    init_norm_params,
)
from .gpt_moe import (
    is_moe_block,
    moe_block_stack,
    moe_blocks_param_specs,
    moe_layer_config,
)
from .vit import ViTConfig, vit_embed, vit_param_specs, vit_pool_logits

PyTree = Any


def init_vit_moe_params(key, cfg: ViTConfig) -> Dict[str, PyTree]:
    """Like ``init_vit_params`` but blocks are a heterogeneous LIST with MoE
    blocks' ``mlp`` replaced by the expert layer params."""
    assert cfg.moe_experts > 0, "use init_vit_params for dense models"
    import math

    kp, kpos, kh, kb = jax.random.split(key, 4)
    dt = cfg.dtype
    mcfg = moe_layer_config(cfg)
    blocks: List[Dict[str, PyTree]] = []
    for i, k in enumerate(jax.random.split(kb, cfg.nlayers)):
        if is_moe_block(cfg, i):
            bp = init_block_params(k, cfg.block, mlp=False)
            bp["moe"] = init_moe_params(jax.random.fold_in(k, 1), mcfg)
        else:
            bp = init_block_params(k, cfg.block)
        blocks.append(bp)
    return {
        "patch_proj": {
            "w": (jax.random.normal(kp, (cfg.patch_dim, cfg.dim))
                  / math.sqrt(cfg.patch_dim)).astype(dt),
            "b": jnp.zeros((cfg.dim,), dt),
        },
        "pos_emb": (jax.random.normal(kpos, (cfg.num_patches, cfg.dim)) * 0.02).astype(dt),
        "blocks": blocks,
        "ln_f": init_norm_params(cfg.dim, dt, cfg.norm),
        "head": {
            "w": (jax.random.normal(kh, (cfg.dim, cfg.num_classes))
                  / math.sqrt(cfg.dim)).astype(dt),
            "b": jnp.zeros((cfg.num_classes,), dt),
        },
    }


def vit_moe_forward(
    params: Dict[str, PyTree],
    images: jnp.ndarray,
    cfg: ViTConfig,
    axis: Optional[str] = None,
    sp: bool = False,
    ep_axis: Optional[str] = None,
    dropout_key: Optional[jax.Array] = None,
    remat: RematMode = False,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """[B, H, W, C] images -> ([B, num_classes(/tp)] logits, mean aux loss
    over MoE blocks).  ``params['blocks']`` is the heterogeneous per-block
    list from :func:`init_vit_moe_params`.  ``remat`` checkpoints each
    block (False | True | 'flash' | 'flash_offload')."""
    h = vit_embed(params, images, cfg)
    if axis is not None and sp:
        from ..parallel.tensor_parallel import split_to_sp

        h = split_to_sp(h, axis)
    # the shared dense/expert loop; moe_block_forward reads causality from
    # cfg.block.causal — False here, so expert_choice routing is allowed
    h, aux_mean = moe_block_stack(
        params["blocks"], h, cfg, axis=axis, sp=sp, ep_axis=ep_axis,
        dropout_key=dropout_key, remat=remat,
    )
    return vit_pool_logits(params, h, cfg, axis=axis, sp=sp), aux_mean


def vit_moe_loss(
    params: Dict[str, PyTree],
    batch: Dict[str, jnp.ndarray],
    cfg: ViTConfig,
    axis: Optional[str] = None,
    sp: bool = False,
    ep_axis: Optional[str] = None,
    dropout_key: Optional[jax.Array] = None,
    remat: RematMode = False,
) -> jnp.ndarray:
    """Mean CE + ``cfg.moe_aux_weight`` x mean load-balance aux (identically
    0 under expert-choice routing).  ``batch``: {'images': [B, H, W, C],
    'labels': int [B]}."""
    from .gpt import vocab_parallel_xent

    logits, aux = vit_moe_forward(
        params, batch["images"], cfg, axis=axis, sp=sp, ep_axis=ep_axis,
        dropout_key=dropout_key, remat=remat,
    )
    tp = axis if logits.shape[-1] != cfg.num_classes else None
    ce = vocab_parallel_xent(logits, batch["labels"], tp)
    return ce + cfg.moe_aux_weight * aux.astype(ce.dtype)


def vit_moe_param_specs(
    cfg: ViTConfig,
    tp_axis: Optional[str] = None,
    ep_axis: Optional[str] = None,
) -> Dict[str, PyTree]:
    """:func:`..vit.vit_param_specs`' non-block entries + the MoE families'
    shared per-block spec list — each layout exists once."""
    specs = vit_param_specs(cfg, tp_axis=tp_axis)
    specs["blocks"] = moe_blocks_param_specs(cfg, tp_axis, ep_axis)
    return specs
