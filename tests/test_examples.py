"""CI smoke for every example script: each runs end-to-end on the 8-device
CPU sim in a subprocess (examples configure their own platform via
TDP_CPU_SIM, so they must NOT inherit this test process's JAX).  The analogue
of the reference treating its examples/ as the de-facto test suite
(SURVEY.md §4) — but actually wired into CI.

obs-integrated examples additionally get TDP_RUNREPORT pointed at a temp
file and must leave a schema-valid ``RUNREPORT.json`` behind — the driver
artifacts are self-reporting, not just exit-code-0."""

import json
import os
import pathlib
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

REPO = pathlib.Path(__file__).resolve().parent.parent
EXAMPLES = sorted(
    p.name
    for pat in ("train_*.py", "serve_*.py")
    for p in (REPO / "examples").glob(pat)
)

# Examples wired through obs.Telemetry: each must produce a valid
# RUNREPORT.json under the CI runner.  Per-example extra assertions probe
# the counters the example exists to report; ``comm`` names the ledger
# dimension the example's parallelism must show bytes for, and those
# examples also get TDP_TRACE pointed at a temp file that must come back
# as a valid Perfetto-loadable Chrome trace.
OBS_EXAMPLES = {
    "train_llama.py": {},
    # ``numerics`` probes the PR-7 section: train_tp_dp fuses
    # numerics_stats into its compiled step (healthy run: timeline + dtype
    # ledger, zero alerts); train_resilient's chaos NaN spike must appear
    # as a numerics_alert BEFORE the rollback event on the timeline
    # ``autoplan`` probes the PR-13 section: train_tp_dp's planner phase
    # plans the layout from the three cost models, proves the chosen plan
    # trains, and records the validated section + plan_selected event
    "train_tp_dp.py": {"comm": "dp", "memory": True, "numerics": "healthy",
                       "autoplan": True},
    "train_pipeline.py": {"counter": "pipeline", "field": "bubble_fraction"},
    "train_interleaved_pipeline.py": {
        "counter": "pipeline", "field": "bubble_fraction"},
    # zero-bubble A/B (PR 14): the report's pipeline section must carry
    # the validated zb-vs-1f1b bubble pair (validate_runreport enforces
    # zb strictly below the 1f1b reference) and the schedule-build events
    "train_zb_pipeline.py": {
        "counter": "pipeline", "field": "bubble_fraction", "zb": True},
    # ``autoplan`` additionally probes the PR-18 MoE planner phase: the
    # ep-arm enumeration, the chosen plan's GSPMD training proof, and the
    # validated section riding the same RUNREPORT
    "train_moe.py": {"counter": "moe", "field": "imbalance", "comm": "moe",
                     "autoplan": True},
    # overlap-audited examples (PR 3): GSPMD FSDP's param all-gathers and
    # the ZeRO owner-scatter both ledger onto the data axis.  ``memory``
    # probes the PR-6 mem-ledger section; for the FSDP example the probe
    # additionally demands SHARDED leaf evidence (resident < global) —
    # ZeRO-3 proven from the compiled program's own input layouts
    "train_fsdp_offload.py": {"comm": "dp", "memory": "sharded"},
    "train_zero_ema_ckpt.py": {"comm": "dp"},
    # self-healing loop (PR 4): chaos NaN spike -> rollback -> recovered;
    # the report must carry the resilience verdict AND the fault/rollback
    # events on its timeline
    "train_resilient.py": {"comm": "dp", "resilience": "recovered",
                           "numerics": "alert_before_rollback"},
    # continuous-batching engine (PR 5): the report must carry the serving
    # section (TTFT/TPOT, tokens/s, occupancy, pool) with the compile-once
    # evidence, plus the request lifecycle events.  "stress" (PR 9) adds
    # the per-priority percentiles + verdict and the SIGTERM drain demo's
    # engine_drained event
    "serve_gpt.py": {"serving": "stress"},
    # context-parallel long-context tier (PR 20): the serving section must
    # carry the ``long_context`` block (cp width, ring hop/byte totals that
    # reconcile with the hop model) and the cp_prefill_chunk / cp_ring_hop
    # events — with the compile-once evidence intact despite the ring
    "serve_long_context.py": {"serving": "long_context"},
    # multi-replica router (PR 15): the report must carry the validated
    # ``router`` section — per-replica serving sections + the fleet
    # roll-up with affinity/migration evidence — and the routing /
    # handoff / degradation events on the timeline
    "serve_router.py": {"router": True},
}


@pytest.mark.parametrize("script", EXAMPLES)
def test_example_runs_on_cpu_sim(script, tmp_path):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    env.pop("TDP_RUNREPORT", None)
    env["TDP_CPU_SIM"] = "8"
    env["TDP_SMOKE"] = "1"  # examples that support it shrink their step count
    env["PYTHONPATH"] = f"{REPO}{os.pathsep}" + env.get("PYTHONPATH", "")
    env.pop("TDP_TRACE", None)
    report_path = trace_path = None
    if script in OBS_EXAMPLES:
        report_path = tmp_path / "RUNREPORT.json"
        env["TDP_RUNREPORT"] = str(report_path)
        if OBS_EXAMPLES[script].get("comm"):
            trace_path = tmp_path / "trace.json"
            env["TDP_TRACE"] = str(trace_path)
    res = subprocess.run(
        [sys.executable, str(REPO / "examples" / script)],
        env=env,
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert res.returncode == 0, (
        f"{script} failed (rc={res.returncode})\n"
        f"--- stdout ---\n{res.stdout[-2000:]}\n--- stderr ---\n{res.stderr[-2000:]}"
    )
    if report_path is None:
        return

    # the run must leave a schema-valid, self-consistent report behind
    from torchdistpackage_tpu.obs import validate_runreport

    assert report_path.exists(), (
        f"{script} exited 0 but wrote no RUNREPORT.json\n{res.stdout[-1000:]}")
    report = json.loads(report_path.read_text())
    errs = validate_runreport(report)
    assert errs == [], f"{script} RUNREPORT invalid: {errs}"
    assert report["steps"] > 0
    assert report["step_time_s"]["n"] > 0
    assert report["compile"]["count"] >= 1
    # markdown sibling rides along
    assert report_path.with_suffix(".md").exists()

    probe = OBS_EXAMPLES[script]
    if probe.get("counter"):
        counters = report["counters"]
        assert probe["counter"] in counters, (script, counters)
        val = counters[probe["counter"]][probe["field"]]
        assert isinstance(val, (int, float)) and val >= 0.0, (script, val)
        if probe["field"] == "bubble_fraction":
            assert val < 1.0
        if probe["counter"] == "moe":
            assert sum(counters["moe"]["expert_tokens"]) > 0

    if probe.get("zb"):
        # the zero-bubble A/B's evidence: schedule named, the zb bubble
        # strictly below the paired 1f1b reference, timed arms recorded,
        # and the schedule-build events on the timeline
        pipe = report["counters"]["pipeline"]
        assert pipe["schedule"] == "zb", pipe
        assert pipe["bubble_fraction"] < pipe["bubble_fraction_1f1b"], pipe
        assert pipe["step_time_zb_s"] > 0 and pipe["step_time_1f1b_s"] > 0
        kinds = {e["kind"] for e in report["events"]}
        assert {"zb_wgrad_deferred", "zb_cooldown_filled"} <= kinds, kinds

    if probe.get("resilience"):
        res = report.get("resilience")
        assert res, (script, "no resilience section")
        assert res["verdict"] == probe["resilience"], (script, res)
        assert res["rollbacks"] >= 1 and res["faults_injected"] >= 1, res
        kinds = {e["kind"] for e in report["events"]}
        assert {"fault_injected", "rollback"} <= kinds, (script, kinds)

    if probe.get("serving"):
        srv = report.get("serving")
        assert srv, (script, "no serving section")
        assert srv["requests"]["completed"] > 0, srv
        assert srv["tokens_per_sec"] > 0, srv
        for key in ("ttft_s", "tpot_s"):
            assert {"p50", "p95", "p99"} <= set(srv[key]), (key, srv[key])
        assert 0.0 < srv["slot_occupancy"]["mean"] <= 1.0, srv
        assert 0.0 < srv["kv_pool"]["mean_utilization"] <= 1.0, srv
        # compile-once: one decode + one prefill signature for the whole run
        assert srv["decode_signatures"] == 1, srv
        assert srv["prefill_signatures"] == 1, srv
        kinds = {e["kind"] for e in report["events"]}
        assert {"request_admitted", "prefill_chunk",
                "request_retired", "slots_snapshot"} <= kinds, kinds
        if probe["serving"] == "stress":
            from torchdistpackage_tpu.obs import SERVING_VERDICTS

            assert srv["verdict"] in SERVING_VERDICTS, srv["verdict"]
            prios = srv["priorities"]
            assert len(prios) >= 2, (script, prios)
            for row in prios.values():
                assert {"p50", "p95", "p99"} <= set(row["ttft_s"]), row
            # the SIGTERM demo drained and its events hit the timeline
            assert "engine_drained" in kinds, kinds
            assert "preemption" in kinds, kinds  # the real signal arrived
            # the fast-path phase: shared-system-prompt traffic hit the
            # prefix cache and the speculative engine drove the run the
            # report records (hit/accept rates validated in [0, 1])
            assert srv["prefix_hit_rate"] > 0, srv
            assert 0.0 <= srv["spec_accept_rate"] <= 1.0, srv
            assert srv["spec"]["k"] >= 1, srv
            assert {"prefix_hit", "spec_draft", "spec_verify"} <= kinds, kinds
        if probe["serving"] == "long_context":
            lc = srv.get("long_context")
            assert lc, (script, "no long_context block")
            assert lc["cp"] >= 2 and lc["cp_axis"], lc
            assert lc["prefill_chunks"] > 0, lc
            # every ring hop the engine booked is on the timeline's model:
            # hops = chunks * 4 * (cp-1) * nlayers, bytes follow the pool
            assert lc["ring_hops"] > 0 and lc["ring_bytes"] > 0, lc
            assert lc["ring_hops"] % lc["prefill_chunks"] == 0, lc
            assert {"cp_prefill_chunk", "cp_ring_hop"} <= kinds, kinds

    if probe.get("router"):
        rt = report.get("router")
        assert rt, (script, "no router section")
        fleet = rt["fleet"]
        # disaggregation + affinity did the work: warm traffic landed on
        # its KV, every request handed prefill->decode by block
        # migration, warm handoffs shared prefix blocks on arrival
        assert fleet["affinity"]["hit_rate"] > 0, fleet["affinity"]
        assert fleet["migrations"]["handoffs"] >= 1, fleet["migrations"]
        assert fleet["migrations"]["bytes"] > 0, fleet["migrations"]
        assert fleet["migrations"]["shared_blocks"] > 0, fleet["migrations"]
        # the chaos phase killed a replica: evacuated, fleet degraded
        assert fleet["verdict"] == "degraded", fleet
        assert fleet["evacuations"] >= 1 and fleet["n_alive"] < len(
            rt["replicas"]), fleet
        # the elastic phase (PR 19): the autoscaler revived the corpse
        # under the burst and parked the surplus in the calm tail, and
        # the chunked wire healed its seeded chunk drop under the retry
        # budget (no re-prefill fallback spent)
        asc = fleet["autoscale"]
        assert asc["verdict"] == "elastic", asc
        assert asc["scale_ups"] >= 1 and asc["scale_downs"] >= 1, asc
        assert fleet["migrations"]["retries"] >= 1, fleet["migrations"]
        assert fleet["migrations"]["fallbacks"] == 0, fleet["migrations"]
        # compile-once per live decode replica
        for row in rt["replicas"]:
            if row["alive"] and row["role"] in ("decode", "both"):
                assert row["decode_signatures"] == 1, row
        kinds = {e["kind"] for e in report["events"]}
        assert {"request_routed", "blocks_migrated", "request_migrated",
                "replica_degraded", "scale_decision",
                "migration_retry"} <= kinds, kinds

    if probe.get("autoplan"):
        # the PR-13 planner section: a chosen plan with per-term score
        # breakdowns, candidate/pruned accounting, and the selection
        # event on the timeline (validate_runreport already ranged it)
        aps = report.get("autoplan")
        assert aps, (script, "no autoplan section")
        assert aps["verdict"] == "ok" and aps["chosen"], aps
        assert aps["chosen"]["terms"] is not None
        assert aps["n_candidates"] > 0
        assert 0 <= aps["n_pruned_oom"] <= aps["n_candidates"]
        kinds = {e["kind"] for e in report["events"]}
        assert "plan_selected" in kinds, kinds
        if script == "train_moe.py":
            # PR 18: the MoE planner emitted real ep arms — the chosen
            # plan carries the ep mesh factor and the ranked set crossed
            # in ep>1 candidates (8 experts / 8 sim devices)
            assert "ep" in aps["chosen"]["mesh_axes"], aps["chosen"]
            assert any(r.get("ep", 1) > 1 for r in aps["ranked"]), aps

    if probe.get("memory"):
        # the PR-6 memory section: per-program static breakdown captured
        # through the same AOT hook as the comm ledger, verdict validated
        mem = report["memory"]
        from torchdistpackage_tpu.obs import MEM_VERDICTS

        assert mem["verdict"] in MEM_VERDICTS, mem
        progs = mem["programs"]
        assert progs, (script, "no static mem ledgers captured")
        for p in progs:
            assert p["argument_bytes"] > 0, (script, p)
            assert p["peak_estimate_bytes"] >= p["temp_bytes"], (script, p)
        if probe["memory"] == "sharded":
            # FSDP evidence: at least one param leaf resident at a
            # fraction of its replicated (global) estimate
            rows = [r for p in progs for r in p.get("per_leaf", [])]
            sharded = [r for r in rows if r["shard_count"] > 1]
            assert sharded, (script, "no sharded leaves evidenced")
            assert all(
                r["resident_bytes"] < r["global_bytes"] for r in sharded)
            assert any(r["shard_count"] >= 8 for r in sharded), (
                script, "expected a fully FSDP-sharded leaf on the "
                "8-device sim", sorted({r['shard_count'] for r in sharded}))

    if probe.get("numerics"):
        num = report["numerics"]
        if probe["numerics"] == "healthy":
            # in-step stats flowed: per-step timeline with finite norms,
            # a dtype ledger from the compiled step, zero alerts
            assert num["timeline"], (script, "empty numerics timeline")
            assert num["summary"]["grad_norm_final"] > 0, num["summary"]
            assert num["alerts"]["count"] == 0, (script, num["alerts"])
            assert num["dtype_ledgers"], (script, "no dtype ledger")
            per = num["dtype_ledgers"][0]["per_dtype"]
            assert any(b["flops"] > 0 for b in per.values()), per
        if probe["numerics"] == "alert_before_rollback":
            # the chaos NaN spike surfaces as a numerics_alert, and it
            # lands on the timeline BEFORE the rollback decision
            assert num["alerts"]["by_reason"].get("nonfinite_loss"), num
            ev = report["events"]
            alert_t = min(e["t_mono"] for e in ev
                          if e["kind"] == "numerics_alert")
            rollback_t = min(e["t_mono"] for e in ev
                             if e["kind"] == "rollback")
            assert alert_t < rollback_t, (script, alert_t, rollback_t)

    if probe.get("comm"):
        # the comm section must ledger this example's parallelism dimension
        comm = report["comm"]
        assert comm, (script, "empty comm section")
        per_dim = comm["ledger"]["per_dim"]
        assert probe["comm"] in per_dim, (script, per_dim)
        assert per_dim[probe["comm"]]["bytes"] > 0, (script, per_dim)
        assert comm["verdict"] in ("comm-bound", "compute-bound", "unknown")
        # and the Perfetto trace must exist and validate
        from torchdistpackage_tpu.obs import validate_trace

        assert trace_path.exists(), f"{script} wrote no trace.json"
        trace = json.loads(trace_path.read_text())
        assert validate_trace(trace) == [], script
        assert any(e.get("ph") == "X" for e in trace["traceEvents"]), script


def test_examples_discovered():
    # guard against the glob silently matching nothing
    assert len(EXAMPLES) >= 6, EXAMPLES
