"""Numerics observability: per-layer training-dynamics stats, the HLO
dtype ledger, and threshold-driven ``numerics_alert`` events.

The obs stack answers "how fast" (Telemetry spans + cost_analysis MFU),
"where do the bytes go on the wire" (:mod:`.comm_ledger` +
:mod:`.comm_model`) and "what is resident" (:mod:`.mem_ledger`); nothing
answered **"is the math healthy"** — a run could train on vanishing
gradients or a silently-f32 matmul for hours and the report would show a
great MFU.  Three layers of truth, symmetric to the comm and memory
stacks:

1. **In-step stats** (:func:`numerics_stats`): a jittable pure function
   over the (grads, params, updates) the train step already holds —
   global and per-layer-group L2 norms, the update ratio
   ``|update| / |param|`` (the classic learning-rate health signal),
   non-finite counts, and low-precision *range-health* fractions (how
   much of the gradient mass would underflow bf16, overflow f16, or
   quantize to zero at int8).  Fused INTO the compiled step — one
   program, donate-friendly, no extra dispatch
   (``DataParallel.make_train_step(numerics=True)``).
2. **HLO dtype ledger** (:func:`dtype_ledger_from_compiled`): per-dtype
   FLOP and byte accounting parsed from the AOT-compiled step's HLO text
   — the same no-second-compile ``Telemetry._compile_entry`` hook as the
   comm/mem ledgers.  This PROVES what actually runs in bf16 vs f32 vs
   int8: the evidence channel quantized collectives / quantized KV are
   verified against (a "quantized" config whose ledger shows zero s8
   bytes is lying).
3. **Alerts + report** (:func:`check_alerts` / :func:`numerics_report`):
   :class:`~.telemetry.Telemetry` promotes the per-step stats to a
   timeline with threshold-driven ``numerics_alert`` events (explosion,
   vanishing, update-ratio out of band, non-finite loss/grads) and
   Perfetto counter tracks (``grad_norm``, ``update_ratio``), and
   ``finalize()`` builds the validated RUNREPORT ``numerics`` section.

The shared-reduction contract: :func:`global_grad_norm` here is THE
global-norm implementation — ``parallel/clip.py`` delegates to it, so a
step that both clips and monitors computes the grouped squared-sum
reduction once (XLA CSEs the identical subgraphs) and the clipped-step
trajectory is bitwise-unchanged vs pre-fold HEAD (parity-tested).

Known limitation: on legacy jax (no vma tracking) the per-leaf psum axes
come back empty, so norms of TP-sharded leaves are per-shard only — the
same ``requires_vma`` caveat the tight-tolerance parity goldens carry.
"""

from __future__ import annotations

import re
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Tuple

NUMERICS_SCHEMA = "tdp-numerics/v1"
DTYPE_LEDGER_SCHEMA = "tdp-dtype-ledger/v1"

# Alert thresholds (Telemetry accepts overrides).  The bands are loose on
# purpose: an alert should mean "look at this run", not "tuesday".
DEFAULT_THRESHOLDS: Dict[str, float] = {
    # global grad-norm explosion / vanishing (absolute, post-reduction)
    "grad_norm_explode": 1.0e3,
    "grad_norm_vanish": 1.0e-7,
    # |update| / |param| out of band: >1e-1 means steps rewrite the net,
    # <1e-6 means the optimizer is effectively frozen
    "update_ratio_high": 1.0e-1,
    "update_ratio_low": 1.0e-6,
}

# Low-precision range constants: bf16 shares f32's exponent range, so its
# underflow line is the f32 smallest normal; f16's max is famously 65504.
BF16_TINY = 1.17549435e-38
F16_MAX = 65504.0


# ----------------------------------------------------------- shared norms


def _vma_axes(x) -> Tuple[str, ...]:
    """Mesh axes a traced value varies over (sorted; empty outside
    shard_map or on legacy jax without vma tracking)."""
    from ..compat import typeof

    return tuple(sorted(getattr(typeof(x), "vma", frozenset())))


def _psum_grouped(pairs: Iterable[Tuple[Tuple[str, ...], Any]]):
    """Sum ``(axes, scalar)`` pairs: accumulate per distinct axes-set in
    encounter order, psum each set ONCE, then total — one scalar psum per
    distinct sharding instead of one per leaf.  This is the exact
    accumulation order ``parallel/clip.py`` used pre-fold, so the global
    norm (and thus clipping) stays bitwise-identical."""
    import jax
    import jax.numpy as jnp

    by_axes: Dict[Tuple[str, ...], Any] = {}
    for axes, s in pairs:
        by_axes[axes] = by_axes.get(axes, 0.0) + s
    total = jnp.zeros((), dtype=jnp.float32)
    for axes, s in by_axes.items():
        total = total + (jax.lax.psum(s, axes) if axes else s)
    return total


def _sq_pairs(tree) -> List[Tuple[Tuple[str, ...], Any]]:
    import jax
    import jax.numpy as jnp

    out = []
    for g in jax.tree.leaves(tree):
        sq = jnp.sum(jnp.square(g.astype(jnp.float32)))
        out.append((_vma_axes(sq), sq))
    return out


def global_grad_norm(tree) -> Any:
    """True global L2 norm of a (possibly mixed-sharded) pytree — traced;
    inside shard_map each leaf's squared sum is psum-ed over exactly the
    mesh axes it varies on.  The one implementation ``parallel/clip.py``
    and :func:`numerics_stats` share."""
    import jax.numpy as jnp

    return jnp.sqrt(_psum_grouped(_sq_pairs(tree)))


# ------------------------------------------------------------- step stats


def default_group_fn(path) -> str:
    """Leaf path -> layer-group name: the first path component, plus the
    index when the model is a list of blocks (``blocks/0``, ``blocks/3``)
    — coarse enough to stay a handful of scalars, fine enough to say
    WHICH layer's gradients died."""
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:
            parts.append(str(p))
    if not parts:
        return "params"
    if len(parts) >= 2 and parts[1].isdigit():
        return f"{parts[0]}/{parts[1]}"
    return parts[0]


def _grouped_sq(tree, group_fn) -> Dict[str, List[Tuple[Tuple[str, ...], Any]]]:
    import jax
    import jax.numpy as jnp

    groups: Dict[str, List[Tuple[Tuple[str, ...], Any]]] = {}
    for path, g in jax.tree_util.tree_flatten_with_path(tree)[0]:
        sq = jnp.sum(jnp.square(g.astype(jnp.float32)))
        groups.setdefault(group_fn(path), []).append((_vma_axes(sq), sq))
    return groups


def numerics_stats(
    grads,
    params=None,
    updates=None,
    group_fn: Optional[Callable] = None,
    eps: float = 1e-12,
) -> Dict[str, Any]:
    """Training-dynamics stats over one step's (grads, params, updates).

    Pure and jittable — call it INSIDE the train step (after the grad
    reduction, before the param update) so monitoring rides in the same
    compiled program as training: no extra dispatch, no second fetch, and
    the norms see exactly the grads the optimizer sees.  Returns a dict
    of f32 scalars (fetch with the step outputs):

    - ``grad_norm`` / ``param_norm`` / ``update_norm`` — global L2 norms
      (param/update only when the trees are passed).
    - ``update_ratio`` — ``update_norm / (param_norm + eps)``.
    - ``nonfinite_grads`` — count of NaN/Inf gradient elements.
    - ``bf16_underflow_frac`` / ``f16_overflow_frac`` / ``int8_zero_frac``
      — fraction of nonzero grad elements below bf16's smallest normal,
      above f16's max, and (per leaf, against its own amax) inside the
      dead zone a symmetric int8 quantizer rounds to zero.  The health
      gauges for running grads/collectives at low precision.
    - ``groups`` — per-layer-group sub-dicts of the same norms
      (:func:`default_group_fn` grouping unless ``group_fn`` is given).

    Under shard_map every reduction psums over exactly the axes each leaf
    varies on, so TP/FSDP-sharded trees report true global values.
    """
    import jax
    import jax.numpy as jnp

    gf = group_fn or default_group_fn
    out: Dict[str, Any] = {"grad_norm": global_grad_norm(grads)}

    groups: Dict[str, Dict[str, Any]] = {}
    for name, pairs in _grouped_sq(grads, gf).items():
        groups[name] = {"grad_norm": jnp.sqrt(_psum_grouped(pairs))}
    if params is not None:
        out["param_norm"] = global_grad_norm(params)
        for name, pairs in _grouped_sq(params, gf).items():
            groups.setdefault(name, {})["param_norm"] = jnp.sqrt(
                _psum_grouped(pairs))
    if updates is not None:
        out["update_norm"] = global_grad_norm(updates)
        for name, pairs in _grouped_sq(updates, gf).items():
            groups.setdefault(name, {})["update_norm"] = jnp.sqrt(
                _psum_grouped(pairs))
    if params is not None and updates is not None:
        out["update_ratio"] = out["update_norm"] / (out["param_norm"] + eps)
        for g in groups.values():
            if "update_norm" in g and "param_norm" in g:
                g["update_ratio"] = g["update_norm"] / (g["param_norm"] + eps)
    out["groups"] = groups

    # non-finite + low-precision range fractions over the gradient mass
    nonfinite, under, over, dead, total = [], [], [], [], []
    for g in jax.tree.leaves(grads):
        if not jnp.issubdtype(g.dtype, jnp.floating):
            continue
        a = jnp.abs(g.astype(jnp.float32))
        axes = _vma_axes(a)
        nonfinite.append((axes, jnp.sum(~jnp.isfinite(g)).astype(jnp.float32)))
        nz = a > 0
        under.append((axes, jnp.sum(nz & (a < BF16_TINY)).astype(jnp.float32)))
        over.append((axes, jnp.sum(a > F16_MAX).astype(jnp.float32)))
        # per-leaf symmetric int8 scale: values under amax/(2*127) round
        # to the zero bucket — the quantizer's dead zone
        amax = jnp.max(a)
        if axes:
            amax = jax.lax.pmax(amax, axes)
        dead.append((axes, jnp.sum(nz & (a < amax / 254.0)).astype(jnp.float32)))
        total.append((axes, jnp.asarray(g.size, jnp.float32)))
    if total:
        n = _psum_grouped(total)
        out["nonfinite_grads"] = _psum_grouped(nonfinite)
        out["bf16_underflow_frac"] = _psum_grouped(under) / n
        out["f16_overflow_frac"] = _psum_grouped(over) / n
        out["int8_zero_frac"] = _psum_grouped(dead) / n
    return out


# ----------------------------------------------------------------- alerts


def check_alerts(
    rec: Dict[str, Any], thresholds: Optional[Dict[str, float]] = None
) -> List[Dict[str, Any]]:
    """Threshold checks over one HOST-side step record (floats, as built
    by ``Telemetry.end_step``).  Returns ``[{reason, value, threshold}]``
    — empty when healthy.  Reasons: ``nonfinite_loss``,
    ``nonfinite_grads``, ``grad_explosion``, ``grad_vanishing``,
    ``update_ratio_high``, ``update_ratio_low``."""
    import math

    th = dict(DEFAULT_THRESHOLDS)
    if thresholds:
        th.update(thresholds)
    alerts: List[Dict[str, Any]] = []

    def add(reason, value, threshold=None):
        alerts.append({
            "reason": reason, "value": value, "threshold": threshold})

    loss = rec.get("loss")
    if isinstance(loss, (int, float)) and not math.isfinite(loss):
        add("nonfinite_loss", loss)
    nf = rec.get("nonfinite_grads")
    if isinstance(nf, (int, float)) and nf > 0:
        add("nonfinite_grads", nf)
    gn = rec.get("grad_norm")
    if isinstance(gn, (int, float)):
        if not math.isfinite(gn):
            if not any(a["reason"] == "nonfinite_grads" for a in alerts):
                add("nonfinite_grads", gn)
        elif gn >= th["grad_norm_explode"]:
            add("grad_explosion", gn, th["grad_norm_explode"])
        elif 0.0 < gn <= th["grad_norm_vanish"]:
            add("grad_vanishing", gn, th["grad_norm_vanish"])
    ur = rec.get("update_ratio")
    if isinstance(ur, (int, float)) and math.isfinite(ur):
        if ur >= th["update_ratio_high"]:
            add("update_ratio_high", ur, th["update_ratio_high"])
        elif 0.0 < ur <= th["update_ratio_low"]:
            add("update_ratio_low", ur, th["update_ratio_low"])
    return alerts


# ----------------------------------------------------------- dtype ledger

# A defining HLO instruction: result type(s), op name, open paren.  The
# result may be a tuple '(f32[2]{0}, s8[4]{0})' — every shape inside is
# counted.  Same shape token grammar as comm_ledger.
_SHAPE_RE = re.compile(r"\b([a-z]\w*)\[([0-9,]*)\]")
_DEF_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%[^\s=]+\s+=\s+(?P<res>\(?[^(]*?\)?)\s+"
    r"(?P<op>[\w-]+)\((?P<rest>.*)$"
)
_CONTRACT_RE = re.compile(r"lhs_contracting_dims=\{([0-9,]*)\}")

_DTYPE_BITS = {
    "pred": 8, "s2": 2, "u2": 2, "s4": 4, "u4": 4,
    "s8": 8, "u8": 8, "f8e4m3fn": 8, "f8e5m2": 8, "f8e4m3b11fnuz": 8,
    "f8e4m3fnuz": 8, "f8e5m2fnuz": 8, "f8e3m4": 8, "f8e4m3": 8,
    "s16": 16, "u16": 16, "f16": 16, "bf16": 16,
    "s32": 32, "u32": 32, "f32": 32,
    "s64": 64, "u64": 64, "f64": 64, "c64": 64,
    "c128": 128,
}

# Result buffers of these ops alias/bookkeep rather than compute — they
# would double-count the producing instruction's bytes.
_NO_ALLOC_OPS = frozenset({
    "parameter", "get-tuple-element", "tuple", "bitcast", "constant",
    "after-all", "partition-id", "replica-id",
})


def _shape_elems(dims: str) -> int:
    n = 1
    for d in dims.split(","):
        if d:
            n *= int(d)
    return n


def dtype_ledger_from_hlo(
    hlo_text: str, label: Optional[str] = None
) -> Dict[str, Any]:
    """Per-dtype byte/FLOP/op accounting of an HLO module's instructions.

    - ``bytes``: sum of result-buffer bytes per result dtype over every
      compute-defining instruction (bookkeeping ops — parameter, tuple,
      get-tuple-element, bitcast, constant — excluded).  A traffic-mix
      proxy, not a liveness peak (that is :mod:`.mem_ledger`'s job).
    - ``flops``: matmul FLOPs per OPERAND dtype, ``2 * |result| * K``
      from each ``dot``'s result shape and lhs contracting dims — the
      precision the MXU actually multiplies in.  Elementwise/conv FLOPs
      are not attributed (cost_analysis owns the total; this ledger owns
      the *mix*).
    - ``ops``: instruction count per result dtype.

    The quantization evidence channel: an int8-collective or int8-KV arm
    must show s8 bytes here, and a "bf16 training" run whose dot FLOPs
    sit in f32 has a silent upcast.
    """
    per: Dict[str, Dict[str, float]] = {}

    def bucket(dt: str) -> Dict[str, float]:
        return per.setdefault(dt, {"bytes": 0, "ops": 0, "flops": 0})

    for line in hlo_text.splitlines():
        m = _DEF_RE.match(line)
        if m is None:
            continue
        op = m.group("op")
        if op in _NO_ALLOC_OPS:
            continue
        shapes = _SHAPE_RE.findall(m.group("res"))
        if not shapes:
            continue
        for i, (dt, dims) in enumerate(shapes):
            bits = _DTYPE_BITS.get(dt)
            if bits is None:
                continue
            b = bucket(dt)
            b["bytes"] += _shape_elems(dims) * bits // 8
            if i == 0:
                b["ops"] += 1
        if op == "dot":
            rest = m.group("rest")
            operands = _SHAPE_RE.findall(rest)
            cm = _CONTRACT_RE.search(line)
            if operands and cm is not None:
                lhs_dt, lhs_dims = operands[0]
                lhs_shape = [int(d) for d in lhs_dims.split(",") if d]
                k = 1
                for idx in cm.group(1).split(","):
                    if idx and int(idx) < len(lhs_shape):
                        k *= lhs_shape[int(idx)]
                out_elems = sum(
                    _shape_elems(dims) for _, dims in shapes)
                bucket(lhs_dt)["flops"] += 2 * out_elems * k
    total_bytes = sum(b["bytes"] for b in per.values())
    total_flops = sum(b["flops"] for b in per.values())
    ledger: Dict[str, Any] = {
        "schema": DTYPE_LEDGER_SCHEMA,
        "label": label,
        "per_dtype": {
            dt: {k: int(v) for k, v in b.items()}
            for dt, b in sorted(per.items())
        },
        "total_bytes": int(total_bytes),
        "total_flops": int(total_flops),
    }
    if total_bytes:
        ledger["byte_frac"] = {
            dt: round(b["bytes"] / total_bytes, 4)
            for dt, b in sorted(per.items()) if b["bytes"]}
    if total_flops:
        ledger["flop_frac"] = {
            dt: round(b["flops"] / total_flops, 4)
            for dt, b in sorted(per.items()) if b["flops"]}
    return ledger


def dtype_ledger_from_compiled(
    compiled, label: Optional[str] = None
) -> Optional[Dict[str, Any]]:
    """Dtype ledger from a compiled executable; None when the backend
    can't render HLO text (mirrors ``comm_ledger.ledger_from_compiled``)."""
    try:
        text = compiled.as_text()
    except Exception:
        return None
    if not isinstance(text, str) or not text:
        return None
    return dtype_ledger_from_hlo(text, label=label)


def render_dtype_table(ledger: Optional[Dict[str, Any]]) -> str:
    """Human summary (bench.py prints this next to the comm/mem tables)."""
    if not ledger or not ledger.get("per_dtype"):
        return "dtype ledger: no typed instructions parsed"
    L = ["dtype ledger (per compiled step):",
         f"{'dtype':>8} {'ops':>6} {'bytes':>12} {'matmul flops':>14}"]
    for dt, b in ledger["per_dtype"].items():
        L.append(
            f"{dt:>8} {b['ops']:>6} {_fmt_bytes(b['bytes']):>12} "
            + (f"{b['flops']:.3e}" if b["flops"] else "-").rjust(14))
    fr = ledger.get("flop_frac")
    if fr:
        L.append("  matmul flop mix: " + ", ".join(
            f"{dt} {f:.1%}" for dt, f in fr.items()))
    return "\n".join(L)


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024
    return f"{n:.1f}GiB"


# ---------------------------------------------------------- report section


def numerics_report(
    timeline: Sequence[Dict[str, Any]] = (),
    dtype_ledgers: Sequence[Optional[Dict[str, Any]]] = (),
    events: Iterable[Dict[str, Any]] = (),
    parity: Optional[Dict[str, Any]] = None,
    thresholds: Optional[Dict[str, float]] = None,
) -> Dict[str, Any]:
    """The RUNREPORT ``numerics`` section: timeline summary + alert roll-up
    + dtype ledger(s) (+ the optional A/B :mod:`.parity` verdict)."""
    import math

    import numpy as np

    tl = [dict(t) for t in timeline]
    summary: Dict[str, Any] = {"steps": len(tl)}
    gns = [t["grad_norm"] for t in tl
           if isinstance(t.get("grad_norm"), (int, float))
           and math.isfinite(t["grad_norm"])]
    if gns:
        summary["grad_norm_final"] = gns[-1]
        summary["grad_norm_mean"] = float(np.mean(gns))
        summary["grad_norm_max"] = float(np.max(gns))
    urs = [t["update_ratio"] for t in tl
           if isinstance(t.get("update_ratio"), (int, float))
           and math.isfinite(t["update_ratio"])]
    if urs:
        summary["update_ratio_final"] = urs[-1]
        summary["update_ratio_mean"] = float(np.mean(urs))
    summary["nonfinite_steps"] = sum(
        1 for t in tl if t.get("nonfinite_grads"))

    alert_events = [e for e in events if e.get("kind") == "numerics_alert"]
    by_reason: Dict[str, int] = {}
    for e in alert_events:
        by_reason[str(e.get("reason"))] = by_reason.get(
            str(e.get("reason")), 0) + 1
    alerts: Dict[str, Any] = {"count": len(alert_events),
                              "by_reason": by_reason}
    if alert_events:
        first = alert_events[0]
        alerts["first"] = {
            "step": first.get("step"), "reason": first.get("reason"),
            "value": first.get("value")}

    stride = max(1, len(tl) // 64)
    section: Dict[str, Any] = {
        "schema": NUMERICS_SCHEMA,
        "summary": summary,
        "alerts": alerts,
        "timeline": tl[::stride],
        "dtype_ledgers": [
            {k: v for k, v in d.items() if k != "schema"}
            for d in dtype_ledgers if d],
        "thresholds": dict(DEFAULT_THRESHOLDS, **(thresholds or {})),
    }
    if parity is not None:
        section["parity"] = dict(parity)
    return section
