"""Flash-attention block-size autotuner.

The Pallas flash kernel (ops/flash_attention.py) takes ``block_q``/``block_k``
tile sizes whose best values depend on the chip generation (VMEM size, MXU
shape) and the problem shape.  The reference delegates kernel tuning to
cuDNN/bitsandbytes; on TPU it is OUR kernel, so the framework ships the tuner:
time fwd+bwd over a candidate grid on the attached backend and report the
ranking.

Usage (library)::

    from torchdistpackage_tpu.tools import tune_flash_blocks
    best, report = tune_flash_blocks(batch=8, heads=12, seq=2048, head_dim=64)

or CLI: ``python -m torchdistpackage_tpu.tools.flash_tune --seq 2048``.

Timing uses the same host-transfer sync discipline as bench.py: chain the
iterations through a data dependency and fetch a scalar at the end
(``block_until_ready`` can return early over the axon TPU tunnel).
"""

from __future__ import annotations

import time
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

# (block_q, block_k) candidates; clamped per-shape by the kernel's gcd rule
DEFAULT_CANDIDATES: Tuple[Tuple[int, int], ...] = (
    (128, 128),
    (128, 256),
    (128, 512),
    (256, 256),
    (256, 512),
    (256, 1024),
    (512, 512),
    (512, 1024),
    (1024, 1024),
)


def _time_config(
    q, k, v, block_q: int, block_k: int, causal: bool, steps: int, warmup: int
) -> float:
    """Seconds per fwd+bwd step for one (block_q, block_k)."""
    from ..ops.flash_attention import flash_attention

    def loss(q, k, v):
        return jnp.sum(
            flash_attention(
                q, k, v, causal=causal, block_q=block_q, block_k=block_k
            ).astype(jnp.float32)
        )

    step = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))

    # chain iterations through q so the run can't dead-code or overlap past
    # the timer; final scalar fetch bounds execution
    def chain(q, n):
        for _ in range(n):
            dq, _, _ = step(q, k, v)
            q = q + 0 * dq
        return q

    q1 = chain(q, warmup)
    float(jnp.sum(q1[0, 0, 0].astype(jnp.float32)))
    t0 = time.perf_counter()
    q2 = chain(q, steps)
    float(jnp.sum(q2[0, 0, 0].astype(jnp.float32)))
    return (time.perf_counter() - t0) / steps


def tune_flash_blocks(
    batch: int = 8,
    heads: int = 12,
    seq: int = 2048,
    head_dim: int = 64,
    causal: bool = True,
    dtype=jnp.bfloat16,
    candidates: Sequence[Tuple[int, int]] = DEFAULT_CANDIDATES,
    steps: int = 10,
    warmup: int = 2,
    seed: int = 0,
) -> Tuple[Tuple[int, int], List[dict]]:
    """Measure every (block_q, block_k) candidate at the given shape.

    Returns ``(best, report)`` where ``report`` is a list of
    ``{"block_q", "block_k", "ms", "rel"}`` sorted fastest-first (``rel`` is
    time relative to the winner).  Candidates that exceed the sequence are
    deduped after the kernel's clamp-to-divisor rule, so the report has no
    repeated effective configs."""
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(seed), 3)
    shape = (batch, heads, seq, head_dim)
    q = jax.random.normal(kq, shape, dtype)
    k = jax.random.normal(kk, shape, dtype)
    v = jax.random.normal(kv, shape, dtype)

    import math

    seen = set()
    rows = []
    for bq, bk in candidates:
        eff = (math.gcd(min(bq, seq), seq), math.gcd(min(bk, seq), seq))
        if eff in seen:
            continue
        seen.add(eff)
        try:
            dt = _time_config(q, k, v, bq, bk, causal, steps, warmup)
        except Exception as e:  # one bad tile must not kill the sweep
            rows.append({"block_q": eff[0], "block_k": eff[1],
                         "ms": None, "error": repr(e)[:200]})
            continue
        rows.append({"block_q": eff[0], "block_k": eff[1], "ms": dt * 1e3})
    ok = [r for r in rows if r.get("ms") is not None]
    if not ok:
        raise RuntimeError(f"no flash block config succeeded: {rows}")
    ok.sort(key=lambda r: r["ms"])
    best_ms = ok[0]["ms"]
    for r in ok:
        r["rel"] = round(r["ms"] / best_ms, 3)
        r["ms"] = round(r["ms"], 3)
    report = ok + [r for r in rows if r.get("ms") is None]
    return (ok[0]["block_q"], ok[0]["block_k"]), report


def main(argv: Optional[Sequence[str]] = None) -> None:
    import argparse
    import json

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--heads", type=int, default=12)
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--head-dim", type=int, default=64)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--no-causal", action="store_true")
    args = ap.parse_args(argv)
    best, report = tune_flash_blocks(
        batch=args.batch, heads=args.heads, seq=args.seq,
        head_dim=args.head_dim, causal=not args.no_causal, steps=args.steps,
    )
    from ..utils.logging import master_print

    master_print(json.dumps({
        "backend": jax.default_backend(),
        "chip": jax.devices()[0].device_kind,
        "shape": [args.batch, args.heads, args.seq, args.head_dim],
        "best": {"block_q": best[0], "block_k": best[1]},
        "report": report,
    }, indent=1))


if __name__ == "__main__":
    main()
