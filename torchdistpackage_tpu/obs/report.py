"""End-of-run report: schema, validation, JSON + markdown rendering.

``RUNREPORT.json`` is the machine-readable artifact a run leaves behind
(the driver's CI asserts every integrated example produces a valid one);
the sibling ``RUNREPORT.md`` is the human summary.  The schema is
versioned and validated structurally — :func:`validate_runreport` returns
a list of problems (empty = valid) rather than raising, so callers can
decide whether a malformed report is fatal.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

RUNREPORT_SCHEMA = "tdp-runreport/v1"

# the self-healing loop's end states (resilience/loop.py summary verdicts)
RESILIENCE_VERDICTS = ("clean", "recovered", "preempted", "aborted")

# the serving engine's end states (serving/engine.py serving_summary):
# overloaded = demand was refused (shed / expired requests), degraded =
# the engine preempted or healed faults to keep serving, healthy = neither
SERVING_VERDICTS = ("healthy", "degraded", "overloaded")

# the fleet balance verdicts (serving/router.py owns the policy and the
# skew threshold; the vocabulary is mirrored here so obs — a leaf
# subsystem — validates router sections without importing serving)
FLEET_BALANCE_VERDICTS = ("balanced", "skewed", "degraded")

# the autoscaler's end states (serving/autoscale.py owns the control
# policy; vocabulary mirrored for the same leaf-subsystem reason):
# static = never acted, elastic = acted within the thrash budget,
# thrashing = more scale flips than the budget allows
AUTOSCALE_VERDICTS = ("static", "elastic", "thrashing")

# the auto-sharding planner's end states (dist/autoplan.py imports these —
# obs is a leaf subsystem, so the schema vocabulary lives here): ``ok`` = a
# plan was chosen, ``all_oom`` = every candidate was pruned by the memory
# budget (a clean no-plan verdict, not a crash)
AUTOPLAN_SCHEMA = "tdp-autoplan/v1"
PLAN_VERDICTS = ("ok", "all_oom")

# the memory section's headroom verdicts (obs/mem_ledger.py owns the
# thresholds; re-exported here next to the other verdict vocabularies)
from .mem_ledger import MEM_VERDICTS  # noqa: E402

# the A/B run-parity verdicts (obs/parity.py; numerics.parity sub-section)
from .parity import PARITY_VERDICTS  # noqa: E402

# top-level key -> required python type (None = any); everything Telemetry
# emits, and everything validate checks.
_REQUIRED: Dict[str, type] = {
    "schema": str,
    "run": str,
    "backend": str,
    "n_devices": int,
    "n_processes": int,
    "steps": int,
    "step_time_s": dict,
    "spans_mean_s": dict,
    "throughput": dict,
    "mfu": dict,
    "memory": dict,
    "numerics": dict,
    "compile": dict,
    "hosts": dict,
    "comm": dict,
    "counters": dict,
    "events": list,
}


def default_report_path() -> Optional[str]:
    """The ``TDP_RUNREPORT`` env var — how the CI example runner points
    each subprocess at its own report file.  Empty/unset -> None."""
    return os.environ.get("TDP_RUNREPORT") or None


def validate_runreport(report: Any) -> List[str]:
    """Structural validation; returns problem strings (empty list = valid)."""
    errs: List[str] = []
    if not isinstance(report, dict):
        return [f"report is {type(report).__name__}, expected dict"]
    for key, typ in _REQUIRED.items():
        if key not in report:
            errs.append(f"missing key {key!r}")
        elif not isinstance(report[key], typ):
            errs.append(
                f"{key!r} is {type(report[key]).__name__}, expected {typ.__name__}")
    if errs:
        return errs
    if report["schema"] != RUNREPORT_SCHEMA:
        errs.append(
            f"schema {report['schema']!r} != {RUNREPORT_SCHEMA!r}")
    if report["steps"] < 0:
        errs.append(f"steps {report['steps']} < 0")
    st = report["step_time_s"]
    if st.get("n", 0) > 0:
        for k in ("mean", "min", "max", "p50"):
            if not isinstance(st.get(k), (int, float)):
                errs.append(f"step_time_s.{k} missing/non-numeric")
    for i, ev in enumerate(report["events"]):
        if not isinstance(ev, dict) or "kind" not in ev or "t_mono" not in ev:
            errs.append(f"events[{i}] lacks kind/t_mono")
            break
    hosts = report["hosts"]
    if "n_hosts" not in hosts or "per_host" not in hosts:
        errs.append("hosts lacks n_hosts/per_host")
    comm = report["comm"]
    if comm:  # empty dict = no compiled step was observed; that's valid
        if "ledger" not in comm or "verdict" not in comm:
            errs.append("comm section lacks ledger/verdict")
        elif comm["verdict"] not in ("comm-bound", "compute-bound", "unknown"):
            errs.append(f"comm verdict {comm['verdict']!r} invalid")
    errs.extend(_validate_memory(report["memory"]))
    errs.extend(_validate_numerics(report["numerics"]))
    res = report.get("resilience")
    if res is not None:  # optional: present when a ResilientLoop drove the run
        if not isinstance(res, dict):
            errs.append(f"resilience is {type(res).__name__}, expected dict")
        elif res.get("verdict") not in RESILIENCE_VERDICTS:
            errs.append(f"resilience verdict {res.get('verdict')!r} invalid")
        elif not isinstance(res.get("rollbacks"), int) or res["rollbacks"] < 0:
            errs.append("resilience.rollbacks missing/negative")
    errs.extend(_validate_serving(report.get("serving")))
    errs.extend(_validate_router(report.get("router")))
    errs.extend(_validate_compression(report.get("compression")))
    errs.extend(_validate_autoplan(report.get("autoplan")))
    errs.extend(_validate_pipeline(report["counters"].get("pipeline")))
    return errs


#: schedules the pipeline counters section may name (obs/aggregate.py's
#: ``pipeline_bubble_fraction`` vocabulary)
PIPELINE_SCHEDULES = ("forward", "1f1b", "zb")


def _validate_pipeline(pipe: Any) -> List[str]:
    """The optional ``counters.pipeline`` section (the pipelined examples
    and the ZB A/B record it): schedule-shape fields must be coherent,
    bubble fractions in range, and a ``zb`` record claiming a win over
    1F1B must actually show one — a section whose own numbers contradict
    the schedule it names is a reporting bug, surfaced here."""
    if pipe is None:
        return []
    if not isinstance(pipe, dict):
        return [f"counters.pipeline is {type(pipe).__name__}, expected dict"]
    errs: List[str] = []
    for key in ("pipe_size", "num_microbatches"):
        v = pipe.get(key)
        if not isinstance(v, int) or v < 1:
            errs.append(f"counters.pipeline.{key} missing/invalid: {v!r}")
    bf = pipe.get("bubble_fraction")
    if not isinstance(bf, (int, float)) or not (0.0 <= bf < 1.0):
        errs.append(f"counters.pipeline.bubble_fraction out of [0,1): {bf!r}")
    sched = pipe.get("schedule")
    if sched is not None and sched not in PIPELINE_SCHEDULES:
        errs.append(
            f"counters.pipeline.schedule {sched!r} not in "
            f"{PIPELINE_SCHEDULES}")
    ref = pipe.get("bubble_fraction_1f1b")
    if ref is not None:
        if not isinstance(ref, (int, float)) or not (0.0 <= ref < 1.0):
            errs.append(
                f"counters.pipeline.bubble_fraction_1f1b out of [0,1): "
                f"{ref!r}")
        elif sched == "zb" and isinstance(bf, (int, float)) and bf >= ref:
            errs.append(
                f"counters.pipeline: zb bubble_fraction {bf} not below the "
                f"1f1b reference {ref} — the zero-bubble claim is "
                f"contradicted by the section's own numbers")
    return errs


def _validate_autoplan(ap: Any) -> List[str]:
    """The optional ``autoplan`` section (dist/autoplan.py ``plan``): the
    candidate/pruned counts, the chosen plan (None only on the all-OOM
    verdict), ranked alternatives, and the optional modeled-vs-measured
    audit record."""
    if ap is None:
        return []
    if not isinstance(ap, dict):
        return [f"autoplan is {type(ap).__name__}, expected dict"]
    errs: List[str] = []
    if ap.get("schema") != AUTOPLAN_SCHEMA:
        errs.append(f"autoplan.schema {ap.get('schema')!r} invalid")
    if ap.get("verdict") not in PLAN_VERDICTS:
        errs.append(f"autoplan.verdict {ap.get('verdict')!r} invalid")
    nc, npr = ap.get("n_candidates"), ap.get("n_pruned_oom")
    if not isinstance(nc, int) or nc < 0:
        errs.append("autoplan.n_candidates missing/negative")
    if not isinstance(npr, int) or npr < 0 or (
            isinstance(nc, int) and npr > nc):
        errs.append("autoplan.n_pruned_oom missing/out of range")
    chosen = ap.get("chosen")
    if ap.get("verdict") == "all_oom":
        if chosen is not None:
            errs.append("autoplan.chosen set despite all_oom verdict")
        if isinstance(nc, int) and isinstance(npr, int) and npr != nc:
            errs.append("autoplan all_oom but n_pruned_oom != n_candidates")
    elif not isinstance(chosen, dict):
        errs.append("autoplan.chosen missing/non-dict")
    else:
        for k in ("key", "step_s", "compute_s", "comm_s"):
            if k == "key":
                if not isinstance(chosen.get(k), str) or not chosen[k]:
                    errs.append("autoplan.chosen.key missing")
            elif not isinstance(chosen.get(k), (int, float)) or chosen[k] < 0:
                errs.append(f"autoplan.chosen.{k} missing/negative")
        if not isinstance(chosen.get("mesh_axes"), dict):
            errs.append("autoplan.chosen.mesh_axes missing")
        if not isinstance(chosen.get("terms"), list):
            errs.append("autoplan.chosen.terms missing (per-term breakdown)")
    ranked = ap.get("ranked")
    if not isinstance(ranked, list):
        errs.append("autoplan.ranked missing/non-list")
        ranked = []
    for i, r in enumerate(ranked):
        if not isinstance(r, dict) or not r.get("key") or not isinstance(
                r.get("step_s"), (int, float)):
            errs.append(f"autoplan.ranked[{i}] lacks key/step_s")
            break
    mvm = ap.get("modeled_vs_measured")
    if mvm is not None:
        if not isinstance(mvm, dict) or not isinstance(
                mvm.get("rows"), list) or not mvm["rows"]:
            errs.append("autoplan.modeled_vs_measured lacks rows")
        elif not isinstance(mvm.get("ordering_agrees"), bool):
            errs.append("autoplan.modeled_vs_measured lacks ordering_agrees")
        else:
            for i, r in enumerate(mvm["rows"]):
                if not all(isinstance(r.get(k), (int, float)) and r[k] > 0
                           for k in ("modeled_step_s", "measured_step_s")):
                    errs.append(
                        f"autoplan.modeled_vs_measured.rows[{i}] invalid")
                    break
    return errs


def _validate_compression(comp: Any) -> List[str]:
    """The optional ``compression`` section (obs/comm_model.py
    ``compression_report``): mode, per-leaf policy roll-up, and
    predicted-vs-ledger-measured bytes per axis."""
    if comp is None:
        return []
    if not isinstance(comp, dict):
        return [f"compression is {type(comp).__name__}, expected dict"]
    errs: List[str] = []
    if not isinstance(comp.get("mode"), str) or not comp["mode"]:
        errs.append("compression.mode missing")
    pol = comp.get("policy")
    if not isinstance(pol, dict) or not isinstance(
            pol.get("n_leaves"), int) or not isinstance(
            pol.get("n_compressed"), int):
        errs.append("compression.policy lacks n_leaves/n_compressed")
    rows = comp.get("per_axis")
    if not isinstance(rows, list):
        errs.append("compression.per_axis missing/non-list")
        rows = []
    for i, r in enumerate(rows):
        if not isinstance(r, dict) or not r.get("axes"):
            errs.append(f"compression.per_axis[{i}] lacks axes")
            break
        for k in ("predicted_bytes", "measured_bytes"):
            v = r.get(k)
            if v is not None and (not isinstance(v, (int, float)) or v < 0):
                errs.append(f"compression.per_axis[{i}].{k} invalid")
    return errs


def _validate_memory(mem: Any) -> List[str]:
    """The required ``memory`` section (obs/mem_ledger.py): per-program
    static breakdown, modeled-vs-measured peak, headroom verdict."""
    errs: List[str] = []
    if mem.get("verdict") not in MEM_VERDICTS:
        errs.append(f"memory verdict {mem.get('verdict')!r} invalid")
    progs = mem.get("programs")
    if not isinstance(progs, list):
        errs.append("memory.programs missing/non-list")
        progs = []
    byte_keys = ("argument_bytes", "output_bytes", "temp_bytes",
                 "alias_bytes", "generated_code_bytes",
                 "peak_estimate_bytes")
    for i, p in enumerate(progs):
        if not isinstance(p, dict):
            errs.append(f"memory.programs[{i}] is not a dict")
            break
        for k in byte_keys:
            v = p.get(k)
            if not isinstance(v, int) or v < 0:
                errs.append(f"memory.programs[{i}].{k} missing/negative")
                break
    for k in ("modeled_peak_bytes", "measured_peak_bytes",
              "capacity_bytes", "peak_frac", "headroom_frac"):
        v = mem.get(k, None)
        if v is not None and not isinstance(v, (int, float)):
            errs.append(f"memory.{k} non-numeric")
    kv = mem.get("kv_pool")
    if kv is not None and kv.get("accounting_match") is False:
        # the serving engine's shape math and the device buffer disagree —
        # a real accounting bug, surfaced as a validation failure
        errs.append(
            f"memory.kv_pool accounting mismatch: expected "
            f"{kv.get('pool_bytes_expected')} != actual {kv.get('pool_bytes')}")
    return errs


def _validate_numerics(num: Any) -> List[str]:
    """The required ``numerics`` section (obs/numerics.py): timeline
    summary, alert roll-up, per-dtype HLO ledgers, optional A/B parity."""
    errs: List[str] = []
    alerts = num.get("alerts")
    if not isinstance(alerts, dict) or not isinstance(
            alerts.get("count"), int) or alerts["count"] < 0:
        errs.append("numerics.alerts.count missing/negative")
    elif alerts["count"] > 0 and not alerts.get("by_reason"):
        errs.append("numerics.alerts.by_reason empty with count > 0")
    if not isinstance(num.get("timeline"), list):
        errs.append("numerics.timeline missing/non-list")
    else:
        for i, t in enumerate(num["timeline"]):
            if not isinstance(t, dict) or "step" not in t:
                errs.append(f"numerics.timeline[{i}] lacks step")
                break
    leds = num.get("dtype_ledgers")
    if not isinstance(leds, list):
        errs.append("numerics.dtype_ledgers missing/non-list")
        leds = []
    for i, led in enumerate(leds):
        per = led.get("per_dtype") if isinstance(led, dict) else None
        if not isinstance(per, dict):
            errs.append(f"numerics.dtype_ledgers[{i}].per_dtype missing")
            break
        for dt, b in per.items():
            if not all(isinstance(b.get(k), int) and b[k] >= 0
                       for k in ("bytes", "ops", "flops")):
                errs.append(
                    f"numerics.dtype_ledgers[{i}].per_dtype[{dt!r}] "
                    f"lacks bytes/ops/flops")
                break
    summ = num.get("summary")
    if not isinstance(summ, dict):
        errs.append("numerics.summary missing/non-dict")
    else:
        for k in ("grad_norm_final", "update_ratio_final"):
            v = summ.get(k)
            if v is not None and not isinstance(v, (int, float)):
                errs.append(f"numerics.summary.{k} non-numeric")
    par = num.get("parity")
    if par is not None:
        if not isinstance(par, dict):
            errs.append(f"numerics.parity is {type(par).__name__}")
        elif par.get("verdict") not in PARITY_VERDICTS:
            errs.append(
                f"numerics.parity verdict {par.get('verdict')!r} invalid")
        elif not isinstance(par.get("streams"), list):
            errs.append("numerics.parity.streams missing/non-list")
    return errs


def _validate_serving(srv: Any) -> List[str]:
    """The optional ``serving`` section (a ServingEngine drove the run):
    TTFT/TPOT percentiles, aggregate tokens/s, slot occupancy and KV-pool
    utilization must be present and sane."""
    if srv is None:
        return []
    if not isinstance(srv, dict):
        return [f"serving is {type(srv).__name__}, expected dict"]
    errs: List[str] = []
    tps = srv.get("tokens_per_sec")
    if not isinstance(tps, (int, float)) or tps < 0:
        errs.append("serving.tokens_per_sec missing/negative")
    completed = srv.get("requests", {}).get("completed")
    if not isinstance(completed, int) or completed < 0:
        errs.append("serving.requests.completed missing/negative")
    for key in ("ttft_s", "tpot_s"):
        pct = srv.get(key)
        if not isinstance(pct, dict):
            errs.append(f"serving.{key} missing/non-dict")
            continue
        # ttft is stamped for every completed request; tpot may legitimately
        # be empty (every request retired on its first token)
        if completed and not pct and key == "ttft_s":
            errs.append("serving.ttft_s empty with completed requests")
        for p in ("p50", "p95", "p99"):
            if pct and not isinstance(pct.get(p), (int, float)):
                errs.append(f"serving.{key}.{p} missing/non-numeric")
    occ = srv.get("slot_occupancy", {}).get("mean")
    if not isinstance(occ, (int, float)) or not (0.0 <= occ <= 1.0):
        errs.append("serving.slot_occupancy.mean missing/out of [0,1]")
    util = srv.get("kv_pool", {}).get("mean_utilization")
    if not isinstance(util, (int, float)) or not (0.0 <= util <= 1.0):
        errs.append("serving.kv_pool.mean_utilization missing/out of [0,1]")
    # stress fields (PR 9) — optional for back-compat, validated when set
    if "verdict" in srv and srv["verdict"] not in SERVING_VERDICTS:
        errs.append(
            f"serving.verdict {srv['verdict']!r} not in {SERVING_VERDICTS}")
    reqs = srv.get("requests", {})
    # the verdict must cite evidence (PR 11) AND agree with the counters
    # that define it — a verdict whose own numbers contradict it is a
    # reporting bug, surfaced here instead of trusted downstream
    if "verdict_basis" in srv and (
            not isinstance(srv["verdict_basis"], str)
            or not srv["verdict_basis"]):
        errs.append("serving.verdict_basis empty/non-string")
    if "verdict" in srv and srv["verdict"] in SERVING_VERDICTS:
        refused = reqs.get("shed", 0) + reqs.get("expired", 0)
        degraded = (reqs.get("preempted", 0)
                    + (srv.get("faults") or {}).get("detected", 0))
        want = ("overloaded" if refused > 0
                else "degraded" if degraded > 0 else "healthy")
        if srv["verdict"] != want:
            errs.append(
                f"serving.verdict {srv['verdict']!r} contradicts its "
                f"evidence (shed+expired={refused}, "
                f"preempted+faults={degraded} -> {want!r})")
    for key in ("shed", "expired", "cancelled", "preempted", "resumed"):
        if key in reqs and (not isinstance(reqs[key], int) or reqs[key] < 0):
            errs.append(f"serving.requests.{key} non-int/negative")
    prios = srv.get("priorities")
    if prios is not None:
        if not isinstance(prios, dict):
            errs.append("serving.priorities non-dict")
        else:
            for p, row in prios.items():
                if not isinstance(row, dict) or not isinstance(
                        row.get("ttft_s", {}), dict):
                    errs.append(f"serving.priorities[{p}] malformed")
    faults = srv.get("faults")
    if faults is not None and (
            not isinstance(faults, dict)
            or faults.get("healed", 0) > faults.get("detected", 0)):
        errs.append("serving.faults malformed (healed > detected)")
    # fast-path fields (PR 10) — optional for back-compat, ranged when set
    for key in ("prefix_hit_rate", "spec_accept_rate"):
        if key in srv and (
                not isinstance(srv[key], (int, float))
                or not (0.0 <= srv[key] <= 1.0)):
            errs.append(f"serving.{key} non-numeric/out of [0,1]")
    spec = srv.get("spec")
    if spec is not None and (
            not isinstance(spec, dict)
            or spec.get("accepted", 0) > spec.get("drafted", 0)):
        errs.append("serving.spec malformed (accepted > drafted)")
    # expert-load fields (PR 18) — present for MoE engines, ranged when set
    moe = srv.get("moe")
    if moe is not None:
        if not isinstance(moe, dict):
            errs.append("serving.moe non-dict")
        else:
            imb = moe.get("imbalance")
            if not isinstance(imb, (int, float)) or imb < 0:
                errs.append("serving.moe.imbalance missing/negative")
            ent = moe.get("load_entropy")
            if not isinstance(ent, (int, float)) or not (0.0 <= ent <= 1.0):
                errs.append("serving.moe.load_entropy missing/out of [0,1]")
            dr = moe.get("dropped_token_rate")
            if not isinstance(dr, (int, float)) or not (0.0 <= dr <= 1.0):
                errs.append(
                    "serving.moe.dropped_token_rate missing/out of [0,1]")
            ne = moe.get("num_experts")
            if not isinstance(ne, int) or ne < 2:
                errs.append("serving.moe.num_experts missing/< 2")
            et = moe.get("expert_tokens")
            if not isinstance(et, list) or (
                    isinstance(ne, int) and len(et) != ne):
                errs.append("serving.moe.expert_tokens missing/wrong length")
            if moe.get("dispatch") not in (
                    "gather", "pallas", "dense", "sorted", "auto"):
                errs.append(
                    f"serving.moe.dispatch {moe.get('dispatch')!r} unknown")
    # ring-paged-prefill fields (PR 20) — present for cp_axis engines
    lc = srv.get("long_context")
    if lc is not None:
        if not isinstance(lc, dict):
            errs.append("serving.long_context non-dict")
        else:
            cp = lc.get("cp")
            if not isinstance(cp, int) or cp < 1:
                errs.append("serving.long_context.cp missing/< 1")
            if not isinstance(lc.get("cp_axis"), str) or not lc["cp_axis"]:
                errs.append("serving.long_context.cp_axis missing/empty")
            for k in ("max_ctx", "chunk"):
                v = lc.get(k)
                if not isinstance(v, int) or v < 1:
                    errs.append(f"serving.long_context.{k} missing/< 1")
            for k in ("prefill_chunks", "ring_hops", "ring_bytes"):
                v = lc.get(k)
                if not isinstance(v, int) or v < 0:
                    errs.append(
                        f"serving.long_context.{k} missing/negative")
            # a width-1 'ring' has no hops; width > 1 with chunks must
            # have accumulated hop accounting
            if (isinstance(cp, int) and cp > 1
                    and lc.get("prefill_chunks", 0) > 0
                    and not lc.get("ring_hops", 0)):
                errs.append(
                    "serving.long_context.ring_hops zero with cp > 1 and "
                    "prefill chunks recorded")
    errs.extend(_validate_serving_slo(srv))
    return errs


def _validate_serving_slo(srv: Dict[str, Any]) -> List[str]:
    """The ``serving.slo`` sub-section (PR 11): per-priority deadline
    attainment in [0, 1], goodput bounded by the aggregate tokens/s
    (goodput counts a SUBSET of the generated tokens over the same
    span), and the TTFT calibration record's ranges (positive bias,
    non-negative relative errors)."""
    slo = srv.get("slo")
    if slo is None:
        return []
    if not isinstance(slo, dict):
        return [f"serving.slo is {type(slo).__name__}, expected dict"]
    errs: List[str] = []
    gp = slo.get("goodput_tok_s")
    if not isinstance(gp, (int, float)) or gp < 0:
        errs.append("serving.slo.goodput_tok_s missing/negative")
    tps = srv.get("tokens_per_sec")
    if (isinstance(gp, (int, float)) and isinstance(tps, (int, float))
            and gp > tps * 1.001 + 1e-9):
        errs.append(
            f"serving.slo.goodput_tok_s {gp} exceeds tokens_per_sec {tps}")
    att = slo.get("attainment")
    if att is not None and (
            not isinstance(att, (int, float)) or not 0.0 <= att <= 1.0):
        errs.append("serving.slo.attainment out of [0, 1]")
    for p, row in (slo.get("priorities") or {}).items():
        if not isinstance(row, dict):
            errs.append(f"serving.slo.priorities[{p}] non-dict")
            continue
        for k in ("completed", "met", "missed", "shed", "expired",
                  "goodput_tokens"):
            v = row.get(k)
            if not isinstance(v, int) or v < 0:
                errs.append(f"serving.slo.priorities[{p}].{k} "
                            "missing/negative")
                break
        else:
            if row["met"] + row["missed"] != row["completed"]:
                errs.append(
                    f"serving.slo.priorities[{p}]: met+missed != completed")
        ra = row.get("attainment")
        if ra is not None and (
                not isinstance(ra, (int, float)) or not 0.0 <= ra <= 1.0):
            errs.append(f"serving.slo.priorities[{p}].attainment "
                        "out of [0, 1]")
    cal = slo.get("calibration")
    if cal is not None:
        if not isinstance(cal, dict):
            errs.append("serving.slo.calibration non-dict")
            return errs
        bias = cal.get("bias")
        if bias is not None and (
                not isinstance(bias, (int, float)) or bias <= 0):
            errs.append("serving.slo.calibration.bias non-positive")
        if not isinstance(cal.get("n"), int) or cal["n"] < 0:
            errs.append("serving.slo.calibration.n missing/negative")
        for p, row in (cal.get("priorities") or {}).items():
            for k, v in (row or {}).items():
                if k.startswith("rel_err_") and (
                        not isinstance(v, (int, float)) or v < 0):
                    errs.append(
                        f"serving.slo.calibration.priorities[{p}].{k} "
                        "negative/non-numeric")
    return errs


def _validate_router(rt: Any) -> List[str]:
    """The optional ``router`` section (a serving Router drove the run):
    one full serving section per replica — each re-validated through
    :func:`_validate_serving` — plus the fleet roll-up, whose invariants
    are cross-replica: fleet goodput cannot exceed the sum of the
    replica token rates (goodput counts a subset of the same tokens over
    a span at least as long as any replica's), the affinity hit rate is
    a fraction of routed requests, and the per-replica verdict list must
    agree with the replica sections it rolls up."""
    if rt is None:
        return []
    if not isinstance(rt, dict):
        return [f"router is {type(rt).__name__}, expected dict"]
    errs: List[str] = []
    reps = rt.get("replicas")
    if not isinstance(reps, list) or not reps:
        return ["router.replicas missing/empty"]
    for i, row in enumerate(reps):
        if not isinstance(row, dict):
            errs.append(f"router.replicas[{i}] non-dict")
            continue
        for key in ("index", "role", "alive"):
            if key not in row:
                errs.append(f"router.replicas[{i}].{key} missing")
        errs.extend(f"router.replicas[{i}]: {e}"
                    for e in _validate_serving(row))
    fleet = rt.get("fleet")
    if not isinstance(fleet, dict):
        errs.append("router.fleet missing/non-dict")
        return errs
    if fleet.get("verdict") not in SERVING_VERDICTS:
        errs.append(
            f"router.fleet.verdict {fleet.get('verdict')!r} not in "
            f"{SERVING_VERDICTS}")
    verdicts = fleet.get("verdicts")
    if (not isinstance(verdicts, list) or len(verdicts) != len(reps)
            or any(v not in SERVING_VERDICTS for v in verdicts)):
        errs.append("router.fleet.verdicts missing/mislengthed/invalid")
    elif verdicts != [row.get("verdict") for row in reps
                      if isinstance(row, dict)]:
        errs.append(
            "router.fleet.verdicts disagree with the replica sections")
    gp = fleet.get("goodput_tok_s")
    if not isinstance(gp, (int, float)) or gp < 0:
        errs.append("router.fleet.goodput_tok_s missing/negative")
    else:
        cap = sum(row.get("tokens_per_sec", 0.0) for row in reps
                  if isinstance(row, dict))
        if gp > cap * 1.001 + 1e-9:
            errs.append(
                f"router.fleet.goodput_tok_s {gp} exceeds the sum of "
                f"replica tokens_per_sec {cap}")
    aff = fleet.get("affinity")
    if not isinstance(aff, dict):
        errs.append("router.fleet.affinity missing/non-dict")
    else:
        hr = aff.get("hit_rate")
        if not isinstance(hr, (int, float)) or not (0.0 <= hr <= 1.0):
            errs.append("router.fleet.affinity.hit_rate out of [0, 1]")
        for k in ("routed", "affinity_routed"):
            if not isinstance(aff.get(k), int) or aff[k] < 0:
                errs.append(f"router.fleet.affinity.{k} missing/negative")
    mig = fleet.get("migrations")
    if not isinstance(mig, dict):
        errs.append("router.fleet.migrations missing/non-dict")
    else:
        for k in ("handoffs", "blocks", "bytes", "compressed"):
            v = mig.get(k)
            if not isinstance(v, int) or v < 0:
                errs.append(f"router.fleet.migrations.{k} missing/negative")
        # the fault-tolerant wire (PR 19): retry/fallback counters are
        # optional (old reports) but must be sane when present, and a
        # fallback implies the transfer's handoff never completed — the
        # counters may never exceed what the wire actually carried
        for k in ("retries", "fallbacks"):
            v = mig.get(k)
            if v is not None and (not isinstance(v, int) or v < 0):
                errs.append(f"router.fleet.migrations.{k} negative/non-int")
    for k in ("rebalances", "evacuations"):
        v = fleet.get(k)
        if not isinstance(v, int) or v < 0:
            errs.append(f"router.fleet.{k} missing/negative")
    slo = fleet.get("slo")
    if not isinstance(slo, dict):
        errs.append("router.fleet.slo missing/non-dict")
    else:
        att = slo.get("attainment")
        if att is not None and (
                not isinstance(att, (int, float)) or not 0.0 <= att <= 1.0):
            errs.append("router.fleet.slo.attainment out of [0, 1]")
        prios = slo.get("priorities")
        if not isinstance(prios, dict):
            errs.append("router.fleet.slo.priorities missing/non-dict")
        else:
            for k, row in prios.items():
                a = row.get("attainment") if isinstance(row, dict) else None
                if a is not None and (
                        not isinstance(a, (int, float))
                        or not 0.0 <= a <= 1.0):
                    errs.append(
                        f"router.fleet.slo.priorities[{k}].attainment "
                        f"out of [0, 1]")
        per = slo.get("per_replica")
        if not isinstance(per, list) or len(per) != len(reps):
            errs.append("router.fleet.slo.per_replica missing/mislengthed")
    bal = fleet.get("balance")
    if not isinstance(bal, dict):
        errs.append("router.fleet.balance missing/non-dict")
    else:
        if bal.get("verdict") not in FLEET_BALANCE_VERDICTS:
            errs.append(
                f"router.fleet.balance.verdict {bal.get('verdict')!r} "
                f"not in {FLEET_BALANCE_VERDICTS}")
        idx = bal.get("imbalance_index")
        if idx is not None and (
                not isinstance(idx, (int, float)) or idx < 1.0 - 1e-9):
            errs.append(
                "router.fleet.balance.imbalance_index below 1 (it is "
                "max/mean served tokens within a role group)")
        if not bal.get("basis"):
            errs.append("router.fleet.balance.basis missing/empty (the "
                        "verdict must cite its evidence)")
        if (fleet.get("verdict") in SERVING_VERDICTS
                and fleet.get("verdict") != "healthy"
                and bal.get("verdict") == "balanced"):
            errs.append(
                "router.fleet.balance.verdict 'balanced' contradicts "
                f"fleet verdict {fleet.get('verdict')!r}")
    asc = fleet.get("autoscale")
    if asc is not None:
        errs.extend(_validate_autoscale(asc))
    return errs


def _validate_autoscale(asc: Any) -> List[str]:
    """The optional ``router.fleet.autoscale`` subsection (an
    ``Autoscaler`` was attached): verdict-vs-evidence cross-checked in
    BOTH directions — a ``static`` verdict with recorded scale actions
    lies about what the controller did, and a non-``static`` verdict
    with zero actions claims activity the ledger cannot attribute;
    ``thrashing`` must agree with the action count vs the thrash budget,
    and the action total must reconcile with its up/down split."""
    if not isinstance(asc, dict):
        return ["router.fleet.autoscale non-dict"]
    errs: List[str] = []
    if asc.get("verdict") not in AUTOSCALE_VERDICTS:
        errs.append(
            f"router.fleet.autoscale.verdict {asc.get('verdict')!r} not "
            f"in {AUTOSCALE_VERDICTS}")
    for k in ("actions", "evals", "scale_ups", "scale_downs", "holds"):
        v = asc.get(k)
        if not isinstance(v, int) or v < 0:
            errs.append(f"router.fleet.autoscale.{k} missing/negative")
    if not asc.get("basis"):
        errs.append("router.fleet.autoscale.basis missing/empty (the "
                    "verdict must cite its evidence)")
    actions = asc.get("actions")
    ups, downs = asc.get("scale_ups"), asc.get("scale_downs")
    if (isinstance(actions, int) and isinstance(ups, int)
            and isinstance(downs, int) and actions != ups + downs):
        errs.append(
            f"router.fleet.autoscale.actions {actions} != scale_ups "
            f"{ups} + scale_downs {downs}")
    verdict = asc.get("verdict")
    if isinstance(actions, int) and verdict in AUTOSCALE_VERDICTS:
        if verdict == "static" and actions > 0:
            errs.append(
                f"router.fleet.autoscale.verdict 'static' contradicts "
                f"{actions} recorded scale actions")
        if verdict != "static" and actions == 0:
            errs.append(
                f"router.fleet.autoscale.verdict {verdict!r} with 0 "
                f"actions — 'static' is the only verdict for a "
                f"controller that never acted")
        thrash_at = asc.get("thrash_at")
        if isinstance(thrash_at, int):
            if verdict == "thrashing" and actions <= thrash_at:
                errs.append(
                    f"router.fleet.autoscale.verdict 'thrashing' with "
                    f"{actions} actions <= thrash_at {thrash_at}")
            if verdict == "elastic" and actions > thrash_at:
                errs.append(
                    f"router.fleet.autoscale.verdict 'elastic' with "
                    f"{actions} actions > thrash_at {thrash_at}")
    return errs


def render_summary_line(report: Dict[str, Any]) -> str:
    """One line for stdout at end of run."""
    parts = [f"[obs] run={report['run']} steps={report['steps']}"]
    st = report.get("step_time_s", {})
    if st.get("n"):
        parts.append(f"step={st['mean'] * 1e3:.1f}ms(p99 {st['p99'] * 1e3:.1f})")
    tp = report.get("throughput", {})
    if "tokens_per_sec" in tp:
        parts.append(f"tok/s={tp['tokens_per_sec']:.0f}")
    mfu = report.get("mfu", {})
    if "xla" in mfu:
        parts.append(f"mfu_xla={mfu['xla']:.3f}")
    mem = report.get("memory", {})
    if mem.get("reported"):
        parts.append(f"peak_hbm={mem['peak_bytes_in_use'] / 1e9:.2f}GB")
    if mem.get("verdict") and mem["verdict"] != "unknown":
        frac = mem.get("headroom_frac")
        parts.append(
            f"mem={mem['verdict']}"
            + (f"(headroom {frac:.0%})" if isinstance(frac, (int, float))
               else ""))
    num = report.get("numerics", {})
    gn = num.get("summary", {}).get("grad_norm_final")
    if isinstance(gn, (int, float)):
        parts.append(f"gnorm={gn:.3g}")
    if num.get("alerts", {}).get("count"):
        reasons = ",".join(sorted(num["alerts"]["by_reason"]))
        parts.append(f"NUMERICS={num['alerts']['count']}alert({reasons})")
    par = num.get("parity")
    if par and par.get("verdict") and par["verdict"] != "unknown":
        parts.append(f"parity={par['verdict']}")
    hosts = report.get("hosts", {})
    if hosts.get("straggler") is not None:
        parts.append(f"STRAGGLER=host{hosts['straggler']}")
    comm = report.get("comm", {})
    if comm.get("verdict") and comm.get("verdict") != "unknown":
        frac = comm.get("comm_fraction")
        parts.append(
            f"{comm['verdict']}"
            + (f"(comm {frac:.0%})" if isinstance(frac, (int, float)) else ""))
    res = report.get("resilience")
    if res and res.get("verdict") and res["verdict"] != "clean":
        parts.append(
            f"RESILIENCE={res['verdict']}"
            f"(rollbacks {res.get('rollbacks', 0)})")
    cmpx = report.get("compression")
    if cmpx:
        pol = cmpx.get("policy", {})
        parts.append(
            f"compress={cmpx.get('mode', '?')}"
            f"({pol.get('n_compressed', 0)}/{pol.get('n_leaves', 0)} leaves)")
    ap = report.get("autoplan")
    if ap:
        if ap.get("verdict") == "all_oom":
            parts.append(f"AUTOPLAN=all_oom({ap.get('n_pruned_oom', 0)} pruned)")
        elif ap.get("chosen"):
            tail = ""
            mvm = ap.get("modeled_vs_measured")
            if mvm and mvm.get("rows"):
                r0 = mvm["rows"][0]
                if isinstance(r0.get("rel_err"), (int, float)):
                    tail = f"(model {r0['rel_err']:+.0%} vs measured)"
            parts.append(f"plan={ap['chosen']['key']}{tail}")
    srv = report.get("serving")
    if srv and isinstance(srv.get("tokens_per_sec"), (int, float)):
        tail = ""
        p50 = srv.get("ttft_s", {}).get("p50")
        if isinstance(p50, (int, float)):
            tail = f"(ttft p50 {p50 * 1e3:.0f}ms)"
        parts.append(f"serve={srv['tokens_per_sec']:.1f}tok/s{tail}")
        slo = srv.get("slo") or {}
        if slo.get("attainment") is not None:
            parts.append(
                f"goodput={slo.get('goodput_tok_s', 0.0):.1f}tok/s"
                f"(att {slo['attainment']:.0%})")
        if srv.get("verdict") and srv["verdict"] != "healthy":
            reqs = srv.get("requests", {})
            detail = ", ".join(
                f"{k} {reqs.get(k, 0)}"
                for k in ("shed", "expired", "preempted")
                if reqs.get(k))
            parts.append(
                f"SERVING={srv['verdict']}" + (f"({detail})" if detail else ""))
    rt = report.get("router")
    if rt and isinstance(rt.get("fleet"), dict):
        fleet = rt["fleet"]
        aff = fleet.get("affinity") or {}
        mig = fleet.get("migrations") or {}
        att = fleet.get("attainment")
        parts.append(
            f"fleet={fleet.get('n_alive', '?')}/"
            f"{fleet.get('n_replicas', '?')}rep "
            f"{fleet.get('tokens_per_sec', 0.0):.1f}tok/s"
            f"(aff {aff.get('hit_rate', 0.0):.0%}, "
            f"mig {mig.get('handoffs', 0)}/"
            f"{mig.get('bytes', 0) / 1e6:.2f}MB"
            + (f", att {att:.0%}" if att is not None else "") + ")")
        if fleet.get("verdict") and fleet["verdict"] != "healthy":
            parts.append(f"FLEET={fleet['verdict']}")
        bal = fleet.get("balance") or {}
        if bal.get("verdict") and bal["verdict"] != "balanced":
            idx = bal.get("imbalance_index")
            parts.append(
                f"BALANCE={bal['verdict']}"
                + (f"({idx:.2f})" if idx is not None else ""))
    return "  ".join(parts)


def render_markdown(report: Dict[str, Any]) -> str:
    """Human summary: headline table, MFU cross-check, counters, memory,
    and the event timeline."""
    L: List[str] = [f"# Run report — {report['run']}", ""]
    L.append(
        f"`{report['backend']}` · chip `{report.get('chip', '?')}` · "
        f"{report['n_devices']} device(s) / {report['n_processes']} process(es) · "
        f"{report['steps']} steps · {report.get('wall_time_s', 0):.1f}s wall")
    L.append("")

    st = report.get("step_time_s", {})
    if st.get("n"):
        L.append("## Step time (steady-state)")
        L.append("")
        L.append("| mean | min | p50 | p95 | p99 | max |")
        L.append("|---|---|---|---|---|---|")
        L.append(
            "| " + " | ".join(
                f"{st[k] * 1e3:.2f} ms"
                for k in ("mean", "min", "p50", "p95", "p99", "max")) + " |")
        L.append("")
        spans = report.get("spans_mean_s", {})
        if spans:
            L.append(
                "Span means: " + ", ".join(
                    f"{k} {v * 1e3:.2f} ms" for k, v in spans.items()))
            L.append("")

    tp = report.get("throughput", {})
    if "tokens_per_sec" in tp:
        L.append("## Throughput")
        L.append("")
        L.append(f"- mean **{tp['tokens_per_sec']:.1f} tok/s**, "
                 f"final {tp['tokens_per_sec_final']:.1f} tok/s")
        traj = tp.get("trajectory")
        if traj:
            L.append(f"- trajectory ({len(traj)} pts): "
                     + " ".join(f"{t:.0f}" for t in traj))
        L.append("")

    mfu = report.get("mfu", {})
    if mfu:
        L.append("## MFU / FLOPs")
        L.append("")
        if "xla" in mfu:
            L.append(f"- XLA cost-analysis MFU: **{mfu['xla']:.3f}**")
        if "formula" in mfu:
            L.append(f"- hand-formula MFU: {mfu['formula']:.3f}")
        if "xla_vs_formula_rel" in mfu:
            L.append(f"- XLA vs formula FLOPs: {mfu['xla_vs_formula_rel']:+.1%}")
        if "xla_flops_per_step" in mfu:
            L.append(f"- FLOPs/step (XLA): {mfu['xla_flops_per_step']:.3e}")
        if "xla_bytes_per_step" in mfu:
            L.append(f"- bytes moved/step (XLA): {mfu['xla_bytes_per_step']:.3e}")
        L.append("")

    mem = report.get("memory", {})
    if mem.get("reported") or mem.get("programs"):
        L.append("## Memory")
        L.append("")
        if mem.get("verdict"):
            L.append(f"- headroom verdict: **{mem['verdict']}** "
                     f"({mem.get('verdict_basis', '')})")
        if mem.get("reported"):
            L.append(
                f"- measured peak HBM: "
                f"**{mem['peak_bytes_in_use'] / 1e9:.3f} GB**"
                + (f" of {mem['capacity_bytes'] / 1e9:.1f} GB capacity"
                   if mem.get("capacity_bytes") else ""))
        if mem.get("modeled_peak_bytes"):
            L.append(f"- modeled (static ledger) peak: "
                     f"{mem['modeled_peak_bytes'] / 1e9:.3f} GB")
        kv = mem.get("kv_pool")
        if kv:
            match = kv.get("accounting_match")
            L.append(
                f"- serving KV pool: {kv.get('pool_bytes', 0) / 1e6:.2f} MB "
                f"device buffer ("
                + ("matches" if match else "MISMATCHES" if match is False
                   else "vs") + " the engine's shape math)")
        progs = mem.get("programs") or []
        if progs:
            L.append("")
            L.append("| program | args | outputs | temps | gen code "
                     "| donated | static peak |")
            L.append("|---|---|---|---|---|---|---|")
            for p in progs:
                L.append(
                    "| " + (p.get("label") or "?") + " | "
                    + " | ".join(
                        f"{p[k] / 1e6:.2f} MB"
                        for k in ("argument_bytes", "output_bytes",
                                  "temp_bytes", "generated_code_bytes",
                                  "alias_bytes", "peak_estimate_bytes"))
                    + " |")
            lead = progs[0]
            if lead.get("n_leaves"):
                L.append("")
                L.append(
                    f"- argument attribution ({lead['label']}): "
                    f"{lead['n_leaves']} leaves, "
                    f"{lead['sharded_leaves']} sharded / "
                    f"{lead['replicated_leaves']} replicated")
        L.append("")

    num = report.get("numerics", {})
    if (num.get("timeline") or num.get("dtype_ledgers")
            or num.get("alerts", {}).get("count")):
        L.append("## Numerics")
        L.append("")
        summ = num.get("summary", {})
        if "grad_norm_final" in summ:
            L.append(
                f"- grad norm: final **{summ['grad_norm_final']:.4g}**, "
                f"mean {summ.get('grad_norm_mean', 0):.4g}, "
                f"max {summ.get('grad_norm_max', 0):.4g}")
        if "update_ratio_final" in summ:
            L.append(f"- update ratio |Δp|/|p|: final "
                     f"{summ['update_ratio_final']:.3g}, mean "
                     f"{summ.get('update_ratio_mean', 0):.3g}")
        alerts = num.get("alerts", {})
        if alerts.get("count"):
            first = alerts.get("first", {})
            L.append(
                f"- **{alerts['count']} numerics alert(s)**: "
                + ", ".join(f"{r}×{n}"
                            for r, n in sorted(alerts["by_reason"].items()))
                + (f" — first at step {first.get('step')}"
                   f" ({first.get('reason')})" if first else ""))
        else:
            L.append("- no numerics alerts")
        for led in (num.get("dtype_ledgers") or [])[:1]:
            per = led.get("per_dtype") or {}
            if per:
                L.append("")
                L.append("| dtype | ops | buffer bytes | matmul FLOPs |")
                L.append("|---|---|---|---|")
                for dt, b in per.items():
                    L.append(f"| {dt} | {b['ops']} | {b['bytes']:,} | "
                             + (f"{b['flops']:.3e} |" if b['flops']
                                else "- |"))
        par = num.get("parity")
        if par:
            L.append("")
            L.append(f"- A/B parity ({' vs '.join(par.get('labels', []))}): "
                     f"**{par.get('verdict')}**")
            for c in par.get("streams", []):
                mrd = c.get("max_rel_delta")
                L.append(
                    f"  - {c.get('key')}: {c.get('verdict')} over "
                    f"{c.get('n_common')} steps"
                    + (f", max rel delta {mrd:.3g}"
                       if isinstance(mrd, (int, float)) else ""))
        L.append("")

    comp = report.get("compile", {})
    L.append(f"Compiles: {comp.get('count', 0)} "
             f"({comp.get('recompiles', 0)} recompiles), "
             f"{comp.get('time_s', 0):.1f}s total")
    L.append("")

    comm = report.get("comm", {})
    if comm.get("ledger", {}).get("n_collectives"):
        led = comm["ledger"]
        model = comm.get("model", {})
        L.append("## Communication")
        L.append("")
        L.append(
            f"- verdict: **{comm.get('verdict', 'unknown')}** "
            f"({comm.get('verdict_basis', '')})")
        if "comm_fraction" in comm:
            L.append(f"- modeled comm fraction of step: "
                     f"**{comm['comm_fraction']:.1%}** "
                     f"({comm['modeled_comm_s'] * 1e3:.3f} ms modeled vs "
                     f"{comm['measured_step_s'] * 1e3:.2f} ms measured)")
        if "modeled_compute_s" in comm:
            L.append(f"- modeled compute: "
                     f"{comm['modeled_compute_s'] * 1e3:.3f} ms")
        ov = comm.get("overlap")
        if ov:
            dist = ov.get("mean_sched_distance")
            L.append(
                f"- achieved overlap: **{ov['achieved_fraction']:.1%}** of "
                f"modeled comm hidden ({ov['hidden_ops']}/{ov['async_ops']} "
                f"async + {ov['sync_ops']} sync collectives"
                + (f", mean sched distance {dist:.0f} instr" if dist is not None
                   else "") + ")")
            if "comm_fraction_effective" in comm:
                L.append(f"- effective (exposed) comm fraction: "
                         f"{comm['comm_fraction_effective']:.1%}")
        if "overlap_headroom_s" in comm:
            L.append(f"- overlap headroom: "
                     f"{comm['overlap_headroom_s'] * 1e3:.3f} ms"
                     + (" (vs zero-overlap floor; see achieved overlap above)"
                        if ov else ""))
        L.append(f"- model source: {model.get('source', '?')} "
                 f"(chip {model.get('chip', '?')})")
        L.append("")
        L.append("| dim | collectives | bytes/step | modeled time |")
        L.append("|---|---|---|---|")
        per_dim_s = model.get("per_dim_s", {})
        for dim, st in sorted(led.get("per_dim", {}).items()):
            t = per_dim_s.get(dim)
            L.append(
                f"| {dim} | {st['ops']} | {st['bytes']:,} | "
                + (f"{t * 1e3:.3f} ms |" if isinstance(t, (int, float))
                   else "- |"))
        L.append("")

    cmpx = report.get("compression")
    if cmpx:
        L.append("## Compression")
        L.append("")
        pol = cmpx.get("policy", {})
        L.append(f"- mode: **{cmpx.get('mode', '?')}** — "
                 f"{pol.get('n_compressed', 0)}/{pol.get('n_leaves', 0)} "
                 f"grad leaves on the int8 ring")
        rows = cmpx.get("per_axis") or []
        if rows:
            L.append("")
            L.append("| axes | predicted bytes | ledger-measured bytes |")
            L.append("|---|---|---|")
            for r in rows:
                pred = r.get("predicted_bytes")
                meas = r.get("measured_bytes")
                L.append(
                    f"| {r['axes']} | "
                    + (f"{pred:,} | " if isinstance(pred, int) else "- | ")
                    + (f"{meas:,} |" if isinstance(meas, int) else "- |"))
        L.append("")

    ap = report.get("autoplan")
    if ap:
        L.append("## Auto-sharding plan")
        L.append("")
        L.append(
            f"- {ap.get('n_candidates', 0)} candidate(s) enumerated, "
            f"**{ap.get('n_pruned_oom', 0)} pruned over-budget** before any "
            f"compile (`plan_rejected_oom` events carry each)")
        basis = ap.get("basis") or {}
        if basis:
            L.append(
                f"- scoring basis: comm `{basis.get('comm', '?')}`, compute "
                f"`{basis.get('compute', '?')}`, memory "
                f"`{basis.get('memory', '?')}`")
        chosen = ap.get("chosen")
        if ap.get("verdict") == "all_oom":
            L.append("- **no plan fits the memory budget** (verdict "
                     "`all_oom`) — every candidate pruned")
        elif chosen:
            mem = chosen.get("memory") or {}
            L.append(
                f"- chosen: **`{chosen['key']}`** — modeled step "
                f"{chosen['step_s'] * 1e3:.3f} ms (compute "
                f"{chosen['compute_s'] * 1e3:.3f} + comm "
                f"{chosen['comm_s'] * 1e3:.3f}), modeled resident "
                f"{mem.get('total_bytes', 0) / 1e6:.1f} MB/device")
            terms = chosen.get("terms") or []
            if terms:
                L.append("")
                L.append("| term | op | axes | payload | x | modeled |")
                L.append("|---|---|---|---|---|---|")
                for t in terms:
                    tag = " (int8)" if t.get("compressed") else ""
                    L.append(
                        f"| {t['name']}{tag} | {t['op']} | "
                        f"{'+'.join(t['axes'])} | {t['payload_bytes']:,} B "
                        f"| {t['count']} | {t['total_s'] * 1e3:.3f} ms |")
        ranked = ap.get("ranked") or []
        if len(ranked) > 1:
            L.append("")
            L.append("| rank | plan | modeled step | comm | resident | "
                     "verdict |")
            L.append("|---|---|---|---|---|---|")
            for i, r in enumerate(ranked):
                mem = r.get("memory") or {}
                L.append(
                    f"| {i + 1} | `{r['key']}` | {r['step_s'] * 1e3:.3f} ms "
                    f"| {r['comm_s'] * 1e3:.3f} ms "
                    f"| {mem.get('total_bytes', 0) / 1e6:.1f} MB "
                    f"| {mem.get('verdict', '?')} |")
        mvm = ap.get("modeled_vs_measured")
        if mvm and mvm.get("rows"):
            agree = mvm.get("ordering_agrees")
            L.append("")
            L.append(
                "- modeled vs measured: ordering "
                + ("**agrees**" if agree else "**DISAGREES** (per-term "
                   "breakdowns above are the audit trail)"))
            for r in mvm["rows"]:
                re_ = r.get("rel_err")
                L.append(
                    f"  - `{r['key']}`: modeled "
                    f"{r['modeled_step_s'] * 1e3:.3f} ms vs measured "
                    f"{r['measured_step_s'] * 1e3:.3f} ms"
                    + (f" ({re_:+.1%})" if isinstance(re_, (int, float))
                       else ""))
        L.append("")

    res = report.get("resilience")
    if res:
        L.append("## Resilience")
        L.append("")
        L.append(f"- verdict: **{res.get('verdict', '?')}**")
        L.append(f"- rollbacks: {res.get('rollbacks', 0)} "
                 f"(budget {res.get('max_rollbacks', '?')})")
        if res.get("faults_injected"):
            L.append(f"- chaos faults injected: {res['faults_injected']}")
        if res.get("data_offset"):
            L.append(f"- data stream advanced by {res['data_offset']} "
                     f"batch(es) past poisoned windows")
        if res.get("last_checkpoint") is not None:
            L.append(f"- last good checkpoint: step {res['last_checkpoint']}")
        if res.get("hang_suspected"):
            L.append(f"- watchdog hang episodes: {res['hang_suspected']}")
        L.append("")

    srv = report.get("serving")
    if srv:
        L.append("## Serving")
        L.append("")
        reqs = srv.get("requests", {})
        L.append(f"- requests: **{reqs.get('completed', 0)} completed** "
                 f"({reqs.get('queued', 0)} queued, "
                 f"{reqs.get('in_flight', 0)} in flight at finalize)")
        if srv.get("verdict"):
            stress = ", ".join(
                f"{k} {reqs.get(k, 0)}"
                for k in ("shed", "expired", "preempted", "cancelled",
                          "resumed")
                if reqs.get(k))
            L.append(f"- verdict: **{srv['verdict']}**"
                     + (f" ({stress})" if stress else "")
                     + (f" — {srv['verdict_basis']}"
                        if srv.get("verdict_basis") else ""))
        faults = srv.get("faults") or {}
        if faults.get("detected"):
            L.append(f"- faults: {faults['detected']} detected, "
                     f"{faults.get('healed', 0)} healed "
                     f"({faults.get('audits', 0)} invariant audits)")
        pc = srv.get("prefix_cache") or {}
        if pc.get("enabled"):
            L.append(
                f"- prefix cache: hit rate "
                f"**{srv.get('prefix_hit_rate', 0.0):.0%}** "
                f"({pc.get('hits', 0)} hits, {pc.get('cached_tokens', 0)} "
                f"tokens, {pc.get('cow_copies', 0)} COW, "
                f"{pc.get('evictions', 0)} evictions)")
        spec = srv.get("spec") or {}
        if spec.get("k"):
            L.append(
                f"- speculative decode (k={spec['k']}): accept rate "
                f"**{srv.get('spec_accept_rate', 0.0):.0%}** "
                f"({spec.get('accepted', 0)}/{spec.get('drafted', 0)} "
                f"drafts)")
        prios = srv.get("priorities") or {}
        if len(prios) > 1:
            L.append("")
            L.append("| priority | completed | TTFT p50 | TTFT p99 "
                     "| TPOT p50 |")
            L.append("|---|---|---|---|---|")
            for p in sorted(prios, key=lambda x: -int(x)):
                row = prios[p]
                tt, tp = row.get("ttft_s") or {}, row.get("tpot_s") or {}
                fmt = (lambda d, k: f"{d[k] * 1e3:.2f} ms"
                       if isinstance(d.get(k), (int, float)) else "-")
                L.append(
                    f"| {p} | {row.get('completed', 0)} "
                    f"| {fmt(tt, 'p50')} | {fmt(tt, 'p99')} "
                    f"| {fmt(tp, 'p50')} |")
            L.append("")
        L.append(f"- aggregate throughput: "
                 f"**{srv.get('tokens_per_sec', 0.0):.1f} tok/s** "
                 f"({srv.get('generated_tokens', 0)} tokens)")
        for key, label in (("ttft_s", "TTFT"), ("tpot_s", "TPOT")):
            pct = srv.get(key) or {}
            if pct:
                L.append(
                    f"- {label}: " + " / ".join(
                        f"{p} {pct[p] * 1e3:.2f} ms"
                        for p in ("p50", "p95", "p99") if p in pct))
        occ = srv.get("slot_occupancy", {})
        pool = srv.get("kv_pool", {})
        if occ:
            L.append(f"- slot occupancy: mean "
                     f"**{occ.get('mean', 0.0):.1%}** of "
                     f"{occ.get('num_slots', '?')} slots")
        if pool:
            L.append(
                f"- KV pool: {pool.get('num_blocks', '?')} blocks x "
                f"{pool.get('block_size', '?')} positions "
                f"(x{pool.get('dp_groups', 1)} dp) — mean utilization "
                f"{pool.get('mean_utilization', 0.0):.1%}, peak "
                f"{pool.get('peak_utilization', 0.0):.1%}")
        L.append(
            f"- {srv.get('decode_steps', 0)} decode steps "
            f"(mean batch {srv.get('decode_batch_mean', 0.0):.2f}) + "
            f"{srv.get('prefill_chunks', 0)} prefill chunks; "
            f"{srv.get('decode_signatures', '?')} decode signature(s) "
            f"compiled")
        slo = srv.get("slo") or {}
        if slo:
            att = slo.get("attainment")
            L.append(
                f"- SLO goodput: **{slo.get('goodput_tok_s', 0.0):.1f} "
                f"tok/s** ({slo.get('goodput_tokens', 0)} deadline-meeting "
                f"tokens)"
                + (f", attainment **{att:.0%}**" if att is not None
                   else " — no deadlines submitted"))
            cal = slo.get("calibration") or {}
            if cal.get("n"):
                bias = cal.get("bias")
                L.append(
                    f"- TTFT calibration: {cal['n']} prediction(s) "
                    f"resolved, EWMA bias "
                    + (f"**{bias:.3f}**" if isinstance(bias, (int, float))
                       else "unset")
                    + f" ({cal.get('pending', 0)} pending)")
            sp = slo.get("priorities") or {}
            if sp:
                L.append("")
                L.append("| priority | completed | met | missed | shed "
                         "| expired | attainment | goodput tokens |")
                L.append("|---|---|---|---|---|---|---|---|")
                for p in sorted(sp, key=lambda x: -int(x)):
                    row = sp[p]
                    ra = row.get("attainment")
                    L.append(
                        f"| {p} | {row.get('completed', 0)} "
                        f"| {row.get('met', 0)} | {row.get('missed', 0)} "
                        f"| {row.get('shed', 0)} | {row.get('expired', 0)} "
                        f"| " + (f"{ra:.0%}" if ra is not None else "-")
                        + f" | {row.get('goodput_tokens', 0)} |")
                L.append("")
        ta = srv.get("tick_accounting") or {}
        if ta.get("ticks"):
            pm = ta.get("phases_mean_s") or {}
            L.append(
                f"- tick accounting: {ta['ticks']} ticks, mean "
                f"{ta.get('mean_tick_s', 0.0) * 1e3:.2f} ms ("
                + ", ".join(f"{k} {v * 1e3:.2f}" for k, v in pm.items()
                            if v > 0)
                + " ms)")
        L.append("")

    rt = report.get("router")
    if rt and isinstance(rt.get("fleet"), dict):
        fleet = rt["fleet"]
        L.append("## Router fleet")
        L.append("")
        L.append(
            f"- verdict: **{fleet.get('verdict', '?')}** "
            f"({fleet.get('n_alive', '?')}/{fleet.get('n_replicas', '?')} "
            f"replicas alive)")
        L.append(
            f"- fleet throughput: "
            f"**{fleet.get('tokens_per_sec', 0.0):.1f} tok/s** "
            f"({fleet.get('generated_tokens', 0)} tokens), goodput "
            f"{fleet.get('goodput_tok_s', 0.0):.1f} tok/s")
        aff = fleet.get("affinity") or {}
        L.append(
            f"- prefix affinity: hit rate "
            f"**{aff.get('hit_rate', 0.0):.0%}** "
            f"({aff.get('affinity_routed', 0)}/{aff.get('routed', 0)} "
            f"routed warm, {aff.get('fallbacks', 0)} shed-fallbacks)")
        mig = fleet.get("migrations") or {}
        L.append(
            f"- KV migrations: {mig.get('handoffs', 0)} handoffs "
            f"({mig.get('blocks', 0)} blocks copied, "
            f"{mig.get('shared_blocks', 0)} prefix-shared on arrival, "
            f"{mig.get('bytes', 0) / 1e6:.2f} MB wire, "
            f"{mig.get('compressed', 0)} int8-compressed) over "
            f"{mig.get('signatures', 0)} compiled pair program(s)")
        if mig.get("retries") or mig.get("fallbacks"):
            L.append(
                f"- migration wire: {mig.get('retries', 0)} chunk "
                f"re-request(s) healed by backoff, "
                f"{mig.get('fallbacks', 0)} dead transfer(s) fell back "
                f"to re-prefill")
        asc = fleet.get("autoscale") or {}
        if asc:
            L.append(
                f"- autoscale: **{asc.get('verdict', '?')}** "
                f"({asc.get('scale_ups', 0)} up / "
                f"{asc.get('scale_downs', 0)} down / "
                f"{asc.get('retiers', 0)} retier over "
                f"{asc.get('evals', 0)} evals) — {asc.get('basis', '')}")
        L.append(
            f"- rebalances: {fleet.get('rebalances', 0)} "
            f"({fleet.get('rebalanced_requests', 0)} requests moved), "
            f"evacuations: {fleet.get('evacuations', 0)} "
            f"({fleet.get('evacuated_requests', 0)} rehomed)")
        slo = fleet.get("slo") or {}
        if slo:
            att = slo.get("attainment")
            prio_bits = ", ".join(
                f"p{k}: {row['attainment']:.0%}"
                for k, row in sorted((slo.get("priorities") or {}).items())
                if isinstance(row, dict)
                and row.get("attainment") is not None)
            L.append(
                f"- fleet SLO attainment: "
                f"**{att:.0%}**" if att is not None
                else "- fleet SLO attainment: **n/a** (no deadlines)")
            if prio_bits:
                L[-1] += f" ({prio_bits})"
        bal = fleet.get("balance") or {}
        if bal:
            idx = bal.get("imbalance_index")
            L.append(
                f"- load balance: **{bal.get('verdict', '?')}**"
                + (f" (imbalance index {idx:.2f})" if idx is not None
                   else "")
                + f" — {bal.get('basis', '')}")
        reps = rt.get("replicas") or []
        if reps:
            L.append("")
            L.append("| replica | role | zone | alive | verdict | tok/s "
                     "| completed | migrated in/out | hit rate | SLO att |")
            L.append("|---|---|---|---|---|---|---|---|---|---|")
            for row in reps:
                reqs = row.get("requests") or {}
                ratt = (row.get("slo") or {}).get("attainment")
                L.append(
                    f"| {row.get('index', '?')} | {row.get('role', '?')} "
                    f"| {row.get('zone', '?')} "
                    f"| {'yes' if row.get('alive') else 'DEAD'} "
                    f"| {row.get('verdict', '?')} "
                    f"| {row.get('tokens_per_sec', 0.0):.1f} "
                    f"| {reqs.get('completed', 0)} "
                    f"| {reqs.get('migrated_in', 0)}/"
                    f"{reqs.get('migrated_out', 0)} "
                    f"| {row.get('prefix_hit_rate', 0.0):.0%} "
                    f"| {f'{ratt:.0%}' if ratt is not None else 'n/a'} |")
        L.append("")

    counters = report.get("counters", {})
    if counters:
        L.append("## Counters")
        L.append("")
        for name, val in counters.items():
            L.append(f"- **{name}**: `{json.dumps(val)}`")
        L.append("")

    hosts = report.get("hosts", {})
    if hosts.get("n_hosts", 1) > 1:
        L.append("## Hosts")
        L.append("")
        L.append("| host | mean | min | max |")
        L.append("|---|---|---|---|")
        for h in hosts["per_host"]:
            mark = " ⚠" if h["process"] == hosts.get("straggler") else ""
            L.append(f"| {h['process']}{mark} | {h['mean'] * 1e3:.2f} ms "
                     f"| {h['min'] * 1e3:.2f} | {h['max'] * 1e3:.2f} |")
        L.append("")

    events = report.get("events", [])
    if events:
        L.append("## Event timeline")
        L.append("")
        t0 = events[0]["t_mono"]
        n_ticks = sum(1 for ev in events if ev.get("kind") == "engine_tick")
        if n_ticks:
            # per-tick accounting is trace material, not summary material
            L.append(f"- ({n_ticks} `engine_tick` record(s) elided — "
                     f"scrub them in the Perfetto trace)")
        for ev in events:
            if ev.get("kind") == "engine_tick":
                continue
            extras = {k: v for k, v in ev.items()
                      if k not in ("type", "kind", "t_wall", "t_mono", "process")
                      and v is not None}
            tail = f" {json.dumps(extras)}" if extras else ""
            L.append(f"- `+{ev['t_mono'] - t0:8.3f}s` p{ev['process']} "
                     f"**{ev['kind']}**{tail}")
        L.append("")
    return "\n".join(L)


def write_runreport(report: Dict[str, Any], path: str) -> None:
    """Write ``path`` (JSON) and a sibling ``.md``; best-effort on OSError
    (a read-only checkout must not crash the run at its last step)."""
    try:
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(report, f, indent=1, default=str)
        md = os.path.splitext(path)[0] + ".md"
        with open(md, "w") as f:
            f.write(render_markdown(report))
    except OSError:
        pass
