"""Vision Transformer — the vision model family the reference exercises its
DP/ZeRO paths with (``examples/test_ddp.py:74-86`` uses timm resnet50;
``examples/test_zero_optim.py:88`` notes timm ViT).  Instead of wrapping an
external torch model, the ViT is built from the same TP/SP transformer blocks
as the GPT flagship, so every parallel strategy (DP, TP+SP, ZeRO, FSDP, EMA)
applies to a vision workload unchanged.

TPU notes: patchify is one reshape+matmul (a conv with stride=patch is
exactly a [P*P*C, D] matmul on unfolded patches — MXU-friendly, no conv
lowering needed); non-causal attention; mean-pool head (no CLS token keeps
shapes static and pooling free).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional

import jax

from ..compat import axis_size
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..parallel.tensor_parallel import (
    RematMode,
    TransformerConfig,
    block_forward,
    init_block_params,
    init_norm_params,
    layer_norm,
    norm_param_specs,
    stacked_block_specs,
)

PyTree = Any


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    channels: int = 3
    num_classes: int = 1000
    dim: int = 384
    nheads: int = 6
    nlayers: int = 12
    ffn_mult: int = 4
    dtype: Any = jnp.float32
    # 'naive' | 'flash' | 'ring' | 'ulysses' — ring/ulysses run non-causal
    # context parallelism over ``context_axis`` (patch tokens sharded)
    attn_impl: str = "naive"
    context_axis: Optional[str] = None
    dropout_rate: float = 0.0  # residual dropout (needs a dropout_key)
    # MoE knobs (models/vit_moe.py, V-MoE style): >0 experts turns every
    # moe_every-th block's FFN into the expert layer.  ViT is an ENCODER
    # (causal=False), so — unlike GPT-MoE — the 'expert_choice' router is
    # allowed here: the Zhou et al. setting, balanced by construction.
    moe_experts: int = 0
    moe_top_k: int = 2
    moe_every: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 1e-2
    moe_router: str = "topk"  # 'topk' | 'expert_choice' (encoder: both ok)
    moe_dispatch: str = "auto"  # 'dense' | 'sorted' | 'auto' (see MoEConfig)
    # 'layer' | 'rms' and 'gelu' | 'swiglu' — same structural dispatch as
    # the GPT family (tensor_parallel/layers.py)
    norm: str = "layer"
    act: str = "gelu"

    def __post_init__(self):
        if self.context_axis is not None and self.attn_impl not in ("ring", "ulysses"):
            raise ValueError(
                f"context_axis={self.context_axis!r} requires attn_impl "
                f"'ring' or 'ulysses' (got {self.attn_impl!r})"
            )

    @property
    def num_patches(self) -> int:
        assert self.image_size % self.patch_size == 0
        return (self.image_size // self.patch_size) ** 2

    @property
    def patch_dim(self) -> int:
        return self.patch_size * self.patch_size * self.channels

    @property
    def block(self) -> TransformerConfig:
        return TransformerConfig(
            dim=self.dim, nheads=self.nheads, nlayers=self.nlayers,
            ffn_mult=self.ffn_mult, causal=False, dtype=self.dtype,
            attn_impl=self.attn_impl, context_axis=self.context_axis,
            dropout_rate=self.dropout_rate, norm=self.norm, act=self.act,
        )


def patchify(images: jnp.ndarray, patch: int) -> jnp.ndarray:
    """[B, H, W, C] -> [B, N, P*P*C] non-overlapping patches (pure reshape /
    transpose — XLA fuses it into the following matmul's operand load)."""
    B, H, W, C = images.shape
    gh, gw = H // patch, W // patch
    x = images.reshape(B, gh, patch, gw, patch, C)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(B, gh * gw, patch * patch * C)


def init_vit_params(key, cfg: ViTConfig) -> Dict[str, PyTree]:
    kp, kpos, kh, kb = jax.random.split(key, 4)
    dt = cfg.dtype
    keys = jax.random.split(kb, cfg.nlayers)
    blocks = [init_block_params(k, cfg.block) for k in keys]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs, axis=0), *blocks)
    return {
        "patch_proj": {
            "w": (jax.random.normal(kp, (cfg.patch_dim, cfg.dim))
                  / math.sqrt(cfg.patch_dim)).astype(dt),
            "b": jnp.zeros((cfg.dim,), dt),
        },
        "pos_emb": (jax.random.normal(kpos, (cfg.num_patches, cfg.dim)) * 0.02).astype(dt),
        "blocks": stacked,
        "ln_f": init_norm_params(cfg.dim, dt, cfg.norm),
        "head": {
            "w": (jax.random.normal(kh, (cfg.dim, cfg.num_classes))
                  / math.sqrt(cfg.dim)).astype(dt),
            "b": jnp.zeros((cfg.num_classes,), dt),
        },
    }


def vit_embed(
    params: Dict[str, PyTree],
    images: jnp.ndarray,
    cfg: ViTConfig,
) -> jnp.ndarray:
    """[B, H, W, C] images -> [B, N(/cp), D] patch embedding — shared by
    :func:`vit_forward` and the pipeline's stage-0 ``first_fn`` (one
    implementation, no drift)."""
    x = patchify(images.astype(cfg.dtype), cfg.patch_size)
    cp = cfg.context_axis if cfg.attn_impl in ("ring", "ulysses") else None
    if cp is not None:
        # context parallelism: slice the LOCAL patch chunk before the
        # projection so the [B, S, D] embed activation and its matmul are
        # O(S/cp) per device (patchify itself is a free reshape); the
        # (non-causal) ring/all_to_all inside the blocks sees the rest
        n_cp = axis_size(cp)
        if x.shape[1] % n_cp != 0:
            raise ValueError(
                f"num_patches {x.shape[1]} not divisible by context-parallel "
                f"size {n_cp} — trailing patches would be silently dropped"
            )
        s_loc = x.shape[1] // n_cp
        off = jax.lax.axis_index(cp) * s_loc
        x = jax.lax.dynamic_slice_in_dim(x, off, s_loc, axis=1)
        h = x @ params["patch_proj"]["w"] + params["patch_proj"]["b"]
        return h + jax.lax.dynamic_slice_in_dim(params["pos_emb"], off, s_loc, axis=0)
    h = x @ params["patch_proj"]["w"] + params["patch_proj"]["b"]
    return h + params["pos_emb"]


def vit_pool_logits(
    params: Dict[str, PyTree],
    h: jnp.ndarray,
    cfg: ViTConfig,
    axis: Optional[str] = None,
    sp: bool = False,
) -> jnp.ndarray:
    """Post-blocks hidden -> [B, num_classes(/tp)] logits (SP gather, final
    LN, patch mean-pool with the CP mean-of-means, class head) — shared by
    :func:`vit_forward` and the pipeline's last stage."""
    if axis is not None and sp:
        from ..parallel.tensor_parallel import gather_from_sp

        h = gather_from_sp(h, axis)
    h = layer_norm(h, params["ln_f"])
    pooled = jnp.mean(h, axis=1)  # mean-pool over (local) patches
    cp = cfg.context_axis if cfg.attn_impl in ("ring", "ulysses") else None
    if cp is not None:
        pooled = jax.lax.pmean(pooled, cp)  # equal chunks: mean of means
    return pooled @ params["head"]["w"] + params["head"]["b"]


def vit_forward(
    params: Dict[str, PyTree],
    images: jnp.ndarray,
    cfg: ViTConfig,
    axis: Optional[str] = None,
    sp: bool = False,
    remat: RematMode = False,
    dropout_key = None,
) -> jnp.ndarray:
    """[B, H, W, C] images -> [B, num_classes] logits.  TP(/SP) over ``axis``
    inside shard_map, serial when None — same contract as gpt_forward."""
    from ..parallel.tensor_parallel import scan_blocks

    h = vit_embed(params, images, cfg)
    if axis is not None and sp:
        from ..parallel.tensor_parallel import split_to_sp

        h = split_to_sp(h, axis)
    h = scan_blocks(params["blocks"], h, cfg.block, axis, sp, remat=remat,
                    dropout_key=dropout_key)
    return vit_pool_logits(params, h, cfg, axis=axis, sp=sp)


def vit_loss(
    params: Dict[str, PyTree],
    batch: Dict[str, jnp.ndarray],
    cfg: ViTConfig,
    axis: Optional[str] = None,
    sp: bool = False,
    remat: RematMode = False,
    dropout_key = None,
) -> jnp.ndarray:
    """Mean softmax cross-entropy.  ``batch``: {'images': [B,H,W,C],
    'labels': int [B]}.  Under TP the class dim of the head is sharded and
    the CE closes with the same collectives as the GPT vocab-parallel CE."""
    from .gpt import vocab_parallel_xent

    logits = vit_forward(params, batch["images"], cfg, axis=axis, sp=sp,
                         remat=remat, dropout_key=dropout_key)
    # static shape tells whether the head was class-sharded: a local shard is
    # narrower than num_classes (shapes are trace-time constants under XLA)
    tp = axis if logits.shape[-1] != cfg.num_classes else None
    return vocab_parallel_xent(logits, batch["labels"], tp)


def vit_param_specs(
    cfg: ViTConfig,
    tp_axis: Optional[str] = None,
    pipe_axis: Optional[str] = None,
) -> Dict[str, PyTree]:
    """PartitionSpec tree matching :func:`init_vit_params`: per-block TP specs
    with a leading stack-dim entry (``pipe_axis`` shards the stack for
    pipelining, None replicates it); class-sharded head when the class count
    divides the TP size (else keep the head replicated by passing specs with
    ``head`` overridden to P())."""
    blocks = stacked_block_specs(
        tp_axis, stack_axis=pipe_axis, norm=cfg.norm, act=cfg.act)
    head_w = P(None, tp_axis) if tp_axis else P()
    head_b = P(tp_axis) if tp_axis else P()
    return {
        "patch_proj": {"w": P(), "b": P()},
        "pos_emb": P(),
        "blocks": blocks,
        "ln_f": norm_param_specs(cfg.norm),
        "head": {"w": head_w, "b": head_b},
    }


def vit_pipeline_1f1b(
    params: Dict[str, PyTree],
    batch: Dict[str, jnp.ndarray],
    cfg: ViTConfig,
    num_microbatches: int,
    tp_axis: Optional[str] = None,
    pipe_axis: str = "pipe",
    sp: bool = False,
    remat: RematMode = True,
    dropout_key: Optional[jax.Array] = None,
):
    """1F1B-scheduled ViT training core: returns ``(loss, grads)`` (see
    ``parallel.pipeline_parallel.pipeline_1f1b``).  The reference's PP
    example pipelines a VISION classifier
    (examples/model_parallel/test_pipeline.py:54-123, DummyClsDataset) — this
    is that capability on the native ViT: stage 0 embeds
    (:func:`vit_embed`), the block stack is the pipelined region, the last
    stage pools + classifies (:func:`vit_pool_logits`).

    ``batch``: {'images': [M, mbs, H, W, C], 'labels': int [M, mbs]}.
    Params use :func:`vit_param_specs` with ``pipe_axis`` set.
    ``dropout_key`` threads residual dropout through the pipeline with
    per-(stage, microbatch, layer) masks, same recipe as
    ``gpt_pipeline_1f1b``."""
    from ..parallel.pipeline_parallel import pipeline_1f1b
    from ..parallel.tensor_parallel import scan_blocks, split_to_sp
    from .gpt import vocab_parallel_xent

    # CP composition note: unlike the GPT CE (a mean over context-LOCAL
    # tokens, which makes the context axis a plain data axis), the ViT loss
    # pmean-pools patches over the context axis INSIDE the model, so
    # context must be treated as a MODEL axis by the train step:
    #   DataParallel(mesh, axis='data')      # context NOT in the data axes
    # Params then stay context-invariant-typed and shard_map AD resolves
    # each leaf on its own — pre-pool leaves get the automatic
    # transpose-psum of their per-rank SHARES, the post-pool class head
    # keeps its single full grad.  (An axis-wide sum would double-count the
    # head; an axis-wide mean would halve the shares.)  Golden-tested in
    # tests/test_vit.py::test_vit_1f1b_with_cp_matches_serial.

    def first_fn(p, images):
        h = vit_embed(p, images, cfg)
        if tp_axis is not None and sp:
            h = split_to_sp(h, tp_axis)
        return h

    def stage_fn(p, x, m):
        k = None
        if dropout_key is not None and cfg.dropout_rate > 0.0:
            k = jax.random.fold_in(dropout_key, jax.lax.axis_index(pipe_axis))
            k = jax.random.fold_in(k, m)
        return scan_blocks(
            p["blocks"], x, cfg.block, tp_axis, sp, remat=remat, dropout_key=k
        )

    def last_fn(p, y, labels):
        logits = vit_pool_logits(p, y, cfg, axis=tp_axis, sp=sp)
        tp = tp_axis if logits.shape[-1] != cfg.num_classes else None
        return vocab_parallel_xent(logits, labels, tp)

    return pipeline_1f1b(
        params,
        batch["images"],
        batch["labels"],
        first_fn=first_fn,
        stage_fn=stage_fn,
        last_fn=last_fn,
        num_microbatches=num_microbatches,
        pipe_axis=pipe_axis,
        stage_takes_mb=True,
    )
