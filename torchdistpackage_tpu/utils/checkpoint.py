"""Sharded checkpoint save/resume + model-parallel ckpt naming.

The reference ships only a per-partition filename helper
(``dist/model_parallel_ckpt.py:4-21`` — suffix ``_tp_{r}_pp_{r}.pth``; note
its bare ``is_mode_inited`` NameError, SURVEY §2#15) and rank-0 state
reconstruction inside ShardedEMA; there is **no** unified save/load or resume
(SURVEY §5).  Here checkpointing is first-class and TPU-native: Orbax writes
each array *shard-parallel* from every host (no rank-0 gather, no per-rank
files to stitch), records the mesh/PartitionSpec layout, and restores
directly into any sharding you ask for — so a checkpoint written on one mesh
can resume on another (e.g. TP=4 -> TP=2) by just passing the new specs.

- :func:`get_mp_ckpt_suffix` — behavioral parity with the reference helper
  (with the NameError fixed), for users who want legacy-style names.
- :func:`save_checkpoint` / :func:`load_checkpoint` — one-shot pytree
  save/restore (params, opt state, EMA, step counters, ...).
- :class:`CheckpointManager` — step-numbered checkpoints, retention policy,
  and ``latest_step`` resume — the missing "resume logic".
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

PyTree = Any


def get_mp_ckpt_suffix() -> str:
    """Per-partition filename suffix, e.g. ``_tp_0_pp_1`` — parity with
    ``get_mp_ckpt_suffix`` (model_parallel_ckpt.py:4-21), minus its
    ``is_mode_inited`` NameError.  Empty string when no model parallelism."""
    from ..dist.topology import PIPE_AXIS, TENSOR_AXIS, tpc

    suffix = ""
    if tpc.is_mode_inited(TENSOR_AXIS):
        suffix += f"_tp_{tpc.process_axis_index(TENSOR_AXIS)}"
    if tpc.is_mode_inited(PIPE_AXIS):
        suffix += f"_pp_{tpc.process_axis_index(PIPE_AXIS)}"
    return suffix


def _ocp():
    import orbax.checkpoint as ocp

    return ocp


def _norm_path(path: str) -> str:
    """Absolutize local paths; leave URI schemes (gs://, s3://, ...) intact —
    Orbax handles those natively and abspath would mangle them."""
    if "://" in path:
        return path
    return os.path.abspath(path)


def save_checkpoint(path: str, state: PyTree, force: bool = True) -> None:
    """Write ``state`` (any pytree of arrays/scalars) to ``path``.

    Every host writes its own shards in parallel; jax.Arrays keep their
    sharding metadata.  Replaces the reference's nonexistent save path and
    ShardedEMA's rank-0 send/recv reconstruction (sharded_ema.py:36-61).
    """
    ocp = _ocp()
    path = _norm_path(path)
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, state, force=force)


def load_checkpoint(
    path: str,
    template: Optional[PyTree] = None,
    mesh: Optional[Mesh] = None,
    specs: Optional[PyTree] = None,
) -> PyTree:
    """Restore a pytree from ``path``.

    - ``template=None``: restore as numpy arrays (host-side inspection).
    - ``template`` given (arrays or ShapeDtypeStructs): restore into that
      structure's shapes/dtypes/shardings.
    - ``mesh`` + ``specs`` given: override shardings — this is the
      resharding-resume path (checkpoint from one mesh, resume on another).
    """
    ocp = _ocp()
    path = _norm_path(path)
    if specs is not None and mesh is None:
        from ..dist.topology import tpc

        mesh = tpc.get_view()
    if mesh is not None and specs is None:
        raise ValueError("load_checkpoint: `mesh` given without `specs`")
    if specs is not None and template is None:
        raise ValueError(
            "load_checkpoint: resharding restore (`specs`) needs `template` "
            "for the shapes/dtypes"
        )
    with ocp.StandardCheckpointer() as ckptr:
        if template is None:
            return ckptr.restore(path)

        if mesh is not None and specs is not None:
            def abstract(x, s):
                shape = np.shape(x)
                dtype = getattr(x, "dtype", np.asarray(x).dtype)
                return jax.ShapeDtypeStruct(
                    shape, dtype, sharding=NamedSharding(mesh, s or PartitionSpec())
                )

            template = jax.tree.map(abstract, template, specs)
        else:
            def abstract(x):
                if isinstance(x, jax.ShapeDtypeStruct):
                    return x
                shape = np.shape(x)
                dtype = getattr(x, "dtype", np.asarray(x).dtype)
                sharding = getattr(x, "sharding", None)
                return jax.ShapeDtypeStruct(shape, dtype, sharding=sharding)

            template = jax.tree.map(abstract, template)
        return ckptr.restore(path, template)


def auto_resume(
    mgr: "CheckpointManager",
    template: PyTree,
    mesh: Optional[Mesh] = None,
    specs: Optional[PyTree] = None,
    verify: bool = True,
):
    """``(start_step, state)`` for a preemption-safe loop: restore the
    newest *good* checkpoint when one exists (resuming at ``step + 1``),
    else start fresh from ``template``.  One call makes any training
    script relaunch-safe::

        start, state = auto_resume(mgr, {'params': params, 'opt': opt_state})
        with GracefulShutdown() as stop:
            for step in range(start, total): ...

    "Newest good", not "latest": a step that fails integrity verification
    (``resilience.ckpt_guard`` manifest mismatch) or whose restore raises
    is **quarantined** — renamed aside to ``<dir>.quarantine/<step>`` with
    a ``ckpt_quarantine`` event recording the step and reason — and the
    walk continues to the next older step.  A corrupted latest checkpoint
    therefore costs one save interval instead of the run (``verify=False``
    restores the old raise-on-corruption behavior).

    ``mesh``/``specs`` flow through to :meth:`CheckpointManager.restore`
    for resharding resumes (checkpoint from one mesh layout, resume on
    another)."""
    steps = sorted(mgr.all_steps(), reverse=True)
    for step in steps:
        try:
            if verify:
                from ..resilience.ckpt_guard import verify_checkpoint

                problems = verify_checkpoint(mgr.directory, step)
                if problems:
                    raise RuntimeError(
                        "integrity verification failed: "
                        + "; ".join(problems[:3]))
            state = mgr.restore(step, template=template, mesh=mesh, specs=specs)
            return step + 1, state
        except Exception as e:  # corrupt step: quarantine, walk back
            if not verify:
                raise
            from ..resilience.ckpt_guard import quarantine_checkpoint

            quarantine_checkpoint(mgr.directory, step, reason=repr(e))
            reload_fn = getattr(mgr, "reload", None)
            if callable(reload_fn):
                reload_fn()
    return 0, template


class CheckpointManager:
    """Step-numbered checkpoints with retention + latest-step resume.

    The subsystem the reference lacks entirely (SURVEY §5 "no unified
    save/load, no resume logic").  Usage::

        mgr = CheckpointManager(dir, max_to_keep=3)
        mgr.save(step, {'params': params, 'opt': opt_state})
        ...
        step = mgr.latest_step()          # None if fresh run
        state = mgr.restore(step, template={'params': params, 'opt': opt_state})
    """

    def __init__(self, directory: str, max_to_keep: int = 3, save_interval_steps: int = 1):
        ocp = _ocp()
        self.directory = _norm_path(directory)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
            ),
        )

    def save(self, step: int, state: PyTree, wait: bool = False) -> bool:
        ocp = _ocp()
        saved = self._mgr.save(step, args=ocp.args.StandardSave(state))
        if wait:
            self._mgr.wait_until_finished()
        if saved:
            from ..obs.events import emit_event

            emit_event("checkpoint_save", step=int(step), wait=bool(wait),
                       directory=str(self.directory))
        return saved

    def restore(
        self,
        step: Optional[int] = None,
        template: Optional[PyTree] = None,
        mesh: Optional[Mesh] = None,
        specs: Optional[PyTree] = None,
    ) -> PyTree:
        ocp = _ocp()
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(f"no checkpoints under {self.directory}")
        if specs is not None and mesh is None:
            from ..dist.topology import tpc

            mesh = tpc.get_view()
        if mesh is not None and specs is None:
            raise ValueError("restore: `mesh` given without `specs`")
        if specs is not None and template is None:
            raise ValueError(
                "restore: resharding restore (`specs`) needs `template` "
                "for the shapes/dtypes"
            )
        if template is None:
            return self._mgr.restore(step)
        if mesh is not None and specs is not None:
            def abstract(x, s):
                return jax.ShapeDtypeStruct(
                    np.shape(x),
                    getattr(x, "dtype", np.asarray(x).dtype),
                    sharding=NamedSharding(mesh, s or PartitionSpec()),
                )

            template = jax.tree.map(abstract, template, specs)
        out = self._mgr.restore(step, args=ocp.args.StandardRestore(template))
        from ..obs.events import emit_event

        emit_event("checkpoint_restore", step=int(step),
                   directory=str(self.directory),
                   resharded=mesh is not None)
        return out

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self):
        return self._mgr.all_steps()

    def wait_until_finished(self) -> None:
        self._mgr.wait_until_finished()

    def reload(self) -> None:
        """Re-scan the directory (needed after a step dir was renamed
        aside externally, e.g. quarantine of a corrupt checkpoint)."""
        reload_fn = getattr(self._mgr, "reload", None)
        if callable(reload_fn):
            reload_fn()

    def close(self) -> None:
        self._mgr.close()

    def __enter__(self) -> "CheckpointManager":
        return self

    def __exit__(self, *exc) -> None:
        # Wait for outstanding ASYNC saves before closing, even when the
        # block is unwinding on an exception: a crash between save() and
        # process teardown must not strand a partially-committed step
        # (Orbax only lists fully-committed steps, so an abandoned save
        # would silently lose the newest checkpoint).
        try:
            self.wait_until_finished()
        finally:
            self.close()
