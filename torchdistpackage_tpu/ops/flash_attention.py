"""Flash attention as a Pallas TPU kernel (fwd + custom-VJP bwd).

The reference only *derives* this math in a single-device numpy study
(explore/flash-attn/tile_attn.py:100-212 — tiled online-softmax fwd+bwd); it
ships no kernel.  Here it is a first-class TPU kernel: blockwise online
softmax with f32 accumulators in VMEM, MXU matmuls via ``jnp.dot`` with
``preferred_element_type``, causal upper-block skipping (the loop over KV
blocks stops at the diagonal), and a standard flash backward (recompute
probabilities from the saved logsumexp; dq kernel loops over KV blocks, dkv
kernel loops over Q blocks).

On CPU (tests / CI sim) the kernels run in Pallas interpreter mode
automatically, so the same code path is exercised everywhere.

Current scope: K/V for one (batch, head) stays VMEM-resident per program
(O(S) VMEM, fine to S ~ 16k at D=64 bf16; long-context runs shard S over the
ring first — ops/ring_attention.py — so per-shard S stays moderate).  A
blocked-KV 3D-grid revision lifts this ceiling for single-chip long S.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30  # finite "minus infinity": avoids (-inf) - (-inf) NaNs


def _interpret() -> bool:
    return jax.default_backend() == "cpu"


def mha_reference(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = True,
    sm_scale: Optional[float] = None,
) -> jnp.ndarray:
    """Plain softmax(QK^T)V golden — [B, H, S, D] layout."""
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * sm_scale
    if causal:
        Sq, Sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((Sq, Sk), dtype=bool), k=Sk - Sq)
        s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)


# ------------------------------------------------------------------- forward


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, sm_scale, causal, block_k, seq_k):
    block_q = q_ref.shape[1]
    d = q_ref.shape[2]
    qi = pl.program_id(1)

    q = q_ref[0]  # [Bq, D] storage dtype — MXU takes bf16 in, f32 out
    m = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((block_q, 1), jnp.float32)
    acc = jnp.zeros((block_q, d), jnp.float32)

    num_kv = seq_k // block_k
    if causal:
        # process KV blocks up to and including the diagonal block
        hi = jax.lax.div((qi + 1) * block_q + block_k - 1, block_k)
        hi = jnp.minimum(hi, num_kv)
    else:
        hi = num_kv

    def body(j, carry):
        m, l, acc = carry
        kblk = k_ref[0, pl.ds(j * block_k, block_k), :]
        vblk = v_ref[0, pl.ds(j * block_k, block_k), :]
        s = jnp.dot(q, kblk.T, preferred_element_type=jnp.float32) * sm_scale
        if causal:
            qpos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            kpos = j * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(kpos <= qpos, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * corr + jnp.dot(
            p.astype(vblk.dtype), vblk, preferred_element_type=jnp.float32
        )
        return m_new, l, acc

    m, l, acc = jax.lax.fori_loop(0, hi, body, (m, l, acc))
    o_ref[0] = (acc / l).astype(o_ref.dtype)
    lse_ref[0] = m + jnp.log(l)  # [Bq, 1]


def _fwd(q, k, v, sm_scale, causal, block_q, block_k):
    BH, Sq, D = q.shape
    Sk = k.shape[1]
    grid = (BH, Sq // block_q)
    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal, block_k=block_k, seq_k=Sk
    )
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, Sk, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, Sk, D), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, Sq, D), q.dtype),
            jax.ShapeDtypeStruct((BH, Sq, 1), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v)
    return o, lse


# ------------------------------------------------------------------ backward


def _bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *, sm_scale, causal, block_k, seq_k
):
    block_q = q_ref.shape[1]
    d = q_ref.shape[2]
    qi = pl.program_id(1)

    q = q_ref[0]
    do = do_ref[0]
    lse = lse_ref[0]  # [Bq, 1]
    delta = delta_ref[0]
    dq = jnp.zeros((block_q, d), jnp.float32)

    num_kv = seq_k // block_k
    if causal:
        hi = jnp.minimum(jax.lax.div((qi + 1) * block_q + block_k - 1, block_k), num_kv)
    else:
        hi = num_kv

    def body(j, dq):
        kblk = k_ref[0, pl.ds(j * block_k, block_k), :]
        vblk = v_ref[0, pl.ds(j * block_k, block_k), :]
        s = jnp.dot(q, kblk.T, preferred_element_type=jnp.float32) * sm_scale
        if causal:
            qpos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            kpos = j * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(kpos <= qpos, s, NEG_INF)
        p = jnp.exp(s - lse)  # [Bq, Bk]
        dp = jnp.dot(do, vblk.T, preferred_element_type=jnp.float32)
        ds = (p * (dp - delta)).astype(kblk.dtype)
        return dq + jnp.dot(ds, kblk, preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, hi, body, dq)
    dq_ref[0] = (dq * sm_scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    *, sm_scale, causal, block_q, seq_q,
):
    block_k = k_ref.shape[1]
    d = k_ref.shape[2]
    ki = pl.program_id(1)

    k = k_ref[0]
    v = v_ref[0]
    dk = jnp.zeros((block_k, d), jnp.float32)
    dv = jnp.zeros((block_k, d), jnp.float32)

    num_q = seq_q // block_q
    # causal: only q blocks at or after this kv block contribute
    lo = jax.lax.div(ki * block_k, block_q) if causal else 0

    def body(i, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(i * block_q, block_q), :]
        do = do_ref[0, pl.ds(i * block_q, block_q), :]
        lse = lse_ref[0, pl.ds(i * block_q, block_q), :]  # [Bq, 1]
        delta = delta_ref[0, pl.ds(i * block_q, block_q), :]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * sm_scale  # [Bq, Bk]
        if causal:
            qpos = i * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            kpos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(kpos <= qpos, s, NEG_INF)
        p = jnp.exp(s - lse)
        dv = dv + jnp.dot(p.T.astype(do.dtype), do, preferred_element_type=jnp.float32)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = (p * (dp - delta)).astype(q.dtype)
        dk = dk + jnp.dot(ds.T, q, preferred_element_type=jnp.float32)
        return dk, dv

    dk, dv = jax.lax.fori_loop(lo, num_q, body, (dk, dv))
    dk_ref[0] = (dk * sm_scale).astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _bwd(sm_scale, causal, block_q, block_k, res, dout):
    q, k, v, o, lse = res
    BH, Sq, D = q.shape
    Sk = k.shape[1]
    delta = jnp.sum(dout.astype(jnp.float32) * o.astype(jnp.float32), axis=-1, keepdims=True)  # [BH, Sq, 1]

    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, sm_scale=sm_scale, causal=causal, block_k=block_k, seq_k=Sk
        ),
        grid=(BH, Sq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, Sk, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, Sk, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=_interpret(),
    )(q, k, v, dout, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, sm_scale=sm_scale, causal=causal, block_q=block_q, seq_q=Sq
        ),
        grid=(BH, Sk // block_k),
        in_specs=[
            pl.BlockSpec((1, Sq, D), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, Sq, D), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, Sq, 1), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, Sq, 1), lambda b, j: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, D), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, j: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        interpret=_interpret(),
    )(q, k, v, dout, lse, delta)
    return dq, dk, dv


# ------------------------------------------------------------------ public op


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, sm_scale, causal, block_q, block_k):
    o, _ = _fwd(q, k, v, sm_scale, causal, block_q, block_k)
    return o


def _flash_fwd_rule(q, k, v, sm_scale, causal, block_q, block_k):
    o, lse = _fwd(q, k, v, sm_scale, causal, block_q, block_k)
    return o, (q, k, v, o, lse)


def _flash_bwd_rule(sm_scale, causal, block_q, block_k, res, dout):
    return _bwd(sm_scale, causal, block_q, block_k, res, dout)


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    block_q: int = 256,
    block_k: int = 512,
) -> jnp.ndarray:
    """Blockwise (flash) attention.  [B, H, S, D] layout, differentiable.

    Block sizes are clamped to the sequence lengths; S must be divisible by
    the (clamped) block sizes — pad upstream for ragged lengths.
    """
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(D)
    block_q = min(block_q, Sq)
    block_k = min(block_k, Sk)
    if Sq % block_q or Sk % block_k:
        raise ValueError(f"seq lengths ({Sq}, {Sk}) not divisible by blocks ({block_q}, {block_k})")
    qf = q.reshape(B * H, Sq, D)
    kf = k.reshape(B * H, Sk, D)
    vf = v.reshape(B * H, Sk, D)
    o = _flash(qf, kf, vf, float(sm_scale), bool(causal), int(block_q), int(block_k))
    return o.reshape(B, H, Sq, D)
