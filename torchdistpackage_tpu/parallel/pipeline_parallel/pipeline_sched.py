"""SPMD pipeline schedule — analogue of the reference's 1F1B scheduler +
p2p comm layer (``pipeline_parallel/pipeline_sched.py`` 269 LoC,
``pipeline_parallel/comm.py`` 595 LoC).

The reference drives warmup -> steady 1F1B -> cooldown from Python, moving
activations with batched NCCL isend/irecv guarded by a shape-meta handshake
(comm.py:26-105) and a defensive ``cuda.synchronize`` (comm.py:326-327).
Under XLA the whole schedule is **one compiled collective program**:

- microbatches advance through stages inside a ``lax.scan`` over
  ``M + P - 1`` ticks (fill -> steady -> drain);
- inter-stage transfer is a single ``ppermute`` per tick over the ``pipe``
  axis — shapes are static at trace time, so the reference's entire meta
  protocol and race guard vanish by construction;
- backward is JAX AD through the scan: the transpose of ``ppermute`` is the
  reverse ``ppermute``, which *is* the backward pipeline, microbatch grads
  accumulating in the scan-carry — the reference's grad-accumulate-then-
  reduce-once behavior (naive_ddp.py:108-110) falls out;
- peak memory is governed by ``jax.checkpoint`` around the stage body
  (1F1B's raison d'être — bounded live activations — achieved by remat
  rather than schedule order, which XLA controls anyway);
- the pipeline bubble is the same (P-1)/(M+P-1) as the reference's 1F1B.

Non-linear stage graphs (the reference supports CLIP-style fwd_fn/bwd_fn
pairs, Intro.md:54-66) are supported the same way: ``stage_fn`` is arbitrary
user code — it sees (stage_params, activation, per-tick aux) and can branch on
``stage_index``.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax

from ...compat import axis_size
import jax.numpy as jnp

from ...dist.topology import PIPE_AXIS
from ..tensor_parallel.layers import RematMode, checkpoint_block

PyTree = Any


def _stage_probe(stage_params, microbatches, stage_fn, pipe_axis):
    """(zero_state, want_vma): the stage activation's shape/dtype and the
    varying-axis set the scan carry must hold.

    The carry's vma is a fixed point: the tick computes
    ``shift_right(stage_fn(params, where(first, mb, state)))``, so the state
    must vary over exactly ``vma(stage_fn output) | vma(mb) | {pipe}`` — which
    itself depends on the state's vma.  Iterate ``jax.eval_shape`` (whose
    results carry vma) until stable; this handles both under-marking (output
    picks up axes from sharded params) and over-marking (output drops axes via
    an internal psum) for any TP/SP/PP composition."""
    from ..data_parallel import _mark_varying, _vma

    mb_vma = _vma(microbatches)
    want_vma = mb_vma | {pipe_axis}
    probe0 = microbatches[0]
    out_shape = None
    for _ in range(8):  # bounded by the number of mesh axes
        probe = probe0
        missing = tuple(a for a in want_vma if a not in _vma(probe))
        if missing:
            probe = _mark_varying(probe, missing)
        out_shape = jax.eval_shape(stage_fn, stage_params, probe)
        new_want = frozenset(getattr(out_shape, "vma", frozenset())) | mb_vma | {pipe_axis}
        if new_want == want_vma:
            break
        want_vma = new_want
    zero_state = jnp.zeros(out_shape.shape, out_shape.dtype)
    missing = tuple(a for a in want_vma if a not in _vma(zero_state))
    if missing:
        zero_state = _mark_varying(zero_state, missing)
    return zero_state, want_vma


def _zeros_like_shapes(shapes):
    """Zero pytree matching ShapeDtypeStructs (or values), reproducing vma."""
    from ..data_parallel import _mark_varying

    def z(a):
        from ...compat import typeof

        aval = a if isinstance(a, jax.ShapeDtypeStruct) else typeof(a)
        x = jnp.zeros(aval.shape, aval.dtype)
        vm = tuple(getattr(aval, "vma", ()))
        return _mark_varying(x, vm) if vm else x

    return jax.tree.map(
        z, shapes, is_leaf=lambda a: isinstance(a, jax.ShapeDtypeStruct)
    )


def _normalized_first_fn(first_fn, x_shape, want_vma):
    """``(first_v, first_missing)``: ``first_v`` wraps ``first_fn`` to emit
    the scan-carry vma; ``first_missing`` (static) lists the axes the
    normalization must ADD.  If it contains the pipe axis, the added pvary's
    transpose is a pipe psum — illegal inside a stage-gated cond, so callers
    then run ``first_v`` unconditionally + select instead."""
    from ..data_parallel import _mark_varying, _vma

    first_missing = tuple(
        a for a in want_vma if a not in frozenset(getattr(x_shape, "vma", frozenset()))
    )

    def first_v(p, mb):
        o = first_fn(p, mb)
        miss = tuple(a for a in want_vma if a not in _vma(o))
        return _mark_varying(o, miss) if miss else o

    return first_v, first_missing


def stage_index(pipe_axis: str = PIPE_AXIS):
    return jax.lax.axis_index(pipe_axis)


def is_first_stage(pipe_axis: str = PIPE_AXIS):
    return jax.lax.axis_index(pipe_axis) == 0


def is_last_stage(pipe_axis: str = PIPE_AXIS):
    return jax.lax.axis_index(pipe_axis) == axis_size(pipe_axis) - 1


def last_stage_value(x, pipe_axis: str = PIPE_AXIS):
    """Cheaply broadcast a (small) per-stage value from the last stage to all
    stages: mask + psum.  The scalar analogue of the reference's loss returned
    by the final stage."""
    return jax.lax.psum(jnp.where(is_last_stage(pipe_axis), x, jnp.zeros_like(x)), pipe_axis)


def shift_right(x, pipe_axis: str = PIPE_AXIS, circular: bool = False):
    """Send to the next stage: stage s's value arrives at s+1.  Non-circular
    (default): stage 0 receives zeros — the ppermute analogue of
    send_forward/recv_forward (comm.py:362-435).  ``circular``: stage 0
    receives stage P-1's value — the wrap edge of the interleaved (virtual
    chunk) schedule, carrying a finished chunk's activation back to stage 0
    as the next chunk's input."""
    n = axis_size(pipe_axis)
    last_edge = [(n - 1, 0)] if circular else []
    return jax.lax.ppermute(
        x, pipe_axis, [(i, i + 1) for i in range(n - 1)] + last_edge
    )


def shift_left(x, pipe_axis: str = PIPE_AXIS, circular: bool = False):
    """Send to the previous stage: stage s's value arrives at s-1.  The
    cotangent channel of the 1F1B schedule — analogue of
    send_backward/recv_backward (comm.py:362-435).  ``circular``: stage P-1
    receives stage 0's value (the wrap cotangent from chunk v+1 back to
    chunk v under the interleaved schedule)."""
    n = axis_size(pipe_axis)
    wrap_edge = [(0, n - 1)] if circular else []
    return jax.lax.ppermute(
        x, pipe_axis, [(i, i - 1) for i in range(1, n)] + wrap_edge
    )


def _transfer_dim(shape, n: int) -> int:
    """The dim sliced by sharded inter-stage transfers: first one divisible
    by the axis size (batch/seq dims come first, leaving the minor-most lane
    dim intact when possible); -1 = leaf transfers unsliced."""
    for d, s in enumerate(shape):
        if s % n == 0 and s >= n:
            return d
    return -1


def _slice_state(x, tdims, axis: str):
    """Each ``axis`` rank keeps its 1/n slice of every leaf's transfer dim."""
    i = jax.lax.axis_index(axis)
    n = axis_size(axis)

    def one(a, d):
        if d < 0:
            return a
        sz = a.shape[d] // n
        return jax.lax.dynamic_slice_in_dim(a, i * sz, sz, axis=d)

    return jax.tree.map(one, x, tdims)


def _gather_state(x, tdims, axis: str):
    """Reassemble the full state from the per-rank slices (transpose:
    psum_scatter — AD keeps replicated-param grads exact through this)."""

    def one(a, d):
        if d < 0:
            return a
        return jax.lax.all_gather(a, axis, axis=d, tiled=True)

    return jax.tree.map(one, x, tdims)


def _pipeline_scan(
    stage_params: PyTree,
    microbatches: jnp.ndarray,
    stage_fn: Callable[[PyTree, jnp.ndarray], jnp.ndarray],
    num_microbatches: int,
    pipe_axis: str,
    remat: RematMode,
    make_acc: Callable,
    consume: Callable,
    first_fn: Callable = None,
    params: PyTree = None,
):
    """Shared fill -> steady -> drain scan driver for the pipelined schedules.

    Each tick: stage 0 consumes microbatch ``min(t, M-1)`` (clamped in the
    drain phase — those results never reach a consumer), other stages consume
    what ``shift_right`` delivered; the stage output is both shifted onward
    and handed to ``consume``.

    - ``make_acc(zero_state, want_vma) -> acc0`` builds the scan's accumulator
      (output buffer / loss sum / None).
    - ``consume(acc, y, m_idx, steady) -> acc`` folds in the stage output for
      completed microbatch ``m_idx``; ``steady`` is the traced ``t >= P-1``
      validity predicate.
    - ``first_fn(params, mb) -> x`` (optional): stage-0 preprocessing (e.g.
      token embedding) applied PER TICK inside the scan, so raw microbatch
      inputs — not M pre-embedded activations — are what stays resident.
      ``params`` is pipe-pvaried here so the embed cond-gates to stage 0 only
      (its grad psum over pipe sits at the pvary transpose, outside the scan).
      ``microbatches`` is then the raw-input pytree ``[M, ...]``.
    """
    from ..data_parallel import pvary_params

    M = num_microbatches
    P_ = axis_size(pipe_axis)
    ticks = M + P_ - 1
    first = is_first_stage(pipe_axis)
    # prevent_cse=False: body_fn executes inside the tick lax.scan below,
    # whose loop structure already blocks CSE (same rationale as scan_blocks)
    body_fn = checkpoint_block(stage_fn, remat, prevent_cse=False)

    if first_fn is None:
        zero_state, want_vma = _stage_probe(
            stage_params, microbatches, stage_fn, pipe_axis
        )
        first_v, first_missing = None, ()
    else:
        # pipe-pvary so first_fn's output is pipe-varying -> stage-gated cond
        # below is legal AND only stage 0 pays the embed FLOPs
        params = pvary_params(params, (pipe_axis,))
        mb0 = jax.tree.map(
            lambda a: jax.lax.dynamic_index_in_dim(a, 0, axis=0, keepdims=False),
            microbatches,
        )
        x_shape = jax.eval_shape(first_fn, params, mb0)
        zero_state, want_vma = _stage_probe(
            stage_params, _zeros_like_shapes(x_shape)[None], stage_fn, pipe_axis
        )
        first_v, first_missing = _normalized_first_fn(first_fn, x_shape, want_vma)

    acc0 = make_acc(zero_state, want_vma)

    def tick(carry, t):
        state, acc = carry
        mb = jax.tree.map(
            lambda a: jax.lax.dynamic_index_in_dim(
                a, jnp.minimum(t, M - 1), axis=0, keepdims=False
            ),
            microbatches,
        )
        if first_fn is None:
            x = jnp.where(first, mb, state)
        elif pipe_axis not in first_missing:
            x = jax.lax.cond(
                first, lambda op: first_v(params, op[0]), lambda op: op[1], (mb, state)
            )
        else:
            x = jnp.where(first, first_v(params, mb), state)
        y = body_fn(stage_params, x)
        nxt = shift_right(y, pipe_axis)
        m_idx = jnp.maximum(t - (P_ - 1), 0)
        acc = consume(acc, y, m_idx, t >= P_ - 1)
        return (nxt, acc), None

    (_, acc), _ = jax.lax.scan(tick, (zero_state, acc0), jnp.arange(ticks))
    return acc


def pipeline_forward(
    stage_params: PyTree,
    microbatches: jnp.ndarray,
    stage_fn: Callable[[PyTree, jnp.ndarray], jnp.ndarray],
    num_microbatches: int,
    pipe_axis: str = PIPE_AXIS,
    remat: RematMode = True,
    collect_outputs: bool = True,
    first_fn: Callable = None,
    params: PyTree = None,
):
    """Run the pipelined forward inside shard_map.

    - ``stage_params``: this stage's local params (e.g. its slab of stacked
      layers, ``[L_local, ...]`` leaves).
    - ``microbatches``: ``[M, mbs, ...]`` local microbatch inputs (only read
      on stage 0; pass the same array everywhere).
    - ``stage_fn(stage_params, x) -> y``: one stage's compute; activations
      must keep shape/dtype across stages (classic linear pipeline).

    Returns ``outputs`` of shape ``[M, mbs, ...]`` — valid on the **last**
    stage (garbage elsewhere; combine with :func:`last_stage_value` or mask).
    When ``collect_outputs=False`` returns None (use the scanning loss variant
    in :func:`pipeline_loss` instead to avoid materializing outputs).
    """
    from ..data_parallel import _mark_varying, _vma

    M = num_microbatches

    def make_acc(zero_state, want_vma):
        if not collect_outputs:
            return None
        outputs = jnp.zeros((M,) + zero_state.shape, zero_state.dtype)
        missing = tuple(a for a in want_vma if a not in _vma(outputs))
        return _mark_varying(outputs, missing) if missing else outputs

    def consume(outputs, y, m_idx, steady):
        if outputs is None:
            return None
        return jax.lax.cond(
            steady,
            lambda o: jax.lax.dynamic_update_index_in_dim(o, y, m_idx, axis=0),
            lambda o: o,
            outputs,
        )

    return _pipeline_scan(
        stage_params, microbatches, stage_fn, M, pipe_axis, remat, make_acc, consume,
        first_fn=first_fn, params=params,
    )


def pipeline_loss(
    stage_params: PyTree,
    microbatches: jnp.ndarray,
    targets: jnp.ndarray,
    stage_fn: Callable[[PyTree, jnp.ndarray], jnp.ndarray],
    loss_fn: Callable[[jnp.ndarray, jnp.ndarray], jnp.ndarray],
    num_microbatches: int,
    pipe_axis: str = PIPE_AXIS,
    remat: RematMode = True,
    first_fn: Callable = None,
    params: PyTree = None,
) -> jnp.ndarray:
    """Pipelined forward + per-microbatch loss on the last stage, without
    materializing the output buffer.  Returns the mean loss, valid on every
    stage (masked psum broadcast).

    ``targets``: ``[M, mbs, ...]`` — read on the last stage only.
    ``loss_fn(y, target) -> scalar`` (mean over the microbatch).
    ``first_fn(params, mb) -> x`` (optional): per-tick stage-0 preprocessing;
    ``microbatches`` is then the raw input pytree (see ``_pipeline_scan``).
    """
    from ..data_parallel import _mark_varying, _vma

    M = num_microbatches
    last = is_last_stage(pipe_axis)

    def make_acc(zero_state, want_vma):
        loss0 = jnp.zeros(())
        missing = tuple(a for a in (want_vma | _vma(targets)) if a not in _vma(loss0))
        return _mark_varying(loss0, missing) if missing else loss0

    def consume(loss_sum, y, m_idx, steady):
        tgt = jax.lax.dynamic_index_in_dim(targets, m_idx, axis=0, keepdims=False)
        mb_loss = loss_fn(y, tgt)
        valid = jnp.logical_and(last, steady)
        return loss_sum + jnp.where(valid, mb_loss, 0.0)

    loss_sum = _pipeline_scan(
        stage_params, microbatches, stage_fn, M, pipe_axis, remat, make_acc, consume,
        first_fn=first_fn, params=params,
    )
    # broadcast from the last stage; grads flow back through the mask
    return jax.lax.psum(loss_sum, pipe_axis) / M


# --------------------------------------------------------------------- 1F1B


def ring_slots(num_microbatches: int, pipe_size: int, num_chunks: int = 1) -> int:
    """Stage-input slots the 1F1B schedule keeps live:
    ``min(V*M, 2*P*V - 1)`` (``V = num_chunks``; classic ``min(M, 2P-1)`` at
    V=1).

    This is the schedule's memory guarantee — peak in-flight activations are
    bounded by the pipeline depth (x the chunk count under interleaving),
    NOT the microbatch count (the property the reference's steady-state 1F1B
    interleave exists for, pipeline_parallel/pipeline_sched.py:163-211).
    Derivation: unit k's slot may be overwritten only after unit ``k - R``'s
    backward, and ``t_f(k) - t_b(k-R)`` >= 0 for every (stage, chunk) iff
    ``R >= (P-1-2s) + (V-1-2v)P + PV``, maximized at s=0, v=0 as
    ``2PV - 1``."""
    return min(
        num_microbatches * num_chunks, 2 * pipe_size * num_chunks - 1
    )


def pipeline_1f1b(
    params: PyTree,
    inputs: PyTree,
    targets: PyTree,
    first_fn: Callable[[PyTree, PyTree], jnp.ndarray],
    stage_fn: Callable[[PyTree, jnp.ndarray], jnp.ndarray],
    last_fn: Callable[[PyTree, jnp.ndarray, PyTree], jnp.ndarray],
    num_microbatches: int,
    pipe_axis: str = PIPE_AXIS,
    stage_takes_mb: bool = False,
    stage_returns_aux: bool = False,
    num_chunks: int = 1,
    transfer_shard_axis: Optional[str] = None,
):
    """One-forward-one-backward pipeline schedule: returns ``(loss, grads)``
    directly (do NOT wrap in ``jax.grad`` — the backward pipeline runs inside).

    The match for the reference's steady-state 1F1B interleave
    (pipeline_parallel/pipeline_sched.py:163-211), rebuilt for SPMD/XLA: one
    ``lax.scan`` over ``M + 2P - 2`` ticks where **every tick carries one
    forward and one backward unit of work** —

    - fwd: stage ``s`` runs microbatch ``m_f = t - s`` (fill wavefront), stage
      0 sourcing it from ``first_fn(params, inputs[m_f])`` (embed), others
      from the activation ``ppermute``-d in last tick; the stage INPUT is
      saved in a ring buffer of :func:`ring_slots` slots.
    - bwd: stage ``s`` runs microbatch ``m_b = t - 2(P-1) + s``: recompute the
      stage from its saved input under ``jax.vjp`` (the remat), pull the
      output cotangent from the next stage's ``shift_left`` (or, on the last
      stage, from the vjp of ``last_fn``'s per-microbatch loss), accumulate
      param grads, and send the input cotangent upstream.

    Peak live activations are O(P) — independent of M — versus O(M) for AD
    through :func:`pipeline_loss`'s forward scan (which must keep every tick's
    carry for the reverse pass).  Total FLOPs are the same as remat-AD: fwd +
    recompute + bwd per microbatch.

    ``first_fn``/``last_fn`` take the FULL ``params`` pytree, so embedding and
    head weights get their gradients here too (on their owning stage, then
    psum-ed over ``pipe`` for every param leaf that is replicated across
    stages — the explicit form of shard_map's transpose).

    ``inputs``/``targets``: pytrees with leading dim ``M`` (raw microbatches;
    read on the first / last stage respectively).  ``last_fn(params, y, tgt)``
    returns the microbatch's mean loss.  Returns the mean loss over all M
    (identical on every stage) and a grads pytree matching ``params``.

    ``stage_returns_aux``: ``stage_fn`` returns ``(y, aux)`` where ``aux`` is
    a scalar **auxiliary loss term produced mid-pipeline** (e.g. the MoE
    load-balance loss, which arises on every stage that holds expert blocks
    — it cannot be computed in ``last_fn``, which only sees the final
    activation).  The schedule adds each microbatch's aux to the loss once
    (forward unit, masked to real microbatches) and backpropagates it with a
    unit cotangent through the stage's vjp (backward unit) — so aux
    gradients flow into the stage's params AND upstream through ``dx``
    exactly as if ``total = last_fn_loss + sum_stages aux`` had been
    differentiated as one expression.  ``aux`` must already carry whatever
    weight the caller wants (the returned loss is ``mean_m [CE_m +
    sum_stages aux_{s,m}]``).

    ``num_chunks`` (V > 1): the **interleaved schedule** (virtual pipeline
    stages, the Megatron-style bubble reduction): each physical stage holds
    V model chunks — chunk v of stage s is global layer-slab ``v*P + s``
    (round-robin) — and ``stage_fn(params, x, m, v)`` additionally receives
    the chunk index to select its slab.  Forward unit order per stage is
    ``sigma(v, m) = (m // P)*P*V + v*P + (m % P)`` (groups of P microbatches
    sweep all chunks before the next group — requires ``M % P == 0``, as
    Megatron's interleaved schedule does); the backward mirrors it with the
    chunk order reversed.  Inter-stage transfer becomes a CIRCULAR ppermute:
    the P-1 -> 0 wrap edge carries a finished chunk's activation back as the
    next chunk's input (and stage 0's cotangent back to stage P-1), and the
    schedule arithmetic guarantees each wrap payload arrives exactly one
    tick before its consumer.  Total ticks ``VM + PV + P - 2`` of 1/V-sized
    units vs ``V(M + 2P - 2)`` chunk-equivalents non-interleaved — the
    fill/drain bubble shrinks whenever ``P + 2V - 2 < PV`` (any P >= 3); the
    price is the deeper ring buffer, ``min(VM, 2PV-1)`` slots of 1 chunk's
    activation each (:func:`ring_slots`).  At V=1 every formula reduces to
    the classic schedule above.

    ``transfer_shard_axis``: shard the inter-stage state over this (tensor)
    axis — the analogue of the reference's ``scatter_gather_tensors``
    (pipeline_parallel/comm.py:108-155), which splits the p2p payload 1/tp
    before send and all-gathers after receive.  Here the state stays SLICED
    through the whole schedule (each TP rank carries slice ``i`` of the
    first divisible dim): stage entry all-gathers over the axis, stage exit
    slices — both INSIDE the differentiated stage fn, so AD's
    all_gather <-> psum_scatter transposition keeps every gradient exact
    (the Megatron SP conjugate pair).  The pipe ``ppermute`` payload AND the
    activation ring buffer shrink by 1/tp (beyond the reference, which only
    shards the wire bytes).  Pointless under SP, where the state is already
    sequence-sharded — meant for the non-SP TP pipeline.
    """
    from ..data_parallel import _mark_varying, _vma, pvary_params

    M = num_microbatches
    V = num_chunks
    P_ = axis_size(pipe_axis)
    if V < 1:
        raise ValueError(f"num_chunks must be >= 1, got {V}")
    if V > 1 and M % P_ != 0:
        raise ValueError(
            f"the interleaved schedule requires num_microbatches ({M}) "
            f"divisible by pipe size ({P_}): the last microbatch group would "
            f"otherwise break the sigma(v, m) dependency spacing"
        )
    R = ring_slots(M, P_, V)
    T = V * M + P_ * V + P_ - 2  # == M + 2(P-1) at V=1
    s = jax.lax.axis_index(pipe_axis)
    first = is_first_stage(pipe_axis)
    last = is_last_stage(pipe_axis)
    circular = V > 1

    # Mark params pipe-varying so every vjp below yields LOCAL per-stage
    # grads (no implicit psum inside the scan's conds, where a pipe
    # collective would be illegal); the single explicit psum for
    # pipe-replicated leaves happens once at the end (see ``sync``).
    orig_params = params
    params = pvary_params(params, (pipe_axis,))

    # ``stage_takes_mb``: stage_fn(params, x, m) also receives the microbatch
    # index m (int32, < M) — for per-microbatch stage behavior such as
    # dropout keys.  The bwd recompute replays the same m, so key-derived
    # masks are identical between forward and recompute.  With V > 1 the
    # stage fn must take (p, x, m, v) — v selects the chunk's param slab.
    if V > 1:
        # fail the CONTRACT loudly: a stage_fn(p, x) or (p, x, m) would
        # otherwise surface as an opaque arity TypeError from inside tracing
        # when the scheduler calls it with four arguments
        try:
            import inspect

            sig_params = inspect.signature(stage_fn).parameters.values()
        except (TypeError, ValueError):
            sig_params = None  # unintrospectable callable: let it through
        if sig_params is not None and not any(
            p.kind is inspect.Parameter.VAR_POSITIONAL for p in sig_params
        ):
            n_pos = sum(
                p.kind in (inspect.Parameter.POSITIONAL_ONLY,
                           inspect.Parameter.POSITIONAL_OR_KEYWORD)
                for p in sig_params
            )
            if n_pos < 4:
                raise ValueError(
                    f"num_chunks > 1 (interleaved schedule) requires a "
                    f"stage_fn with signature (params, x, microbatch_idx, "
                    f"chunk_idx); got a callable taking {n_pos} positional "
                    f"args. The scheduler passes m to replay per-microbatch "
                    f"behavior in the backward recompute and v to select "
                    f"the chunk's param slab."
                )
        call_stage = stage_fn  # (p, x, m, v)
    elif stage_takes_mb:
        call_stage = lambda p, x, m, v: stage_fn(p, x, m)
    else:
        call_stage = lambda p, x, m, v: stage_fn(p, x)

    take_mb = lambda tree, i: jax.tree.map(
        lambda a: jax.lax.dynamic_index_in_dim(a, i, axis=0, keepdims=False), tree
    )
    mb0_in = take_mb(inputs, jnp.zeros((), jnp.int32))
    mb0_tgt = take_mb(targets, jnp.zeros((), jnp.int32))

    if transfer_shard_axis is not None:
        # Sharded inter-stage state (see docstring): slice at every stage
        # exit, gather at every entry — INSIDE the differentiated fns, so
        # the schedule below (carry, ring buffer, ppermutes, cotangents)
        # only ever sees 1/tp-sized state and AD stays exact.
        tax = transfer_shard_axis
        tsz = axis_size(tax)
        full_state = jax.eval_shape(first_fn, params, mb0_in)
        tdims = jax.tree.map(lambda a: _transfer_dim(a.shape, tsz), full_state)
        _first0, _stage0, _last0 = first_fn, call_stage, last_fn

        def _close_scalar(v):
            # A scalar that ESCAPES the slice/gather conjugate pair (aux
            # losses, a last_fn that doesn't psum over tax internally) is
            # computed from gathered — tax-varying-TYPED but value-equal —
            # state.  Left varying, its vjp transpose-psums a FULL
            # per-rank grad contribution tp times (overcount), while the
            # sliced-state path's grads are exact shares — no global
            # rescale can fix both.  pmean is exact on the equal values,
            # restores invariance, and seeds each rank with the correct
            # 1/tp cotangent so the transpose-psum sums to exactly 1x.
            return jax.lax.pmean(v, tax) if tax in _vma(v) else v

        def first_fn(p, mb):
            return _slice_state(_first0(p, mb), tdims, tax)

        def call_stage(p, x, m, v):
            out = _stage0(p, _gather_state(x, tdims, tax), m, v)
            if stage_returns_aux:
                y, aux = out
                return _slice_state(y, tdims, tax), _close_scalar(aux)
            return _slice_state(out, tdims, tax)

        def last_fn(p, y, tgt):
            return _close_scalar(_last0(p, _gather_state(y, tdims, tax), tgt))

    # ---- state aval fixed point (stage in/out shape + varying axes)
    x_shape = jax.eval_shape(first_fn, params, mb0_in)
    want_vma = frozenset(getattr(x_shape, "vma", frozenset())) | {pipe_axis}
    zero_state = None
    aux_shape = None
    for _ in range(8):  # bounded by the number of mesh axes
        zero_state = _zeros_like_shapes(x_shape)
        missing = tuple(a for a in want_vma if a not in _vma(zero_state))
        if missing:
            zero_state = _mark_varying(zero_state, missing)
        out_shape = jax.eval_shape(
            call_stage, params, zero_state,
            jnp.zeros((), jnp.int32), jnp.zeros((), jnp.int32),
        )
        y_shape, aux_shape = out_shape if stage_returns_aux else (out_shape, None)
        new_want = frozenset(getattr(y_shape, "vma", frozenset())) | want_vma
        if new_want == want_vma:
            break
        want_vma = new_want
    if y_shape.shape != x_shape.shape or y_shape.dtype != x_shape.dtype:
        raise ValueError(
            f"stage_fn must preserve activation shape/dtype for pipelining: "
            f"{x_shape.shape}/{x_shape.dtype} -> {y_shape.shape}/{y_shape.dtype}"
        )

    # first_v normalizes first_fn's output vma; if that adds a PIPE marking
    # (degenerate first_fn that ignores params, e.g. identity), its vjp
    # contains a pipe psum and must run unconditionally each tick rather than
    # inside the stage-gated cond.  Static, trace-time choice.
    first_v, _first_missing = _normalized_first_fn(first_fn, x_shape, want_vma)
    first_vjp_in_cond = pipe_axis not in _first_missing

    # ---- one backward unit of work (runs under lax.cond when bwd is active)
    def run_bwd(opers):
        x_saved, cot_in, mb_tgt, mb_in, m_b, v_b = opers
        if stage_returns_aux:
            (y_, aux_), vjp_stage = jax.vjp(
                lambda p, xx: call_stage(p, xx, m_b, v_b), params, x_saved
            )
        else:
            y_, vjp_stage = jax.vjp(
                lambda p, xx: call_stage(p, xx, m_b, v_b), params, x_saved
            )

        def last_branch(op):
            y_, mb_tgt, _ = op
            loss_m, vjp_last = jax.vjp(
                lambda p, yy: last_fn(p, yy, mb_tgt), params, y_
            )
            one = jnp.ones(jnp.shape(loss_m), jnp.result_type(loss_m))
            miss = tuple(a for a in _vma(loss_m) if a not in _vma(one))
            dp_last, g = vjp_last(_mark_varying(one, miss) if miss else one)
            return loss_m, dp_last, g

        last_shapes = jax.eval_shape(last_branch, (y_, mb_tgt, cot_in))

        def mid_branch(op):
            _, _, cot_in = op
            zl, zp, _ = _zeros_like_shapes(last_shapes)
            return zl, zp, cot_in

        # the loss seed lives on the LAST chunk of the last stage (chunk
        # V-1 is the model's tail under the round-robin slab assignment)
        loss_m, dp_last, g = jax.lax.cond(
            jnp.logical_and(last, v_b == V - 1),
            last_branch, mid_branch, (y_, mb_tgt, cot_in)
        )

        if stage_returns_aux:
            # unit cotangent on the stage's aux loss term: total loss holds
            # +aux per (stage, microbatch), so d total / d aux = 1 (the
            # schedule's b_active mask zeroes fill/drain ticks afterwards)
            one_aux = jnp.ones(jnp.shape(aux_), jnp.result_type(aux_))
            miss = tuple(a for a in _vma(aux_) if a not in _vma(one_aux))
            dp_stage, dx = vjp_stage(
                (g, _mark_varying(one_aux, miss) if miss else one_aux)
            )
        else:
            dp_stage, dx = vjp_stage(g)

        if first_vjp_in_cond:
            def first_branch(op):
                mb_in, dx = op
                _, vjp_first = jax.vjp(lambda p: first_v(p, mb_in), params)
                (dp_first,) = vjp_first(dx)
                return dp_first

            first_shapes = jax.eval_shape(first_branch, (mb_in, dx))
            # the embed's vjp belongs to stage 0's CHUNK-0 units only (the
            # model's head-end slab); wrap units (v > 0) pass dx upstream
            dp_first = jax.lax.cond(
                jnp.logical_and(first, v_b == 0),
                first_branch,
                lambda op: _zeros_like_shapes(first_shapes),
                (mb_in, dx),
            )
            dp = jax.tree.map(lambda a, b, c: a + b + c, dp_stage, dp_last, dp_first)
        else:
            dp = jax.tree.map(lambda a, b: a + b, dp_stage, dp_last)
        return loss_m, dp, dx

    # ---- carry init (zeros with the right vma, via abstract eval; legacy
    # jax's ShapeDtypeStruct has no vma kwarg and nothing to carry anyway)
    _zvma = _vma(zero_state)

    def _stacked_struct(a):
        if _zvma:
            return jax.ShapeDtypeStruct((R,) + a.shape, a.dtype, vma=_zvma)
        return jax.ShapeDtypeStruct((R,) + a.shape, a.dtype)

    saved0 = _zeros_like_shapes(
        jax.tree.map(_stacked_struct, jax.eval_shape(lambda z: z, zero_state))
    )
    cot0 = zero_state
    bwd_shapes = jax.eval_shape(
        run_bwd,
        (zero_state, cot0, mb0_tgt, mb0_in,
         jnp.zeros((), jnp.int32), jnp.zeros((), jnp.int32)),
    )
    # the loss accumulator inherits the TRUE loss aval's varying axes (e.g. a
    # vocab-parallel CE has already psum-ed over 'tensor', so the loss must
    # NOT be marked tensor-varying — downstream model-axis normalization keys
    # on the loss vma)
    loss0, grads0, _ = _zeros_like_shapes(bwd_shapes)
    if stage_returns_aux:
        # the fwd units also add per-stage aux terms into the accumulator
        aux_vma = frozenset(getattr(aux_shape, "vma", frozenset()))
        miss = tuple(a for a in aux_vma if a not in _vma(loss0))
        if miss:
            loss0 = _mark_varying(loss0, miss)

    def tick(carry, t):
        state, cot_state, saved_x, grads_acc, loss_sum = carry

        # -------- forward unit: stage s runs its k-th fwd unit at tick s+k,
        # with (chunk, microbatch) = sigma^-1(k); V=1 degenerates to the
        # classic wavefront m_f = t - s
        k_f = t - s
        f_active = (k_f >= 0) & (k_f < V * M)
        k_f_c = jnp.clip(k_f, 0, V * M - 1)
        r_f = jnp.remainder(k_f_c, P_ * V)
        v_f = r_f // P_
        m_f_c = (k_f_c // (P_ * V)) * P_ + jnp.remainder(r_f, P_)
        mb_in = take_mb(inputs, m_f_c)
        x = jax.lax.cond(
            jnp.logical_and(first, v_f == 0),
            lambda op: first_v(params, op[0]), lambda op: op[1], (mb_in, state)
        )
        if stage_returns_aux:
            y, aux_f = call_stage(params, x, m_f_c, v_f)
        else:
            y, aux_f = call_stage(params, x, m_f_c, v_f), None
        slot_f = jnp.remainder(k_f_c, R)
        saved_x = jax.lax.cond(
            f_active,
            lambda b: jax.lax.dynamic_update_index_in_dim(b, x, slot_f, axis=0),
            lambda b: b,
            saved_x,
        )

        # -------- backward unit: mirrored order (chunks reversed), delayed
        # by the first microbatch's full-model forward (PV - 1 ticks)
        k_b = t - (P_ - 1 - s) - (P_ * V - 1)
        b_active = (k_b >= 0) & (k_b < V * M)
        k_b_c = jnp.clip(k_b, 0, V * M - 1)
        r_b = jnp.remainder(k_b_c, P_ * V)
        v_b = (V - 1) - r_b // P_
        m_b_c = (k_b_c // (P_ * V)) * P_ + jnp.remainder(r_b, P_)
        # the unit's own fwd counter locates its ring-buffer slot
        k_unit = (k_b_c // (P_ * V)) * (P_ * V) + v_b * P_ + jnp.remainder(r_b, P_)
        x_saved = jax.lax.dynamic_index_in_dim(
            saved_x, jnp.remainder(k_unit, R), axis=0, keepdims=False
        )
        mb_in_b = take_mb(inputs, m_b_c)
        opers = (x_saved, cot_state, take_mb(targets, m_b_c), mb_in_b, m_b_c, v_b)
        # Run the bwd unit UNCONDITIONALLY and mask the accumulation, the
        # same uniform-body rule the forward follows (line `y = stage_fn`
        # above): ``b_active`` is pipe-varying, and a collective inside a
        # branch-divergent cond is undefined — XLA's collective-permute in
        # particular is a FULL-mesh rendezvous, so a ring-attention stage
        # (ppermute over 'context') inside ``cond(b_active, ...)`` deadlocks
        # or silently corrupts.  The extra recompute+bwd FLOPs are paid only
        # on the PV+P-2 fill/drain ticks (2(P-1) at V=1) where b_active is
        # false anyway.
        loss_m, dp, dx = run_bwd(opers)
        mask_b = lambda g: jnp.where(b_active, g, jnp.zeros((), g.dtype))
        loss_m = mask_b(loss_m)
        dp = jax.tree.map(mask_b, dp)
        dx = jax.tree.map(mask_b, dx)

        if not first_vjp_in_cond:
            # degenerate first_fn (ignores params): its vjp contains a pipe
            # psum (transpose of the vma normalization), so it must run
            # unconditionally.  Mask the cotangent to stage 0's bwd window
            # before, and the (pipe-replicated) grad after, so the final sync
            # psum yields exactly stage 0's contribution.
            gate = jnp.logical_and(jnp.logical_and(first, v_b == 0), b_active)
            dxm = jax.tree.map(
                lambda a: jnp.where(gate, a, jnp.zeros((), a.dtype)), dx
            )
            _, vjp_first = jax.vjp(lambda p: first_v(p, mb_in_b), params)
            (dp_first,) = vjp_first(dxm)
            dp_first = jax.tree.map(
                lambda g: g * gate.astype(jnp.result_type(g)), dp_first
            )
            dp = jax.tree.map(jnp.add, dp, dp_first)

        grads_acc = jax.tree.map(jnp.add, grads_acc, dp)
        loss_sum = loss_sum + loss_m
        if aux_f is not None:
            # each real microbatch's per-stage aux counts once, at its fwd
            # unit (the bwd recompute only carries its gradient)
            loss_sum = loss_sum + jnp.where(
                f_active, aux_f.astype(loss_sum.dtype), jnp.zeros((), loss_sum.dtype)
            )
        return (
            shift_right(y, pipe_axis, circular=circular),
            shift_left(dx, pipe_axis, circular=circular),
            saved_x, grads_acc, loss_sum,
        ), None

    (_, _, _, grads, loss_sum), _ = jax.lax.scan(
        tick, (zero_state, cot0, saved0, grads0, loss0), jnp.arange(T)
    )

    # mean over microbatches; broadcast the last stage's loss everywhere
    loss = jax.lax.psum(loss_sum, pipe_axis) / M
    inv = 1.0 / M

    # replicated-across-stages params (embed/head, anything not pipe-sharded)
    # get contributions from their owning stage only — make every stage agree,
    # the explicit form of shard_map's transpose-psum.
    def sync(g, p):
        g = g * inv if not isinstance(g, jax.ShapeDtypeStruct) else g
        if pipe_axis in _vma(p):
            return g
        if pipe_axis in _vma(g):
            return jax.lax.psum(g, pipe_axis)
        return g

    grads = jax.tree.map(lambda g, p: sync(g, p), grads, orig_params)
    return loss, grads
