"""KV-cache autoregressive generation for the dense GPT/Llama families.

The reference is a training toolkit — it has no inference path at all.  A
complete framework needs one, and decode is where TPU-first design choices
differ most from training:

- **Static shapes end-to-end**: the KV cache is a fixed ``[L, B, Hkv,
  max_len, hd]`` buffer written with ``dynamic_update_slice``; attention
  always scores against the full buffer with a position mask (`key_pos <=
  query_pos`).  No growing tensors, so the whole decode loop is ONE
  ``lax.scan`` inside ONE jit — no per-token retrace, no host round-trips.
- **One cached-block implementation serves prefill AND decode**: prefill is
  the S_in=P case (offset 0), decode the S_in=1 case (offset t) of the same
  function — the reference-style "two code paths that drift" problem cannot
  exist.
- **TP composes exactly like training**: the same param specs shard q/kv
  heads and the vocab-parallel head; the per-shard last-position logits are
  psum-assembled into full [B, V] rows (tiny at S_in=1), sampling is
  replicated-deterministic across shards, and GQA serves grouped KV heads
  without materializing repeats.
- RoPE rotates at the true global positions (``offset + arange(S_in)``),
  traced, so the rotation is correct at every decode step inside the scan.

All families decode: dense GPT, ``llama_config`` models
(RMSNorm/SwiGLU/RoPE/GQA), and the MoE family — whose inference dispatch
is the NO-DROP limit of the training router (:func:`forward_cached_moe`:
capacity raised to >= E/top_k, so token t's routing never depends on what
other tokens routed — the property that makes incremental decode equal
the full forward).
"""

from __future__ import annotations

import functools
import math
from typing import Any, Dict, Optional, Tuple

import jax

from ..compat import axis_size
import jax.numpy as jnp

from ..parallel.tensor_parallel.layers import (
    TransformerConfig,
    _close_row_parallel,
    compute_qkv,
    dense,
    layer_norm,
    mlp_partial,
    rope_cache,
)
from .gpt import GPTConfig, gpt_head, vocab_parallel_embed

PyTree = Any


def init_kv_cache(
    cfg: GPTConfig, batch: int, max_len: int, axis_size: int = 1,
    quantized: bool = False,
) -> Dict[str, Any]:
    """Zeroed cache ``{'k','v': [L, B, Hkv_local, max_len, hd]}`` in
    ``cfg.dtype``.  ``axis_size`` divides the KV heads for TP (call inside
    shard_map with ``axis_size(axis)``, or build the global
    [L, B, Hkv, ...] array outside and shard dim 2 over the tensor axis).

    ``quantized=True``: int8 KV storage — each 'k'/'v' entry becomes a
    ``(q8, scale)`` pair (scale [L, B, Hkv, max_len] f32, one symmetric
    scale per written position-vector, computed at append time).  Decode
    reads the cache once per token, so at long context the KV bytes — not
    the weights — bound throughput (docs/BENCH_AB.md 6b); int8 halves
    them vs bf16.  Dequant happens in-register inside the attention
    einsums (:func:`_cached_attention` folds the k-scale into the score
    and the v-scale into the probabilities).  The pair is a pytree, so
    the decode scan slices/stacks it like any dense cache leaf."""
    hkv, rem = divmod(cfg.block.kv_head_count, axis_size)
    if rem or hkv == 0:
        raise ValueError(
            f"kv_heads {cfg.block.kv_head_count} not divisible by tp "
            f"{axis_size} (whole KV heads per shard)"
        )
    shape = (cfg.nlayers, batch, hkv, max_len, cfg.block.head_dim)
    if quantized:
        def entry():
            return (jnp.zeros(shape, jnp.int8),
                    jnp.ones(shape[:-1], jnp.float32))
        return {"k": entry(), "v": entry()}
    return {"k": jnp.zeros(shape, cfg.dtype), "v": jnp.zeros(shape, cfg.dtype)}


def _kv_quant(x: jnp.ndarray):
    """[..., hd] -> (int8 [..., hd], scale [...]) — symmetric per-vector."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    scale = jnp.maximum(amax, 1e-30) / 127.0
    q = jnp.clip(
        jnp.round(x.astype(jnp.float32) / scale[..., None]), -127, 127
    ).astype(jnp.int8)
    return q, scale


def _cache_write(c, val: jnp.ndarray, offset):
    """Append ``val`` [B, Hkv, S_in, hd] at ``offset`` — dense array or
    quantized (q8, scale) pair, one code path for both."""
    if isinstance(c, tuple):
        q8, scale = c
        vq, vs = _kv_quant(val)
        return (
            jax.lax.dynamic_update_slice(q8, vq, (0, 0, offset, 0)),
            jax.lax.dynamic_update_slice(scale, vs, (0, 0, offset)),
        )
    return jax.lax.dynamic_update_slice(c, val.astype(c.dtype), (0, 0, offset, 0))


def _cached_attention(q: jnp.ndarray, ck, cv, offset, window=None) -> jnp.ndarray:
    """Grouped-query attention of q [B, H, S_in, hd] against the full cache
    ck/cv [B, Hkv, T, hd], masked to ``key_pos <= offset + query_row``.
    f32 softmax, 1/sqrt(hd) scale — the mha_reference conventions.

    ``offset`` is a scalar (every row at the same position — the
    ``generate()`` batch) OR a [B] vector of per-row positions — the
    serving engine's continuous batch, where every slot sits at its own
    depth.  The vector form broadcasts the mask per row and is otherwise
    the identical computation, so the two agree bitwise when the vector is
    constant.

    Quantized caches pass ``(q8, scale)`` pairs: the int8 payload is upcast
    in-register and the per-position scale folds into the scores (k) or
    the probabilities (v) — both exact because the scale is constant along
    the contracted hd dim, so HBM only ever moves int8 cache bytes."""
    B, H, S_in, hd = q.shape
    k_scale = v_scale = None
    if isinstance(ck, tuple):
        ck, k_scale = ck
    if isinstance(cv, tuple):
        cv, v_scale = cv
    Hkv, T = ck.shape[1], ck.shape[2]
    g = H // Hkv
    qg = q.reshape(B, Hkv, g, S_in, hd)
    s = jnp.einsum(
        "bkgqh,bkth->bkgqt", qg.astype(jnp.float32) if k_scale is not None else qg,
        ck.astype(qg.dtype if k_scale is None else jnp.float32),
    ).astype(jnp.float32)
    if k_scale is not None:
        s = s * k_scale[:, :, None, None, :]
    s = s * (1.0 / math.sqrt(hd))
    key_pos = jnp.arange(T)
    qpos = jnp.asarray(offset)[..., None] + jnp.arange(S_in)  # [S_in] | [B, S_in]
    mask = key_pos[None, :] <= qpos[..., None]
    if window is not None:  # Mistral: key in (qpos - window, qpos]
        mask = mask & (key_pos[None, :] > qpos[..., None] - window)
    if mask.ndim == 2:  # scalar offset: broadcast over the batch
        mask = mask[None]
    s = jnp.where(mask[:, None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    if v_scale is not None:
        p = p * v_scale[:, :, None, None, :]
        out = jnp.einsum("bkgqt,bkth->bkgqh", p, cv.astype(jnp.float32))
        out = out.astype(q.dtype)
    else:
        p = p.astype(cv.dtype)
        out = jnp.einsum("bkgqt,bkth->bkgqh", p, cv)
    return out.reshape(B, H, S_in, hd)


def cached_block_forward(
    p: Dict[str, PyTree],
    x: jnp.ndarray,
    cfg: TransformerConfig,
    ck: jnp.ndarray,
    cv: jnp.ndarray,
    offset,
    axis: Optional[str] = None,
    rope: "tuple | None" = None,
    ffn=None,
    cache_ops: "tuple | None" = None,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One pre-LN block with KV caching: writes this call's k/v into the
    cache at ``[offset, offset + S_in)`` and attends against the whole
    buffer.  x: [B, S_in, D].  Returns ``(y, ck, cv)`` with the updated
    cache.  Prefill is S_in=P at offset 0; decode is S_in=1 at offset t —
    one implementation, both phases.

    ``ffn``: optional ``(p, h) -> z`` replacing the dense MLP half (h is
    the post-ln2 activation; z must be the COMPLETE ffn output — no
    pending TP partial sums) — how the MoE families plug their expert
    layer into the same cached block.

    ``cache_ops``: optional ``(write, attend)`` pair swapping the cache
    LAYOUT under the same block: ``write(c, val, offset) -> c`` and
    ``attend(q, ck, cv, offset, window=...) -> out``.  Default is the
    contiguous ``[B, Hkv, T, hd]`` buffer; ``serving/paged_cache.py``
    passes block-pool ops (and [B]-vector offsets) so the serving engine
    reuses this exact block — the transformer math cannot drift between
    the two layouts because there is only one copy of it."""
    B, S_in, D = x.shape
    write, attend = cache_ops if cache_ops is not None else (
        _cache_write, _cached_attention)
    h = layer_norm(x, p["ln1"], cfg.norm_eps)
    q, k, v = compute_qkv(p["attn"], h, cfg, rope=rope)
    ck = write(ck, k, offset)
    cv = write(cv, v, offset)
    if cache_ops is None and isinstance(offset, int) and offset == 0 and S_in > 1:
        # prefill: every cached key IS this call's k, so causal attention
        # over (q, k, v) equals the cache-masked form — and runs the
        # model's own kernel via the shared core_attention dispatch (flash
        # on TPU) instead of materializing the [S_in, total] masked score
        # matrix
        from ..parallel.tensor_parallel.layers import core_attention

        out = core_attention(q, k, v, cfg)
    else:
        out = attend(q, ck, cv, offset, window=cfg.sliding_window)
    out = out.transpose(0, 2, 1, 3).reshape(B, S_in, q.shape[1] * cfg.head_dim)
    y = dense(out, p["attn"]["wo"])
    y = _close_row_parallel(y, p["attn"]["bo"], axis, False)
    x = x + y

    h = layer_norm(x, p["ln2"], cfg.norm_eps)
    if ffn is None:
        z = mlp_partial(p["mlp"], h)
        z = _close_row_parallel(z, p["mlp"]["b2"], axis, False)
    else:
        z = ffn(p, h)
    return x + z, ck, cv


def _embed_at(
    params: Dict[str, PyTree],
    tokens: jnp.ndarray,
    positions: jnp.ndarray,
    axis: Optional[str],
) -> jnp.ndarray:
    """[B, S_in] ids at the given global positions -> [B, S_in, D]."""
    h = vocab_parallel_embed(params["tok_emb"], tokens, axis)
    if "pos_emb" in params:  # learned positions; rope models skip this
        h = h + jnp.take(params["pos_emb"], positions, axis=0)
    return h


def forward_cached(
    params: Dict[str, PyTree],
    tokens: jnp.ndarray,
    cfg: GPTConfig,
    cache: Dict[str, jnp.ndarray],
    offset,
    axis: Optional[str] = None,
    all_logits: bool = False,
) -> Tuple[Dict[str, jnp.ndarray], jnp.ndarray]:
    """Run ``tokens`` [B, S_in] (occupying global positions
    ``offset + arange(S_in)``) through the cached stack.  Returns the
    updated cache and the LAST position's vocab-local logits [B, V_local].
    The layer dim rides a ``lax.scan`` over the stacked block params with
    the cache slices as per-layer carries-through (scan ys)."""
    bcfg = cfg.block
    S_in = tokens.shape[1]
    positions = offset + jnp.arange(S_in)
    h = _embed_at(params, tokens, positions, axis)
    rope = (
        rope_cache(positions, bcfg.head_dim, bcfg.rope_theta,
                   scaling=bcfg.rope_scaling)
        if bcfg.rope
        else None
    )

    def body(hc, xs):
        lp, ck, cv = xs
        y, ck, cv = cached_block_forward(
            lp, hc, bcfg, ck, cv, offset, axis=axis, rope=rope
        )
        return y, (ck, cv)

    h, (ck, cv) = jax.lax.scan(
        body, h, (params["blocks"], cache["k"], cache["v"])
    )
    if all_logits:
        # per-position logits [B, S_in, V_local] — the speculative-decode
        # verify pass needs the model's argmax at EVERY drafted position
        return {"k": ck, "v": cv}, gpt_head(
            params, h, axis, False, eps=cfg.norm_eps)
    logits = gpt_head(params, h[:, -1:, :], axis, False, eps=cfg.norm_eps)  # [B, 1, V_local]
    return {"k": ck, "v": cv}, logits[:, 0, :]


def forward_cached_moe(
    params: Dict[str, PyTree],
    tokens: jnp.ndarray,
    cfg: GPTConfig,
    cache: Dict[str, jnp.ndarray],
    offset,
    axis: Optional[str] = None,
    ep_axis: Optional[str] = None,
) -> Tuple[Dict[str, jnp.ndarray], jnp.ndarray]:
    """:func:`forward_cached` for the MoE family (heterogeneous block
    LIST, expert FFN every moe_every-th block).

    Inference-time dispatch is EXACT no-drop routing — every token reaches
    every expert it routed to, so token t's output never depends on what
    other tokens (batch rows, or the incremental history) routed.  This is
    what makes incremental decode == full forward: capacity-based drops
    are a training-batch interaction with no incremental equivalent.

    - ``ep_axis=None`` (single-host serving): the ragged route-then-group
      path (:func:`..parallel.moe.moe_serve_forward`) — ``jax.lax.
      ragged_dot`` grouped GEMMs over exactly ``T*top_k`` rows, no
      ``E/top_k`` capacity-padding tax at prefill.
    - ``ep_axis`` set (EP-sharded serving, inside shard_map on the moe
      mesh view): experts stay sharded over ``moe_ep`` at inference —
      each device holds ``E/ep`` experts and tokens ride the training
      all_to_all exchange, with capacity raised to the no-drop bound
      (``cf >= E/top_k`` ⇒ no token evicted).  Composes with TP decode
      (``axis``): attention heads/vocab shard over ``tensor``, experts
      over ``moe_ep``."""
    import dataclasses as _dc

    from ..parallel.moe import moe_forward, moe_serve_forward
    from .gpt_moe import moe_layer_config

    bcfg = cfg.block
    mcfg = moe_layer_config(cfg)
    mcfg = _dc.replace(
        mcfg,
        capacity_factor=max(
            mcfg.capacity_factor, mcfg.num_experts / mcfg.top_k
        ),
    )
    S_in = tokens.shape[1]
    positions = offset + jnp.arange(S_in)
    h = _embed_at(params, tokens, positions, axis)
    rope = (
        rope_cache(positions, bcfg.head_dim, bcfg.rope_theta,
                   scaling=bcfg.rope_scaling)
        if bcfg.rope
        else None
    )

    if ep_axis is None:
        def moe_ffn(p, hh):
            return moe_serve_forward(p["moe"], hh, mcfg)
    else:
        def moe_ffn(p, hh):
            z, _aux = moe_forward(
                p["moe"], hh, mcfg, ep_axis=ep_axis, causal=bcfg.causal)
            return z

    ks, vs = [], []
    layer = lambda c, i: jax.tree.map(lambda a: a[i], c)  # tuple-safe (int8)
    for i, bp in enumerate(params["blocks"]):
        h, ck, cv = cached_block_forward(
            bp, h, bcfg, layer(cache["k"], i), layer(cache["v"], i), offset,
            axis=axis, rope=rope, ffn=moe_ffn if "moe" in bp else None,
        )
        ks.append(ck)
        vs.append(cv)
    stack = lambda cs: jax.tree.map(lambda *xs: jnp.stack(xs), *cs)
    cache = {"k": stack(ks), "v": stack(vs)}
    logits = gpt_head(params, h[:, -1:, :], axis, False, eps=cfg.norm_eps)
    return cache, logits[:, 0, :]


def _full_logits(logits: jnp.ndarray, cfg: GPTConfig, axis: Optional[str]):
    """Vocab-local [..., V_local] -> full [..., V] (psum-assembled shard
    slabs; tiny at a handful of positions per sequence).  Identity when
    serial.  Any leading shape: [B, V_local] for ordinary decode, [B,
    K+1, V_local] for the speculative multi-position verify step."""
    if axis is None:
        return logits
    n = axis_size(axis)
    i = jax.lax.axis_index(axis)
    full = jnp.zeros(logits.shape[:-1] + (cfg.vocab_size,), logits.dtype)
    start = (0,) * (logits.ndim - 1) + (i * logits.shape[-1],)
    full = jax.lax.dynamic_update_slice(full, logits, start)
    return jax.lax.psum(full, axis)


def _sample(
    logits: jnp.ndarray,
    key: Optional[jax.Array],
    temperature: float,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
) -> jnp.ndarray:
    """Greedy argmax when ``key`` is None, else temperature sampling with
    optional top-k and/or top-p (nucleus) filtering.  On full [B, V]
    logits, so TP shards make the identical choice.

    Filter order is the standard one: temperature -> top-k -> top-p.
    Masked logits become -inf (zero probability after softmax); top-p
    keeps the SMALLEST prefix of the probability-sorted vocab whose mass
    reaches ``top_p`` (the argmax always survives, so top_p -> 0 degrades
    to greedy rather than an empty support)."""
    if top_k is not None and top_k < 1:
        raise ValueError(f"top_k must be >= 1, got {top_k}")
    # temperature == 0 is the common shorthand for greedy — honor it instead
    # of dividing by zero (NaN logits -> undefined categorical draws)
    if key is None or temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    if temperature < 0.0:
        raise ValueError(f"temperature must be >= 0, got {temperature}")
    x = logits.astype(jnp.float32) / temperature
    V = x.shape[-1]
    neg = jnp.array(-jnp.inf, x.dtype)
    need_k = top_k is not None and top_k < V
    need_p = top_p is not None and top_p < 1.0
    if need_k and not need_p:
        # O(V·k) threshold; the full sort is only needed for the nucleus
        kth = jax.lax.top_k(x, top_k)[0][..., -1:]
        x = jnp.where(x < kth, neg, x)
    elif need_k or need_p:
        sorted_x = jnp.sort(x, axis=-1)[..., ::-1]  # ONE descending sort
        if need_k:
            x = jnp.where(x < sorted_x[..., top_k - 1][..., None], neg, x)
            # the filtered distribution's descending sort, for the nucleus
            sorted_x = jnp.where(jnp.arange(V) < top_k, sorted_x, neg)
        if need_p:
            probs = jax.nn.softmax(sorted_x, axis=-1)
            cum = jnp.cumsum(probs, axis=-1)
            # keep ranks whose PRECEDING mass is < top_p; rank 0 is kept
            # unconditionally so top_p -> 0 really is greedy (strict '<'
            # alone would empty the support at top_p == 0.0)
            keep = jnp.roll(cum, 1, axis=-1).at[..., 0].set(0.0) < top_p
            keep = keep.at[..., 0].set(True)
            cutoff = jnp.min(
                jnp.where(keep, sorted_x, jnp.inf), axis=-1, keepdims=True
            )
            x = jnp.where(x < cutoff, neg, x)
    return jax.random.categorical(key, x, axis=-1).astype(jnp.int32)


def generate(
    params: Dict[str, PyTree],
    prompt: jnp.ndarray,
    cfg: GPTConfig,
    max_new_tokens: int,
    axis: Optional[str] = None,
    key: Optional[jax.Array] = None,
    temperature: float = 1.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    ep_axis: Optional[str] = None,
    kv_quant: bool = False,
) -> jnp.ndarray:
    """Autoregressively extend ``prompt`` [B, P] by ``max_new_tokens``.
    Greedy when ``key`` is None, else temperature sampling with optional
    ``top_k`` / ``top_p`` (nucleus) filtering (:func:`_sample`).  Returns
    [B, P + max_new_tokens] (prompt included).

    Serial when ``axis`` is None; under TP call inside shard_map with the
    training param specs (``gpt_param_specs(cfg, tp_axis=axis)``) — the
    returned tokens are psum/argmax-deterministic and identical on every
    shard.  Jit the whole call: prefill is one batched forward, then ONE
    ``lax.scan`` of single-token steps — no per-token recompilation.

    MoE configs decode through :func:`forward_cached_moe` — exact no-drop
    routing; ragged grouped GEMMs when ``ep_axis`` is None, EP-SHARDED
    experts (all_to_all over ``ep_axis``, e.g. the moe view's 'moe_ep')
    when set — its docstring has the semantics.  ``P + max_new_tokens <=
    cfg.max_seq`` for learned positions."""
    if ep_axis is not None and not cfg.moe_experts:
        raise ValueError("ep_axis is only meaningful for MoE configs")
    if cfg.attn_impl in ("ring", "ulysses"):
        raise NotImplementedError(
            "context-parallel decode is not supported: the KV cache is not "
            "sequence-sharded. attn_impl is a runtime choice — decode a "
            "CP-trained checkpoint with dataclasses.replace(cfg, "
            "attn_impl='flash', context_axis=None)"
        )
    if cfg.moe_experts:
        fwd = functools.partial(forward_cached_moe, ep_axis=ep_axis)
    else:
        fwd = forward_cached
    B, P = prompt.shape
    if max_new_tokens < 1:
        # the prefill below would still sample one token and
        # dynamic_update_slice would CLAMP its out-of-bounds write onto the
        # last prompt position — silently corrupting the prompt
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
    total = P + max_new_tokens
    if cfg.pos == "learned" and total > cfg.max_seq:
        raise ValueError(
            f"P + max_new_tokens = {total} exceeds the learned position "
            f"table ({cfg.max_seq})"
        )
    n_shards = 1 if axis is None else axis_size(axis)
    cache = init_kv_cache(cfg, B, total, axis_size=n_shards,
                          quantized=kv_quant)

    cache, logits = fwd(params, prompt, cfg, cache, 0, axis)
    k0 = None
    if key is not None:
        key, k0 = jax.random.split(key)
    first = _sample(
        _full_logits(logits, cfg, axis), k0, temperature, top_k, top_p)

    tokens = jnp.zeros((B, total), jnp.int32)
    tokens = jax.lax.dynamic_update_slice(tokens, prompt.astype(jnp.int32), (0, 0))
    tokens = jax.lax.dynamic_update_slice(tokens, first[:, None], (0, P))

    def step(carry, i):
        tokens, cache, key = carry
        pos = P + i  # position of the token being fed
        tok = jax.lax.dynamic_slice(tokens, (0, pos), (B, 1))
        cache, logits = fwd(params, tok, cfg, cache, pos, axis)
        sk = None
        if key is not None:
            key, sk = jax.random.split(key)
        nxt = _sample(
            _full_logits(logits, cfg, axis), sk, temperature, top_k, top_p)
        tokens = jax.lax.dynamic_update_slice(tokens, nxt[:, None], (0, pos + 1))
        return (tokens, cache, key), None

    if max_new_tokens > 1:
        (tokens, cache, key), _ = jax.lax.scan(
            step, (tokens, cache, key), jnp.arange(max_new_tokens - 1)
        )
    if axis is not None:
        # every shard computed the identical sequence; pmax re-types the
        # result as axis-invariant so callers can use out_specs P()
        tokens = jax.lax.pmax(tokens, axis)
    return tokens


def speculative_generate(
    params: Dict[str, PyTree],
    draft_params: Dict[str, PyTree],
    prompt: jnp.ndarray,
    cfg: GPTConfig,
    max_new_tokens: int,
    draft_cfg: Optional[GPTConfig] = None,
    num_draft: int = 4,
    kv_quant: bool = False,
) -> jnp.ndarray:
    """Greedy speculative decoding: a cheap DRAFT model proposes
    ``num_draft`` tokens per macro-step, the target model verifies them
    in ONE (K+1)-position cached forward, and the longest agreeing prefix
    plus the target's own correction token are emitted.

    **Lossless by construction**: every emitted token is the target
    model's greedy argmax on its certified prefix, whatever the draft
    proposes — a random draft only makes it slow, never wrong (the test
    asserts bit-equality with :func:`generate` for good, quantized AND
    adversarial drafts).  Decode is weight-bandwidth-bound
    (docs/BENCH_AB.md 6b), and a (K+1)-row verify forward reads the
    weights ONCE — so accepted drafts amortize the target's HBM traffic
    over up to K+1 tokens.  The natural self-speculative pairing is
    ``draft_params = tools.surgery.quantize_decode_params(params)``:
    the int8 draft is ~1.7x faster per token and near-always agrees.

    Static-shape design: both KV caches are fixed buffers; stale entries
    past the certified position are never attended (the position mask
    excludes them) and are overwritten when real tokens reach them, so
    rejected drafts need NO cache rollback.  The macro loop is a
    ``lax.while_loop`` on the certified position — data-dependent
    progress (1..K+1 tokens per macro-step) with zero retraces.

    Single-sequence (B == 1), serial (no TP axis) — the latency regime
    speculative decoding exists for.  ``draft_cfg`` defaults to ``cfg``
    (self-speculation); a distinct smaller model needs the same vocab.
    """
    if cfg.moe_experts:
        raise NotImplementedError(
            "speculative_generate supports the dense families")
    if cfg.attn_impl in ("ring", "ulysses"):
        raise NotImplementedError(
            "context-parallel decode is not supported: the KV cache is not "
            "sequence-sharded. attn_impl is a runtime choice — decode a "
            "CP-trained checkpoint with dataclasses.replace(cfg, "
            "attn_impl='flash', context_axis=None)"
        )
    B, P = prompt.shape
    if B != 1:
        raise ValueError(f"speculative decode is B == 1 (got {B})")
    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
    K = int(num_draft)
    if K < 1:
        raise ValueError(f"num_draft must be >= 1, got {K}")
    dcfg = draft_cfg or cfg
    if dcfg.vocab_size != cfg.vocab_size:
        raise ValueError("draft and target must share a vocabulary")
    total = P + max_new_tokens + K + 1  # slack for overshoot writes
    if cfg.pos == "learned" and total > cfg.max_seq:
        raise ValueError(
            f"P + max_new_tokens + num_draft + 1 = {total} exceeds the "
            f"learned position table ({cfg.max_seq})")
    cache_v = init_kv_cache(cfg, 1, total, quantized=kv_quant)
    cache_d = init_kv_cache(dcfg, 1, total, quantized=kv_quant)

    cache_v, logits = forward_cached(params, prompt, cfg, cache_v, 0)
    cache_d, _ = forward_cached(draft_params, prompt, dcfg, cache_d, 0)
    first = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [1]

    tokens = jnp.zeros((1, total), jnp.int32)
    tokens = jax.lax.dynamic_update_slice(tokens, prompt.astype(jnp.int32), (0, 0))
    tokens = jax.lax.dynamic_update_slice(tokens, first[:, None], (0, P))
    target_last = P + max_new_tokens - 1  # index of the final required token

    def macro(state):
        tokens, cache_v, cache_d, t = state

        # ---- draft K tokens after certified position t
        def dstep(carry, i):
            cache_d, tok = carry
            cache_d, lg = forward_cached(
                draft_params, tok, dcfg, cache_d, t + i)
            nxt = jnp.argmax(lg, axis=-1).astype(jnp.int32)[:, None]  # [1,1]
            return (cache_d, nxt), nxt[0, 0]

        tok_t = jax.lax.dynamic_slice(tokens, (0, t), (1, 1))
        (cache_d, _), drafts = jax.lax.scan(
            dstep, (cache_d, tok_t), jnp.arange(K))  # drafts [K]

        # ---- verify: one (K+1)-position target forward over
        # [tokens[t], d_1..d_K] at offsets t..t+K
        cand = jnp.concatenate([tok_t[0], drafts])[None, :]  # [1, K+1]
        cache_v, all_lg = forward_cached(
            params, cand, cfg, cache_v, t, all_logits=True)  # [1, K+1, V]
        verify = jnp.argmax(all_lg[0], axis=-1).astype(jnp.int32)  # [K+1]
        # verify[i] = target's token for position t+i+1
        agree = (drafts == verify[:K]).astype(jnp.int32)
        n = jnp.sum(jnp.cumprod(agree))  # accepted draft prefix length

        # emit verify[0..n] at positions t+1..t+n+1: write ALL K+1 (the
        # tail past t+n+1 is uncertified overshoot — overwritten later,
        # never read: the final slice stops at the certified frontier)
        tokens = jax.lax.dynamic_update_slice(tokens, verify[None, :], (0, t + 1))
        return tokens, cache_v, cache_d, t + n + 1

    def cond(state):
        return state[3] < target_last

    tokens, cache_v, cache_d, t = jax.lax.while_loop(
        cond, macro, (tokens, cache_v, cache_d, P))
    return tokens[:, : P + max_new_tokens]


def beam_generate(
    params: Dict[str, PyTree],
    prompt: jnp.ndarray,
    cfg: GPTConfig,
    max_new_tokens: int,
    num_beams: int = 4,
    return_all: bool = False,
    kv_quant: bool = False,
) -> jnp.ndarray:
    """Fixed-length beam search (deterministic, log-prob scored).

    Standard beam semantics: at every step the ``num_beams * V``
    continuations of the live beams are scored by accumulated
    log-probability and the top ``num_beams`` survive (parent beams may
    be cloned or dropped — the KV caches are re-gathered along the batch
    dim accordingly, the textbook cost of beam search).  The
    best-scoring beam is returned (``return_all`` gives every beam,
    best first).  No ``length_penalty`` knob: every beam has the same
    length here, so a length normalization cannot change the ranking.

    The framework's generation API is fixed-length (no EOS machinery —
    the reference has no inference path at all, and stopping criteria
    are a serving-layer concern), so this is exhaustive-length beam
    search: parity with ``transformers.generate(num_beams=N,
    do_sample=False)`` holds when HF's early stopping is disabled
    (tests/test_generate.py::test_beam_matches_hf_and_greedy).  B == 1,
    serial.  ``kv_quant`` stores both caches int8 exactly as in
    :func:`generate` (the beam reorder gathers the (q8, scale) pytree
    unchanged).

    The whole search is one jit: prefill once, replicate the cache
    across beams, then ONE ``lax.scan`` of select-and-extend steps
    (static shapes throughout; beam reordering is a batch-dim gather).
    """
    B, P = prompt.shape
    if B != 1:
        raise ValueError(f"beam search is B == 1 (got {B})")
    if num_beams < 1:
        raise ValueError(f"num_beams must be >= 1, got {num_beams}")
    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
    if cfg.attn_impl in ("ring", "ulysses"):
        raise NotImplementedError(
            "context-parallel decode is not supported (see generate)")
    total = P + max_new_tokens
    if cfg.pos == "learned" and total > cfg.max_seq:
        raise ValueError(
            f"P + max_new_tokens = {total} exceeds the learned position "
            f"table ({cfg.max_seq})")
    V = cfg.vocab_size
    nb = int(num_beams)
    fwd = forward_cached_moe if cfg.moe_experts else forward_cached

    # prefill every beam with the same prompt (identical rows; the first
    # expansion step de-duplicates by taking the top-nb of ONE row)
    cache = init_kv_cache(cfg, nb, total, quantized=kv_quant)
    tiled = jnp.broadcast_to(prompt.astype(jnp.int32), (nb, P))
    cache, logits = fwd(params, tiled, cfg, cache, 0)  # [nb, V]
    lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    # beams start distinct: the nb best FIRST tokens of beam 0
    first_lp, first_tok = jax.lax.top_k(lp[0], nb)  # [nb]
    scores = first_lp
    tokens = jnp.zeros((nb, total), jnp.int32)
    tokens = jax.lax.dynamic_update_slice(tokens, tiled, (0, 0))
    tokens = jax.lax.dynamic_update_slice(
        tokens, first_tok.astype(jnp.int32)[:, None], (0, P))

    def step(carry, i):
        tokens, cache, scores = carry
        pos = P + i
        tok = jax.lax.dynamic_slice(tokens, (0, pos), (nb, 1))
        cache, logits = fwd(params, tok, cfg, cache, pos)  # [nb, V]
        lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        cand = scores[:, None] + lp  # [nb, V]
        top, flat_idx = jax.lax.top_k(cand.reshape(-1), nb)
        parent = flat_idx // V
        nxt = (flat_idx % V).astype(jnp.int32)
        tokens = tokens[parent]
        cache = jax.tree.map(lambda c: c[:, parent], cache)  # [L, nb, ...]
        tokens = jax.lax.dynamic_update_slice(
            tokens, nxt[:, None], (0, pos + 1))
        return (tokens, cache, top), None

    if max_new_tokens > 1:
        (tokens, cache, scores), _ = jax.lax.scan(
            step, (tokens, cache, scores), jnp.arange(max_new_tokens - 1))

    order = jnp.argsort(-scores)
    out = tokens[order][:, :total]
    return out if return_all else out[:1]
