from .flash_attention import flash_attention, mha_reference
from .ring_attention import ring_attention, ulysses_attention
