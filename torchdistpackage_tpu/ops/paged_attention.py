"""Paged decode attention as a Pallas TPU kernel (vLLM PagedAttention
lineage): walk the per-slot block table *inside* the kernel.

The serving engine's gather path (serving/paged_cache.py ``gather_kv``)
materializes every slot's blocks into a contiguous ``[B, Hkv,
max_blocks*bs, hd]`` view before the dense ``_cached_attention`` — O(max
context) HBM read AND written per decode tick, whatever the slot's actual
length, plus an f32 upcast temp of the same size on the int8 pool.  This
kernel removes that round trip: the grid runs ``(slot, kv_head,
kv-block-step)`` and each program DMAs ONE pool block into VMEM through a
scalar-prefetched block table (``PrefetchScalarGridSpec`` — the table IS
the index map), runs online-softmax flash accumulation against it with
per-row position masking, and stops issuing fresh fetches past the slot's
live length (the index map clamps dead steps onto the last live block, so
Mosaic's block-revisit elision skips the re-fetch).  Per-tick attention
HBM traffic scales with the tokens a slot actually holds, VMEM per
program is O(block) — which is what opens 32k+ serving contexts
(docs/long_context.md) on the same pool.

One entry point covers every serving shape:

- ``S_in = 1`` ordinary decode, ``S_in = K+1`` the speculative verify
  step, ``S_in = chunk`` chunked prefill — all the same kernel, so both
  compiled engine programs ride it;
- scalar or ``[B]``-vector offsets (each slot at its own depth);
- GQA: q heads grouped per KV head OUTSIDE the kernel (a reshape, not a
  repeat) — a KV block is fetched once per group;
- sliding-window masking (Mistral semantics, matching
  ``_cached_attention``);
- int8 pools: ``(q8, scale)`` block pairs are dequantized IN-REGISTER —
  the scale folds into the scores (k) / probabilities (v) exactly as the
  gather path folds it, but the f32 gathered view is never materialized,
  extending the EQuARX thesis (PAPERS.md 2506.17615 — keep quantized
  bytes quantized until the compute that consumes them) from wire
  collectives to the KV-cache read path.

Numerics: scores and the online softmax run in f32 (matching the gather
path's f32 softmax); the accumulation ORDER differs (blockwise online
rescale vs one full-row softmax), so logits agree to float tolerance and
greedy tokens bit-match the gather goldens (tests/test_paged_attention.py
locks dense, GQA, sliding-window, vector offsets, and the K+1 verify
shape).  The gather path stays in-tree as the parity oracle.

On CPU the kernel runs in Pallas interpreter mode automatically (same
``_interpret`` switch as ops/flash_attention.py), so every test exercises
the identical code path the TPU compiles.

Tuning: ``fetch_width`` (pool blocks streamed per grid step — each is an
independent BlockSpec input, so Mosaic pipelines the DMAs) and
``q_pad_to`` (pad the in-kernel q rows to a tile-friendly multiple; the
K+1 verify shape lands at awkward row counts like G*(K+1)) come from the
per-chip autotuned table (tools/flash_tune.py ``--paged``,
docs/PAGED_TUNE_v5e.json), with conservative fallbacks for unmeasured
chips and the interpreter.
"""

from __future__ import annotations

import functools
import math
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash_attention import _interpret, _out_struct

NEG_INF = -1e30  # finite "minus infinity": avoids (-inf) - (-inf) NaNs

_LANES = 128  # m/l scratch keeps a full lane dim for layout friendliness

#: Per-chip tuned kernel parameters, measured by tools/flash_tune.py
#: ``--paged`` (docs/PAGED_TUNE_v5e.json).  ``fetch_width`` = pool blocks
#: streamed per grid step; ``q_pad_to`` = q-row padding multiple (the
#: K+1 verify shape's G*(K+1) rows are rarely tile-aligned).
_TUNED_PAGED = (
    ("v5 lite", {"fetch_width": 4, "q_pad_to": 8}),
    ("v5e", {"fetch_width": 4, "q_pad_to": 8}),
)
#: Conservative fallback for unmeasured chips and the CPU interpreter:
#: one block per step, minimal f32 sublane padding.
_FALLBACK_PAGED = {"fetch_width": 1, "q_pad_to": 8}


@functools.lru_cache(maxsize=None)
def _paged_params_for(device_kind: str) -> dict:
    dk = device_kind.lower()
    for sub, params in _TUNED_PAGED:
        if sub in dk:
            return dict(params)
    if jax.default_backend() != "cpu":
        import logging

        logging.getLogger(__name__).warning(
            "paged_attention: no autotuned row for device_kind=%r; serving "
            "conservative fallback %s — run tools/flash_tune.py --paged on "
            "this chip and add a _TUNED_PAGED row", device_kind,
            _FALLBACK_PAGED)
    return dict(_FALLBACK_PAGED)


def default_paged_params() -> dict:
    """``{fetch_width, q_pad_to}`` for the attached chip — autotuned when
    measured, :data:`_FALLBACK_PAGED` otherwise.  Device kind re-read per
    call (only the per-kind lookup is cached), mirroring
    ``flash_attention.default_tiles``."""
    try:
        dk = jax.devices()[0].device_kind
    except Exception:
        return dict(_FALLBACK_PAGED)
    return _paged_params_for(dk)


def resolve_attn_impl(impl: Optional[str]) -> str:
    """``'auto'``/None -> ``'pallas'`` on TPU, ``'gather'`` elsewhere (the
    interpreter-mode kernel is correct on CPU but slow — tests opt in
    explicitly).  Explicit values pass through validated."""
    if impl in (None, "auto"):
        return "pallas" if jax.default_backend() == "tpu" else "gather"
    if impl not in ("pallas", "gather"):
        raise ValueError(
            f"attn_impl must be 'pallas', 'gather' or 'auto', got {impl!r}")
    return impl


def _compiler_params():
    if _interpret():
        return None
    return pltpu.CompilerParams(
        dimension_semantics=("parallel", "parallel", "arbitrary")
    )


def _kernel(
    tab_ref, off_ref, q_ref, *refs,
    S_in, bs, window, sm_scale, quantized, fetch_width, rows,
):
    """Grid ``(slot b, kv-head h, kv-step j)``; ``refs`` carries the
    ``fetch_width`` per-step KV blocks ((k, v) dense or (k8, ks, v8, vs)
    quantized, sub-block-major), then the output ref and the (acc, m, l)
    online-softmax VMEM scratch carried across j steps."""
    per = 4 if quantized else 2
    kv_refs = refs[:fetch_width * per]
    o_ref = refs[fetch_width * per]
    acc_ref, m_ref, l_ref = refs[fetch_width * per + 1:]
    b = pl.program_id(0)
    j = pl.program_id(2)
    off = off_ref[b]
    hi = (off + S_in + bs - 1) // bs  # live KV blocks for this slot

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    q = q_ref[0, 0]  # [rows, hd]
    # row r covers query position off + (r % S_in) (group-major rows);
    # padded rows past the real R mask everything and are sliced off
    qpos = off + jax.lax.broadcasted_iota(jnp.int32, (rows, bs), 0) % S_in

    for i in range(fetch_width):
        blk = j * fetch_width + i  # absolute pool-block step

        @pl.when(blk < hi)
        def _compute(i=i, blk=blk):
            if quantized:
                k8 = kv_refs[4 * i][0, 0]
                ks = kv_refs[4 * i + 1][0, 0]
                v8 = kv_refs[4 * i + 2][0, 0]
                vs = kv_refs[4 * i + 3][0, 0]
                kblk = k8.astype(jnp.float32)
                s = jnp.dot(q.astype(jnp.float32), kblk.T,
                            preferred_element_type=jnp.float32)
                s = s * ks[None, :]
            else:
                kblk = kv_refs[2 * i][0, 0]
                s = jnp.dot(q, kblk.T,
                            preferred_element_type=jnp.float32)
            s = s * sm_scale
            kpos = blk * bs + jax.lax.broadcasted_iota(
                jnp.int32, (rows, bs), 1)
            keep = kpos <= qpos
            if window is not None:  # Mistral: key in (qpos - window, qpos]
                keep = keep & (kpos > qpos - window)
            s = jnp.where(keep, s, NEG_INF)
            m = m_ref[:, :1]
            l = l_ref[:, :1]
            m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
            p = jnp.exp(s - m_new)
            corr = jnp.exp(m - m_new)
            l_ref[...] = jnp.broadcast_to(
                l * corr + jnp.sum(p, axis=-1, keepdims=True), l_ref.shape)
            if quantized:
                pv = p * vs[None, :]
                upd = jnp.dot(pv, v8.astype(jnp.float32),
                              preferred_element_type=jnp.float32)
            else:
                vblk = kv_refs[2 * i + 1][0, 0]
                upd = jnp.dot(p.astype(vblk.dtype), vblk,
                              preferred_element_type=jnp.float32)
            acc_ref[...] = acc_ref[...] * corr + upd
            m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)

    @pl.when(j == (hi - 1) // fetch_width)
    def _write():
        # l > 0 for every real row (a query always attends its own
        # position); padded rows divide garbage that is sliced away
        o_ref[0, 0] = (acc_ref[...] / l_ref[:, :1]).astype(o_ref.dtype)


def paged_decode_attention(
    q: jnp.ndarray,
    k_pool: Any,
    v_pool: Any,
    tables: jnp.ndarray,
    offsets,
    *,
    window: Optional[int] = None,
    sm_scale: Optional[float] = None,
    fetch_width: Optional[int] = None,
    q_pad_to: Optional[int] = None,
) -> jnp.ndarray:
    """Attention of ``q`` [B, H, S_in, hd] against each slot's paged
    context, walking the block table in-kernel.

    ``k_pool``/``v_pool``: one layer's pool ``[num_blocks, Hkv, bs, hd]``
    (or its int8 ``(q8 [..., hd], scale [...])`` pair).  ``tables``
    [B, max_blocks] int32 block tables; ``offsets`` scalar or [B] — slot
    b's rows sit at positions ``offsets[b] + arange(S_in)`` and attend
    keys at ``kpos <= qpos`` (``window`` additionally bounds below).
    Returns [B, H, S_in, hd] in ``q.dtype`` — drop-in for the gather
    path's ``_cached_attention`` output (float-tolerance equal; the
    engine goldens assert token bit parity).
    """
    B, H, S_in, hd = q.shape
    quantized = isinstance(k_pool, tuple)
    k_arr = k_pool[0] if quantized else k_pool
    nb, Hkv, bs, _hd = k_arr.shape
    groups, rem = divmod(H, Hkv)
    if rem:
        raise ValueError(
            f"GQA needs q heads divisible by kv heads, got {H} vs {Hkv}")
    mb = tables.shape[-1]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(hd)
    params = default_paged_params()
    fw = int(fetch_width if fetch_width is not None else
             params["fetch_width"])
    fw = max(1, min(fw, mb))
    pad_to = int(q_pad_to if q_pad_to is not None else params["q_pad_to"])

    offs = jnp.asarray(offsets, jnp.int32)
    if offs.ndim == 0:
        offs = jnp.broadcast_to(offs, (B,))
    # group-major rows: row r = g*S_in + s covers position off + s
    R = groups * S_in
    rows = -(-R // pad_to) * pad_to
    qr = q.reshape(B, Hkv, R, hd)
    if rows != R:
        qr = jnp.pad(qr, ((0, 0), (0, 0), (0, rows - R), (0, 0)))

    def qidx(b, h, j, tab, off):
        return (b, h, 0, 0)

    def kvidx(b, h, j, tab, off, i=0, ndim=4):
        # clamp dead steps onto the last live block: consecutive grid
        # steps then revisit the same index and Mosaic skips the re-fetch
        # — attention HBM traffic scales with the slot's ACTUAL length
        hi1 = (off[b] + S_in + bs - 1) // bs - 1
        blk = jnp.minimum(jnp.minimum(j * fw + i, hi1), mb - 1)
        idx = tab[b, blk]
        return (idx, h, 0, 0) if ndim == 4 else (idx, h, 0)

    in_specs = [pl.BlockSpec((1, 1, rows, hd), qidx)]
    operands = [qr]
    for pool in (k_pool, v_pool):
        for i in range(fw):
            if quantized:
                p8, ps = pool
                in_specs.append(pl.BlockSpec(
                    (1, 1, bs, hd), functools.partial(kvidx, i=i)))
                operands.append(p8)
                in_specs.append(pl.BlockSpec(
                    (1, 1, bs), functools.partial(kvidx, i=i, ndim=3)))
                operands.append(ps)
            else:
                in_specs.append(pl.BlockSpec(
                    (1, 1, bs, hd), functools.partial(kvidx, i=i)))
                operands.append(pool)
    # interleave per sub-block: kernel expects (k, v) / (k8, ks, v8, vs)
    # pairs sub-block-major — reorder the flat k-then-v lists
    per = 2 if quantized else 1
    k_ops, v_ops = operands[1:1 + fw * per], operands[1 + fw * per:]
    k_specs, v_specs = in_specs[1:1 + fw * per], in_specs[1 + fw * per:]
    ordered_ops, ordered_specs = [operands[0]], [in_specs[0]]
    for i in range(fw):
        ordered_ops.extend(k_ops[per * i:per * (i + 1)])
        ordered_ops.extend(v_ops[per * i:per * (i + 1)])
        ordered_specs.extend(k_specs[per * i:per * (i + 1)])
        ordered_specs.extend(v_specs[per * i:per * (i + 1)])

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, Hkv, -(-mb // fw)),
        in_specs=ordered_specs,
        out_specs=pl.BlockSpec((1, 1, rows, hd), qidx),
        scratch_shapes=[
            pltpu.VMEM((rows, hd), jnp.float32),     # acc
            pltpu.VMEM((rows, _LANES), jnp.float32),  # m
            pltpu.VMEM((rows, _LANES), jnp.float32),  # l
        ],
    )
    kernel = functools.partial(
        _kernel, S_in=S_in, bs=bs, window=window, sm_scale=float(sm_scale),
        quantized=quantized, fetch_width=fw, rows=rows)
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=_out_struct((B, Hkv, rows, hd), q.dtype, q),
        compiler_params=_compiler_params(),
        interpret=_interpret(),
    )(tables.astype(jnp.int32), offs, *ordered_ops)
    return out[:, :, :R].reshape(B, H, S_in, hd)


# ------------------------------------------------ CP ring carry entry point


def _cp_kernel(
    tab_ref, off_ref, q_ref, *refs,
    S_in, bs, window, sm_scale, fetch_width, rows, nb, has_carry,
):
    """Ring-hop variant of :func:`_kernel` for context-parallel prefill
    (ops/ring_paged.py): the pool operand is ONE rank's slice
    [nb, Hkv, bs, hd] reached through a RE-BASED table (global id minus
    the source rank's slice base), so entries outside ``[0, nb)`` mean
    "another rank owns this block" — the index map clamps them onto a
    valid fetch and the in-kernel ownership test masks them out of the
    scores.  Instead of normalizing, the kernel RETURNS the raw online
    -softmax carry (acc, m, l); the ring accumulates it across hops
    (``has_carry`` seeds the scratch from the previous hop's output) and
    normalizes once after the last hop."""
    n_c = 3 if has_carry else 0
    carry_refs = refs[:n_c]
    kv_refs = refs[n_c:n_c + fetch_width * 2]
    acc_o, m_o, l_o = refs[n_c + fetch_width * 2:n_c + fetch_width * 2 + 3]
    acc_ref, m_ref, l_ref = refs[n_c + fetch_width * 2 + 3:]
    b = pl.program_id(0)
    j = pl.program_id(2)
    off = off_ref[b]
    hi = (off + S_in + bs - 1) // bs  # live KV blocks for this slot

    @pl.when(j == 0)
    def _init():
        if has_carry:
            acc_ref[...] = carry_refs[0][0, 0]
            m_ref[...] = carry_refs[1][0, 0]
            l_ref[...] = carry_refs[2][0, 0]
        else:
            m_ref[...] = jnp.full_like(m_ref, NEG_INF)
            l_ref[...] = jnp.zeros_like(l_ref)
            acc_ref[...] = jnp.zeros_like(acc_ref)

    q = q_ref[0, 0]  # [rows, hd]
    qpos = off + jax.lax.broadcasted_iota(jnp.int32, (rows, bs), 0) % S_in

    for i in range(fetch_width):
        blk = j * fetch_width + i

        @pl.when(blk < hi)
        def _compute(i=i, blk=blk):
            raw = tab_ref[b, blk]  # re-based id; out of [0, nb) = remote
            owned = (raw >= 0) & (raw < nb)
            kblk = kv_refs[2 * i][0, 0]
            s = jnp.dot(q, kblk.T, preferred_element_type=jnp.float32)
            s = s * sm_scale
            kpos = blk * bs + jax.lax.broadcasted_iota(
                jnp.int32, (rows, bs), 1)
            keep = (kpos <= qpos) & owned
            if window is not None:
                keep = keep & (kpos > qpos - window)
            s = jnp.where(keep, s, NEG_INF)
            m = m_ref[:, :1]
            l = l_ref[:, :1]
            m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
            p = jnp.exp(s - m_new)
            corr = jnp.exp(m - m_new)
            l_ref[...] = jnp.broadcast_to(
                l * corr + jnp.sum(p, axis=-1, keepdims=True), l_ref.shape)
            vblk = kv_refs[2 * i + 1][0, 0]
            upd = jnp.dot(p.astype(vblk.dtype), vblk,
                          preferred_element_type=jnp.float32)
            acc_ref[...] = acc_ref[...] * corr + upd
            m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)

    @pl.when(j == (hi - 1) // fetch_width)
    def _write():
        acc_o[0, 0] = acc_ref[...]
        m_o[0, 0] = m_ref[...]
        l_o[0, 0] = l_ref[...]


def paged_carry_attention(
    q: jnp.ndarray,
    k_pool: jnp.ndarray,
    v_pool: jnp.ndarray,
    tables_local: jnp.ndarray,
    offsets,
    *,
    carry: Optional[Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]] = None,
    window: Optional[int] = None,
    sm_scale: Optional[float] = None,
    fetch_width: Optional[int] = None,
    q_pad_to: Optional[int] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One ring hop of CP paged prefill: accumulate ``q`` [B, H, S_in,
    hd] against ONE rank's pool slice ``[nb, Hkv, bs, hd]`` reached
    through ``tables_local`` (= global tables minus that rank's slice
    base; out-of-slice entries are masked in-kernel), returning the
    UN-normalized online-softmax carry ``(acc [B, Hkv, rows, hd] f32,
    m [B, Hkv, rows, 128] f32, l [B, Hkv, rows, 128] f32)``.

    ``offsets`` must already include the rank's sub-chunk base (the q
    rows sit at ``offsets[b] + arange(S_in)`` globally), so the existing
    live-length walk (``hi``), dead-step clamping and position masking
    carry over from :func:`paged_decode_attention` unchanged.  Pass the
    previous hop's return as ``carry`` to continue accumulation; finish
    with :func:`finalize_paged_carry`.  ``l`` may be zero mid-ring (no
    owned key seen yet) — only the final carry's ``l`` must be positive,
    guaranteed because each row's own position is pool-resident on
    exactly one rank.  Int8 pools are not supported (the engine rejects
    ``kv_quant`` under ``cp_axis``)."""
    if isinstance(k_pool, tuple):
        raise NotImplementedError(
            "paged_carry_attention does not support int8 pools")
    B, H, S_in, hd = q.shape
    nb, Hkv, bs, _hd = k_pool.shape
    groups, rem = divmod(H, Hkv)
    if rem:
        raise ValueError(
            f"GQA needs q heads divisible by kv heads, got {H} vs {Hkv}")
    mb = tables_local.shape[-1]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(hd)
    params = default_paged_params()
    fw = int(fetch_width if fetch_width is not None else
             params["fetch_width"])
    fw = max(1, min(fw, mb))
    pad_to = int(q_pad_to if q_pad_to is not None else params["q_pad_to"])

    offs = jnp.asarray(offsets, jnp.int32)
    if offs.ndim == 0:
        offs = jnp.broadcast_to(offs, (B,))
    R = groups * S_in
    rows = -(-R // pad_to) * pad_to
    qr = q.reshape(B, Hkv, R, hd)
    if rows != R:
        qr = jnp.pad(qr, ((0, 0), (0, 0), (0, rows - R), (0, 0)))

    def qidx(b, h, j, tab, off):
        return (b, h, 0, 0)

    def kvidx(b, h, j, tab, off, i=0):
        # same dead-step clamp as the decode kernel, plus a clamp of the
        # re-based table entry into the slice (remote blocks fetch SOME
        # valid block; the in-kernel ownership test masks the scores)
        hi1 = (off[b] + S_in + bs - 1) // bs - 1
        blk = jnp.minimum(jnp.minimum(j * fw + i, hi1), mb - 1)
        idx = jnp.clip(tab[b, blk], 0, nb - 1)
        return (idx, h, 0, 0)

    has_carry = carry is not None
    in_specs = [pl.BlockSpec((1, 1, rows, hd), qidx)]
    operands = [qr]
    if has_carry:
        for c, lanes in zip(carry, (hd, _LANES, _LANES)):
            in_specs.append(pl.BlockSpec((1, 1, rows, lanes), qidx))
            operands.append(c)
    for i in range(fw):
        in_specs.append(pl.BlockSpec(
            (1, 1, bs, hd), functools.partial(kvidx, i=i)))
        operands.append(k_pool)
        in_specs.append(pl.BlockSpec(
            (1, 1, bs, hd), functools.partial(kvidx, i=i)))
        operands.append(v_pool)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, Hkv, -(-mb // fw)),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, rows, hd), qidx),
            pl.BlockSpec((1, 1, rows, _LANES), qidx),
            pl.BlockSpec((1, 1, rows, _LANES), qidx),
        ],
        scratch_shapes=[
            pltpu.VMEM((rows, hd), jnp.float32),      # acc
            pltpu.VMEM((rows, _LANES), jnp.float32),  # m
            pltpu.VMEM((rows, _LANES), jnp.float32),  # l
        ],
    )
    kernel = functools.partial(
        _cp_kernel, S_in=S_in, bs=bs, window=window,
        sm_scale=float(sm_scale), fetch_width=fw, rows=rows, nb=nb,
        has_carry=has_carry)
    acc, m, l = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            _out_struct((B, Hkv, rows, hd), jnp.float32, q),
            _out_struct((B, Hkv, rows, _LANES), jnp.float32, q),
            _out_struct((B, Hkv, rows, _LANES), jnp.float32, q),
        ],
        compiler_params=_compiler_params(),
        interpret=_interpret(),
    )(tables_local.astype(jnp.int32), offs, *operands)
    return acc, m, l


def finalize_paged_carry(carry, B: int, H: int, S_in: int, hd: int,
                         dtype) -> jnp.ndarray:
    """Normalize the last ring hop's carry and restore the public
    [B, H, S_in, hd] layout (undo group-major packing + row padding)."""
    acc, _m, l = carry
    Hkv = acc.shape[1]
    R = (H // Hkv) * S_in
    out = acc / l[..., :1]
    return out[:, :, :R].reshape(B, H, S_in, hd).astype(dtype)


# --------------------------------------------------- modeled HBM footprint


def modeled_attend_temp_bytes(
    impl: str, *, batch: int, kv_heads: int, max_blocks: int,
    block_size: int, head_dim: int, s_in: int = 1, groups: int = 1,
    itemsize: int = 4, fetch_width: Optional[int] = None,
) -> int:
    """Modeled per-layer attention working-set bytes for one decode step —
    the MemoryModel-style no-compile estimate the 32k serving test (and a
    capacity planner) judges against ``obs.mem_ledger.headroom_verdict``.

    ``gather``: the dense per-slot view ``[B, Hkv, max_blocks*bs, hd]``
    materialized for k AND v (the int8 pool additionally upcasts both to
    f32 in the einsum, so ``itemsize=4`` models that case too) — O(max
    context) whatever the slot holds.  ``pallas``: q/out rows plus
    ``fetch_width`` double-buffered KV blocks per program — O(block),
    independent of context."""
    if impl == "gather":
        return 2 * batch * kv_heads * max_blocks * block_size * head_dim * itemsize
    if impl == "pallas":
        fw = int(fetch_width or _FALLBACK_PAGED["fetch_width"])
        rows = groups * s_in
        blocks = 2 * 2 * fw * block_size * head_dim * itemsize  # k+v, 2-buf
        return batch * kv_heads * (2 * rows * head_dim * itemsize + blocks)
    raise ValueError(f"impl must be 'gather' or 'pallas', got {impl!r}")
