"""Run-parity: compare two runs' record streams into an
``exact | bounded | diverged`` verdict.

The quantization/optimization levers the ROADMAP gates on (int8
collectives, int8 KV, backward-splitting schedules) all make the same
promise: "numerically equivalent, or boundedly close".  Nothing in the
repo could *check* that promise across two runs — parity lived in ad-hoc
``np.testing.assert_allclose`` calls inside individual tests.  This
module is the reusable harness:

- :func:`stream_of` — extract a ``{step: value}`` scalar stream from a
  list of step records (a ``JsonlSink`` file, ``Telemetry.history``) or
  from a RUNREPORT's ``numerics.timeline``.
- :func:`compare_streams` — per-step deltas over the common steps, a
  downsampled drift curve, and the verdict: ``exact`` (bitwise-equal),
  ``bounded`` (every delta inside ``atol + rtol * |ref|``), ``diverged``
  (a delta escapes the band, or non-finiteness on one side only).
- :func:`param_divergence` — per-leaf L2 distance between two final
  param trees (which layer drifted, not just that something did).
- :func:`parity_section` — roll the comparisons into the RUNREPORT
  ``numerics.parity`` sub-section (``Telemetry.record_parity``).

``tools/parity_diff.py`` is the CLI over the same functions: point it at
two RUNREPORT.json / records.jsonl files and it renders the drift table,
the per-dtype ledger shift between the arms, and the verdict (nonzero
exit on ``diverged`` — a CI gate, like ``tools/bench_trend``).

Deliberately jax-free except :func:`param_divergence` (lazy import), so
the CLI runs on login nodes without touching a backend.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

PARITY_SCHEMA = "tdp-parity/v1"

#: The A/B verdict vocabulary (RUNREPORT ``numerics.parity.verdict``).
PARITY_VERDICTS = ("exact", "bounded", "diverged", "unknown")


def stream_of(source: Any, key: str = "loss") -> Dict[int, float]:
    """``{step: value}`` from a records list or a RUNREPORT dict.

    - a list of dicts: every ``type == "step"`` record carrying ``key``
      (non-step records — events, comm records — are skipped);
    - a RUNREPORT dict: the ``numerics.timeline`` entries carrying
      ``key`` (the per-step stream the report retains).
    """
    if isinstance(source, dict):
        records = (source.get("numerics") or {}).get("timeline") or []
    else:
        records = [r for r in source
                   if isinstance(r, dict) and r.get("type", "step") == "step"]
    out: Dict[int, float] = {}
    for r in records:
        if not isinstance(r, dict) or "step" not in r:
            continue
        v = r.get(key)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            out[int(r["step"])] = float(v)
    return out


def compare_streams(
    a: Dict[int, float],
    b: Dict[int, float],
    key: str = "loss",
    rtol: float = 0.05,
    atol: float = 1e-9,
) -> Dict[str, Any]:
    """Per-step comparison of two scalar streams over their common steps.

    The bound is elementwise ``|a - b| <= atol + rtol * max(|a|, |b|)``
    (allclose semantics, symmetric in the arms).  Non-finite on BOTH
    sides at a step counts as agreement (both runs blew up identically);
    one-sided non-finiteness is divergence regardless of tolerance.
    """
    steps = sorted(set(a) & set(b))
    cmp: Dict[str, Any] = {
        "key": key, "rtol": rtol, "atol": atol,
        "n_a": len(a), "n_b": len(b), "n_common": len(steps),
    }
    if not steps:
        cmp.update(verdict="unknown", max_abs_delta=None, max_rel_delta=None)
        return cmp
    deltas: List[Tuple[int, float, float]] = []  # (step, abs delta, rel)
    n_mismatch = 0
    first_mismatch = None
    one_sided_nonfinite = False
    for s in steps:
        va, vb = a[s], b[s]
        fa, fb = math.isfinite(va), math.isfinite(vb)
        if not fa or not fb:
            if fa != fb:
                one_sided_nonfinite = True
                n_mismatch += 1
                if first_mismatch is None:
                    first_mismatch = s
                deltas.append((s, math.inf, math.inf))
            else:
                deltas.append((s, 0.0, 0.0))
            continue
        d = abs(va - vb)
        ref = max(abs(va), abs(vb))
        rel = d / ref if ref > 0 else (0.0 if d == 0 else math.inf)
        deltas.append((s, d, rel))
        if d > atol + rtol * ref:
            n_mismatch += 1
            if first_mismatch is None:
                first_mismatch = s
    finite_d = [d for _, d, _ in deltas if math.isfinite(d)]
    finite_r = [r for _, _, r in deltas if math.isfinite(r)]
    cmp["max_abs_delta"] = max(finite_d) if finite_d else math.inf
    cmp["mean_abs_delta"] = (
        sum(finite_d) / len(finite_d) if finite_d else math.inf)
    cmp["max_rel_delta"] = max(finite_r) if finite_r else math.inf
    cmp["n_mismatch"] = n_mismatch
    cmp["first_mismatch_step"] = first_mismatch
    stride = max(1, len(deltas) // 64)
    cmp["drift_curve"] = [
        {"step": s, "delta": d if math.isfinite(d) else None,
         "rel": r if math.isfinite(r) else None}
        for s, d, r in deltas[::stride]]
    if one_sided_nonfinite or n_mismatch:
        cmp["verdict"] = "diverged"
    elif all(d == 0.0 for _, d, _ in deltas):
        cmp["verdict"] = "exact"
    else:
        cmp["verdict"] = "bounded"
    return cmp


def param_divergence(params_a: Any, params_b: Any) -> Dict[str, Any]:
    """Per-leaf L2 distance between two (same-structure) param trees.

    Host-side — fetches both trees.  Returns ``{per_leaf: [{path, norm_a,
    norm_b, diff_norm, rel}], global: {diff_norm, rel}}`` sorted by
    descending relative drift, so the first row answers "which layer
    moved".
    """
    import jax
    import numpy as np

    flat_a = jax.tree_util.tree_flatten_with_path(params_a)[0]
    flat_b = jax.tree_util.tree_leaves(params_b)
    if len(flat_a) != len(flat_b):
        raise ValueError(
            f"param trees differ in structure: {len(flat_a)} vs "
            f"{len(flat_b)} leaves")
    rows: List[Dict[str, Any]] = []
    sq_diff = sq_a = 0.0
    for (path, la), lb in zip(flat_a, flat_b):
        xa = np.asarray(jax.device_get(la), dtype=np.float64)
        xb = np.asarray(jax.device_get(lb), dtype=np.float64)
        na = float(np.linalg.norm(xa))
        nb = float(np.linalg.norm(xb))
        nd = float(np.linalg.norm(xa - xb))
        sq_diff += nd * nd
        sq_a += na * na
        rows.append({
            "path": jax.tree_util.keystr(path),
            "norm_a": na, "norm_b": nb, "diff_norm": nd,
            "rel": nd / na if na > 0 else (0.0 if nd == 0 else math.inf),
        })
    rows.sort(key=lambda r: -r["rel"])
    g = math.sqrt(sq_diff)
    ga = math.sqrt(sq_a)
    return {
        "per_leaf": rows,
        "global": {
            "diff_norm": g,
            "rel": g / ga if ga > 0 else (0.0 if g == 0 else math.inf),
        },
    }


def parity_section(
    streams: Sequence[Dict[str, Any]] = (),
    params: Optional[Dict[str, Any]] = None,
    labels: Tuple[str, str] = ("a", "b"),
    extra: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Roll stream comparisons (+ optional :func:`param_divergence`) into
    the RUNREPORT ``numerics.parity`` sub-section.  The section verdict is
    the WORST stream verdict (diverged > bounded > exact > unknown with
    unknown only when nothing compared)."""
    order = {"diverged": 3, "bounded": 2, "exact": 1, "unknown": 0}
    worst = "unknown"
    for c in streams:
        v = c.get("verdict", "unknown")
        if order.get(v, 0) > order.get(worst, 0):
            worst = v
    section: Dict[str, Any] = {
        "schema": PARITY_SCHEMA,
        "labels": list(labels),
        "verdict": worst,
        "streams": [dict(c) for c in streams],
    }
    if params is not None:
        section["params"] = {
            "global": dict(params.get("global", {})),
            # the artifact keeps the 8 worst leaves; the full table is a
            # tool-side (parity_diff) rendering concern
            "per_leaf": [dict(r) for r in params.get("per_leaf", [])[:8]],
            "n_leaves": len(params.get("per_leaf", [])),
        }
    if extra:
        section.update(extra)
    return section
