"""Multi-host bootstrap — analogue of ``setup_distributed``
(``torchdistpackage/dist/launch_from_slurm.py:16-62``).

The reference reads SLURM (or torchrun) env vars, resolves the master address
via ``scontrol`` and calls ``dist.init_process_group``.  On TPU the rendezvous
is ``jax.distributed.initialize``; on Cloud TPU pods it normally needs *no*
arguments (the TPU runtime supplies topology), while SLURM CPU/GPU clusters
need explicit coordinator/process info — we support both, plus a no-op
single-process path so the same script runs anywhere.
"""

from __future__ import annotations

import os
import socket
import subprocess
from typing import Optional

import jax

_INITIALIZED = False


def find_free_port() -> int:
    """Pick an OS-assigned free port (launch_from_slurm.py:8-13 analogue)."""
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _slurm_master_addr(nodelist: str) -> str:
    # The reference shells out to ``scontrol show hostname`` and takes the
    # first host (launch_from_slurm.py:34-35); same here, with a fallback for
    # simple "host1,host2" lists when scontrol is absent.
    try:
        out = subprocess.run(
            ["scontrol", "show", "hostname", nodelist],
            capture_output=True, text=True, check=True,
        ).stdout
        return out.split()[0]
    except (OSError, subprocess.CalledProcessError):
        # Expand a compressed list like "node[01-08],other" to its first host
        # ("node01") when scontrol is unavailable.
        first = nodelist.split(",")[0]
        if "[" in first:
            prefix, rng = first.split("[", 1)
            start = rng.rstrip("]").split("-")[0].split(",")[0]
            return prefix + start
        return first


def setup_distributed(port: Optional[int] = None) -> None:
    """Initialize the JAX distributed runtime from the environment.

    Resolution order (mirrors launch_from_slurm.py:29-55):

    1. SLURM: ``SLURM_PROCID`` / ``SLURM_NTASKS`` / ``SLURM_NODELIST``.
    2. torchrun-style: ``RANK`` / ``WORLD_SIZE`` / ``MASTER_ADDR`` / ``MASTER_PORT``.
    3. Cloud TPU pod: ``jax.distributed.initialize()`` with no args if the TPU
       runtime env is present (``TPU_WORKER_HOSTNAMES`` etc.).
    4. Single process: no-op.

    Safe to call twice (idempotent), unlike the reference.
    """
    global _INITIALIZED
    if _INITIALIZED:
        return

    env = os.environ
    if "SLURM_PROCID" in env and int(env.get("SLURM_NTASKS", "1")) > 1:
        rank = int(env["SLURM_PROCID"])
        world = int(env["SLURM_NTASKS"])
        addr = _slurm_master_addr(env["SLURM_NODELIST"])
        port = port or int(env.get("MASTER_PORT", "12345"))
        jax.distributed.initialize(
            coordinator_address=f"{addr}:{port}",
            num_processes=world,
            process_id=rank,
        )
    elif "RANK" in env and int(env.get("WORLD_SIZE", "1")) > 1:
        rank = int(env["RANK"])
        world = int(env["WORLD_SIZE"])
        addr = env.get("MASTER_ADDR", "127.0.0.1")
        port = port or int(env.get("MASTER_PORT", "12345"))
        jax.distributed.initialize(
            coordinator_address=f"{addr}:{port}",
            num_processes=world,
            process_id=rank,
        )
    elif (
        len(env.get("TPU_WORKER_HOSTNAMES", "").split(",")) > 1
        or "MEGASCALE_COORDINATOR_ADDRESS" in env
    ):
        jax.distributed.initialize()
    # else: single-process — nothing to do.
    _INITIALIZED = True
