"""Input pipeline helpers: host batches -> mesh-sharded device arrays with
double buffering.

The reference ships no input pipeline (its examples hand-roll
``DummyClsDataset`` tensors, SURVEY §4); on TPU the equivalent concern is
real: per-step ``device_put`` of the next batch should overlap with the
current step's compute, or the step time grows by the host->HBM transfer.
``prefetch_to_sharding`` keeps ``prefetch`` batches in flight — JAX's
``device_put`` is async, so enqueueing N+1's transfer before N's result is
consumed gives the overlap for free.
"""

from __future__ import annotations

import collections
import itertools
from typing import Any, Iterable, Iterator, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

PyTree = Any


def shard_batch(batch: PyTree, mesh: Mesh, spec: PyTree) -> PyTree:
    """Place one host batch on the mesh.  ``spec`` is either a single
    PartitionSpec applied to every leaf or a matching pytree of specs."""
    if isinstance(spec, P):
        sh = NamedSharding(mesh, spec)
        return jax.tree.map(lambda x: jax.device_put(x, sh), batch)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        batch,
        spec,
        is_leaf=lambda x: x is None,
    )


def prefetch_to_sharding(
    it: Iterable[PyTree],
    mesh: Mesh,
    spec: PyTree,
    prefetch: int = 2,
) -> Iterator[PyTree]:
    """Iterate device-resident batches, keeping ``prefetch`` transfers in
    flight ahead of the consumer (the TPU analogue of a pinned-memory
    prefetching dataloader).  ``prefetch=0`` degrades to plain per-step
    placement."""
    if prefetch <= 0:
        for b in it:
            yield shard_batch(b, mesh, spec)
        return
    it = iter(it)
    buf: collections.deque = collections.deque()
    for b in itertools.islice(it, prefetch):
        buf.append(shard_batch(b, mesh, spec))
    _end = object()  # unique sentinel: a None *batch* must not end the stream
    while buf:
        nxt = next(it, _end)
        if nxt is not _end:
            buf.append(shard_batch(nxt, mesh, spec))
        yield buf.popleft()


def global_batch_from_local(
    local_batch: PyTree, mesh: Mesh, spec: PyTree
) -> PyTree:
    """Assemble a GLOBAL jax.Array batch from each process's LOCAL shard —
    the multi-host input path (``jax.make_array_from_process_local_data``).

    On a multi-host mesh every process loads only the rows its own devices
    will consume (1/process_count of the global batch) and calls this with
    the same ``spec``; the result is a global array identical to what
    :func:`shard_batch` would produce from full-batch host data, without any
    host ever materializing the full batch.  Single-process (tests, one
    chip): degenerates to :func:`shard_batch` semantics exactly.

    The reference has no analogue — its DataLoader duty is delegated to
    torch DataLoader with a DistributedSampler per rank; this is the
    SPMD-global-array equivalent of that per-rank sharding.
    """
    import numpy as np

    def one(x, s):
        sh = NamedSharding(mesh, s if isinstance(s, P) else P())
        return jax.make_array_from_process_local_data(sh, np.asarray(x))

    if isinstance(spec, P):
        return jax.tree.map(lambda x: one(x, spec), local_batch)
    return jax.tree.map(
        one, local_batch, spec, is_leaf=lambda x: x is None
    )


def microbatch(batch: PyTree, num_microbatches: int) -> PyTree:
    """Reshape every leaf's leading dim B into [M, B/M] — the layout the
    pipelined losses consume (``gpt_pipeline_1f1b``'s [M, mbs, ...])."""

    def split(x):
        b = x.shape[0]
        if b % num_microbatches != 0:
            raise ValueError(
                f"batch dim {b} not divisible by num_microbatches {num_microbatches}"
            )
        return x.reshape(num_microbatches, b // num_microbatches, *x.shape[1:])

    return jax.tree.map(split, batch)
