"""Heterogeneous stage activations for the SPMD pipeline — the analogue of
the reference's shape-meta handshake
(``torchdistpackage/parallel/pipeline_parallel/comm.py:26-105``), which lets
adjacent stages exchange tensors of different shapes/dtypes by sending a
(ndim, shape, dtype) preamble before every payload.

Under XLA the exchange is a ``ppermute`` inside one traced program, so the
carried state must have ONE static aval — a runtime shape handshake cannot
exist.  What CAN exist is the same capability expressed statically: the
inter-stage state becomes a flat **bus** sized to the largest edge, every
stage packs/unpacks its true activation to/from the bus, and the per-stage
computation dispatches through ``lax.switch`` on the stage index (every
branch has the bus aval in and out, so the program stays uniform).  The
shape contract the reference checks at runtime (stage s's output must be
what stage s+1 expects) is validated here at TRACE time, which is strictly
earlier.

Costs and constraints, stated honestly:

- wire + ring-buffer bytes are the LARGEST edge's, not each edge's own
  (padding rides the ppermute; the reference sends exact sizes).
- padding is provably inert: ``unpack`` reads only the leading
  ``size`` elements, so pad lanes never influence the forward, and the
  ``pad`` transpose discards their cotangents.
- stage fns must be collective-free (no TP/CP psums inside): the switch
  branches are pipe-divergent, and a collective inside divergent control
  flow is undefined (same rule pipeline_sched.py's scan body documents).
  This matches the reference's capability, whose heterogeneous stages are
  plain per-stage modules.
"""

from __future__ import annotations

from typing import Any, Callable, List, Sequence

import jax

from ...compat import axis_size
import jax.numpy as jnp

from ...dist.topology import PIPE_AXIS

PyTree = Any


def _aval(x) -> jax.ShapeDtypeStruct:
    return jax.ShapeDtypeStruct(jnp.shape(x), jnp.result_type(x))


def _bus_aval(edges: Sequence[jax.ShapeDtypeStruct]) -> jax.ShapeDtypeStruct:
    size = max(int(jnp.prod(jnp.array(e.shape)) if e.shape else 1) for e in edges)
    dtype = jnp.result_type(*[e.dtype for e in edges])
    # an integer edge promoted onto a float bus would silently corrupt
    # values past the float's integer-exact range (int32 id >= 2^24 through
    # an f32 bus) — refuse the mix instead
    for e in edges:
        if jnp.issubdtype(e.dtype, jnp.integer) != jnp.issubdtype(dtype, jnp.integer):
            raise ValueError(
                f"bus dtype {dtype} cannot carry edge dtype {e.dtype} "
                f"exactly: integer and float edges cannot share one bus — "
                f"use a uniform edge dtype (or cast inside the stage fns)"
            )
    return jax.ShapeDtypeStruct((size,), dtype)


def bus_pack(x: jnp.ndarray, bus: jax.ShapeDtypeStruct) -> jnp.ndarray:
    """Flatten ``x`` into the leading elements of a bus-shaped vector."""
    flat = x.reshape(-1).astype(bus.dtype)
    pad = bus.shape[0] - flat.shape[0]
    if pad < 0:
        raise ValueError(f"edge {x.shape} exceeds the bus ({bus.shape[0]})")
    return jnp.pad(flat, (0, pad)) if pad else flat


def bus_unpack(bus_val: jnp.ndarray, edge: jax.ShapeDtypeStruct) -> jnp.ndarray:
    """Recover the true activation of ``edge`` from the bus vector."""
    size = 1
    for s in edge.shape:
        size *= s
    return bus_val[:size].reshape(edge.shape).astype(edge.dtype)


def make_heterogeneous_stage(
    stage_fns: List[Callable],
    edges: Sequence,
    pipe_axis: str = PIPE_AXIS,
):
    """Adapt P HETEROGENEOUS stage functions to ``pipeline_1f1b``'s
    uniform-state contract.

    ``stage_fns[s]``: ``(params, x, m) -> y`` where ``x`` has the aval of
    ``edges[s]`` and ``y`` the aval of ``edges[s+1]`` (``m`` is the
    microbatch index — pass ``stage_takes_mb=True`` to the scheduler).
    ``edges``: P+1 avals (arrays or ShapeDtypeStructs): ``edges[0]`` is
    ``first_fn``'s output, ``edges[s]`` the stage-s input, ``edges[P]``
    the last stage's output (what ``last_fn`` receives).

    Returns ``(wrap_first, stage_fn, wrap_last)``:

    - ``wrap_first(first_fn)``: first_fn's ``edges[0]`` output packed onto
      the bus;
    - ``stage_fn(params, bus, m)``: ``lax.switch`` on the stage index —
      branch s unpacks ``edges[s]``, runs ``stage_fns[s]``, packs
      ``edges[s+1]``; the output aval is verified against ``edges[s+1]``
      at trace time (the handshake, moved to trace time);
    - ``wrap_last(last_fn)``: ``last_fn(params, y, tgt)`` receives the
      unpacked ``edges[P]`` activation.
    """
    edges = [_aval(e) if not isinstance(e, jax.ShapeDtypeStruct) else e
             for e in edges]
    if len(stage_fns) != len(edges) - 1:
        raise ValueError(
            f"{len(stage_fns)} stage fns need {len(stage_fns) + 1} edge "
            f"avals, got {len(edges)}"
        )
    bus = _bus_aval(edges)
    P_ = len(stage_fns)

    def _branch(s):
        def run(params, bus_val, m):
            x = bus_unpack(bus_val, edges[s])
            y = stage_fns[s](params, x, m)
            got = _aval(y)
            want = edges[s + 1]
            if got.shape != want.shape or got.dtype != want.dtype:
                raise ValueError(
                    f"stage {s} produced {got.shape}/{got.dtype}, but stage "
                    f"{s + 1} expects {want.shape}/{want.dtype} — the edge "
                    f"contract (edges[{s + 1}]) is violated"
                )
            return bus_pack(y, bus)

        return run

    branches = [_branch(s) for s in range(P_)]

    def stage_fn(params, bus_val, m):
        n = axis_size(pipe_axis)  # static inside shard_map
        if n != P_:
            # without this, lax.switch CLAMPS the stage index: extra
            # stages silently re-run the last branch / missing stages never
            # run, and every bus aval matches so no shape error ever fires
            raise ValueError(
                f"{P_} heterogeneous stage fns on a {n}-rank "
                f"{pipe_axis!r} axis — one fn per stage is required"
            )
        s = jax.lax.axis_index(pipe_axis)
        return jax.lax.switch(s, branches, params, bus_val, m)

    def wrap_first(first_fn):
        def first(params, mb):
            out = first_fn(params, mb)
            got = _aval(out)
            if got.shape != edges[0].shape or got.dtype != edges[0].dtype:
                raise ValueError(
                    f"first_fn produced {got.shape}/{got.dtype}, expected "
                    f"edges[0] = {edges[0].shape}/{edges[0].dtype}"
                )
            return bus_pack(out, bus)

        return first

    def wrap_last(last_fn):
        def last(params, bus_val, tgt):
            return last_fn(params, bus_unpack(bus_val, edges[-1]), tgt)

        return last

    return wrap_first, stage_fn, wrap_last
