"""Benchmark: flagship GPT training throughput (tokens/sec/chip).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "config",
"chip", "mfu", "peak_flops_est"}.

On TPU: a GPT-125M-class model at seq 2048, bf16 matmuls, full train step
(fwd+bwd+adamw) on the available chip(s) (single-chip DP mesh when only
one), PLUS a best-effort ~1B-param config (``--big``: d2048/L16, remat +
streamed CE — the north-star direction) measured in its own child first so
the 1b line precedes the headline 125m line.  Set ``BENCH_BIG=0`` to skip.
On CPU (no TPU attached): a tiny config so the harness still produces a line.

Baseline policy (BASELINE.md "first measurement wins" + VERDICT r2 item 2):
``BENCH_BASELINE.json`` stores one record per **(backend, config)** — a new
config NEVER overwrites another config's record — and ``vs_baseline`` is
computed against the BEST value recorded for the backend, so switching to a
slower config reports < 1.0 instead of silently re-basing.

MFU: model FLOPs/token = 6·N_params + 12·L·S·D (PaLM-style accounting:
6N for the dense matmuls fwd+bwd, 12·L·S·D for the attention score/value
matmuls; remat recompute is hardware overhead and deliberately NOT counted —
MFU is model FLOPs over peak). Peak bf16 FLOP/s looked up by device_kind
(table shared with the obs subsystem: ``obs.peak_flops_for``).  The line
ALSO carries the obs-derived cross-check from XLA ``cost_analysis`` of the
compiled step (``mfu_xla``, ``flops_per_token_xla``,
``mfu_xla_vs_formula_rel``): compiler-counted FLOPs include non-matmul ops
and remat recompute, so xla >= formula and a small positive rel diff is
expected; a LARGE one is printed to stderr, never hidden.

A/B mode: ``python bench.py --ab`` runs the candidate
(batch, remat, xent_chunk) configs ONE CHILD PROCESS EACH (fresh backend per
candidate — an OOM/hang in one config cannot abort the others, and there is
no allocator-fragmentation carry-over), printing one JSON line per config
plus a "winner" line, and recording each config's first measurement in the
baselines file. Use this to choose the default config honestly.

Overlap A/B: ``python bench.py --overlap on`` applies the latency-hiding
XLA preset (``dist/overlap.py``, validated against the local jaxlib)
inside the measurement child before backend init; ``--overlap off`` runs
the identical config untouched.  Both rows carry the same ``config_hash``
(the pairing key), an ``overlap`` field naming the arm, and the compiled
step's HLO async evidence (``overlap_async_ops``,
``overlap_async_bytes_fraction``, ``overlap_mean_sched_distance`` from the
comm ledger) so the A/B proves WHERE the win comes from, not just that it
exists.  See docs/overlap.md.

Hang-proof structure: the accelerator backend behind the axon tunnel can
HANG at init (not just raise — observed: ``jax.devices()`` blocking >400 s),
so the parent process never touches JAX.  Before paying for a full
measurement child it first runs a cheap ``--probe`` child (imports jax,
touches the device list, ``BENCH_PROBE_TIMEOUT`` default 90 s) and retries
the probe ``BENCH_PROBE_ATTEMPTS`` times (default 4) with
``BENCH_PROBE_DELAY`` (default 30 s) between attempts — several short shots
across the run instead of one 900 s gamble against a flaky tunnel.  Only a
successful probe launches the measurement child
(``BENCH_ACCEL_TIMEOUT``, default 900 s).  A probe that ANSWERS with
backend cpu short-circuits the retries — that is a CPU-only host, not a
flaky tunnel.  When no accelerator is reachable, the harness re-runs
pinned to CPU (``BENCH_CPU_TIMEOUT``, default 600 s) AND — because a CPU
number says nothing about the TPU record — finishes with the last-good
accelerator record from ``BENCH_BASELINE.json`` carrying an explicit
``"stale": true`` + its original measurement date and a reason that
distinguishes init hangs / measurement failures / CPU-only hosts, so the
driver artifact preserves the accelerator history instead of a bare CPU
line.
If everything fails it still prints the JSON line with an ``error`` field.
Run with ``--measure`` to execute the measurement directly in-process.
"""

import dataclasses
import functools
import json
import os
import subprocess
import sys
import time

# (batch_per_chip, remat, xent_chunk) A/B candidates on the accelerator;
# module scope so the parent's --ab timeout scales with the same list the
# child runs.  The default single-config run uses the first entry — keep it
# set to the A/B winner (docs/BENCH_AB.md).  xent_chunk streams the head+CE
# over sequence chunks (gpt_loss(xent_chunk=...)) instead of materializing
# the ~2 GB [B, S, V] logits.
TPU_CANDIDATES = [
    (16, "flash", None),
    (16, True, None),
    (8, False, None),
]

# ~1B-param candidates (--big): the north-star direction (BASELINE.json
# targets a 7B mixed-parallel model; a 125M single-chip record must not be
# the framework's ceiling).  d2048/L16/seq2048 ≈ 0.94B params; remat +
# streamed CE are mandatory at this size on a 16 GB chip.  Larger d
# amortizes the non-matmul fraction, so MFU should EXCEED the 125M
# config's (target >= 0.45).
BIG_CANDIDATES = [
    (4, "flash", 256),
    (8, "flash", 256),
    (4, True, 256),
    (8, True, 256),
    # residuals offloaded to pinned_host: HBM cost of the 'flash' policy
    # drops to ~one block in flight — candidate for batches that OOM in
    # plain 'flash' mode (untested on-chip until the tunnel returns)
    (16, "flash_offload", 256),
]

# Long-context candidates (--long): the 125M model at seq 8192 — the
# single-chip long-S story (CP spreads S across chips; this measures the
# per-chip leaf: flash tiles at long S + remat='flash' + streamed CE).
# (1024, 1024) tiles measured fastest at EVERY v5e shape including S=8192
# (docs/FLASH_TUNE_v5e.json, 4 reports).  Measured 2026-07-31: b2 flash
# 54,868 tok/s (MFU 0.437) beats b4 52,208 and b2 flash_offload 45,704
# (the offload is a memory lever; it costs host-DMA bandwidth when the
# shape fits in HBM — docs/BENCH_AB.md session 5).
LONG_CANDIDATES = [
    (2, "flash", 512),
    (4, "flash", 512),
    (2, "flash_offload", 512),
]
# MoE candidates (--moe): GPT-MoE on one chip (EP=1 — expert compute is
# local; this measures the ROUTING + DISPATCH + expert-FFN leaf the EP
# all_to_all wraps at scale).  4-tuples: (batch, remat, xent_chunk,
# dispatch).  Measured 2026-07-31 (docs/BENCH_AB.md): b8 sorted 66,636
# tok/s (MFU 0.358 activated) wins; sorted beats dense 10.2% at the
# identical b2 config.  Dense at b>=4 is untestable (the [T, E, C]
# one-hots alone exceed HBM).  PR 18 adds the fused Pallas dispatch
# ('pallas': gather -> expert FFN -> weighted scatter in one kernel, no
# [E, C, D] slot view in HBM — ops/moe_dispatch.py) as a paired arm
# against the sorted incumbent; on-chip numbers pending the tunnel.
MOE_CANDIDATES = [
    (8, "flash", None, "sorted"),
    (8, "flash", None, "pallas"),
    (16, "flash", None, "sorted"),
    (2, "flash", None, "sorted"),
    (2, "flash", None, "dense"),
]

# Retired candidates (recorded in BENCH_BASELINE.json / docs/BENCH_AB.md):
# (32, True, None) 22,263 collapses (spills); (16, False, 256) OOMs —
# streamed CE removes the logits but b16 no-remat still saves every block
# activation (12 x [16, 2048, 768] bf16 + per-head tensors), which exhausts
# v5e HBM.  Session-4 (2026-07-31) on-chip results: post-tile-tune,
# b16+remat (85,299) beat b8 no-remat (82,765); remat='flash' (save the
# flash kernel's o/lse so the backward skips its fwd re-run) pushed b16 to
# 89,815 — the current record and headline default.  Larger flash-remat
# batches lost ground (b24 87,127; b32+ce256 85,618): past b16 the extra
# arithmetic intensity no longer covers the saved-activation traffic.
# ce256 variants cost ~2% at 125M and stay retired from the sweep (the
# streamed CE is a memory lever, not a throughput one).

def _peak_flops(device_kind: str):
    # the lookup table lives in obs.telemetry (one source for the repo);
    # only measurement children call this, so the import stays out of the
    # jax-free parent process
    from torchdistpackage_tpu.obs import peak_flops_for

    return peak_flops_for(device_kind)


def _only_index(argv):
    """--only N: restrict an --ab child to candidate N (one child per
    candidate keeps an OOM in one config from aborting the others)."""
    for i, a in enumerate(argv):
        if a == "--only" and i + 1 < len(argv):
            return int(argv[i + 1])
    return None


def _flag_value(argv, flag):
    """Value of ``--flag path`` style args (None when absent)."""
    for i, a in enumerate(argv):
        if a == flag and i + 1 < len(argv):
            return argv[i + 1]
    return None


def _measure() -> None:
    import jax

    # honor JAX_PLATFORMS even when a sitecustomize force-registered another
    # backend (matches tests/conftest.py and __graft_entry__.py)
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    # --overlap on: apply the latency-hiding XLA preset BEFORE the first
    # device touch (flags are parsed at backend init; dist/overlap.py
    # validates them against this jaxlib and drops what it rejects).
    # --overlap off runs the identical config with no flag changes — the
    # paired A/B row.
    ov = _flag_value(sys.argv, "--overlap")
    if ov not in (None, "on", "off"):
        raise SystemExit(f"--overlap must be 'on' or 'off', got {ov!r}")
    if ov == "on":
        from torchdistpackage_tpu.dist import overlap as _overlap

        _overlap.configure(preset="auto")
    # --grad-compress {off,int8,auto}: run the step through a DataParallel
    # mesh so the grad reduction is an explicit, ledgered collective (the
    # A/B's comm_bytes_per_dim delta is the headline).  On an explicit
    # JAX_PLATFORMS=cpu run there is only one device and no collective to
    # measure — bootstrap the 8-device sim (must precede backend init).
    gc = _flag_value(sys.argv, "--grad-compress")
    if gc not in (None, "off", "int8", "auto"):
        raise SystemExit(
            f"--grad-compress must be 'off', 'int8' or 'auto', got {gc!r}")
    # --autoplan: plan the parallelism from the three cost models
    # (dist/autoplan.py) and run the chosen plan against the hand-picked
    # default at equal config_hash.  Like --grad-compress, an explicit
    # JAX_PLATFORMS=cpu run bootstraps the 8-device sim so there is a
    # mesh to plan over.
    autoplan = "--autoplan" in sys.argv
    if (gc or autoplan) and os.environ.get("JAX_PLATFORMS") == "cpu":
        from torchdistpackage_tpu.dist.overlap import cpu_sim

        cpu_sim(8)
    import jax.numpy as jnp

    main(jax, jnp, ab="--ab" in sys.argv, only=_only_index(sys.argv),
         big="--big" in sys.argv, long="--long" in sys.argv,
         moe="--moe" in sys.argv, trace=_flag_value(sys.argv, "--trace"),
         overlap=ov, grad_compress=gc, autoplan=autoplan)


def _load_baselines(path: str) -> dict:
    """{backend: {config_str: record}} with migration from the two legacy
    layouts (flat record; {backend: record})."""
    try:
        with open(path) as f:
            raw = json.load(f)
    except (OSError, ValueError):
        return {}
    if "backend" in raw and "value" in raw:  # oldest: one flat record
        raw = {raw["backend"]: raw}
    out = {}
    for backend, rec in raw.items():
        if isinstance(rec, dict) and "value" in rec:  # legacy: one per backend
            out[backend] = {rec.get("config", "?"): rec}
        else:
            out[backend] = dict(rec)
    return out


def _best_recorded(baselines: dict, backend: str, fallback: float,
                   metric: str = None) -> float:
    """The BEST value recorded for ``backend`` across configs OF THE SAME
    metric (size class) — the vs_baseline denominator.  A config switch can
    never re-base history, but different model sizes are different series:
    the 1b config must not report vs_baseline ~0.1 merely because a 125m
    record exists."""
    return max(
        (
            r["value"]
            for r in baselines.get(backend, {}).values()
            # records predating metric stamping match NO scoped query — a
            # legacy 125m record must not pollute the 1b denominator
            if metric is None or r.get("metric") == metric
        ),
        default=fallback,
    )


def _record_baseline(baselines: dict, path: str, backend: str, config: str,
                     value: float, chip: str = "?",
                     metric: str = "gpt-train-throughput") -> None:
    """First measurement of (backend, config) wins; later runs never touch it."""
    per_cfg = baselines.setdefault(backend, {})
    if config not in per_cfg:
        per_cfg[config] = {
            "backend": backend, "value": value,
            "unit": "tokens/sec/chip", "config": config,
            "recorded": time.strftime("%Y-%m-%d"),
            "chip": chip, "metric": metric,
        }
        try:
            with open(path, "w") as f:
                json.dump(baselines, f, indent=1)
        except OSError:
            pass  # read-only checkout: keep reporting, skip recording


def _last_good_accel_line(baselines: dict, reason: str = "unreachable"):
    """The best non-CPU record across configs, reshaped into a bench line
    with an explicit staleness marker — emitted when the accelerator can't
    produce a fresh number this run so the driver artifact carries the
    accelerator history honestly instead of only a CPU number.  ``reason``
    states what actually failed (init probes vs the measurement itself) so
    the artifact never misattributes a regression to tunnel flakiness."""
    best = None
    for backend, per_cfg in baselines.items():
        if backend == "cpu":
            continue
        for rec in per_cfg.values():
            if best is None or rec["value"] > best["value"]:
                best = rec
    if best is None:
        return None
    return {
        "metric": best.get("metric", "gpt-125m-train-throughput"),
        "value": round(best["value"], 2),
        "unit": best.get("unit", "tokens/sec/chip"),
        "vs_baseline": 1.0,
        "config": best.get("config", "?"),
        "chip": best.get("chip", best.get("backend", "accel")),
        "stale": True,
        "measured_this_run": False,
        "recorded": best.get("recorded", "unknown"),
        "stale_reason": f"{reason}; last-good record shown",
    }


def _run_config(jax, jnp, cfg, batch_size, steps, warmup, remat, xent_chunk=None,
                trace=None, grad_compress=None):
    """One timed measurement; returns (tokens_per_sec_chip, global_batch,
    flops_per_token, xla_flops_per_token, comm_ledger, mem).

    ``mem`` carries the run's memory AND numerics evidence columns merged
    straight onto the JSON line: ``peak_hbm_bytes`` (max per-device
    measured peak) and ``mem_headroom_frac`` (1 - peak/capacity on the
    hottest device) when the backend reports memory stats, plus
    ``mem_modeled_peak_bytes`` from the compiled step's static buffer
    ledger ({} on the CPU sim); ``grad_norm_final`` — the global grad
    norm of the LAST timed step, computed inside the same compiled
    program (obs.numerics.global_grad_norm, shared with clip) so a bench
    round also certifies the math was alive, not just fast; and
    ``dtype_flop_frac`` — the compiled step's matmul-FLOP mix per dtype
    from the HLO dtype ledger (bf16 vs f32 vs int8 — the precision
    evidence, printed as a table on stderr).

    ``comm_ledger`` is the HLO collective ledger of the compiled step
    (``obs.comm_ledger``) — None when AOT compilation was unavailable.
    ``trace``: path — after the timed loop, re-run a few steps under
    ``obs.Telemetry`` and export the Perfetto host trace there (costs one
    extra AOT compile; opt-in).

    ``xla_flops_per_token`` comes from XLA ``cost_analysis`` of the
    *compiled* step (obs.compiled_cost — compiler ground truth, per
    device), vs the 6N+12LSD hand formula of ``flops_per_token``.  The two
    bracket the truth from opposite sides: XLA counts EVERYTHING it runs
    (non-matmul ops, optimizer, remat recompute), the hand formula counts
    model matmul FLOPs only — so XLA >= formula, with the gap widening
    under remat.  None when the backend reports no cost analysis."""
    import optax

    from torchdistpackage_tpu.models import gpt_loss, init_gpt_params

    if cfg.moe_experts:
        from torchdistpackage_tpu.models import gpt_moe_loss, init_gpt_moe_params

        params = init_gpt_moe_params(jax.random.PRNGKey(0), cfg)

        def loss_fn(p, batch):
            return gpt_moe_loss(p, batch, cfg, remat=remat)

    else:
        params = init_gpt_params(jax.random.PRNGKey(0), cfg)

        def loss_fn(p, batch):
            return gpt_loss(p, batch, cfg, remat=remat, xent_chunk=xent_chunk)

    opt = optax.adamw(3e-4)
    state = opt.init(params)

    # DP mesh over all attached chips so per-chip throughput is honest on
    # multi-chip hosts: params replicated, batch sharded on its leading dim.
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    n_chips = max(1, jax.device_count())
    mesh = Mesh(jax.devices(), axis_names=("data",))
    replicated = NamedSharding(mesh, P())
    batch_sharded = NamedSharding(mesh, P("data"))
    params = jax.device_put(params, replicated)
    state = jax.device_put(state, replicated)

    # 6N counts only matmul params: tok_emb/pos_emb forwards are gather/add
    # (backward scatter-add), never executed as matmuls — counting them would
    # inflate MFU ~15% at this vocab size (the head matmul params DO count).
    # MoE: experts count at top_k/E — each token's FLOPs touch only its
    # routed experts (the standard sparse-MFU accounting); router counts in
    # full.
    n_matmul_params = 0
    for k, sub in params.items():
        if k in ("tok_emb", "pos_emb"):
            continue
        if k == "blocks" and isinstance(sub, list):  # MoE heterogeneous list
            for bp in sub:
                for name, leafset in bp.items():
                    if name == "moe":
                        ex = sum(l.size for l in jax.tree.leaves(leafset["experts"]))
                        n_matmul_params += leafset["router"]["w"].size
                        n_matmul_params += ex * cfg.moe_top_k // cfg.moe_experts
                    else:
                        n_matmul_params += sum(
                            l.size for l in jax.tree.leaves(leafset))
        else:
            n_matmul_params += sum(l.size for l in jax.tree.leaves(sub))
    flops_per_token = (
        6 * n_matmul_params + 12 * cfg.nlayers * cfg.max_seq * cfg.dim
    )

    # donate params/opt-state: relaxes buffer lifetimes so XLA updates in
    # place instead of holding input AND output copies of ~1.6 GB of
    # params+moments — a pure lifetime annotation, no semantic change
    from torchdistpackage_tpu.obs.numerics import global_grad_norm

    if grad_compress is not None:
        # --grad-compress arm: the step runs through DataParallel so the
        # grad reduction is an EXPLICIT shard_map collective the ledger
        # can attribute (the plain-jit replicated step has no dp
        # collective to compress).  'off' takes the identical DP path
        # with the exact pmean — the paired baseline.  compress_min_size
        # is lowered so the tiny CPU-sim config's leaves qualify.
        from torchdistpackage_tpu.parallel.data_parallel import DataParallel

        dp = DataParallel(
            mesh=mesh,
            grad_compress=None if grad_compress == "off" else grad_compress,
            compress_min_size=4096,
        )
        step = dp.make_train_step(loss_fn, opt, numerics=True)
    else:
        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def step(params, state, batch):
            loss, grads = jax.value_and_grad(loss_fn)(params, batch)
            # numerics evidence rides in the same program: one extra scalar
            gnorm = global_grad_norm(grads)
            updates, state = opt.update(grads, state, params)
            return jax.tree.map(jnp.add, params, updates), state, loss, gnorm

    k1, k2 = jax.random.split(jax.random.PRNGKey(1))
    global_batch = batch_size * n_chips
    batch = {
        "tokens": jax.random.randint(k1, (global_batch, cfg.max_seq), 0, cfg.vocab_size),
        "targets": jax.random.randint(k2, (global_batch, cfg.max_seq), 0, cfg.vocab_size),
    }
    batch = jax.device_put(batch, batch_sharded)

    # AOT-compile so XLA's own cost analysis of the EXACT program being
    # timed is captured (no second trace/compile: the compiled executable
    # is what the loop runs).  Per-device FLOPs -> per-token via the
    # per-chip token count.
    from torchdistpackage_tpu.obs import compiled_cost, ledger_from_compiled
    from torchdistpackage_tpu.obs import mem_ledger as _mem
    from torchdistpackage_tpu.obs import numerics as _numerics

    xla_flops_per_token = None
    ledger = None
    mem_led = None
    dtype_led = None
    run_step = step
    try:
        compiled = step.lower(params, state, batch).compile()
        cost = compiled_cost(compiled)
        if cost.get("flops"):
            xla_flops_per_token = cost["flops"] / (
                global_batch * cfg.max_seq / n_chips)
        # the same no-second-compile hook feeds the comm ledger: which
        # collectives the step runs, over which axes, moving which bytes
        ledger = ledger_from_compiled(compiled, mesh=mesh)
        # ... the static memory ledger (args/temps/donation savings) ...
        mem_led = _mem.static_ledger(compiled, label="train_step")
        # ... and the per-dtype HLO ledger (bf16 vs f32 vs int8 mix)
        dtype_led = _numerics.dtype_ledger_from_compiled(
            compiled, label="train_step")
        run_step = compiled
    except Exception as e:
        print(f"bench: AOT compile/cost-analysis unavailable ({e!r}); "
              f"falling back to the jit cache", file=sys.stderr)

    # NB: sync via host transfer (float(loss)), NOT block_until_ready — over
    # the axon TPU tunnel block_until_ready can return before execution
    # completes, which makes timings fictitious.  The steps form a data
    # dependency chain (params feed the next step), so fetching the final
    # loss bounds the whole run.
    for _ in range(warmup):
        params, state, loss, gnorm = run_step(params, state, batch)
    float(loss)

    t0 = time.perf_counter()
    for _ in range(steps):
        params, state, loss, gnorm = run_step(params, state, batch)
    float(loss)
    dt = time.perf_counter() - t0
    # the DP (--grad-compress) step returns the fused numerics-stats dict
    # in the gnorm slot; the plain step returns the bare scalar
    grad_norm_final = float(
        gnorm["grad_norm"] if isinstance(gnorm, dict) else gnorm)

    if trace:
        # opt-in Perfetto host trace of the SAME step: a short
        # Telemetry-wrapped run after the timed loop (separate so the
        # wrapper's bookkeeping can't pollute the measurement)
        try:
            from torchdistpackage_tpu.obs import Telemetry, export_trace

            tel = Telemetry(run="bench", tokens_per_step=global_batch * cfg.max_seq,
                            report_path="", trace_path="", mesh=mesh,
                            poll_memory=False)
            tstep = tel.wrap_step(step)
            for i in range(3):
                params, state, loss, gnorm = tstep(params, state, batch)
                tel.end_step(step=i, loss=loss, grad_norm=gnorm)
            tel.finalize(write=False, print_summary=False)
            export_trace(tel, trace)
            print(f"bench: wrote Perfetto trace to {trace}", file=sys.stderr)
        except Exception as e:
            print(f"bench: trace export failed ({e!r})", file=sys.stderr)

    # memory evidence for the JSON line: measured per-device peak +
    # headroom against capacity (the number that decides whether a bigger
    # batch even runs), modeled static peak alongside
    mem = {}
    live = _mem.live_memory()
    if live["reported"]:
        mem["peak_hbm_bytes"] = max(
            r["peak_bytes_in_use"] for r in live["per_device"])
        if live["peak_frac"]:
            mem["mem_headroom_frac"] = round(1.0 - live["peak_frac"], 4)
    if mem_led is not None:
        mem["mem_modeled_peak_bytes"] = mem_led["peak_estimate_bytes"]
        print(_mem.render_table(mem_led), file=sys.stderr)
    # numerics evidence: the final step's global grad norm (a NaN/0 here
    # means the measured throughput trained garbage) + the dtype FLOP mix
    mem["grad_norm_final"] = round(grad_norm_final, 6)
    if dtype_led is not None:
        if dtype_led.get("flop_frac"):
            mem["dtype_flop_frac"] = dtype_led["flop_frac"]
        print(_numerics.render_dtype_table(dtype_led), file=sys.stderr)

    return (global_batch * cfg.max_seq * steps / dt / n_chips, global_batch,
            flops_per_token, xla_flops_per_token, ledger, mem)


def _run_pp_plan_config(jax, jnp, cfg, chosen, batch_size, steps, warmup,
                        remat, microbatches=8, schedule="1f1b"):
    """Time a pp>1 plan (tokens/sec/chip, mean step seconds) through the
    PIPELINE runner: the plan's mesh + PartitionSpecs drive
    ``gpt_pipeline_1f1b`` (or ``gpt_pipeline_zb`` for ``schedule='zb'``)
    inside a ``DataParallel`` train step — the schedule the planner's pp
    compute term models is the schedule that runs, so pp plans are now
    *measured*, not just scored (the ROADMAP item-1 follow-up).  The
    batch rides ``[M, global_batch/M, S]`` with dim 1 sharded over
    ``data``; ``xent_chunk`` does not apply (the pipelined last stage
    streams per-microbatch already)."""
    import optax

    from jax.sharding import NamedSharding, PartitionSpec as P

    from torchdistpackage_tpu.dist import autoplan as _autoplan
    from torchdistpackage_tpu.models import (
        gpt_pipeline_1f1b, gpt_pipeline_zb, init_gpt_params)
    from torchdistpackage_tpu.parallel.data_parallel import DataParallel

    M = microbatches
    n_chips = max(1, jax.device_count())
    global_batch = batch_size * n_chips
    if global_batch % M or (global_batch // M) % chosen["dp"]:
        raise ValueError(
            f"pp runner needs microbatches ({M}) | global batch "
            f"({global_batch}) and dp ({chosen['dp']}) | per-microbatch "
            f"rows ({global_batch // M})")
    mesh = _autoplan.build_mesh(chosen)
    specs = _autoplan.plan_param_specs(chosen, cfg)
    params = init_gpt_params(jax.random.PRNGKey(0), cfg)
    tp_axis = "tensor" if chosen["tp"] > 1 else None
    sched_fn = gpt_pipeline_zb if schedule == "zb" else gpt_pipeline_1f1b

    def vg_fn(p, b):
        return sched_fn(p, b, cfg, num_microbatches=M, tp_axis=tp_axis,
                        sp=tp_axis is not None, remat=remat)

    opt = optax.adamw(3e-4)
    dp = DataParallel(mesh=mesh)
    sharded = dp.broadcast_params(params, param_specs=specs)
    state = opt.init(sharded)
    step = dp.make_train_step(
        value_and_grad_fn=vg_fn, optimizer=opt, param_specs=specs,
        batch_spec={"tokens": P(None, "data"), "targets": P(None, "data")})

    k1, k2 = jax.random.split(jax.random.PRNGKey(1))
    shape = (M, global_batch // M, cfg.max_seq)
    batch = jax.device_put({
        "tokens": jax.random.randint(k1, shape, 0, cfg.vocab_size),
        "targets": jax.random.randint(k2, shape, 0, cfg.vocab_size),
    }, NamedSharding(mesh, P(None, "data")))

    for _ in range(warmup):
        sharded, state, loss = step(sharded, state, batch)
    float(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        sharded, state, loss = step(sharded, state, batch)
    float(loss)
    dt = time.perf_counter() - t0
    return global_batch * cfg.max_seq * steps / dt / n_chips, dt / steps


def _run_moe_plan_config(jax, jnp, cfg, chosen, batch_size, steps, warmup,
                         remat):
    """Time a MoE plan (tokens/sec/chip, mean step seconds) through a
    GSPMD jit step: the plan's mesh (``data x ep x tensor``) with the
    REAL ``gpt_moe_param_specs`` tree from ``plan_param_specs`` (expert
    stacks sharded over ``ep``, router replicated) and the batch over
    ``("data", "ep")`` — XLA derives the dispatch all_to_all the ep
    sharding implies, which is exactly the collective the planner's
    ``moe-all-to-all`` term prices."""
    import optax

    from jax.sharding import NamedSharding, PartitionSpec as P

    from torchdistpackage_tpu.dist import autoplan as _autoplan
    from torchdistpackage_tpu.models import gpt_moe_loss, init_gpt_moe_params

    params = init_gpt_moe_params(jax.random.PRNGKey(0), cfg)

    def loss_fn(p, batch):
        return gpt_moe_loss(p, batch, cfg, remat=remat)

    opt = optax.adamw(3e-4)
    state = opt.init(params)
    mesh = _autoplan.build_mesh(chosen)
    n_chips = max(1, jax.device_count())
    specs = _autoplan.plan_param_specs(chosen, cfg)
    params = jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs,
        is_leaf=lambda x: x is None)
    state = jax.device_put(state, NamedSharding(mesh, P()))

    k1, k2 = jax.random.split(jax.random.PRNGKey(1))
    global_batch = batch_size * n_chips
    batch = jax.device_put({
        "tokens": jax.random.randint(
            k1, (global_batch, cfg.max_seq), 0, cfg.vocab_size),
        "targets": jax.random.randint(
            k2, (global_batch, cfg.max_seq), 0, cfg.vocab_size),
    }, NamedSharding(mesh, _autoplan.batch_partition_spec(chosen)))

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(params, state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, state = opt.update(grads, state, params)
        return jax.tree.map(jnp.add, params, updates), state, loss

    for _ in range(warmup):
        params, state, loss = step(params, state, batch)
    float(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        params, state, loss = step(params, state, batch)
    float(loss)
    dt = time.perf_counter() - t0
    return global_batch * cfg.max_seq * steps / dt / n_chips, dt / steps


def _run_plan_config(jax, jnp, cfg, chosen, batch_size, steps, warmup, remat,
                     xent_chunk=None, microbatches=8):
    """Time the planner-chosen plan (tokens/sec/chip) through the same
    model/batch/steps as :func:`_run_config`.  Four runners cover every
    executable plan (``dist.autoplan.enumerate_candidates(
    executable_only=True)``):

    - MoE configs -> :func:`_run_moe_plan_config` (GSPMD over the plan's
      ``data x ep x tensor`` mesh; MoE plans are always pp == 1, dp
      layout, uncompressed);
    - pure dp with grad compression -> ``DataParallel(grad_compress=
      'int8')`` (the int8 ring only exists on the shard_map path);
    - ``pp > 1`` -> the pipeline runner (:func:`_run_pp_plan_config`)
      driving the schedule the plan's ``pp_schedule`` names;
    - everything else (dp / fsdp / tp mixes) -> a GSPMD jit step over the
      plan's mesh with the plan's param PartitionSpecs — XLA derives the
      collectives the specs imply, which is exactly the layout the
      planner scored."""
    import optax

    if getattr(cfg, "moe_experts", 0):
        return _run_moe_plan_config(
            jax, jnp, cfg, chosen, batch_size, steps, warmup, remat)
    if chosen["pp"] > 1:
        return _run_pp_plan_config(
            jax, jnp, cfg, chosen, batch_size, steps, warmup, remat,
            microbatches=microbatches,
            schedule=chosen.get("pp_schedule") or "1f1b")

    from torchdistpackage_tpu.dist import autoplan as _autoplan
    from torchdistpackage_tpu.models import gpt_loss, init_gpt_params

    params = init_gpt_params(jax.random.PRNGKey(0), cfg)

    def loss_fn(p, batch):
        return gpt_loss(p, batch, cfg, remat=remat, xent_chunk=xent_chunk)

    opt = optax.adamw(3e-4)
    state = opt.init(params)

    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = _autoplan.build_mesh(chosen)
    n_chips = max(1, jax.device_count())
    specs = _autoplan.plan_param_specs(chosen, cfg)
    params = jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs,
        is_leaf=lambda x: x is None)
    state = jax.device_put(state, NamedSharding(mesh, P()))

    k1, k2 = jax.random.split(jax.random.PRNGKey(1))
    global_batch = batch_size * n_chips
    batch = jax.device_put({
        "tokens": jax.random.randint(
            k1, (global_batch, cfg.max_seq), 0, cfg.vocab_size),
        "targets": jax.random.randint(
            k2, (global_batch, cfg.max_seq), 0, cfg.vocab_size),
    }, NamedSharding(mesh, _autoplan.batch_partition_spec(chosen)))

    if (chosen["compress"]["grads"] and chosen["layout"] == "dp"
            and chosen["tp"] == 1):
        from torchdistpackage_tpu.parallel.data_parallel import DataParallel

        dp = DataParallel(mesh=mesh, grad_compress="int8",
                          compress_min_size=4096)
        step = dp.make_train_step(loss_fn, opt)
    else:
        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def step(params, state, batch):
            loss, grads = jax.value_and_grad(loss_fn)(params, batch)
            updates, state = opt.update(grads, state, params)
            return jax.tree.map(jnp.add, params, updates), state, loss

    for _ in range(warmup):
        params, state, loss = step(params, state, batch)[:3]
    float(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        params, state, loss = step(params, state, batch)[:3]
    float(loss)
    dt = time.perf_counter() - t0
    return global_batch * cfg.max_seq * steps / dt / n_chips, dt / steps


def _run_autoplan(jax, jnp, cfg, batch_size, steps, warmup, remat,
                  xent_chunk, baselines, baseline_path, backend, chip, peak,
                  size_tag) -> None:
    """The ``--autoplan`` A/B: measure the hand-picked default, close the
    loop (the measured step calibrates the compute term; a comm_bench
    calibration grounds the comm terms incl. the int8 arms), plan, run
    the chosen plan, and emit the paired ``ap-{default,planned}`` rows at
    equal ``config_hash``.

    Pipeline plans are executable now (the ``_run_pp_plan_config``
    runner): when the chosen plan has pp>1 it is timed under the schedule
    the planner picked, and in EITHER case the best-ranked pp>1 plan is
    additionally timed under BOTH schedules (classic 1F1B and zero-
    bubble) so ``attach_measured`` carries the bubble audit — modeled
    slot-accounting bubble fractions next to a measured one
    (``measured_bubble_fraction`` for the zb arm = ``1 - t_ideal/t_zb``
    with ``t_ideal`` the no-bubble extrapolation ``t_1f1b * (1 -
    bf_1f1b)`` from the measured 1F1B arm's own slot model)."""
    import hashlib

    from torchdistpackage_tpu.dist import autoplan as _autoplan
    from torchdistpackage_tpu.obs.aggregate import (
        pipeline_bubble_fraction, pipeline_time_inflation)
    from torchdistpackage_tpu.obs.comm_model import CommModel

    n_chips = max(1, jax.device_count())
    tps_def, global_batch, fpt, fpt_xla, _ledger, _mem = _run_config(
        jax, jnp, cfg, batch_size, steps, warmup, remat,
        xent_chunk=xent_chunk)
    step_def = global_batch * cfg.max_seq / (tps_def * n_chips)
    fpt_basis = fpt_xla or fpt
    # sustained per-device FLOP/s the DEFAULT config actually achieved —
    # the measurement-grounded compute basis (HLO FLOPs / measured step)
    eff = fpt_basis * global_batch * cfg.max_seq / n_chips / step_def

    # calibrate the comm model on a dp x tp view of the attached chips so
    # the planner's per-axis alpha/beta (incl. the int8-ring arms) come
    # from THIS fabric, not the generation tables
    comm_model = None
    try:
        from jax.sharding import Mesh

        import numpy as _np

        tp_cal = 2 if n_chips % 2 == 0 and n_chips > 1 else 1
        cal_mesh = Mesh(
            _np.asarray(jax.devices()).reshape(n_chips // tp_cal, tp_cal),
            axis_names=("data", "tensor"))
        comm_model = CommModel.calibrate(
            mesh=cal_mesh, sizes=(1 << 14, 1 << 18), iters=3,
            ops=("all_reduce", "all_gather"),
            compressed_ops=("int8_all_reduce", "int8_reduce_scatter",
                            "int8_all_gather"))
    except Exception as e:
        print(f"bench: comm calibration failed ({e!r}); using the table "
              f"model", file=sys.stderr)

    # microbatch count for pp candidates: the largest power of two <= 8
    # dividing the global batch (the pp runner reshapes [M, B/M, S])
    M_plan = 8
    while M_plan > 1 and global_batch % M_plan:
        M_plan //= 2

    result = _autoplan.plan(
        cfg, n_chips, global_batch=global_batch,
        comm_model=comm_model, effective_flops=eff, fpt=fpt_basis,
        executable_only=True, device_kind=chip, microbatches=M_plan)
    chosen = result["chosen"]
    if chosen is None:
        # every executable candidate over the HBM budget: report the
        # default arm plus the verdict instead of crashing the child
        print("bench: autoplan found NO executable plan within the memory "
              f"budget ({result['n_pruned_oom']}/{result['n_candidates']} "
              "pruned)", file=sys.stderr)
        print(json.dumps({
            "metric": f"gpt-{size_tag}-train-throughput",
            "value": round(tps_def, 2), "unit": "tokens/sec/chip",
            "config": f"gpt d{cfg.dim} L{cfg.nlayers} seq{cfg.max_seq} "
                      f"b{global_batch} ap-default",
            "chip": chip, "backend": backend, "autoplan": "default",
            "autoplan_verdict": "all_oom",
            "plan_pruned_oom": result["n_pruned_oom"],
        }))
        return
    print(f"bench: autoplan chose {chosen['key']} "
          f"(modeled step {chosen['step_s'] * 1e3:.3f} ms vs default "
          f"measured {step_def * 1e3:.3f} ms; "
          f"{result['n_pruned_oom']}/{result['n_candidates']} pruned OOM)",
          file=sys.stderr)

    tps_plan, step_plan = _run_plan_config(
        jax, jnp, cfg, chosen, batch_size, steps, warmup, remat,
        xent_chunk=xent_chunk, microbatches=M_plan)
    rows = [{
        "key": chosen["key"], "modeled_step_s": chosen["step_s"],
        "measured_step_s": step_plan,
    }]
    if chosen["pp"] > 1:
        rows[0]["pp_schedule"] = chosen["pp_schedule"]
        rows[0]["modeled_bubble_fraction"] = chosen["bubble_fraction"]
        rows[0]["microbatches"] = M_plan

    # the bubble audit: time the best-ranked pp>1 plan under BOTH
    # schedules (one measurement is reused when the chosen plan IS that
    # pp plan) so the modeled 1F1B-vs-ZB tick accounting meets wall clock
    pp_row = chosen if chosen["pp"] > 1 else next(
        (r for r in result["ranked"] if r["pp"] > 1), None)
    pp_audit = None
    if pp_row is not None:
        try:
            infl = {s: pipeline_time_inflation(M_plan, pp_row["pp"], s)
                    for s in ("1f1b", "zb")}
            bf = {s: pipeline_bubble_fraction(
                M_plan, pp_row["pp"], schedule=s) for s in ("1f1b", "zb")}
            meas = {}
            for sched in ("1f1b", "zb"):
                if pp_row is chosen and sched == chosen["pp_schedule"]:
                    meas[sched] = step_plan
                else:
                    _, meas[sched] = _run_pp_plan_config(
                        jax, jnp, cfg, pp_row, batch_size, steps, warmup,
                        remat, microbatches=M_plan, schedule=sched)
            t_ideal = meas["1f1b"] * (1.0 - bf["1f1b"])
            for sched in ("1f1b", "zb"):
                rows.append({
                    "key": f"{pp_row['key']}·{sched}",
                    "modeled_step_s": (
                        pp_row["compute_s"] / infl[pp_row["pp_schedule"]]
                        * infl[sched] + pp_row["comm_s"]),
                    "measured_step_s": meas[sched],
                    "pp_schedule": sched,
                    "modeled_bubble_fraction": round(bf[sched], 4),
                    "measured_bubble_fraction": round(
                        max(0.0, 1.0 - t_ideal / meas[sched]), 4),
                    "microbatches": M_plan,
                })
            pp_audit = {
                "key": pp_row["key"], "microbatches": M_plan,
                "zb_vs_1f1b_measured": round(meas["zb"] / meas["1f1b"], 4),
                "zb_vs_1f1b_modeled": round(infl["zb"] / infl["1f1b"], 4),
                "bubble_fraction_zb": round(bf["zb"], 4),
                "bubble_fraction_1f1b": round(bf["1f1b"], 4),
            }
        except ValueError as e:
            print(f"bench: pp bubble audit skipped ({e})", file=sys.stderr)
    _autoplan.attach_measured(result, rows)

    metric = f"gpt-{size_tag}-train-throughput"
    base_config_str = (
        f"gpt d{cfg.dim} L{cfg.nlayers} seq{cfg.max_seq} b{global_batch}")
    config_hash = hashlib.sha1(
        f"{metric}|{base_config_str}".encode()).hexdigest()[:12]
    for arm, tps in (("default", tps_def), ("planned", tps_plan)):
        config_str = f"{base_config_str} ap-{arm}"
        _record_baseline(baselines, baseline_path, backend, config_str, tps,
                         chip=chip, metric=metric)
        line = {
            "metric": metric,
            "value": round(tps, 2),
            "unit": "tokens/sec/chip",
            "vs_baseline": round(
                tps / _best_recorded(baselines, backend, tps, metric=metric),
                4),
            "config": config_str,
            "chip": chip,
            "backend": backend,
            "config_hash": config_hash,
            "autoplan": arm,
        }
        if peak:
            line["peak_flops_est"] = peak
            line["mfu"] = round(tps * fpt / peak, 4)
        if arm == "planned":
            mvm = result["modeled_vs_measured"]["rows"][0]
            line["plan"] = chosen["key"]
            if chosen.get("ep"):
                line["plan_ep"] = chosen["ep"]
            line["autoplan_tok_s"] = round(tps, 2)
            line["plan_modeled_step_s"] = round(chosen["step_s"], 6)
            line["plan_measured_step_s"] = round(step_plan, 6)
            line["plan_modeled_vs_measured_rel"] = mvm["rel_err"]
            line["plan_candidates"] = result["n_candidates"]
            line["plan_pruned_oom"] = result["n_pruned_oom"]
            line["plan_comm_basis"] = result["basis"]["comm"]
            line["vs_default"] = round(tps / tps_def, 4)
            if chosen["pp"] > 1:
                line["plan_pp_schedule"] = chosen["pp_schedule"]
                line["bubble_fraction"] = chosen["bubble_fraction"]
                line["plan_microbatches"] = M_plan
            if pp_audit is not None:
                # the 1F1B-vs-ZB pair timed through the pipeline runner:
                # modeled vs measured schedule ratio + both tick-model
                # bubble fractions (bench_trend trends bubble_fraction)
                line["pp_audit"] = pp_audit
                line.setdefault(
                    "bubble_fraction", pp_audit["bubble_fraction_zb"])
        print(json.dumps(line))


def main(jax, jnp, ab: bool = False, only=None, big: bool = False,
         long: bool = False, moe: bool = False, trace=None,
         overlap=None, grad_compress=None, autoplan: bool = False) -> None:
    from torchdistpackage_tpu.models import GPTConfig

    # Backend probe with CPU fallback: an accelerator backend that errors at
    # init degrades to a CPU measurement (hangs are handled by the parent's
    # child-process timeout — see module docstring).
    try:
        backend = jax.default_backend()
    except Exception:
        jax.config.update("jax_platforms", "cpu")
        backend = jax.default_backend()
    on_accel = backend not in ("cpu",)

    chip = jax.devices()[0].device_kind
    peak = _peak_flops(chip) if on_accel else None

    if on_accel and moe:
        # MoE leaf: the 125M dense trunk with 8 experts every 2nd block
        # (Switch placement) — 0.57B total params, ~0.18B activated/token
        cfg = GPTConfig(
            vocab_size=32768, dim=768, nheads=12, nlayers=12, max_seq=2048,
            ffn_mult=4, dtype=jnp.bfloat16, attn_impl="flash",
            moe_experts=8, moe_top_k=2, moe_every=2,
        )
        candidates = MOE_CANDIDATES
        steps, warmup = 10, 2
        size_tag = "moe8x125m"
    elif on_accel and long:
        # long-context leaf: 125M at S=8192 (the CP ring's per-chip config)
        cfg = GPTConfig(
            vocab_size=32768, dim=768, nheads=12, nlayers=12, max_seq=8192,
            ffn_mult=4, dtype=jnp.bfloat16, attn_impl="flash",
        )
        candidates = LONG_CANDIDATES
        steps, warmup = 8, 2
        size_tag = "125m-s8k"
    elif on_accel and big:
        cfg = GPTConfig(
            vocab_size=32768, dim=2048, nheads=16, nlayers=16, max_seq=2048,
            ffn_mult=4, dtype=jnp.bfloat16, attn_impl="flash",
        )
        candidates = BIG_CANDIDATES
        steps, warmup = 10, 2
        size_tag = "1b"
    elif on_accel:
        cfg = GPTConfig(
            vocab_size=32768, dim=768, nheads=12, nlayers=12, max_seq=2048,
            ffn_mult=4, dtype=jnp.bfloat16, attn_impl="flash",
        )
        candidates = TPU_CANDIDATES
        steps, warmup = 12, 3
        size_tag = "125m"
    else:
        cfg = GPTConfig(
            vocab_size=512, dim=128, nheads=4, nlayers=4, max_seq=256,
            ffn_mult=2, dtype=jnp.float32,
        )
        candidates = [(4, False, None)]
        steps, warmup = 5, 2
        size_tag = "tiny"
        if moe:
            # tiny-MoE CPU leaf: keeps --moe --autoplan runnable on the
            # 8-device sim (the planner's ep arms need experts to shard)
            cfg = dataclasses.replace(
                cfg, moe_experts=4, moe_top_k=2, moe_every=2)
            candidates = [(4, False, None, "sorted")]
            size_tag = "tiny-moe"

    baseline_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_BASELINE.json")
    baselines = _load_baselines(baseline_path)

    if autoplan:
        # --autoplan measures the default config, plans from the three
        # cost models, and emits the paired ap-{default,planned} rows
        batch_size, remat, xent_chunk = candidates[0][:3]
        _run_autoplan(jax, jnp, cfg, batch_size, steps, warmup, remat,
                      xent_chunk, baselines, baseline_path, backend, chip,
                      peak, size_tag)
        return

    if only is not None:
        if only >= len(candidates):
            # the parent sweeps TPU_CANDIDATES indices; a child that fell
            # back to CPU has a 1-entry list — emit a marker (instead of
            # silently printing nothing with rc 0) so the parent can stop
            print(json.dumps({"skipped_candidate": only, "backend": backend}))
            return
        candidates = candidates[only:only + 1]
    elif not ab:
        candidates = candidates[:1]

    results = []
    for cand in candidates:
        batch_size, remat, xent_chunk = cand[:3]
        dispatch = cand[3] if len(cand) > 3 else None
        run_cfg = (
            dataclasses.replace(cfg, moe_dispatch=dispatch) if dispatch else cfg
        )
        tps, global_batch, fpt, fpt_xla, ledger, mem = _run_config(
            jax, jnp, run_cfg, batch_size, steps, warmup, remat,
            xent_chunk=xent_chunk, trace=trace, grad_compress=grad_compress)
        # remat: False | True | 'flash' | 'flash_offload' (save the flash
        # kernel's residuals — in HBM or pinned_host — so the backward skips
        # the Pallas fwd re-run; scan_blocks docstring)
        remat_tag = {False: "", True: " remat"}.get(remat, f" remat-{remat}")
        moe_tag = f"-moe{cfg.moe_experts}" if cfg.moe_experts else ""
        base_config_str = (
            f"gpt{moe_tag} d{cfg.dim} L{cfg.nlayers} seq{cfg.max_seq} b{global_batch}"
            f"{remat_tag}"
            f"{f' ce{xent_chunk}' if xent_chunk else ''}"
            f"{f' {dispatch}' if dispatch else ''}"
        )
        metric = f"gpt-{size_tag}-train-throughput"
        # --overlap / --grad-compress A/B pairing: each arm is a DIFFERENT
        # config for baseline recording (a flag change must not overwrite
        # the other's first-measurement record) but the arms share
        # config_hash — the join key that pairs the JSON rows of one A/B.
        config_str = base_config_str
        if overlap:
            config_str = f"{config_str} ov-{overlap}"
        if grad_compress:
            config_str = f"{config_str} gc-{grad_compress}"
        _record_baseline(baselines, baseline_path, backend, config_str, tps,
                         chip=chip, metric=metric)
        best = _best_recorded(baselines, backend, tps, metric=metric)
        line = {
            "metric": metric,
            "value": round(tps, 2),
            "unit": "tokens/sec/chip",
            "vs_baseline": round(tps / best, 4),
            "config": config_str,
            "chip": chip,
            "backend": backend,
        }
        if overlap or grad_compress:
            import hashlib

            line["config_hash"] = hashlib.sha1(
                f"{metric}|{base_config_str}".encode()).hexdigest()[:12]
        if grad_compress:
            line["grad_compress"] = grad_compress
        if overlap:
            line["overlap"] = overlap
            try:
                from torchdistpackage_tpu.dist.overlap import active

                rec = active() or {}
                line["overlap_preset"] = rec.get("preset")
                line["overlap_flags_applied"] = len(rec.get("applied", []))
                line["overlap_flags_dropped"] = len(rec.get("dropped", []))
            except Exception:
                pass
        if ledger is not None and ledger.get("async"):
            # the HLO-level overlap evidence for THIS compiled step: how
            # many collectives went async and how far the scheduler
            # spread their -start/-done pairs (obs.comm_ledger)
            a = ledger["async"]
            tot = ledger.get("total_bytes") or 0
            line["overlap_async_ops"] = a["ops"]
            line["overlap_async_bytes_fraction"] = (
                round(a["bytes"] / tot, 4) if tot else 0.0)
            if a.get("mean_sched_distance") is not None:
                line["overlap_mean_sched_distance"] = a["mean_sched_distance"]
        # memory columns: measured peak HBM + headroom fraction (absent on
        # the CPU sim, which reports no memory stats), modeled static peak
        line.update(mem)
        if peak:
            line["peak_flops_est"] = peak
            line["mfu"] = round(tps * fpt / peak, 4)
            if fpt_xla:
                line["mfu_xla"] = round(tps * fpt_xla / peak, 4)
        if ledger is not None:
            # comm-ledger summary next to MFU: the per-dimension collective
            # bytes of the exact compiled step the numbers above timed
            # (stderr — stdout stays one JSON line per config)
            from torchdistpackage_tpu.obs.comm_ledger import render_table

            print(render_table(ledger), file=sys.stderr)
            if ledger.get("per_dim"):
                line["comm_bytes_per_dim"] = {
                    d: v["bytes"] for d, v in ledger["per_dim"].items()}
        if fpt_xla:
            # the peak cancels in the ratio, so the cross-check works on
            # CPU too; |rel| > 15% is printed loudly, never hidden (remat
            # recompute and non-matmul ops are IN the XLA count only)
            line["flops_per_token_formula"] = round(fpt)
            line["flops_per_token_xla"] = round(fpt_xla)
            rel = (fpt_xla - fpt) / fpt
            line["mfu_xla_vs_formula_rel"] = round(rel, 4)
            if abs(rel) > 0.15:
                print(
                    f"bench: XLA cost-analysis FLOPs/token ({fpt_xla:.3e}) "
                    f"vs 6N+12LSD formula ({fpt:.3e}) disagree by "
                    f"{rel:+.1%} (remat={remat}) — see line field "
                    f"mfu_xla_vs_formula_rel", file=sys.stderr)
        results.append(line)
        if ab or only is not None:
            print(json.dumps(line))

    if ab and only is None:
        winner = max(results, key=lambda r: r["value"])
        print(json.dumps({"ab_winner": winner["config"], "value": winner["value"]}))
    elif only is None:
        print(json.dumps(results[0]))


def _probe() -> None:
    """--probe mode: touch the backend and print one JSON marker.  Run in a
    short-lived child — the only point is to find out whether backend init
    hangs WITHOUT committing a 900 s measurement timeout to the answer."""
    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    devs = jax.devices()
    # platforms that REGISTERED but errored at init: a cpu answer with a
    # failed accelerator platform is a transient init failure (retryable),
    # not proof of a CPU-only host
    try:
        from jax._src import xla_bridge

        failed = sorted(getattr(xla_bridge, "_backend_errors", None)
                        or getattr(xla_bridge, "_backends_errors", {}))
    except Exception:
        failed = []
    print(json.dumps({
        "probe_backend": jax.default_backend(),
        "probe_chip": devs[0].device_kind,
        "probe_n_devices": len(devs),
        "probe_failed_platforms": failed,
    }))


def _probe_accel(attempts: int, probe_timeout: float, delay: float) -> str:
    """Retry short init probes across the run.  Returns

    - ``'accel'`` as soon as a probe sees a non-CPU backend,
    - ``'cpu'`` when a probe ANSWERS with backend cpu — a deterministic
      statement that no accelerator platform is visible on this host, so
      retrying is pointless (a CPU-only dev box must not pay 4 probes + 90 s
      of sleeps, and must not be reported as a tunnel outage), and
    - ``'hang'`` when every attempt hung or crashed (the flaky-tunnel mode
      that the retries exist for)."""
    for i in range(attempts):
        if i:
            time.sleep(delay)
        out = _run_child({}, probe_timeout, ("--probe",), capture=True,
                         quiet=True)
        if out is None:
            print(f"bench: init probe {i + 1}/{attempts} hung/failed",
                  file=sys.stderr)
            continue
        for ln in out.splitlines():
            try:
                rec = json.loads(ln)
            except ValueError:
                continue
            if rec.get("probe_backend") == "cpu":
                if rec.get("probe_failed_platforms"):
                    # an accelerator platform registered but errored at
                    # init — that's the flaky tunnel, not a CPU-only box:
                    # keep retrying
                    print(
                        f"bench: init probe {i + 1}/{attempts} fell back to "
                        f"CPU (failed platforms: "
                        f"{rec['probe_failed_platforms']}); retrying",
                        file=sys.stderr,
                    )
                    break
                print("bench: probe reports a CPU-only host; not retrying",
                      file=sys.stderr)
                return "cpu"
            if rec.get("probe_backend"):
                return "accel"
    return "hang"


def _run_child(env_extra: dict, timeout: float, extra_args=(), capture=False,
               quiet=False):
    """Run a bench.py child (``--measure`` unless the args say otherwise).
    Returns True/False, or (when ``capture``) the child's stdout str on
    success / None on failure.  ``capture`` captures stdout ONLY — stderr
    stays inherited so OOM / XLA tracebacks from a failing candidate remain
    visible.  ``quiet`` keeps captured stdout out of the parent's stdout
    (probe markers are parent-internal, not bench output)."""
    env = dict(os.environ, **env_extra)
    # persistent XLA compile cache shared across measurement children: the
    # A/B sweep's one-child-per-candidate isolation would otherwise pay the
    # full compile (~20-40 s on the chip) per child for near-identical HLO
    env.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_bench_cache")
    args = list(extra_args)
    if "--probe" not in args:
        args = ["--measure", *args]
    try:
        res = subprocess.run(
            [sys.executable, os.path.abspath(__file__), *args],
            env=env,
            timeout=timeout,
            stdout=subprocess.PIPE if capture else None,
            text=capture,
        )
        if capture:
            if not quiet:
                sys.stdout.write(res.stdout)
                sys.stdout.flush()
            return res.stdout if res.returncode == 0 else None
        return res.returncode == 0
    except subprocess.TimeoutExpired:
        print(f"bench: child timed out after {timeout:.0f}s", file=sys.stderr)
        return None if capture else False


def _ab_main(timeout: float, allow_cpu: bool = False,
             big: bool = False, long: bool = False,
             moe: bool = False, overlap=None) -> None:
    """One child per candidate: an OOM/hang in one config cannot abort the
    sweep (observed: b16 no-remat exhausts v5e HBM and killed the round-3
    sweep's remaining configs), and each child gets a fresh backend — no
    allocator fragmentation carry-over between configs.

    A child that lands on CPU (explicit JAX_PLATFORMS=cpu, or accelerator
    init failure inside the child) must not feed the sweep: its measurement
    of a TPU candidate is meaningless.  Two markers catch it — the
    ``skipped_candidate`` marker (out-of-range index on the CPU 1-entry
    list) and, for candidate 0 which IS in range on CPU, the line's own
    ``backend`` field — either stops the sweep without updating ``best``.
    Exception: under an EXPLICIT ``JAX_PLATFORMS=cpu`` (``allow_cpu``) the
    user asked for the CPU sweep, so CPU lines are the legitimate result
    and only the end-of-list marker stops."""
    cands = (MOE_CANDIDATES if moe else LONG_CANDIDATES if long
             else BIG_CANDIDATES if big else TPU_CANDIDATES)
    extra = (("--moe",) if moe else ("--long",) if long
             else ("--big",) if big else ())
    if overlap:
        extra = (*extra, "--overlap", overlap)
    best = None
    for i in range(len(cands)):
        out = _run_child(
            {}, timeout, ("--ab", "--only", str(i), *extra), capture=True)
        if out is None:
            print(
                f"bench: candidate {i} {cands[i]} failed/timed out",
                file=sys.stderr,
            )
            continue
        stop = False
        for ln in out.splitlines():
            try:
                rec = json.loads(ln)
            except ValueError:
                continue
            if "skipped_candidate" in rec or (
                    rec.get("backend") == "cpu" and not allow_cpu):
                stop = True
                continue
            if "value" in rec and (best is None or rec["value"] > best["value"]):
                best = rec
        if stop:
            if not allow_cpu:
                print("bench: a sweep child fell back to CPU; stopping the "
                      "A/B sweep (TPU candidates are meaningless on CPU)",
                      file=sys.stderr)
            break
    if best is not None:
        print(json.dumps({"ab_winner": best["config"], "value": best["value"]}))
    else:
        print(json.dumps({"ab_winner": None, "error": "no candidate succeeded"}))


if __name__ == "__main__":
    if "--probe" in sys.argv:
        _probe()
        sys.exit(0)
    if "--measure" in sys.argv:
        _measure()  # prints the JSON line(s) itself
        sys.exit(0)

    accel_timeout = float(os.environ.get("BENCH_ACCEL_TIMEOUT", "900"))
    cpu_timeout = float(os.environ.get("BENCH_CPU_TIMEOUT", "600"))
    probe_attempts = int(os.environ.get("BENCH_PROBE_ATTEMPTS", "4"))
    probe_timeout = float(os.environ.get("BENCH_PROBE_TIMEOUT", "90"))
    probe_delay = float(os.environ.get("BENCH_PROBE_DELAY", "30"))

    on_cpu = os.environ.get("JAX_PLATFORMS", "") == "cpu"
    _baseline_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_BASELINE.json")

    if "--ab" in sys.argv:
        if not on_cpu and _probe_accel(
                probe_attempts, probe_timeout, probe_delay) != "accel":
            print("bench: accelerator unreachable; not starting the A/B "
                  "sweep (TPU candidates are meaningless on CPU)",
                  file=sys.stderr)
            print(json.dumps(
                {"ab_winner": None, "error": "accelerator unreachable"}))
            sys.exit(0)
        _ab_main(cpu_timeout if on_cpu else accel_timeout, allow_cpu=on_cpu,
                 big="--big" in sys.argv, long="--long" in sys.argv,
                 moe="--moe" in sys.argv,
                 overlap=_flag_value(sys.argv, "--overlap"))
        sys.exit(0)

    # `python bench.py --long` / `--moe` measure their own series
    # (gpt-125m-s8k / gpt-moe8x125m) instead of the S=2048 headline — the
    # flag must reach the measurement children or results would land in the
    # wrong baseline series while appearing to succeed.  moe-first order
    # matches _ab_main and main() so every entry point resolves a
    # conflicting `--long --moe` to the same sweep.
    long_flag = (("--moe",) if "--moe" in sys.argv
                 else ("--long",) if "--long" in sys.argv else ())
    _trace_path = _flag_value(sys.argv, "--trace")
    if _trace_path:
        # forward the Perfetto-trace request to the measurement children
        long_flag = (*long_flag, "--trace", _trace_path)
    _ov = _flag_value(sys.argv, "--overlap")
    if _ov:
        # forward the overlap A/B arm to the measurement children (the
        # child applies/validates the XLA preset before backend init)
        long_flag = (*long_flag, "--overlap", _ov)
    _gc = _flag_value(sys.argv, "--grad-compress")
    if _gc:
        # forward the grad-compression arm (the child routes the step
        # through DataParallel(grad_compress=...) so the reduction is a
        # ledgered collective)
        long_flag = (*long_flag, "--grad-compress", _gc)
    if "--autoplan" in sys.argv:
        # forward the planner A/B arm (the child plans from the measured
        # default step + a comm calibration, then times the chosen plan)
        long_flag = (*long_flag, "--autoplan")
    if on_cpu:
        ok = _run_child({}, cpu_timeout, long_flag)
    else:
        ok = False
        probed = _probe_accel(probe_attempts, probe_timeout, probe_delay)
        if probed == "accel":
            # the ~1B north-star config measures in its OWN child first,
            # best-effort: an OOM/hang there cannot cost the headline line
            # (and its line precedes the headline so the parsed last line
            # stays the 125m record series); skipped under --long, which is
            # a different series entirely
            if not long_flag and os.environ.get("BENCH_BIG", "1") != "0":
                if not _run_child({}, accel_timeout, ("--big",)):
                    print("bench: 1b config child failed; continuing with "
                          "the headline config", file=sys.stderr)
            ok = _run_child({}, accel_timeout, long_flag)
            if not ok:
                # init works (probe passed) — the failure was in the
                # measurement itself; one retry before giving up on the chip
                print("bench: accelerator measurement failed after a good "
                      "probe; retrying once", file=sys.stderr)
                ok = _run_child({}, accel_timeout, long_flag)
        if not ok:
            print("bench: accelerator unreachable/failed; measuring on CPU "
                  "and attaching the last-good accelerator record",
                  file=sys.stderr)
            cpu_ok = _run_child({"JAX_PLATFORMS": "cpu"}, cpu_timeout)
            reason = {
                "accel": "accelerator measurement children failed after a "
                         "successful init probe",
                "cpu": "no accelerator platform visible on this host "
                       "(probe answered cpu)",
                "hang": "accelerator backend unreachable this run "
                        "(init probes exhausted)",
            }[probed]
            stale = _last_good_accel_line(
                _load_baselines(_baseline_path), reason=reason)
            if stale is not None:
                if not cpu_ok:
                    stale["error"] = "cpu fallback measurement also failed"
                print(json.dumps(stale))
                ok = True
            else:
                ok = cpu_ok
    if not ok:
        print(json.dumps({
            "metric": "gpt-train-throughput",
            "value": 0.0,
            "unit": "tokens/sec/chip",
            "vs_baseline": 0.0,
            "error": "all measurement children failed or timed out",
        }))
