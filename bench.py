"""Benchmark: flagship GPT training throughput (tokens/sec/chip).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

On TPU: a GPT-125M-class model at seq 2048, bf16 matmuls, full train step
(fwd+bwd+adamw) on the available chip(s) (single-chip DP mesh when only one).
On CPU (no TPU attached): a tiny config so the harness still produces a line.
``vs_baseline`` compares against BENCH_BASELINE.json if present (first
recorded measurement wins as baseline — the reference publishes no numbers,
BASELINE.md), else 1.0.

Hang-proof structure: the accelerator backend behind the axon tunnel can
HANG at init (not just raise — observed: ``jax.devices()`` blocking >400 s),
so the parent process never touches JAX.  It runs the measurement in a child
process with a timeout (``BENCH_ACCEL_TIMEOUT``, default 900 s), and on
timeout/crash re-runs pinned to CPU (``BENCH_CPU_TIMEOUT``, default 600 s).
If everything fails it still prints the JSON line with an ``error`` field.
Run with ``--measure`` to execute the measurement directly in-process.
"""

import json
import os
import subprocess
import sys
import time


def _measure() -> None:
    import jax

    # honor JAX_PLATFORMS even when a sitecustomize force-registered another
    # backend (matches tests/conftest.py and __graft_entry__.py)
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import jax.numpy as jnp

    main(jax, jnp)


def main(jax, jnp) -> None:
    import optax

    from torchdistpackage_tpu.models import GPTConfig, gpt_loss, init_gpt_params

    # Backend probe with CPU fallback: an accelerator backend that errors at
    # init degrades to a CPU measurement (hangs are handled by the parent's
    # child-process timeout — see module docstring).
    try:
        backend = jax.default_backend()
    except Exception:
        jax.config.update("jax_platforms", "cpu")
        backend = jax.default_backend()
    on_accel = backend not in ("cpu",)

    if on_accel:
        cfg = GPTConfig(
            vocab_size=32768, dim=768, nheads=12, nlayers=12, max_seq=2048,
            ffn_mult=4, dtype=jnp.bfloat16, attn_impl="flash",
        )
        # block remat frees activation HBM -> 2x batch fits, higher MXU
        # utilization (measured +7% over b8 no-remat on v5e)
        batch_size, steps, warmup, remat = 16, 12, 3, True
    else:
        cfg = GPTConfig(
            vocab_size=512, dim=128, nheads=4, nlayers=4, max_seq=256,
            ffn_mult=2, dtype=jnp.float32,
        )
        batch_size, steps, warmup, remat = 4, 5, 2, False

    params = init_gpt_params(jax.random.PRNGKey(0), cfg)
    opt = optax.adamw(3e-4)
    state = opt.init(params)

    def loss_fn(p, batch):
        return gpt_loss(p, batch, cfg, remat=remat)

    # DP mesh over all attached chips so per-chip throughput is honest on
    # multi-chip hosts: params replicated, batch sharded on its leading dim.
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    n_chips = max(1, jax.device_count())
    mesh = Mesh(jax.devices(), axis_names=("data",))
    replicated = NamedSharding(mesh, P())
    batch_sharded = NamedSharding(mesh, P("data"))
    params = jax.device_put(params, replicated)
    state = jax.device_put(state, replicated)

    @jax.jit
    def step(params, state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, state = opt.update(grads, state, params)
        return jax.tree.map(jnp.add, params, updates), state, loss

    k1, k2 = jax.random.split(jax.random.PRNGKey(1))
    global_batch = batch_size * n_chips
    batch = {
        "tokens": jax.random.randint(k1, (global_batch, cfg.max_seq), 0, cfg.vocab_size),
        "targets": jax.random.randint(k2, (global_batch, cfg.max_seq), 0, cfg.vocab_size),
    }
    batch = jax.device_put(batch, batch_sharded)

    # NB: sync via host transfer (float(loss)), NOT block_until_ready — over
    # the axon TPU tunnel block_until_ready can return before execution
    # completes, which makes timings fictitious.  The steps form a data
    # dependency chain (params feed the next step), so fetching the final
    # loss bounds the whole run.
    for _ in range(warmup):
        params, state, loss = step(params, state, batch)
    float(loss)

    t0 = time.perf_counter()
    for _ in range(steps):
        params, state, loss = step(params, state, batch)
    float(loss)
    dt = time.perf_counter() - t0

    tokens_per_sec_chip = global_batch * cfg.max_seq * steps / dt / n_chips

    # Baselines are keyed by (backend, config): the first measurement of a
    # given config on a given backend wins, and a CONFIG change re-records
    # instead of reporting a ratio that conflates config and code changes.
    config_str = (
        f"gpt d{cfg.dim} L{cfg.nlayers} seq{cfg.max_seq} b{global_batch}"
        f"{' remat' if remat else ''}"
    )
    baseline_path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "BENCH_BASELINE.json")
    try:
        with open(baseline_path) as f:
            baselines = json.load(f)
        if "backend" in baselines and "value" in baselines:  # legacy flat format
            baselines = {baselines["backend"]: baselines}
    except (OSError, ValueError):
        baselines = {}
    rec = baselines.get(backend)
    vs_baseline = 1.0
    if rec and rec.get("value") and rec.get("config") == config_str:
        vs_baseline = tokens_per_sec_chip / float(rec["value"])
    else:
        baselines[backend] = {
            "backend": backend, "value": tokens_per_sec_chip,
            "unit": "tokens/sec/chip", "config": config_str,
        }
        try:
            with open(baseline_path, "w") as f:
                json.dump(baselines, f)
        except OSError:
            pass  # read-only checkout: report vs_baseline=1.0, keep the line

    print(json.dumps({
        "metric": f"gpt-{'125m' if on_accel else 'tiny'}-train-throughput",
        "value": round(tokens_per_sec_chip, 2),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(vs_baseline, 4),
        "config": config_str,
    }))


def _run_child(env_extra: dict, timeout: float) -> bool:
    env = dict(os.environ, **env_extra)
    try:
        res = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--measure"],
            env=env,
            timeout=timeout,
        )
        return res.returncode == 0
    except subprocess.TimeoutExpired:
        print(f"bench: child timed out after {timeout:.0f}s", file=sys.stderr)
        return False


if __name__ == "__main__":
    if "--measure" in sys.argv:
        _measure()  # prints the JSON line itself
        sys.exit(0)

    accel_timeout = float(os.environ.get("BENCH_ACCEL_TIMEOUT", "900"))
    cpu_timeout = float(os.environ.get("BENCH_CPU_TIMEOUT", "600"))

    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        ok = _run_child({}, cpu_timeout)
    else:
        ok = _run_child({}, accel_timeout)
        if not ok:
            print(
                "bench: accelerator path failed or hung; re-running on CPU",
                file=sys.stderr,
            )
            ok = _run_child({"JAX_PLATFORMS": "cpu"}, cpu_timeout)
    if not ok:
        print(json.dumps({
            "metric": "gpt-train-throughput",
            "value": 0.0,
            "unit": "tokens/sec/chip",
            "vs_baseline": 0.0,
            "error": "all measurement children failed or timed out",
        }))
