"""Worker for tests/test_multiprocess.py — NOT a pytest file.

Each of the two spawned processes runs this script: jax.distributed
bootstrap through the REAL ``dist/launch.py`` torchrun-style env path
(RANK / WORLD_SIZE / MASTER_ADDR / MASTER_PORT, the analogue of the
reference's ``setup_distributed``, launch_from_slurm.py:16-62), then forms
global meshes spanning both processes and drives the package's own
collective smoke test plus a DP train step whose loss the parent checks
for cross-rank and vs-single-process parity.
"""

import os
import sys

# 4 virtual CPU devices per process -> 8 global (XLA_FLAGS writes are
# centralized in dist/overlap.py; cpu_sim also pins the cpu platform)
from torchdistpackage_tpu.dist.overlap import cpu_sim

cpu_sim(4)

import jax

# cross-process CPU collectives ride gloo (the CPU stand-in for ICI/DCN)
jax.config.update("jax_cpu_collectives_implementation", "gloo")

from torchdistpackage_tpu.dist.launch import setup_distributed

setup_distributed()
rank = jax.process_index()
assert jax.process_count() == 2, jax.process_count()
assert jax.local_device_count() == 4 and jax.device_count() == 8

import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from torchdistpackage_tpu.dist import tpc
from torchdistpackage_tpu.dist.topology import test_comm
from torchdistpackage_tpu.models import (
    GPTConfig,
    gpt_loss,
    init_gpt_params,
)
from torchdistpackage_tpu.parallel import DataParallel
from torchdistpackage_tpu.utils.data import global_batch_from_local

# --- collectives over axes whose groups SPAN the two processes
tpc.setup_process_groups([("data", 4), ("tensor", 2)])
res = test_comm(tpc.get_view())
assert res == {"data": True, "tensor": True}, res
print(f"rank {rank}: test_comm ok {res}", flush=True)

# --- DP train-step parity: every process computes the SAME global step
tpc.reset()
tpc.setup_process_groups([("data", 8)])
mesh = tpc.get_view()
cfg = GPTConfig(
    vocab_size=64, dim=32, nheads=4, nlayers=2, max_seq=16, ffn_mult=2,
    dtype=jnp.float32,
)
params = init_gpt_params(jax.random.PRNGKey(0), cfg)
dp = DataParallel(mesh=mesh)
sharded = dp.broadcast_params(params)
opt = optax.sgd(1e-2)
state = opt.init(sharded)
step = dp.make_train_step(
    lambda p, b: gpt_loss(p, b, cfg),
    opt,
    batch_spec={"tokens": P("data"), "targets": P("data")},
)

# global batch of 8 rows; this process materializes ONLY its 4 local rows
k1, k2 = jax.random.split(jax.random.PRNGKey(7))
tokens = np.asarray(jax.random.randint(k1, (8, 16), 0, cfg.vocab_size))
targets = np.asarray(jax.random.randint(k2, (8, 16), 0, cfg.vocab_size))
lo, hi = 4 * rank, 4 * rank + 4
batch = global_batch_from_local(
    {"tokens": tokens[lo:hi], "targets": targets[lo:hi]},
    mesh,
    {"tokens": P("data"), "targets": P("data")},
)
for _ in range(2):
    sharded, state, loss = step(sharded, state, batch)
print(f"rank {rank}: LOSS={float(loss):.8f}", flush=True)

# --- obs cross-host aggregation: each process contributes rank-distinct
# step times; the allgathered view must see both hosts and flag rank 1
# (5x rank 0, ratio 5/3 over the median of the two) as the straggler on
# EVERY process
from torchdistpackage_tpu.obs import cross_host_step_stats

stats = cross_host_step_stats([0.010 * (1 + 4 * rank)] * 4)
assert stats["n_hosts"] == 2, stats
means = [round(h["mean"], 4) for h in stats["per_host"]]
assert means == [0.01, 0.05], stats
assert stats["straggler"] == 1, stats
print(
    f"rank {rank}: OBS_AGG n_hosts={stats['n_hosts']} "
    f"straggler={stats['straggler']} ratio={stats['straggler_ratio']:.2f}",
    flush=True,
)

# --- resilience consistency guard over the REAL two-process allgather:
# agreeing fingerprints (step + config + the DP-replicated params, whose
# per-host local-shard checksums must match) pass on both ranks; a
# rank-skewed step counter must raise desync_detected on EVERY process
from torchdistpackage_tpu.obs import default_event_log
from torchdistpackage_tpu.resilience import check_consistency

agree = check_consistency(step=7, config={"lr": 1e-2}, params=sharded)
assert agree["ok"] and agree["n_hosts"] == 2, agree

skewed = check_consistency(step=7 + rank, config={"lr": 1e-2})
assert not skewed["ok"] and skewed["mismatched"] == ["step"], skewed
desync = default_event_log().of_kind("desync_detected")
assert len(desync) == 1 and desync[0]["mismatched"] == ["step"], desync
print(
    f"rank {rank}: CONSISTENCY ok_hosts={agree['n_hosts']} "
    f"desync={skewed['mismatched']}",
    flush=True,
)
