"""End-to-end example: train a Llama-family model (RMSNorm + SwiGLU + RoPE
+ GQA) with DP x TP+SP.

The reference has no Llama models; this example exists to show the modern
decoder recipe is one ``llama_config()`` call away — every parallel lever
(here: DataParallel + TP with sequence parallelism + remat) is the same as
the GPT family's because norm/act/rope/GQA are carried structurally by the
param tree (tensor_parallel/layers.py).

- real TPU chips:      python examples/train_llama.py
- 8-device CPU sim:    TDP_CPU_SIM=8 python examples/train_llama.py
"""

import os

if os.environ.get("TDP_CPU_SIM"):
    # XLA_FLAGS handling is centralized in dist/overlap.py (test_repo_lint
    # bans direct writes); cpu_sim also pins the cpu platform, replacing
    # the old post-import jax.config.update dance.
    from torchdistpackage_tpu.dist.overlap import cpu_sim

    cpu_sim(os.environ["TDP_CPU_SIM"])

import jax

import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from torchdistpackage_tpu import setup_distributed, tpc
from torchdistpackage_tpu.models import (
    gpt_loss,
    gpt_param_specs,
    init_gpt_params,
    llama_config,
)
from torchdistpackage_tpu.obs import Telemetry
from torchdistpackage_tpu.parallel.data_parallel import DataParallel


def main():
    setup_distributed()
    ndev = len(jax.devices())
    tp = 2 if ndev % 2 == 0 else 1
    tpc.setup_process_groups([("data", ndev // tp), ("tensor", tp)])
    print(f"mesh: {dict(tpc.get_view().shape)}")

    on_cpu = jax.default_backend() == "cpu"
    cfg = llama_config(
        vocab_size=512 if on_cpu else 32768,
        dim=64 if on_cpu else 512,
        nheads=4 if on_cpu else 8,
        kv_heads=2 if on_cpu else 4,  # GQA: kv_heads % tp == 0
        nlayers=2 if on_cpu else 8,
        max_seq=32 if on_cpu else 1024,
        dtype=jnp.float32 if on_cpu else jnp.bfloat16,
        attn_impl="naive" if on_cpu else "flash",
    )
    print(f"llama: {cfg.num_params() / 1e6:.1f}M params, ffn {cfg.block.ffn_dim}")

    params = init_gpt_params(jax.random.PRNGKey(0), cfg)
    axis = "tensor" if tp > 1 else None
    specs = gpt_param_specs(cfg, tp_axis=axis) if tp > 1 else None

    def loss_fn(p, batch):
        return gpt_loss(p, batch, cfg, axis=axis, sp=tp > 1, remat=not on_cpu)

    opt = optax.adamw(3e-4)
    dp = DataParallel()
    params = dp.broadcast_params(params, param_specs=specs)
    opt_state = opt.init(params)
    step = dp.make_train_step(
        loss_fn, opt, param_specs=specs,
        batch_spec={"tokens": P("data"), "targets": P("data")},
    )

    B = 4 * max(1, ndev // tp)
    mesh = tpc.get_view()
    # obs session: per-step spans + recompile watch + RUNREPORT.json (when
    # TDP_RUNREPORT is set, as under the CI example runner)
    tel = Telemetry(run="train_llama", tokens_per_step=B * cfg.max_seq,
                    mesh=mesh)
    step = tel.wrap_step(step)
    for it in range(5):
        k1, k2 = jax.random.split(jax.random.PRNGKey(100 + it))
        batch = {
            "tokens": jax.random.randint(k1, (B, cfg.max_seq), 0, cfg.vocab_size),
            "targets": jax.random.randint(k2, (B, cfg.max_seq), 0, cfg.vocab_size),
        }
        batch = jax.tree.map(
            lambda a: jax.device_put(a, NamedSharding(mesh, P("data"))), batch
        )
        params, opt_state, loss = step(params, opt_state, batch)
        rec = tel.end_step(step=it, loss=loss)
        print(f"iter {it}: loss {rec['loss']:.4f}  ({rec['step_time_s']:.2f}s)")
    assert jnp.isfinite(rec["loss"])
    tel.finalize()
    print("ok")


if __name__ == "__main__":
    main()
