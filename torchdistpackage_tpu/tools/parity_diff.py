"""A/B run-parity diff: compare two runs' artifacts into a drift verdict.

    python -m torchdistpackage_tpu.tools.parity_diff RUN_A RUN_B
        [--key loss] [--rtol 0.05] [--atol 1e-9] [--label-a fp32]
        [--label-b int8]

``RUN_A`` / ``RUN_B`` are either ``RUNREPORT.json`` files (the per-step
stream comes from their ``numerics.timeline``) or ``JsonlSink`` record
files (one JSON step record per line).  The tool prints:

- the per-step drift table (downsampled) with the
  ``exact | bounded | diverged`` verdict from
  :func:`...obs.parity.compare_streams`;
- when both inputs are RUNREPORTs with a ``numerics`` section, the
  per-dtype HLO ledger SHIFT between the arms — the evidence that e.g.
  an int8 arm actually runs int8 (s8 bytes appear) rather than silently
  upcasting;
- when both inputs are RUNREPORTs with a ``comm`` section, the per-AXIS
  collective-bytes shift between the arms — the wire-savings evidence
  (a compressed arm's axis bytes dropping ~3-4x) rendered next to the
  drift, so one command shows both the win and its numeric cost;
- one final JSON line with the verdict and the headline deltas.

Exit code: 0 for ``exact``/``bounded``, 1 for ``diverged``, 2 for usage/
input errors — a CI gate over quantization/optimization A/Bs, the way
``tools/bench_trend`` gates the bench rounds.

Deliberately jax-free (a login-node / CI gate tool over artifacts on
disk, like ``bench_trend``), hence the bare prints.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional, Tuple

from ..obs.parity import PARITY_VERDICTS, compare_streams, stream_of


def load_run(path: str) -> Tuple[Any, Optional[Dict[str, Any]]]:
    """(stream source, report-or-None) from a RUNREPORT.json or a records
    JSONL file.  A JSON object is a report; anything else is parsed line
    by line as JSONL records."""
    with open(path) as f:
        text = f.read()
    try:
        doc = json.loads(text)
        if isinstance(doc, dict):
            return doc, doc
        if isinstance(doc, list):
            return doc, None
    except ValueError:
        pass
    records: List[Dict[str, Any]] = []
    for ln in text.splitlines():
        ln = ln.strip()
        if not ln:
            continue
        try:
            rec = json.loads(ln)
        except ValueError:
            continue
        if isinstance(rec, dict):
            records.append(rec)
    if not records:
        raise ValueError(f"{path}: neither a JSON report nor JSONL records")
    return records, None


def dtype_shift(
    rep_a: Optional[Dict[str, Any]], rep_b: Optional[Dict[str, Any]]
) -> Optional[List[Dict[str, Any]]]:
    """Per-dtype byte/FLOP deltas between two reports' primary dtype
    ledgers; None when either side lacks one."""
    def primary(rep):
        leds = ((rep or {}).get("numerics") or {}).get("dtype_ledgers") or []
        return leds[0].get("per_dtype") if leds else None

    pa, pb = primary(rep_a), primary(rep_b)
    if not pa or not pb:
        return None
    rows = []
    for dt in sorted(set(pa) | set(pb)):
        a = pa.get(dt, {"bytes": 0, "ops": 0, "flops": 0})
        b = pb.get(dt, {"bytes": 0, "ops": 0, "flops": 0})
        rows.append({
            "dtype": dt,
            "bytes_a": a["bytes"], "bytes_b": b["bytes"],
            "bytes_delta": b["bytes"] - a["bytes"],
            "flops_a": a["flops"], "flops_b": b["flops"],
            "flops_delta": b["flops"] - a["flops"],
        })
    return rows


def comm_axis_shift(
    rep_a: Optional[Dict[str, Any]], rep_b: Optional[Dict[str, Any]]
) -> Optional[List[Dict[str, Any]]]:
    """Per-axis collective-bytes deltas between two reports' comm ledgers
    (collectives aggregated by the mesh-axis set they span); None when
    either side lacks a ledger.  The compressed-bytes evidence: an int8
    arm's compressed axis shows its bytes divided by the wire win, while
    untouched axes match — a drop appearing on the WRONG axis (or none at
    all) means the compression didn't land where claimed."""
    def per_axis(rep):
        colls = (((rep or {}).get("comm") or {}).get("ledger") or {}).get(
            "collectives")
        if not colls:
            return None
        agg: Dict[str, Dict[str, int]] = {}
        for c in colls:
            key = "+".join(c.get("axes") or []) or "?"
            e = agg.setdefault(key, {"bytes": 0, "ops": 0})
            e["bytes"] += int(c.get("bytes", 0))
            e["ops"] += 1
        return agg

    pa, pb = per_axis(rep_a), per_axis(rep_b)
    if pa is None or pb is None:
        return None
    rows = []
    for ax in sorted(set(pa) | set(pb)):
        a = pa.get(ax, {"bytes": 0, "ops": 0})
        b = pb.get(ax, {"bytes": 0, "ops": 0})
        rows.append({
            "axes": ax,
            "bytes_a": a["bytes"], "bytes_b": b["bytes"],
            "ops_a": a["ops"], "ops_b": b["ops"],
            "ratio": round(a["bytes"] / b["bytes"], 3) if b["bytes"] else None,
        })
    return rows


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m torchdistpackage_tpu.tools.parity_diff",
        description="Compare two runs' per-step streams into an "
                    "exact|bounded|diverged verdict (nonzero exit on "
                    "diverged).")
    ap.add_argument("run_a", help="RUNREPORT.json or records.jsonl of arm A")
    ap.add_argument("run_b", help="RUNREPORT.json or records.jsonl of arm B")
    ap.add_argument("--key", default="loss",
                    help="step-record scalar to compare (default: loss)")
    ap.add_argument("--rtol", type=float, default=0.05,
                    help="relative drift bound for 'bounded' (default 0.05)")
    ap.add_argument("--atol", type=float, default=1e-9,
                    help="absolute drift floor (default 1e-9)")
    ap.add_argument("--label-a", default="a", help="display name for arm A")
    ap.add_argument("--label-b", default="b", help="display name for arm B")
    args = ap.parse_args(argv)

    try:
        src_a, rep_a = load_run(args.run_a)
        src_b, rep_b = load_run(args.run_b)
    except (OSError, ValueError) as e:
        print(f"parity_diff: {e}", file=sys.stderr)
        return 2
    sa = stream_of(src_a, key=args.key)
    sb = stream_of(src_b, key=args.key)
    cmp = compare_streams(sa, sb, key=args.key, rtol=args.rtol,
                          atol=args.atol)
    assert cmp["verdict"] in PARITY_VERDICTS

    print(f"parity: {args.label_a} ({len(sa)} steps) vs "
          f"{args.label_b} ({len(sb)} steps), key={args.key!r}, "
          f"{cmp['n_common']} common")
    if cmp["n_common"]:
        print(f"{'step':>6} {'|a-b|':>12} {'rel':>10}")
        for row in cmp["drift_curve"]:
            d, r = row["delta"], row["rel"]
            print(f"{row['step']:>6} "
                  + (f"{d:>12.4e}" if d is not None else f"{'nonfinite':>12}")
                  + (f" {r:>10.3e}" if r is not None else f" {'-':>10}"))
        print(f"max |a-b| = {cmp['max_abs_delta']:.4e}, "
              f"max rel = {cmp['max_rel_delta']:.3e} "
              f"(bound: atol {args.atol:g} + rtol {args.rtol:g})")
        if cmp.get("first_mismatch_step") is not None:
            print(f"first out-of-bound step: {cmp['first_mismatch_step']}")

    shift = dtype_shift(rep_a, rep_b)
    if shift:
        print(f"\ndtype ledger shift ({args.label_a} -> {args.label_b}):")
        print(f"{'dtype':>8} {'bytes A':>14} {'bytes B':>14} "
              f"{'flops A':>12} {'flops B':>12}")
        for r in shift:
            print(f"{r['dtype']:>8} {r['bytes_a']:>14,} {r['bytes_b']:>14,} "
                  f"{r['flops_a']:>12.3e} {r['flops_b']:>12.3e}")

    cshift = comm_axis_shift(rep_a, rep_b)
    if cshift:
        print(f"\ncomm ledger shift per axis "
              f"({args.label_a} -> {args.label_b}):")
        print(f"{'axes':>16} {'bytes A':>14} {'bytes B':>14} "
              f"{'A/B':>7} {'ops A':>6} {'ops B':>6}")
        for r in cshift:
            ratio = f"{r['ratio']:.2f}x" if r["ratio"] else "-"
            print(f"{r['axes']:>16} {r['bytes_a']:>14,} {r['bytes_b']:>14,} "
                  f"{ratio:>7} {r['ops_a']:>6} {r['ops_b']:>6}")

    line = {
        "metric": "parity",
        "key": args.key,
        "verdict": cmp["verdict"],
        "n_common": cmp["n_common"],
        "max_abs_delta": cmp.get("max_abs_delta"),
        "max_rel_delta": cmp.get("max_rel_delta"),
        "labels": [args.label_a, args.label_b],
    }
    if shift:
        line["dtype_bytes_delta"] = {
            r["dtype"]: r["bytes_delta"] for r in shift if r["bytes_delta"]}
    if cshift:
        line["comm_axis_bytes"] = {
            r["axes"]: {"a": r["bytes_a"], "b": r["bytes_b"],
                        "ratio": r["ratio"]}
            for r in cshift}
    print(json.dumps(line))
    if cmp["verdict"] == "diverged":
        print(f"\n!!! DIVERGED: {args.label_b} drifted past the bound vs "
              f"{args.label_a} (key {args.key!r})", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
