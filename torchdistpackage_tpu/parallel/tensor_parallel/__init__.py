from .tp_utils import (
    gather_from_sp,
    get_tp_axis,
    reduce_from_tp,
    scatter_to_sp,
    set_tp_axis,
    split_to_sp,
)
from .layers import (
    RematMode,
    TransformerConfig,
    attention_partial,
    block_forward,
    block_param_specs,
    checkpoint_block,
    init_block_params,
    init_transformer_params,
    layer_norm,
    mlp_partial,
    scan_blocks,
    stacked_block_specs,
    transformer_forward,
    transformer_param_specs,
)
