"""Fault matrix for the resilience subsystem: every injected fault must be
detected, land on the event timeline, and either recover with
exact-trajectory parity (where parity is defined) or abort cleanly.

Kept cheap per the PR-3 budget note: ONE tiny jitted train step (fwd+grad
folded into a single ``value_and_grad`` program) is compiled once at
module scope and reused by every trajectory test; everything else
(manifests, retries, watchdog, monitor, consistency) is pure host-side
python.
"""

import math
import os
import signal
import threading

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from torchdistpackage_tpu.obs.events import (
    EventLog,
    set_default_event_log,
)
from torchdistpackage_tpu.resilience import (
    ChaosMonkey,
    CheckpointCorruptError,
    DivergenceMonitor,
    Fault,
    GuardedCheckpointManager,
    ResilientLoop,
    Watchdog,
    check_consistency,
    config_fingerprint,
    consistency_fingerprint,
    corrupt_checkpoint,
    param_checksum,
    verify_checkpoint,
    verify_template,
    with_retries,
    write_manifest,
)
from torchdistpackage_tpu.utils import CheckpointManager, GracefulShutdown, auto_resume

# ------------------------------------------------------------ tiny model
# One compiled program for the whole module: linear regression, fwd+grad
# in a single value_and_grad jit (the cheapest real "training step" that
# still exercises checkpoint payloads, optimizer state, and determinism).

_OPT = optax.sgd(0.1)


@jax.jit
def _step(params, opt_state, batch):
    def loss_fn(p):
        pred = batch["x"] @ p["w"] + p["b"]
        return jnp.mean((pred - batch["y"]) ** 2)

    loss, grads = jax.value_and_grad(loss_fn)(params)
    updates, opt_state = _OPT.update(grads, opt_state, params)
    return optax.apply_updates(params, updates), opt_state, loss


def _make_batch(index: int):
    # fully index-derived (no RNG object): the offset shift after a
    # rollback IS the data/RNG-stream advance
    x = np.sin(np.arange(32, dtype=np.float32).reshape(8, 4) + index)
    y = np.cos(np.arange(8, dtype=np.float32) + index * 0.5)
    return {"x": x, "y": y}


def _init():
    params = {"w": jnp.zeros((4,)), "b": jnp.zeros(())}
    return params, _OPT.init(params)


def _payload(params, opt_state, offset=0):
    return {"params": params, "opt": opt_state,
            "loop": {"data_offset": jnp.int32(offset)}}


@pytest.fixture()
def events():
    """Fresh process-default event log per test — assertions against the
    timeline must not see a neighbor test's events."""
    log = EventLog()
    set_default_event_log(log)
    yield log
    set_default_event_log(None)


# ===================================================== checkpoint hardening


def test_manifest_write_verify_roundtrip(tmp_path, events):
    params, opt = _init()
    d = str(tmp_path / "run")
    with GuardedCheckpointManager(d, max_to_keep=4) as mgr:
        mgr.save(0, _payload(params, opt), wait=True)
        # manifest written at commit, checkpoint verifies clean
        assert os.path.exists(os.path.join(d, "manifests", "0.json"))
        assert verify_checkpoint(d, 0) == []
        # template structure check: drift in the tree fails loudly
        assert verify_template(d, 0, _payload(params, opt)) == []
        bad = {"params": {"w": jnp.zeros((5,))}}
        assert verify_template(d, 0, bad) != []


@pytest.mark.parametrize("mode", ["truncate", "bitflip"])
def test_corruption_detected_and_quarantined(tmp_path, events, mode):
    """Corrupt ckpt -> fallback: auto_resume restores the newest GOOD step,
    quarantines the bad one, and the skip lands on the timeline."""
    params, opt = _init()
    d = str(tmp_path / "run")
    with GuardedCheckpointManager(d, max_to_keep=4) as mgr:
        for s in range(3):
            mgr.save(s, _payload(params, opt, offset=s), wait=True)
        corrupt_checkpoint(d, step=2, mode=mode)
        assert verify_checkpoint(d, 2) != []
        # direct restore of the bad step raises, not garbage
        with pytest.raises(CheckpointCorruptError):
            mgr.restore(2, template=_payload(params, opt))
        start, state = auto_resume(mgr, _payload(params, opt))
        # walked back: resumed AFTER step 1, with step 1's payload
        assert start == 2
        assert int(state["loop"]["data_offset"]) == 1
        # bad step renamed aside for post-mortem, manager no longer sees it
        assert os.path.isdir(os.path.join(d + ".quarantine", "2"))
        assert mgr.latest_step() == 1
    quark = events.of_kind("ckpt_quarantine")
    assert len(quark) == 1 and quark[0]["step"] == 2, quark
    assert events.of_kind("fault_injected")[0]["fault"] == "ckpt_corrupt"


def test_unmanifested_corruption_still_walks_back(tmp_path, events):
    """A plain (manifest-less) manager's corrupt step is caught by the
    restore failure itself — auto_resume must still fall back."""
    params, opt = _init()
    d = str(tmp_path / "run")
    with CheckpointManager(d, max_to_keep=4) as mgr:
        for s in range(2):
            mgr.save(s, _payload(params, opt, offset=s), wait=True)
        # wreck step 1 thoroughly: every file truncated to zero
        step_dir = os.path.join(d, "1")
        for root, _dirs, files in os.walk(step_dir):
            for f in files:
                with open(os.path.join(root, f), "r+b") as fh:
                    fh.truncate(0)
        start, state = auto_resume(mgr, _payload(params, opt))
        assert start == 1
        assert int(state["loop"]["data_offset"]) == 0
    assert [e["step"] for e in events.of_kind("ckpt_quarantine")] == [1]


def test_template_drift_reraises_not_quarantines(tmp_path, events):
    """A restore template that drifted from the checkpoint is a CALLER
    bug: auto_resume must fail loudly, not rename good checkpoints aside
    one by one until the run silently restarts from step 0."""
    params, opt = _init()
    d = str(tmp_path / "run")
    with GuardedCheckpointManager(d, max_to_keep=4) as mgr:
        for s in range(2):
            mgr.save(s, _payload(params, opt, offset=s), wait=True)
        drifted = {"params": {"w": jnp.zeros((5,))}}
        with pytest.raises(ValueError, match="does not match its recorded"):
            auto_resume(mgr, drifted)
        # every checkpoint survived untouched
        assert sorted(mgr.all_steps()) == [0, 1]
    assert not os.path.exists(d + ".quarantine")
    assert events.of_kind("ckpt_quarantine") == []


def test_manifestless_template_drift_reraises(tmp_path, events):
    """Even without a manifest, a readable checkpoint + failing restore is
    a template problem: the template-free probe proves the bytes fine and
    the original error surfaces instead of a quarantine."""
    params, opt = _init()
    d = str(tmp_path / "run")
    with CheckpointManager(d, max_to_keep=4) as mgr:
        mgr.save(0, _payload(params, opt), wait=True)
        with pytest.raises(Exception, match="[Kk]ey mismatch"):
            auto_resume(mgr, {"params": {"w": jnp.zeros((5,))}})
        assert mgr.latest_step() == 0
    assert events.of_kind("ckpt_quarantine") == []


def test_transient_oserror_retries_then_reraises(tmp_path, events):
    """Persistent OSError (storage down) must NOT quarantine: retry with
    backoff, then fail loudly with every checkpoint still in place."""
    params, opt = _init()
    d = str(tmp_path / "run")
    with CheckpointManager(d, max_to_keep=4) as mgr:
        mgr.save(0, _payload(params, opt), wait=True)
        real_restore = mgr.restore
        mgr.restore = lambda *a, **k: (_ for _ in ()).throw(OSError("mount gone"))
        with pytest.raises(OSError, match="mount gone"):
            auto_resume(mgr, _payload(params, opt))
        mgr.restore = real_restore
        assert mgr.latest_step() == 0
    assert events.of_kind("ckpt_quarantine") == []
    assert len(events.of_kind("ckpt_retry")) == 3  # backoff was attempted


def test_with_retries_backoff_and_budget(events):
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("transient")
        return 42

    assert with_retries(flaky, retries=5, base_delay_s=0.001) == 42
    assert len(events.of_kind("ckpt_retry")) == 2
    with pytest.raises(OSError):
        with_retries(lambda: (_ for _ in ()).throw(OSError("down")),
                     retries=2, base_delay_s=0.001)
    # budget exhausted after exactly `retries` retry events more
    assert len(events.of_kind("ckpt_retry")) == 4


def test_manifests_pruned_with_retention(tmp_path):
    """Retention-removed steps must not leave manifests behind: the
    manifests dir stays bounded over a long run."""
    params, opt = _init()
    d = str(tmp_path / "run")
    with GuardedCheckpointManager(d, max_to_keep=2) as mgr:
        for s in range(5):
            mgr.save(s, _payload(params, opt, offset=s), wait=True)
        assert sorted(mgr.all_steps()) == [3, 4]
    mdir = os.path.join(d, "manifests")
    assert sorted(os.listdir(mdir)) == ["3.json", "4.json"]


def test_stale_manifest_pruned_at_init(tmp_path, events):
    """Fresh run, same directory: a manifest lingering from a previous
    run's step 0 must not condemn the new run's step 0."""
    import shutil

    params, opt = _init()
    d = str(tmp_path / "run")
    with GuardedCheckpointManager(d, max_to_keep=3) as mgr:
        mgr.save(0, _payload(params, opt), wait=True)
    shutil.rmtree(os.path.join(d, "0"))  # steps cleared, manifests forgotten
    assert os.path.exists(os.path.join(d, "manifests", "0.json"))
    with GuardedCheckpointManager(d, max_to_keep=3) as mgr2:
        # construction pruned the orphaned manifest...
        assert not os.path.exists(os.path.join(d, "manifests", "0.json"))
        mgr2.save(0, _payload(params, opt, offset=7), wait=True)
        # ...so the recycled step 0 verifies against ITS manifest, clean
        assert verify_checkpoint(d, 0) == []
        start, state = auto_resume(mgr2, _payload(params, opt))
        assert start == 1 and int(state["loop"]["data_offset"]) == 7
    assert events.of_kind("ckpt_quarantine") == []


def test_stale_manifest_mtime_crosscheck(tmp_path):
    """verify_checkpoint ignores a manifest whose recorded files all
    postdate it (recycled step) but still flags real tampering."""
    import json as _json

    params, opt = _init()
    d = str(tmp_path / "run")
    with GuardedCheckpointManager(d, max_to_keep=2) as mgr:
        mgr.save(0, _payload(params, opt), wait=True)
    mpath = os.path.join(d, "manifests", "0.json")
    with open(mpath) as f:
        manifest = _json.load(f)
    # poison a checksum: an APPLICABLE manifest must flag it...
    manifest["files"][0]["sha256"] = "0" * 64
    with open(mpath, "w") as f:
        _json.dump(manifest, f)
    assert any("checksum" in p for p in verify_checkpoint(d, 0))
    # ...but the same manifest pushed into the past (as if every file were
    # rewritten by a new incarnation of step 0) proves nothing
    manifest["files_max_mtime"] -= 10_000.0
    with open(mpath, "w") as f:
        _json.dump(manifest, f)
    assert verify_checkpoint(d, 0) == []


def test_ckpt_manager_ctx_waits_on_exception(tmp_path):
    """An exception between save() and teardown must not strand the async
    save: __exit__ waits for the commit before closing."""
    params, opt = _init()
    d = str(tmp_path / "run")
    with pytest.raises(RuntimeError, match="boom"):
        with CheckpointManager(d, max_to_keep=2) as mgr:
            mgr.save(0, _payload(params, opt), wait=False)
            raise RuntimeError("boom")
    with CheckpointManager(d, max_to_keep=2) as mgr2:
        assert mgr2.latest_step() == 0  # the save committed anyway


# =========================================================== chaos parity


def test_armed_unfired_chaos_is_bit_identical(tmp_path, events):
    """Acceptance: chaos armed but silent == no resilience subsystem at
    all, bit for bit (losses AND final params)."""
    params, opt = _init()
    d = str(tmp_path / "run")
    with GuardedCheckpointManager(d, max_to_keep=3) as mgr:
        loop = ResilientLoop(
            _step, _make_batch, mgr, total_steps=6, save_every=2,
            chaos=ChaosMonkey(faults=[Fault("nan_spike", step=99)], seed=7))
        res = loop.run(params, opt)
    assert res.verdict == "clean" and res.summary["faults_injected"] == 0

    p, o = _init()
    hand = {}
    for s in range(6):
        p, o, loss = _step(p, o, _make_batch(s))
        hand[s] = float(loss)
    assert hand == res.losses
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        p, res.params)
    assert events.of_kind("rollback") == []


def test_nan_spike_rollback_exact_parity(tmp_path, events):
    """NaN spike at step 5 -> rollback to the step-3 checkpoint, data
    stream advanced past the poisoned window, and from there the recovered
    trajectory is bit-identical to a hand replay of the same checkpoint
    over the same shifted indices."""
    params, opt = _init()
    d = str(tmp_path / "run")
    with GuardedCheckpointManager(d, max_to_keep=4) as mgr:
        loop = ResilientLoop(
            _step, _make_batch, mgr, total_steps=10, save_every=2,
            max_rollbacks=2, chaos=ChaosMonkey([Fault("nan_spike", step=5)]))
        res = loop.run(params, opt)
    assert res.verdict == "recovered"
    assert res.summary["rollbacks"] == 1
    assert res.summary["data_offset"] == 2  # skipped window (3, 5]
    assert sorted(res.losses) == list(range(10))
    assert all(math.isfinite(v) for v in res.losses.values())

    rb = events.of_kind("rollback")
    assert len(rb) == 1
    assert rb[0]["from_step"] == 5 and rb[0]["to_step"] == 3
    fi = events.of_kind("fault_injected")
    assert len(fi) == 1 and fi[0]["fault"] == "nan_spike" and fi[0]["step"] == 5

    # parity golden: hand-replay from the step-3 checkpoint with the
    # shifted stream — every loss and the final params must match exactly
    with GuardedCheckpointManager(d, max_to_keep=4) as mgr2:
        st = mgr2.restore(3, template=_payload(params, opt))
    p, o = st["params"], st["opt"]
    for s in range(4, 10):
        p, o, loss = _step(p, o, _make_batch(s + 2))
        assert float(loss) == res.losses[s], s
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        p, res.params)


def test_rollback_budget_spent_aborts_cleanly(tmp_path, events):
    """A persistent divergence exhausts max_rollbacks and the loop aborts
    with a verdict instead of looping forever or crashing."""
    params, opt = _init()
    d = str(tmp_path / "run")
    with GuardedCheckpointManager(d, max_to_keep=4) as mgr:
        loop = ResilientLoop(
            _step, _make_batch, mgr, total_steps=8, save_every=1,
            max_rollbacks=1,
            chaos=ChaosMonkey([Fault("nan_spike", step=3, repeat=True)]))
        res = loop.run(params, opt)
    assert res.aborted and res.verdict == "aborted"
    assert res.summary["rollbacks"] == 1
    ab = events.of_kind("resilience_abort")
    assert len(ab) == 1 and ab[0]["rollbacks_used"] == 1
    # checkpoints survive the abort: a babysitter relaunch can still resume
    with GuardedCheckpointManager(d, max_to_keep=4) as mgr2:
        assert mgr2.latest_step() is not None


def test_sigterm_mid_run_resume_exact_trajectory(tmp_path, events):
    """Chaos SIGTERM -> grace-window save -> relaunch resumes -> the
    stitched trajectory equals an uninterrupted run exactly."""
    params, opt = _init()
    d = str(tmp_path / "run")
    with GuardedCheckpointManager(d, max_to_keep=4) as mgr:
        loop = ResilientLoop(
            _step, _make_batch, mgr, total_steps=8, save_every=3,
            chaos=ChaosMonkey([Fault("sigterm", step=4)]))
        res1 = loop.run(params, opt)
    assert res1.preempted and res1.verdict == "preempted"
    assert max(res1.losses) == 4  # finished the in-flight step, then saved
    pre = events.of_kind("preemption")
    assert len(pre) == 1 and pre[0]["signal"] == "SIGTERM"

    # relaunch: fresh objects, same dir, no chaos
    with GuardedCheckpointManager(d, max_to_keep=4) as mgr2:
        loop2 = ResilientLoop(_step, _make_batch, mgr2, total_steps=8,
                              save_every=3)
        res2 = loop2.run(*_init())
    assert res2.verdict == "clean"
    assert sorted(res2.losses) == [5, 6, 7]

    p, o = _init()
    for s in range(8):
        p, o, loss = _step(p, o, _make_batch(s))
        got = res1.losses.get(s, res2.losses.get(s))
        assert float(loss) == got, s
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        p, res2.params)


def test_grace_save_forced_past_save_interval(tmp_path, events):
    """A manager with save_interval_steps > 1 declines off-interval saves;
    the preemption grace-window save must be FORCED through, and the
    reported last_checkpoint must be a checkpoint that actually exists."""
    params, opt = _init()
    d = str(tmp_path / "run")
    with GuardedCheckpointManager(d, max_to_keep=4,
                                  save_interval_steps=5) as mgr:
        loop = ResilientLoop(
            _step, _make_batch, mgr, total_steps=8, save_every=1,
            chaos=ChaosMonkey([Fault("sigterm", step=2)]))
        res = loop.run(params, opt)
    assert res.preempted
    # step 2 is off the 5-step interval — only the forced save committed it
    assert res.summary["last_checkpoint"] == 2
    with GuardedCheckpointManager(d, max_to_keep=4) as mgr2:
        assert mgr2.latest_step() == 2
        start, state = auto_resume(mgr2, _payload(params, opt))
        assert start == 3


def test_declined_forced_save_is_loud(tmp_path, events):
    """If even a forced save is declined, the summary must not claim the
    step was checkpointed — and the decline lands on the timeline."""

    class _DecliningManager(CheckpointManager):
        def save(self, step, state, wait=False, force=False):
            return False

    params, opt = _init()
    d = str(tmp_path / "run")
    with _DecliningManager(d, max_to_keep=2) as mgr:
        res = ResilientLoop(_step, _make_batch, mgr, total_steps=2,
                            save_every=1).run(params, opt)
    assert res.verdict == "clean"
    assert res.summary["last_checkpoint"] is None
    skipped = events.of_kind("checkpoint_save_skipped")
    assert skipped and skipped[-1]["forced"] and skipped[-1]["step"] == 1


def test_stall_trips_watchdog_hang_suspected(tmp_path, events):
    """Host stall (chaos sleep) longer than the watchdog timeout ->
    hang_suspected on the timeline; the beat after the stall resolves it."""
    params, opt = _init()
    d = str(tmp_path / "run")
    dog = Watchdog(timeout_s=0.15, poll_s=0.03)
    with GuardedCheckpointManager(d, max_to_keep=3) as mgr:
        loop = ResilientLoop(
            _step, _make_batch, mgr, total_steps=5, save_every=5,
            watchdog=dog,
            chaos=ChaosMonkey([Fault("stall", step=3, duration_s=0.5)]))
        res = loop.run(params, opt)
    assert res.verdict == "clean"  # a stall is latency, not divergence
    assert res.summary["hang_suspected"] == 1
    sus = events.of_kind("hang_suspected")
    assert len(sus) == 1 and sus[0]["last_step"] == 3
    assert [e["fault"] for e in events.of_kind("fault_injected")] == ["stall"]
    assert len(events.of_kind("hang_resolved")) == 1


# ============================================================== watchdog


def test_watchdog_abort_escalation_uses_exit_hook(events):
    """Silence past timeout+grace with abort=True calls the (injected)
    exit hook with the configured code — the babysitter-relaunch path."""
    exited = []
    dog = Watchdog(timeout_s=0.05, poll_s=0.02, abort=True,
                   abort_grace_s=0.05, exit_code=87,
                   _exit=lambda code: exited.append(code))
    with dog:
        dog.beat(0)
        deadline = 2.0
        t0 = os.times().elapsed
        while not exited and os.times().elapsed - t0 < deadline:
            threading.Event().wait(0.02)
    assert exited == [87]
    kinds = [e["kind"] for e in events.as_list()]
    assert "hang_suspected" in kinds and "hang_abort" in kinds


# ==================================================== consistency guards


def test_desync_detected_on_divergent_fingerprints(events):
    """Cross-host disagreement (simulated gather) -> desync_detected with
    the offending component named; agreement -> ok, silent."""
    labels, vec = consistency_fingerprint(step=7, config={"lr": 1e-3})
    ok = check_consistency(step=7, config={"lr": 1e-3},
                           _gathered=np.asarray([vec, vec]))
    assert ok["ok"] and ok["n_hosts"] == 2 and ok["mismatched"] == []
    assert events.of_kind("desync_detected") == []

    vec_b = list(vec)
    vec_b[labels.index("step")] += 1  # host 1 is a step ahead
    bad = check_consistency(step=7, config={"lr": 1e-3},
                            _gathered=np.asarray([vec, vec_b]))
    assert not bad["ok"] and bad["mismatched"] == ["step"]
    ev = events.of_kind("desync_detected")
    assert len(ev) == 1 and ev[0]["mismatched"] == ["step"]


def test_fingerprint_gather_is_exact():
    """The allgather must compare fingerprints exactly: float64 values
    travel bit-cast as int32 lanes, so step counters above 2**24 and
    param-checksum sums that a float32 gather would conflate stay
    distinct."""
    from torchdistpackage_tpu.resilience.watchdog import (
        _f64_to_lanes,
        _lanes_to_f64,
    )

    # values float32 provably conflates (same f32, different f64)
    pairs = [
        (float(2 ** 24), float(2 ** 24 + 1)),     # big step counters
        (1.0e9, 1.0e9 + 1.0),                      # param checksums
        (123456789.0, np.nextafter(123456789.0, np.inf)),  # 1-ulp drift
    ]
    for a, b in pairs:
        assert np.float32(a) == np.float32(b)  # the old failure mode
        vec_a, vec_b = [a, 7.0], [b, 7.0]
        gathered = _lanes_to_f64(
            np.stack([_f64_to_lanes(vec_a), _f64_to_lanes(vec_b)]), 2)
        assert gathered[0, 0] != gathered[1, 0]  # drift stays visible
        assert gathered[0, 1] == gathered[1, 1]
        np.testing.assert_array_equal(gathered[0], vec_a)
        np.testing.assert_array_equal(gathered[1], vec_b)
    # and agreement still compares equal through the round trip
    res = check_consistency(
        step=2 ** 30,
        _gathered=np.asarray([[float(2 ** 30)], [float(2 ** 30)]]))
    assert res["ok"]


def test_fingerprint_components():
    params = {"w": jnp.arange(4.0), "b": jnp.ones(())}
    assert param_checksum(params) == param_checksum(
        {"w": jnp.arange(4.0), "b": jnp.ones(())})
    assert param_checksum(params) != param_checksum(
        {"w": jnp.arange(4.0) + 1, "b": jnp.ones(())})
    assert config_fingerprint({"a": 1, "b": 2}) == config_fingerprint(
        {"b": 2, "a": 1})  # key order must not matter
    assert config_fingerprint({"a": 1}) != config_fingerprint({"a": 2})
    labels, vec = consistency_fingerprint(
        step=3, config={"x": 1}, params=params,
        rng_key=jax.random.PRNGKey(0), code=True)
    assert labels == ["step", "config_a", "config_b", "code_a", "code_b",
                      "rng", "params"]
    assert all(math.isfinite(v) for v in vec)
    with pytest.raises(ValueError, match="nothing to check"):
        check_consistency()


# ==================================================== divergence monitor


def test_divergence_monitor_matrix():
    m = DivergenceMonitor(window=16, zmax=3.0, min_history=4)
    assert m.check(float("nan")) == "nonfinite"
    assert m.check(float("inf")) == "nonfinite"
    assert m.check(1.0, grad_norm=float("nan")) == "nonfinite"
    # too little history: even a huge loss passes (warmup protection)
    assert m.check(1e9) == "ok"
    for v in (1.0, 1.1, 0.9, 1.0, 1.05, 0.95):
        m.observe(v)
    assert m.check(1.02) == "ok"
    assert m.check(50.0) == "spike"
    m.reset()
    assert m.check(50.0) == "ok"  # window cleared
    hard = DivergenceMonitor(max_loss=10.0)
    assert hard.check(11.0) == "spike"


# =============================================== GracefulShutdown upgrades


def test_graceful_shutdown_usr_signals_and_grace(events):
    with GracefulShutdown(signals=("SIGUSR1", "USR2"), grace_s=30.0) as stop:
        assert not stop.requested
        signal.raise_signal(signal.SIGUSR1)
        assert stop.requested
        assert stop.deadline_mono is not None
    ev = events.of_kind("preemption")
    assert len(ev) == 1
    assert ev[0]["signal"] == "SIGUSR1" and ev[0]["grace_s"] == 30.0
    assert ev[0]["grace_deadline_mono"] == stop.deadline_mono


def test_graceful_shutdown_rejects_non_main_thread():
    err = []

    def enter():
        try:
            with GracefulShutdown():
                pass
        except RuntimeError as e:
            err.append(str(e))

    t = threading.Thread(target=enter)
    t.start()
    t.join()
    assert err and "main thread" in err[0]


def test_graceful_shutdown_unknown_signal_name():
    with pytest.raises(ValueError, match="unknown signal"):
        GracefulShutdown(signals=("SIGNOPE",))


# ======================================================== chaos plumbing


def test_chaos_fault_validation_and_grad_injection():
    with pytest.raises(ValueError, match="unknown fault kind"):
        Fault("meteor_strike", step=0)
    chaos = ChaosMonkey([Fault("nan_spike", step=2)])
    grads = {"w": jnp.ones((3,)), "n": jnp.arange(3)}  # int leaf untouched
    out = chaos.perturb_grads(2, grads)
    assert bool(jnp.all(jnp.isnan(out["w"])))
    assert jnp.issubdtype(out["n"].dtype, jnp.integer)
    # fired once: a second pass is inert
    out2 = chaos.perturb_grads(2, grads)
    assert bool(jnp.all(jnp.isfinite(out2["w"])))
    # disabled harness never fires
    off = ChaosMonkey([Fault("nan_spike", step=0)], enabled=False)
    assert off.perturb_loss(0, 1.5) == 1.5 and off.fired_count == 0


def test_manifest_detects_unrecorded_file(tmp_path, events):
    params, opt = _init()
    d = str(tmp_path / "run")
    with GuardedCheckpointManager(d, max_to_keep=2) as mgr:
        mgr.save(0, _payload(params, opt), wait=True)
    extra = os.path.join(d, "0", "sneaky.bin")
    with open(extra, "wb") as f:
        f.write(b"tampered")
    problems = verify_checkpoint(d, 0)
    assert any("unrecorded" in p for p in problems), problems


def test_write_manifest_requires_committed_step(tmp_path):
    with pytest.raises(FileNotFoundError):
        write_manifest(str(tmp_path), 3)
