"""Pluggable metric sinks.

Every sink speaks one protocol — ``write(record: dict)`` for per-step /
event records and ``write_summary(report: dict)`` at end of run — so a
train loop wires its telemetry once and the operator picks destinations:

- :class:`JsonlSink` — always available, the durable artifact.  This is
  also THE single JSONL code path in the package:
  ``utils.MetricsLogger`` and ``obs.EventLog`` both write through it.
- :class:`TensorBoardSink` — scalars via ``tensorboardX`` or TF, behind an
  optional-import guard (the container need not ship either).
- :class:`PrometheusTextfileSink` — node-exporter textfile-collector
  format, written atomically; no client library needed (the textfile
  format is plain ``name{labels} value`` lines).
- :class:`MultiSink` — fan-out.

The serving engine's live export rides the same protocol:
``ServingEngine(metrics_sink=...)`` writes one ``serving_metrics``
record per tick (schema
:data:`~..serving.tracing.SERVING_METRICS_SCHEMA`, fields documented in
docs/serving.md "Serving observability") — through
:class:`PrometheusTextfileSink` that is a live per-tick gauge set
(queue depth, slot occupancy, batch/pool utilization, per-phase tick
seconds) an external scraper can watch while the engine runs.
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import Any, Dict, Iterable, List, Optional


class JsonlSink:
    """Append one JSON line per record.  Opens lazily, appends, flushes per
    write (a preempted run keeps everything emitted so far)."""

    def __init__(self, path: str) -> None:
        self.path = path
        self._f = None

    def _file(self):
        if self._f is None:
            self._f = open(self.path, "a")
        return self._f

    def write(self, record: Dict[str, Any]) -> None:
        f = self._file()
        f.write(json.dumps(record) + "\n")
        f.flush()

    def write_summary(self, report: Dict[str, Any]) -> None:
        self.write({"type": "summary", **report})

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None


def tensorboard_available() -> bool:
    try:
        import tensorboardX  # noqa: F401

        return True
    except ImportError:
        pass
    try:
        from torch.utils import tensorboard  # noqa: F401

        return True
    except ImportError:
        return False


class TensorBoardSink:
    """Scalar records -> TensorBoard.  Optional dependency: raises a clear
    ImportError at CONSTRUCTION (not at first write, deep inside a train
    loop) when no writer implementation is installed; gate with
    :func:`tensorboard_available`."""

    def __init__(self, logdir: str) -> None:
        writer = None
        try:
            from tensorboardX import SummaryWriter

            writer = SummaryWriter(logdir)
        except ImportError:
            try:
                from torch.utils.tensorboard import SummaryWriter

                writer = SummaryWriter(logdir)
            except ImportError:
                raise ImportError(
                    "TensorBoardSink needs tensorboardX or torch; neither is "
                    "installed — use JsonlSink (always available) or check "
                    "obs.tensorboard_available() before constructing"
                )
        self._writer = writer

    def write(self, record: Dict[str, Any]) -> None:
        step = int(record.get("step", 0))
        for k, v in record.items():
            if isinstance(v, (int, float)) and k != "step":
                self._writer.add_scalar(k, float(v), step)

    def write_summary(self, report: Dict[str, Any]) -> None:
        self._writer.add_text("runreport", json.dumps(report, indent=1))
        self._writer.flush()

    def close(self) -> None:
        self._writer.close()


class PrometheusTextfileSink:
    """Latest-value gauges in node-exporter textfile-collector format.

    Each ``write`` updates the in-memory gauge set and atomically rewrites
    ``path`` (tmp + rename — the collector must never read a torn file).
    Labels: ``run`` and ``process`` on every gauge.
    """

    def __init__(self, path: str, prefix: str = "tdp", run: str = "run") -> None:
        self.path = path
        self.prefix = prefix
        self.run = run
        self._gauges: Dict[str, float] = {}

    def write(self, record: Dict[str, Any]) -> None:
        for k, v in record.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                self._gauges[k] = float(v)
        self._flush(process=record.get("process", 0))

    def write_summary(self, report: Dict[str, Any]) -> None:
        flat = _flatten_scalars(report)
        for k, v in flat.items():
            self._gauges[f"summary_{k}"] = v
        self._flush()

    def _flush(self, process: int = 0) -> None:
        lines: List[str] = []
        labels = f'{{run="{self.run}",process="{process}"}}'
        for k in sorted(self._gauges):
            name = f"{self.prefix}_{_sanitize(k)}"
            lines.append(f"# TYPE {name} gauge")
            lines.append(f"{name}{labels} {self._gauges[k]:.10g}")
        body = "\n".join(lines) + "\n"
        d = os.path.dirname(os.path.abspath(self.path)) or "."
        try:
            fd, tmp = tempfile.mkstemp(dir=d, prefix=".prom_tmp")
            with os.fdopen(fd, "w") as f:
                f.write(body)
            os.replace(tmp, self.path)
        except OSError:
            pass  # read-only dir: scrape target simply goes stale

    def close(self) -> None:
        pass


class MultiSink:
    """Fan a record out to several sinks; one failing sink (e.g. a full
    disk behind JsonlSink) must not take down the others."""

    def __init__(self, sinks: Iterable[Any]) -> None:
        self.sinks = list(sinks)

    def write(self, record: Dict[str, Any]) -> None:
        for s in self.sinks:
            try:
                s.write(record)
            except Exception:
                pass

    def write_summary(self, report: Dict[str, Any]) -> None:
        for s in self.sinks:
            try:
                s.write_summary(report)
            except Exception:
                pass

    def close(self) -> None:
        for s in self.sinks:
            try:
                s.close()
            except Exception:
                pass


def _sanitize(name: str) -> str:
    return "".join(c if (c.isalnum() or c == "_") else "_" for c in name)


def _flatten_scalars(tree: Dict[str, Any], prefix: str = "") -> Dict[str, float]:
    out: Dict[str, float] = {}
    for k, v in tree.items():
        key = f"{prefix}{k}"
        if isinstance(v, bool):
            continue
        if isinstance(v, (int, float)):
            out[key] = float(v)
        elif isinstance(v, dict):
            out.update(_flatten_scalars(v, prefix=f"{key}_"))
    return out
