"""Serving fast path: refcounted prefix cache + copy-on-write blocks +
static-k speculative decoding (PR 10).

The load-bearing claims, asserted against goldens / the event timeline:

- a warm shared-prefix admission maps resident blocks instead of
  re-prefilling (prefill ticks drop, ``prefix_hit`` event) and still
  emits tokens BIT-equal to the cold ``generate()`` golden — including
  the whole-prompt-cached case, which copy-on-writes its last block
  (``block_cow``), and with TWO concurrent writers COWing the same
  source block;
- sharing never breaks block conservation: retire/preempt/cancel on a
  shared block decrement rather than free (the co-owner keeps decoding
  bit-exactly), the refcount-aware audit passes every tick — including
  under the PR-9 ``table_corrupt`` / ``alloc_exhaust`` chaos faults —
  and refcount-0 cached blocks are evicted LRU only under pressure
  (``cache_evict``);
- temp-0 speculative decode is token-bitwise-identical to
  non-speculative decode (the dense engine here; GQA + sliding-window
  via per-family bundles), the hot loop stays at ONE decode signature
  (the verify program at fixed k), and a drained speculative in-flight
  request resumes to exact temp-0 parity;
- ``estimate_ttft`` subtracts already-resident prefill chunks (warm vs
  cold queue), so the PR-9 deadline gate does not shed warm traffic.

Everything dense rides ONE module-scope engine (3 slots, 10 usable
blocks, ``prefix_cache=True, spec_k=2``); the family matrix adds two
lazily-built bundles — a handful of compiled programs for the file."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torchdistpackage_tpu.models import (
    GPTConfig,
    generate,
    init_gpt_params,
    llama_config,
)
from torchdistpackage_tpu.obs.events import EventLog, set_default_event_log
from torchdistpackage_tpu.obs.report import _validate_serving
from torchdistpackage_tpu.resilience import ChaosMonkey, Fault
from torchdistpackage_tpu.serving import BlockAllocator, Request, ServingEngine
from torchdistpackage_tpu.serving.paged_cache import chain_block_hashes

CFG = GPTConfig(vocab_size=64, dim=32, nheads=4, nlayers=2, max_seq=32)
BS, CHUNK, K = 4, 4, 2
NEW = 6
P8 = 8                      # two FULL blocks: the whole-prompt/COW case
USABLE = 10                 # need/req = ceil((8+6+2)/4) = 4 with spec slack

FAMILY_CFGS = {
    "gqa": llama_config(vocab_size=64, dim=32, nheads=4, nlayers=2,
                        max_seq=32, kv_heads=2, ffn_hidden=48,
                        dtype=jnp.float32),
    "sliding": llama_config(vocab_size=64, dim=32, nheads=4, nlayers=2,
                            max_seq=32, kv_heads=2, ffn_hidden=48,
                            dtype=jnp.float32, sliding_window=6),
}


def _prompt(seed, n=P8, cfg=CFG):
    return np.asarray(jax.random.randint(
        jax.random.PRNGKey(seed), (n,), 0, cfg.vocab_size)).astype(np.int32)


@pytest.fixture(scope="module")
def fp():
    """Shared params, the P8 golden, and ONE prefix+spec engine."""
    params = init_gpt_params(jax.random.PRNGKey(0), CFG)
    gold = jax.jit(lambda p, t: generate(p, t, CFG, max_new_tokens=NEW))

    def want(prompt):
        return np.asarray(gold(params, jnp.asarray(prompt)[None]))[0]

    eng = ServingEngine(params, CFG, num_slots=3, block_size=BS,
                        chunk=CHUNK, num_blocks=USABLE + 1,
                        prefix_cache=True, spec_k=K)
    return {"params": params, "eng": eng, "want": want}


@pytest.fixture()
def event_log(fp):
    log = EventLog()
    set_default_event_log(log)
    fp["eng"]._ev = log
    yield log
    set_default_event_log(None)


def _fresh(eng):
    """Between tests: no live work, and every block either free or
    CACHED (prefix retention is deliberate cross-test state; a leaked
    refcount is not)."""
    assert eng.n_busy == 0 and not eng.queue, "previous test leaked state"
    for a in eng._allocs:
        assert a.in_use == 0, "previous test leaked block refcounts"
        assert a.n_free + a.n_cached == a.n_usable, "blocks went missing"
    eng.reset_metrics()
    eng.chaos = None
    eng._draining = False
    eng._tick_ewma = None
    eng._ttft_bias = None  # calibration is measurement state, like the EWMA
    eng._inject.clear()
    return eng


def _run_audited(eng):
    while eng.queue or eng.n_busy:
        eng.step()
        rep = eng.audit(heal=False)
        assert rep["ok"], (eng._tick, rep["violations"])
        assert eng._tick < 300


# ------------------------------------------------------- allocator unit


def test_allocator_refcounts_share_cache_evict():
    a = BlockAllocator(8)
    got = a.alloc(3)
    a.register(got[0], "h0")
    a.register(got[1], "h1")
    assert a.match(["h0", "h1"]) == got[:2]
    assert a.match(["h0", "hX", "h1"]) == got[:1]  # longest PREFIX only

    # share bumps the refcount: two frees to release; audit wants the
    # reference count to EQUAL the refcount (legal sharing), and flags
    # a mismatch as `shared`
    a.share(got[0])
    assert a.audit([got, [got[0]]])["ok"]
    rep = a.audit([got])  # one reference, refcount 2
    assert not rep["ok"] and rep["shared"] == [got[0]]
    a.free([got[0]])
    assert a.in_use == 3  # still owned once
    assert a.audit([got])["ok"]

    # release: registered blocks go to the cached LRU, not the free list
    a.free(got)
    assert a.in_use == 0 and a.n_cached == 2
    assert a.n_free + a.n_cached == a.n_usable
    assert a.audit([])["ok"]  # conservation counts cached blocks

    # a cached block revives via share (off the LRU, refcount 1)
    a.share(got[1])
    assert a.in_use == 1 and a.n_cached == 1
    a.free([got[1]])

    # eviction ONLY under pressure, LRU first, hashes dropped
    rest = a.alloc(a.n_free)
    assert a.n_cached == 2 and a.cache_evictions == 0
    more = a.alloc(1)  # free list empty: evicts the LRU cached block
    assert more is not None and a.cache_evictions == 1
    assert a.pop_evicted() == [got[0]]
    assert a.match(["h0"]) == []  # the prefix is gone with the block
    assert a.match(["h1"]) == [got[1]]
    a.free(rest + more)
    # reclaim purges refcounts, cache membership, and registrations
    healed = a.reclaim(list(range(1, 8)))
    assert a.n_free == a.n_usable and a.n_cached == 0 and a.in_use == 0
    assert a.match(["h1"]) == [] and healed
    with pytest.raises(ValueError):
        a.share(got[0])  # non-resident


def test_warm_admission_logits_bitwise(fp):
    """Acceptance bar, at the paged-forward level: a chunk computed
    against a SHARED prefix block (mapped into a different table row)
    produces logits BIT-identical to the same chunk in the cold run —
    sharing is pure table indirection, zero numerics."""
    from torchdistpackage_tpu.serving import init_paged_kv
    from torchdistpackage_tpu.serving.paged_cache import paged_forward

    params = fp["params"]
    prompt = _prompt(35)  # 8 tokens = 2 chunks of 4
    pool = init_paged_kv(CFG, 8, BS)
    step = jax.jit(lambda c, t, tab, off: paged_forward(
        params, t, CFG, c, tab, off, last_idx=jnp.asarray([BS - 1])))
    cold_tab = jnp.asarray([[1, 2, 0]], jnp.int32)
    t0 = jnp.asarray(prompt[:BS])[None]
    t1 = jnp.asarray(prompt[BS:])[None]
    pool, _ = step(pool, t0, cold_tab, jnp.asarray([0], jnp.int32))
    pool, cold_logits = step(pool, t1, cold_tab, jnp.asarray([BS], jnp.int32))
    # warm: block 1 (the shared prefix) mapped into a DIFFERENT table;
    # the second chunk writes into a fresh block and attends through it
    warm_tab = jnp.asarray([[1, 3, 0]], jnp.int32)
    pool, warm_logits = step(pool, t1, warm_tab, jnp.asarray([BS], jnp.int32))
    np.testing.assert_array_equal(
        np.asarray(cold_logits), np.asarray(warm_logits),
        err_msg="shared-prefix chunk logits drifted from the cold run")


# ---------------------------------------------- warm admission + estimate


def test_warm_prefix_hit_parity_and_prefill_savings(fp, event_log):
    eng = _fresh(fp["eng"])
    base = _prompt(40)
    warm = np.concatenate([base[:BS], _prompt(41, 3)])  # shares block 0
    cold_want, warm_want = fp["want"](base), fp["want"](warm)

    r0 = eng.submit(Request(base.tolist(), NEW))
    _run_audited(eng)
    cold_chunks = eng.stats["prefill_chunks"]
    np.testing.assert_array_equal(eng.finished[r0]["tokens"], cold_want)
    assert eng.stats["prefix_hits"] == 0  # nothing resident yet

    eng.reset_metrics()
    r1 = eng.submit(Request(warm.tolist(), NEW))
    _run_audited(eng)
    np.testing.assert_array_equal(
        eng.finished[r1]["tokens"], warm_want,
        err_msg="warm prefix admission diverged from its cold run")
    hits = event_log.of_kind("prefix_hit")
    assert len(hits) == 1 and hits[0]["cached_tokens"] == BS
    assert not hits[0]["cow"]
    # prefill ticks saved ∝ hit: 7-token remainder = 2 chunks vs 2 for 8
    assert eng.stats["prefill_chunks"] < cold_chunks
    s = eng.serving_summary()
    assert s["prefix_hit_rate"] == pytest.approx(BS / len(warm))
    assert s["decode_signatures"] == 1 and s["prefill_signatures"] == 1
    assert _validate_serving(s) == []
    # the validator bites on out-of-range fast-path rates
    assert any("prefix_hit_rate" in e for e in _validate_serving(
        dict(s, prefix_hit_rate=2.0)))
    assert any("spec" in e for e in _validate_serving(
        dict(s, spec={"drafted": 1, "accepted": 2})))


def test_estimate_ttft_warm_vs_cold_queue(fp):
    """Satellite: admission estimates subtract already-resident prefill
    chunks, so warm shared-prefix traffic is not spuriously shed."""
    eng = _fresh(fp["eng"])
    warm_prompt = _prompt(40)  # resident from the previous test
    cold_prompt = _prompt(44)
    eng._tick_ewma = 0.01
    # cold: 2 chunks of 4; warm: both blocks resident, COW-capped to 1
    # recomputed token = 1 chunk
    assert eng.estimate_ttft(P8, tokens=cold_prompt.tolist()) == \
        pytest.approx(0.02)
    assert eng.estimate_ttft(P8, tokens=warm_prompt.tolist()) == \
        pytest.approx(0.01)
    # queued work ahead is costed at its WARM price too
    req = Request(warm_prompt.tolist(), NEW)
    import dataclasses
    req = dataclasses.replace(req, rid=0)
    eng._seq[0] = 0
    eng.queue.append((req, 0.0))
    assert eng.estimate_ttft(P8, tokens=cold_prompt.tolist()) == \
        pytest.approx(0.03)  # 2 cold + 1 warm queued
    eng.queue.clear()
    del eng._seq[0]


def test_estimate_ttft_calibration_converges_and_warm_stays(fp):
    """Satellite (PR 11): the TTFT calibration loop.  Feed a
    deliberately skewed sequence — the engine's measured TTFT is
    consistently 2x its raw (ticks x EWMA) estimate — and the bias EWMA
    must converge to the true factor (tracking actual/RAW, not
    actual/corrected, which would converge to sqrt(2)); estimate_ttft
    then predicts the skewed truth.  A warm-cache prediction resolved at
    its true (warm) cost must leave the converged bias put — warm
    traffic is cheaper because fewer chunks run, not because the clock
    model is wrong, so it must not be 'corrected'."""
    eng = _fresh(fp["eng"])
    eng._tick_ewma = 0.01
    cold = _prompt(44)              # nothing resident: 2 chunks raw
    for i in range(40):
        est = eng.estimate_ttft(P8, tokens=cold.tolist())
        raw = est / (eng._ttft_bias if eng._ttft_bias is not None else 1.0)
        assert raw == pytest.approx(0.02)
        eng._ttft_pred[9000 + i] = {"est": est, "raw": raw}
        eng._resolve_ttft(9000 + i, actual=0.04, priority=0)
    assert eng._ttft_bias == pytest.approx(2.0, rel=0.02)
    assert eng.estimate_ttft(P8, tokens=cold.tolist()) == \
        pytest.approx(0.04, rel=0.02)

    # warm prompt (resident from the earlier module tests): 1 chunk raw,
    # biased to 0.02 — and resolving it at exactly that cost holds the
    # bias (extends the PR-10 warm/cold queue evidence into calibration)
    warm = _prompt(40)
    est_w = eng.estimate_ttft(P8, tokens=warm.tolist())
    assert est_w == pytest.approx(0.02, rel=0.02)
    eng._ttft_pred[9999] = {"est": est_w, "raw": est_w / eng._ttft_bias}
    eng._resolve_ttft(9999, actual=est_w, priority=2)
    assert eng._ttft_bias == pytest.approx(2.0, rel=0.05)

    cal = eng.serving_summary()["slo"]["calibration"]
    assert cal["n"] == 41 and cal["pending"] == 0
    assert cal["bias"] == pytest.approx(2.0, rel=0.05)
    # the warm prediction was spot-on: zero relative error at its class
    assert cal["priorities"]["2"]["rel_err_p50"] == pytest.approx(
        0.0, abs=1e-9)
    # the skewed class's error shrinks as the bias converges: the median
    # (late, converged) error is far below the first prediction's 50%
    assert cal["priorities"]["0"]["rel_err_p50"] < 0.05
    assert _validate_serving(eng.serving_summary()) == []
    eng._ttft_bias = None  # leave no calibration state for later tests


# --------------------------------------------------- COW + shared safety


def test_cow_whole_prompt_cached_concurrent_writers(fp, event_log):
    """Two requests whose WHOLE prompt is resident admitted the same
    tick: each COWs the same source block into its own copy, writes its
    recomputed last token there, and decodes bit-identically to the cold
    golden — the concurrent-writer case block sharing must survive."""
    eng = _fresh(fp["eng"])
    prompt = _prompt(50)
    want = fp["want"](prompt)
    r0 = eng.submit(Request(prompt.tolist(), NEW))
    _run_audited(eng)
    np.testing.assert_array_equal(eng.finished[r0]["tokens"], want)

    eng.reset_metrics()
    r1 = eng.submit(Request(prompt.tolist(), NEW))
    r2 = eng.submit(Request(prompt.tolist(), NEW))
    eng.step()
    cows = event_log.of_kind("block_cow")
    assert len(cows) == 2, "both whole-prompt hits must COW"
    assert cows[0]["src_block"] == cows[1]["src_block"]
    assert cows[0]["dst_block"] != cows[1]["dst_block"]
    _run_audited(eng)
    for r in (r1, r2):
        np.testing.assert_array_equal(
            eng.finished[r]["tokens"], want,
            err_msg="COW writer diverged from the cold golden")
    s = eng.serving_summary()
    assert s["prefix_cache"]["cow_copies"] == 2
    assert s["prefix_cache"]["cow_signatures"] == 1  # one compiled copy
    assert s["decode_signatures"] == 1
    hits = event_log.of_kind("prefix_hit")
    assert len(hits) == 2 and all(h["cow"] for h in hits)


def test_preempt_on_shared_blocks_never_frees_coowner(fp, event_log):
    """A preempted (and a cancelled) sharer must DECREMENT, not free:
    the co-owner keeps decoding on the shared blocks bit-exactly."""
    eng = _fresh(fp["eng"])
    prompt = _prompt(60)
    want = fp["want"](prompt)
    a = eng.submit(Request(prompt.tolist(), NEW))
    _run_audited(eng)  # A completes; blocks cached + registered
    eng.reset_metrics()

    a2 = eng.submit(Request(prompt.tolist(), NEW))          # COW + share
    b = eng.submit(Request(prompt.tolist(), NEW))           # shares too
    eng.step()
    shared_counts = [v for v in eng._allocs[0]._ref.values() if v > 1]
    assert shared_counts, "expected refcount > 1 on the shared prefix"

    # a high-priority request that cannot fit evicts the most recent
    # same-priority sharer; the survivor's blocks must stay live
    hi = eng.submit(Request(_prompt(61).tolist(), NEW, priority=5))
    _run_audited(eng)
    pre = event_log.of_kind("request_preempted")
    assert len(pre) == 1 and pre[0]["by_rid"] == hi
    for rid in (a2, b):
        f = eng.finished[rid]
        assert f["reason"] == "max_tokens"
        np.testing.assert_array_equal(
            f["tokens"], want,
            err_msg="sharer diverged after its co-owner was preempted")
    np.testing.assert_array_equal(
        eng.finished[hi]["tokens"], fp["want"](_prompt(61)))
    assert eng.serving_summary()["requests"]["preempted"] == 1

    # cancel a sharer mid-flight: same decrement discipline
    eng.reset_metrics()
    c1 = eng.submit(Request(prompt.tolist(), NEW))
    c2 = eng.submit(Request(prompt.tolist(), NEW))
    for _ in range(3):
        eng.step()
    assert eng.cancel(c1) is True
    _run_audited(eng)
    np.testing.assert_array_equal(eng.finished[c2]["tokens"], want)
    assert _kinds_count(event_log, "request_cancelled") == 1


def _kinds_count(log, kind):
    return sum(1 for e in log.as_list() if e["kind"] == kind)


def test_cache_eviction_only_under_pressure(fp, event_log):
    """Refcount-0 cached blocks are retained until the free list cannot
    cover a fresh allocation, then evicted LRU with a ``cache_evict``
    event — block conservation holds throughout."""
    eng = _fresh(fp["eng"])
    alloc = eng._allocs[0]
    # fill the cache with distinct retired prefixes
    seeds = (70, 71, 72)
    for s in seeds:
        eng.submit(Request(_prompt(s).tolist(), 1))
    _run_audited(eng)
    assert alloc.n_cached > 0
    evictions_before = eng.stats["cache_evictions"]
    # two cold requests need 8 fresh blocks; free+cached covers them only
    # by evicting
    assert alloc.n_free < 8 <= alloc.n_free + alloc.n_cached
    r = [eng.submit(Request(_prompt(80 + i).tolist(), NEW))
         for i in range(2)]
    _run_audited(eng)
    for i, rid in enumerate(r):
        np.testing.assert_array_equal(
            eng.finished[rid]["tokens"], fp["want"](_prompt(80 + i)))
    assert eng.stats["cache_evictions"] > evictions_before
    assert event_log.of_kind("cache_evict")
    # the evicted prefix is findable no more
    oldest = chain_block_hashes(_prompt(seeds[0]), BS)
    assert alloc.match(oldest) == []


# ----------------------------------------------------- chaos w/ refcounts


@pytest.mark.parametrize("fault", ["table_corrupt", "alloc_exhaust"])
def test_chaos_faults_green_with_refcounts(fp, event_log, fault):
    """Satellite: the PR-9 chaos faults stay green on a prefix+spec
    engine — the refcount-aware audit heals, only the poisoned request
    replays, co-batched output is bit-identical, one decode signature."""
    eng = _fresh(fp["eng"])
    p0, p1 = _prompt(90), _prompt(91)
    kw = {"slot": 1} if fault == "table_corrupt" else {}
    eng.chaos = ChaosMonkey(faults=[Fault(fault, step=4, **kw)], seed=0)
    rids = [eng.submit(Request(p.tolist(), NEW)) for p in (p0, p1)]
    _run_audited(eng)
    eng.chaos = None
    for rid, p in zip(rids, (p0, p1)):
        np.testing.assert_array_equal(
            eng.finished[rid]["tokens"], fp["want"](p),
            err_msg=f"{fault}: tokens diverged under refcounted sharing")
    s = eng.serving_summary()
    assert s["decode_signatures"] == 1
    assert s["faults"]["healed"] == s["faults"]["detected"] >= 1
    kinds = {e["kind"] for e in event_log.as_list()}
    assert {"engine_fault_detected", "engine_recovered"} <= kinds


# ------------------------------------------------ speculative decode claims


def test_spec_drain_resume_exact_parity(fp, event_log, tmp_path):
    """A speculative in-flight request drained mid-decode resumes to
    exact temp-0 token parity (the descriptor's emitted list IS the
    accepted-draft state; replay rides chunked prefill + the warm
    prefix cache)."""
    eng = _fresh(fp["eng"])
    prompt = _prompt(95)
    want = fp["want"](prompt)
    g = eng.submit(Request(prompt.tolist(), NEW))
    smp = eng.submit(Request(_prompt(96).tolist(), NEW, temperature=1.0,
                             top_k=16, seed=7))
    while not any(s.state == "decode" and s.generated
                  for s in eng._slots):
        eng.step()
    path = str(tmp_path / "spec_drain.json")
    payload = eng.drain(persist_path=path)
    assert eng.n_busy == 0 and payload["n"] == 2
    assert _kinds_count(event_log, "engine_drained") == 1

    eng._draining = False
    rids = eng.resume(path)
    _run_audited(eng)
    f = eng.finished[rids[0]]
    np.testing.assert_array_equal(
        f["tokens"], want,
        err_msg="speculative drain/resume broke temp-0 parity")
    assert f["new_tokens"] == NEW
    smp_f = eng.finished[rids[1]]
    assert smp_f["new_tokens"] == NEW
    assert np.all(smp_f["tokens"] < CFG.vocab_size)
    s = eng.serving_summary()
    assert s["requests"]["resumed"] == 2
    assert s["decode_signatures"] == 1


def test_spec_sampled_deterministic_replay(fp):
    """Sampled speculative decode draws from the slot's own key stream:
    same seed replays the same tokens, different seeds differ, every
    token is in-vocab (residual rejection sampling never leaves the
    filtered support)."""
    eng = _fresh(fp["eng"])
    prompt = _prompt(97)

    def run(seed):
        rid = eng.submit(Request(prompt.tolist(), NEW, temperature=1.0,
                                 top_k=16, top_p=0.9, seed=seed))
        _run_audited(eng)
        return eng.finished[rid]["tokens"]

    a, b, c = run(3), run(3), run(4)
    np.testing.assert_array_equal(a, b)
    assert not np.array_equal(a, c)
    assert np.all(a[P8:] < CFG.vocab_size)
    assert eng.serving_summary()["decode_signatures"] == 1


def test_lifecycle_trace_preempt_drain_resume(fp, event_log, tmp_path):
    """Acceptance (PR 11): a preempted-then-resumed SPECULATIVE request's
    full lifecycle reconstructs from the trace alone — every phase span
    present and ordered (queued → prefill → decode/verify ticks →
    preempted → queued → drained, then the resumed instance through to
    retirement), flow-linked across the drain→resume restart — and the
    whole traced path adds zero compiled programs
    (``decode_signatures == 1``)."""
    from torchdistpackage_tpu.obs.trace import build_trace, validate_trace
    from torchdistpackage_tpu.serving import (
        assemble_request_timelines,
        lifecycle_phases,
        request_trace_events,
        validate_request_record,
    )

    eng = _fresh(fp["eng"])
    pa, pv, ph = _prompt(120), _prompt(121), _prompt(122)
    a = eng.submit(Request(pa.tolist(), NEW))
    v = eng.submit(Request(pv.tolist(), NEW))

    def _decoding(rid):
        return any(s.rid == rid and s.state == "decode" and s.generated
                   for s in eng._slots)

    while not (_decoding(a) and _decoding(v)):
        eng.step()
        assert eng._tick < 100
    # v (most recently admitted at equal priority) is the preemption
    # victim; the freed blocks cover hi, v waits in the queue
    hi = eng.submit(Request(ph.tolist(), NEW, priority=5))
    while not _decoding(hi):
        eng.step()
        assert eng._tick < 100
    assert any(r.rid == v for r, _t in eng.queue), "victim not requeued"

    path = str(tmp_path / "obs_drain.json")
    payload = eng.drain(persist_path=path)
    assert payload["n"] == 3
    eng._draining = False
    rids = eng.resume(path)
    _run_audited(eng)
    s = eng.serving_summary()
    assert s["decode_signatures"] == 1 and s["prefill_signatures"] == 1
    assert _validate_serving(s) == []

    events = event_log.as_list()
    records = assemble_request_timelines(events)
    for rec in records:
        assert validate_request_record(rec) == [], rec
    by_uid = {r["uid"]: r for r in records}
    (vrec,) = [r for r in records if r["rid"] == v and r["terminal"] ==
               "drained"]

    # every phase span present and ORDERED: the preempted speculative
    # request's walk, reconstructed purely from the timeline
    assert lifecycle_phases(vrec) == [
        "queued", "admitted", "prefill", "decode", "preempted", "queued",
        "drained"]
    names = [sp["name"] for sp in vrec["spans"]]
    assert names == ["queued", "prefill", "decode", "queued"]
    for s0, s1 in zip(vrec["spans"], vrec["spans"][1:]):
        assert s1["t0"] >= s0["t1"] - 1e-9, "phase spans out of order"
    # per-tick children: chunked prefill and the SPECULATIVE verify ticks
    child_kinds = {c["name"] for c in vrec["ticks"]}
    assert {"prefill_chunk", "verify_tick"} <= child_kinds

    # flow-linked across drain -> resume: the drained instance names the
    # instance that continues it, and the continuation retires cleanly
    assert vrec["resumed_to"] is not None
    rrec = by_uid[vrec["resumed_to"]]
    assert rrec["resumed_from"] == vrec["uid"]
    assert lifecycle_phases(rrec) == [
        "queued", "admitted", "prefill", "decode", "retired"]
    assert rrec["spans"][0]["t0"] >= vrec["spans"][-1]["t1"] - 1e-9
    # the resumed request replayed to the unpreempted golden
    np.testing.assert_array_equal(
        eng.finished[rrec["rid"]]["tokens"], fp["want"](pv),
        err_msg="preempt+drain+resume broke the token stream")
    # the other two drained instances resumed and retired too
    assert len(rids) == 3 and all(
        eng.finished[r]["reason"] == "max_tokens" for r in rids)

    # and it all renders as a loadable Perfetto trace with the requeue
    # and resume flow arrows connecting the journey
    trace = build_trace([], events=events)
    assert validate_trace(trace) == []
    flows = [e for e in trace["traceEvents"] if e.get("cat") == "flow"]
    names = {e["name"] for e in flows}
    assert "resume" in names, "drain->resume flow arrow missing"
    req_events = request_trace_events(events)
    starts = [e for e in req_events if e["ph"] == "s"]
    ends = [e for e in req_events if e["ph"] == "f"]
    assert starts and len(starts) == len(ends)
    for sev in starts:
        (fev,) = [e for e in ends if e["id"] == sev["id"]]
        assert fev["ts"] >= sev["ts"], "flow arrow points backwards"


@pytest.mark.parametrize(
    "family",
    # slow tier (PR-19 budget payback): each param compiles a fresh
    # engine pair.  Fast-tier holders: the dense shared-engine spec
    # tests above (test_spec_drain_resume_exact_parity,
    # test_spec_sampled_deterministic_replay) prove the speculative
    # verify/rollback machinery, and test_serving.py's staggered matrix
    # proves the gqa/sliding attention variants under paged decode.
    [pytest.param(f, marks=pytest.mark.slow) for f in ("gqa", "sliding")])
def test_spec_family_parity(family):
    """Acceptance matrix: temp-0 speculative paged decode bit-equals
    non-speculative ``generate()`` for the GQA and sliding-window
    families too (dense is covered by the shared-engine tests)."""
    cfg = FAMILY_CFGS[family]
    params = init_gpt_params(jax.random.PRNGKey(0), cfg)
    prompts = np.stack([_prompt(10 + i, 5, cfg) for i in range(2)])
    want = np.asarray(jax.jit(
        lambda p, t: generate(p, t, cfg, max_new_tokens=NEW)
    )(params, jnp.asarray(prompts)))
    eng = ServingEngine(params, cfg, num_slots=2, block_size=BS,
                        chunk=CHUNK, prefix_cache=True, spec_k=K)
    r0 = eng.submit(Request(prompts[0].tolist(), NEW))
    eng.step()
    eng.step()  # slot 0 decoding when slot 1 admits: staggered offsets
    r1 = eng.submit(Request(prompts[1].tolist(), NEW))
    _run_audited(eng)
    for rid, row in ((r0, 0), (r1, 1)):
        np.testing.assert_array_equal(
            eng.finished[rid]["tokens"], want[row],
            err_msg=f"{family}: speculative decode diverged from generate()")
    s = eng.serving_summary()
    assert s["decode_signatures"] == 1 and s["prefill_signatures"] == 1
    assert 0.0 <= s["spec_accept_rate"] <= 1.0
    assert _validate_serving(s) == []
