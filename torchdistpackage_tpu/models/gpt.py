"""Flagship GPT-style model — the framework's end-to-end reference model,
playing the role of the reference's ``tensor_parallel/transformer.py`` test
model (transformer.py:88-100) scaled up to a *complete* LM: token + position
embeddings, a TP/SP block stack, final LN and LM head with cross-entropy.

TPU-first design decisions (vs the reference's torch modules):

- **Vocab-parallel embedding and LM head** (the Megatron pattern the reference
  never implements — its models start at the hidden layer): the token
  embedding is sharded over the vocab dim on the ``tensor`` axis; lookup masks
  out-of-shard ids and ``psum``-s partial one-hot gathers.  The LM head is
  column-parallel over vocab, and the cross-entropy is computed **on the
  sharded logits** (max/psum/log-sum-exp over the tensor axis) so full
  ``[B, S, V]`` logits are never materialized — the dominant activation of an
  LM trains at 1/tp of the memory.
- **Layer stack as a ``lax.scan`` over stacked params** ([L, ...] leaves) —
  one compiled block body regardless of depth; shard the leading dim over
  ``pipe`` for pipeline parallelism (see :func:`gpt_pipeline_loss`).
- One implementation serves serial, TP, TP+SP, and TP+SP+PP execution: the
  parallelism is carried entirely by ``axis=`` arguments and PartitionSpecs.
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Any, Dict, Optional

import jax

from ..compat import axis_size
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..parallel.pipeline_parallel import pipeline_1f1b, pipeline_loss
from ..parallel.tensor_parallel import (
    RematMode,
    TransformerConfig,
    block_forward,
    block_param_specs,
    dense,
    scan_blocks,
    gather_from_sp,
    init_block_params,
    init_norm_params,
    layer_norm,
    norm_param_specs,
    split_to_sp,
)

PyTree = Any


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab_size: int
    dim: int
    nheads: int
    nlayers: int
    max_seq: int
    ffn_mult: int = 4
    causal: bool = True
    dtype: Any = jnp.float32
    # 'naive' | 'flash' (Pallas kernel) | 'ring' | 'ulysses' (context
    # parallel — sequence sharded over ``context_axis``, see ops/ring_attention)
    attn_impl: str = "naive"
    context_axis: Optional[str] = None  # mesh axis for 'ring'/'ulysses'
    cp_layout: str = "contiguous"  # 'zigzag' balances causal ring FLOPs
    dropout_rate: float = 0.0  # residual dropout (needs a dropout_key)
    # grouped-query attention: KV head count (None = MHA, 1 = MQA);
    # see TransformerConfig.kv_heads
    kv_heads: Optional[int] = None
    # position encoding: 'learned' (table added at embed, the reference
    # style) | 'rope' (rotary: q/k rotated at their global positions inside
    # attention; no pos_emb table — see TransformerConfig.rope).  RoPE
    # composes with CP (chunk-offset/zigzag positions) and GQA.
    pos: str = "learned"
    rope_theta: float = 10000.0
    # optional 'linear'/'llama3' rope-scaling dict (long-context
    # checkpoints; see tensor_parallel.layers._scaled_inv_freq)
    rope_scaling: "dict | None" = None
    # 'layer' | 'rms' and 'gelu' | 'swiglu' — the Llama family is
    # norm='rms', act='swiglu', pos='rope' (see :func:`llama_config`);
    # both are carried structurally by the param tree
    # (TransformerConfig.norm/act), so every parallel path (TP/SP/PP/CP,
    # ZeRO, checkpointing) serves both families unchanged.
    norm: str = "layer"
    act: str = "gelu"
    # explicit FFN hidden width (overrides ffn_mult) — Llama-style ~8d/3
    # widths are not integer multiples of d
    ffn_hidden: Optional[int] = None
    # norm epsilon: preserved from HF checkpoints (rms_norm_eps is 1e-5 or
    # 1e-6 depending on the family) by models/convert.py
    norm_eps: float = 1e-5
    # sliding-window attention (Mistral family) — see
    # TransformerConfig.sliding_window
    sliding_window: Optional[int] = None
    # Mixture-of-Experts (0 = dense model).  With ``moe_experts > 0`` every
    # ``moe_every``-th block's FFN becomes an expert layer (Switch-style
    # alternation); use the gpt_moe_* family (models/gpt_moe.py) which
    # handles the heterogeneous block list and the aux load-balance loss.
    moe_experts: int = 0
    moe_top_k: int = 2
    moe_every: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 1e-2
    # 'topk' only for this family: GPT is autoregressive and
    # 'expert_choice' routing is non-causal (each expert ranks the whole
    # sequence -> future-token leak), so gpt_moe rejects it at trace time.
    # EC remains available through moe_forward(causal=False) for
    # encoder/non-AR models built from the same MoE layer.
    moe_router: str = "topk"
    moe_dispatch: str = "auto"  # 'dense' | 'sorted' | 'pallas' | 'auto' (see MoEConfig)

    def __post_init__(self):
        if self.context_axis is not None and self.attn_impl not in ("ring", "ulysses"):
            raise ValueError(
                f"context_axis={self.context_axis!r} requires attn_impl "
                f"'ring' or 'ulysses' (got {self.attn_impl!r}): a chunk-local "
                f"attention with per-shard position offsets would be a "
                f"silently different model"
            )
        if self.cp_layout != "contiguous" and self.attn_impl != "ring":
            raise ValueError(
                f"cp_layout={self.cp_layout!r} applies to attn_impl='ring' "
                f"only (got {self.attn_impl!r})"
            )
        if self.pos not in ("learned", "rope"):
            raise ValueError(f"pos must be 'learned' or 'rope', got {self.pos!r}")

    @property
    def block(self) -> TransformerConfig:
        return TransformerConfig(
            dim=self.dim,
            nheads=self.nheads,
            nlayers=self.nlayers,
            ffn_mult=self.ffn_mult,
            causal=self.causal,
            dtype=self.dtype,
            attn_impl=self.attn_impl,
            context_axis=self.context_axis,
            cp_layout=self.cp_layout,
            dropout_rate=self.dropout_rate,
            kv_heads=self.kv_heads,
            rope=self.pos == "rope",
            rope_theta=self.rope_theta,
            rope_scaling=self.rope_scaling,
            norm=self.norm,
            act=self.act,
            ffn_hidden=self.ffn_hidden,
            norm_eps=self.norm_eps,
            sliding_window=self.sliding_window,
        )

    def num_params(self) -> int:
        D, V, L = self.dim, self.vocab_size, self.nlayers
        F = self.block.ffn_dim
        if self.kv_heads is not None and self.kv_heads != self.nheads:
            Dkv = self.kv_heads * (D // self.nheads)
            attn = (D * D + D) + (2 * D * Dkv + 2 * Dkv)  # wq/bq + wkv/bkv
        else:
            attn = 3 * D * D + 3 * D
        # swiglu stacks gate/up: one extra [D, F] + [F] vs the gelu MLP
        mlp = (3 * D * F + 2 * F + D) if self.act == "swiglu" else (2 * D * F + F + D)
        norm = D if self.norm == "rms" else 2 * D  # per norm site
        per_block = attn + D * D + D + mlp + 2 * norm
        pos = self.max_seq * D if self.pos == "learned" else 0
        return V * D + pos + L * per_block + norm + D * V


def llama_config(
    vocab_size: int,
    dim: int,
    nheads: int,
    nlayers: int,
    max_seq: int,
    kv_heads: Optional[int] = None,
    ffn_hidden: Optional[int] = None,
    rope_theta: float = 10000.0,
    rope_scaling: "dict | None" = None,
    dtype: Any = jnp.bfloat16,
    **kw,
) -> GPTConfig:
    """Llama-family preset: RMSNorm + SwiGLU + RoPE (+ GQA when ``kv_heads``
    is set) — the modern decoder recipe, composed entirely from existing
    framework levers, so every parallel path (TP/SP, PP incl. interleaved,
    CP ring/ulysses/zigzag, ZeRO/FSDP, remat incl. 'flash') serves it
    unchanged.  ``ffn_hidden`` defaults to the Llama width ceil(8d/3)
    rounded up to a multiple of 256 (TP- and MXU-friendly).

    One deliberate divergence: the framework keeps its (zero-initialized)
    bias leaves in attention/MLP where Llama is bias-free — structurally
    uniform with the GPT family, numerically inert at init."""
    if ffn_hidden is None:
        ffn_hidden = -(-8 * dim // 3)  # ceil
        ffn_hidden = -(-ffn_hidden // 256) * 256
    return GPTConfig(
        vocab_size=vocab_size,
        dim=dim,
        nheads=nheads,
        nlayers=nlayers,
        max_seq=max_seq,
        kv_heads=kv_heads,
        ffn_hidden=ffn_hidden,
        pos="rope",
        rope_theta=rope_theta,
        rope_scaling=rope_scaling,
        norm="rms",
        act="swiglu",
        dtype=dtype,
        **kw,
    )


# ------------------------------------------------------------------ embedding


def vocab_parallel_embed(
    tok_emb: jnp.ndarray, tokens: jnp.ndarray, axis: Optional[str] = None
) -> jnp.ndarray:
    """Token lookup from a vocab-sharded embedding table.

    ``tok_emb``: [V_local, D] (the local shard; V_local == V when serial).
    Out-of-shard ids contribute zeros; a ``psum`` over the tensor axis
    assembles the full embedding.  Backward is the transpose scatter-add into
    the local shard only — no gradient communication for the table."""
    if axis is None:
        return jnp.take(tok_emb, tokens, axis=0)
    v_loc = tok_emb.shape[0]
    offset = jax.lax.axis_index(axis) * v_loc
    local = tokens - offset
    valid = (local >= 0) & (local < v_loc)
    emb = jnp.take(tok_emb, jnp.where(valid, local, 0), axis=0)
    emb = jnp.where(valid[..., None], emb, jnp.zeros((), emb.dtype))
    return jax.lax.psum(emb, axis)


def vocab_parallel_xent(
    logits: jnp.ndarray, targets: jnp.ndarray, axis: Optional[str] = None
) -> jnp.ndarray:
    """Mean token cross-entropy on vocab-sharded logits.

    ``logits``: [..., V_local]; ``targets``: int [...].  Log-sum-exp and the
    target-logit gather each close with one small collective over the tensor
    axis — the full softmax is never formed."""
    if axis is None:
        lse = jax.nn.logsumexp(logits, axis=-1)
        tl = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
        return jnp.mean(lse - tl)
    v_loc = logits.shape[-1]
    offset = jax.lax.axis_index(axis) * v_loc
    # the max shift is gradient-neutral (and pmax has no AD rule)
    m = jax.lax.pmax(jnp.max(jax.lax.stop_gradient(logits), axis=-1), axis)
    z = jax.lax.psum(jnp.sum(jnp.exp(logits - m[..., None]), axis=-1), axis)
    lse = jnp.log(z) + m
    local = targets - offset
    valid = (local >= 0) & (local < v_loc)
    tl = jnp.take_along_axis(logits, jnp.where(valid, local, 0)[..., None], axis=-1)[..., 0]
    tl = jax.lax.psum(jnp.where(valid, tl, jnp.zeros((), tl.dtype)), axis)
    return jnp.mean(lse - tl)


# -------------------------------------------------------------------- forward


def gpt_embed(
    params: Dict[str, PyTree],
    tokens: jnp.ndarray,
    axis: Optional[str] = None,
    context_axis: Optional[str] = None,
    cp_layout: str = "contiguous",
):
    """[B, S] ids -> [B, S, D] hidden.  With ``context_axis`` the tokens are
    the context-LOCAL chunk [B, S/cp] and the position embedding follows the
    shard's global positions: contiguous (shard i owns
    [i*S_loc, (i+1)*S_loc)) or zigzag (chunks i and 2n-1-i — gather the
    owned rows)."""
    S = tokens.shape[-1]
    h = vocab_parallel_embed(params["tok_emb"], tokens, axis)
    if "pos_emb" not in params:  # rope: positions enter inside attention
        return h
    if context_axis is None:
        return h + params["pos_emb"][:S]
    if cp_layout == "zigzag":
        from ..ops.ring_attention import zigzag_positions

        n = axis_size(context_axis)
        pos, _ = zigzag_positions(jax.lax.axis_index(context_axis), S, n)
        return h + jnp.take(params["pos_emb"], pos, axis=0)
    off = jax.lax.axis_index(context_axis) * S
    return h + jax.lax.dynamic_slice_in_dim(params["pos_emb"], off, S, axis=0)


def gpt_head(
    params: Dict[str, PyTree],
    h: jnp.ndarray,
    axis: Optional[str] = None,
    sp: bool = False,
    eps: float = 1e-5,
):
    """Final LN + column-parallel LM head.  Returns vocab-local logits
    [B, S, V_local] (full V when serial)."""
    h = layer_norm(h, params["ln_f"], eps)
    if axis is not None and sp:
        h = gather_from_sp(h, axis)
    return dense(h, params["head"])


def gpt_forward(
    params: Dict[str, PyTree],
    tokens: jnp.ndarray,
    cfg: GPTConfig,
    axis: Optional[str] = None,
    sp: bool = False,
    remat: RematMode = False,
    dropout_key: Optional[jax.Array] = None,
) -> jnp.ndarray:
    """tokens [B, S] -> logits [B, S, V_local].  Serial when ``axis`` is None,
    TP(/SP) inside shard_map otherwise.  ``remat`` checkpoints each block:
    False | True | 'flash' (save the flash kernel's residuals) |
    'flash_offload' (same, parked in pinned_host memory) — see
    :func:`..parallel.tensor_parallel.scan_blocks`.

    ``dropout_key`` enables residual dropout at ``cfg.dropout_rate``; under a
    mesh derive it with ``axis_unique_key(key, 'data')`` (utils/random.py) so
    data shards draw distinct masks while TP shards stay consistent.

    Context parallelism (``cfg.attn_impl`` 'ring'/'ulysses' +
    ``cfg.context_axis``): pass the context-LOCAL token chunk [B, S/cp]
    (in_spec ``P(None, context_axis)``); activations stay sequence-sharded
    end-to-end and only the attention op communicates over the context ring.
    The mean CE over local tokens then needs a ``pmean`` over the context
    axis, which the train step performs when the context axis is included in
    its data axes (the context axis IS a data axis for loss/grad purposes:
    equal shards make the global mean the mean of shard means)."""
    h = gpt_hidden(
        params, tokens, cfg, axis=axis, sp=sp, remat=remat,
        dropout_key=dropout_key,
    )
    return gpt_head(params, h, axis, sp, eps=cfg.norm_eps)


def gpt_hidden(
    params: Dict[str, PyTree],
    tokens: jnp.ndarray,
    cfg: GPTConfig,
    axis: Optional[str] = None,
    sp: bool = False,
    remat: RematMode = False,
    dropout_key: Optional[jax.Array] = None,
) -> jnp.ndarray:
    """tokens [B, S] -> post-blocks hidden [B, S(/tp if sp), D] — the shared
    embed + block-stack body of :func:`gpt_forward` and the streamed-CE path
    of :func:`gpt_loss` (one implementation, no drift)."""
    h = gpt_embed(params, tokens, axis, context_axis=cfg.context_axis, cp_layout=cfg.cp_layout)
    if axis is not None and sp:
        h = split_to_sp(h, axis)
    return scan_blocks(
        params["blocks"], h, cfg.block, axis, sp, remat=remat,
        dropout_key=dropout_key,
    )


def streamed_head_loss(
    params: Dict[str, PyTree],
    h: jnp.ndarray,
    targets: jnp.ndarray,
    axis: Optional[str] = None,
    chunk: int = 256,
    eps: float = 1e-5,
) -> jnp.ndarray:
    """Head + CE scanned over SEQUENCE chunks: the [B, S, V] logits are never
    materialized — each scan step computes one [B, chunk, V] slab, reduces it
    to its lse/target-logit, and discards it.  The serial/DP-mode analogue of
    the vocab-parallel CE's memory win (for GPT-125M at S=2048, V=32k the
    full logits are ~2 GB of HBM traffic per step).  Equal chunks, so the
    mean of chunk means is the token mean.  ``h``: post-blocks hidden
    [B, S, D] (pre final-LN)."""
    h = layer_norm(h, params["ln_f"], eps)
    B, S, D = h.shape
    if S % chunk != 0:
        raise ValueError(
            f"sequence length {S} not divisible by xent_chunk {chunk} — "
            f"the fallback would materialize the full logits the caller "
            f"opted out of"
        )
    n = S // chunk
    hc = h.reshape(B, n, chunk, D).transpose(1, 0, 2, 3)  # [n, B, chunk, D]
    tc = targets.reshape(B, n, chunk).transpose(1, 0, 2)

    # checkpoint the body: without it, AD stacks each slab's softmax
    # residuals to O(B*S*V) — exactly the memory this function avoids
    @functools.partial(jax.checkpoint, prevent_cse=False)
    def body(acc, xt):
        hh, tt = xt
        return acc + vocab_parallel_xent(dense(hh, params["head"]), tt, axis), None

    # the carry must be closed over the body's varying axes (DESIGN.md §2):
    # under a DP mesh h/targets are data-varying, so the accumulator is too
    from ..parallel.data_parallel import _mark_varying, _vma

    acc0 = _mark_varying(
        jnp.zeros((), jnp.float32), tuple(_vma(h) | _vma(targets))
    )
    total, _ = jax.lax.scan(body, acc0, (hc, tc))
    return total / n


def gpt_loss(
    params: Dict[str, PyTree],
    batch: Dict[str, jnp.ndarray],
    cfg: GPTConfig,
    axis: Optional[str] = None,
    sp: bool = False,
    remat: RematMode = False,
    dropout_key: Optional[jax.Array] = None,
    xent_chunk: Optional[int] = None,
) -> jnp.ndarray:
    """Mean next-token cross-entropy.  ``batch``: {'tokens': [B, S],
    'targets': [B, S]}.  ``xent_chunk`` streams the head+CE over sequence
    chunks of that size instead of materializing full logits
    (:func:`streamed_head_loss`)."""
    if xent_chunk is not None:
        h = gpt_hidden(
            params, batch["tokens"], cfg, axis=axis, sp=sp, remat=remat,
            dropout_key=dropout_key,
        )
        if axis is not None and sp:
            h = gather_from_sp(h, axis)
        return streamed_head_loss(
            params, h, batch["targets"], axis, chunk=xent_chunk,
            eps=cfg.norm_eps,
        )
    logits = gpt_forward(
        params, batch["tokens"], cfg, axis=axis, sp=sp, remat=remat,
        dropout_key=dropout_key,
    )
    return vocab_parallel_xent(logits, batch["targets"], axis)


# ------------------------------------------------------------------- pipeline


def gpt_pipeline_loss(
    params: Dict[str, PyTree],
    batch: Dict[str, jnp.ndarray],
    cfg: GPTConfig,
    num_microbatches: int,
    tp_axis: Optional[str] = None,
    pipe_axis: str = "pipe",
    sp: bool = False,
    remat: RematMode = True,
) -> jnp.ndarray:
    """Pipelined GPT loss (traced; call inside shard_map over a mesh with the
    ``pipe`` axis, optionally + ``tensor``/``data``).

    ``batch``: {'tokens': [M, mbs, S], 'targets': [M, mbs, S]} microbatched on
    the leading dim.  The embedding runs PER TICK inside the pipeline scan on
    stage 0 (its grad arrives via the shard_map transpose psum over ``pipe``,
    the analogue of tied-embedding grad sync), so only the raw int tokens —
    never M pre-embedded activations — stay resident; the block stack is the
    pipelined region (each stage scans its slab of the layer-stacked params);
    LN + head + vocab-parallel CE run in the last stage's per-microbatch
    loss."""
    M = num_microbatches
    tokens, targets = batch["tokens"], batch["targets"]

    def first_fn(p, toks):
        h = gpt_embed(p, toks, tp_axis, context_axis=cfg.context_axis, cp_layout=cfg.cp_layout)
        if tp_axis is not None and sp:
            h = split_to_sp(h, tp_axis)
        return h

    def stage_fn(stacked, x):
        return scan_blocks(stacked, x, cfg.block, tp_axis, sp)

    def mb_loss(y, tgt):
        logits = gpt_head(params, y, tp_axis, sp, eps=cfg.norm_eps)
        return vocab_parallel_xent(logits, tgt, tp_axis)

    return pipeline_loss(
        params["blocks"],
        tokens,
        targets,
        stage_fn=stage_fn,
        loss_fn=mb_loss,
        num_microbatches=M,
        pipe_axis=pipe_axis,
        remat=remat,
        first_fn=first_fn,
        params=params,
    )


def interleave_stage_params(
    params: Dict[str, PyTree], num_chunks: int, pipe_size: int
) -> Dict[str, PyTree]:
    """Reshape the ``[L, ...]``-stacked block leaves into the interleaved
    pipeline layout ``[V, P, L/(P*V), ...]``: chunk v of stage s holds global
    layer slab ``v*P + s`` (round-robin — exactly the reshape's index
    decomposition, v major).  Shard dim 1 over the pipe axis
    (:func:`gpt_interleaved_param_specs`)."""

    def r(a):
        L = a.shape[0]
        if L % (num_chunks * pipe_size) != 0:
            raise ValueError(
                f"nlayers {L} not divisible by num_chunks*pipe "
                f"({num_chunks}*{pipe_size})"
            )
        return a.reshape(
            num_chunks, pipe_size, L // (num_chunks * pipe_size), *a.shape[1:]
        )

    return {**params, "blocks": jax.tree.map(r, params["blocks"])}


def deinterleave_stage_params(
    params: Dict[str, PyTree], num_chunks: int, pipe_size: int
) -> Dict[str, PyTree]:
    """Inverse of :func:`interleave_stage_params`: ``[V, P, Lc, ...]`` block
    leaves back to the ``[L, ...]`` stacked layout (serial layer order).
    Lets a checkpoint written from interleaved training resume classic
    pipelined (or serial) training and vice versa — the layouts are pure
    reshapes of each other."""

    def r(a):
        if a.shape[:2] != (num_chunks, pipe_size):
            raise ValueError(
                f"leaf leading dims {a.shape[:2]} != (V={num_chunks}, "
                f"P={pipe_size}) — not an interleaved layout"
            )
        return a.reshape(num_chunks * pipe_size * a.shape[2], *a.shape[3:])

    return {**params, "blocks": jax.tree.map(r, params["blocks"])}


def gpt_interleaved_param_specs(
    cfg: GPTConfig,
    tp_axis: Optional[str] = None,
    pipe_axis: str = "pipe",
) -> Dict[str, PyTree]:
    """Specs for the :func:`interleave_stage_params` layout: block leaves are
    ``[V, P, Lc, ...]`` with dim 1 (the stage dim) sharded over ``pipe``."""
    base = gpt_param_specs(cfg, tp_axis=tp_axis, pipe_axis=None)
    blocks = jax.tree.map(
        # [L, ...] spec (None, *dims) -> [V, P, Lc, ...] spec
        lambda s: P(None, pipe_axis, None, *tuple(s)[1:]),
        base["blocks"],
        is_leaf=lambda x: isinstance(x, P),
    )
    return {**base, "blocks": blocks}


def gpt_pipeline_1f1b(
    params: Dict[str, PyTree],
    batch: Dict[str, jnp.ndarray],
    cfg: GPTConfig,
    num_microbatches: int,
    tp_axis: Optional[str] = None,
    pipe_axis: str = "pipe",
    sp: bool = False,
    remat: RematMode = True,
    dropout_key: Optional[jax.Array] = None,
    num_chunks: int = 1,
    shard_transfers: Optional[bool] = None,
):
    """1F1B-scheduled GPT training step core: returns ``(loss, grads)``
    directly (do NOT wrap in ``jax.grad`` — see
    :func:`...pipeline_parallel.pipeline_1f1b`).  Peak live activations are
    O(pipe_size), independent of the microbatch count, matching the
    reference's steady-state interleave
    (pipeline_parallel/pipeline_sched.py:163-211).

    Stage ownership: stage 0 embeds (per tick), the last stage runs LN + head
    + vocab-parallel CE inside its backward unit; embed/head grads are
    psum-ed over ``pipe`` once at the end.

    ``batch``: {'tokens': [M, mbs, S], 'targets': [M, mbs, S]}.

    ``dropout_key`` enables residual dropout through the pipeline: the key is
    folded with the stage index and the microbatch index (the schedule hands
    ``stage_fn`` the latter via ``stage_takes_mb``), and scan_blocks folds
    the local layer index — so every (stage, microbatch, layer) draws a
    distinct mask, and the 1F1B backward's recompute replays the exact same
    chain deterministically.  Derive the key per the usual recipe
    (``axis_unique_key(key, 'data')``) so data shards differ too.

    ``num_chunks`` (V > 1) runs the INTERLEAVED schedule (virtual pipeline
    stages — see ``pipeline_1f1b``): pass params in the
    :func:`interleave_stage_params` layout with
    :func:`gpt_interleaved_param_specs`; requires ``M % pipe == 0``.

    ``shard_transfers`` (default: auto — on exactly when ``tp_axis`` is set
    and ``sp`` is off): carry the inter-stage activation sliced 1/tp over
    the tensor axis (``pipeline_1f1b(transfer_shard_axis=...)``, the
    ``scatter_gather_tensors`` analogue, comm.py:108-155) — pipe-edge bytes
    and ring-buffer memory drop by tp.  Under SP the state is already
    sequence-sharded, so there is nothing to slice.
    """
    if shard_transfers is None:
        shard_transfers = tp_axis is not None and not sp
    transfer_shard_axis = tp_axis if shard_transfers else None

    def first_fn(p, toks):
        h = gpt_embed(p, toks, tp_axis, context_axis=cfg.context_axis, cp_layout=cfg.cp_layout)
        if tp_axis is not None and sp:
            h = split_to_sp(h, tp_axis)
        return h

    def fold_key(m, extra):
        k = None
        if dropout_key is not None and cfg.dropout_rate > 0.0:
            k = jax.random.fold_in(dropout_key, jax.lax.axis_index(pipe_axis))
            k = jax.random.fold_in(k, m)
            if extra is not None:
                k = jax.random.fold_in(k, extra)
        return k

    if num_chunks == 1:

        def stage_fn(p, x, m):
            return scan_blocks(
                p["blocks"], x, cfg.block, tp_axis, sp, remat=remat,
                dropout_key=fold_key(m, None),
            )

    else:

        def stage_fn(p, x, m, v):
            # local leaves are [V, 1, Lc, ...]; select chunk v's slab
            slab = jax.tree.map(
                lambda a: jax.lax.dynamic_index_in_dim(
                    a, v, axis=0, keepdims=False
                )[0],
                p["blocks"],
            )
            return scan_blocks(
                slab, x, cfg.block, tp_axis, sp, remat=remat,
                dropout_key=fold_key(m, v),
            )

    def last_fn(p, y, tgt):
        logits = gpt_head(p, y, tp_axis, sp, eps=cfg.norm_eps)
        return vocab_parallel_xent(logits, tgt, tp_axis)

    return pipeline_1f1b(
        params,
        batch["tokens"],
        batch["targets"],
        first_fn=first_fn,
        stage_fn=stage_fn,
        last_fn=last_fn,
        num_microbatches=num_microbatches,
        pipe_axis=pipe_axis,
        stage_takes_mb=True,
        num_chunks=num_chunks,
        transfer_shard_axis=transfer_shard_axis,
    )


def gpt_pipeline_zb(
    params: Dict[str, PyTree],
    batch: Dict[str, jnp.ndarray],
    cfg: GPTConfig,
    num_microbatches: int,
    tp_axis: Optional[str] = None,
    pipe_axis: str = "pipe",
    sp: bool = False,
    remat: RematMode = True,
    dropout_key: Optional[jax.Array] = None,
    shard_transfers: Optional[bool] = None,
):
    """Zero-bubble GPT training step core: the :func:`gpt_pipeline_1f1b`
    contract (returns ``(loss, grads)`` directly) on the
    :func:`...pipeline_parallel.pipeline_zb_1f1b` schedule — backward
    split into a dgrad wavefront plus an M-tick wgrad drain; same stage
    ownership (stage 0 embeds, last stage runs LN + head + vocab-parallel
    CE), same dropout-key recipe (the key folds (stage, microbatch), so
    the dgrad AND wgrad recomputes replay identical masks).  No
    interleaved (``num_chunks``) variant; ``shard_transfers`` defaults on
    exactly when ``tp_axis`` is set and ``sp`` is off, as in the classic
    schedule."""
    from ..parallel.pipeline_parallel import pipeline_zb_1f1b

    if shard_transfers is None:
        shard_transfers = tp_axis is not None and not sp

    def first_fn(p, toks):
        h = gpt_embed(p, toks, tp_axis, context_axis=cfg.context_axis,
                      cp_layout=cfg.cp_layout)
        if tp_axis is not None and sp:
            h = split_to_sp(h, tp_axis)
        return h

    def stage_fn(p, x, m):
        k = None
        if dropout_key is not None and cfg.dropout_rate > 0.0:
            k = jax.random.fold_in(
                dropout_key, jax.lax.axis_index(pipe_axis))
            k = jax.random.fold_in(k, m)
        return scan_blocks(
            p["blocks"], x, cfg.block, tp_axis, sp, remat=remat,
            dropout_key=k,
        )

    def last_fn(p, y, tgt):
        logits = gpt_head(p, y, tp_axis, sp, eps=cfg.norm_eps)
        return vocab_parallel_xent(logits, tgt, tp_axis)

    return pipeline_zb_1f1b(
        params,
        batch["tokens"],
        batch["targets"],
        first_fn=first_fn,
        stage_fn=stage_fn,
        last_fn=last_fn,
        num_microbatches=num_microbatches,
        pipe_axis=pipe_axis,
        stage_takes_mb=True,
        transfer_shard_axis=tp_axis if shard_transfers else None,
    )


# ----------------------------------------------------------------- init/specs


def init_gpt_params(key, cfg: GPTConfig) -> Dict[str, PyTree]:
    ke, kp, kh, kb = jax.random.split(key, 4)
    D, V, S = cfg.dim, cfg.vocab_size, cfg.max_seq
    dt = cfg.dtype
    keys = jax.random.split(kb, cfg.nlayers)
    blocks = [init_block_params(k, cfg.block) for k in keys]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs, axis=0), *blocks)
    out = {
        "tok_emb": (jax.random.normal(ke, (V, D)) * 0.02).astype(dt),
        "blocks": stacked,
        "ln_f": init_norm_params(D, dt, cfg.norm),
        "head": (jax.random.normal(kh, (D, V)) * (1.0 / math.sqrt(D))).astype(dt),
    }
    if cfg.pos == "learned":  # rope models carry no position table
        out["pos_emb"] = (jax.random.normal(kp, (S, D)) * 0.02).astype(dt)
    return out


def gpt_param_specs(
    cfg: GPTConfig,
    tp_axis: Optional[str] = None,
    pipe_axis: Optional[str] = None,
) -> Dict[str, PyTree]:
    """PartitionSpec tree: vocab-sharded embedding/head over ``tp_axis``,
    block stack sharded over ``pipe_axis`` on the layer dim composed with the
    per-block TP specs."""
    from ..parallel.tensor_parallel import stacked_block_specs

    blocks = stacked_block_specs(
        tp_axis, stack_axis=pipe_axis, gqa=cfg.block.is_gqa,
        norm=cfg.norm, act=cfg.act)
    out = {
        "tok_emb": P(tp_axis, None) if tp_axis else P(),
        "blocks": blocks,
        "ln_f": norm_param_specs(cfg.norm),
        "head": P(None, tp_axis) if tp_axis else P(),
    }
    if cfg.pos == "learned":
        out["pos_emb"] = P()
    return out
