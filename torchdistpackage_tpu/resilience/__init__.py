"""resilience — survive faults instead of merely observing them.

PR 1–3 built the observability to *see* failures (preemption, NaN
watchdog, straggler events, the comm ledger, RUNREPORT); this subsystem
is the machinery to *survive* them, plus the chaos harness that proves it:

- :mod:`.chaos` — deterministic, seed-driven fault injection (checkpoint
  corruption, mid-step SIGTERM, NaN/Inf spikes, per-host stalls, host
  dropout); every injection is a structured ``fault_injected`` event, so
  recovery is asserted against the timeline.
- :mod:`.ckpt_guard` — hardened checkpoint I/O: bounded retry with
  exponential backoff + jitter, per-checkpoint integrity manifests
  (file hashes + per-leaf tree spec) written at commit and verified at
  restore, quarantine-and-fall-back for checkpoints that fail.
- :mod:`.loop` — :class:`ResilientLoop`, the self-healing driver:
  divergence monitor (non-finite / loss-spike z-score) → rollback to the
  last good checkpoint → advance the data stream past the poisoned
  window → clean abort with a RUNREPORT verdict once the retry budget is
  spent.  Exact-trajectory parity with an unfaulted run when no fault
  fires.
- :mod:`.watchdog` — heartbeat hang detection (``hang_suspected`` →
  configurable hard abort so the babysitter can relaunch) and cross-host
  consistency guards (step / config hash / code hash / RNG / param
  checksum agreement via one small allgather → ``desync_detected``).

Like ``obs``, this package imports the rest of the repo lazily where
possible so the chaos/verification helpers stay usable from lightweight
tooling.
"""

from .chaos import (
    ENGINE_FAULT_KINDS,
    FAULT_KINDS,
    TRANSPORT_FAULT_KINDS,
    ChaosMonkey,
    Fault,
    corrupt_checkpoint,
)
from .ckpt_guard import (
    CheckpointCorruptError,
    GuardedCheckpointManager,
    manifest_path,
    quarantine_checkpoint,
    quarantine_dir,
    tree_spec,
    verify_checkpoint,
    verify_template,
    with_retries,
    write_manifest,
)
from .loop import DivergenceMonitor, LoopResult, ResilientLoop
from .watchdog import (
    Watchdog,
    check_consistency,
    code_fingerprint,
    config_fingerprint,
    consistency_fingerprint,
    param_checksum,
)

__all__ = [
    "ENGINE_FAULT_KINDS",
    "FAULT_KINDS",
    "TRANSPORT_FAULT_KINDS",
    "ChaosMonkey",
    "Fault",
    "corrupt_checkpoint",
    "CheckpointCorruptError",
    "GuardedCheckpointManager",
    "manifest_path",
    "quarantine_checkpoint",
    "quarantine_dir",
    "tree_spec",
    "verify_checkpoint",
    "verify_template",
    "with_retries",
    "write_manifest",
    "DivergenceMonitor",
    "LoopResult",
    "ResilientLoop",
    "Watchdog",
    "check_consistency",
    "code_fingerprint",
    "config_fingerprint",
    "consistency_fingerprint",
    "param_checksum",
]
