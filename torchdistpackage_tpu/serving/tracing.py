"""Serving observability: request-lifecycle tracing + tick-level accounting.

The engine's event timeline (obs/events.py) records every lifecycle
TRANSITION — admitted, preempted, shed, retired — but a transition log is
not a *trace*: "where did request 17's four seconds go?" needs spans, and
"what did tick 230 spend its time on?" needs per-tick attribution.  This
module closes both gaps, entirely HOST-side (it processes plain event
dicts; no device call, no new compiled program — the engine's
``decode_signatures == 1`` contract is untouched):

- **Request-lifecycle assembly** (:func:`assemble_request_timelines`).
  Replays the timeline into one record per request *instance*: phase
  spans (``queued`` → ``prefill`` → ``decode``, re-entering ``queued``
  on preemption / fault requeue), per-tick child spans (``prefill_chunk``
  / ``decode_tick`` / ``verify_tick`` from the ``engine_tick`` rid
  attribution), instant marks (``admitted``, ``preempted``,
  ``fault_requeued``, ``drained``), a terminal state, and drain→resume
  links (``request_resumed`` carries ``orig_rid``, so a restarted
  engine's request chains back to the instance it continues).  The
  ``sequence`` field is the ordered phase walk — what the acceptance
  tests assert lifecycle reconstruction against.
- **Perfetto rendering** (:func:`request_trace_events`,
  :func:`tick_trace_events`, :func:`serving_trace_events`).  Each
  request instance becomes one async track (Chrome ``b``/``e`` events
  keyed by ``cat="request", id=uid``) with nested phase and tick spans
  plus ``n`` instants; preempt→re-admit and drain→resume are flow
  arrows (``s``/``f``), so one request's journey across ticks,
  preemptions, and an engine restart renders CONNECTED in
  https://ui.perfetto.dev.  ``engine_tick`` events additionally become
  per-phase lanes (audit / sched / prefill / draft / decode / fetch /
  host, laid back-to-back from the tick start — the same reconstruction
  idiom as obs/trace.py's step spans) and counter tracks (queue depth,
  slot occupancy, batch utilization, pool utilization, live hit/accept
  rates).  ``obs.trace.chrome_trace_events`` appends all of it
  automatically when serving events are present, so
  ``decode_bench --serve --trace out.json`` (and ``TDP_TRACE``) just
  work.
- **Fleet stitching** (:func:`assemble_fleet_request_timelines`,
  :func:`fleet_trace_events`).  A multi-replica timeline — every engine
  tagged ``replica=i`` by the Router, router decisions interleaved —
  stitches each ROUTER rid's engine instances into one journey:
  ``request_routed`` names the first placement, ``request_migrated``
  (``src_rid``/``dst_rid``) each cross-replica hop, ``blocks_migrated``
  the priced KV legs.  The rendering gives each replica its own
  Perfetto process, the router a decision lane, and draws ``route`` /
  ``migrate`` flow arrows across processes, so a request that prefills
  on replica A and decodes on replica B reads as ONE connected track.
  ``serving_trace_events`` dispatches to it automatically when events
  carry replica tags.
- **Live export** (:func:`serving_metrics_record`).  Flattens a tick
  record into the documented ``serving_metrics`` schema
  (:data:`SERVING_METRICS_SCHEMA`; docs/serving.md "Serving
  observability") — the record shape the engine's ``metrics_sink=``
  writes through the existing :mod:`~..obs.exporters` sinks
  (Prometheus-textfile gauges / JSONL lines an external scraper can
  watch while the engine runs).
- **Operator table** (:func:`phase_table`) — the per-tick phase
  breakdown as text, printed by ``decode_bench --serve --trace`` next
  to the latency tables.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Sequence

#: Schema tag on every ``metrics_sink`` record (docs/serving.md
#: "Serving observability" documents the fields).
SERVING_METRICS_SCHEMA = "tdp-serving-metrics/v1"

#: Per-tick phases, in execution order (the order the lanes are laid
#: back-to-back from the tick start): invariant ``audit``, host
#: ``sched``-uling (expiry + admission + the COW flush), the ``prefill``
#: chunk dispatch, the host ``draft``-er (speculative only), the
#: ``decode``/verify dispatch, output ``fetch`` (device→host transfer,
#: including the telemetry sync), and the residual ``host`` walk.
TICK_PHASES = ("audit", "sched", "prefill", "draft", "decode", "fetch",
               "host")

#: Request phase-span vocabulary (re-entered on preemption/requeue).
REQUEST_PHASES = ("queued", "prefill", "decode")

#: Terminal states a request instance can reach.  ``exported`` ends an
#: instance on the engine that migrated it out; the importing engine's
#: instance (opened by ``request_imported``) continues the request.
REQUEST_TERMINALS = ("retired", "cancelled", "shed", "expired", "drained",
                     "exported")

#: Chrome tids for the tick phase lanes (obs/trace.py owns 0-4 for the
#: step spans; serving lanes start at 10).
TICK_TIDS = {name: 10 + i for i, name in enumerate(TICK_PHASES)}


def serving_metrics_record(rec: Dict[str, Any]) -> Dict[str, Any]:
    """Flatten one engine tick record into the ``serving_metrics`` sink
    schema: scalar gauges only (PrometheusTextfileSink turns every
    numeric field into a gauge; JsonlSink keeps the record whole)."""
    out: Dict[str, Any] = {
        "type": "serving_metrics",
        "schema": SERVING_METRICS_SCHEMA,
        "tick": rec["tick"],
        "tick_s": rec.get("tick_s", 0.0),
        "queue_depth": rec.get("queue_depth", 0),
        "busy_slots": rec.get("busy", 0),
        "prefill_slots": rec.get("prefill_slots", 0),
        "decode_slots": rec.get("decode_slots", 0),
        "batch_util": rec.get("batch_util", 0.0),
        "pool_util": rec.get("pool_util", 0.0),
        "admitted": rec.get("admitted", 0),
        "expired": rec.get("expired", 0),
        "emitted_tokens": rec.get("emitted_tokens", 0),
        "prefix_hit_rate": rec.get("prefix_hit_rate", 0.0),
        "spec_accept_rate": rec.get("spec_accept_rate", 0.0),
    }
    phases = rec.get("phases") or {}
    for name in TICK_PHASES:
        out[f"phase_{name}_s"] = float(phases.get(name, 0.0))
    return out


# ------------------------------------------------------ lifecycle assembly


def _new_record(rid: int, instance: int) -> Dict[str, Any]:
    return {
        "rid": int(rid),
        "uid": f"{int(rid)}.{instance}",
        "spans": [],        # [{"name", "t0", "t1"}] phase-level
        "ticks": [],        # [{"name", "tick", "t0", "t1"}] per-tick children
        "marks": [],        # [{"name", "t"}] instants
        "sequence": [],     # ordered phase/mark walk (the lifecycle)
        "terminal": None,
        "resumed_from": None,
        "resumed_to": None,
        "preemptions": 0,
        "args": {},
        "_phase": None,
        "_t_phase": None,
    }


def _open_phase(rec: Dict[str, Any], name: str, t: float) -> None:
    rec["_phase"], rec["_t_phase"] = name, t
    rec["sequence"].append(name)


def _close_phase(rec: Dict[str, Any], t: float) -> None:
    if rec["_phase"] is None:
        return
    t0 = rec["_t_phase"]
    rec["spans"].append(
        {"name": rec["_phase"], "t0": t0, "t1": max(t, t0)})
    rec["_phase"] = rec["_t_phase"] = None


def _mark(rec: Dict[str, Any], name: str, t: float) -> None:
    rec["marks"].append({"name": name, "t": t})
    rec["sequence"].append(name)


def assemble_request_timelines(
    events: Iterable[Dict[str, Any]],
) -> List[Dict[str, Any]]:
    """Replay an event timeline into per-request-instance lifecycle
    records (submission order).  Tolerant of a log attached mid-run: an
    event for a request whose submission was never seen opens a fresh
    record at that event.  Request ids restart at 0 per engine, so
    instances are keyed ``uid = "<rid>.<n>"`` — a reused rid (several
    engines sharing one timeline, or drain→resume) gets a NEW instance,
    and ``request_resumed`` links the new instance to the one it
    continues (``resumed_from`` / ``resumed_to``)."""
    records: List[Dict[str, Any]] = []
    open_by_rid: Dict[int, Dict[str, Any]] = {}
    all_by_rid: Dict[int, List[Dict[str, Any]]] = {}

    def start(rid: int, t: float) -> Dict[str, Any]:
        rec = _new_record(rid, len(all_by_rid.get(rid, [])))
        records.append(rec)
        open_by_rid[rid] = rec
        all_by_rid.setdefault(rid, []).append(rec)
        _open_phase(rec, "queued", t)
        return rec

    def ensure(rid: int, t: float) -> Dict[str, Any]:
        rec = open_by_rid.get(rid)
        return rec if rec is not None else start(rid, t)

    def finish(rid: int, t: float, terminal: str) -> None:
        rec = ensure(rid, t)
        _close_phase(rec, t)
        rec["terminal"] = terminal
        rec["sequence"].append(terminal)
        open_by_rid.pop(rid, None)

    def requeue(rid: int, t: float, mark: str) -> None:
        rec = open_by_rid.get(rid)
        if rec is None:
            return
        _close_phase(rec, t)
        _mark(rec, mark, t)
        rec["preemptions"] += 1
        _open_phase(rec, "queued", t)

    for e in events:
        kind = e.get("kind")
        t = e.get("t_mono")
        if kind is None or t is None:
            continue
        rid = e.get("rid")
        if kind == "request_submitted":
            if rid in open_by_rid:  # rid reused without a terminal: rotate
                _close_phase(open_by_rid[rid], t)
                open_by_rid.pop(rid)
            rec = start(rid, t)
            rec["args"] = {
                k: e[k] for k in ("prompt_len", "max_new_tokens",
                                  "priority", "deadline_s")
                if e.get(k) is not None}
        elif kind == "request_resumed":
            rec = ensure(rid, t)
            parents = [r for r in all_by_rid.get(e.get("orig_rid"), [])
                       if r is not rec]
            if parents:
                rec["resumed_from"] = parents[-1]["uid"]
                parents[-1]["resumed_to"] = rec["uid"]
        elif kind == "request_admitted":
            rec = ensure(rid, t)
            _close_phase(rec, t)
            _mark(rec, "admitted", t)
            _open_phase(rec, "prefill", t)
        elif kind == "engine_tick":
            t0 = e.get("t_start", t)
            spec = bool(e.get("spec"))
            for r in e.get("prefill_rids") or []:
                rec = open_by_rid.get(r)
                if rec is not None:
                    rec["ticks"].append({"name": "prefill_chunk",
                                         "tick": e.get("tick"),
                                         "t0": t0, "t1": t})
            for r in e.get("decode_rids") or []:
                rec = open_by_rid.get(r)
                if rec is None:
                    continue
                if rec["_phase"] == "prefill":
                    # the final prefill chunk and the first decode run in
                    # ONE tick, and admission may also have happened mid-
                    # tick — clamp the switch so phases never overlap
                    t_sw = max(t0, rec["_t_phase"] if rec["_t_phase"]
                               is not None else t0)
                    _close_phase(rec, t_sw)
                    _open_phase(rec, "decode", t_sw)
                rec["ticks"].append(
                    {"name": "verify_tick" if spec else "decode_tick",
                     "tick": e.get("tick"), "t0": t0, "t1": t})
        elif kind == "request_preempted":
            requeue(rid, t, "preempted")
        elif kind == "engine_recovered":
            rids = e.get("requeued_rids")
            if rids is None:
                rids = [rid] if (rid is not None
                                 and e.get("action") == "requeued") else []
            for r in rids:
                requeue(r, t, "fault_requeued")
        elif kind == "request_imported":
            # a migrated-in instance: opens straight in DECODE (no queue,
            # no prefill — the KV arrives by migrate_blocks).  orig_rid
            # names the SRC-engine instance; on a per-engine timeline
            # that rid lives in another engine's namespace, so the
            # cross-engine link is stitched at fleet scope
            # (assemble_fleet_request_timelines), not here.
            if rid in open_by_rid:  # rid reused without a terminal: rotate
                _close_phase(open_by_rid[rid], t)
                open_by_rid.pop(rid)
            rec = _new_record(rid, len(all_by_rid.get(rid, [])))
            records.append(rec)
            open_by_rid[rid] = rec
            all_by_rid.setdefault(rid, []).append(rec)
            rec["args"] = {
                k: e[k] for k in ("orig_rid", "n_shared", "n_live",
                                  "emitted_tokens")
                if e.get(k) is not None}
            _mark(rec, "imported", t)
            _open_phase(rec, "decode", t)
        elif kind == "request_exported":
            finish(rid, t, "exported")
        elif kind == "request_retired":
            finish(rid, t, "retired")
        elif kind == "request_cancelled":
            finish(rid, t, "cancelled")
        elif kind == "request_shed":
            finish(rid, t, "shed")
        elif kind == "request_expired":
            finish(rid, t, "expired")
        elif kind == "engine_drained":
            for r in list(open_by_rid):
                rec = open_by_rid[r]
                _close_phase(rec, t)
                _mark(rec, "drained", t)
                rec["terminal"] = "drained"
                open_by_rid.pop(r)
    return records


def lifecycle_phases(record: Dict[str, Any]) -> List[str]:
    """The ordered phase/mark walk of one request instance — e.g.
    ``['queued', 'admitted', 'prefill', 'decode', 'preempted', 'queued',
    'drained']`` — what "the lifecycle reconstructs from the trace"
    means, concretely."""
    return list(record["sequence"])


def validate_request_record(record: Dict[str, Any]) -> List[str]:
    """Structural checks on one assembled record: known vocabulary,
    spans time-ordered and non-negative, tick children inside the
    record's overall window.  Returns problem strings (empty = good)."""
    errs: List[str] = []
    uid = record.get("uid", "?")
    last_t = None
    for s in record["spans"]:
        if s["name"] not in REQUEST_PHASES:
            errs.append(f"{uid}: unknown phase {s['name']!r}")
        if s["t1"] < s["t0"]:
            errs.append(f"{uid}: span {s['name']} ends before it starts")
        if last_t is not None and s["t0"] < last_t - 1e-9:
            errs.append(f"{uid}: span {s['name']} overlaps its predecessor")
        last_t = s["t1"]
    term = record.get("terminal")
    if term is not None and term not in REQUEST_TERMINALS:
        errs.append(f"{uid}: unknown terminal {term!r}")
    if record["spans"]:
        lo = record["spans"][0]["t0"] - 1e-9
        hi = record["spans"][-1]["t1"] + 1e-9
        for c in record["ticks"]:
            if c["t0"] < lo or c["t1"] > hi:
                errs.append(f"{uid}: tick child {c['name']} outside spans")
                break
    return errs


# ------------------------------------------------------- Perfetto rendering


def _serving_t0(events: Sequence[Dict[str, Any]]) -> Optional[float]:
    ts = [e.get("t_start", e["t_mono"]) for e in events if "t_mono" in e]
    return min(ts) if ts else None


def request_trace_events(
    events: Sequence[Dict[str, Any]],
    process: int = 0,
    t0: Optional[float] = None,
) -> List[Dict[str, Any]]:
    """Chrome trace events for the per-request tracks: one async track
    per request instance (``cat="request"``, ``id=uid``) holding the
    outer request span, nested phase spans, per-tick children, and
    instant marks; flow arrows (``s``/``f``) connect a preemption to its
    re-admission and a drained instance to the instance that resumes
    it."""
    records = assemble_request_timelines(events)
    if t0 is None:
        t0 = _serving_t0(events)
    if t0 is None:
        return []

    def us(t: float) -> float:
        return round(max(t - t0, 0.0) * 1e6, 3)

    out: List[Dict[str, Any]] = []
    by_uid = {r["uid"]: r for r in records}

    def window(rec):
        ts = ([s["t0"] for s in rec["spans"]]
              + [s["t1"] for s in rec["spans"]]
              + [m["t"] for m in rec["marks"]])
        return (min(ts), max(ts)) if ts else None

    for rec in records:
        win = window(rec)
        if win is None:
            continue
        base = {"cat": "request", "id": rec["uid"], "pid": process, "tid": 0}
        args = dict(rec["args"])
        if rec["terminal"]:
            args["terminal"] = rec["terminal"]
        if rec["resumed_from"]:
            args["resumed_from"] = rec["resumed_from"]
        out.append({"ph": "b", "name": f"req{rec['rid']}",
                    "ts": us(win[0]), "args": args, **base})
        for s in rec["spans"]:
            out.append({"ph": "b", "name": s["name"], "ts": us(s["t0"]),
                        **base})
            out.append({"ph": "e", "name": s["name"], "ts": us(s["t1"]),
                        **base})
        for c in rec["ticks"]:
            out.append({"ph": "b", "name": c["name"], "ts": us(c["t0"]),
                        "args": {"tick": c.get("tick")}, **base})
            out.append({"ph": "e", "name": c["name"], "ts": us(c["t1"]),
                        **base})
        for m in rec["marks"]:
            out.append({"ph": "n", "name": m["name"], "ts": us(m["t"]),
                        **base})
        out.append({"ph": "e", "name": f"req{rec['rid']}",
                    "ts": us(win[1]), **base})
        # preempt/fault requeue -> next admission, as flow arrows
        readmits = [m["t"] for m in rec["marks"] if m["name"] == "admitted"]
        for i, m in enumerate(m for m in rec["marks"]
                              if m["name"] in ("preempted",
                                               "fault_requeued")):
            nxt = [t for t in readmits if t >= m["t"]]
            if not nxt:
                continue
            fid = f"requeue-{rec['uid']}-{i}"
            flow = {"cat": "flow", "name": "requeue", "id": fid,
                    "pid": process, "tid": 0}
            out.append({"ph": "s", "ts": us(m["t"]), **flow})
            out.append({"ph": "f", "bp": "e", "ts": us(nxt[0]), **flow})
        # drain -> resume, across engine instances
        if rec["resumed_from"] and rec["resumed_from"] in by_uid:
            parent = by_uid[rec["resumed_from"]]
            pwin = window(parent)
            if pwin is not None:
                fid = f"resume-{rec['uid']}"
                flow = {"cat": "flow", "name": "resume", "id": fid,
                        "pid": process, "tid": 0}
                out.append({"ph": "s", "ts": us(pwin[1]), **flow})
                out.append({"ph": "f", "bp": "e", "ts": us(win[0]), **flow})
    return out


def tick_trace_events(
    events: Sequence[Dict[str, Any]],
    process: int = 0,
    t0: Optional[float] = None,
) -> List[Dict[str, Any]]:
    """Chrome trace events for the tick accounting: per-phase lanes
    (``X`` spans laid back-to-back from each tick's start, the same
    reconstruction as obs/trace.py's step spans) plus counter tracks —
    queue depth, busy/prefill/decode slots, batch + pool utilization,
    and the live prefix-hit / spec-accept rates."""
    ticks = [e for e in events if e.get("kind") == "engine_tick"
             and "t_mono" in e]
    if not ticks:
        return []
    if t0 is None:
        t0 = _serving_t0(ticks)

    def us(t: float) -> float:
        return round(max(t - t0, 0.0) * 1e6, 3)

    out: List[Dict[str, Any]] = []
    for name, tid in TICK_TIDS.items():
        out.append({"ph": "M", "name": "thread_name", "pid": process,
                    "tid": tid, "args": {"name": f"tick/{name}"}})
        out.append({"ph": "M", "name": "thread_sort_index", "pid": process,
                    "tid": tid, "args": {"sort_index": tid}})
    for e in ticks:
        start = e.get("t_start", e["t_mono"])
        phases = e.get("phases") or {}
        cursor = start
        for name in TICK_PHASES:
            dur = float(phases.get(name, 0.0) or 0.0)
            if dur > 0:
                out.append({
                    "ph": "X", "name": name, "cat": "tick",
                    "pid": process, "tid": TICK_TIDS[name],
                    "ts": us(cursor), "dur": round(dur * 1e6, 3),
                    "args": {"tick": e.get("tick")},
                })
            cursor += dur
        ts = us(start)
        out.append({"ph": "C", "name": "serving_queue_depth",
                    "pid": process, "tid": 0, "ts": ts,
                    "args": {"queued": e.get("queue_depth", 0)}})
        out.append({"ph": "C", "name": "serving_slots", "pid": process,
                    "tid": 0, "ts": ts,
                    "args": {"busy": e.get("busy", 0),
                             "prefill": e.get("prefill_slots", 0),
                             "decode": e.get("decode_slots", 0)}})
        out.append({"ph": "C", "name": "serving_utilization",
                    "pid": process, "tid": 0, "ts": ts,
                    "args": {"batch": e.get("batch_util", 0.0),
                             "pool": e.get("pool_util", 0.0)}})
        out.append({"ph": "C", "name": "serving_rates", "pid": process,
                    "tid": 0, "ts": ts,
                    "args": {"prefix_hit": e.get("prefix_hit_rate", 0.0),
                             "spec_accept": e.get("spec_accept_rate",
                                                  0.0)}})
    return out


def serving_trace_events(
    events: Sequence[Dict[str, Any]],
    process: int = 0,
    t0: Optional[float] = None,
) -> List[Dict[str, Any]]:
    """Everything serving adds to a Chrome trace: request-flow tracks +
    tick lanes + counters.  ``obs.trace.chrome_trace_events`` calls this
    when serving events are on the timeline; pass the same ``t0`` the
    rest of the trace uses so both land on one axis.

    A FLEET timeline — engine events carrying the ``replica`` tag the
    Router stamps on each engine's log — dispatches to
    :func:`fleet_trace_events` instead: one Perfetto process per
    replica plus the router decision lane, so two engines' tick lanes
    never interleave on one track (``process`` is ignored; fleet pids
    are fixed by :func:`fleet_pid`)."""
    if t0 is None:
        t0 = _serving_t0([e for e in events if "t_mono" in e])
    if any(e.get("replica") is not None
           and e.get("kind") not in ROUTER_EVENT_KINDS for e in events):
        return fleet_trace_events(events, t0=t0)
    return (tick_trace_events(events, process=process, t0=t0)
            + request_trace_events(events, process=process, t0=t0))


# ------------------------------------------------------ fleet (multi-replica)

#: Event kinds emitted by the Router itself (the decision ledger + the
#: PR-15 routing/migration records).  On a fleet timeline these stay on
#: the router lane; everything else carrying a ``replica`` tag is an
#: engine event and belongs to that replica's stream.
ROUTER_EVENT_KINDS = frozenset({
    "route_decision", "request_routed", "handoff_decision",
    "rebalance_decision", "request_migrated", "blocks_migrated",
    "replica_degraded", "replica_up", "replica_down",
    # elastic fleet (PR 19): autoscaler evaluations and the migration
    # wire's retry/fallback records — router-tier decisions, so they
    # ride the router lane of a fleet trace
    "scale_decision", "migration_retry", "migration_fallback",
})

#: Chrome pid of the router decision lane in a fleet trace.
ROUTER_PID = 99


def fleet_pid(replica: int) -> int:
    """Chrome pid of replica ``i``'s process in a fleet trace."""
    return 100 + int(replica)


def _split_fleet_events(
    events: Iterable[Dict[str, Any]],
) -> tuple:
    """Split one shared fleet timeline into the router's own events and
    per-replica engine streams (keyed by the ``replica`` tag
    ``Router.__init__`` stamps on each engine's log)."""
    router_ev: List[Dict[str, Any]] = []
    streams: Dict[Any, List[Dict[str, Any]]] = {}
    for e in events:
        if e.get("kind") is None or e.get("t_mono") is None:
            continue
        if e["kind"] in ROUTER_EVENT_KINDS:
            router_ev.append(e)
        elif e.get("replica") is not None:
            streams.setdefault(e["replica"], []).append(e)
    return router_ev, streams


def _record_t0(rec: Dict[str, Any]) -> Optional[float]:
    ts = [s["t0"] for s in rec["spans"]] + [m["t"] for m in rec["marks"]]
    if rec.get("_t_phase") is not None:
        ts.append(rec["_t_phase"])
    return min(ts) if ts else None


def _record_t1(rec: Dict[str, Any]) -> Optional[float]:
    ts = [s["t1"] for s in rec["spans"]] + [m["t"] for m in rec["marks"]]
    if rec.get("_t_phase") is not None:
        ts.append(rec["_t_phase"])
    return max(ts) if ts else None


def _find_instance(
    records: Sequence[Dict[str, Any]], engine_rid: Any, t: float,
) -> Optional[Dict[str, Any]]:
    """The request instance a router record at time ``t`` refers to: the
    LATEST instance of that engine rid that had already started (engine
    rids are reused, so 'rid 3 on replica 1' alone is ambiguous — 'rid 3
    on replica 1 as of t' is not: the engine-side event precedes the
    router record that cites it)."""
    best, best_t = None, None
    for r in records:
        if r["rid"] != engine_rid:
            continue
        rt = _record_t0(r)
        if rt is None or rt > t + 1e-6:
            continue
        if best is None or rt >= best_t:
            best, best_t = r, rt
    return best


def assemble_fleet_request_timelines(
    events: Iterable[Dict[str, Any]],
) -> Dict[str, Any]:
    """Stitch one shared fleet timeline into per-ROUTER-rid journeys.

    Splits the timeline on the ``replica`` tag, assembles each replica's
    engine events with :func:`assemble_request_timelines` (uids become
    ``"r<replica>/<rid>.<n>"``), then walks the router's own records to
    link each router rid's engine instances in placement order:
    ``request_routed`` names the first hop (replica + engine rid), each
    ``request_migrated`` names the next (``src_rid``/``dst_rid`` pin the
    exact instances), and ``blocks_migrated`` prices the KV legs.

    Returns ``{"journeys", "replicas", "router_events"}``; each journey
    is ``{rid, hops, decisions, migrations, sequence, outcome}`` where
    ``sequence`` is the request's full cross-replica phase walk
    (``@replica<i>`` markers between hops) — what "a migrated request
    reconstructs from the trace alone" means at fleet scope."""
    router_ev, streams = _split_fleet_events(events)
    replicas: Dict[Any, List[Dict[str, Any]]] = {}
    for rep in sorted(streams):
        recs = assemble_request_timelines(streams[rep])
        rename = {r["uid"]: f"r{rep}/{r['uid']}" for r in recs}
        for r in recs:
            r["replica"] = rep
            r["uid"] = rename[r["uid"]]
            if r["resumed_from"] in rename:
                r["resumed_from"] = rename[r["resumed_from"]]
            if r["resumed_to"] in rename:
                r["resumed_to"] = rename[r["resumed_to"]]
        replicas[rep] = recs

    journeys: Dict[Any, Dict[str, Any]] = {}
    order: List[Dict[str, Any]] = []

    def journey(rid: Any) -> Dict[str, Any]:
        j = journeys.get(rid)
        if j is None:
            j = {"rid": rid, "hops": [], "decisions": [],
                 "migrations": [], "sequence": [], "outcome": None}
            journeys[rid] = j
            order.append(j)
        return j

    def uid_of(rep: Any, erid: Any, t: float) -> Optional[str]:
        rec = _find_instance(replicas.get(rep, ()), erid, t)
        return rec["uid"] if rec is not None else None

    for e in router_ev:
        kind, t, rid = e["kind"], e["t_mono"], e.get("rid")
        if kind == "route_decision":
            j = journey(rid)
            j["decisions"].append(
                {"kind": kind, "t": t, "outcome": e.get("outcome"),
                 "chosen": e.get("chosen")})
            if e.get("outcome") == "shed":
                j["outcome"] = "shed"
        elif kind == "request_routed":
            journey(rid)["hops"].append(
                {"replica": e.get("replica"),
                 "engine_rid": e.get("replica_rid"),
                 "uid": uid_of(e.get("replica"), e.get("replica_rid"), t),
                 "via": "routed", "t": t})
        elif kind == "handoff_decision":
            journey(rid)["decisions"].append(
                {"kind": kind, "t": t, "outcome": e.get("outcome"),
                 "chosen": e.get("chosen")})
        elif kind == "request_migrated":
            journey(rid)["hops"].append(
                {"replica": e.get("dst_replica"),
                 "engine_rid": e.get("dst_rid"),
                 "uid": uid_of(e.get("dst_replica"), e.get("dst_rid"), t),
                 "via": e.get("mode", "migrated"), "t": t,
                 "src_replica": e.get("src_replica"),
                 "src_rid": e.get("src_rid")})
        elif kind == "blocks_migrated":
            journey(rid)["migrations"].append(
                {"t": t, "src_replica": e.get("src_replica"),
                 "dst_replica": e.get("dst_replica"),
                 "n_blocks": e.get("n_blocks"),
                 "n_shared": e.get("n_shared"),
                 "bytes": e.get("bytes"),
                 "compressed": e.get("compressed"), "dcn": e.get("dcn")})

    by_uid = {r["uid"]: r
              for recs in replicas.values() for r in recs}
    for j in order:
        seq: List[str] = []
        for h in j["hops"]:
            rec = by_uid.get(h["uid"])
            if rec is None:
                continue
            seq.append(f"@replica{h['replica']}")
            seq.extend(rec["sequence"])
        j["sequence"] = seq
        if j["outcome"] is None and j["hops"]:
            last = by_uid.get(j["hops"][-1]["uid"])
            if last is not None:
                j["outcome"] = last["terminal"]
    return {"journeys": order, "replicas": replicas,
            "router_events": router_ev}


def fleet_trace_events(
    events: Sequence[Dict[str, Any]],
    t0: Optional[float] = None,
) -> List[Dict[str, Any]]:
    """Chrome trace events for a multi-replica fleet timeline: one
    Perfetto process per replica (pid :func:`fleet_pid`, carrying that
    engine's tick lanes + request tracks exactly as the single-engine
    renderer draws them), a ``router`` process (pid :data:`ROUTER_PID`)
    with one instant per decision-ledger record, a ``route`` flow arrow
    from each placement decision to the engine instance it created, and
    a ``migrate`` flow arrow across processes for every cross-replica
    hop — carrying the priced wire bytes from ``blocks_migrated`` — so
    a migrated request reads as ONE connected track in
    https://ui.perfetto.dev."""
    router_ev, streams = _split_fleet_events(events)
    all_ev = router_ev + [e for s in streams.values() for e in s]
    if t0 is None:
        t0 = _serving_t0(all_ev)
    if t0 is None:
        return []

    def us(t: float) -> float:
        return round(max(t - t0, 0.0) * 1e6, 3)

    fleet = assemble_fleet_request_timelines(events)
    by_uid = {r["uid"]: r
              for recs in fleet["replicas"].values() for r in recs}
    out: List[Dict[str, Any]] = [
        {"ph": "M", "name": "process_name", "pid": ROUTER_PID, "tid": 0,
         "args": {"name": "router"}},
        {"ph": "M", "name": "process_sort_index", "pid": ROUTER_PID,
         "tid": 0, "args": {"sort_index": ROUTER_PID}},
        {"ph": "M", "name": "thread_name", "pid": ROUTER_PID, "tid": 0,
         "args": {"name": "decisions"}},
    ]
    for rep in sorted(fleet["replicas"]):
        pid = fleet_pid(rep)
        out.append({"ph": "M", "name": "process_name", "pid": pid,
                    "tid": 0, "args": {"name": f"replica{rep}"}})
        out.append({"ph": "M", "name": "process_sort_index", "pid": pid,
                    "tid": 0, "args": {"sort_index": pid}})
        out.extend(tick_trace_events(streams[rep], process=pid, t0=t0))
        out.extend(request_trace_events(streams[rep], process=pid, t0=t0))
    # the router decision lane: every ledger record, with its evidence
    for e in router_ev:
        args = {k: v for k, v in e.items()
                if k not in ("type", "kind", "t_wall", "t_mono", "process")}
        out.append({"ph": "i", "name": e["kind"], "cat": "router",
                    "s": "t", "pid": ROUTER_PID, "tid": 0,
                    "ts": us(e["t_mono"]), "args": args})
    # flow arrows: router -> first placement, then hop -> hop
    for j in fleet["journeys"]:
        hops = [h for h in j["hops"] if h["uid"] in by_uid]
        if not hops:
            continue
        fid = f"route-{j['rid']}"
        out.append({"ph": "s", "cat": "flow", "name": "route", "id": fid,
                    "pid": ROUTER_PID, "tid": 0, "ts": us(hops[0]["t"])})
        out.append({"ph": "f", "bp": "e", "cat": "flow", "name": "route",
                    "id": fid, "pid": fleet_pid(hops[0]["replica"]),
                    "tid": 0, "ts": us(hops[0]["t"])})
        for k, h in enumerate(hops[1:]):
            src_rep = h.get("src_replica")
            src = _find_instance(
                fleet["replicas"].get(src_rep, ()), h.get("src_rid"),
                h["t"]) if src_rep is not None else None
            t_s = _record_t1(src) if src is not None else h["t"]
            t_s = h["t"] if t_s is None else min(t_s, h["t"])
            dst = by_uid[h["uid"]]
            t_f = _record_t0(dst)
            t_f = t_s if t_f is None else max(t_f, t_s)
            args = {"via": h["via"]}
            legs = [m for m in j["migrations"]
                    if m.get("src_replica") == src_rep
                    and m.get("dst_replica") == h["replica"]]
            if legs:
                leg = min(legs, key=lambda m: abs(m["t"] - h["t"]))
                args.update({kk: leg[kk] for kk in
                             ("n_blocks", "n_shared", "bytes",
                              "compressed", "dcn") if kk in leg})
            mid = f"mig-{j['rid']}-{k}"
            out.append({"ph": "s", "cat": "flow", "name": "migrate",
                        "id": mid, "pid": fleet_pid(src_rep)
                        if src_rep is not None else ROUTER_PID,
                        "tid": 0, "ts": us(t_s), "args": args})
            out.append({"ph": "f", "bp": "e", "cat": "flow",
                        "name": "migrate", "id": mid,
                        "pid": fleet_pid(h["replica"]), "tid": 0,
                        "ts": us(t_f)})
    return out


# ---------------------------------------------------------- operator table


def phase_table(events: Iterable[Dict[str, Any]]) -> str:
    """Text table of the per-tick phase breakdown over ``engine_tick``
    records — totals, mean ms, and share of accounted tick time per
    phase.  ``decode_bench --serve --trace`` prints it next to the
    latency tables."""
    ticks = [e for e in events if e.get("kind") == "engine_tick"]
    if not ticks:
        return "tick phase breakdown: no engine_tick records"
    totals = {name: 0.0 for name in TICK_PHASES}
    counts = {name: 0 for name in TICK_PHASES}
    for e in ticks:
        for name in TICK_PHASES:
            dur = float((e.get("phases") or {}).get(name, 0.0) or 0.0)
            totals[name] += dur
            counts[name] += 1 if dur > 0 else 0
    accounted = sum(totals.values()) or 1.0
    lines = [f"tick phase breakdown ({len(ticks)} ticks, "
             f"{accounted * 1e3:.1f} ms accounted):",
             f"  {'phase':<9} {'total_ms':>10} {'mean_ms':>9} "
             f"{'ticks':>6} {'share':>7}"]
    for name in TICK_PHASES:
        n = counts[name]
        lines.append(
            f"  {name:<9} {totals[name] * 1e3:>10.2f} "
            f"{(totals[name] / n * 1e3 if n else 0.0):>9.3f} "
            f"{n:>6} {totals[name] / accounted:>6.1%}")
    return "\n".join(lines)
