"""Preemption-safe training: save-on-signal + auto-resume, end to end.

The reference's only recovery mechanism relaunches the JOB from scratch
(slurm_job_monitor.py:97-122).  Here the training loop itself is
relaunch-safe: ``auto_resume`` restores the latest checkpoint (sharded,
via Orbax), ``GracefulShutdown`` traps SIGTERM/SIGINT so a preemption
writes a final checkpoint inside the grace window, and the babysitter's
relaunch then loses at most one save interval.

This example DEMONSTRATES the full cycle in one process: it trains, sends
itself a real SIGTERM mid-run (the preemption), saves and exits the loop,
then "relaunches" (fresh objects, same ckpt dir) and finishes — asserting
the resumed trajectory's final loss matches an uninterrupted run exactly.

- real TPU chips:      python examples/train_preemptible.py
- 8-device CPU sim:    TDP_CPU_SIM=8 python examples/train_preemptible.py
"""

import os
import signal
import tempfile

if os.environ.get("TDP_CPU_SIM"):
    # XLA_FLAGS handling is centralized in dist/overlap.py (test_repo_lint
    # bans direct writes); cpu_sim also pins the cpu platform, replacing
    # the old post-import jax.config.update dance.
    from torchdistpackage_tpu.dist.overlap import cpu_sim

    cpu_sim(os.environ["TDP_CPU_SIM"])

import jax

import jax.numpy as jnp
import numpy as np
import optax

from torchdistpackage_tpu import setup_distributed, tpc
from torchdistpackage_tpu.models import GPTConfig, gpt_loss, init_gpt_params
from torchdistpackage_tpu.parallel import ZeroOptimizer
from torchdistpackage_tpu.utils import (
    CheckpointManager,
    GracefulShutdown,
    auto_resume,
    fix_rand,
)

TOTAL_STEPS = 8
SAVE_EVERY = 2
PREEMPT_AT = 5  # the uninterruptible step after which SIGTERM arrives


def make_batch(cfg, ndev, step):
    # batch derived from the STEP, so an interrupted and a straight run see
    # identical data — the precondition for exact-trajectory resume
    k1, k2 = jax.random.split(jax.random.PRNGKey(1000 + step))
    batch = {
        "tokens": jax.random.randint(k1, (4 * ndev, cfg.max_seq), 0, cfg.vocab_size),
        "targets": jax.random.randint(k2, (4 * ndev, cfg.max_seq), 0, cfg.vocab_size),
    }
    return jax.tree.map(lambda a: jax.device_put(a, tpc.sharding("data")), batch)


def run(ckdir, cfg, ndev, preempt_at=None):
    """One 'launch': resume if a checkpoint exists, train until done or
    preempted.  Returns (last_step_completed, losses_by_step)."""
    key = fix_rand(0)
    params = init_gpt_params(key, cfg)
    zero = ZeroOptimizer(optax.adamw(1e-3))
    params = zero.place_params(params)
    state = zero.init(params)
    step_fn = zero.make_train_step(lambda p, b: gpt_loss(p, b, cfg))

    losses = {}
    with CheckpointManager(ckdir, max_to_keep=2) as mgr:
        start, restored = auto_resume(
            mgr, {"params": params, "state": state})
        params, state = restored["params"], restored["state"]
        if start:
            print(f"[resume] continuing from step {start}")
        with GracefulShutdown() as stop:
            last = start - 1
            for i in range(start, TOTAL_STEPS):
                params, state, loss = step_fn(params, state, make_batch(cfg, ndev, i))
                losses[i] = float(loss)
                last = i
                print(f"step {i}: loss={losses[i]:.4f}")
                if preempt_at is not None and i == preempt_at:
                    os.kill(os.getpid(), signal.SIGTERM)  # the preemption
                if stop.requested or (i + 1) % SAVE_EVERY == 0 or i == TOTAL_STEPS - 1:
                    # wait on the preemption save: the process is about to die
                    mgr.save(i, {"params": params, "state": state},
                             wait=stop.requested)
                if stop.requested:
                    print(f"[preempted] saved at step {i}, exiting cleanly")
                    break
            mgr.wait_until_finished()
    return last, losses


def main():
    setup_distributed()
    ndev = len(jax.devices())
    tpc.setup_process_groups([("data", ndev)])
    cfg = GPTConfig(vocab_size=256, dim=64, nheads=4, nlayers=2, max_seq=32,
                    ffn_mult=2, dtype=jnp.float32)

    # launch 1: preempted mid-run; launch 2: auto-resumes and finishes
    ckdir = os.path.join(tempfile.mkdtemp(prefix="tdp_preempt_"), "run")
    last, l1 = run(ckdir, cfg, ndev, preempt_at=PREEMPT_AT)
    assert last == PREEMPT_AT, (last, PREEMPT_AT)
    last, l2 = run(ckdir, cfg, ndev)
    assert last == TOTAL_STEPS - 1

    # golden: an uninterrupted run in a fresh dir — trajectories must agree
    straight_dir = os.path.join(tempfile.mkdtemp(prefix="tdp_straight_"), "run")
    _, ls = run(straight_dir, cfg, ndev)
    for i, want in ls.items():
        got = l1.get(i, l2.get(i))
        np.testing.assert_allclose(got, want, rtol=1e-5, err_msg=f"step {i}")
    print("preempt+resume trajectory == straight trajectory — resume is exact")


if __name__ == "__main__":
    main()
