"""Test harness: simulate an 8-device mesh on CPU.

The reference has no CI-able tests (its examples need real multi-GPU SLURM —
SURVEY.md §4).  We do better natively: force 8 virtual CPU devices before JAX
initializes, so every sharding/collective path runs as a real 8-way SPMD
program in CI without hardware.
"""

import os

# Must run before any backend initializes (XLA_FLAGS is parsed at backend
# init; importing jax is safe, initializing it is not).  All XLA_FLAGS
# writes go through dist/overlap.py — this file's own lint
# (test_repo_lint.test_no_direct_xla_flags_writes) enforces it.
# cpu_sim(8) merges --xla_force_host_platform_device_count=8, sets
# JAX_PLATFORMS=cpu AND pins the jax platform config — the axon
# sitecustomize force-registers the TPU backend via
# jax.config.update("jax_platforms", "axon,cpu"), which a bare env var
# does not override.
from torchdistpackage_tpu.dist.overlap import cpu_sim

cpu_sim(8)

import jax  # noqa: E402

import pytest  # noqa: E402

from torchdistpackage_tpu.dist import tpc  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_tpc():
    yield
    tpc.reset()


@pytest.fixture
def devices8():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual CPU devices, got {len(devs)}"
    return devs[:8]
