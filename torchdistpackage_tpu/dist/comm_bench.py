"""Collective bandwidth benchmark — analogue of the reference's
``torchdistpackage/dist/py_comm_test.py`` (84 LoC).

The reference times NCCL all_reduce / all_gather / reduce_scatter /
all_to_all and reports algorithm- and bus-bandwidth with the nccl-tests
correction factors (py_comm_test.py:10-17,49-51).  Here the same harness runs
jitted XLA collectives over any named mesh axis, so the numbers measure
ICI/DCN (or the CPU-sim fabric in tests).  Bus-bandwidth factors follow the
same convention:

- all_reduce:      busbw = algbw * 2 * (n-1)/n
- all_gather:      busbw = algbw * (n-1)/n
- reduce_scatter:  busbw = algbw * (n-1)/n
- all_to_all:      busbw = algbw * (n-1)/n
- ppermute (ring p2p): busbw = algbw (each link carries the payload once)

algbw = bytes / time, where bytes is the *full* (global) payload size, as in
nccl-tests.

Results are **obs-schema comm records** (``obs.comm_ledger.comm_record``:
op / axis / bytes / time_s / algbw_GBps / busbw_GBps) — the same shape the
HLO ledger aggregates and the alpha-beta model calibrates against
(``obs.comm_model.CommModel.calibrate``), so measurement, calibration, and
reporting round-trip through one schema.  ``test_collection`` can stream
them to any obs sink (``JsonlSink`` et al.) instead of ad-hoc dicts.

Int8-ring arms (PR 8): ``int8_all_reduce`` / ``int8_reduce_scatter`` /
``int8_all_gather`` time the quantized rings of ``dist/compressed.py``
through the same harness.  Their records keep ``bytes`` at the ORIGINAL
payload (directly comparable to the exact arm's row; effective busbw
above the link rate IS the compression win) and add ``compressed`` /
``base_op`` / ``elem_bytes`` — the fields
``CommModel.calibrate(compressed_ops=...)`` uses to refit alpha/beta
against the compressed wire bytes, grounding
``predict_compressed`` in measurement (quant FLOPs included).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from ..compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from ..obs.comm_ledger import comm_record
from .topology import tpc

_BUSBW_FACTOR = {
    "all_reduce": lambda n: 2 * (n - 1) / n,
    "all_gather": lambda n: (n - 1) / n,
    "reduce_scatter": lambda n: (n - 1) / n,
    "all_to_all": lambda n: (n - 1) / n,
    "ppermute": lambda n: 1.0,
    # int8-ring arms (dist/compressed.py): busbw uses the base op's factor
    # over the ORIGINAL payload — an EFFECTIVE bus bandwidth directly
    # comparable to the exact arm's row (the wire moves ~4x fewer bytes,
    # so effective busbw above the link rate is the compression win;
    # CommModel.calibrate refits against the compressed wire bytes).
    "int8_all_reduce": lambda n: 2 * (n - 1) / n,
    "int8_reduce_scatter": lambda n: (n - 1) / n,
    "int8_all_gather": lambda n: (n - 1) / n,
}


def _timeit(fn, arg, warmup: int = 2, iters: int = 10) -> float:
    """Median wall time of ``fn(arg)`` with device sync, seconds."""
    for _ in range(warmup):
        jax.block_until_ready(fn(arg))
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(arg))
        times.append(time.perf_counter() - t0)
    return float(np.median(times))


def bench_collective(
    op: str,
    axis: str,
    nbytes: int = 1 << 24,
    mesh: Optional[Mesh] = None,
    dtype=jnp.bfloat16,
    warmup: int = 2,
    iters: int = 10,
) -> Dict[str, float]:
    """Time one collective over ``axis`` and return timing + bandwidth stats.

    ``nbytes`` is the global payload size (like the reference's tensor size,
    py_comm_test.py:22-30).  Returns an obs-schema comm record
    (``{op, axis, axis_size, bytes, time_s, algbw_GBps, busbw_GBps}``).
    """
    if mesh is None:
        mesh = tpc.get_view()
    n = mesh.shape[axis]
    elem = jnp.dtype(dtype).itemsize
    # divisible by n (and by n*n for all_to_all's [count//n, n] local split)
    quantum = n * n if op == "all_to_all" else n
    count = max(quantum, nbytes // elem // quantum * quantum)

    if op == "all_reduce":
        body = lambda x: jax.lax.psum(x, axis)
        in_spec, out_spec = P(), P()
        shape = (count,)
    elif op == "all_gather":
        body = lambda x: jax.lax.all_gather(x, axis, tiled=True)
        in_spec, out_spec = P(axis), P(axis)
        shape = (count,)
    elif op == "reduce_scatter":
        body = lambda x: jax.lax.psum_scatter(x, axis, tiled=True)
        in_spec, out_spec = P(), P(axis)
        shape = (count,)
    elif op == "all_to_all":
        body = lambda x: jax.lax.all_to_all(x, axis, split_axis=1, concat_axis=0, tiled=True)
        in_spec, out_spec = P(axis), P(axis)
        shape = (count // n, n)
    elif op == "ppermute":
        perm = [(i, (i + 1) % n) for i in range(n)]
        body = lambda x: jax.lax.ppermute(x, axis, perm)
        in_spec, out_spec = P(axis), P(axis)
        shape = (count,)
    # --- int8-ring arms (dist/compressed.py): same harness, quantized
    # wire.  bytes on the record stays the ORIGINAL payload (nccl-tests
    # convention, comparable to the exact arm); calibration derives the
    # compressed wire bytes from it (obs.comm_model.compressed_wire_bytes
    # via the record's elem_bytes).
    elif op == "int8_all_reduce":
        from .compressed import int8_ring_pmean

        body = lambda x: int8_ring_pmean(x, axis) * n  # sum, mirrors psum
        in_spec, out_spec = P(), P()
        shape = (count,)
    elif op == "int8_reduce_scatter":
        from .compressed import int8_ring_reduce_scatter

        body = lambda x: int8_ring_reduce_scatter(x, axis, 0)
        in_spec, out_spec = P(), P(axis)
        shape = (count,)
    elif op == "int8_all_gather":
        from .compressed import int8_ring_all_gather

        body = lambda x: int8_ring_all_gather(x, axis, 0)
        in_spec, out_spec = P(axis), P(axis)
        shape = (count,)
    else:
        raise ValueError(f"unknown collective {op!r}")

    fn = jax.jit(shard_map(body, mesh=mesh, in_specs=(in_spec,), out_specs=out_spec))
    x = jnp.ones(shape, dtype=dtype)
    t = _timeit(fn, x, warmup=warmup, iters=iters)
    size = x.size * elem
    algbw = size / t / 1e9
    extra = (
        {"compressed": True, "base_op": op[len("int8_"):], "elem_bytes": elem}
        if op.startswith("int8_") else {}
    )
    return comm_record(
        op=op,
        axis=axis,
        nbytes=size,
        axis_size=n,
        time_s=t,
        algbw_GBps=algbw,
        busbw_GBps=algbw * _BUSBW_FACTOR[op](n),
        **extra,
    )


def test_collection(
    axis: str,
    sizes: Sequence[int] = (1 << 20, 1 << 24),
    ops: Sequence[str] = ("all_reduce", "all_gather", "reduce_scatter", "all_to_all", "ppermute"),
    mesh: Optional[Mesh] = None,
    verbose: bool = True,
    sink: Optional[Any] = None,
) -> List[Dict[str, float]]:
    """Sweep collectives x sizes over an axis — analogue of
    ``test_collection`` (py_comm_test.py:20-57).

    ``sink``: an obs sink (anything with ``write(record)``) or a path
    string — each comm record is streamed there as JSONL on the master
    process, the package's one structured-output path (no ad-hoc dicts).
    """
    if isinstance(sink, str):
        from ..obs.exporters import JsonlSink

        sink = JsonlSink(sink)
    rows = []
    is_master = True
    try:
        is_master = jax.process_index() == 0
    except Exception:
        pass
    for op in ops:
        for nbytes in sizes:
            row = bench_collective(op, axis, nbytes=nbytes, mesh=mesh)
            rows.append(row)
            if sink is not None and is_master:
                try:
                    sink.write(row)
                except Exception:
                    pass
            if verbose:
                from ..utils.logging import master_print

                master_print(
                    f"{op:>14} axis={axis}({row['axis_size']}) "
                    f"{row['bytes']/2**20:8.1f} MiB  "
                    f"{row['time_s']*1e3:8.3f} ms  "
                    f"alg {row['algbw_GBps']:7.2f} GB/s  "
                    f"bus {row['busbw_GBps']:7.2f} GB/s"
                )
    return rows
