"""Auto-sharding planner (dist/autoplan.py, PR 13).

Host-side units pin the planner's three cost-model couplings:

- the analytic shape table against ``jax.eval_shape`` of the real init
  (leaf count + total bytes, per family);
- the analytic memory mirror against ``MemoryModel.estimate`` over the
  REAL (config, mesh, specs) triple — byte-identical, every candidate;
- the analytic spec assignment against :func:`plan_param_specs`'s real
  PartitionSpec tree (shard counts, incl. the ZeRO
  first-free-divisible-dim fsdp insertion);
- compression arms chosen iff the (calibrated) CommModel approves,
  awkward chip counts, the clean all-OOM verdict, ranking determinism,
  section validation, the event kinds, and the jax-free CLI.

The measured-validation arm shares ONE module-scope compiled bundle
(tier-1 budget rule): the planner's top-3 structurally distinct plans
each compile one tiny value_and_grad+sgd step and are timed once; every
measured assertion reads that bundle.
"""

import functools
import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from torchdistpackage_tpu.dist import autoplan as ap
from torchdistpackage_tpu.models import GPTConfig, gpt_loss, init_gpt_params
from torchdistpackage_tpu.obs.comm_model import AxisCost, CommModel
from torchdistpackage_tpu.obs.events import default_event_log
from torchdistpackage_tpu.obs.report import _validate_autoplan

TINY = GPTConfig(vocab_size=512, dim=128, nheads=4, nlayers=4, max_seq=128,
                 ffn_mult=2, dtype=jnp.float32)

#: dict-config twin of TINY — what the jax-free CLI consumes
TINY_DICT = {"vocab_size": 512, "dim": 128, "nheads": 4, "nlayers": 4,
             "max_seq": 128, "ffn_mult": 2, "dtype": "float32"}


def _cpu_model(alpha_s=50e-6, beta=1e9):
    """A deterministic 'calibrated' model with CPU-sim-shaped link
    parameters: dispatch-dominated alpha, modest bandwidth."""
    c = AxisCost(alpha_s, beta, "calibrated")
    return CommModel({"data": c, "tensor": c, "pipe": c}, default=c,
                     chip="cpu-sim", source="calibrated")


# --------------------------------------------------------------- shape table


def test_shape_table_matches_eval_shape():
    """The analytic table IS the real param tree: leaf count and total
    bytes equal jax.eval_shape of the family init — for the dense GPT,
    a Llama-shaped GQA/SwiGLU/RMS/rope config, and the headless
    transformer family."""
    from torchdistpackage_tpu.obs.mem_ledger import _shapes_for_config
    from torchdistpackage_tpu.parallel.tensor_parallel import (
        TransformerConfig,
    )

    llama = GPTConfig(vocab_size=256, dim=64, nheads=8, nlayers=2,
                      max_seq=64, kv_heads=2, pos="rope", norm="rms",
                      act="swiglu", ffn_hidden=96, dtype=jnp.float32)
    tfm = TransformerConfig(dim=64, nheads=4, nlayers=3, ffn_mult=4)
    for cfg in (TINY, llama, tfm):
        d = ap.model_dims(cfg)
        leaves = jax.tree.leaves(_shapes_for_config(cfg))
        real_bytes = sum(
            int(np.prod(l.shape)) * np.dtype(l.dtype).itemsize
            for l in leaves)
        table = ap.param_table(d)
        table_bytes = sum(
            r.count * int(np.prod(r.shape)) * d.dtype_size for r in table)
        assert table_bytes == real_bytes, type(cfg).__name__
        assert sum(r.count for r in table) == len(leaves), type(cfg).__name__


def test_analytic_memory_matches_memory_model():
    """The jax-free memory mirror (the CLI's pruning judge) is
    byte-identical to ``MemoryModel.estimate`` over the real spec tree
    for EVERY candidate — so a plan the CLI prunes is exactly a plan the
    acceptance-path model prunes."""
    d = ap.model_dims(TINY)
    for c in ap.enumerate_candidates(d, 8, 8):
        a = ap.estimate_memory_analytic(d, c, 8, capacity_bytes=10**9)
        m = ap.estimate_memory_model(TINY, c, 8, capacity_bytes=10**9)
        for k in ("params_bytes", "grads_bytes", "opt_bytes", "act_bytes",
                  "total_bytes"):
            assert a[k] == m[k], (c["key"], k, a[k], m[k])
        assert a["verdict"] == m["verdict"], c["key"]


def test_spec_table_matches_real_partition_specs():
    """The rendered per-leaf spec table (the emitted plan's audit
    payload) agrees with the REAL PartitionSpec tree: identical per-leaf
    shard counts under the plan's mesh sizes — tp dims, the pipe stack
    dim, and the ZeRO data-axis insertion all land on the same dims."""
    from torchdistpackage_tpu.obs.mem_ledger import (
        _shapes_for_config, _shard_count,
    )

    d = ap.model_dims(TINY)
    cands = {c["key"]: c for c in ap.enumerate_candidates(d, 8, 8)}
    for key in ("fsdp4·tp2", "dp2·tp4", "fsdp8"):
        c = cands[key]
        table = {r["path"]: r for r in ap.spec_table(d, c)}
        shapes = _shapes_for_config(TINY)
        flat, treedef = jax.tree_util.tree_flatten(shapes)
        specs = treedef.flatten_up_to(ap.plan_param_specs(c, TINY))
        real_total = 0
        for leaf, spec in zip(flat, specs):
            real_total += -(-int(np.prod(leaf.shape))
                            // _shard_count(spec, c["mesh_axes"]))
        tab_total = sum(
            r.count * -(-int(np.prod(r.shape)) // ap._leaf_shards(r, c))
            for r in ap.param_table(d))
        assert real_total == tab_total, key
        # and the stacked-attention leaf's assignment is the expected one
        if c["tp"] > 1:
            assert "tensor" in table["blocks.attn.wqkv"]["spec"], table


# --------------------------------------------------------------- enumeration


def test_awkward_chip_counts_factor():
    """6 and 24 chips: every candidate's mesh multiplies back to the chip
    count, tp always divides nheads, dp always divides the batch — and a
    plan still exists (pure dp covers any count)."""
    # every shardable dim divisible by both 2 and 3, so the awkward
    # factor is reachable: tp|nheads AND tp|dim AND tp|ffn AND tp|vocab
    wide = dict(TINY_DICT, nheads=12, dim=96, vocab_size=768)
    for n_chips, batch in ((6, 12), (24, 24)):
        res = ap.plan(wide, n_chips, global_batch=batch,
                      memory="analytic", emit=False)
        assert res["verdict"] == "ok" and res["chosen"] is not None
        d = ap.model_dims(wide)
        cands = ap.enumerate_candidates(d, n_chips, batch)
        assert cands
        tps = set()
        for c in cands:
            assert c["dp"] * c["tp"] * c["pp"] == n_chips, c
            assert d.nheads % c["tp"] == 0
            assert batch % c["dp"] == 0
            tps.add(c["tp"])
        assert 3 in tps, f"awkward factor 3 never enumerated at {n_chips}"


def test_pp_candidates_modeled_and_executable():
    """Pipeline splits are in the search space (schedule-aware bubble on
    the compute term, ppermute comm term over the pipe axis) AND — PR 14
    — in the executable set: bench's pipeline runner drives the 1F1B/ZB
    schedules, so ``executable_only`` keeps pp>1 arms (restricted to the
    dp layout, no compression).  Every pp row records which schedule the
    planner priced it under and that schedule's tick-model bubble."""
    res = ap.plan(TINY_DICT, 8, global_batch=8, memory="analytic",
                  emit=False, top=64)
    pp_rows = [r for r in res["ranked"] if r["pp"] > 1]
    assert pp_rows, "no pipeline candidates enumerated"
    assert all(r["bubble_fraction"] > 0 for r in pp_rows)
    assert all(r["pp_schedule"] in ("1f1b", "zb") for r in pp_rows)
    assert all(r["pp_schedule"] is None and r["bubble_fraction"] == 0
               for r in res["ranked"] if r["pp"] == 1)
    # re-score one pp candidate directly: the ppermute term is priced
    d = ap.model_dims(TINY_DICT)
    c = next(c for c in ap.enumerate_candidates(d, 8, 8) if c["pp"] > 1)
    terms = ap.comm_terms(d, c, 8, _cpu_model())
    assert any(t["op"] == "ppermute" for t in terms), terms
    # at the default microbatches=8, pp=2 sits in the zb-wins regime
    # (M < 2(P-1) is false at P=2... the cheaper arm is schedule-derived,
    # not hardcoded) — pin against the aggregate model directly
    from torchdistpackage_tpu.obs.aggregate import pipeline_time_inflation

    for r in pp_rows:
        want = min(
            ("1f1b", "zb"),
            key=lambda s: pipeline_time_inflation(8, r["pp"], schedule=s))
        assert r["pp_schedule"] == want, r

    res_x = ap.plan(TINY_DICT, 8, global_batch=8, memory="analytic",
                    emit=False, executable_only=True, top=64)
    pp_x = [r for r in res_x["ranked"] if r["pp"] > 1]
    assert pp_x, "executable set lost its pp candidates"
    # executable pp arms: dp layout only, no compression arms
    assert all(r["layout"] == "dp" for r in pp_x)
    assert all(not r["compress"]["grads"] and not r["compress"]["acts"]
               for r in pp_x)


def test_all_oom_is_a_clean_verdict():
    """A model too big for any plan: verdict ``all_oom``, chosen None,
    every candidate pruned WITH a ``plan_rejected_oom`` event, and the
    section still validates — no crash anywhere on the path."""
    log = default_event_log()
    before = len(log.of_kind("plan_rejected_oom"))
    res = ap.plan(TINY_DICT, 8, global_batch=8, memory="analytic",
                  capacity_bytes=4096, emit=True)
    assert res["verdict"] == "all_oom"
    assert res["chosen"] is None
    assert res["n_pruned_oom"] == res["n_candidates"] > 0
    events = log.of_kind("plan_rejected_oom")
    assert len(events) - before == res["n_candidates"]
    assert all(e["total_bytes"] > e["capacity_bytes"] for e in events[-3:])
    assert _validate_autoplan(res) == []


def test_compression_only_when_calibrated_model_approves():
    """The int8 arm is chosen iff the calibrated model approves it: with
    compressed-axis parameters that make the ring fast, the winner
    carries ``+gc8`` and its term records ``model_approves=True``; with
    parameters that make the ring a loss, the winner is the exact arm."""
    exact = AxisCost(1e-6, 50e9, "calibrated")
    fast8 = CommModel({"data": exact}, default=exact, source="calibrated",
                      compressed_axis_costs={
                          "data": AxisCost(1e-6, 200e9, "calibrated-int8")})
    slow8 = CommModel({"data": exact}, default=exact, source="calibrated",
                      compressed_axis_costs={
                          "data": AxisCost(5e-4, 1e8, "calibrated-int8")})
    kw = dict(global_batch=8, memory="analytic", emit=False,
              executable_only=True)
    win = ap.plan(TINY_DICT, 8, comm_model=fast8, **kw)["chosen"]
    assert win["compress"]["grads"] is True, win["key"]
    term = next(t for t in win["terms"] if t["compressed"])
    assert term["model_approves"] is True
    assert term["basis"] == "calibrated-int8"
    lose = ap.plan(TINY_DICT, 8, comm_model=slow8, **kw)["chosen"]
    assert lose["compress"]["grads"] is False, lose["key"]
    # the model's own verdict matches: the ring it rejected predicts
    # slower than the exact collective it kept
    rec = slow8.predict_compressed(
        "all_reduce", 1 << 20, 8, axes=("data",))
    assert rec["compress"] is False


def test_plan_ranking_deterministic():
    """Same inputs -> bit-identical result (ranking ties broken by key),
    twice."""
    kw = dict(global_batch=8, memory="analytic", emit=False,
              comm_model=_cpu_model())
    a = ap.plan(TINY_DICT, 8, **kw)
    b = ap.plan(TINY_DICT, 8, **kw)
    assert json.dumps(a, sort_keys=True) == json.dumps(b, sort_keys=True)


def test_section_validation_catches_corruption():
    res = ap.plan(TINY_DICT, 8, global_batch=8, memory="analytic",
                  emit=False)
    assert _validate_autoplan(res) == []
    assert _validate_autoplan(None) == []
    bad = dict(res, verdict="maybe")
    assert any("verdict" in e for e in _validate_autoplan(bad))
    bad = dict(res, n_pruned_oom=res["n_candidates"] + 1)
    assert any("n_pruned_oom" in e for e in _validate_autoplan(bad))
    bad = dict(res, chosen=None)
    assert any("chosen" in e for e in _validate_autoplan(bad))
    bad = json.loads(json.dumps(res))
    bad["chosen"].pop("terms")
    assert any("terms" in e for e in _validate_autoplan(bad))


def test_plan_selected_event_emitted():
    log = default_event_log()
    before = len(log.of_kind("plan_selected"))
    res = ap.plan(TINY_DICT, 8, global_batch=8, memory="analytic",
                  emit=True)
    evs = log.of_kind("plan_selected")
    assert len(evs) == before + 1
    assert evs[-1]["key"] == res["chosen"]["key"]
    assert evs[-1]["n_candidates"] == res["n_candidates"]


def test_plan_prefill_tier_prices_ring_and_prunes_oom():
    """The PR-20 CP prefill planner: each ring width's modeled TTFT =
    compute split cp ways + every ppermute hop priced through the
    CommModel at the SAME per-hop payloads the engine's HLO ledger
    shows; per-rank memory (pool/cp + ring working set) gates through
    ``headroom_verdict``.  At a capacity only the split arms fit, cp1 is
    pruned with the OOM evidence, the widest arm wins on modeled TTFT,
    and the planner events land on the timeline."""
    cfg = {"dim": 32, "nheads": 4, "nlayers": 1, "max_seq": 131072,
           "vocab_size": 64, "kv_heads": 2, "dtype": "float32"}
    log = default_event_log()
    sel0 = len(log.of_kind("plan_selected"))
    oom0 = len(log.of_kind("plan_rejected_oom"))
    plan = ap.plan_prefill_tier(
        cfg, context_len=131072, chunk=512, block_size=512,
        cp_widths=(1, 2, 3, 4, 8), capacity_bytes=40_000_000)
    assert plan["verdict"] == "ok"
    assert plan["skipped_widths"] == [3]  # 512 % 3 != 0: not executable
    assert [p["key"] for p in plan["pruned"]] == ["cp1"]
    assert plan["chosen"]["key"] == "cp8"
    by_cp = {r["cp"]: r for r in plan["ranked"]}
    # compute splits down, ring volume grows, with cp — and the hop
    # count matches the per-chunk HLO model times the chunk walk
    assert by_cp[8]["compute_s"] < by_cp[2]["compute_s"]
    assert by_cp[8]["ring_hops"] > by_cp[2]["ring_hops"] > 0
    n_chunks = 131072 // 512
    assert by_cp[2]["ring_hops"] == n_chunks * 4 * (2 - 1) * 1
    ops = {t["name"]: t for t in plan["chosen"]["terms"]}
    assert ops["cp-ring-fresh"]["op"] == "ppermute"
    assert ops["cp-ring-pool"]["per_op_s"] > 0
    assert log.of_kind("plan_selected")[-1]["key"] == "cp8"
    assert len(log.of_kind("plan_selected")) == sel0 + 1
    assert len(log.of_kind("plan_rejected_oom")) == oom0 + 1

    # no width fits -> the clean all_oom verdict, no winner event
    bad = ap.plan_prefill_tier(
        cfg, context_len=131072, chunk=512, block_size=512,
        cp_widths=(2, 4), capacity_bytes=1_000_000, emit=False)
    assert bad["verdict"] == "all_oom" and bad["chosen"] is None
    assert bad["n_pruned_oom"] == 2


# ------------------------------------------------------------- MoE / EP (PR 18)

MOE_TINY = GPTConfig(vocab_size=64, dim=32, nheads=4, nlayers=4, max_seq=32,
                     moe_experts=4, moe_top_k=2, moe_every=2,
                     moe_capacity_factor=2.0, dtype=jnp.float32)


def test_moe_plan_enumerates_ep_candidates():
    """MoE configs plan instead of raising (PR 18): every dp x tp point
    crosses in ep | gcd(dp, E) arms with a dedicated ``ep`` mesh axis
    (``data = dp/ep``); pp, fsdp, and compression stay out of the MoE
    set; ep>1 rows price the dispatch all_to_all over the ep axis."""
    res = ap.plan(MOE_TINY, 8, global_batch=8, memory="model",
                  comm_model=_cpu_model(), emit=False, top=64)
    assert res["verdict"] == "ok" and res["chosen"] is not None
    assert _validate_autoplan(res) == []
    rows = res["ranked"]
    assert {1, 2, 4} <= {r.get("ep", 1) for r in rows}
    for r in rows:
        assert r["pp"] == 1 and r["layout"] == "dp"
        assert not r["compress"]["grads"] and not r["compress"]["acts"]
        assert r["mesh_axes"]["data"] * r["mesh_axes"]["ep"] == r["dp"]
        if r["ep"] > 1:
            assert f"ep{r['ep']}" in r["key"]
        else:
            assert "ep" not in r["key"]
    d = ap.model_dims(MOE_TINY)
    ep_row = next(r for r in rows if r["ep"] > 1)
    a2a = [t for t in ap.comm_terms(d, ep_row, 8, _cpu_model())
           if t["name"] == "moe-all-to-all"]
    assert a2a and a2a[0]["op"] == "all_to_all" and a2a[0]["axes"] == ["ep"]
    assert a2a[0]["count"] == 4 * d.n_moe_layers
    assert all(t["name"] != "moe-all-to-all" for t in ap.comm_terms(
        d, next(r for r in rows if r["ep"] == 1), 8, _cpu_model()))
    # activated-FLOP accounting: the capacity factor inflates the expert
    # FLOP term (flop_weight = top_k * cf / E on expert leaves)
    import dataclasses as _dc

    d2 = _dc.replace(d, moe_capacity_factor=2 * d.moe_capacity_factor)
    assert ap.flops_per_token(d2) > ap.flops_per_token(d)


def test_moe_memory_pin_and_shape_table():
    """The PR-13 byte-identical pin extends to MoE: the analytic mirror
    equals ``MemoryModel.estimate`` over the REAL gpt_moe spec tree
    (expert stacks EP-sharded via ``gpt_moe_param_specs``) for EVERY
    candidate, and the analytic table matches ``jax.eval_shape`` of
    ``init_gpt_moe_params`` leaf-for-leaf in count and bytes."""
    from torchdistpackage_tpu.obs.mem_ledger import _shapes_for_config

    d = ap.model_dims(MOE_TINY)
    leaves = jax.tree.leaves(_shapes_for_config(MOE_TINY))
    real_bytes = sum(
        int(np.prod(l.shape)) * np.dtype(l.dtype).itemsize for l in leaves)
    table = ap.param_table(d)
    table_bytes = sum(
        r.count * int(np.prod(r.shape)) * d.dtype_size for r in table)
    assert table_bytes == real_bytes
    assert sum(r.count for r in table) == len(leaves)
    for c in ap.enumerate_candidates(d, 8, 8):
        a = ap.estimate_memory_analytic(d, c, 8, capacity_bytes=10**9)
        m = ap.estimate_memory_model(MOE_TINY, c, 8, capacity_bytes=10**9)
        for k in ("params_bytes", "grads_bytes", "opt_bytes", "act_bytes",
                  "total_bytes"):
            assert a[k] == m[k], (c["key"], k, a[k], m[k])
        assert a["verdict"] == m["verdict"], c["key"]
        # ep>1 shrinks per-device expert bytes vs its ep=1 sibling
        if c["ep"] > 1:
            sib = dict(c, ep=1, mesh_axes=dict(c["mesh_axes"],
                                               data=c["dp"], ep=1))
            assert a["params_bytes"] < ap.estimate_memory_analytic(
                d, sib, 8, capacity_bytes=10**9)["params_bytes"]


def test_moe_transformer_family_rejected():
    """The transformer family has no expert blocks — a dict config with
    experts but no vocab still fails loudly instead of mispricing."""
    with pytest.raises(ValueError, match="gpt"):
        ap.model_dims({"dim": 64, "nheads": 4, "nlayers": 2,
                       "moe_experts": 4})


# ------------------------------------------------- measured validation arm


@pytest.fixture(scope="module")
def measured_bundle():
    """ONE module-scope compiled bundle (tier-1 budget rule): plan TINY
    on the 8-dev sim with a CPU-shaped calibrated model restricted to the
    three structurally distinct dp layouts, then time each of the top-3
    plans through one tiny value_and_grad+sgd GSPMD step (3 compiles
    total in this file)."""
    # allow_pp=False: this bundle exercises the dp/tp GSPMD runner
    # layouts (the pipelined runner has its own goldens in
    # tests/test_pipeline.py and the bench.py --autoplan pp audit)
    result = ap.plan(
        TINY, 8, global_batch=8, comm_model=_cpu_model(),
        memory="model", executable_only=True, compression=False,
        layouts=("dp",), allow_pp=False, emit=True)
    top3 = result["ranked"][:3]
    assert len(top3) == 3
    opt = optax.sgd(1e-3)

    def measure(c):
        params = init_gpt_params(jax.random.PRNGKey(0), TINY)
        mesh = ap.build_mesh(c)
        specs = ap.plan_param_specs(c, TINY)
        params = jax.tree.map(
            lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
            params, specs)
        state = jax.device_put(opt.init(params), NamedSharding(mesh, P()))
        k1, k2 = jax.random.split(jax.random.PRNGKey(1))
        batch = jax.device_put({
            "tokens": jax.random.randint(
                k1, (8, TINY.max_seq), 0, TINY.vocab_size),
            "targets": jax.random.randint(
                k2, (8, TINY.max_seq), 0, TINY.vocab_size),
        }, NamedSharding(mesh, ap.batch_partition_spec(c)))

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def step(p, s, b):
            loss, g = jax.value_and_grad(
                lambda p_: gpt_loss(p_, b, TINY))(p)
            u, s = opt.update(g, s, p)
            return jax.tree.map(jnp.add, p, u), s, loss

        for _ in range(2):  # compile + warm
            params, state, loss = step(params, state, batch)
        assert np.isfinite(float(loss))
        t0 = time.perf_counter()
        for _ in range(6):
            params, state, loss = step(params, state, batch)
        float(loss)
        return (time.perf_counter() - t0) / 6

    rows = [{"key": r["key"], "modeled_step_s": r["step_s"],
             "measured_step_s": measure(r)} for r in top3]
    ap.attach_measured(result, rows)
    return result


def test_top3_are_structurally_distinct(measured_bundle):
    keys = [r["key"] for r in measured_bundle["ranked"][:3]]
    assert len(set(keys)) == 3
    tps = {measured_bundle["ranked"][i]["tp"] for i in range(3)}
    assert len(tps) == 3, f"top-3 collapsed onto one tp split: {keys}"


def test_modeled_vs_measured_ordering(measured_bundle):
    """The acceptance claim: the measured ordering of the planner's top-3
    agrees with the modeled ordering, or the disagreement is disclosed in
    the section's modeled_vs_measured record.  The extremes are asserted
    HARD — the modeled-best plan must measure faster than the
    modeled-worst of the three (15% noise margin): a planner that
    mis-ranks the ends is steering users wrong."""
    mvm = measured_bundle["modeled_vs_measured"]
    assert _validate_autoplan(measured_bundle) == []
    rows = {r["key"]: r for r in mvm["rows"]}
    order = mvm["modeled_order"]
    best, worst = rows[order[0]], rows[order[-1]]
    assert best["measured_step_s"] < worst["measured_step_s"] * 1.15, mvm
    if not mvm["ordering_agrees"]:
        # the disclosure contract: both orderings and per-row rel errs
        # are in the section for the RUNREPORT to render
        assert mvm["measured_order"] and all(
            r.get("rel_err") is not None for r in mvm["rows"]), mvm


def test_chosen_plan_trains(measured_bundle):
    """The emitted winner is executable end to end (the bundle already
    compiled and stepped it — finite loss asserted inside) and carries
    the audit payload: per-term breakdown + rendered per-leaf specs."""
    chosen = measured_bundle["chosen"]
    assert chosen["terms"], chosen
    assert chosen["param_specs"], chosen
    paths = {r["path"] for r in chosen["param_specs"]}
    assert {"tok_emb", "head"} <= paths


# ----------------------------------------------------------------- CLI


def test_cli_plan_table_and_json(tmp_path, capsys):
    from torchdistpackage_tpu.tools.autoplan import main

    cfg_path = tmp_path / "model.json"
    cfg_path.write_text(json.dumps(TINY_DICT))
    rc = main(["--config", str(cfg_path), "--chips", "8", "--batch", "16",
               "--hbm-gb", "1", "--chip", "TPU v5e"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "chosen:" in out
    line = json.loads(out.strip().splitlines()[-1])
    assert line["metric"] == "autoplan" and line["verdict"] == "ok"
    assert line["chosen"]["key"]


def test_cli_all_oom_exits_nonzero(tmp_path, capsys):
    from torchdistpackage_tpu.tools.autoplan import main

    cfg_path = tmp_path / "model.json"
    cfg_path.write_text(json.dumps(TINY_DICT))
    rc = main(["--config", str(cfg_path), "--chips", "8",
               "--hbm-gb", "0.00001"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "NO PLAN FITS" in out
    line = json.loads(out.strip().splitlines()[-1])
    assert line["verdict"] == "all_oom" and line["chosen"] is None


def test_cli_unreadable_config_exits_2(tmp_path, capsys):
    from torchdistpackage_tpu.tools.autoplan import main

    assert main(["--config", str(tmp_path / "missing.json"),
                 "--chips", "8"]) == 2
    bad = tmp_path / "bad.json"
    bad.write_text("[1, 2]")
    assert main(["--config", str(bad), "--chips", "8"]) == 2
    capsys.readouterr()
