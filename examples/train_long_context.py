"""End-to-end example: TRAIN a GPT at long sequence length with ring context
parallelism.

Capability the reference lacks entirely (SURVEY §5: "No ring attention, no
context parallel" — its only seed is the single-device tiled-softmax study,
explore/flash-attn/tile_attn.py:100-212).  Here the GLOBAL sequence is
sharded over a 'context' mesh axis end-to-end: each device embeds its own
token chunk (pos-emb sliced at the shard's global offset), every transformer
block runs on the local chunk, and only the attention op communicates — KV
shards rotate around the ICI ring (``attn_impl='ring'``), through the Pallas
flash kernel per hop.  Activation memory per device is O(S/cp); attention
FLOPs stay causal-halved via the per-hop past/diagonal/future split.

The context axis is treated as a data axis by the train step (grads pmean
over it — equal shards make the global mean the mean of shard means), so
``DataParallel`` drives the whole thing unchanged.

- real TPU chips:      python examples/train_long_context.py   (S=8192)
- 8-device CPU sim:    TDP_CPU_SIM=8 python examples/train_long_context.py
"""

import os

if os.environ.get("TDP_CPU_SIM"):
    # XLA_FLAGS handling is centralized in dist/overlap.py (test_repo_lint
    # bans direct writes); cpu_sim also pins the cpu platform, replacing
    # the old post-import jax.config.update dance.
    from torchdistpackage_tpu.dist.overlap import cpu_sim

    cpu_sim(os.environ["TDP_CPU_SIM"])

import jax

import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from torchdistpackage_tpu import setup_distributed, tpc
from torchdistpackage_tpu.models import GPTConfig, gpt_loss, init_gpt_params
from torchdistpackage_tpu.parallel import DataParallel

SMOKE = bool(os.environ.get("TDP_SMOKE"))


def main():
    setup_distributed()
    ndev = len(jax.devices())
    tpc.setup_process_groups([("context", ndev)])
    mesh = tpc.get_view()

    # long-context flagship: S >= 8k sharded over the context ring
    S = 2048 if SMOKE else 8192
    steps = 3 if SMOKE else 20
    cfg = GPTConfig(
        vocab_size=512,
        dim=128,
        nheads=4,
        nlayers=2,
        max_seq=S,
        ffn_mult=2,
        attn_impl="ring",
        context_axis="context",
        # zigzag: shard i owns chunks i and 2n-1-i, so the causal FLOPs are
        # balanced across the ring (no shard idles while the last one
        # computes the whole triangle) — batches are host-permuted below
        cp_layout="zigzag",
    )
    B = 2
    params = init_gpt_params(jax.random.PRNGKey(0), cfg)
    opt = optax.adam(1e-3)

    dp = DataParallel(mesh=mesh, axis=("context",))
    sharded = dp.broadcast_params(params)
    state = opt.init(sharded)
    # remat='flash' + streamed CE: the long-context memory stack
    # (docs/long_context.md "Memory levers") — the ring op's per-hop flash
    # (o, lse) residuals are saved so the backward skips the kernel re-run,
    # and the [B, S_loc, V] logits never materialize.  The chunk must
    # divide the context-LOCAL sequence shard (S/ndev), so derive it.
    xc = min(256, S // ndev)
    step = dp.make_train_step(
        lambda p, b: gpt_loss(p, b, cfg, remat="flash", xent_chunk=xc),
        opt,
        batch_spec={"tokens": P(None, "context"), "targets": P(None, "context")},
    )

    bsh = NamedSharding(mesh, P(None, "context"))
    losses = []
    for i in range(steps):
        k1, k2 = jax.random.split(jax.random.PRNGKey(100 + i))
        del k2
        # copy task: target[i] = tokens[i-1] — solvable ONLY via attention to
        # the previous position (predict-NEXT on i.i.d. tokens would be
        # context-free: loss would fall to the unigram floor with attention
        # broken), so the loss decrease actually validates the ring
        tokens = jax.random.randint(k1, (B, S), 0, cfg.vocab_size)
        targets = jnp.concatenate([tokens[:, :1], tokens[:, :-1]], axis=1)
        # same permutation for tokens and targets; the mean CE is invariant
        from torchdistpackage_tpu.ops.ring_attention import zigzag_permute

        batch = {
            "tokens": zigzag_permute(tokens, ndev, seq_dim=1),
            "targets": zigzag_permute(targets, ndev, seq_dim=1),
        }
        batch = jax.device_put(batch, bsh)
        sharded, state, loss = step(sharded, state, batch)
        losses.append(float(loss))
        print(f"step {i}: loss={losses[-1]:.4f}  (S={S}, context={ndev})")

    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], "training must reduce the loss"
    print(
        f"trained GPT at S={S} over a {ndev}-way context ring: "
        f"loss {losses[0]:.4f} -> {losses[-1]:.4f}; per-device activation "
        f"residency S/cp = {S // ndev} tokens"
    )


if __name__ == "__main__":
    main()
