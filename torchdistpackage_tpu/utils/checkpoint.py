"""Sharded checkpoint save/resume + model-parallel ckpt naming.

The reference ships only a per-partition filename helper
(``dist/model_parallel_ckpt.py:4-21`` — suffix ``_tp_{r}_pp_{r}.pth``; note
its bare ``is_mode_inited`` NameError, SURVEY §2#15) and rank-0 state
reconstruction inside ShardedEMA; there is **no** unified save/load or resume
(SURVEY §5).  Here checkpointing is first-class and TPU-native: Orbax writes
each array *shard-parallel* from every host (no rank-0 gather, no per-rank
files to stitch), records the mesh/PartitionSpec layout, and restores
directly into any sharding you ask for — so a checkpoint written on one mesh
can resume on another (e.g. TP=4 -> TP=2) by just passing the new specs.

- :func:`get_mp_ckpt_suffix` — behavioral parity with the reference helper
  (with the NameError fixed), for users who want legacy-style names.
- :func:`save_checkpoint` / :func:`load_checkpoint` — one-shot pytree
  save/restore (params, opt state, EMA, step counters, ...).
- :class:`CheckpointManager` — step-numbered checkpoints, retention policy,
  and ``latest_step`` resume — the missing "resume logic".
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

PyTree = Any


def get_mp_ckpt_suffix() -> str:
    """Per-partition filename suffix, e.g. ``_tp_0_pp_1`` — parity with
    ``get_mp_ckpt_suffix`` (model_parallel_ckpt.py:4-21), minus its
    ``is_mode_inited`` NameError.  Empty string when no model parallelism."""
    from ..dist.topology import PIPE_AXIS, TENSOR_AXIS, tpc

    suffix = ""
    if tpc.is_mode_inited(TENSOR_AXIS):
        suffix += f"_tp_{tpc.process_axis_index(TENSOR_AXIS)}"
    if tpc.is_mode_inited(PIPE_AXIS):
        suffix += f"_pp_{tpc.process_axis_index(PIPE_AXIS)}"
    return suffix


def _ocp():
    import orbax.checkpoint as ocp

    return ocp


def _norm_path(path: str) -> str:
    """Absolutize local paths; leave URI schemes (gs://, s3://, ...) intact —
    Orbax handles those natively and abspath would mangle them."""
    if "://" in path:
        return path
    return os.path.abspath(path)


def save_checkpoint(path: str, state: PyTree, force: bool = True) -> None:
    """Write ``state`` (any pytree of arrays/scalars) to ``path``.

    Every host writes its own shards in parallel; jax.Arrays keep their
    sharding metadata.  Replaces the reference's nonexistent save path and
    ShardedEMA's rank-0 send/recv reconstruction (sharded_ema.py:36-61).
    """
    ocp = _ocp()
    path = _norm_path(path)
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, state, force=force)


def load_checkpoint(
    path: str,
    template: Optional[PyTree] = None,
    mesh: Optional[Mesh] = None,
    specs: Optional[PyTree] = None,
) -> PyTree:
    """Restore a pytree from ``path``.

    - ``template=None``: restore as numpy arrays (host-side inspection).
    - ``template`` given (arrays or ShapeDtypeStructs): restore into that
      structure's shapes/dtypes/shardings.
    - ``mesh`` + ``specs`` given: override shardings — this is the
      resharding-resume path (checkpoint from one mesh, resume on another).
    """
    ocp = _ocp()
    path = _norm_path(path)
    if specs is not None and mesh is None:
        from ..dist.topology import tpc

        mesh = tpc.get_view()
    if mesh is not None and specs is None:
        raise ValueError("load_checkpoint: `mesh` given without `specs`")
    if specs is not None and template is None:
        raise ValueError(
            "load_checkpoint: resharding restore (`specs`) needs `template` "
            "for the shapes/dtypes"
        )
    with ocp.StandardCheckpointer() as ckptr:
        if template is None:
            return ckptr.restore(path)

        if mesh is not None and specs is not None:
            def abstract(x, s):
                shape = np.shape(x)
                dtype = getattr(x, "dtype", np.asarray(x).dtype)
                return jax.ShapeDtypeStruct(
                    shape, dtype, sharding=NamedSharding(mesh, s or PartitionSpec())
                )

            template = jax.tree.map(abstract, template, specs)
        else:
            def abstract(x):
                if isinstance(x, jax.ShapeDtypeStruct):
                    return x
                shape = np.shape(x)
                dtype = getattr(x, "dtype", np.asarray(x).dtype)
                sharding = getattr(x, "sharding", None)
                return jax.ShapeDtypeStruct(shape, dtype, sharding=sharding)

            template = jax.tree.map(abstract, template)
        return ckptr.restore(path, template)


def _process_count() -> int:
    """Best-effort pod size: 1 before/without distributed init."""
    try:
        import jax

        return int(jax.process_count())
    except Exception:
        return 1


def _pod_any(flag: bool, n_proc: int) -> bool:
    """OR-reduce a per-host boolean across the pod.  **Collective** when
    ``n_proc > 1`` — every process must call it; single-host: identity."""
    if n_proc <= 1:
        return bool(flag)
    import jax.numpy as jnp
    from jax.experimental import multihost_utils

    gathered = multihost_utils.process_allgather(
        jnp.asarray([1 if flag else 0], jnp.int32))
    return bool(np.any(np.asarray(gathered)))


def _probe_readable(mgr: "CheckpointManager", step: int) -> bool:
    """Can step ``step`` be deserialized at all (template-less, host-side)?
    Distinguishes a corrupt checkpoint (unreadable no matter what) from a
    caller bug (readable checkpoint, mismatched restore template)."""
    try:
        mgr.restore(step)
        return True
    except Exception:
        return False


def auto_resume(
    mgr: "CheckpointManager",
    template: PyTree,
    mesh: Optional[Mesh] = None,
    specs: Optional[PyTree] = None,
    verify: bool = True,
):
    """``(start_step, state)`` for a preemption-safe loop: restore the
    newest *good* checkpoint when one exists (resuming at ``step + 1``),
    else start fresh from ``template``.  One call makes any training
    script relaunch-safe::

        start, state = auto_resume(mgr, {'params': params, 'opt': opt_state})
        with GracefulShutdown() as stop:
            for step in range(start, total): ...

    "Newest good", not "latest": a step that is **proven corrupt** is
    quarantined — renamed aside to ``<dir>.quarantine/<step>`` with a
    ``ckpt_quarantine`` event recording the step and reason — and the walk
    continues to the next older step, so a corrupted latest checkpoint
    costs one save interval instead of the run.  Proven corrupt means the
    integrity manifest (``resilience.ckpt_guard``) fails verification, or
    a manifest-less step cannot be deserialized even template-free.
    Everything else fails **loudly** instead of wiping resume state:

    - a transient ``OSError`` is retried with backoff and, if persistent,
      re-raised — an infra outage must not quarantine good checkpoints;
    - a restore error on a step whose manifest verified (or that a
      template-free probe can read) is a caller bug — wrong/drifted
      template, resharding misconfig — and is re-raised as-is;
    - on a multi-host pod, the per-step verification verdict is agreed
      across hosts (any host seeing corruption condemns the step for
      all), only process 0 performs the rename, and restore errors after
      an agreed-good verification re-raise rather than rename a step dir
      out from under peers mid-restore.

    ``verify=False`` restores the old raise-on-any-failure behavior.
    ``mesh``/``specs`` flow through to :meth:`CheckpointManager.restore`
    for resharding resumes (checkpoint from one mesh layout, resume on
    another)."""
    from ..resilience.ckpt_guard import (
        CheckpointCorruptError,
        GuardedCheckpointManager,
        manifest_path,
        quarantine_checkpoint,
        verify_checkpoint,
        verify_template,
        with_retries,
    )

    n_proc = _process_count()
    # a GuardedCheckpointManager already retries transient I/O internally;
    # wrapping it again would only multiply the backoff schedule
    restore_retries = 0 if isinstance(mgr, GuardedCheckpointManager) else 3

    def _quarantine(step: int, reason: str) -> None:
        quarantine_checkpoint(mgr.directory, step, reason=reason)
        reload_fn = getattr(mgr, "reload", None)
        if callable(reload_fn):
            reload_fn()

    steps = sorted(mgr.all_steps(), reverse=True)
    for step in steps:
        has_manifest = False
        if verify:
            problems = verify_checkpoint(mgr.directory, step)
            if _pod_any(bool(problems), n_proc):
                _quarantine(step, reason="integrity verification failed: "
                            + "; ".join(problems[:3] or ["(on another host)"]))
                continue
            has_manifest = os.path.exists(manifest_path(mgr.directory, step))
            if has_manifest:
                drift = verify_template(mgr.directory, step, template)
                if drift:
                    raise ValueError(
                        f"auto_resume: checkpoint step {step} verified OK "
                        "but the restore template does not match its "
                        "recorded tree (drifted model/config?): "
                        + "; ".join(drift[:5]))
        try:
            state = with_retries(
                lambda s=step: mgr.restore(
                    s, template=template, mesh=mesh, specs=specs),
                retries=restore_retries, label="restore",
                retry_on=(OSError,))
            return step + 1, state
        except OSError:
            # transient-I/O retries exhausted: storage trouble, not proven
            # corruption — fail loudly, keep every checkpoint in place
            raise
        except Exception as e:
            if not verify or n_proc > 1:
                raise
            if not isinstance(e, CheckpointCorruptError) and (
                has_manifest or _probe_readable(mgr, step)
            ):
                # bytes are hash-verified (or deserialize fine without the
                # template): the failure is the caller's restore request,
                # not the checkpoint — quarantining would wipe good state
                raise
            _quarantine(step, reason=repr(e))
    return 0, template


class CheckpointManager:
    """Step-numbered checkpoints with retention + latest-step resume.

    The subsystem the reference lacks entirely (SURVEY §5 "no unified
    save/load, no resume logic").  Usage::

        mgr = CheckpointManager(dir, max_to_keep=3)
        mgr.save(step, {'params': params, 'opt': opt_state})
        ...
        step = mgr.latest_step()          # None if fresh run
        state = mgr.restore(step, template={'params': params, 'opt': opt_state})
    """

    def __init__(self, directory: str, max_to_keep: int = 3, save_interval_steps: int = 1):
        ocp = _ocp()
        self.directory = _norm_path(directory)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
            ),
        )

    def save(self, step: int, state: PyTree, wait: bool = False,
             force: bool = False) -> bool:
        """Returns True iff the step was actually saved — with
        ``save_interval_steps > 1`` Orbax declines off-interval steps
        unless ``force=True`` (the grace-window/final-save path)."""
        ocp = _ocp()
        saved = self._mgr.save(
            step, args=ocp.args.StandardSave(state), force=force)
        if wait:
            self._mgr.wait_until_finished()
        if saved:
            from ..obs.events import emit_event

            emit_event("checkpoint_save", step=int(step), wait=bool(wait),
                       directory=str(self.directory))
        return saved

    def restore(
        self,
        step: Optional[int] = None,
        template: Optional[PyTree] = None,
        mesh: Optional[Mesh] = None,
        specs: Optional[PyTree] = None,
    ) -> PyTree:
        ocp = _ocp()
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(f"no checkpoints under {self.directory}")
        if specs is not None and mesh is None:
            from ..dist.topology import tpc

            mesh = tpc.get_view()
        if mesh is not None and specs is None:
            raise ValueError("restore: `mesh` given without `specs`")
        if specs is not None and template is None:
            raise ValueError(
                "restore: resharding restore (`specs`) needs `template` "
                "for the shapes/dtypes"
            )
        if template is None:
            return self._mgr.restore(step)
        if mesh is not None and specs is not None:
            def abstract(x, s):
                return jax.ShapeDtypeStruct(
                    np.shape(x),
                    getattr(x, "dtype", np.asarray(x).dtype),
                    sharding=NamedSharding(mesh, s or PartitionSpec()),
                )

            template = jax.tree.map(abstract, template, specs)
        out = self._mgr.restore(step, args=ocp.args.StandardRestore(template))
        from ..obs.events import emit_event

        emit_event("checkpoint_restore", step=int(step),
                   directory=str(self.directory),
                   resharded=mesh is not None)
        return out

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self):
        return self._mgr.all_steps()

    def wait_until_finished(self) -> None:
        self._mgr.wait_until_finished()

    def reload(self) -> None:
        """Re-scan the directory (needed after a step dir was renamed
        aside externally, e.g. quarantine of a corrupt checkpoint)."""
        reload_fn = getattr(self._mgr, "reload", None)
        if callable(reload_fn):
            reload_fn()

    def close(self) -> None:
        self._mgr.close()

    def __enter__(self) -> "CheckpointManager":
        return self

    def __exit__(self, *exc) -> None:
        # Wait for outstanding ASYNC saves before closing, even when the
        # block is unwinding on an exception: a crash between save() and
        # process teardown must not strand a partially-committed step
        # (Orbax only lists fully-committed steps, so an abandoned save
        # would silently lose the newest checkpoint).
        try:
            self.wait_until_finished()
        finally:
            self.close()
