"""Context-parallel chunked prefill over paged KV (PR 20).

The tentpole contract (docs/long_context.md "CP prefill serving"): a
prompt's prefill chunks run across a ``context`` mesh axis — each CP
rank owns a contiguous slice of the prompt and fills its OWN slice of
the block-sharded paged pool, ring-passing (k, v) payloads via
python-unrolled ppermutes so every hop is priced in the HLO comm
ledger.  The bar is BIT parity: temperature-0 tokens from a CP engine
must equal the single-replica chunked-prefill engine's, fp pool,
dense/GQA/sliding, gather oracle and pallas carry kernel, including
the prefill-tier -> decode-replica handoff — while ``decode_signatures``
stays 1 (the S_in=1 signature compiles the local-slice + psum-combine
decode, not a second ring program).

Reference engines are banked per session (``bundle_bank`` in conftest —
ROADMAP 5b): every test here shares one golden run per model family.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torchdistpackage_tpu.dist import tpc
from torchdistpackage_tpu.models import (
    GPTConfig, gpt_param_specs, init_gpt_params, llama_config)
from torchdistpackage_tpu.obs import (
    EventLog, ledger_from_compiled, set_default_event_log)
from torchdistpackage_tpu.obs.comm_ledger import cp_ring_overlap
from torchdistpackage_tpu.obs.mem_ledger import headroom_verdict
from torchdistpackage_tpu.obs.report import _validate_serving
from torchdistpackage_tpu.ops.paged_attention import modeled_attend_temp_bytes
from torchdistpackage_tpu.ops.ring_paged import (
    modeled_cp_working_set_bytes, ring_chunk_bytes, ring_hops_per_chunk)
from torchdistpackage_tpu.serving import Request, Router, ServingEngine

PROMPT, NEW, BS, CHUNK = 9, 6, 4, 4

CFGS = {
    "dense": lambda: GPTConfig(vocab_size=64, dim=32, nheads=4, nlayers=2,
                               max_seq=64),
    "gqa": lambda: llama_config(vocab_size=64, dim=32, nheads=4, nlayers=2,
                                max_seq=64, kv_heads=2, ffn_hidden=48,
                                dtype=jnp.float32),
    "sliding": lambda: llama_config(vocab_size=64, dim=32, nheads=4,
                                    nlayers=2, max_seq=64, kv_heads=2,
                                    ffn_hidden=48, dtype=jnp.float32,
                                    sliding_window=6),
}


def _prompts(cfg, n=2):
    return np.stack([
        np.asarray(jax.random.randint(
            jax.random.PRNGKey(10 + i), (PROMPT,), 0, cfg.vocab_size))
        for i in range(n)
    ]).astype(np.int32)


@pytest.fixture(scope="module")
def refs(bundle_bank):
    """Per-family golden bundle: unsharded single-replica chunked-prefill
    run (the parity oracle), banked for the session.  num_blocks=16 so
    CP engines at cp in {1, 2, 4} can share the same pool geometry (the
    router's handoff check requires equal geometry across replicas)."""

    def get(fam):
        def build():
            cfg = CFGS[fam]()
            params = init_gpt_params(jax.random.PRNGKey(0), cfg)
            prompts = _prompts(cfg)
            eng = ServingEngine(params, cfg, num_slots=2, block_size=BS,
                                chunk=CHUNK, num_blocks=16)
            rids = [eng.submit(Request(p.tolist(), NEW)) for p in prompts]
            eng.run_until_idle(max_ticks=500)
            want = [np.asarray(eng.finished[r]["tokens"]) for r in rids]
            assert eng.serving_summary()["decode_signatures"] == 1
            return {"cfg": cfg, "params": params, "prompts": prompts,
                    "want": want}
        return bundle_bank.get(("cp-ref", fam), build)

    return get


def _cp_engine(ref, cp, *, impl="gather", **kw):
    devices = jax.devices()
    tpc.setup_process_groups([("context", cp)], devices=devices[:cp])
    mesh = tpc.get_view()
    return ServingEngine(ref["params"], ref["cfg"], num_slots=2,
                         block_size=BS, chunk=CHUNK, num_blocks=16,
                         mesh=mesh, cp_axis="context", attn_impl=impl, **kw)


def _assert_parity(ref, eng, tag):
    rids = [eng.submit(Request(p.tolist(), NEW)) for p in ref["prompts"]]
    eng.run_until_idle(max_ticks=500)
    for w, r in zip(ref["want"], rids):
        np.testing.assert_array_equal(w, eng.finished[r]["tokens"],
                                      err_msg=tag)
    return eng.serving_summary()


# ------------------------------------------------------------ bit parity


@pytest.mark.parametrize("fam,cp", [
    ("dense", 2),
    ("sliding", 2),
    # wider rings and the dense family's 4-way split exercise no new
    # signature shapes (sub-chunk routing covered by the cp=4 dense arm)
    # — slow tier keeps them without charging tier-1 two more compiles
    pytest.param("dense", 4, marks=pytest.mark.slow),
    pytest.param("gqa", 4, marks=pytest.mark.slow),
])
def test_cp_prefill_token_parity(refs, fam, cp):
    """CP chunked prefill is bit-identical to the single-replica oracle,
    and the host-side ring ledger agrees with the hop/byte model."""
    ref = refs(fam)
    s = _assert_parity(ref, _cp_engine(ref, cp), f"{fam} cp={cp}")
    cfg = ref["cfg"]
    assert s["decode_signatures"] == 1 and s["prefill_signatures"] == 1
    lc = s["long_context"]
    assert lc["cp"] == cp and lc["cp_axis"] == "context"
    assert lc["ring_hops"] == \
        lc["prefill_chunks"] * ring_hops_per_chunk(cfg.nlayers, cp)
    assert lc["ring_bytes"] == lc["prefill_chunks"] * ring_chunk_bytes(
        nlayers=cfg.nlayers, cp=cp, batch=2,
        kv_heads=cfg.block.kv_head_count, head_dim=cfg.block.head_dim,
        chunk=CHUNK, nb_local=16 // cp, block_size=BS, itemsize=4)


def test_cp1_degenerate_is_ring_free(refs):
    """cp=1 on a context mesh is the identity: same tokens, zero hops —
    the validated long_context block still renders (cp=1, ring_bytes=0)."""
    ref = refs("dense")
    s = _assert_parity(ref, _cp_engine(ref, 1), "dense cp=1")
    lc = s["long_context"]
    assert lc["cp"] == 1 and lc["ring_hops"] == 0 and lc["ring_bytes"] == 0
    assert lc["prefill_chunks"] > 0


def test_cp_pallas_carry_matches_gather(refs):
    """The pallas carry entry point (un-normalized online-softmax carry
    accumulated across ranks, finalized once) reproduces the gather
    oracle's tokens bit-for-bit on the GQA family under cp=2."""
    ref = refs("gqa")
    s = _assert_parity(ref, _cp_engine(ref, 2, impl="pallas"),
                       "gqa cp=2 pallas")
    assert s["decode_signatures"] == 1
    assert s["long_context"]["ring_hops"] > 0


@pytest.mark.slow
def test_cp_composes_with_tensor_parallel(refs, devices8):
    """cp=2 x tp=2: the ring runs over ``context`` while attention heads
    shard over ``tensor`` — tokens still bit-match the serial oracle."""
    from jax.sharding import NamedSharding

    ref = refs("gqa")
    cfg = ref["cfg"]
    tpc.setup_process_groups([("context", 2), ("tensor", 2)],
                             devices=devices8[:4])
    mesh = tpc.get_view()
    specs = gpt_param_specs(cfg, tp_axis="tensor")
    sharded = jax.tree.map(
        lambda a, sp: jax.device_put(a, NamedSharding(mesh, sp)),
        ref["params"], specs)
    eng = ServingEngine(sharded, cfg, num_slots=2, block_size=BS,
                        chunk=CHUNK, num_blocks=16, mesh=mesh,
                        axis="tensor", cp_axis="context")
    _assert_parity(ref, eng, "gqa tp=2 x cp=2")


# ------------------------------------------- prefill tier -> decode tier


def test_cp_prefill_tier_handoff(refs):
    """PR-15 disaggregation composes with CP prefill: the prefill
    replica rings a long prompt to first token, the router migrates its
    paged blocks to a plain decode replica, and the finished tokens
    still bit-match the single-replica oracle.  The handoff of a
    >=long_ctx_threshold prompt emits ``kv_handoff_long``."""
    ref = refs("gqa")
    log = EventLog()
    set_default_event_log(log)
    try:
        pre = _cp_engine(ref, 2)
        dec = ServingEngine(ref["params"], ref["cfg"], num_slots=2,
                            block_size=BS, chunk=CHUNK, num_blocks=16)
        pre._ev = log
        dec._ev = log
        router = Router([pre, dec], roles=["prefill", "decode"],
                        long_ctx_threshold=8)
        rids = [router.submit(Request(p.tolist(), NEW))
                for p in ref["prompts"]]
        router.run_until_idle()
    finally:
        set_default_event_log(None)
    for w, r in zip(ref["want"], rids):
        np.testing.assert_array_equal(w, router.finished[r]["tokens"],
                                      err_msg="cp prefill-tier handoff")
        assert router.finished[r]["replica"] == 1

    # tier separation: the CP replica only prefills, the decode replica
    # only decodes — one signature each
    assert pre.stats["decode_steps"] == 0 and pre.stats["prefill_chunks"] > 0
    assert dec.stats["prefill_chunks"] == 0 and dec.stats["decode_steps"] > 0
    sp, sd = pre.serving_summary(), dec.serving_summary()
    assert sp["prefill_signatures"] == 1 and sp["long_context"]["cp"] == 2
    assert sd["decode_signatures"] == 1 and sd["prefill_signatures"] == 0
    assert pre.stats["migrated_out"] == 2 and dec.stats["migrated_in"] == 2
    assert _validate_serving(sp) == []

    kinds = {e["kind"] for e in log.as_list()}
    assert {"cp_prefill_chunk", "cp_ring_hop", "kv_handoff_long"} <= kinds
    evs = [e for e in log.as_list() if e["kind"] == "kv_handoff_long"]
    assert len(evs) == 2
    for e in evs:
        assert e["cp"] == 2 and e["length"] >= 8 and e["bytes"] > 0
        assert e["src_replica"] == 0 and e["dst_replica"] == 1
        assert e["n_blocks"] == -(-(PROMPT + 1) // BS)


# --------------------------------------------------- HLO comm evidence


def test_cp_ring_hops_priced_per_hop(refs, devices8):
    """The comm-ledger acceptance bar: the compiled prefill chunk shows
    exactly ``4*(cp-1)*nlayers`` collective-permutes on the cp dim — the
    layer loop is python-unrolled, so there is no while-body undercount
    — and their HLO byte total equals the host model's
    ``ring_chunk_bytes``.  ``cp_ring_overlap`` summarizes the window."""
    ref = refs("dense")
    cfg = ref["cfg"]
    eng = _cp_engine(ref, 2)
    B, C, mb = eng.num_slots, eng.chunk, eng.max_blocks
    samp = {"temperature": jnp.zeros((B,), jnp.float32),
            "top_k": jnp.full((B,), cfg.vocab_size, jnp.int32),
            "top_p": jnp.ones((B,), jnp.float32)}
    lowered = eng._step_fn.lower(
        eng.params, eng.cache, jnp.zeros((B, C), jnp.int32),
        jnp.zeros((B, mb), jnp.int32), jnp.zeros((B,), jnp.int32),
        jnp.zeros((B,), jnp.int32), samp, jnp.zeros((B, 2), jnp.uint32))
    led = ledger_from_compiled(lowered.compile(), mesh=tpc.get_view())

    cps = [c for c in led["collectives"] if c["dim"] == "cp"]
    perms = [c for c in cps if "permute" in c["op"]]
    assert len(perms) == ring_hops_per_chunk(cfg.nlayers, 2) == 8
    assert all(c["bytes"] > 0 for c in perms)
    assert sum(c["bytes"] for c in perms) == ring_chunk_bytes(
        nlayers=cfg.nlayers, cp=2, batch=B,
        kv_heads=cfg.block.kv_head_count, head_dim=cfg.block.head_dim,
        chunk=C, nb_local=eng.num_blocks // 2, block_size=BS, itemsize=4)
    # plus the two combine all-reduces (logits psum, token pmax) and
    # nothing else on the cp dim
    assert len(cps) - len(perms) == 2

    ov = cp_ring_overlap(led)
    assert ov["cp_hops"] == 8
    assert ov["cp_hop_bytes"] == sum(c["bytes"] for c in perms)
    assert ov["cp_async_hops"] >= 0  # CPU HLO: sync; on-chip in ROADMAP 5c


# ------------------------------------------------------------ validation


def test_cp_engine_validation():
    """Construction-time guard rails (no compiles): mesh required,
    unsupported feature combos rejected, chunk and explicit num_blocks
    must split evenly across ranks, default num_blocks rounds UP."""
    cfg = CFGS["dense"]()
    params = init_gpt_params(jax.random.PRNGKey(0), cfg)
    with pytest.raises(ValueError, match="mesh"):
        ServingEngine(params, cfg, num_slots=2, block_size=BS, chunk=CHUNK,
                      cp_axis="context")
    tpc.setup_process_groups([("context", 2)], devices=jax.devices()[:2])
    mesh = tpc.get_view()
    kw = dict(num_slots=2, block_size=BS, mesh=mesh, cp_axis="context")
    with pytest.raises(ValueError, match="chunk"):
        ServingEngine(params, cfg, chunk=3, **kw)
    with pytest.raises(ValueError, match="num_blocks"):
        ServingEngine(params, cfg, chunk=CHUNK, num_blocks=15, **kw)
    for bad in (dict(spec_k=2), dict(kv_quant="int8"),
                dict(prefix_cache=True)):
        with pytest.raises((ValueError, NotImplementedError)):
            ServingEngine(params, cfg, chunk=CHUNK, **bad, **kw)
    # default pool geometry rounds up to a cp multiple
    eng = ServingEngine(params, cfg, chunk=CHUNK, **kw)
    assert eng.num_blocks % 2 == 0


# ----------------------------------------------- 128k/256k headroom math


def _cp_verdicts(*, max_ctx, cp, kv_heads=2, head_dim=8, nlayers=1,
                 block_size=512, chunk=512):
    """The acceptance-bar shape math at a long context: per-device bytes
    for (a) a single replica holding the whole pool and attending via
    the gather view, vs (b) one CP rank holding pool/cp plus the ring
    working set on the block-bounded pallas path."""
    nb = max_ctx // block_size
    pool = 2 * nlayers * nb * kv_heads * block_size * head_dim * 4
    mb = nb
    gather_ws = modeled_attend_temp_bytes(
        "gather", batch=1, kv_heads=kv_heads, max_blocks=mb,
        block_size=block_size, head_dim=head_dim, itemsize=4)
    pallas_ws = modeled_attend_temp_bytes(
        "pallas", batch=1, kv_heads=kv_heads, max_blocks=mb,
        block_size=block_size, head_dim=head_dim, itemsize=4, groups=2)
    cp_ws = modeled_cp_working_set_bytes(
        kv_heads=kv_heads, head_dim=head_dim, block_size=block_size,
        nb_local=nb // cp, chunk=chunk, cp=cp,
        attend_temp_bytes=pallas_ws)
    single = pool + gather_ws
    ranked = pool // cp + cp_ws
    return single, ranked


@pytest.mark.parametrize("max_ctx,cp", [(131072, 2), (262144, 4)])
def test_cp_headroom_verdicts(max_ctx, cp):
    """128k and 256k MemoryModel verdicts, pure shape math: at a budget
    sized between the two footprints, pool + gather view reads
    ``oom_risk`` while the CP rank's pool slice + ring working set reads
    ``ok`` — the quantitative case for the prefill tier."""
    single, ranked = _cp_verdicts(max_ctx=max_ctx, cp=cp)
    # the ring's rotating double-buffers cost ~1.5x the resident pool
    # slice, so CP's win at cp=2 is real but not free — the honest
    # budget is the one the single replica exactly exhausts
    assert ranked < 0.8 * single
    capacity = single
    assert headroom_verdict(single, capacity)["verdict"] == "oom_risk"
    assert headroom_verdict(ranked, capacity)["verdict"] == "ok"


# -------------------------------------------------- 128k CP serving (slow)


@pytest.mark.slow
def test_128k_cp_long_context_serving():
    """The PR-12 32k acceptance row, grown to 128k on a CP mesh: a
    128k-capacity engine split cp=2 serves a long prompt through ring
    paged prefill on the pallas carry path and decodes at one signature
    per phase; the rendered RUNREPORT memory section carries the
    ok-vs-oom_risk verdict pair from :func:`_cp_verdicts`."""
    from torchdistpackage_tpu.obs.mem_ledger import mem_report
    from torchdistpackage_tpu.serving import pool_bytes

    cfg = llama_config(vocab_size=64, dim=32, nheads=4, nlayers=1,
                       max_seq=131072, kv_heads=2, ffn_hidden=48,
                       dtype=jnp.float32)
    params = init_gpt_params(jax.random.PRNGKey(0), cfg)
    tpc.setup_process_groups([("context", 2)], devices=jax.devices()[:2])
    mesh = tpc.get_view()
    eng = ServingEngine(params, cfg, num_slots=1, block_size=512,
                        chunk=512, max_ctx=131072, mesh=mesh,
                        cp_axis="context", attn_impl="pallas")
    assert eng.max_blocks == 256
    prompt = np.asarray(jax.random.randint(
        jax.random.PRNGKey(1), (2048,), 0, cfg.vocab_size), np.int32)
    rid = eng.submit(Request(prompt.tolist(), 4))
    eng.run_until_idle(max_ticks=100)
    f = eng.finished[rid]
    assert f["reason"] == "max_tokens" and f["new_tokens"] == 4
    s = eng.serving_summary()
    assert s["decode_signatures"] == 1 and s["prefill_signatures"] == 1
    assert s["long_context"]["ring_hops"] > 0

    # parity against the unsharded single-replica engine on the same
    # prompt — 128k pool geometry, not just the toy 64-token configs
    ref = ServingEngine(params, cfg, num_slots=1, block_size=512,
                        chunk=512, max_ctx=131072, attn_impl="pallas")
    rr = ref.submit(Request(prompt.tolist(), 4))
    ref.run_until_idle(max_ticks=100)
    np.testing.assert_array_equal(ref.finished[rr]["tokens"], f["tokens"])

    single, ranked = _cp_verdicts(max_ctx=131072, cp=2)
    capacity = single
    assert headroom_verdict(single, capacity)["verdict"] == "oom_risk"
    assert headroom_verdict(ranked, capacity)["verdict"] == "ok"
    # the real pool agrees with the shape math it halves: pool_bytes
    # sums the sharded leaves' GLOBAL shape, so /cp gives the per-rank
    # slice the verdict charges
    pool = pool_bytes(eng.cache)
    assert pool == 2 * cfg.nlayers * eng.num_blocks * 2 * 512 * 8 * 4
    section = mem_report(
        measured_peak_bytes=ranked, capacity_bytes=capacity,
        kv_pool={"pool_bytes": pool, "pool_bytes_expected": pool},
        emit=False)
    assert section["verdict"] == "ok"
    assert section["kv_pool"]["accounting_match"] is True
