"""ZeRO golden tests — the reference's discipline (examples/test_zero_optim.py:
27-66): Bf16ZeroOptimizer vs plain DDP+Adam, params must track.  Here: ZeRO
(sharded masters/state) vs single-device adam on the same seed, plus the
hybrid intra-node variant and TP composition."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from torchdistpackage_tpu.dist import tpc
from torchdistpackage_tpu.parallel.zero import ZeroOptimizer, zero_partition_spec
from tests.test_data_parallel import _data, make_mlp_params, mlp_loss


def test_zero_partition_spec():
    spec, d = zero_partition_spec((32, 16), P(), "data", 8)
    assert spec == P("data") and d == 0
    spec, d = zero_partition_spec((30, 16), P(), "data", 8)
    assert spec == P(None, "data") and d == 1
    spec, d = zero_partition_spec((30, 15), P(), "data", 8)
    assert spec == P() and d == -1
    # TP-sharded dim is not reusable: data goes to the next free dim
    spec, d = zero_partition_spec((32, 16), P("tensor"), "data", 8)
    assert spec == P("tensor", "data") and d == 1


def _serial_trajectory(params, opt, nsteps=4):
    state = opt.init(params)

    @jax.jit
    def step(p, s, b):
        loss, g = jax.value_and_grad(mlp_loss)(p, b)
        u, s = opt.update(g, s, p)
        return jax.tree.map(jnp.add, p, u), s, loss

    hist = []
    for i in range(nsteps):
        batch = _data(jax.random.PRNGKey(100 + i))
        params, state, loss = step(params, state, batch)
        hist.append(float(loss))
    return params, hist


@pytest.mark.parametrize("accum", [1, 2])
def test_zero_matches_serial_adam(devices8, accum):
    tpc.setup_process_groups([("data", 8)], devices=devices8)
    params = make_mlp_params(jax.random.PRNGKey(0))
    opt = optax.adam(1e-2)
    ref_params, ref_losses = _serial_trajectory(params, opt)

    zero = ZeroOptimizer(opt)
    zp = zero.place_params(params)
    zs = zero.init(zp)
    # masters really are sharded over data
    m = zs["master"]["w1"]
    assert m.sharding.spec == P("data")
    step = zero.make_train_step(mlp_loss, grad_accum_iters=accum)

    for i in range(4):
        batch = _data(jax.random.PRNGKey(100 + i))
        zp, zs, loss = step(zp, zs, zero_shard_batch(batch))
        np.testing.assert_allclose(float(loss), ref_losses[i], rtol=1e-4, atol=1e-5)

    for k in params:
        np.testing.assert_allclose(
            np.asarray(zp[k]), np.asarray(ref_params[k]), rtol=1e-3, atol=1e-5
        )


def zero_shard_batch(batch):
    import jax
    from jax.sharding import NamedSharding

    mesh = tpc.get_view()
    return jax.tree.map(
        lambda x: jax.device_put(x, NamedSharding(mesh, P("data"))), batch
    )


def test_hybrid_zero(devices8):
    """Shard state over the intra 'node' sub-axis only; grads still average
    over the whole data group (Intro.md:69-77 semantics)."""
    tpc.setup_process_groups([("data", 8)], devices=devices8)
    view = tpc.build_hybrid_mesh(intra_size=4)
    params = make_mlp_params(jax.random.PRNGKey(0))
    opt = optax.adam(1e-2)
    ref_params, ref_losses = _serial_trajectory(params, opt)

    zero = ZeroOptimizer(
        opt,
        mesh=view,
        shard_axis="data_intra",
        grad_reduce_axes=("data_inter", "data_intra"),
    )
    zp = zero.place_params(params)
    zs = zero.init(zp)
    # master sharded 4-way (intra), replicated over inter
    assert zs["master"]["w1"].sharding.spec == P("data_intra")
    step = zero.make_train_step(mlp_loss)

    from jax.sharding import NamedSharding

    for i in range(4):
        batch = _data(jax.random.PRNGKey(100 + i))
        batch = jax.tree.map(
            lambda x: jax.device_put(
                x, NamedSharding(view, P(("data_inter", "data_intra")))
            ),
            batch,
        )
        zp, zs, loss = step(zp, zs, batch)
        np.testing.assert_allclose(float(loss), ref_losses[i], rtol=1e-4, atol=1e-5)

    for k in params:
        np.testing.assert_allclose(
            np.asarray(zp[k]), np.asarray(ref_params[k]), rtol=1e-3, atol=1e-5
        )


def test_zero_with_tp(devices8):
    """ZeRO over data axis composed with TP=2 sharded transformer params."""
    import functools

    from torchdistpackage_tpu.parallel.tensor_parallel import (
        TransformerConfig,
        init_transformer_params,
        transformer_forward,
        transformer_param_specs,
    )

    cfg = TransformerConfig(dim=32, nheads=4, nlayers=1, ffn_mult=2)
    S = 16
    tpc.setup_process_groups([("data", 4), ("tensor", 2)], devices=devices8)
    mesh = tpc.get_view()
    params = init_transformer_params(jax.random.PRNGKey(0), cfg)
    specs = transformer_param_specs(cfg, axis="tensor")
    opt = optax.adam(1e-2)

    def tp_loss(p, batch):
        out = transformer_forward(p, batch["x"], cfg, axis="tensor", sp=True)
        return jnp.mean((out - batch["y"]) ** 2)

    def serial_loss(p, batch):
        out = transformer_forward(p, batch["x"], cfg)
        return jnp.mean((out - batch["y"]) ** 2)

    sstate = opt.init(params)

    @jax.jit
    def serial_step(p, s, b):
        loss, g = jax.value_and_grad(serial_loss)(p, b)
        u, s = opt.update(g, s, p)
        return jax.tree.map(jnp.add, p, u), s, loss

    zero = ZeroOptimizer(opt, mesh=mesh, param_specs=specs)
    zp = zero.place_params(params)
    zs = zero.init(zp)
    # a TP-sharded weight gets data inserted on its free dim
    assert zs["master"]["blocks"][0]["mlp"]["w1"].sharding.spec == P("data", "tensor")
    step = zero.make_train_step(tp_loss)

    sparams = params
    from jax.sharding import NamedSharding

    for i in range(3):
        kx, ky = jax.random.split(jax.random.PRNGKey(10 + i))
        batch = {
            "x": jax.random.normal(kx, (8, S, cfg.dim)),
            "y": jax.random.normal(ky, (8, S, cfg.dim)),
        }
        sparams, sstate, sloss = serial_step(sparams, sstate, batch)
        dbatch = jax.tree.map(
            lambda x: jax.device_put(x, NamedSharding(mesh, P("data"))), batch
        )
        zp, zs, dloss = step(zp, zs, dbatch)
        np.testing.assert_allclose(float(dloss), float(sloss), rtol=1e-4, atol=1e-5)

    np.testing.assert_allclose(
        np.asarray(zp["blocks"][0]["mlp"]["w1"]),
        np.asarray(sparams["blocks"][0]["mlp"]["w1"]),
        rtol=1e-3,
        atol=1e-5,
    )
