"""Trace replay: routing policy at 10^5-request scale, with no devices.

ROADMAP 2(c)+5(a): a routing policy ("prefix-affinity vs load", "when
to rebalance", "how tight can deadlines get") can only be MEASURED at
a scale no test fleet reaches — millions of requests, diurnal load,
long-tailed prefix sharing.  This tool closes that gap on one CPU: it
drives a synthetic-but-structured workload through the REAL
:class:`~..serving.router.Router` and REAL
:class:`~..serving.engine.ServingEngine` scheduling stack, with only
the device programs swapped for the host-side
:class:`~..serving.sim.StubDeviceStep` (same admission gate, same
preemption/shed/deadline policy, same allocator + audit, same
migration lanes — see serving/sim.py for why parity claims survive the
stub).  Every routing knob becomes a measurable curve.

The workload has the four structures routing policy actually reacts to:

- **Zipf shared prefixes** — prompts open with one of ``--groups``
  system prefixes drawn from a Zipf-like law, so prefix-affinity
  routing has a real popularity skew to exploit.
- **Diurnal arrivals** — a sinusoidal Poisson arrival rate whose peak
  deliberately exceeds fleet capacity (queues grow, deadlines shed)
  and whose trough idles it.
- **Multi-turn re-arrivals** — a fraction of completed conversations
  re-arrive with their full context plus a new user turn (warm prefix,
  growing length).
- **Mixed priorities/deadlines** — three priority classes, a slice of
  them with TTFT budgets tight enough to shed at peak.

Evidence out (the point of the exercise):

- the **FLEETREPORT** (``Router.summary()``), schema-validated through
  ``obs.report._validate_router`` before it is reported;
- the **decision ledger** — every placement is checked attributable to
  a ``route_decision``/``handoff_decision``/``rebalance_decision``
  record, and every fleet-size change to a non-hold ``scale_decision``
  (``attribution.complete``); ``--ledger`` writes the router-scope
  records as JSONL;
- optional ``--report`` (the RUNREPORT convention: JSON at the path +
  a sibling ``.md``) and ``--trace`` (a fleet Perfetto trace of the
  last ``--history`` events).

ISSUE-19 elastic-fleet mode: ``--spares N`` provisions N extra parked
replicas (``provisioned_spare`` — they cost nothing until revived),
``--autoscale`` attaches the goodput-driven
:class:`~..serving.autoscale.Autoscaler`, and ``--chaos`` seeds
transport faults (every ``TRANSPORT_FAULT_KINDS`` member, including
replica death mid-migration) into the migration wire.  Arrival rate is
computed from the CORE replicas only, so the load — and the reported
``config_hash`` — is identical with autoscaling on or off: ``--ab``
runs both arms back to back and reports the attainment delta at equal
hash.  Attainment/goodput/replica-count curves are sampled every
``--curve-every`` ticks into the report.

Usage::

    python -m torchdistpackage_tpu.tools.trace_replay \
        --n-requests 100000 --replicas 4 --spares 2 \
        --autoscale --chaos \
        --report /tmp/FLEETREPORT.json --ledger /tmp/ledger.jsonl

Prints one ``{"metric": "trace-replay", ...}`` JSON line (the
bench_trend contract) plus the fleet summary line.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import time
from typing import Any, Dict, List, Optional

import numpy as np

REPLAY_SCHEMA = "tdp-trace-replay/v1"


class LedgerCounter:
    """EventLog sink that tallies the decision ledger as it streams —
    the attribution check at 10^5 scale without holding 10^6 event
    dicts in memory.  Optionally tees router-scope records (the ledger
    proper, not per-tick engine telemetry) to an inner JSONL sink."""

    def __init__(self, sink: Any = None) -> None:
        from ..serving.tracing import ROUTER_EVENT_KINDS

        self._router_kinds = ROUTER_EVENT_KINDS
        self._sink = sink
        self.kinds: Dict[str, int] = {}
        self.route_outcomes: Dict[str, int] = {}
        self.handoff_outcomes: Dict[str, int] = {}
        self.rebalance_moved = 0
        self.scale_actions = 0
        self.scale_holds = 0

    def write(self, rec: Dict[str, Any]) -> None:
        kind = rec.get("kind")
        self.kinds[kind] = self.kinds.get(kind, 0) + 1
        if kind == "route_decision":
            o = rec.get("outcome")
            self.route_outcomes[o] = self.route_outcomes.get(o, 0) + 1
        elif kind == "handoff_decision":
            o = rec.get("outcome")
            self.handoff_outcomes[o] = self.handoff_outcomes.get(o, 0) + 1
        elif kind == "rebalance_decision":
            self.rebalance_moved += int(rec.get("moved", 0))
        elif kind == "scale_decision":
            if rec.get("action") in ("scale_up", "scale_down"):
                self.scale_actions += 1
            else:
                self.scale_holds += 1
        if self._sink is not None and kind in self._router_kinds:
            self._sink.write(rec)


class SyntheticWorkload:
    """Request generator with the four structures described in the
    module docstring.  ``next_request()`` yields Request kwargs;
    ``register(rid, ...)``/``complete(rid, tokens)`` feed finished
    conversations back in as multi-turn re-arrivals."""

    def __init__(
        self,
        rng: np.random.RandomState,
        vocab: int,
        block_size: int,
        max_ctx: int,
        n_groups: int = 32,
        zipf_a: float = 1.2,
        multiturn_p: float = 0.3,
        max_turns: int = 3,
    ) -> None:
        self.rng = rng
        self.vocab = vocab
        self.max_ctx = max_ctx
        self.multiturn_p = multiturn_p
        self.max_turns = max_turns
        w = (1.0 + np.arange(n_groups)) ** -zipf_a
        self.group_p = w / w.sum()
        self.prefixes = [
            rng.randint(0, vocab,
                        size=int(rng.choice([2, 3, 4])) * block_size
                        ).tolist()
            for _ in range(n_groups)]
        self.pool: List[tuple] = []    # (tokens, turn) finished convos
        self._turn: Dict[int, int] = {}  # router rid -> turn number
        self.stats = {"fresh": 0, "multiturn": 0, "by_prio": {}}

    def _tail(self) -> List[int]:
        return self.rng.randint(
            0, self.vocab, size=int(self.rng.randint(3, 13))).tolist()

    def next_request(self) -> Dict[str, Any]:
        max_new = int(self.rng.randint(4, 13))
        tokens = None
        turn = 0
        if self.pool and self.rng.random_sample() < self.multiturn_p:
            prev, prev_turn = self.pool.pop(
                int(self.rng.randint(len(self.pool))))
            cont = prev + self._tail()
            if len(cont) + max_new <= self.max_ctx:
                tokens, turn = cont, prev_turn + 1
        if tokens is None:
            g = int(self.rng.choice(len(self.group_p), p=self.group_p))
            tokens = self.prefixes[g] + self._tail()
        self.stats["multiturn" if turn else "fresh"] += 1
        prio = int(self.rng.choice([0, 0, 0, 0, 0, 0, 1, 1, 1, 2]))
        self.stats["by_prio"][prio] = self.stats["by_prio"].get(prio, 0) + 1
        # deadline mix: most unconstrained, a band of generous TTFT
        # budgets, and a tight slice that sheds when peak queues form
        u = self.rng.random_sample()
        deadline = None if u < 0.6 else (0.25 if u < 0.9 else 0.02)
        return {"tokens": tokens, "max_new_tokens": max_new,
                "priority": prio, "deadline_s": deadline,
                "temperature": 0.0 if self.rng.random_sample() < 0.7
                else 0.8, "seed": int(self.rng.randint(1 << 31)),
                "_turn": turn}

    def register(self, rid: int, turn: int) -> None:
        if turn < self.max_turns:
            self._turn[rid] = turn

    def complete(self, rid: int, tokens: List[int]) -> None:
        turn = self._turn.pop(rid, None)
        if turn is None:
            return
        self.pool.append((tokens, turn))
        if len(self.pool) > 4096:  # bounded re-arrival candidate pool
            self.pool.pop(0)


def run_replay(
    n_requests: int = 20_000,
    n_replicas: int = 4,
    num_slots: int = 16,
    block_size: int = 16,
    chunk: int = 16,
    vocab: int = 512,
    seed: int = 0,
    disaggregate: bool = True,
    rate_util: float = 0.9,
    diurnal_amp: float = 0.6,
    diurnal_period: int = 2048,
    rebalance_every: int = 8,
    rebalance_watermark: int = 4,
    history_max: int = 65_536,
    groups: int = 32,
    zipf_a: float = 1.2,
    multiturn_p: float = 0.3,
    long_docs: int = 0,
    long_doc_len: int = 512,
    ledger_path: Optional[str] = None,
    max_ticks: Optional[int] = None,
    autoscale: bool = False,
    n_spares: int = 0,
    autoscale_kw: Optional[Dict[str, Any]] = None,
    chaos: bool = False,
    chaos_faults: int = 12,
    curve_every: int = 512,
) -> Dict[str, Any]:
    """Drive ``n_requests`` through a stubbed fleet; return the replay
    report (validated FLEETREPORT + attribution + sim/wall costs).
    Keeps the last ``history_max`` events in memory for trace
    rendering; the full ledger streams through :class:`LedgerCounter`
    (and to ``ledger_path`` as JSONL when given).

    ``n_spares`` extra replicas join the fleet PARKED
    (``provisioned_spare``); arrival rate comes from the core replicas
    only, so the workload — and the returned ``config_hash`` — is
    byte-identical whether ``autoscale`` is on or off (the A/B
    contract).  ``chaos=True`` seeds ``chaos_faults`` transport faults
    (cycling every ``TRANSPORT_FAULT_KINDS`` member, death included)
    across the migration-send sequence space.

    ``long_docs > 0`` carves that many submissions out of
    ``n_requests`` and replaces them with ``long_doc_len``-token
    documents spread evenly over the arrival schedule — the
    mixed-traffic starvation probe (docs/long_context.md "CP prefill
    serving"): the returned ``mixed_traffic`` block carries per-class
    latency percentiles in TICKS, so "one long document does not starve
    the short requests' TTFT" is an assertable, compile-free claim
    (tests/test_fleet_obs.py)."""
    import hashlib

    from ..models.gpt import GPTConfig
    from ..obs.events import (
        EventLog,
        default_event_log,
        set_default_event_log,
    )
    from ..obs.report import _validate_router
    from ..serving.autoscale import Autoscaler
    from ..serving.engine import Request, ServingEngine
    from ..serving.router import Router
    from ..serving.sim import StubDeviceStep
    from ..serving.transport import ChunkedWireTransport

    max_ctx = 8 * block_size + 64
    if long_docs:
        max_ctx = max(max_ctx, long_doc_len + 64)
    cfg = GPTConfig(vocab_size=vocab, dim=64, nheads=4, nlayers=2,
                    max_seq=max_ctx)
    rng = np.random.RandomState(seed)
    wl = SyntheticWorkload(rng, vocab, block_size, max_ctx,
                           n_groups=groups, zipf_a=zipf_a,
                           multiturn_p=multiturn_p)

    ledger_sink = None
    if ledger_path is not None:
        from ..obs.exporters import JsonlSink

        ledger_sink = JsonlSink(ledger_path)
    counter = LedgerCounter(sink=ledger_sink)
    log = EventLog(sink=counter, history_max=history_max,
                   all_processes=True)
    prev_log = default_event_log()
    set_default_event_log(log)

    # everything that shapes the WORKLOAD and fleet hardware — but NOT
    # the autoscale switch — goes into the hash, so an A/B pair proves
    # "same offered load, same fleet, only the controller differs"
    config_hash = hashlib.sha256(json.dumps({
        "n_requests": n_requests, "n_replicas": n_replicas,
        "n_spares": n_spares, "num_slots": num_slots,
        "block_size": block_size, "chunk": chunk, "vocab": vocab,
        "seed": seed, "disaggregate": disaggregate,
        "rate_util": rate_util, "diurnal_amp": diurnal_amp,
        "diurnal_period": diurnal_period,
        "rebalance_every": rebalance_every,
        "rebalance_watermark": rebalance_watermark, "groups": groups,
        "zipf_a": zipf_a, "multiturn_p": multiturn_p,
        "long_docs": long_docs, "long_doc_len": long_doc_len,
        "chaos": chaos, "chaos_faults": chaos_faults,
    }, sort_keys=True).encode()).hexdigest()[:16]

    try:
        n_total = n_replicas + max(0, n_spares)
        stubs = [StubDeviceStep() for _ in range(n_total)]
        engines = [
            ServingEngine(None, cfg, num_slots=num_slots,
                          block_size=block_size, chunk=chunk,
                          max_ctx=max_ctx, prefix_cache=True,
                          max_queue=8 * num_slots, device_step=st)
            for st in stubs]
        roles = (["prefill"] + ["decode"] * (n_replicas - 1)
                 if disaggregate and n_replicas > 1
                 else ["both"] * n_replicas)
        roles += ["both"] * max(0, n_spares)

        monkey = None
        transport = None
        if chaos:
            from ..resilience.chaos import (
                TRANSPORT_FAULT_KINDS,
                ChaosMonkey,
                Fault,
            )

            # seed faults across the migration-send sequence space:
            # cycle every kind (recoverable singles plus one repeating
            # drop and the death) at rng-chosen, collision-free seqs
            frng = np.random.RandomState(seed + 1)
            horizon = max(16, n_requests // 4)
            seqs = sorted(frng.choice(
                np.arange(1, horizon), size=min(chaos_faults, horizon - 1),
                replace=False).tolist())
            plan = []
            for k, s in enumerate(seqs):
                kind = TRANSPORT_FAULT_KINDS[k % len(TRANSPORT_FAULT_KINDS)]
                plan.append(Fault(
                    kind, step=int(s),
                    duration_s=9.0 if kind == "transport_stall" else 0.0,
                    repeat=(kind == "chunk_drop" and k % 8 == 4)))
            monkey = ChaosMonkey(faults=plan, seed=seed)
            transport = ChunkedWireTransport(chaos=monkey)

        router = Router(engines, roles=roles,
                        rebalance_every=rebalance_every,
                        rebalance_watermark=rebalance_watermark,
                        transport=transport)
        for i in range(n_replicas, n_total):
            router.set_alive(i, False, reason="provisioned_spare")
        asc = Autoscaler(router, **(autoscale_kw or {})) if autoscale \
            else None

        # arrival pacing: steady-state decode width is the fleet's
        # non-prefill slots, each retiring ~1 token/tick, so capacity
        # is ~decode_slots/avg_new requests per tick; the diurnal peak
        # runs (1 + amp) * rate_util over that on purpose.  Spares are
        # EXCLUDED — offered load must not change when they exist
        decode_slots = num_slots * sum(
            1 for r in roles[:n_replicas] if r != "prefill")
        avg_new = 8.0
        base_rate = rate_util * decode_slots / avg_new
        if max_ticks is None:
            max_ticks = int(4 * n_requests * avg_new
                            / max(decode_slots, 1)) + 10_000

        def _slo_totals():
            met = demand = good = 0
            for e in engines:
                for row in e._slo_by_prio.values():
                    met += row["met"]
                    demand += (row["completed"] + row["shed"]
                               + row["expired"])
                    good += row["goodput_tokens"]
            return met, demand, good

        curves: Dict[str, List[Any]] = {
            "tick": [], "attainment": [], "goodput_tokens": [],
            "n_alive": [], "queued": []}
        prev_slo = _slo_totals()

        def _sample(t: int) -> None:
            nonlocal prev_slo
            met, demand, good = _slo_totals()
            d_met = met - prev_slo[0]
            d_dem = demand - prev_slo[1]
            d_good = good - prev_slo[2]
            prev_slo = (met, demand, good)
            curves["tick"].append(t)
            curves["attainment"].append(
                round(d_met / d_dem, 4) if d_dem else None)
            curves["goodput_tokens"].append(d_good)
            curves["n_alive"].append(sum(router.alive))
            curves["queued"].append(
                sum(len(e.queue) for e in engines))

        # mixed traffic: the i-th long document replaces the submission
        # at an evenly spaced mark, so offered load (and the hash'd
        # workload shape) stays n_requests total
        long_marks = {
            int(round((i + 1) * n_requests / (long_docs + 1)))
            for i in range(long_docs)} if long_docs else set()
        long_rids: set = set()
        sub_tick: Dict[int, int] = {}
        waits: Dict[str, List[int]] = {"short": [], "long": []}

        submitted = 0
        tick = 0
        t0 = time.perf_counter()
        while submitted < n_requests or router.has_work():
            if submitted < n_requests:
                lam = base_rate * (1.0 + diurnal_amp * math.sin(
                    2.0 * math.pi * tick / diurnal_period))
                k = min(int(rng.poisson(max(lam, 0.0))),
                        n_requests - submitted)
                for _ in range(k):
                    if submitted in long_marks:
                        rid = router.submit(Request(
                            rng.randint(0, vocab,
                                        size=long_doc_len - 16).tolist(),
                            16, temperature=0.0,
                            seed=int(rng.randint(1 << 31))))
                        if rid not in router.rejected:
                            long_rids.add(rid)
                            sub_tick[rid] = tick
                    else:
                        kw = wl.next_request()
                        turn = kw.pop("_turn")
                        rid = router.submit(Request(**kw))
                        if rid not in router.rejected:
                            wl.register(rid, turn)
                            sub_tick[rid] = tick
                    submitted += 1
            router.step()
            if router.finished:
                # feed completions back as multi-turn re-arrivals and
                # keep the result dict from growing 10^5 entries deep
                for rid, rec in router.finished.items():
                    wl.complete(rid, [int(t) for t in rec["tokens"]])
                    t_sub = sub_tick.pop(rid, None)
                    if t_sub is not None:
                        waits["long" if rid in long_rids
                              else "short"].append(tick - t_sub)
                router.finished.clear()
            tick += 1
            if curve_every and tick % curve_every == 0:
                _sample(tick)
            if tick >= max_ticks:
                break
        _sample(tick)
        wall = time.perf_counter() - t0

        summary = router.summary()
        errs = _validate_router(summary)
        st = router.stats
        attribution = {
            "submitted": submitted,
            "ledger_route_decisions": counter.kinds.get(
                "route_decision", 0),
            "placements": st["routed"],
            "ledger_placements": counter.route_outcomes.get("routed", 0),
            "handoffs": st["handoffs"],
            "ledger_handoffs": (
                counter.handoff_outcomes.get("handoff", 0)
                + counter.handoff_outcomes.get("bounced", 0)),
            "rebalanced": st["rebalanced_requests"],
            "ledger_rebalance_moved": counter.rebalance_moved,
            "scale_actions": asc.actions if asc is not None else 0,
            "ledger_scale_actions": counter.scale_actions,
        }
        attribution["complete"] = (
            attribution["submitted"]
            == attribution["ledger_route_decisions"]
            and attribution["placements"]
            == attribution["ledger_placements"]
            and attribution["handoffs"] == attribution["ledger_handoffs"]
            and attribution["rebalanced"]
            == attribution["ledger_rebalance_moved"]
            and attribution["scale_actions"]
            == attribution["ledger_scale_actions"])
        sim = {
            "sim_device_s": round(sum(s.sim_s for s in stubs), 6),
            "calls": {k: sum(s.calls[k] for s in stubs)
                      for k in stubs[0].calls},
        }
        def _wait_pcts(xs: List[int]) -> Dict[str, Any]:
            if not xs:
                return {"n": 0, "p50_wait_ticks": None,
                        "p99_wait_ticks": None}
            a = np.asarray(xs)
            return {"n": len(xs),
                    "p50_wait_ticks": int(np.percentile(a, 50)),
                    "p99_wait_ticks": int(np.percentile(a, 99))}

        return {
            "schema": REPLAY_SCHEMA,
            "n_requests": n_requests,
            "submitted": submitted,
            "ticks": tick,
            "wall_s": round(wall, 3),
            "config_hash": config_hash,
            "curves": curves,
            "autoscale": asc.summary() if asc is not None else None,
            "chaos": ({"declared": len(monkey.faults),
                       "fired": monkey.fired_count}
                      if monkey is not None else None),
            "workload": dict(wl.stats,
                             multiturn_pool=len(wl.pool),
                             groups=groups, zipf_a=zipf_a,
                             diurnal_amp=diurnal_amp,
                             diurnal_period=diurnal_period,
                             base_rate_req_per_tick=round(base_rate, 3)),
            "summary": summary,
            "mixed_traffic": ({
                "long_docs": long_docs,
                "long_doc_len": long_doc_len,
                "short": _wait_pcts(waits["short"]),
                "long": _wait_pcts(waits["long"]),
            } if long_docs else None),
            "validation_errors": errs,
            "attribution": attribution,
            "sim": sim,
            "events": log,   # popped by main() before serialization
        }
    finally:
        set_default_event_log(prev_log)


def main(argv: Optional[List[str]] = None) -> int:
    from ..obs.report import render_summary_line, write_runreport
    from ..utils.logging import master_print

    ap = argparse.ArgumentParser(
        description="replay a synthetic request trace through the real "
                    "Router on DeviceStep-stubbed engines (no devices)")
    ap.add_argument("--n-requests", type=int, default=20_000)
    ap.add_argument("--replicas", type=int, default=4)
    ap.add_argument("--num-slots", type=int, default=16)
    ap.add_argument("--block-size", type=int, default=16)
    ap.add_argument("--chunk", type=int, default=16)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--flat", action="store_true",
                    help="homogeneous 'both' replicas (default is 1 "
                         "prefill + N-1 decode, which exercises KV "
                         "handoffs)")
    ap.add_argument("--rate-util", type=float, default=0.9,
                    help="mean arrival rate as a fraction of fleet "
                         "decode capacity")
    ap.add_argument("--diurnal-amp", type=float, default=0.6)
    ap.add_argument("--diurnal-period", type=int, default=2048)
    ap.add_argument("--rebalance-every", type=int, default=8)
    ap.add_argument("--rebalance-watermark", type=int, default=4)
    ap.add_argument("--groups", type=int, default=32)
    ap.add_argument("--zipf-a", type=float, default=1.2)
    ap.add_argument("--multiturn-p", type=float, default=0.3)
    ap.add_argument("--long-docs", type=int, default=0,
                    help="long documents carved out of N_REQUESTS and "
                         "spread evenly over the schedule (the "
                         "mixed-traffic starvation probe)")
    ap.add_argument("--long-doc-len", type=int, default=512,
                    help="--long-docs document length in tokens")
    ap.add_argument("--history", type=int, default=65_536,
                    help="events kept in memory for --trace rendering")
    ap.add_argument("--autoscale", action="store_true",
                    help="attach the goodput-driven Autoscaler")
    ap.add_argument("--spares", type=int, default=0,
                    help="extra replicas provisioned PARKED (revived "
                         "only by the autoscaler)")
    ap.add_argument("--chaos", action="store_true",
                    help="seed transport faults (drop/corrupt/stall/"
                         "death) into the migration wire")
    ap.add_argument("--chaos-faults", type=int, default=12)
    ap.add_argument("--curve-every", type=int, default=512,
                    help="ticks between attainment/goodput/replica-"
                         "count curve samples")
    ap.add_argument("--ab", action="store_true",
                    help="run the autoscaling-DISABLED arm too (same "
                         "config hash) and report the attainment delta")
    ap.add_argument("--eval-every", type=int, default=64,
                    help="autoscaler control period (fleet ticks)")
    ap.add_argument("--cooldown", type=int, default=192)
    ap.add_argument("--queue-high", type=float, default=4.0)
    ap.add_argument("--ledger", default=None,
                    help="write router decision records as JSONL")
    ap.add_argument("--report", default=None,
                    help="write the FLEETREPORT as <path> JSON + a "
                         "sibling .md (the RUNREPORT convention)")
    ap.add_argument("--trace", default=None,
                    help="write a fleet Perfetto trace of the retained "
                         "event window")
    args = ap.parse_args(argv)

    for path in (args.ledger, args.trace):
        if path is not None and os.path.dirname(path):
            os.makedirs(os.path.dirname(path), exist_ok=True)

    common = dict(
        n_requests=args.n_requests, n_replicas=args.replicas,
        num_slots=args.num_slots, block_size=args.block_size,
        chunk=args.chunk, seed=args.seed, disaggregate=not args.flat,
        rate_util=args.rate_util, diurnal_amp=args.diurnal_amp,
        diurnal_period=args.diurnal_period,
        rebalance_every=args.rebalance_every,
        rebalance_watermark=args.rebalance_watermark,
        history_max=args.history, groups=args.groups,
        zipf_a=args.zipf_a, multiturn_p=args.multiturn_p,
        long_docs=args.long_docs, long_doc_len=args.long_doc_len,
        n_spares=args.spares, chaos=args.chaos,
        chaos_faults=args.chaos_faults, curve_every=args.curve_every,
        autoscale_kw={"eval_every": args.eval_every,
                      "cooldown": args.cooldown,
                      "queue_high": args.queue_high})

    baseline = None
    if args.ab:
        baseline = run_replay(autoscale=False, **common)
        baseline.pop("events")

    out = run_replay(autoscale=args.autoscale or args.ab,
                     ledger_path=args.ledger, **common)
    log = out.pop("events")

    if args.trace is not None:
        from ..serving.tracing import fleet_trace_events

        with open(args.trace, "w") as f:
            json.dump({"traceEvents": fleet_trace_events(log.as_list())},
                      f)

    fleet = out["summary"]["fleet"]
    report = {
        "run": f"trace-replay-seed{args.seed}",
        "steps": out["ticks"],
        "backend": "sim",
        "chip": "none",
        "n_devices": 0,
        "n_processes": 1,
        "wall_time_s": out["wall_s"],
        "router": out["summary"],
        "counters": {"workload": out["workload"],
                     "attribution": out["attribution"],
                     "mixed_traffic": out["mixed_traffic"],
                     "sim": out["sim"],
                     "curves": out["curves"],
                     "autoscale": out["autoscale"],
                     "chaos": out["chaos"],
                     "replay": {"schema": out["schema"],
                                "n_requests": out["n_requests"],
                                "submitted": out["submitted"],
                                "config_hash": out["config_hash"],
                                "validation_errors":
                                    out["validation_errors"]}},
    }
    if args.report is not None:
        write_runreport(report, args.report)

    asc = out["autoscale"] or {}
    master_print(json.dumps({
        "metric": "trace-replay",
        "value": round(fleet["goodput_tok_s"], 1),
        "n_requests": out["n_requests"],
        "ticks": out["ticks"],
        "wall_s": out["wall_s"],
        "sim_device_s": out["sim"]["sim_device_s"],
        "fleet_goodput_tok_s": round(fleet["goodput_tok_s"], 1),
        "fleet_slo_attainment": fleet["attainment"],
        "migration_count": fleet["migrations"]["handoffs"],
        "migration_bytes": fleet["migrations"]["bytes"],
        "fleet_verdict": fleet["verdict"],
        "balance_verdict": fleet["balance"]["verdict"],
        "autoscale_actions": asc.get("actions", 0),
        "migration_retry_count": fleet["migrations"].get("retries", 0),
        "transport_fallback_count": fleet["migrations"].get(
            "fallbacks", 0),
        "config_hash": out["config_hash"],
        "report_valid": not out["validation_errors"],
        "attribution_complete": out["attribution"]["complete"],
        **({"short_p99_wait_ticks":
                out["mixed_traffic"]["short"]["p99_wait_ticks"],
            "long_p50_wait_ticks":
                out["mixed_traffic"]["long"]["p50_wait_ticks"]}
           if out["mixed_traffic"] else {}),
    }), flush=True)
    if baseline is not None:
        att_on = fleet["attainment"]
        att_off = baseline["summary"]["fleet"]["attainment"]
        master_print(json.dumps({
            "metric": "trace-replay-ab",
            "config_hash": out["config_hash"],
            "config_hash_match": (out["config_hash"]
                                  == baseline["config_hash"]),
            "attainment_autoscaled": att_on,
            "attainment_static": att_off,
            "attainment_delta": round(att_on - att_off, 4),
            "baseline_valid": not baseline["validation_errors"],
            "win": att_on > att_off,
        }), flush=True)
    master_print(render_summary_line(report), flush=True)
    if out["validation_errors"]:
        master_print(json.dumps(
            {"validation_errors": out["validation_errors"]}), flush=True)
        return 1
    return 0 if out["attribution"]["complete"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
