"""Device-mesh topology registry — the TPU-native analogue of the reference's
process-group topology (``torchdistpackage/dist/process_topo.py:53-262``).

The reference builds NCCL process groups from an *ordered* config such as
``[('data', 4), ('pipe', 2), ('tensor', 2)]`` where the **last** listed dim has
stride 1 — i.e. consecutive ranks, i.e. intra-node placement (its
``gen_groups`` stride algorithm, process_topo.py:32-51).  On TPU the natural
substrate is a named :class:`jax.sharding.Mesh`: we reshape the device list in
C order over the configured sizes, so the last-listed axis likewise gets
ICI-adjacent devices.  Every group-getter / predicate of the reference maps to
a mesh-axis query; collectives use axis *names* inside ``shard_map`` instead of
group handles.

**Physical placement** (the reference's core value prop — its stride algorithm
deliberately decides which group lands intra-node, process_topo.py:32-51,
motivated at Intro.md:15-44): on real TPU devices the enumeration order of
``jax.devices()`` does NOT guarantee that a C-order reshape puts an axis's
members on ICI neighbors (2D/3D torus wraparound, multi-slice DCN).  So
:meth:`ParallelContext.setup_process_groups` routes TPU device lists through
``jax.experimental.mesh_utils``:

- single slice: ``create_device_mesh(sizes, devices)`` assigns logical axes to
  physical ICI torus axes from device *coords* — the last-listed (stride-1)
  axis gets the most network-local placement, honoring the ordered-config
  contract on real hardware, not just in enumeration order;
- multi-slice (devices carrying distinct ``slice_index``, i.e. a DCN-connected
  multislice job): ``create_hybrid_device_mesh`` — the DCN dimension is
  absorbed by the OUTERMOST config axes (largest stride = cross-slice, exactly
  the reference's outer-axes-cross-node semantics), overridable per axis via
  ``dcn_config``.

Non-TPU devices (CPU sim, tests) keep the plain C-order reshape, so the
8-device CI sim and the driver dryrun behave exactly as before.


Key translations (reference -> here):

- ``tpc.setup_process_groups(cfg)``   -> :meth:`ParallelContext.setup_process_groups`
- ``dist.new_group(ranks)``           -> (not needed — axes name sub-meshes implicitly)
- ``tpc.get_group('tensor')``         -> axis name ``'tensor'`` (pass to psum etc.)
- ``tpc.get_tp_rank()``               -> :meth:`axis_index` (traced) or
                                         :meth:`process_axis_index` (host-side)
- auto "model" group (process_topo.py:112-116) -> :meth:`model_axes` (tuple of
  all non-data axis names; psum over a tuple == all-reduce over the flattened
  group, so no explicit transpose construction is required)
- ``tpc.build_moe_groups`` (process_topo.py:118-143) -> :meth:`build_moe_mesh`
  — a *view* mesh over the same devices with the data axis factored into
  ``('moe_dp', 'moe_ep')``, ep innermost (matching the reference's contiguous
  ep ranks within each dp group)
- ``setup_node_groups`` (node_group.py:3-32) -> :meth:`build_hybrid_mesh`
  — data axis factored into ``('data_inter', 'data_intra')`` for hybrid
  (intra-node) ZeRO sharding
- ``test_comm()`` (process_topo.py:267-316) -> :func:`test_comm` smoke test.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Tuple, Union

import jax

from ..compat import axis_size
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

AxisName = Union[str, Tuple[str, ...]]

# Canonical axis names (the reference's group "modes").
DATA_AXIS = "data"
TENSOR_AXIS = "tensor"
PIPE_AXIS = "pipe"
EXPERT_AXIS = "moe_ep"
MOE_DATA_AXIS = "moe_dp"
CONTEXT_AXIS = "context"


def _slice_ids(devices: Sequence) -> List[int]:
    """Distinct ``slice_index`` values (sorted).  Devices without the
    attribute (or with ``None``) count as one slice — single-slice TPU jobs
    and CPU sims don't set it."""
    ids = {getattr(d, "slice_index", None) for d in devices}
    if ids == {None}:
        return [0]
    if None in ids:
        raise ValueError(
            "mixed device list: some devices carry slice_index, some don't"
        )
    return sorted(ids)


def _derive_dcn_shape(
    names: Sequence[str],
    sizes: Sequence[int],
    num_slices: int,
    dcn_config: Optional[Dict[str, int]],
) -> List[int]:
    """Per-axis DCN factors (product == num_slices).

    Explicit ``dcn_config`` wins; otherwise the slice count is absorbed
    greedily from the LEFT (outermost axes — largest stride — go cross-slice,
    the reference's outer-axes-cross-node layout, process_topo.py:32-51)."""
    if dcn_config is not None:
        unknown = set(dcn_config) - set(names)
        if unknown:
            raise ValueError(f"dcn_config axes {unknown} not in config {list(names)}")
        shape = [int(dcn_config.get(nm, 1)) for nm in names]
        if math.prod(shape) != num_slices:
            raise ValueError(
                f"dcn_config {dcn_config} multiplies to {math.prod(shape)}, "
                f"but the device list spans {num_slices} slices"
            )
        for nm, s, d in zip(names, sizes, shape):
            if s % d != 0:
                raise ValueError(
                    f"axis {nm!r} of size {s} not divisible by its DCN factor {d}"
                )
        return shape
    shape = []
    remaining = num_slices
    for s in sizes:
        d = math.gcd(remaining, s)
        shape.append(d)
        remaining //= d
    if remaining != 1:
        raise ValueError(
            f"cannot distribute {num_slices} slices over axis sizes "
            f"{list(sizes)}; pass dcn_config explicitly"
        )
    if len(shape) > 1 and shape[-1] != 1:
        # the greedy fallback would put DCN on the stride-1 axis — the one
        # the ordered-config contract promises is the most network-LOCAL
        # (e.g. [('data', 2), ('tensor', 8)] on 4 slices: TP collectives
        # would silently cross DCN every layer).  Never silently: the
        # operator must say so explicitly.
        raise ValueError(
            f"distributing {num_slices} slices over {list(zip(names, sizes))} "
            f"would put a DCN factor on the innermost axis "
            f"{names[-1]!r} (derived {shape}); if that is intended, pass "
            f"dcn_config explicitly"
        )
    return shape


def _assign_devices(
    names: Sequence[str],
    sizes: Sequence[int],
    devices: Sequence,
    topology: str,
    dcn_config: Optional[Dict[str, int]],
) -> np.ndarray:
    """Device ndarray of shape ``sizes`` with physical-topology-aware
    placement on TPU (see module docstring), C-order reshape otherwise."""
    if topology not in ("auto", "ici", "flat"):
        raise ValueError(f"topology must be 'auto'|'ici'|'flat', got {topology!r}")
    is_tpu = (
        getattr(devices[-1], "platform", None) == "tpu"
        and hasattr(devices[-1], "coords")
    )
    if topology == "flat" or (topology == "auto" and not is_tpu):
        if dcn_config:
            raise ValueError("dcn_config requires the topology-aware path")
        return np.array(devices, dtype=object).reshape(sizes)
    if not is_tpu:
        raise ValueError(
            "topology='ici' needs TPU devices with coords; got "
            f"{getattr(devices[-1], 'platform', None)!r}"
        )
    from jax.experimental import mesh_utils

    slices = _slice_ids(devices)
    if len(slices) > 1:
        dcn_shape = _derive_dcn_shape(names, sizes, len(slices), dcn_config)
        per_slice = [s // d for s, d in zip(sizes, dcn_shape)]
        return mesh_utils.create_hybrid_device_mesh(
            per_slice, dcn_shape, devices, allow_split_physical_axes=True
        )
    if dcn_config and math.prod(dcn_config.values()) != 1:
        raise ValueError(
            f"dcn_config {dcn_config} given but the device list is a single slice"
        )
    return mesh_utils.create_device_mesh(
        sizes, devices, allow_split_physical_axes=True
    )


class ParallelContext:
    """Singleton-ish registry of the device mesh and its named-axis views.

    Unlike the reference (``SingletonMeta``, process_topo.py:6-26) we allow
    explicit construction for tests, but ship a module-level ``tpc`` instance
    as the canonical entry point, mirroring ``torch_parallel_context``
    (process_topo.py:262).
    """

    def __init__(self) -> None:
        self._reset()

    # ------------------------------------------------------------------ setup

    def _reset(self) -> None:
        self.mesh: Optional[Mesh] = None
        self._config: List[Tuple[str, int]] = []
        self._views: Dict[str, Mesh] = {}
        self._devices: Optional[np.ndarray] = None  # flat, C-order of config

    def reset(self) -> None:
        """Drop all state (tests / re-setup)."""
        self._reset()

    @property
    def is_initialized(self) -> bool:
        return self.mesh is not None

    def setup_process_groups(
        self,
        config: Sequence[Tuple[str, int]],
        devices: Optional[Sequence[jax.Device]] = None,
        topology: str = "auto",
        dcn_config: Optional[Dict[str, int]] = None,
    ) -> Mesh:
        """Build the base mesh from an ordered ``[(axis, size), ...]`` config.

        Semantics match ``ProcessTopology.setup_process_groups``
        (process_topo.py:70-116): the last-listed axis has stride 1, i.e. its
        members are consecutive devices (ICI-adjacent on TPU, intra-node on
        GPU clusters).  Example::

            tpc.setup_process_groups([('data', 2), ('pipe', 2), ('tensor', 2)])

        gives tensor groups over adjacent device pairs, pipe groups with
        stride 2 and data groups with stride 4 — identical rank layouts to the
        reference's docstring example (process_topo.py:72-90).

        Axis sizes may use ``-1`` for at most one axis, which absorbs the
        remaining device count (convenience over the reference).

        ``topology`` selects the physical placement strategy:

        - ``'auto'`` (default): TPU devices with coords go through
          ``mesh_utils`` (torus-aware, multi-slice-aware); anything else
          (CPU sim) is a plain C-order reshape.
        - ``'ici'``: require the torus-aware path (raise on non-TPU devices).
        - ``'flat'``: force the C-order reshape even on TPU (the pre-round-5
          behavior; also the escape hatch for exotic device lists).

        ``dcn_config`` (multi-slice only) maps axis name -> how many slices
        that axis spans, e.g. ``{'data': 4}`` for pure dp-over-DCN.  The
        product must equal the number of slices; unlisted axes span 1.  By
        default the OUTERMOST config axes absorb the slice count greedily —
        the reference's outer-axes-are-cross-node semantics
        (process_topo.py:32-51)."""
        if devices is None:
            devices = jax.devices()
        devices = list(devices)
        n = len(devices)

        names = [str(d) for d, _ in config]
        sizes = [int(s) for _, s in config]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate axis names in config: {names}")
        if sizes.count(-1) > 1:
            raise ValueError("at most one axis size may be -1")
        if -1 in sizes:
            known = math.prod(s for s in sizes if s != -1)
            if n % known != 0:
                raise ValueError(f"cannot infer -1 axis: {n} devices, known product {known}")
            sizes[sizes.index(-1)] = n // known
        if math.prod(sizes) != n:
            raise ValueError(f"config sizes {sizes} do not multiply to device count {n}")

        arr = _assign_devices(names, sizes, devices, topology, dcn_config)
        self._config = list(zip(names, sizes))
        # flat logical order (C-order of the assigned mesh): every view mesh
        # factors THIS order, so moe/hybrid views inherit the physical
        # placement
        self._devices = arr.reshape(-1)
        self.mesh = Mesh(arr, axis_names=tuple(names))
        self._views = {"default": self.mesh}
        return self.mesh

    # Convenience alias matching JAX vocabulary.
    setup_mesh = setup_process_groups

    def _require_mesh(self) -> Mesh:
        if self.mesh is None:
            raise RuntimeError("ParallelContext not initialized — call setup_process_groups first")
        return self.mesh

    # ------------------------------------------------------------- view meshes

    def build_view(
        self,
        view_name: str,
        split_axis: str,
        sub_names: Tuple[str, str],
        inner_size: int,
    ) -> Mesh:
        """Generic axis factoring: a new Mesh over the *same* devices with
        ``split_axis`` factored into ``(outer, inner)`` where the inner axis
        has consecutive devices.  psum over ``sub_names`` is identical to psum
        over the original axis, so components using different views compose.
        """
        mesh = self._require_mesh()
        if split_axis not in mesh.axis_names:
            raise ValueError(f"axis {split_axis!r} not in mesh axes {mesh.axis_names}")
        size = mesh.shape[split_axis]
        if size % inner_size != 0:
            raise ValueError(f"axis {split_axis!r} of size {size} not divisible by {inner_size}")
        outer = size // inner_size
        new_names: List[str] = []
        new_sizes: List[int] = []
        for name in mesh.axis_names:
            if name == split_axis:
                new_names.extend(sub_names)
                new_sizes.extend([outer, inner_size])
            else:
                new_names.append(name)
                new_sizes.append(mesh.shape[name])
        view = Mesh(self._devices.reshape(new_sizes), axis_names=tuple(new_names))
        self._views[view_name] = view
        return view

    def build_moe_mesh(
        self,
        moe_dp_size: Optional[int] = None,
        moe_ep_size: Optional[int] = None,
    ) -> Mesh:
        """MoE view: data axis -> ('moe_dp', 'moe_ep'), ep innermost.

        Mirrors ``build_moe_groups`` (process_topo.py:118-143): expert-parallel
        ranks are contiguous within each data group (so EP all-to-all rides
        ICI), same-expert replicas form the strided moe_dp groups.
        """
        dp = self.get_dp_size()
        if moe_dp_size and not moe_ep_size:
            if dp % moe_dp_size != 0:
                raise ValueError(f"moe_dp_size {moe_dp_size} does not divide dp size {dp}")
            moe_ep_size = dp // moe_dp_size
        elif moe_ep_size and not moe_dp_size:
            if dp % moe_ep_size != 0:
                raise ValueError(f"moe_ep_size {moe_ep_size} does not divide dp size {dp}")
            moe_dp_size = dp // moe_ep_size
        elif moe_dp_size and moe_ep_size:
            if moe_dp_size * moe_ep_size != dp:
                raise ValueError(f"moe_dp {moe_dp_size} * moe_ep {moe_ep_size} != dp {dp}")
        else:
            raise ValueError("need moe_dp_size or moe_ep_size")
        return self.build_view("moe", DATA_AXIS, (MOE_DATA_AXIS, EXPERT_AXIS), moe_ep_size)

    def build_hybrid_mesh(self, intra_size: int) -> Mesh:
        """Hybrid-ZeRO view: data -> ('data_inter', 'data_intra'), intra
        innermost (ICI-local).  Analogue of ``setup_node_groups``
        (node_group.py:3-32) which builds one group per physical node so ZeRO
        shards only intra-node (Intro.md:69-77)."""
        return self.build_view("hybrid", DATA_AXIS, ("data_inter", "data_intra"), intra_size)

    def get_view(self, name: str = "default") -> Mesh:
        self._require_mesh()
        if name not in self._views:
            raise KeyError(f"mesh view {name!r} not built; have {list(self._views)}")
        return self._views[name]

    # --------------------------------------------------------------- axis info

    @property
    def axis_names(self) -> Tuple[str, ...]:
        return self._require_mesh().axis_names

    def is_mode_inited(self, mode: str) -> bool:
        """Reference semantics (process_topo.py:236-237): axis exists AND has
        size > 1 (in any built view)."""
        if self.mesh is None:
            return False
        for mesh in self._views.values():
            if mode in mesh.axis_names and mesh.shape[mode] > 1:
                return True
        if mode == "model":
            return self.get_mp_size() > 1
        return False

    def _axis_mesh(self, mode: str) -> Mesh:
        for mesh in self._views.values():
            if mode in mesh.axis_names:
                return mesh
        raise KeyError(f"axis {mode!r} not found in any mesh view")

    def get_group_size(self, mode: str) -> int:
        if mode == "global":
            return self._require_mesh().size
        if mode == "model":
            return self.get_mp_size()
        mesh = self._axis_mesh(mode)
        return mesh.shape[mode]

    def get_tp_size(self) -> int:
        return self.get_group_size(TENSOR_AXIS) if self._has_axis(TENSOR_AXIS) else 1

    def get_pp_size(self) -> int:
        return self.get_group_size(PIPE_AXIS) if self._has_axis(PIPE_AXIS) else 1

    def get_dp_size(self) -> int:
        return self.get_group_size(DATA_AXIS) if self._has_axis(DATA_AXIS) else 1

    def get_mp_size(self) -> int:
        """Model-parallel size = product of all non-data base axes — the
        transpose of the data groups, auto-derived like process_topo.py:112-116."""
        mesh = self._require_mesh()
        return math.prod(mesh.shape[a] for a in mesh.axis_names if a != DATA_AXIS)

    def _has_axis(self, mode: str) -> bool:
        try:
            self._axis_mesh(mode)
            return True
        except KeyError:
            return False

    def num_slices(self) -> int:
        """Number of DCN-connected slices the mesh spans (1 on single-slice
        jobs and CPU sims)."""
        return len(_slice_ids(list(self._require_mesh().devices.flat)))

    def model_axes(self) -> Tuple[str, ...]:
        """Axis names forming the auto-derived 'model' group.  Collectives
        accept tuples of axis names, so ``psum(x, tpc.model_axes())`` is the
        all-reduce over the reference's 'model' group."""
        mesh = self._require_mesh()
        return tuple(a for a in mesh.axis_names if a != DATA_AXIS)

    def data_axes(self, view: str = "default") -> Tuple[str, ...]:
        """Axis names whose flattened product is the data-parallel group in the
        given view ('default' -> ('data',); 'moe' -> ('moe_dp', 'moe_ep'))."""
        mesh = self.get_view(view)
        base = {DATA_AXIS, MOE_DATA_AXIS, EXPERT_AXIS, "data_inter", "data_intra"}
        return tuple(a for a in mesh.axis_names if a in base)

    # ---------------------------------------------------- traced (SPMD) queries

    @staticmethod
    def axis_index(mode: AxisName):
        """Rank within an axis — traced; valid inside shard_map/pjit-manual.
        Analogue of ``get_group_rank`` (process_topo.py:155-156)."""
        return jax.lax.axis_index(mode)

    def get_tp_rank(self):
        return self.axis_index(TENSOR_AXIS)

    def get_pp_rank(self):
        return self.axis_index(PIPE_AXIS)

    def get_dp_rank(self):
        return self.axis_index(DATA_AXIS)

    def is_first_in_group(self, mode: AxisName):
        return jax.lax.axis_index(mode) == 0

    def is_last_in_group(self, mode: AxisName):
        return jax.lax.axis_index(mode) == axis_size(mode) - 1

    def is_first_in_pipeline_group(self):
        return self.is_first_in_group(PIPE_AXIS)

    def is_last_in_pipeline_group(self):
        return self.is_last_in_group(PIPE_AXIS)

    def is_using_pp(self) -> bool:
        """Host-side — analogue of ``is_using_pp`` (process_topo.py:264-265)."""
        return self.is_mode_inited(PIPE_AXIS)

    # -------------------------------------------------------- host-side coords

    def device_coords(self, device: Optional[jax.Device] = None) -> Dict[str, int]:
        """Mesh coordinates of a device (host-side introspection; replaces the
        reference's global-rank bookkeeping)."""
        mesh = self._require_mesh()
        if device is None:
            device = mesh.devices.flat[0]
        arr = mesh.devices
        pos = np.argwhere(arr == device)
        if len(pos) == 0:
            raise ValueError(f"device {device} not in mesh")
        return dict(zip(mesh.axis_names, (int(i) for i in pos[0])))

    def process_axis_index(self, mode: str) -> int:
        """Axis index of *this process's* first local device — host-side rank
        analogue for multi-host code (checkpoint naming etc.)."""
        mesh = self._axis_mesh(mode)
        local = [d for d in mesh.devices.flat if d.process_index == jax.process_index()]
        if not local:
            raise RuntimeError(
                f"process {jax.process_index()} has no local device in the mesh; "
                "process_axis_index is only meaningful on participating hosts"
            )
        arr = mesh.devices
        pos = np.argwhere(arr == local[0])[0]
        return int(pos[list(mesh.axis_names).index(mode)])

    def ranks_in_axis(self, mode: str) -> List[List[int]]:
        """All groups of flat device indices for an axis — analogue of
        ``all_ranks`` (process_topo.py:242-246); mainly for tests/debug."""
        mesh = self._axis_mesh(mode)
        flat_index = {d: i for i, d in enumerate(self._devices)}
        ax = list(mesh.axis_names).index(mode)
        moved = np.moveaxis(mesh.devices, ax, -1).reshape(-1, mesh.shape[mode])
        return [[flat_index[d] for d in row] for row in moved]

    # ------------------------------------------------------------ spec helpers

    def spec(self, *names: Optional[AxisName]) -> PartitionSpec:
        return PartitionSpec(*names)

    def sharding(self, *names: Optional[AxisName], view: str = "default") -> NamedSharding:
        return NamedSharding(self.get_view(view), PartitionSpec(*names))


# The canonical context — analogue of ``torch_parallel_context``
# (process_topo.py:262).
tpc = ParallelContext()


def is_using_pp() -> bool:
    return tpc.is_using_pp()


def test_comm(mesh: Optional[Mesh] = None) -> Dict[str, bool]:
    """Smoke-test collectives over every mesh axis — analogue of
    ``test_comm`` (process_topo.py:267-316).

    Runs a psum (all-reduce), all_gather and ring ppermute over each axis of
    the mesh inside one jitted shard_map and checks the numerics, returning
    ``{axis: ok}``.  Unlike the reference this is deterministic and asserts
    values, not just liveness.

    The value checks run INSIDE the computation and come back as one
    replicated ok-count per axis, so the function works unchanged on
    multi-process meshes (a per-shard fetch of the collective outputs would
    touch non-addressable shards; a replicated scalar is always local —
    executed cross-process in ``tests/test_multiprocess.py``).
    """
    from ..compat import shard_map
    import jax.numpy as jnp

    if mesh is None:
        mesh = tpc._require_mesh()
    results: Dict[str, bool] = {}
    for axis in mesh.axis_names:
        n = mesh.shape[axis]

        def body(x):
            total = jax.lax.psum(x, axis)                     # all_reduce
            gathered = jax.lax.all_gather(x, axis, tiled=True)  # all_gather
            nxt = jax.lax.ppermute(                           # ring send/recv
                x, axis, [(i, (i + 1) % n) for i in range(n)]
            )
            i = jax.lax.axis_index(axis)
            prev = ((i - 1) % n).astype(x.dtype)
            ok = (
                jnp.all(total == float(sum(range(n))))
                & jnp.all(gathered[:, 0] == jnp.arange(n, dtype=x.dtype))
                & jnp.all(nxt == prev)
            )
            # every shard must pass -> count == n, replicated over the axis
            return jax.lax.psum(ok.astype(jnp.int32), axis)

        spec = PartitionSpec(axis)
        x = jnp.arange(n, dtype=jnp.float32).reshape(n, 1)
        fn = jax.jit(
            shard_map(body, mesh=mesh, in_specs=(spec,), out_specs=PartitionSpec())
        )
        ok = int(fn(x)) == n
        results[axis] = ok
        if not ok:
            raise AssertionError(f"test_comm failed for axis {axis!r}")
    return results
