"""The BASELINE.json north-star config at REAL scale, abstractly.

``dryrun_multichip`` executes the flagship composition at tiny shapes; this
suite traces it at the actual 7B / 64-chip target (``jax.eval_shape`` —
zero FLOPs, zero array bytes), proving every sharding spec divides, the
interleaved slab layout holds, and the ZeRO partition algebra works at
d4096/L32/TP8/PP2/DP4.  Runs in a subprocess so the 64-device CPU sim
doesn't disturb this process's 8-device backend.
"""

import json
import os
import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent

_CHILD = """
import os
os.environ["XLA_FLAGS"] = " --xla_force_host_platform_device_count=64"
import jax
jax.config.update("jax_platforms", "cpu")
import json, sys
sys.path.insert(0, {repo!r})
import __graft_entry__ as g
print("SUMMARY=" + json.dumps(g.trace_north_star_7b()))
"""


def test_north_star_7b_traces_on_64_device_mesh():
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    res = subprocess.run(
        [sys.executable, "-c", _CHILD.format(repo=str(REPO))],
        env=env,
        capture_output=True,
        text=True,
        timeout=600,
        cwd=str(REPO),
    )
    assert res.returncode == 0, (
        f"trace failed (rc={res.returncode})\n--- stdout ---\n"
        f"{res.stdout[-2000:]}\n--- stderr ---\n{res.stderr[-2000:]}"
    )
    line = [l for l in res.stdout.splitlines() if l.startswith("SUMMARY=")][-1]
    summary = json.loads(line[len("SUMMARY="):])
    # ~7B-class (the reference's north-star model size), scalar loss
    assert 6.0 < summary["params_b"] < 8.0, summary
    assert summary["loss_shape"] == [], summary
    assert "tensor=8" in summary["mesh"] and "pipe=2" in summary["mesh"]
