"""Append-only structured event log — the run's timeline.

Subsumes the print-based side channels (``utils/preemption.py`` signal
prints, ``tools/debug_nan.py`` NaN reports): instead of a line on stderr
that evaporates, a structured record lands in memory (always) and in a
JSONL file (when a path/sink is attached), with both wall-clock and
monotonic timestamps plus the emitting process index — enough to interleave
events from several hosts after the fact.

Every kind the package emits is declared in :data:`EVENT_KINDS` below —
the central registry ``tests/test_repo_lint.py`` checks call sites
against, so a typo'd kind fails CI instead of silently vanishing from the
timeline.  (User code may emit free-form kinds; the registry governs the
package only.)

==================  =====================================================
``run_start/end``   session boundaries (Telemetry emits these)
``compile``         first compilation of a wrapped step
``recompile``       a wrapped step saw a NEW input signature — the silent
                    throughput killer Telemetry exists to catch
``checkpoint_save`` / ``checkpoint_restore``
``preemption``      a termination signal arrived (GracefulShutdown); the
                    record carries the grace deadline when configured
``nan_watchdog``    a ``nan_guard``-ed function produced non-finite output
``loss_scale``      dynamic loss-scale change
``straggler``       a host's step time is an outlier (obs.aggregate)
``decode_cell``     one decode-bench latency cell (tools.decode_bench)
``overlap_configure``  XLA latency-hiding flag outcome (dist.overlap)
``xla_trace_start/stop``  scoped jax.profiler capture window (obs.trace)
==================  =====================================================

Resilience kinds (``torchdistpackage_tpu.resilience``, PR 4):

==================  =====================================================
``fault_injected``  the chaos harness fired a declared fault
``ckpt_retry``      a checkpoint I/O attempt failed and is being retried
``ckpt_quarantine`` a corrupt checkpoint step was renamed aside; resume
                    walked back to the newest good step
``rollback``        the self-healing loop rewound to a good checkpoint
                    after divergence (non-finite / loss-spike)
``resilience_abort``  retry budget spent — the run aborted cleanly with
                    a RUNREPORT ``resilience`` verdict
``hang_suspected`` / ``hang_resolved`` / ``hang_abort``  watchdog
                    heartbeat-gap escalation
``desync_detected`` cross-host consistency check found disagreement
                    (step / config hash / code hash / RNG / param sum)
==================  =====================================================

Memory kinds (``obs.mem_ledger`` + Telemetry, PR 6):

==================  =====================================================
``mem_snapshot``    periodic live/peak HBM sample from the one
                    ``memory_stats`` reader (``mem_ledger.live_memory``)
``oom_risk``        a live sample or the end-of-run memory verdict
                    crossed the OOM-risk line (peak >= 95% of capacity)
==================  =====================================================

Numerics kinds (``obs.numerics`` + Telemetry, PR 7):

==================  =====================================================
``numerics_alert``  a step's training-dynamics stats crossed a health
                    threshold (grad explosion/vanishing, update ratio out
                    of band, non-finite loss/grads); emitted on entering
                    the bad state by ``Telemetry.end_step`` and by
                    ``ResilientLoop`` BEFORE it decides to roll back —
                    the alert precedes the ``rollback`` on the timeline
``nan_block_located``  ``tools.debug_nan.find_nan_block`` walked the
                    model and found the first block producing non-finite
                    values (record carries the block name + bad paths)
==================  =====================================================

Compression kinds (``dist/compressed.py`` + the parallel families, PR 8):

==================  =====================================================
``compress_policy`` ``grad_compress='auto'`` scored each grad leaf's
                    collective through ``CommModel.predict_compressed``
                    while building a train step; the record carries the
                    per-leaf compress/exact choices with both predictions
                    (the ``compression`` RUNREPORT section reads it —
                    ``obs.comm_model.compression_report``)
==================  =====================================================

Serving kinds (``torchdistpackage_tpu.serving``, PR 5):

==================  =====================================================
``request_admitted``  a queued request took a free slot (blocks
                    allocated; record carries the queue wait)
``prefill_chunk``   one chunked-prefill slice ran for the prefilling
                    slots (the admission path that never stalls decodes)
``request_retired`` EOS / max-token completion — slot and blocks freed;
                    the record carries the request's TTFT
``slots_snapshot``  periodic occupancy + KV-pool utilization sample
==================  =====================================================

Serving-under-stress kinds (``serving/engine.py``, PR 9 — the overload /
fault half of the lifecycle; docs/serving.md "Serving under stress"):

==========================  =============================================
``request_preempted``       a higher-priority request evicted this slot:
                            blocks freed, accumulated output discarded,
                            request requeued for prompt replay
``request_shed``            admission refused at the door — bounded queue
                            full, estimated TTFT past the deadline, or
                            the engine is draining (record = the
                            structured rejection verdict)
``request_expired``         a queued request's deadline passed before a
                            slot freed; removed without service
``request_cancelled``       ``cancel(rid)`` retired the request (queued
                            or in-flight; blocks freed same tick)
``engine_fault_detected``   the per-tick invariant audit (block
                            conservation, table/ownership agreement) or
                            the sampled-token validity check found a
                            poisoned slot / leaked block
``engine_recovered``        the fault was healed: poisoned slots retired
                            + requeued, orphaned blocks reclaimed, the
                            rest of the batch untouched
``engine_drained``          ``drain()`` unwound the queue + in-flight
                            slots into restartable descriptors
                            (preemption-safe shutdown)
==========================  =============================================

Serving fast-path kinds (``serving/engine.py``, PR 10 — prefix cache +
speculative decoding; docs/serving.md "Prefix cache" / "Speculative
decoding"):

==========================  =============================================
``prefix_hit``              admission mapped a resident shared prefix
                            into the new slot's table (record carries
                            the cached token count and whether the last
                            block was copy-on-written)
``block_cow``               a whole-prompt cache hit scheduled a
                            copy-on-write of its final block (src/dst
                            block ids; the copy is one fixed-signature
                            compiled program per admission wave)
``spec_draft``              the host drafter proposed ``spec_k`` tokens
                            for every decoding slot this tick
``spec_verify``             the compiled verify step judged the drafts:
                            record carries tokens emitted vs drafts
                            accepted (the accept-rate evidence)
``cache_evict``             allocator pressure evicted refcount-0 cached
                            blocks (LRU) to cover a fresh allocation
==========================  =============================================

Serving observability kinds (``serving/engine.py`` + ``serving/tracing.py``,
PR 11 — request-lifecycle tracing + tick accounting; docs/serving.md
"Serving observability"):

==========================  =============================================
``request_submitted``       a request entered ``submit()`` (rid assigned)
                            — the anchor of the lifecycle trace's
                            ``queued`` span, emitted before any
                            shed/admission decision
``request_resumed``         ``resume()`` re-submitted a drain descriptor;
                            the record carries ``orig_rid``, the flow
                            link a Perfetto request track follows across
                            an engine restart
``engine_tick``             one engine tick's host-side accounting:
                            per-phase durations (audit / sched / prefill
                            / draft / decode / fetch / host), queue
                            depth, slot occupancy, batch + pool
                            utilization, live hit/accept rates, and the
                            per-rid prefill/decode attribution the
                            request trace is assembled from (emitted
                            only for ticks that did work)
==========================  =============================================

Multi-replica router kinds (``serving/router.py``, PR 15 — prefix-affinity
routing, prefill/decode disaggregation, cross-replica KV migration;
docs/serving.md "Multi-replica routing and disaggregation"):

==========================  =============================================
``request_routed``          the router placed a submit on a replica:
                            record carries the replica index, its
                            resident-prefix affinity (tokens), the
                            replica's biased TTFT estimate, and the
                            fallback rank (0 = first choice; >0 = a
                            better-ranked replica shed it first)
``request_migrated``        a request moved between replicas — queued
                            (``rebalance`` / ``evacuation``: KV-free
                            drain-descriptor resume, exact-parity
                            replay) or in-flight (``prefill_handoff``:
                            the disaggregation path, KV travels by
                            ``blocks_migrated``)
``replica_degraded``        the router observed a replica degrading
                            (fault counter moved, or new shed/expired
                            demand = the overloaded verdict) and what it
                            did about it (observed / rebalance /
                            evacuate)
``blocks_migrated``         one cross-pool KV migration ran: src/dst
                            replica, blocks copied vs prefix-shared on
                            arrival, wire bytes, and the comm-model
                            pricing verdict (int8 wire iff the model
                            approved the DCN-crossing leg)
==========================  =============================================

Fleet-observability kinds (``serving/router.py``, PR 17 — the router
decision ledger; docs/serving.md "Fleet observability").  Every
placement the fleet makes is attributable to exactly one of these
records, which carry the INPUTS the decision was made from, not just
the outcome:

==========================  =============================================
``route_decision``          one ``Router.submit`` decision, shed or
                            placed: the full per-replica candidate table
                            (affinity tokens, biased TTFT estimate,
                            load, role) in the order it was ranked, the
                            chosen replica, the replicas that refused
                            first (fallthrough, with their rejection
                            reasons), and the outcome
``handoff_decision``        one disaggregation handoff decision: the
                            import-candidate table (arrival affinity,
                            load, slot/block capacity), the chosen
                            decode replica, and the outcome (``handoff``
                            / ``deferred`` when no target had capacity /
                            ``bounced`` when the import raced away and
                            the request went back to its source)
``rebalance_decision``      one KV-free rebalance decision: what
                            triggered it (``overloaded`` demand /
                            ``watermark`` spread / ``manual``), the
                            per-replica queue depths it saw, the spread,
                            and how many requests it stole and landed
``replica_up``              a replica entered rotation (``set_alive``;
                            record carries the reason — the autoscaler
                            seam of ROADMAP 2(a))
``replica_down``            a replica left rotation: ``set_alive`` or an
                            evacuation (reason ``manual`` /
                            ``faults_detected`` / policy-specific)
``request_exported``        an engine unwound a DECODE slot into a
                            migration descriptor (``export_slot``) — the
                            src half of the cross-replica trace link
``request_imported``        an engine admitted a migration descriptor
                            straight into DECODE (``import_slot``);
                            ``orig_rid`` names the src-engine instance
                            it continues — the dst half of the link
==========================  =============================================

Auto-sharding planner kinds (``dist/autoplan.py``, PR 13):

==========================  =============================================
``plan_selected``           the planner chose a plan: record carries the
                            plan key, its modeled step time, and the
                            candidate/pruned counts (the RUNREPORT
                            ``autoplan`` section is the full audit)
``plan_rejected_oom``       a candidate's modeled per-device resident
                            bytes (``MemoryModel.estimate``) crossed the
                            OOM-risk line — pruned BEFORE any compile
==========================  =============================================

Zero-bubble pipeline kinds (``parallel/pipeline_parallel/zero_bubble.py``,
PR 14 — emitted at schedule-build (trace) time, once per compile):

==========================  =============================================
``zb_wgrad_deferred``       the ZB schedule queued its per-microbatch
                            wgrad work items (x, g, dx) instead of fusing
                            them into the backward wavefront — record
                            carries the unit and queue-slot counts
``zb_cooldown_filled``      the schedule's tick accounting: main-scan vs
                            wgrad-drain tick counts plus the modeled zb
                            and 1f1b bubble fractions at this (P, M) —
                            the numbers the RUNREPORT pipeline counters
                            and the bench A/B rows are checked against
==========================  =============================================

A module-level default log lets deep call sites (signal handlers, debug
callbacks) emit without plumbing a handle through every layer:
``emit_event("preemption", signum=15)``.
"""

from __future__ import annotations

import collections
import datetime
import time
from typing import Any, Dict, FrozenSet, Optional

#: Every event kind the package itself emits.  tests/test_repo_lint.py
#: AST-scans the package for ``emit_event("...")`` / ``.emit("...")``
#: call sites and asserts each literal kind appears here — an unregistered
#: kind is either a typo (the bug this catches) or a new feature that must
#: document itself by adding a line.
EVENT_KINDS: FrozenSet[str] = frozenset({
    # telemetry session
    "run_start", "run_end", "compile", "recompile",
    # checkpoint / preemption
    "checkpoint_save", "checkpoint_restore", "preemption",
    # numerics + hosts
    "nan_watchdog", "loss_scale", "straggler",
    # tools / comm
    "decode_cell", "overlap_configure", "xla_trace_start", "xla_trace_stop",
    # resilience (PR 4)
    "fault_injected", "ckpt_retry", "ckpt_quarantine", "rollback",
    "resilience_abort", "hang_suspected", "hang_resolved", "hang_abort",
    "desync_detected", "checkpoint_save_skipped",
    # serving (PR 5)
    "request_admitted", "prefill_chunk", "request_retired", "slots_snapshot",
    # serving under stress (PR 9)
    "request_preempted", "request_shed", "request_expired",
    "request_cancelled", "engine_fault_detected", "engine_recovered",
    "engine_drained",
    # serving fast path (PR 10)
    "prefix_hit", "block_cow", "spec_draft", "spec_verify", "cache_evict",
    # serving observability (PR 11)
    "request_submitted", "request_resumed", "engine_tick",
    # multi-replica router (PR 15)
    "request_routed", "request_migrated", "replica_degraded",
    "blocks_migrated",
    # fleet observability: the router decision ledger + the engine-side
    # halves of the cross-replica trace link (PR 17)
    "route_decision", "handoff_decision", "rebalance_decision",
    "replica_up", "replica_down", "request_exported", "request_imported",
    # memory observability (PR 6)
    "mem_snapshot", "oom_risk",
    # numerics observability (PR 7)
    "numerics_alert", "nan_block_located",
    # quantized collectives (PR 8)
    "compress_policy",
    # auto-sharding planner (PR 13)
    "plan_selected", "plan_rejected_oom",
    # zero-bubble pipeline schedule (PR 14)
    "zb_wgrad_deferred", "zb_cooldown_filled",
    # MoE dispatch + expert-load serving (PR 18): which dispatch path a
    # trace resolved ('auto' is backend-dependent), and the host-side
    # capacity-overflow alarm (dropped-token rate over threshold)
    "moe_dispatch_selected", "expert_overflow",
    # elastic fleet (PR 19): every autoscaler evaluation (hold included)
    # with its evidence; per-chunk wire re-requests healed by bounded
    # backoff; a transfer declared dead taking the re-prefill fallback;
    # and the engine-side unwind of an import whose KV never arrived
    "scale_decision", "migration_retry", "migration_fallback",
    "import_aborted",
    # ring paged prefill (PR 20): a prefill chunk that rode the cp ring
    # (width + per-rank sub-chunk), the modeled per-tick ring hop/byte
    # accounting, and a long-document prefill->decode KV handoff at the
    # router (length >= long_ctx_threshold)
    "cp_prefill_chunk", "cp_ring_hop", "kv_handoff_long",
})


def _process_index() -> int:
    """Best-effort process index: 0 before/without distributed init."""
    try:
        import jax

        return int(jax.process_index())
    except Exception:
        return 0


class EventLog:
    """In-memory (bounded deque) + optional JSONL-file event log.

    - ``path``: append-mode JSONL file.  Written on the master process only
      unless ``all_processes=True`` (per-host event files on a pod should
      use distinct paths — e.g. suffix ``jax.process_index()``).
    - ``sink``: any object with a ``write(record: dict)`` method (an
      :class:`~.exporters.JsonlSink` or friends) — used instead of ``path``.
    """

    def __init__(
        self,
        path: Optional[str] = None,
        sink=None,
        history_max: int = 4096,
        all_processes: bool = False,
    ) -> None:
        if path is not None and sink is None:
            from .exporters import JsonlSink

            sink = JsonlSink(path)
        self._sink = sink
        self._all_processes = all_processes
        self.events: collections.deque = collections.deque(maxlen=history_max)

    def emit(self, kind: str, **fields: Any) -> Dict[str, Any]:
        """Record one event; returns the record (all processes)."""
        rec: Dict[str, Any] = {
            "type": "event",
            "kind": str(kind),
            # wall clock via datetime (time.time() is lint-banned in the
            # package: every interval in the repo is perf_counter-based)
            "t_wall": datetime.datetime.now().timestamp(),
            # perf_counter shares its epoch with the step records'
            # t_end_s stamps, so events and spans land on one trace axis
            "t_mono": time.perf_counter(),
            "process": _process_index(),
        }
        rec.update(fields)
        self.events.append(rec)
        if self._sink is not None and (self._all_processes or rec["process"] == 0):
            try:
                self._sink.write(rec)
            except OSError:
                pass  # read-only checkout / full disk: keep the in-memory log
        return rec

    def of_kind(self, kind: str):
        return [e for e in self.events if e["kind"] == kind]

    def as_list(self):
        return list(self.events)


class TaggedEventLog:
    """A view of an :class:`EventLog` that stamps fixed fields on every
    emit — how a fleet gives each replica's engine an identity on a
    SHARED timeline without threading a replica index through every
    engine emit site.  ``Router`` wraps each replica's ``_ev`` with
    ``tag_events(log, replica=i)``; downstream consumers
    (``serving.tracing.assemble_fleet_request_timelines``) split the
    one timeline back into per-replica streams on the ``replica`` field.
    Everything except ``emit`` forwards to the wrapped log (same
    history, same sink), and an explicit field on an emit call wins over
    the tag."""

    def __init__(self, inner: EventLog, tags: Dict[str, Any]) -> None:
        self.inner = inner
        self.tags = dict(tags)

    def emit(self, kind: str, **fields: Any) -> Dict[str, Any]:
        return self.inner.emit(kind, **{**self.tags, **fields})

    def __getattr__(self, name: str) -> Any:
        return getattr(self.inner, name)


def tag_events(log: Any, **tags: Any) -> TaggedEventLog:
    """Wrap ``log`` so every emit carries ``tags``.  Re-tagging a
    tagged log replaces its tags instead of stacking views (a Router
    rebuilt over the same engines must not accumulate stale indices)."""
    while isinstance(log, TaggedEventLog):
        log = log.inner
    return TaggedEventLog(log, tags)


_default_log: Optional[EventLog] = None


def default_event_log() -> EventLog:
    """The process-wide event log (created in-memory on first use)."""
    global _default_log
    if _default_log is None:
        _default_log = EventLog()
    return _default_log


def set_default_event_log(log: Optional[EventLog]) -> None:
    """Install (or with None: reset) the process-wide default log.
    ``Telemetry`` installs its own log here so signal handlers and debug
    callbacks land on the same timeline as the step records."""
    global _default_log
    _default_log = log


def emit_event(kind: str, **fields: Any) -> Dict[str, Any]:
    """Emit on the process-wide default log — the zero-plumbing entry point
    for deep call sites (signal handlers, ``jax.debug.callback``)."""
    return default_event_log().emit(kind, **fields)
