from .gpt import (
    GPTConfig,
    gpt_forward,
    gpt_loss,
    gpt_param_specs,
    gpt_pipeline_1f1b,
    gpt_pipeline_loss,
    init_gpt_params,
    vocab_parallel_embed,
    vocab_parallel_xent,
)
from .vit import (
    ViTConfig,
    init_vit_params,
    patchify,
    vit_forward,
    vit_loss,
    vit_param_specs,
)
