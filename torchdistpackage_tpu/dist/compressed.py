"""Quantized gradient all-reduce — an XLA-native take on EQuARX
("Efficient Quantized AllReduce in XLA", arXiv 2506.17615, PAPERS.md): cut
the bytes a data-parallel grad reduction moves over ICI/DCN by carrying
int8 payloads through a manual ring, requantizing per hop exactly the way
the paper does inside XLA's all-reduce stages.

``int8_ring_pmean(g, axis)`` implements mean-all-reduce as

1. ring **reduce-scatter** over ``axis``: N-1 ``ppermute`` hops; each hop
   sends one int8-quantized chunk (1 byte/elem on the wire vs 4 for f32 /
   2 for bf16) plus one f32 scale per chunk, dequantizes, and accumulates
   into the local fp32 partial — per-hop requantization keeps the wire
   format int8 while the accumulator stays full precision,
2. **masked psum** of the finished owner chunks (each rank contributes its
   chunk into a zeroed [N, chunk] int8 buffer; every position has exactly
   one non-zero addend, so integer addition is exact).

Total wire bytes ≈ 3(N-1)/N per element vs 8(N-1)/N for f32 all-reduce — a
~2.7x reduction, at the cost of quantization noise bounded by
``group_amax / 127`` per hop (symmetric per-group scaling).  Gradient noise
of this magnitude is far below SGD's own batch noise in practice; the tests
bound the numeric error and check end-to-end training still converges.

Why a psum rather than the cheaper int8 all_gather for step 2: psum output
is **invariance-typed** over the axis, so the function is a legal drop-in
``pmean`` under ``shard_map(check_vma=True)`` — grad compression therefore
composes with TP/PP meshes (VERDICT r3 weak #3), where the step's
vma-driven bookkeeping (model-axis grad normalization, global-norm clip)
must keep running.  An all_gather result is varying-typed even though its
value is replicated, which would force the whole train step down to
``check_vma=False`` and pure-DP meshes — the old design.

Opt in via ``DataParallel(grad_compress='int8')`` — the compressed path
replaces the default ``pmean`` for leaves large enough to matter
(small leaves keep the exact reduction; the scale traffic would dominate).
"""

from __future__ import annotations

from typing import Tuple

import jax

from ..compat import axis_size
import jax.numpy as jnp


GROUP = 256  # elements per quantization scale (1.5% f32-scale overhead)


def _mark_varying(x, axis: str):
    """Mark ``x`` varying over ``axis`` if it isn't already (idempotent —
    same contract as parallel.data_parallel._mark_varying, duplicated here
    to keep dist/ import-independent of parallel/)."""
    from ..compat import pvary, typeof

    if axis in getattr(typeof(x), "vma", frozenset()):
        return x
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, (axis,), to="varying")
    return pvary(x, (axis,))


def _group_size(n: int) -> int:
    """Largest power of two <= GROUP dividing n (n is a static chunk size)."""
    g = 1
    while g * 2 <= GROUP and n % (g * 2) == 0:
        g *= 2
    return g


def _quant(x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric int8 quantization with PER-GROUP scales: a single per-chunk
    scale lets a few outlier elements wash out the rest of the chunk (quant
    noise ~ amax/127 per element regardless of magnitude), which accumulates
    over the ring's n-1 requantization hops into noise comparable to typical
    gradient values.  Per-group scales keep the noise proportional to the
    LOCAL amax.  x: [c] -> (q [c] int8, scales [c/g] f32)."""
    c = x.shape[0]
    g = _group_size(c)
    grouped = x.reshape(-1, g)
    scale = jnp.maximum(jnp.max(jnp.abs(grouped), axis=1), 1e-30) / 127.0
    q = jnp.clip(jnp.round(grouped / scale[:, None]), -127, 127).astype(jnp.int8)
    return q.reshape(c), scale.astype(jnp.float32)


def _dequant(q: jnp.ndarray, scale: jnp.ndarray) -> jnp.ndarray:
    c = q.shape[0]
    g = c // scale.shape[0]
    return (q.astype(jnp.float32).reshape(-1, g) * scale[:, None]).reshape(c)


def int8_ring_reduce_scatter(
    g: jnp.ndarray, axis: str, scatter_dim: int
) -> jnp.ndarray:
    """``psum_scatter(..., tiled=True)`` with int8 wire format: rank r of
    the mesh ``axis`` receives the SUM over the axis of tile r of
    ``scatter_dim`` (caller normalizes).  Traced; call inside shard_map.

    This is the ZeRO reduce-to-owner (zero_optim.py:203): grads only ever
    travel *toward* their owner shard, so the whole reduction is the ring
    reduce-scatter half of :func:`int8_ring_pmean` — (n-1)/n int8 bytes per
    element on the wire (+ ~1.5% scales) vs 4(n-1)/n for the f32
    ``psum_scatter`` it replaces: ~4x fewer wire bytes, and still 2x under
    a hypothetical bf16 wire.  Like ``psum_scatter`` itself,
    ``scatter_dim`` must divide by the axis size (ZeRO's
    ``zero_partition_spec`` only ever picks such dims; leaves with no
    divisible dim stay replicated and never reach this path).

    Ring schedule: rank r starts by sending chunk r-1 (offset -1 versus
    the pmean ring), so after n-1 accumulate-requantize hops the finished
    chunk at rank r is exactly chunk r — psum_scatter's tiling contract.
    The accumulator stays f32; only the per-hop payload is quantized."""
    n = axis_size(axis)
    if g.shape[scatter_dim] % n != 0:
        raise ValueError(
            f"scatter dim {scatter_dim} of size {g.shape[scatter_dim]} must "
            f"divide by the {axis!r} axis size {n} (same contract as tiled "
            f"psum_scatter)")
    if n == 1:
        return jax.lax.psum_scatter(
            g, axis, scatter_dimension=scatter_dim, tiled=True)

    gm = jnp.moveaxis(g, scatter_dim, 0).astype(jnp.float32)
    rest = gm.shape[1:]
    tile = gm.shape[0] // n
    chunks = gm.reshape(n, -1)  # chunk c = tile c of scatter_dim (C-order)
    # the ring's carries are axis-varying by construction (idx-indexed); an
    # invariance-typed input (e.g. a fully-replicated grad leaf) must be
    # cast up front or the scan carry types mismatch
    chunks = _mark_varying(chunks, axis)

    idx = jax.lax.axis_index(axis)
    fwd = [(i, (i + 1) % n) for i in range(n)]

    def rs_hop(carry, t):
        acc, send_q, send_s = carry
        recv_q = jax.lax.ppermute(send_q, axis, fwd)
        recv_s = jax.lax.ppermute(send_s, axis, fwd)
        c = jnp.mod(idx - t - 2, n)
        mine = jax.lax.dynamic_index_in_dim(acc, c, axis=0, keepdims=False)
        part = mine + _dequant(recv_q, recv_s)
        acc = jax.lax.dynamic_update_index_in_dim(acc, part, c, axis=0)
        q, s = _quant(part)
        return (acc, q, s), None

    q0, s0 = _quant(
        jax.lax.dynamic_index_in_dim(
            chunks, jnp.mod(idx - 1, n), 0, keepdims=False)
    )
    (acc, _, _), _ = jax.lax.scan(rs_hop, (chunks, q0, s0), jnp.arange(n - 1))
    owned = jax.lax.dynamic_index_in_dim(acc, idx, 0, keepdims=False)
    out = jnp.moveaxis(owned.reshape((tile,) + rest), 0, scatter_dim)
    return out.astype(g.dtype)


def int8_ring_pmean(g: jnp.ndarray, axis: str) -> jnp.ndarray:
    """Mean of ``g`` over the mesh ``axis`` with int8 wire format (traced;
    call inside shard_map).  Falls back to exact ``pmean`` when the leading
    dim doesn't divide by the axis size (ragged chunks) or the axis has a
    single member."""
    n = axis_size(axis)
    if n == 1:
        # still a pmean: the caller is promised an invariance-TYPED result
        # (a bare return would stay varying-marked and fail check_vma at
        # the sharded out_specs); over a 1-member axis it's free
        return jax.lax.pmean(g, axis)
    flat = g.reshape(-1)
    if flat.shape[0] % n != 0:
        return jax.lax.pmean(g, axis)

    idx = jax.lax.axis_index(axis)
    chunks = flat.reshape(n, -1).astype(jnp.float32)  # chunk c owned by rank c
    fwd = [(i, (i + 1) % n) for i in range(n)]

    # ---- ring reduce-scatter: after N-1 hops rank r holds the full sum of
    # chunk r.  Hop t: send the partial of chunk (idx - t) % n downstream.
    def rs_hop(carry, t):
        acc, send_q, send_s = carry
        recv_q = jax.lax.ppermute(send_q, axis, fwd)
        recv_s = jax.lax.ppermute(send_s, axis, fwd)
        # chunk being accumulated at this rank on hop t: (idx - t - 1) % n
        c = jnp.mod(idx - t - 1, n)
        mine = jax.lax.dynamic_index_in_dim(acc, c, axis=0, keepdims=False)
        part = mine + _dequant(recv_q, recv_s)
        acc = jax.lax.dynamic_update_index_in_dim(acc, part, c, axis=0)
        q, s = _quant(part)
        return (acc, q, s), None

    q0, s0 = _quant(
        jax.lax.dynamic_index_in_dim(chunks, jnp.mod(idx, n), 0, keepdims=False)
    )
    (acc, _, _), _ = jax.lax.scan(rs_hop, (chunks, q0, s0), jnp.arange(n - 1))
    # chunk c collects its n-1 ring additions at ranks c+1..c+n-1, finishing
    # at rank c-1 — so THIS rank ends holding chunk idx+1 fully reduced
    own_c = jnp.mod(idx + 1, n)
    owned = jax.lax.dynamic_index_in_dim(acc, own_c, 0, keepdims=False) / n

    # ---- gather of the owned (mean) chunks as a MASKED PSUM, int8 on the
    # wire: each rank scatters its quantized chunk into a zero row of an
    # [n, c] buffer and the psum assembles the full tensor — every position
    # has exactly one non-zero contributor, so int8 addition is exact.  A
    # plain all_gather would be varying-TYPED over the axis even though its
    # value is replicated; psum's output is invariance-typed, which is what
    # lets this whole function run under check_vma=True and therefore
    # compose with TP/PP meshes (the vma bookkeeping downstream —
    # normalize_model_axis_grads, clip's global norm — keeps working).
    # Wire cost: 2(n-1)/n int8 bytes/elem here + (n-1)/n in the ring above
    # = ~3 bytes/elem total vs 8 for an f32 all-reduce (2.7x; the pure
    # all_gather variant's 4x is not reachable with invariant typing).
    oq, os_ = _quant(owned)
    padded_q = jnp.zeros((n,) + oq.shape, jnp.int8)
    padded_q = jax.lax.dynamic_update_index_in_dim(padded_q, oq, own_c, axis=0)
    padded_s = jnp.zeros((n,) + os_.shape, jnp.float32)
    padded_s = jax.lax.dynamic_update_index_in_dim(padded_s, os_, own_c, axis=0)
    gq = jax.lax.psum(padded_q, axis)  # [n, c] int8, invariant over axis
    gs = jax.lax.psum(padded_s, axis)  # [n, c/g] f32
    out = jax.vmap(_dequant)(gq, gs)
    return out.reshape(g.shape).astype(g.dtype)
