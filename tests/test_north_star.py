"""The BASELINE.json north-star config at REAL scale, abstractly.

``dryrun_multichip`` executes the flagship composition at tiny shapes;
``trace_north_star_7b`` traces it at the actual 7B / 64-chip target
(``jax.eval_shape`` — zero FLOPs, zero array bytes), proving every
sharding spec divides, the interleaved slab layout holds, and the ZeRO
partition algebra works at d4096/L32/TP8/PP2/DP4.  The function
self-respawns under a 64-device CPU sim (this pytest process holds the
8-device backend), and its own asserts — param count 6-8B, scalar loss,
shape-preserving step — run in that child; a child failure raises here.
"""

import pathlib
import sys

import pytest

pytestmark = pytest.mark.slow

REPO = pathlib.Path(__file__).resolve().parent.parent


def test_north_star_7b_traces_on_64_device_mesh():
    sys.path.insert(0, str(REPO))
    try:
        import __graft_entry__ as g

        # usually the 8-device pytest process -> self-respawn path (None);
        # a 64-device env runs in-process and returns the summary.  Either
        # way assertion/trace failures raise.
        r = g.trace_north_star_7b()
        assert r is None or 6.0 < r["params_b"] < 8.0
    finally:
        sys.path.remove(str(REPO))


def test_moe_flagship_traces_on_64_device_mesh():
    """The expert-stack counterpart: ~3B MoE GPT under ZeRO(moe_dp) x
    EP=4 x MoE-DP=4 x TP=2 x PP=2, sorted dispatch, flash remat — the
    tiny-shape golden (test_zero.py::test_zero_moe_1f1b_full_stack)
    type-checked at real scale."""
    sys.path.insert(0, str(REPO))
    try:
        import __graft_entry__ as g

        r = g.trace_moe_flagship()
        assert r is None or 2.0 < r["params_b"] < 4.5
    finally:
        sys.path.remove(str(REPO))


def test_llama_7b_traces_on_64_device_mesh():
    """The modern-decoder counterpart: Llama3-8B-class (GQA kv8, SwiGLU,
    RoPE, RMSNorm via llama_config) under the same hybrid ZeRO x
    interleaved 1F1B x TP=8+SP x DP=4 64-device layout — the structural
    norm/act dispatch type-checked at real scale."""
    sys.path.insert(0, str(REPO))
    try:
        import __graft_entry__ as g

        r = g.trace_llama_7b()
        assert r is None or 6.5 < r["params_b"] < 8.0
    finally:
        sys.path.remove(str(REPO))
