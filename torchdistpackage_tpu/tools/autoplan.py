"""Auto-sharding planner CLI: plan from a JSON model config + chip count.

    python -m torchdistpackage_tpu.tools.autoplan --config model.json \
        --chips 8 [--batch 64] [--hbm-gb 16] [--chip "TPU v5e"] \
        [--effective-tflops 79] [--no-pp] [--executable-only] [--top 8]

``model.json`` holds the model dims (the GPTConfig / TransformerConfig
field names): ``{"vocab_size": 32768, "dim": 768, "nheads": 12,
"nlayers": 12, "max_seq": 2048, "ffn_mult": 4, "dtype": "bfloat16"}``
(``vocab_size`` absent = the headless transformer family).  The tool
enumerates mesh shapes x layer layouts x compression arms
(``dist/autoplan.py``), prunes candidates over the ``--hbm-gb`` budget,
scores the rest with the alpha-beta comm model for ``--chip`` plus the
6N+12LSD compute term, renders the ranked table, and prints ONE JSON
plan line (the machine-readable result, like ``bench.py``'s output).

Exit code: 0 = a plan was chosen, 1 = EVERY candidate is over the memory
budget (the clean all-OOM verdict — the table shows how far over), 2 =
usage / unreadable config.

Deliberately jax-free (a login-node / capacity-planning CLI, like
``bench_trend`` / ``parity_diff``), hence the bare prints: the analytic
memory mirror (pinned byte-identical to ``MemoryModel.estimate`` by
``tests/test_autoplan.py``) replaces the jax-side estimator, and the
per-generation CommModel tables replace calibration.  Feed a calibrated
model by planning in-process instead: ``dist.autoplan.plan(...,
comm_model=CommModel.calibrate(mesh))``.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional

from ..dist import autoplan as _ap


def _fmt_bytes(n: Optional[float]) -> str:
    if n is None:
        return "-"
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024
    return f"{n:.1f}GiB"


def render_table(result: Dict[str, Any]) -> List[str]:
    """Human ranked table + pruned roll-up for one plan() result."""
    L: List[str] = []
    p = result["params"]
    basis = result["basis"]
    L.append(
        f"autoplan: {p['n_chips']} chip(s), global batch "
        f"{p['global_batch']}, seq {p['seq_len']} — "
        f"{result['n_candidates']} candidate(s), "
        f"{result['n_pruned_oom']} pruned over-budget "
        f"(comm {basis['comm']}, compute {basis['compute']}, "
        f"memory {basis['memory']})")
    ranked = result.get("ranked") or []
    if ranked:
        L.append(
            f"  {'rank':>4}  {'plan':24s} {'step':>10} {'compute':>10} "
            f"{'comm':>10} {'resident':>10}  verdict")
        for i, r in enumerate(ranked):
            mem = r.get("memory") or {}
            L.append(
                f"  {i + 1:>4}  {r['key']:24s} "
                f"{r['step_s'] * 1e3:>8.3f}ms {r['compute_s'] * 1e3:>8.3f}ms "
                f"{r['comm_s'] * 1e3:>8.3f}ms "
                f"{_fmt_bytes(mem.get('total_bytes')):>10}  "
                f"{mem.get('verdict', '?')}")
    for row in result.get("pruned") or []:
        frac = row.get("frac")
        L.append(
            f"  OOM   {row['key']:24s} {_fmt_bytes(row['total_bytes']):>10}"
            f" of {_fmt_bytes(row.get('capacity_bytes'))}"
            + (f" ({frac:.0%})" if isinstance(frac, (int, float)) else ""))
    chosen = result.get("chosen")
    if chosen:
        L.append(f"  chosen: {chosen['key']} — modeled step "
                 f"{chosen['step_s'] * 1e3:.3f} ms, mesh "
                 f"{chosen['mesh_axes']}")
        for t in chosen.get("terms", []):
            tag = " int8" if t.get("compressed") else ""
            L.append(
                f"    {t['name']:>18}{tag}: {t['count']} x {t['op']} over "
                f"{'+'.join(t['axes'])} ({t['payload_bytes']:,} B) -> "
                f"{t['total_s'] * 1e3:.3f} ms")
    else:
        L.append("  NO PLAN FITS: every candidate exceeds the memory "
                 "budget (verdict all_oom)")
    return L


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m torchdistpackage_tpu.tools.autoplan",
        description="Rank parallelism plans for a JSON model config + chip "
                    "count; nonzero exit when no plan fits the memory "
                    "budget.")
    ap.add_argument("--config", required=True,
                    help="JSON file of model dims (GPTConfig field names)")
    ap.add_argument("--chips", type=int, required=True,
                    help="number of devices to plan for")
    ap.add_argument("--batch", type=int, default=None,
                    help="global batch (default: chips)")
    ap.add_argument("--seq", type=int, default=None,
                    help="sequence length (default: config max_seq)")
    ap.add_argument("--hbm-gb", type=float, default=None,
                    help="per-device HBM budget in GB (default: no budget, "
                         "nothing prunes)")
    ap.add_argument("--chip", default=None,
                    help="device kind for the comm/compute tables, e.g. "
                         "'TPU v5e' (default: generic link parameters)")
    ap.add_argument("--effective-tflops", type=float, default=None,
                    help="sustained per-device TFLOP/s for the compute "
                         "term (default: 40%% of the chip's table peak, "
                         "else 1 TFLOP/s 'assumed')")
    ap.add_argument("--optimizer-slots", type=int, default=2,
                    help="f32 moment buffers per param (adam=2)")
    ap.add_argument("--act-factor", type=float, default=1.0,
                    help="activation multiplier per layer boundary")
    ap.add_argument("--microbatches", type=int, default=8,
                    help="microbatch count assumed for pipeline plans")
    ap.add_argument("--no-pp", action="store_true",
                    help="skip pipeline-parallel candidates")
    ap.add_argument("--no-compress", action="store_true",
                    help="skip int8 compression arms")
    ap.add_argument("--executable-only", action="store_true",
                    help="restrict to plans bench's timed runners execute")
    ap.add_argument("--top", type=int, default=8,
                    help="ranked alternatives to keep (default 8)")
    args = ap.parse_args(argv)

    try:
        with open(args.config) as f:
            cfg = json.load(f)
        if not isinstance(cfg, dict):
            raise ValueError(f"config is {type(cfg).__name__}, expected "
                             f"a JSON object")
    except (OSError, ValueError) as e:
        print(f"autoplan: unreadable config {args.config}: {e}",
              file=sys.stderr)
        return 2
    try:
        result = _ap.plan(
            cfg,
            args.chips,
            global_batch=args.batch if args.batch else args.chips,
            seq_len=args.seq,
            capacity_bytes=(int(args.hbm_gb * 1e9) if args.hbm_gb else None),
            effective_flops=(args.effective_tflops * 1e12
                             if args.effective_tflops else None),
            optimizer_slots=args.optimizer_slots,
            act_factor=args.act_factor,
            microbatches=args.microbatches,
            allow_pp=not args.no_pp,
            compression=not args.no_compress,
            executable_only=args.executable_only,
            memory="analytic",  # jax-free mirror, pinned to MemoryModel
            device_kind=args.chip,
            top=args.top,
            emit=False,  # login-node tool: no event timeline to land on
        )
    except ValueError as e:
        print(f"autoplan: {e}", file=sys.stderr)
        return 2
    for ln in render_table(result):
        print(ln)
    chosen = result.get("chosen")
    line = {
        "metric": "autoplan",
        "verdict": result["verdict"],
        "n_candidates": result["n_candidates"],
        "n_pruned_oom": result["n_pruned_oom"],
        "chosen": (None if chosen is None else {
            k: chosen[k] for k in ("key", "mesh_axes", "layout", "compress",
                                   "step_s", "compute_s", "comm_s")
        }),
        "basis": result["basis"],
    }
    print(json.dumps(line))
    return 0 if chosen is not None else 1


if __name__ == "__main__":
    sys.exit(main())
