"""serving — paged KV cache + continuous batching for high-throughput decode.

``models/generate.py`` gives the framework *a* decode path; this package
gives it a SERVING path: a vLLM-style block-pool KV cache
(:mod:`.paged_cache`) and a slot-based continuous-batching engine
(:mod:`.engine`) whose hot loop is two statically-shaped compiled programs
— one decode step, one prefill-chunk step — however many requests of
whatever shapes flow through.  Host code between ticks only rewrites
small int32 block tables.

The transformer math is NOT reimplemented here: ``cached_block_forward``
(models/generate.py) takes ``cache_ops`` and both cache layouts run the
same block, so paged decode agrees with contiguous ``generate()`` to the
bit (tests/test_serving.py).  TP/DP sharding comes from the same mesh
axes as training; ``obs`` integration reports TTFT/TPOT percentiles,
aggregate tokens/s, slot occupancy and pool utilization in the RUNREPORT
``serving`` section.

Overload and faults are scheduler states, not exceptions (docs/serving.md
"Serving under stress"): priority classes with evict-and-requeue
preemption, deadline-aware admission that sheds with structured verdicts,
same-tick cancellation, a per-tick block-conservation audit with
self-healing recovery (chaos-matrix proven), and preemption-safe
SIGTERM drain/resume with exact-token replay — all host-side, so the
two-compiled-programs hot loop survives every path.

The fast path (docs/serving.md "Prefix cache" / "Speculative decoding"):
``prefix_cache=True`` turns the block pool content-addressed — per-block
refcounts, a chain-hash index over full token blocks, copy-on-write for
whole-prompt hits, LRU retention of released prefixes — so shared
system-prompt traffic prefills once per PREFIX; ``spec_k=K`` adds
self-speculative decoding at a static draft width (host n-gram drafter,
one compiled verify program over all k+1 positions, temp-0 bit-exact,
sampled rows via residual rejection sampling).  See docs/serving.md.

Observability (docs/serving.md "Serving observability"): every tick is
decomposed host-side into phase accounting (:mod:`.tracing` —
``engine_tick`` events, Perfetto phase lanes + counter tracks, the
``serving_metrics`` live-export schema), the event timeline reconstructs
each request's full lifecycle as a flow-linked Perfetto track (queued →
prefill → decode across preemptions and drain→resume), and
``serving_summary()['slo']`` reports per-priority deadline attainment,
goodput, and the predicted-vs-actual TTFT calibration whose bias feeds
back into ``estimate_ttft`` — all host arithmetic, zero extra compiled
programs.

Fleet observability (docs/serving.md "Fleet observability"): the Router
keeps a decision LEDGER — every route/handoff/rebalance/liveness
decision is a registered event carrying the candidate table it was made
from — and a request that crosses replicas stitches into one
flow-linked Perfetto track (:func:`assemble_fleet_request_timelines`).
The engine's five device touches sit behind a :class:`DeviceStep` seam
(:mod:`.sim`), so ``tools/trace_replay.py`` can push 10^5+ synthetic
requests through the real Router + :class:`StubDeviceStep` engines on
CPU and emit the validated FLEETREPORT as evidence.
"""

from .autoscale import AUTOSCALE_VERDICTS, Autoscaler
from .engine import Request, ServingEngine
from .router import (
    FLEET_BALANCE_VERDICTS,
    IMBALANCE_SKEWED_AT,
    ROLES,
    Router,
)
from .transport import (
    ChunkedWireTransport,
    LoopbackTransport,
    MigrationTransport,
    ReplicaDiedError,
    TransportDeadError,
    TransportError,
)
from .sim import (
    CompiledDeviceStep,
    DeviceStep,
    LatencyModel,
    StubDeviceStep,
    host_migrate_blocks,
)
from .tracing import (
    REQUEST_PHASES,
    REQUEST_TERMINALS,
    ROUTER_EVENT_KINDS,
    SERVING_METRICS_SCHEMA,
    TICK_PHASES,
    assemble_fleet_request_timelines,
    assemble_request_timelines,
    fleet_trace_events,
    lifecycle_phases,
    phase_table,
    request_trace_events,
    serving_metrics_record,
    serving_trace_events,
    tick_trace_events,
    validate_request_record,
)
from .paged_cache import (
    NULL_BLOCK,
    BlockAllocator,
    block_size_of,
    chain_block_hashes,
    copy_blocks,
    expected_pool_bytes,
    gather_kv,
    init_paged_kv,
    migrate_blocks,
    migration_wire_bytes,
    paged_attention,
    paged_forward,
    paged_forward_moe,
    paged_write,
    pool_bytes,
)

__all__ = [
    "AUTOSCALE_VERDICTS",
    "Autoscaler",
    "Request",
    "ServingEngine",
    "ChunkedWireTransport",
    "LoopbackTransport",
    "MigrationTransport",
    "ReplicaDiedError",
    "TransportDeadError",
    "TransportError",
    "FLEET_BALANCE_VERDICTS",
    "IMBALANCE_SKEWED_AT",
    "ROLES",
    "Router",
    "CompiledDeviceStep",
    "DeviceStep",
    "LatencyModel",
    "StubDeviceStep",
    "host_migrate_blocks",
    "REQUEST_PHASES",
    "REQUEST_TERMINALS",
    "ROUTER_EVENT_KINDS",
    "SERVING_METRICS_SCHEMA",
    "TICK_PHASES",
    "assemble_fleet_request_timelines",
    "fleet_trace_events",
    "assemble_request_timelines",
    "lifecycle_phases",
    "phase_table",
    "request_trace_events",
    "serving_metrics_record",
    "serving_trace_events",
    "tick_trace_events",
    "validate_request_record",
    "NULL_BLOCK",
    "BlockAllocator",
    "block_size_of",
    "chain_block_hashes",
    "copy_blocks",
    "expected_pool_bytes",
    "gather_kv",
    "init_paged_kv",
    "migrate_blocks",
    "migration_wire_bytes",
    "paged_attention",
    "paged_forward",
    "paged_forward_moe",
    "paged_write",
    "pool_bytes",
]
