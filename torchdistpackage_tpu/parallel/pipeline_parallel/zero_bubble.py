"""Zero-bubble (ZB-H1-shaped) pipeline schedule — split backward into
dgrad/wgrad and excise the cooldown's wasted weight-gradient work.

The classic SPMD 1F1B (``pipeline_sched.pipeline_1f1b``) runs one
``lax.scan`` over ``M + 2(P-1)`` ticks whose body carries one forward unit
AND one full backward unit (recompute + grad-input + grad-weight fused in
one ``jax.vjp``).  Under the uniform-body SPMD rule every tick executes
every slot, so the ``2(P-1)`` fill/drain ticks pay the FULL fused backward
on masked garbage — including the weight-gradient (wgrad) matmuls, which
have no cross-stage dependency at all and never needed a wavefront.

The zero-bubble family (MPMD Pipeline Parallelism, arXiv 2412.14374; the
ZB-H1 schedule of Qi et al.) decouples the two halves of the backward:

- **dgrad** (grad-input): ``dx`` must flow upstream on the 1F1B wavefront
  — it IS the backward pipeline's critical path;
- **wgrad** (grad-weight): ``dp`` is a per-(stage, microbatch) leaf
  computation consumed only by the end-of-step accumulator — it can run
  ANY time after its dgrad.

The MPMD papers fill each stage's idle cooldown gaps with the deferred
wgrad work.  An SPMD scan has no per-stage idle gaps to fill — it has
*wasted slot executions* — so the equivalent transformation is to remove
the wgrad ops from the wavefront scan entirely and run them in a dedicated
drain with zero idle slots:

1. **main scan** (``M + 2(P-1)`` ticks): forward unit + dgrad unit.  The
   dgrad differentiates the stage w.r.t. its INPUT only
   (``jax.vjp(lambda x: stage_fn(params, x), x)``) so the wgrad matmuls
   are never traced into this scan's body; each completed unit queues its
   wgrad work item ``(x, g, dx)`` — saved stage input, output cotangent,
   input cotangent — at queue slot ``m`` (the trace-time analogue of the
   reference schedulers' host-side wgrad queue);
2. **wgrad drain scan** (exactly ``M`` ticks): every stage pops its own
   unit ``m`` per tick — all stages busy every tick, no wavefront, no
   bubble — and computes ``dp`` by differentiating w.r.t. PARAMS only
   (``jax.vjp(lambda p: stage_fn(p, x), params)``; the dx ops are never
   traced here).

Slot accounting (the number :func:`~...obs.aggregate.
pipeline_bubble_fraction` reports for ``schedule='zb'``): fwd and dgrad
slots each run ``M + 2(P-1)`` times for M useful, the wgrad slot runs
exactly M times — idle/total = ``4(P-1) / (3M + 4(P-1))``, vs 1F1B's
``2(P-1) / (M + 2(P-1))``: strictly lower at every (P >= 2, M), -> 2/3 of
the 1F1B bubble as M grows, and ~half of it in the deep-pipeline
small-M regime the cooldown bubble actually hurts.

Honest costs (docs/parallelism.md spells these out):

- **extra recompute**: splitting the vjp re-runs the stage forward once in
  the dgrad pass and once in the wgrad pass (the fused 1F1B backward runs
  it once).  In wall-clock units (fwd = dgrad = wgrad = recompute = 1) the
  schedule totals ``3(M + 2P - 2) + 2M`` vs 1F1B's ``4(M + 2P - 2)`` — a
  net win exactly when ``M < 2(P-1)``, the regime where the bubble
  dominates; at large M the 1F1B bubble is already small and ZB's tick
  accounting win is paid for by recompute.
- **memory**: the wgrad queue keeps ``(x, g, dx)`` per microbatch — 3M
  activation-sized buffers vs 1F1B's ``min(M, 2P-1)`` ring.  ZB trades
  1F1B's O(P) activation bound for O(M); pick the schedule per config.

TP x PP synergy (Synergistic Tensor and Pipeline Parallelism, arXiv
2510.27257): the main-scan tick issues the forward boundary ``ppermute``
BETWEEN the forward compute and the dgrad compute — its payload is only
consumed by the next tick's carry, so the whole dgrad unit (including its
SP all-gather/reduce-scatter pairs when the stage runs TP) is independent
work the latency-hiding scheduler can run under the p2p transfer; the
cotangent ``ppermute`` likewise issues after the dgrad with the next
tick's forward as its slack.  ``obs.comm_ledger.tp_pp_overlap`` reads the
achieved overlap back out of the compiled step's HLO (async
collective-permute windows containing tensor-axis collectives).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from ...compat import axis_size
from ...dist.topology import PIPE_AXIS
from .pipeline_sched import (
    _gather_state,
    _normalized_first_fn,
    _slice_state,
    _transfer_dim,
    _zeros_like_shapes,
    is_first_stage,
    is_last_stage,
    shift_left,
    shift_right,
)

PyTree = Any


def zb_schedule_ticks(num_microbatches: int, pipe_size: int):
    """``(main_ticks, wgrad_ticks)`` of the zero-bubble schedule:
    ``M + 2(P-1)`` wavefront ticks (fwd + dgrad slots) plus exactly ``M``
    drain ticks (wgrad slot, every stage busy every tick)."""
    M, P_ = int(num_microbatches), int(pipe_size)
    return M + 2 * (P_ - 1), M


def pipeline_zb_1f1b(
    params: PyTree,
    inputs: PyTree,
    targets: PyTree,
    first_fn: Callable[[PyTree, PyTree], jnp.ndarray],
    stage_fn: Callable[[PyTree, jnp.ndarray], jnp.ndarray],
    last_fn: Callable[[PyTree, jnp.ndarray, PyTree], jnp.ndarray],
    num_microbatches: int,
    pipe_axis: str = PIPE_AXIS,
    stage_takes_mb: bool = False,
    transfer_shard_axis: Optional[str] = None,
):
    """Zero-bubble 1F1B: returns ``(loss, grads)`` directly, same contract
    as :func:`~.pipeline_sched.pipeline_1f1b` (do NOT wrap in ``jax.grad``)
    and bit-compatible loss/grads with it — the dgrad and wgrad passes
    replay the exact vjp subgraphs the fused backward runs, just in two
    scans instead of one.

    Signature subset of ``pipeline_1f1b``: ``first_fn``/``stage_fn``/
    ``last_fn`` take the same arguments (``stage_takes_mb`` hands
    ``stage_fn(params, x, m)`` the microbatch index — dropout keys replay
    identically in the forward, dgrad recompute, and wgrad recompute);
    ``transfer_shard_axis`` slices the inter-stage state 1/tp exactly as
    the classic schedule does.  Not supported here: ``num_chunks > 1``
    (interleaving composes with the split but is a separate schedule) and
    ``stage_returns_aux`` — both raise in ``pipeline_1f1b`` terms by not
    existing in this signature.

    Emits ``zb_wgrad_deferred`` + ``zb_cooldown_filled`` events at trace
    time with the schedule's tick accounting (the RUNREPORT pipeline
    section and the repo-lint kind registry read these).
    """
    from ...obs.aggregate import pipeline_bubble_fraction
    from ...obs.events import emit_event
    from ..data_parallel import _mark_varying, _vma, pvary_params

    M = num_microbatches
    P_ = axis_size(pipe_axis)
    T1, T2 = zb_schedule_ticks(M, P_)
    s = jax.lax.axis_index(pipe_axis)
    first = is_first_stage(pipe_axis)
    last = is_last_stage(pipe_axis)

    emit_event(
        "zb_wgrad_deferred",
        units=M, pipe_size=P_, queue_slots=M,
        note="wgrad work items (x, g, dx) queued per microbatch at trace "
             "time; executed in the drain scan",
    )
    emit_event(
        "zb_cooldown_filled",
        main_ticks=T1, wgrad_ticks=T2, pipe_size=P_, num_microbatches=M,
        bubble_fraction=pipeline_bubble_fraction(M, P_, schedule="zb"),
        bubble_fraction_1f1b=pipeline_bubble_fraction(M, P_, schedule="1f1b"),
    )

    # pipe-pvaried params: every vjp below yields LOCAL per-stage grads;
    # the one explicit psum for pipe-replicated leaves happens in ``sync``.
    orig_params = params
    params = pvary_params(params, (pipe_axis,))

    if stage_takes_mb:
        call_stage = stage_fn  # (p, x, m)
    else:
        call_stage = lambda p, x, m: stage_fn(p, x)

    take_mb = lambda tree, i: jax.tree.map(
        lambda a: jax.lax.dynamic_index_in_dim(a, i, axis=0, keepdims=False),
        tree,
    )
    mb0_in = take_mb(inputs, jnp.zeros((), jnp.int32))
    mb0_tgt = take_mb(targets, jnp.zeros((), jnp.int32))

    if transfer_shard_axis is not None:
        # Sharded inter-stage state (pipeline_1f1b docstring): slice at
        # every stage exit, gather at every entry — inside the
        # differentiated fns, so the wgrad queue and both ppermute
        # channels carry 1/tp-sized state and AD stays exact.
        tax = transfer_shard_axis
        tsz = axis_size(tax)
        full_state = jax.eval_shape(first_fn, params, mb0_in)
        tdims = jax.tree.map(lambda a: _transfer_dim(a.shape, tsz), full_state)
        _first0, _stage0, _last0 = first_fn, call_stage, last_fn

        def _close_scalar(v):
            # same rationale as pipeline_1f1b: a scalar escaping the
            # slice/gather conjugate pair is tax-varying-typed but
            # value-equal; pmean restores invariance and seeds the
            # transpose with the exact 1/tp share
            return jax.lax.pmean(v, tax) if tax in _vma(v) else v

        def first_fn(p, mb):
            return _slice_state(_first0(p, mb), tdims, tax)

        def call_stage(p, x, m):
            return _slice_state(_stage0(p, _gather_state(x, tdims, tax), m),
                                tdims, tax)

        def last_fn(p, y, tgt):
            return _close_scalar(_last0(p, _gather_state(y, tdims, tax), tgt))

    # ---- state aval fixed point (same iteration as pipeline_1f1b)
    x_shape = jax.eval_shape(first_fn, params, mb0_in)
    want_vma = frozenset(getattr(x_shape, "vma", frozenset())) | {pipe_axis}
    zero_state = None
    for _ in range(8):  # bounded by the number of mesh axes
        zero_state = _zeros_like_shapes(x_shape)
        missing = tuple(a for a in want_vma if a not in _vma(zero_state))
        if missing:
            zero_state = _mark_varying(zero_state, missing)
        y_shape = jax.eval_shape(
            call_stage, params, zero_state, jnp.zeros((), jnp.int32))
        new_want = frozenset(getattr(y_shape, "vma", frozenset())) | want_vma
        if new_want == want_vma:
            break
        want_vma = new_want
    if y_shape.shape != x_shape.shape or y_shape.dtype != x_shape.dtype:
        raise ValueError(
            f"stage_fn must preserve activation shape/dtype for pipelining: "
            f"{x_shape.shape}/{x_shape.dtype} -> {y_shape.shape}/{y_shape.dtype}"
        )

    first_v, _first_missing = _normalized_first_fn(first_fn, x_shape, want_vma)
    first_vjp_in_cond = pipe_axis not in _first_missing

    def _ones_seed(v):
        one = jnp.ones(jnp.shape(v), jnp.result_type(v))
        miss = tuple(a for a in _vma(v) if a not in _vma(one))
        return _mark_varying(one, miss) if miss else one

    # ---- one dgrad unit: recompute + vjp w.r.t. the INPUT only — the
    # wgrad (param-cotangent) ops are never traced into the main scan.
    def run_dgrad(opers):
        x_saved, cot_in, mb_tgt, m_b = opers
        y_, vjp_x = jax.vjp(lambda xx: call_stage(params, xx, m_b), x_saved)

        def last_branch(op):
            y_, mb_tgt, _ = op
            # loss seed lives on the last stage; differentiate last_fn
            # w.r.t. the ACTIVATION only (its param grads are wgrad work)
            loss_m, vjp_y = jax.vjp(
                lambda yy: last_fn(params, yy, mb_tgt), y_)
            (g,) = vjp_y(_ones_seed(loss_m))
            return loss_m, g

        last_shapes = jax.eval_shape(last_branch, (y_, mb_tgt, cot_in))

        def mid_branch(op):
            _, _, cot_in = op
            zl, _ = _zeros_like_shapes(last_shapes)
            return zl, cot_in

        loss_m, g = jax.lax.cond(last, last_branch, mid_branch,
                                 (y_, mb_tgt, cot_in))
        (dx,) = vjp_x(g)
        return loss_m, g, dx

    # ---- carry init
    _zvma = _vma(zero_state)

    def _stacked(n):
        def one(a):
            if _zvma:
                return jax.ShapeDtypeStruct((n,) + a.shape, a.dtype, vma=_zvma)
            return jax.ShapeDtypeStruct((n,) + a.shape, a.dtype)

        return _zeros_like_shapes(
            jax.tree.map(one, jax.eval_shape(lambda z: z, zero_state)))

    # the wgrad queue IS the activation ring: slot m holds microbatch m's
    # stage input (written by the fwd unit), output cotangent g and input
    # cotangent dx (written by the dgrad unit) — O(M), not O(P); see the
    # module docstring's memory note
    qx0, qg0, qdx0 = _stacked(M), _stacked(M), _stacked(M)
    cot0 = zero_state
    dgrad_shapes = jax.eval_shape(
        run_dgrad, (zero_state, cot0, mb0_tgt, jnp.zeros((), jnp.int32)))
    loss0, _, _ = _zeros_like_shapes(dgrad_shapes)

    def tick(carry, t):
        state, cot_state, qx, qg, qdx, loss_sum = carry

        # -------- forward unit: wavefront m_f = t - s
        k_f = t - s
        f_active = (k_f >= 0) & (k_f < M)
        m_f = jnp.clip(k_f, 0, M - 1)
        mb_in = take_mb(inputs, m_f)
        x = jax.lax.cond(
            first, lambda op: first_v(params, op[0]), lambda op: op[1],
            (mb_in, state))
        y = call_stage(params, x, m_f)
        qx = jax.lax.cond(
            f_active,
            lambda b: jax.tree.map(
                lambda buf, v: jax.lax.dynamic_update_index_in_dim(
                    buf, v, m_f, axis=0), b, x),
            lambda b: b,
            qx,
        )

        # Issue the forward boundary ppermute HERE, between the forward
        # and dgrad computes: its payload is consumed only by the next
        # tick's carry, so the whole dgrad unit below — including the SP
        # all-gather/reduce-scatter pairs of a TP stage — is independent
        # work the latency-hiding scheduler can hide the transfer behind
        # (the synergy-paper ordering, arXiv 2510.27257).
        nxt = shift_right(y, pipe_axis)

        # -------- dgrad unit: wavefront m_b = t - 2(P-1) + s; runs
        # unconditionally (uniform-body rule — a collective inside a
        # branch-divergent cond is undefined), accumulation masked
        k_b = t - (P_ - 1 - s) - (P_ - 1)
        b_active = (k_b >= 0) & (k_b < M)
        m_b = jnp.clip(k_b, 0, M - 1)
        x_saved = jax.tree.map(
            lambda buf: jax.lax.dynamic_index_in_dim(
                buf, m_b, axis=0, keepdims=False), qx)
        loss_m, g, dx = run_dgrad(
            (x_saved, cot_state, take_mb(targets, m_b), m_b))
        mask_b = lambda v: jnp.where(b_active, v, jnp.zeros((), v.dtype))
        loss_m = mask_b(loss_m)
        dx = jax.tree.map(mask_b, dx)
        # queue the wgrad work item (g, dx) at slot m_b for the drain
        qg, qdx = jax.lax.cond(
            b_active,
            lambda b: tuple(
                jax.tree.map(
                    lambda buf, v: jax.lax.dynamic_update_index_in_dim(
                        buf, v, m_b, axis=0), bi, vi)
                for bi, vi in zip(b, (g, dx))),
            lambda b: b,
            (qg, qdx),
        )
        loss_sum = loss_sum + loss_m
        cot_nxt = shift_left(dx, pipe_axis)
        return (nxt, cot_nxt, qx, qg, qdx, loss_sum), None

    (_, _, qx, qg, qdx, loss_sum), _ = jax.lax.scan(
        tick, (zero_state, cot0, qx0, qg0, qdx0, loss0), jnp.arange(T1))

    # ---- wgrad drain: M ticks, every stage pops its own unit m = j per
    # tick — no wavefront, no idle slots.  Differentiates w.r.t. PARAMS
    # only; the dx ops are never traced here.
    def first_branch(op):
        mb_in, dxm = op
        _, vjp_fp = jax.vjp(lambda p: first_v(p, mb_in), params)
        (dp_first,) = vjp_fp(dxm)
        return dp_first

    def run_wgrad(opers):
        """One deferred wgrad unit: total dp = dp_stage + dp_last +
        dp_first for queued microbatch ``m`` — exactly the param-cotangent
        half the fused 1F1B backward computes, replayed from the queue."""
        x_q, g_q, dx_q, mb_in, mb_tgt, m = opers

        # stage wgrad (the deferred work): recompute + vjp w.r.t. params
        y2, vjp_p = jax.vjp(lambda p: call_stage(p, x_q, m), params)
        (dp_stage,) = vjp_p(g_q)

        # last_fn's param grads (head/loss-side weights), y held fixed —
        # the dp_last partial the fused backward's last_branch computes
        def last_p_branch(op):
            y2, mb_tgt = op
            loss2, vjp_lp = jax.vjp(
                lambda p: last_fn(p, y2, mb_tgt), params)
            (dp_last,) = vjp_lp(_ones_seed(loss2))
            return dp_last

        last_p_shapes = jax.eval_shape(last_p_branch, (y2, mb_tgt))
        dp_last = jax.lax.cond(
            last, last_p_branch,
            lambda op: _zeros_like_shapes(last_p_shapes), (y2, mb_tgt))

        # first_fn's param grads (embed), seeded with the queued dx
        if first_vjp_in_cond:
            first_shapes = jax.eval_shape(first_branch, (mb_in, dx_q))
            dp_first = jax.lax.cond(
                first, first_branch,
                lambda op: _zeros_like_shapes(first_shapes), (mb_in, dx_q))
        else:
            # degenerate first_fn (ignores params): its vjp contains a
            # pipe psum and must run unconditionally — mask cotangent in,
            # (pipe-replicated) grad out, as pipeline_1f1b does
            dxm = jax.tree.map(
                lambda a: jnp.where(first, a, jnp.zeros((), a.dtype)), dx_q)
            dp_first = first_branch((mb_in, dxm))
            dp_first = jax.tree.map(
                lambda gr: gr * first.astype(jnp.result_type(gr)), dp_first)
        return jax.tree.map(
            lambda a, b, c: a + b + c, dp_stage, dp_last, dp_first)

    grads0 = _zeros_like_shapes(jax.eval_shape(
        run_wgrad,
        (zero_state, zero_state, zero_state, mb0_in, mb0_tgt,
         jnp.zeros((), jnp.int32))))

    def wtick(grads_acc, j):
        pop = lambda q: jax.tree.map(
            lambda buf: jax.lax.dynamic_index_in_dim(
                buf, j, axis=0, keepdims=False), q)
        dp = run_wgrad((pop(qx), pop(qg), pop(qdx),
                        take_mb(inputs, j), take_mb(targets, j), j))
        return jax.tree.map(jnp.add, grads_acc, dp), None

    grads, _ = jax.lax.scan(wtick, grads0, jnp.arange(T2))

    # mean over microbatches; broadcast the last stage's loss everywhere
    loss = jax.lax.psum(loss_sum, pipe_axis) / M
    inv = 1.0 / M

    def sync(g, p):
        g = g * inv
        if pipe_axis in _vma(p):
            return g
        if pipe_axis in _vma(g):
            return jax.lax.psum(g, pipe_axis)
        return g

    grads = jax.tree.map(lambda g, p: sync(g, p), grads, orig_params)
    return loss, grads
