"""SLURM job babysitter — analogue of ``slurm_job_monitor``
(``torchdistpackage/tools/slurm_job_monitor.py``, 132 LoC), the reference's
only elastic/fault-recovery mechanism (SURVEY §5): launch an sbatch job, poll
``sacct`` for its state, cancel anything dead/stuck, and relaunch until the
job reaches COMPLETED.

Works unchanged for TPU pods scheduled through SLURM; the launched script is
expected to call :func:`torchdistpackage_tpu.setup_distributed` (which reads
the SLURM env) on each host.  Everything is dependency-free subprocess code
so it can run on a login node.
"""

from __future__ import annotations

import re
import subprocess
import time
from typing import Optional, Sequence

# sacct states that mean "keep waiting".
_LIVE_STATES = ("RUNNING", "PENDING", "REQUEUED", "RESIZING", "SUSPENDED")
_DONE_STATE = "COMPLETED"


def _run(cmd: Sequence[str]) -> str:
    return subprocess.run(
        list(cmd), check=True, capture_output=True, text=True
    ).stdout


def launch_job(sbatch_script: str, *sbatch_args: str) -> str:
    """Submit ``sbatch_script`` and return the job id.

    Analogue of ``launch_job`` (slurm_job_monitor.py:24-40).
    """
    out = _run(["sbatch", *sbatch_args, sbatch_script])
    m = re.search(r"Submitted batch job (\d+)", out)
    if not m:
        raise RuntimeError(f"could not parse job id from sbatch output: {out!r}")
    return m.group(1)


def get_job_state(job_id: str) -> Optional[str]:
    """Primary sacct state for a job id.  None while sacct has no record yet
    — or when sacct itself errors (slurmdbd hiccup): the babysitter must
    survive transient control-plane failures, so those read as "unknown",
    not as a crash."""
    try:
        out = _run(["sacct", "-j", job_id, "--format=JobID,State", "--noheader", "-X"])
    except (subprocess.CalledProcessError, OSError):
        return None
    for line in out.splitlines():
        parts = line.split()
        if len(parts) >= 2 and parts[0] == job_id:
            return parts[1].rstrip("+")
    return None


def determine_job_is_alive(job_id: str) -> bool:
    """True while the job is running or queued — analogue of
    ``determine_job_is_alive`` (slurm_job_monitor.py:55-75)."""
    state = get_job_state(job_id)
    return state is None or state in _LIVE_STATES


def cancel_job(job_id: str) -> None:
    subprocess.run(["scancel", job_id], check=False)


def monitor_job(
    sbatch_script: str,
    *sbatch_args: str,
    poll_interval_s: float = 60.0,
    max_relaunches: Optional[int] = None,
) -> str:
    """Babysit a job to completion: launch, poll, and on any dead state
    (FAILED / NODE_FAIL / TIMEOUT / CANCELLED / ...) cancel + resubmit, until
    sacct reports COMPLETED.  Returns the final (successful) job id.

    Analogue of ``monitor_job`` (slurm_job_monitor.py:97-122).
    ``max_relaunches=None`` retries forever, like the reference.
    """
    relaunches = 0
    job_id = launch_job(sbatch_script, *sbatch_args)
    print(f"[slurm-monitor] launched job {job_id}")
    while True:
        time.sleep(poll_interval_s)
        state = get_job_state(job_id)
        if state == _DONE_STATE:
            print(f"[slurm-monitor] job {job_id} COMPLETED")
            return job_id
        if state is None or state in _LIVE_STATES:
            continue
        print(f"[slurm-monitor] job {job_id} state={state} — relaunching")
        cancel_job(job_id)
        if max_relaunches is not None and relaunches >= max_relaunches:
            raise RuntimeError(
                f"job failed {relaunches + 1} times (last state {state}); giving up"
            )
        # one dead job consumes exactly one relaunch from the budget; a failed
        # *submission* (transient sbatch/control-plane outage) retries below
        # without consuming more — otherwise an outage while a job is down
        # would burn the whole budget with zero real job failures.
        relaunches += 1
        while True:
            try:
                job_id = launch_job(sbatch_script, *sbatch_args)
                break
            except (subprocess.CalledProcessError, OSError, RuntimeError) as e:
                print(f"[slurm-monitor] relaunch submission failed ({e}); retrying")
                time.sleep(poll_interval_s)
        print(f"[slurm-monitor] relaunched as job {job_id}")


if __name__ == "__main__":
    import sys

    if len(sys.argv) < 2:
        print("usage: python -m torchdistpackage_tpu.tools.slurm_job_monitor <sbatch_script> [sbatch args...]")
        raise SystemExit(2)
    monitor_job(sys.argv[1], *sys.argv[2:])
