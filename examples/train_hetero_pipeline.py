"""End-to-end example: HETEROGENEOUS pipeline stages — different activation
widths on every inter-stage edge, the analogue of the reference's shape-meta
handshake capability (parallel/pipeline_parallel/comm.py:26-105), expressed
statically as a max-edge bus with per-stage lax.switch dispatch
(`make_heterogeneous_stage`).

A 2-stage funnel model: stage 0 widens D0=64 -> D1=96, stage 1 narrows
D1=96 -> D2=32; the 1F1B scheduler carries one uniform bus vector sized to
the largest edge, every edge contract is validated at trace time, and the
grads equal serial AD through the composed model.

- real TPU chips:      python examples/train_hetero_pipeline.py
- 8-device CPU sim:    TDP_CPU_SIM=8 python examples/train_hetero_pipeline.py
"""

import functools
import os

if os.environ.get("TDP_CPU_SIM"):
    # XLA_FLAGS handling is centralized in dist/overlap.py (test_repo_lint
    # bans direct writes); cpu_sim also pins the cpu platform, replacing
    # the old post-import jax.config.update dance.
    from torchdistpackage_tpu.dist.overlap import cpu_sim

    cpu_sim(os.environ["TDP_CPU_SIM"])

import jax

import jax.numpy as jnp
import numpy as np
import optax
from torchdistpackage_tpu.compat import shard_map
from jax.sharding import PartitionSpec as P

from torchdistpackage_tpu import setup_distributed, tpc
from torchdistpackage_tpu.parallel.pipeline_parallel import (
    make_heterogeneous_stage,
    pipeline_1f1b,
)


def main():
    setup_distributed()
    ndev = len(jax.devices())
    pp = 2 if ndev % 2 == 0 else 1
    tpc.setup_process_groups([("pipe", pp)], devices=jax.devices()[:pp])
    mesh = tpc.get_view()

    mbs, M = 4, 4
    D0, D1, D2 = 64, 96, 32
    k0, k1 = jax.random.split(jax.random.PRNGKey(0))
    params = {
        "wide": {"w": jax.random.normal(k0, (D0, D1)) / np.sqrt(D0)},
        "narrow": {"w": jax.random.normal(k1, (D1, D2)) / np.sqrt(D1)},
    }

    def widen(p, x, m):
        return jnp.tanh(x @ p["wide"]["w"])

    def narrow(p, x, m):
        return jnp.tanh(x @ p["narrow"]["w"])

    stage_fns = [widen, narrow] if pp == 2 else [
        lambda p, x, m: narrow(p, widen(p, x, m), m)
    ]
    edges = (
        [jax.ShapeDtypeStruct((mbs, d), jnp.float32) for d in (D0, D1, D2)]
        if pp == 2
        else [jax.ShapeDtypeStruct((mbs, d), jnp.float32) for d in (D0, D2)]
    )
    wrap_first, stage_fn, wrap_last = make_heterogeneous_stage(
        stage_fns, edges)

    vg = shard_map(
        functools.partial(
            pipeline_1f1b,
            first_fn=wrap_first(lambda p, mb: mb),
            stage_fn=stage_fn,
            last_fn=wrap_last(lambda p, y, t: jnp.mean((y - t) ** 2)),
            num_microbatches=M,
            stage_takes_mb=True,
        ),
        mesh=mesh,
        in_specs=(P(), P(), P()),
        out_specs=(P(), P()),
    )

    opt = optax.adam(1e-2)
    state = opt.init(params)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(p, s, x, t):
        loss, grads = vg(p, x, t)
        updates, s = opt.update(grads, s, p)
        return jax.tree.map(jnp.add, p, updates), s, loss

    steps = 3 if os.environ.get("TDP_SMOKE") else 30
    kx, kt = jax.random.split(jax.random.PRNGKey(1))
    x = jax.random.normal(kx, (M, mbs, D0))
    t = jax.random.normal(kt, (M, mbs, D2))
    for i in range(steps):
        params, state, loss = step(params, state, x, t)
        print(f"step {i}: loss {float(loss):.4f}")
    assert np.isfinite(float(loss))
    print("heterogeneous pipeline example done")


if __name__ == "__main__":
    main()
