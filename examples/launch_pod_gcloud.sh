#!/bin/bash
# Cloud-TPU (non-SLURM) pod launch — the gcloud twin of launch_pod.sbatch.
#
#     TPU_NAME=my-pod ZONE=us-east5-a ./examples/launch_pod_gcloud.sh
#
# `--worker=all` runs the command on every host of the pod slice
# simultaneously; on Cloud TPU the jax.distributed rendezvous needs NO env
# plumbing (the TPU runtime supplies coordinator + topology —
# dist/launch.py path 3), so the same train script works under both
# launchers unchanged.

set -euo pipefail

TPU_NAME="${TPU_NAME:?set TPU_NAME}"
ZONE="${ZONE:?set ZONE}"
SCRIPT="${SCRIPT:-examples/train_tp_dp.py}"

gcloud compute tpus tpu-vm ssh "$TPU_NAME" --zone "$ZONE" --worker=all \
    --command "cd ~/torchdistpackage_tpu && python -m torchdistpackage_tpu.dist.comm_bench && python $SCRIPT"
