"""Fused Pallas MoE dispatch (ops/moe_dispatch.py).

The load-bearing claims, each against the jnp dispatch paths as parity
oracles (PR-12's paged-attention discipline applied to the expert FFN):

- **Layer parity**: ``moe_forward(dispatch='pallas')`` — the routing
  decision fed straight into the fused gather->FFN->scatter kernel —
  matches the sorted AND dense materializations to ULP-level float
  tolerance (the tile-split matmuls vectorize differently than the
  full-view dot), forward and GRADS (the custom_vjp backward runs
  ``moe_ffn_oracle``, identical math), including a capacity that
  actually drops and the stacked SwiGLU expert.
- **EP parity**: under an EP-sharded mesh only the expert-FFN leg fuses
  (the all_to_all needs the [E, C, D] exchange layout); pallas vs sorted
  through the same shard_map must agree forward and grads.
- **int8**: ``quantize_moe_experts`` (q8, scale) pairs consumed with
  in-register dequant match the oracle's dequantize-then-matmul.
- **Engine token bit-parity**: a ``moe_dispatch='pallas'`` engine emits
  tokens BIT-equal to contiguous ``generate()`` and to the gather
  engine, at one decode signature, and ``serving_summary()['moe']``
  carries the live expert-load block the router's load index consumes.
- **Memory evidence**: the sorted arm's compiled forward materializes
  the [E, C, D] slot view (``modeled_slot_view_bytes`` prices it); the
  fused arm's program never allocates that shape — the HBM round-trip
  the kernel exists to eliminate.

Budget: ONE module-scope bundle (the test_serving MoE family) holds the
golden and the gather/pallas engine pair; layer tests share one routing
decision per shape.  On CPU the kernel runs in interpreter mode — parity
is the claim here; the HBM-traffic win is an on-chip claim (ROADMAP 5c).
"""

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torchdistpackage_tpu.compat import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from torchdistpackage_tpu.dist import tpc
from torchdistpackage_tpu.models import (
    GPTConfig,
    generate,
    init_gpt_moe_params,
)
from torchdistpackage_tpu.obs.events import EventLog, set_default_event_log
from torchdistpackage_tpu.ops.moe_dispatch import (
    fused_moe_ffn,
    modeled_slot_view_bytes,
    moe_ffn_oracle,
    quantize_moe_experts,
    resolve_moe_dispatch,
    slot_maps,
)
from torchdistpackage_tpu.parallel.moe import (
    MoEConfig,
    _top_k_route,
    init_moe_params,
    moe_forward,
    moe_param_specs,
)
from torchdistpackage_tpu.serving import Request, ServingEngine

# The test_serving MoE family: cf = E/top_k -> no drops, so engine tokens
# must be BIT-equal to the contiguous generate() golden.
CFG = GPTConfig(vocab_size=64, dim=32, nheads=4, nlayers=2, max_seq=32,
                moe_experts=4, moe_top_k=2, moe_every=2,
                moe_capacity_factor=2.0)
PROMPT, NEW = 5, 6


def _run_staggered(eng, prompts):
    """The engine's real regime: request B admitted while A decodes."""
    r0 = eng.submit(Request(prompts[0].tolist(), NEW))
    eng.step()
    eng.step()
    r1 = eng.submit(Request(prompts[1].tolist(), NEW))
    eng.run_until_idle(max_ticks=500)
    return [np.asarray(eng.finished[r]["tokens"]) for r in (r0, r1)]


@pytest.fixture(scope="module")
def bundle():
    """Module-scope bundle: golden + the gather/pallas engine pair —
    every engine-level test reuses the same compiled programs."""
    params = init_gpt_moe_params(jax.random.PRNGKey(0), CFG)
    prompts = np.stack([
        np.asarray(jax.random.randint(
            jax.random.PRNGKey(10 + i), (PROMPT,), 0, CFG.vocab_size))
        for i in range(2)
    ]).astype(np.int32)
    want = np.asarray(jax.jit(
        lambda p, t: generate(p, t, CFG, max_new_tokens=NEW)
    )(params, jnp.asarray(prompts)))
    out = {"params": params, "prompts": prompts, "want": want,
           "eng": {}, "tokens": {}}
    ekw = dict(num_slots=2, block_size=8, chunk=4, max_ctx=16)
    for impl in ("pallas", "gather"):
        eng = ServingEngine(params, CFG, moe_dispatch=impl, **ekw)
        out["tokens"][impl] = _run_staggered(eng, prompts)
        out["eng"][impl] = eng
    return out


# ------------------------------------------------------------ layer parity


def _routed(cfg, seed=1, B=2, S=16):
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(seed), (B, S, cfg.dim))
    return params, x


def _loss(p, x, cfg):
    y, aux = moe_forward(p, x, cfg)
    return jnp.mean(y * y) + aux


def test_fused_matches_sorted_and_dense_fwd_and_grad():
    """moe_forward(dispatch='pallas') vs the sorted and dense
    materializations: same routing decision, bit-identical f32 outputs
    AND grads (the fused bwd runs moe_ffn_oracle — the same gather/FFN/
    scatter math the jnp paths compute), for the no-drop capacity, a
    capacity that actually DROPS, and the stacked SwiGLU expert.

    Fast-tier holder for the slow-tier matrix in test_moe.py
    (test_sorted_dispatch_matches_dense / .._under_ep_matches_serial)."""
    base = MoEConfig(dim=16, ffn_dim=32, num_experts=4, top_k=2,
                     capacity_factor=4.0)
    for variant in [base,
                    dataclasses.replace(base, capacity_factor=0.6),
                    dataclasses.replace(base, act="swiglu")]:
        params, x = _routed(variant)
        got = {}
        for dispatch in ("pallas", "sorted", "dense"):
            cfg = dataclasses.replace(variant, dispatch=dispatch)
            got[dispatch] = jax.jit(jax.value_and_grad(
                functools.partial(_loss, x=x, cfg=cfg)))(params)
        for other in ("sorted", "dense"):
            lp, ls = got["pallas"][0], got[other][0]
            np.testing.assert_allclose(
                float(lp), float(ls), rtol=1e-6,
                err_msg=f"cf={variant.capacity_factor} act={variant.act}")
            jax.tree.map(
                lambda a, b: np.testing.assert_allclose(
                    np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7,
                    err_msg=f"pallas vs {other} grads "
                            f"(cf={variant.capacity_factor}, "
                            f"act={variant.act})"),
                got["pallas"][1], got[other][1])


def test_fused_forward_matches_oracle():
    """fused_moe_ffn and moe_ffn_oracle consume the SAME slot maps and
    run the same f32 dot chain; the kernel tiles the capacity dim, so
    parity is ULP-level float tolerance (the PR-12 kernel bar — BIT
    equality is the engine-token claim below) — drops included."""
    T, D, E, k = 24, 16, 4, 2
    experts = init_moe_params(
        jax.random.PRNGKey(0),
        MoEConfig(dim=D, ffn_dim=32, num_experts=E, top_k=k))["experts"]
    tokens = jax.random.normal(jax.random.PRNGKey(1), (T, D), jnp.float32)
    probs = jax.nn.softmax(
        jax.random.normal(jax.random.PRNGKey(2), (T, E)), axis=-1)
    for capacity in (T, 3):  # no-drop bound, and a capacity that drops
        gv, gi, slot, keep = _top_k_route(probs, k, capacity)
        got = fused_moe_ffn(experts, tokens, gv, gi, slot, keep, capacity)
        want = moe_ffn_oracle(experts, tokens, gv, gi, slot, keep, capacity)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-6,
            err_msg=f"capacity={capacity}")


def test_int8_fused_matches_oracle():
    """quantize_moe_experts (q8, scale) pairs through the kernel's
    in-register dequant vs the oracle's dequantize-then-matmul: the same
    dequantized f32 values through the same FFN math, to ULP-level
    tolerance — gelu and SwiGLU expert stacks."""
    T, D, E, k = 16, 16, 4, 2
    for act in ("gelu", "swiglu"):
        experts = init_moe_params(
            jax.random.PRNGKey(0),
            MoEConfig(dim=D, ffn_dim=32, num_experts=E, top_k=k,
                      act=act))["experts"]
        q = quantize_moe_experts(experts)
        assert q["w1"][0].dtype == jnp.int8 and q["w2"][0].dtype == jnp.int8
        tokens = jax.random.normal(jax.random.PRNGKey(1), (T, D), jnp.float32)
        probs = jax.nn.softmax(
            jax.random.normal(jax.random.PRNGKey(2), (T, E)), axis=-1)
        gv, gi, slot, keep = _top_k_route(probs, k, T)
        got = fused_moe_ffn(q, tokens, gv, gi, slot, keep, T)
        want = moe_ffn_oracle(q, tokens, gv, gi, slot, keep, T)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-6,
                                   err_msg=f"act={act}")
        # and the dequantized values track the float expert to quant tol
        fp = moe_ffn_oracle(experts, tokens, gv, gi, slot, keep, T)
        np.testing.assert_allclose(np.asarray(got), np.asarray(fp),
                                   rtol=0.1, atol=0.05)


def test_slot_maps_compress_the_routing_decision():
    """slot_maps is the kernel's contract: each KEPT (token, choice)
    occupies exactly one (expert, slot) cell carrying its renormalized
    gate; dropped choices and empty slots carry comb == 0."""
    T, E, k, capacity = 12, 4, 2, 2  # capacity 2 < T*k/E: drops happen
    probs = jax.nn.softmax(
        jax.random.normal(jax.random.PRNGKey(3), (T, E)), axis=-1)
    gv, gi, slot, keep = _top_k_route(probs, k, capacity)
    idx, comb = slot_maps(gv, gi, slot, keep, capacity)
    assert idx.shape == (E, capacity) and comb.shape == (E, capacity)
    kept = np.asarray(jnp.sum(keep, axis=-1))  # [T, k]
    assert int(kept.sum()) == int((np.asarray(comb) != 0).sum())
    # every kept choice is found at its (expert, slot) cell with its gate
    gv_n, gi_n, sl_n = np.asarray(gv), np.asarray(gi), np.asarray(slot)
    for t in range(T):
        for j in range(k):
            if kept[t, j]:
                e, c = gi_n[t, j], sl_n[t, j]
                assert int(np.asarray(idx)[e, c]) == t
                np.testing.assert_allclose(
                    float(np.asarray(comb)[e, c]), float(gv_n[t, j]),
                    rtol=1e-6)


# --------------------------------------------------------------- EP parity


def test_fused_ep_matches_sorted(devices8):
    """Under EP only the expert-FFN leg fuses (the all_to_all exchange
    needs the [E, C, D] grouped layout — it IS the wire payload):
    dispatch='pallas' through a moe_dp=2 x moe_ep=2 shard_map must match
    'sorted' forward and grads.  Unlike the serial-parity goldens this
    A/B needs no VMA gate: both arms run the SAME shard_map machinery,
    so the legacy fallback's reassociated reductions cancel out.
    Fast-tier EP holder for the slow-tier
    test_sorted_dispatch_under_ep_matches_serial."""
    tpc.setup_process_groups([("data", 4)], devices=devices8[:4])
    tpc.build_moe_mesh(moe_ep_size=2)
    mesh = tpc.get_view("moe")

    base = MoEConfig(dim=16, ffn_dim=32, num_experts=4, top_k=2,
                     capacity_factor=4.0)
    params = init_moe_params(jax.random.PRNGKey(0), base)
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 8, base.dim))
    specs = moe_param_specs("moe_ep")
    xspec = P(("moe_dp", "moe_ep"))
    sharded = jax.tree.map(
        lambda a, s: jax.device_put(a, NamedSharding(mesh, s)), params, specs)
    x_sh = jax.device_put(x, NamedSharding(mesh, xspec))

    got = {}
    for dispatch in ("pallas", "sorted"):
        cfg = dataclasses.replace(base, dispatch=dispatch)

        def loss(p, xx, cfg=cfg):
            y, aux = moe_forward(p, xx, cfg, ep_axis="moe_ep")
            return jax.lax.pmean(
                jnp.mean(y * y) + aux, ("moe_dp", "moe_ep"))

        got[dispatch] = jax.jit(shard_map(
            jax.value_and_grad(loss), mesh=mesh,
            in_specs=(specs, xspec), out_specs=(P(), specs),
        ))(sharded, x_sh)
    np.testing.assert_allclose(
        float(got["pallas"][0]), float(got["sorted"][0]), rtol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7,
            err_msg="pallas vs sorted under EP"),
        got["pallas"][1], got["sorted"][1])


# ------------------------------------------------------ engine token parity


def test_engine_token_bit_parity(bundle):
    """The moe_dispatch='pallas' engine (interpreter-mode kernel at the
    serving no-drop capacity bound C=T) emits tokens BIT-equal to
    contiguous generate() and to the gather engine, one decode signature
    per arm."""
    for impl in ("pallas", "gather"):
        for row, got in enumerate(bundle["tokens"][impl]):
            np.testing.assert_array_equal(
                got, bundle["want"][row],
                err_msg=f"moe_dispatch={impl} diverged from generate()")
        s = bundle["eng"][impl].serving_summary()
        assert s["decode_signatures"] == 1
        assert s["requests"]["completed"] == 2


def test_engine_moe_summary_block(bundle):
    """serving_summary()['moe'] is the live expert-load block the
    router's load index consumes: real per-expert routed-token counts,
    normalized entropy, no drops at cf=E/top_k, and the dispatch arm
    recorded so an A/B artifact names its kernel."""
    for impl in ("pallas", "gather"):
        eng = bundle["eng"][impl]
        moe = eng.serving_summary()["moe"]
        assert moe["dispatch"] == impl
        assert moe["num_experts"] == CFG.moe_experts
        assert len(moe["expert_tokens"]) == CFG.moe_experts
        assert sum(moe["expert_tokens"]) > 0  # stats actually flowed
        assert moe["imbalance"] >= 0.0
        assert 0.0 <= moe["load_entropy"] <= 1.0
        assert moe["dropped_token_rate"] == 0.0  # cf = E/top_k: no drops
        assert eng.moe_imbalance() == pytest.approx(moe["imbalance"])
    # both arms routed through the SAME router weights on the same
    # prompts: the load pictures must agree
    ga = bundle["eng"]["gather"].serving_summary()["moe"]
    pa = bundle["eng"]["pallas"].serving_summary()["moe"]
    np.testing.assert_allclose(pa["expert_tokens"], ga["expert_tokens"])


# ----------------------------------------------------- memory-ledger evidence


def test_compiled_forward_drops_slot_view():
    """The static-ledger evidence (the paged-attention
    test_compiled_decode_drops_gathered_temp claim, for experts): the
    sorted arm's compiled FORWARD materializes the [E, C, D] slot view
    — the HBM buffer modeled_slot_view_bytes prices — while the fused
    arm's program never allocates that shape (its working set is the
    [c_tile, D] scratch).  Forward only: the custom_vjp backward
    deliberately differentiates moe_ffn_oracle, which gathers the view."""
    from torchdistpackage_tpu.obs.mem_ledger import static_ledger

    # ffn_dim deliberately != C: w2 is [E, F, D], which at F == C would
    # alias the slot-view shape string and false-positive the probe
    E, D = 4, 32
    base = MoEConfig(dim=D, ffn_dim=48, num_experts=E, top_k=2,
                     capacity_factor=2.0)
    params = init_moe_params(jax.random.PRNGKey(0), base)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, D))  # T = 64
    C = 64  # ceil(T * top_k * cf / E)
    view = f"f32[{E},{C},{D}]"
    assert modeled_slot_view_bytes(E, C, D) == 2 * E * C * D * 4

    texts = {}
    for dispatch in ("pallas", "sorted"):
        cfg = dataclasses.replace(base, dispatch=dispatch)
        comp = jax.jit(
            lambda p, xx, cfg=cfg: moe_forward(p, xx, cfg)[0]
        ).lower(params, x).compile()
        assert static_ledger(comp) is not None
        texts[dispatch] = comp.as_text()
    assert view in texts["sorted"], (
        "sorted arm lost its [E, C, D] slot view? shapes under test are "
        "stale")
    assert view not in texts["pallas"], (
        "fused forward still materializes the [E, C, D] slot view")


# ------------------------------------------------------------------ resolve


def test_resolve_moe_dispatch():
    """'auto' resolves per backend (the jnp size-based selection on CPU —
    the interpreter kernel is a correctness story, not a speed story),
    records the choice on the event timeline, and junk is rejected at
    both the op and engine layers."""
    log = EventLog()
    set_default_event_log(log)
    try:
        assert resolve_moe_dispatch("auto") == "auto"  # CPU container
        assert resolve_moe_dispatch(None) == "auto"
        sel = log.of_kind("moe_dispatch_selected")
        assert sel and sel[-1]["chosen"] == "auto"
    finally:
        set_default_event_log(None)
    for ok in ("dense", "sorted", "pallas"):
        assert resolve_moe_dispatch(ok) == ok
    with pytest.raises(ValueError, match="dispatch"):
        resolve_moe_dispatch("cuda")
    with pytest.raises(ValueError, match="moe_dispatch"):
        ServingEngine(None, CFG, moe_dispatch="dense")  # engine arm names
    dense_cfg = GPTConfig(vocab_size=64, dim=32, nheads=4, nlayers=2,
                          max_seq=32)
    with pytest.raises(ValueError, match="no MoE"):
        ServingEngine(None, dense_cfg, moe_dispatch="pallas")
