"""Communication observability: HLO collective ledger, alpha-beta cost
model + calibration, and the Perfetto trace exporter.

Ledger assertions run real compiled steps on the 8-device CPU sim (the
conftest mesh): a TP x DP train step must show the dp grad all-reduce at
~param bytes, and a MoE-style step must show the EP all-to-all classified
into the 'moe' dimension.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from torchdistpackage_tpu.compat import shard_map
from torchdistpackage_tpu.dist import tpc
from torchdistpackage_tpu.dist.comm_bench import bench_collective
from torchdistpackage_tpu.obs import (
    COMM_RECORD_SCHEMA,
    CommModel,
    Telemetry,
    XlaStepTrace,
    build_trace,
    comm_record,
    comm_report,
    fit_alpha_beta,
    ledger_from_compiled,
    validate_runreport,
    validate_trace,
)
from torchdistpackage_tpu.obs.comm_ledger import (
    _expand_replica_groups,
    classify_axes,
    ledger_from_hlo,
    parse_hlo_collectives,
    render_table,
)
from torchdistpackage_tpu.obs.comm_model import (
    AxisCost,
    steps_for,
    wire_bytes,
)
from torchdistpackage_tpu.obs.events import set_default_event_log


@pytest.fixture(autouse=True)
def _fresh_default_log():
    set_default_event_log(None)
    yield
    set_default_event_log(None)


# ------------------------------------------------------------ HLO parsing


def test_parse_hlo_literal_groups_and_bytes():
    hlo = (
        "%all-reduce.1 = f32[2,16]{1,0} all-reduce(f32[2,16]{1,0} %x), "
        "channel_id=1, replica_groups={{0,2,4,6},{1,3,5,7}}, "
        'use_global_device_ids=true, to_apply=%add, '
        'metadata={op_name="jit(f)/psum"}'
    )
    (rec,) = parse_hlo_collectives(hlo)
    assert rec["op"] == "all-reduce"
    assert rec["bytes"] == 2 * 16 * 4
    assert rec["groups"] == [[0, 2, 4, 6], [1, 3, 5, 7]]
    assert rec["group_size"] == 4
    assert rec["op_name"] == "jit(f)/psum"


def test_parse_hlo_allgather_scales_by_group_size():
    hlo = (
        "%all-gather.1 = f32[4,16]{1,0} all-gather(f32[2,16]{1,0} %x), "
        "channel_id=2, replica_groups={{0,1},{2,3}}, dimensions={0}"
    )
    (rec,) = parse_hlo_collectives(hlo)
    # operand is the local shard; the full payload is shard * group
    assert rec["bytes"] == 2 * 16 * 4 * 2


def test_parse_hlo_skips_references_and_done_ops():
    hlo = "\n".join([
        "%all-to-all.2 = (f32[4,2]{1,0}, f32[4,2]{1,0}) "
        "all-to-all(f32[4,2]{1,0} %a, f32[4,2]{1,0} %b), channel_id=1, "
        "replica_groups={{0,1}}",
        "%gte = f32[4,2]{1,0} get-tuple-element((f32[4,2]{1,0}, "
        "f32[4,2]{1,0}) %all-to-all.2), index=0",
        "%all-gather-done.1 = f32[8]{0} all-gather-done(f32[8]{0} %ags)",
        "ROOT %t = (f32[4,2]{1,0}) tuple(f32[4,2]{1,0} %gte)",
    ])
    recs = parse_hlo_collectives(hlo)
    assert len(recs) == 1
    assert recs[0]["op"] == "all-to-all"
    # variadic form: full payload = sum of operand chunks
    assert recs[0]["bytes"] == 2 * (4 * 2 * 4)


def test_parse_hlo_async_start_counted_once():
    hlo = "\n".join([
        "%ar-start = f32[8]{0} all-reduce-start(f32[8]{0} %x), "
        "channel_id=5, replica_groups={{0,1,2,3}}",
        "%ar-done = f32[8]{0} all-reduce-done(f32[8]{0} %ar-start)",
    ])
    recs = parse_hlo_collectives(hlo)
    assert len(recs) == 1
    assert recs[0]["async"] is True
    assert recs[0]["bytes"] == 32


def test_parse_hlo_overlap_window_records_collectives_inside():
    """TP-under-PP overlap evidence (PR 14): collectives issued between an
    async op's -start and -done land in its ``overlapped_idx``, and
    ``tp_pp_overlap`` classifies them per dimension — here a tensor-axis
    all-gather + reduce-scatter pair inside a pipeline collective-permute
    window, the synergy-schedule ordering zero_bubble.py arranges."""
    from torchdistpackage_tpu.obs.comm_ledger import tp_pp_overlap

    hlo = "\n".join([
        "%cp-start = f32[8]{0} collective-permute-start(f32[8]{0} %x), "
        "channel_id=1, source_target_pairs={{0,2},{2,0},{1,3},{3,1}}",
        "%ag = f32[16]{0} all-gather(f32[8]{0} %a), channel_id=2, "
        "replica_groups={{0,1},{2,3}}, dimensions={0}",
        "%rs = f32[8]{0} reduce-scatter(f32[16]{0} %b), channel_id=3, "
        "replica_groups={{0,1},{2,3}}, dimensions={0}, to_apply=%add",
        "%cp-done = f32[8]{0} collective-permute-done(f32[8]{0} %cp-start)",
        "%ag2 = f32[16]{0} all-gather(f32[8]{0} %c), channel_id=4, "
        "replica_groups={{0,1},{2,3}}, dimensions={0}",
    ])
    recs = parse_hlo_collectives(hlo)
    assert len(recs) == 4
    cp = recs[0]
    assert cp["async"] is True
    # the window holds exactly the two collectives before -done; the
    # post-done all-gather is outside it
    assert cp["overlapped_idx"] == [1, 2]
    assert cp["sched_distance"] == 2
    assert recs[1]["overlapped_idx"] is None  # sync ops carry no window

    # classified through a 2x2 pipe x tensor mesh, the summary reports
    # the tp pair (all payload bytes) inside the pp permute's slack
    import numpy as np

    class _M:
        devices = np.arange(4).reshape(2, 2)
        axis_names = ("pipe", "tensor")
        shape = {"pipe": 2, "tensor": 2}

    class _D:
        def __init__(self, i):
            self.id = i

    _M.devices = np.array([[_D(0), _D(1)], [_D(2), _D(3)]], dtype=object)
    ledger = ledger_from_hlo(hlo, mesh=_M())
    rep = tp_pp_overlap(ledger)
    assert rep["pp_async_ops"] == 1
    assert rep["pp_windows_with_tp"] == 1
    assert rep["tp_ops_in_pp_windows"] == 2
    assert rep["tp_bytes_in_pp_windows"] == (16 * 4) + (16 * 4)
    assert rep["mean_pp_sched_distance"] == 2
    # an all-sync ledger (the CPU sim's shape) reports cleanly as zero
    assert tp_pp_overlap(None)["pp_async_ops"] == 0


def test_expand_replica_groups_iota():
    assert _expand_replica_groups("{{0,1},{2,3}}") == [[0, 1], [2, 3]]
    assert _expand_replica_groups("[2,4]<=[8]") == [
        [0, 1, 2, 3], [4, 5, 6, 7]]
    # transposed iota: arange(8).reshape(4,2).T.reshape(2,4)
    assert _expand_replica_groups("[2,4]<=[4,2]T(1,0)") == [
        [0, 2, 4, 6], [1, 3, 5, 7]]


def test_classify_axes():
    assert classify_axes(("data",)) == "dp"
    assert classify_axes(("moe_dp",)) == "dp"
    assert classify_axes(("tensor",)) == "tp"
    assert classify_axes(("pipe",)) == "pp"
    assert classify_axes(("moe_ep",)) == "moe"
    assert classify_axes(("data", "tensor")) == "other"  # mixed
    # the context axis classifies as cp since ring paged prefill (PR 20)
    # ledgers its ppermute hops there (cp_ring_overlap reads this bucket)
    assert classify_axes(("context",)) == "cp"


# ---------------------------------------------------- ledger on real steps


def test_ledger_tp_dp_step_dp_bytes_match_params(devices8):
    mesh = tpc.setup_process_groups([("data", 4), ("tensor", 2)])
    D = 32
    params = jnp.ones((D, D), jnp.float32)

    def body(p, x):
        y = x @ p
        y = jax.lax.psum(y, "tensor")          # tp activation collective
        loss = (y ** 2).mean()
        g = jax.grad(lambda p_: ((x @ p_) ** 2).mean())(p)
        g = jax.lax.psum(g, "data")            # dp grad sync
        return loss, g

    f = jax.jit(shard_map(
        body, mesh=mesh, in_specs=(P(), P("data")), out_specs=(P(), P())))
    compiled = f.lower(params, jnp.ones((8, D), jnp.float32)).compile()
    ledger = ledger_from_compiled(compiled, mesh=mesh)
    assert ledger is not None and ledger["n_collectives"] >= 2

    dp = ledger["per_dim"].get("dp")
    assert dp is not None, ledger["per_dim"]
    param_bytes = D * D * 4
    # the dp grad all-reduce moves exactly the param tree
    assert dp["bytes"] == param_bytes, (dp, param_bytes)
    assert "tp" in ledger["per_dim"], ledger["per_dim"]

    # mesh axes recorded for downstream consumers
    assert ledger["mesh_axes"] == {"data": 4, "tensor": 2}
    # render_table never crashes and names every dimension present
    table = render_table(ledger)
    assert "dp" in table and "tp" in table


def test_ledger_moe_step_all_to_all_detected(devices8):
    tpc.setup_process_groups([("data", 8)])
    tpc.build_moe_mesh(moe_ep_size=4)
    mesh = tpc.get_view("moe")

    def body(x):
        return jax.lax.all_to_all(
            x, "moe_ep", split_axis=1, concat_axis=0, tiled=True)

    f = jax.jit(shard_map(
        body, mesh=mesh, in_specs=(P("moe_ep"),), out_specs=P("moe_ep")))
    compiled = f.lower(jnp.ones((16, 8), jnp.float32)).compile()
    ledger = ledger_from_compiled(compiled, mesh=mesh)
    assert ledger is not None
    a2a = [c for c in ledger["collectives"] if c["op"] == "all-to-all"]
    assert a2a, [c["op"] for c in ledger["collectives"]]
    assert a2a[0]["dim"] == "moe"
    assert a2a[0]["axes"] == ["moe_ep"]
    assert ledger["per_dim"]["moe"]["bytes"] > 0


def test_ledger_without_mesh_still_enumerates():
    hlo = (
        "%all-reduce.1 = f32[8]{0} all-reduce(f32[8]{0} %x), "
        "channel_id=1, replica_groups={{0,1}}"
    )
    ledger = ledger_from_hlo(hlo, mesh=None)
    assert ledger["n_collectives"] == 1
    assert ledger["collectives"][0]["dim"] == "other"
    assert ledger["mesh_axes"] is None


# ------------------------------------------------------------- cost model


def test_alpha_beta_math():
    model = CommModel({"data": AxisCost(alpha_s=1e-6, beta_Bps=1e9)})
    n, size = 4, 1 << 20
    # all_reduce: 2(n-1) latency steps + 2(n-1)/n * S wire bytes
    expect = 2 * 3 * 1e-6 + (2 * 3 / 4) * size / 1e9
    got = model.predict("all_reduce", size, n, axes=("data",))
    assert got == pytest.approx(expect, rel=1e-9)
    # hyphenated (ledger) spelling resolves to the same op
    assert model.predict("all-reduce", size, n, axes=("data",)) == got
    # ppermute: single hop, full payload on the wire
    assert model.predict("ppermute", size, n, axes=("data",)) == \
        pytest.approx(1e-6 + size / 1e9, rel=1e-9)
    # n=1: nothing to communicate
    assert model.predict("all_reduce", size, 1, axes=("data",)) == 0.0


def test_steps_and_wire_bytes():
    assert steps_for("all_reduce", 4) == 6
    assert steps_for("all_gather", 4) == 3
    assert steps_for("ppermute", 8) == 1
    assert wire_bytes("all_reduce", 1000, 4) == pytest.approx(1500.0)
    assert wire_bytes("all_gather", 1000, 4) == pytest.approx(750.0)
    assert wire_bytes("ppermute", 1000, 4) == pytest.approx(1000.0)


def test_calibration_fit_recovers_synthetic_alpha_beta():
    alpha, beta = 5e-6, 2.5e9
    rng = np.random.default_rng(0)
    samples = []
    for steps in (1, 3, 6, 14):
        for wire in (1e4, 1e6, 3e7):
            t = steps * alpha + wire / beta
            samples.append((steps, wire, t * rng.uniform(0.98, 1.02)))
    a, b = fit_alpha_beta(samples)
    assert a == pytest.approx(alpha, rel=0.25)
    assert b == pytest.approx(beta, rel=0.1)


def test_fit_alpha_beta_degenerate_latency_only():
    # all timings identical regardless of size: bandwidth unobservable
    a, b = fit_alpha_beta([(1, 0.0, 1e-5), (1, 0.0, 1e-5)])
    assert a == pytest.approx(1e-5)
    assert b == float("inf")


def test_calibrate_on_cpu_sim_mesh(devices8):
    mesh = tpc.setup_process_groups([("data", 4), ("tensor", 2)])
    model = CommModel.calibrate(
        mesh=mesh, sizes=(1 << 12, 1 << 16), ops=("all_reduce",),
        iters=2, warmup=1)
    assert model.source == "calibrated"
    assert set(model.axis_costs) == {"data", "tensor"}
    for c in model.axis_costs.values():
        assert c.kind == "calibrated"
        assert c.alpha_s >= 0.0
        assert c.beta_Bps > 0
    # a calibrated model predicts a finite, sane time for real shapes
    t = model.predict("all_reduce", 1 << 20, 4, axes=("data",))
    assert 0 <= t < 10


def test_comm_report_verdict_and_headroom():
    ledger = ledger_from_hlo(
        "%all-reduce.1 = f32[262144]{0} all-reduce(f32[262144]{0} %x), "
        "channel_id=1, replica_groups={{0,1,2,3}}",
        mesh=None,
    )
    model = CommModel({}, default=AxisCost(1e-6, 1e9), chip="test")
    # comm-bound: modeled comm exceeds modeled compute
    rep = comm_report(ledger, step_time_s=2e-3, model=model,
                      xla_flops=1e6, peak_flops=1e12)
    assert rep["verdict"] == "comm-bound"
    assert rep["modeled_comm_s"] > rep["modeled_compute_s"]
    assert rep["overlap_headroom_s"] >= 0
    # compute-bound: huge compute estimate flips the verdict
    rep2 = comm_report(ledger, step_time_s=2e-3, model=model,
                       xla_flops=1e12, peak_flops=1e12)
    assert rep2["verdict"] == "compute-bound"
    # no step time at all -> explicit unknown, never a crash
    rep3 = comm_report(ledger, step_time_s=None, model=model)
    assert rep3["verdict"] == "unknown"


# ------------------------------------------- comm_bench schema round-trip


def test_bench_collective_emits_obs_schema(devices8, tmp_path):
    mesh = tpc.setup_process_groups([("data", 8)])
    row = bench_collective("all_reduce", "data", nbytes=1 << 12, mesh=mesh,
                           warmup=1, iters=2)
    assert row["schema"] == COMM_RECORD_SCHEMA
    assert row["type"] == "comm"
    for k in ("op", "axis", "bytes", "time_s", "algbw_GBps", "busbw_GBps"):
        assert k in row, row
    assert row["op"] == "all_reduce" and row["axis"] == "data"
    # busbw factor for all_reduce over 8: 2*(8-1)/8
    assert row["busbw_GBps"] == pytest.approx(
        row["algbw_GBps"] * 2 * 7 / 8, rel=1e-9)

    # streams through JsonlSink unchanged (the satellite contract)
    from torchdistpackage_tpu.dist.comm_bench import test_collection

    path = tmp_path / "comm.jsonl"
    rows = test_collection(
        "data", sizes=(1 << 10,), ops=("all_reduce", "ppermute"),
        mesh=mesh, verbose=False, sink=str(path))
    lines = [json.loads(l) for l in path.read_text().splitlines()]
    assert len(lines) == len(rows) == 2
    assert all(l["schema"] == COMM_RECORD_SCHEMA for l in lines)


def test_comm_record_builder():
    rec = comm_record("all_gather", "tensor", 4096, axis_size=2,
                      time_s=1e-4, algbw_GBps=1.0, busbw_GBps=0.5)
    assert rec["bytes"] == 4096 and rec["axis_size"] == 2
    minimal = comm_record("all_reduce", "data", 128)
    assert "time_s" not in minimal  # annotation-only records are legal


# ------------------------------------------------------------------ trace


def _run_telemetry(n_steps=3, **kw):
    tel = Telemetry(run="trace_test", tokens_per_step=8, report_path="",
                    trace_path="", **kw)
    f = jax.jit(lambda x: x * 2.0)
    step = tel.wrap_step(f)
    for i in range(n_steps):
        out = step(jnp.ones((4,)))
        tel.end_step(step=i, loss=out.sum())
    return tel


def test_trace_export_validates_and_loads(tmp_path):
    tel = _run_telemetry()
    tel.finalize(write=False, print_summary=False)
    from torchdistpackage_tpu.obs import export_trace

    path = tmp_path / "trace.json"
    trace = export_trace(tel, str(path))
    assert validate_trace(trace) == []
    # the file round-trips as JSON and still validates
    loaded = json.loads(path.read_text())
    assert validate_trace(loaded) == []
    evs = [e for e in loaded["traceEvents"] if e["ph"] == "X"]
    # every step contributes dispatch/device/fetch spans (data needs a prior
    # step's fetch, so >= 2 of those)
    names = {e["name"].split("[")[0] for e in evs}
    assert {"dispatch", "fetch"} <= names
    assert any(e["name"].startswith("device") for e in evs)
    # instant events from the event log ride along (run_start at least)
    kinds = [e["name"] for e in loaded["traceEvents"] if e["ph"] == "i"]
    assert "run_start" in kinds
    # spans are back-to-back and non-negative
    for e in evs:
        assert e["ts"] >= 0 and e["dur"] >= 0


def test_validate_trace_rejects_garbage():
    assert validate_trace(42)
    assert validate_trace({"no_events": []})
    assert validate_trace({"traceEvents": [{"ph": "Z", "name": "x"}]})
    assert validate_trace(
        {"traceEvents": [{"ph": "X", "name": "x", "ts": 0}]})  # no dur
    assert validate_trace({"traceEvents": []}) == []


def test_build_trace_empty_history_is_valid():
    trace = build_trace([], events=[])
    assert validate_trace(trace) == []


def test_xla_step_trace_window(tmp_path, monkeypatch):
    calls = []
    import jax.profiler as prof

    monkeypatch.setattr(prof, "start_trace", lambda d: calls.append(("start", d)))
    monkeypatch.setattr(prof, "stop_trace", lambda: calls.append(("stop",)))
    xt = XlaStepTrace(str(tmp_path), trace_steps=(1, 2))
    for i in range(4):
        xt.on_step_start(i)
        xt.on_step_end(i)
    assert calls == [("start", str(tmp_path)), ("stop",)]
    assert xt.done
    # idempotent after the window
    xt.on_step_start(1)
    assert calls == [("start", str(tmp_path)), ("stop",)]


def test_xla_step_trace_close_stops_inflight(tmp_path, monkeypatch):
    calls = []
    import jax.profiler as prof

    monkeypatch.setattr(prof, "start_trace", lambda d: calls.append("start"))
    monkeypatch.setattr(prof, "stop_trace", lambda: calls.append("stop"))
    xt = XlaStepTrace(str(tmp_path), trace_steps=(0, 99))
    xt.on_step_start(0)
    assert xt.active
    xt.close()
    assert calls == ["start", "stop"] and not xt.active


# -------------------------------------------- Telemetry comm integration


def test_telemetry_runreport_comm_section(devices8, tmp_path):
    mesh = tpc.setup_process_groups([("data", 4), ("tensor", 2)])
    D = 16

    def body(p, x):
        g = jax.grad(lambda p_: ((x @ p_) ** 2).mean())(p)
        return jax.lax.psum(g, "data").mean()

    f = jax.jit(shard_map(
        body, mesh=mesh, in_specs=(P(), P("data")), out_specs=P()))
    tel = Telemetry(run="comm_int", report_path="", trace_path="", mesh=mesh)
    step = tel.wrap_step(f)
    p, x = jnp.ones((D, D)), jnp.ones((8, D))
    for i in range(3):
        out = step(p, x)
        tel.end_step(step=i, loss=out)
    report = tel.finalize(write=False, print_summary=False)
    assert validate_runreport(report) == []
    comm = report["comm"]
    assert comm, "comm section missing despite compiled step"
    assert comm["ledger"]["per_dim"]["dp"]["bytes"] == D * D * 4
    assert comm["verdict"] in ("comm-bound", "compute-bound")
    assert "modeled_comm_s" in comm and comm["modeled_comm_s"] >= 0
    assert "measured_step_s" in comm
    # ledger rows carry the fields the record schema promises
    for c in comm["ledger"]["collectives"]:
        for k in ("op", "bytes", "axes", "dim"):
            assert k in c
