"""Deterministic fault injection — the chaos harness recovery is proven with.

The reference has no failure testing at all: its babysitter
(``tools/slurm_job_monitor.py``) relaunches dead jobs but nothing ever
*creates* a dead job on purpose, so the recovery path ships untested.  At
pod scale worker failure is the steady state, so every recovery claim in
:mod:`..resilience` is asserted against faults injected here — in the CPU
sim and the multiprocess test worker, never by waiting for real hardware
to break.

Faults are **declared up front** (a list of :class:`Fault` records) and
**seed-driven** (byte positions for checkpoint bit-flips come from a
``random.Random(seed)``), so a failing chaos run replays exactly.  Every
injection lands on the obs timeline as a structured ``fault_injected``
event — tests assert recovery *against the timeline*, not against prints.

Supported fault kinds (``Fault.kind``):

==================  ====================================================
``ckpt_corrupt``    truncate or bit-flip a data file of a *committed*
                    checkpoint step (the failure Orbax's atomic-commit
                    markers cannot catch: the commit succeeded, the bytes
                    rotted afterwards)
``sigterm``         deliver a real SIGTERM to this process before the
                    step runs (preemption mid-run)
``nan_spike``       poison the step's loss (or a grad tree) with
                    NaN/Inf at a chosen step — the divergence the
                    :class:`~.loop.ResilientLoop` monitor must catch
``stall``           sleep ``duration_s`` before the step — an artificial
                    straggler / hung-host window for the
                    :class:`~.watchdog.Watchdog` to detect
``host_dropout``    hard-exit this process (``os._exit``) — the host
                    simply vanishes, as a real failed worker does
==================  ====================================================

Engine-level fault kinds (the serving chaos matrix — ``Fault.step`` is
the ENGINE TICK these fire at, and ``Fault.slot`` picks the victim slot /
dp group; the :class:`~..serving.ServingEngine` drives them through
``chaos=`` and must detect + heal every one, co-batched requests
bit-identical — see docs/serving.md "Serving under stress"):

==================  ====================================================
``slot_stall``      sleep ``duration_s`` inside an engine tick — a
                    wedged tick for the engine's :class:`~.watchdog
                    .Watchdog` to escalate (``hang_suspected``)
``alloc_exhaust``   grab every free block of a dp group's
                    :class:`~..serving.BlockAllocator` without an owner
                    — a block leak the per-tick conservation audit must
                    find and reclaim
``table_corrupt``   overwrite an entry of a live slot's device-bound
                    block-table row — the poisoned slot must be retired
                    and replayed BEFORE the row reaches a compiled step
``nan_logits``      poison one slot's host-fetched sampled token with an
                    out-of-range sentinel — the cheap deterministic
                    stand-in for a NaN logit row (the same idiom as
                    ``nan_spike`` poisoning the fetched loss): the
                    engine's validity check must retire + replay exactly
                    that slot
==================  ====================================================

Transport fault kinds (the KV-migration wire — ``Fault.step`` is the
MIGRATION SEQUENCE NUMBER the fault fires on (the k-th ``send`` of the
:class:`~..serving.transport.ChunkedWireTransport`), and ``Fault.slot``
picks the victim chunk index within that send.  A non-repeating fault
fires on the first fetch attempt only, so the bounded-backoff re-request
recovers it; ``repeat=True`` fires on EVERY attempt — the retry budget
exhausts and the router must take the ``migration_fallback`` re-prefill
path instead.  See docs/resilience.md "Transport faults"):

===============================  =======================================
``chunk_drop``                   a wire chunk never arrives (the fetch
                                 raises instead of delivering bytes)
``chunk_corrupt``                a wire chunk arrives with a flipped
                                 byte — the per-chunk SHA-256 manifest
                                 check must reject it
``transport_stall``              the fetch exceeds the transport's
                                 timeout (``duration_s`` vs
                                 ``timeout_s``) — a timed-out chunk is
                                 re-requested like a dropped one
``replica_death_midmigration``   the destination replica dies after
                                 chunks started flowing — terminal for
                                 the transfer: the router must fall
                                 back without double-owning or leaking
                                 the in-flight request's blocks
===============================  =======================================

Usage::

    chaos = ChaosMonkey(faults=[Fault("nan_spike", step=5)], seed=0)
    loop = ResilientLoop(step_fn, make_batch, mgr, total_steps=10,
                         chaos=chaos)

A :class:`ChaosMonkey` with no faults (or ``enabled=False``) is inert:
``before_step`` and ``perturb_loss`` are pure pass-throughs, so a run
with the harness armed but no fault fired is bit-identical to a run
without it (asserted in ``tests/test_resilience.py``).
"""

from __future__ import annotations

import dataclasses
import os
import random
import signal
import time
from typing import Any, List, Optional, Sequence

#: Faults the serving engine injects/heals (``Fault.step`` = engine tick).
ENGINE_FAULT_KINDS = (
    "slot_stall", "alloc_exhaust", "table_corrupt", "nan_logits")

#: Faults the KV-migration wire injects (``Fault.step`` = migration
#: sequence number, ``Fault.slot`` = victim chunk index within the send;
#: driven by :class:`~..serving.transport.ChunkedWireTransport`).
TRANSPORT_FAULT_KINDS = (
    "chunk_drop", "chunk_corrupt", "transport_stall",
    "replica_death_midmigration")

FAULT_KINDS = (
    "ckpt_corrupt", "sigterm", "nan_spike", "stall", "host_dropout",
) + ENGINE_FAULT_KINDS + TRANSPORT_FAULT_KINDS


@dataclasses.dataclass
class Fault:
    """One declared fault.  ``step`` is the loop step it fires at (before
    the step's computation, except ``nan_spike`` which poisons the step's
    outputs).  Each fault fires once unless ``repeat=True`` — a repeating
    ``nan_spike`` models a *persistently* diverged trajectory, which is how
    the retry budget is exhausted in tests."""

    kind: str
    step: int
    mode: str = "truncate"            # ckpt_corrupt: "truncate" | "bitflip"
    value: float = float("nan")       # nan_spike: injected value (inf works)
    duration_s: float = 0.0           # stall: sleep length
    process: Optional[int] = None     # restrict to one host (None = all)
    target_step: Optional[int] = None  # ckpt_corrupt: ckpt step (None = latest)
    exit_code: int = 42               # host_dropout
    slot: Optional[int] = None        # engine faults: victim slot / dp group
    repeat: bool = False
    fired: int = dataclasses.field(default=0, compare=False)

    def __post_init__(self):
        if self.kind not in FAULT_KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}; one of {FAULT_KINDS}")


def _data_files(step_dir: str) -> List[str]:
    """All regular files of a checkpoint step, largest first — corrupting
    the largest data file guarantees we hit array bytes, not a marker."""
    out = []
    for root, _dirs, files in os.walk(step_dir):
        for f in files:
            out.append(os.path.join(root, f))
    out.sort(key=lambda p: (-os.path.getsize(p), p))
    return out


def corrupt_checkpoint(
    directory: str,
    step: Optional[int] = None,
    mode: str = "truncate",
    rng: Optional[random.Random] = None,
) -> str:
    """Corrupt a committed checkpoint under ``directory`` (a
    ``CheckpointManager`` root): truncate the largest data file of ``step``
    to half, or flip one byte at a seed-chosen offset.  Returns the path of
    the file corrupted.  ``step=None`` targets the newest step."""
    rng = rng or random.Random(0)
    steps = sorted(
        int(d) for d in os.listdir(directory)
        if d.isdigit() and os.path.isdir(os.path.join(directory, d))
    )
    if not steps:
        raise FileNotFoundError(f"no checkpoint steps under {directory}")
    step = steps[-1] if step is None else int(step)
    step_dir = os.path.join(directory, str(step))
    files = _data_files(step_dir)
    if not files:
        raise FileNotFoundError(f"checkpoint step {step} has no files")
    victim = files[0]
    size = os.path.getsize(victim)
    if mode == "truncate":
        with open(victim, "r+b") as f:
            f.truncate(max(0, size // 2))
    elif mode == "bitflip":
        pos = rng.randrange(max(1, size))
        with open(victim, "r+b") as f:
            f.seek(pos)
            b = f.read(1)
            f.seek(pos)
            f.write(bytes([(b[0] if b else 0) ^ 0xFF]))
    else:
        raise ValueError(f"unknown corruption mode {mode!r}")
    from ..obs.events import emit_event

    emit_event(
        "fault_injected", fault="ckpt_corrupt", target_step=step, mode=mode,
        file=os.path.relpath(victim, directory),
    )
    return victim


class ChaosMonkey:
    """Drives the declared fault plan against a training loop.

    The :class:`~.loop.ResilientLoop` calls :meth:`before_step` at the top
    of each iteration and passes the fetched loss through
    :meth:`perturb_loss`; custom loops can do the same, plus
    :meth:`perturb_grads` for grad-tree injection.  ``ckpt_dir`` names the
    checkpoint root ``ckpt_corrupt`` faults operate on (the loop wires its
    manager's directory in automatically).
    """

    def __init__(
        self,
        faults: Sequence[Fault] = (),
        seed: int = 0,
        ckpt_dir: Optional[str] = None,
        enabled: bool = True,
    ) -> None:
        self.faults = [dataclasses.replace(f) for f in faults]
        self.rng = random.Random(seed)
        self.ckpt_dir = ckpt_dir
        self.enabled = enabled

    # ------------------------------------------------------------- plumbing

    @property
    def fired_count(self) -> int:
        return sum(f.fired for f in self.faults)

    def _due(self, step: int, kinds: Sequence[str]) -> List[Fault]:
        if not self.enabled:
            return []
        try:
            import jax

            proc = int(jax.process_index())
        except Exception:  # backend not up: single-process semantics
            proc = 0
        out = []
        for f in self.faults:
            if f.kind not in kinds or f.step != step:
                continue
            if f.fired and not f.repeat:
                continue
            if f.process is not None and f.process != proc:
                continue
            out.append(f)
        return out

    def _emit(self, fault: Fault, **extra: Any) -> None:
        fault.fired += 1
        from ..obs.events import emit_event

        emit_event("fault_injected", fault=fault.kind, step=fault.step, **extra)

    # ------------------------------------------------------------ injectors

    def before_step(self, step: int) -> None:
        """Fire pre-step faults due at ``step``: stall, checkpoint
        corruption, SIGTERM, host dropout (in that order — a stall that
        precedes a SIGTERM models the common 'hung then reclaimed' event)."""
        for f in self._due(step, ("stall",)):
            self._emit(f, duration_s=f.duration_s)
            time.sleep(f.duration_s)
        for f in self._due(step, ("ckpt_corrupt",)):
            if self.ckpt_dir is None:
                raise RuntimeError("ckpt_corrupt fault needs ChaosMonkey(ckpt_dir=...)")
            f.fired += 1  # corrupt_checkpoint emits the event itself
            corrupt_checkpoint(
                self.ckpt_dir, step=f.target_step, mode=f.mode, rng=self.rng)
        for f in self._due(step, ("sigterm",)):
            self._emit(f)
            os.kill(os.getpid(), signal.SIGTERM)
        for f in self._due(step, ("host_dropout",)):
            self._emit(f, exit_code=f.exit_code)
            os._exit(f.exit_code)

    # ------------------------------------------- serving-engine injectors

    def before_engine_tick(self, tick: int, engine: Any) -> None:
        """Fire engine-level faults due at ``tick`` (the engine calls this
        at the top of :meth:`~..serving.ServingEngine.step`, BEFORE its
        invariant audit — so every injected inconsistency is on the table
        when the audit runs, and a healed tick proves detection, not
        luck).  ``nan_logits`` fires later, through
        :meth:`perturb_engine_tokens`."""
        for f in self._due(tick, ("slot_stall",)):
            self._emit(f, duration_s=f.duration_s)
            time.sleep(f.duration_s)
        for f in self._due(tick, ("alloc_exhaust",)):
            g = f.slot or 0
            alloc = engine._allocs[g % len(engine._allocs)]
            stolen = alloc.alloc(alloc.n_free) or []
            # deliberately NOT recorded anywhere the engine can see: the
            # blocks are live with no owner, exactly what a leak looks like
            self._emit(f, group=g, stolen_blocks=len(stolen))
        for f in self._due(tick, ("table_corrupt",)):
            victims = [
                i for i, s in enumerate(engine._slots) if s.state != "free"]
            if not victims:
                continue  # nothing live to corrupt this tick; stays armed
            slot = f.slot if f.slot is not None else victims[0]
            # point the row's first entry at a block this slot does NOT
            # own: seed-chosen from the victim group's free list when one
            # exists (a freed block the step would read stale data from),
            # else the last pool block
            alloc = engine._allocs[slot // engine.slots_per_group]
            pool = alloc._free or [engine.num_blocks - 1]
            bogus = pool[self.rng.randrange(len(pool))]
            engine._tables[slot, 0] = bogus
            self._emit(f, slot=slot, entry=0, bogus_block=int(bogus))

    # ---------------------------------------- migration-wire injectors

    def transport_faults_due(self, seq: int) -> List[Fault]:
        """Transport faults due on migration ``seq`` (the k-th wire send).
        The :class:`~..serving.transport.ChunkedWireTransport` calls this
        once per fetch ATTEMPT of that send: a non-repeating fault is
        consumed by its first firing (the bounded-backoff re-request then
        succeeds — the recoverable arm), while ``repeat=True`` keeps
        firing until the retry budget exhausts (the fallback arm).  The
        transport injects the failure itself and reports it back through
        :meth:`fire` — injection lives where the wire lives."""
        return self._due(seq, TRANSPORT_FAULT_KINDS)

    def fire(self, fault: Fault, **extra: Any) -> None:
        """Record an externally-injected fault: bump its fired count and
        land the ``fault_injected`` evidence on the timeline — for
        injectors (the migration transport) that apply the fault
        themselves but must keep the chaos ledger exact."""
        self._emit(fault, **extra)

    def perturb_engine_tokens(self, tick: int, tokens: Any) -> Any:
        """Poison one slot's host-fetched sampled token when a
        ``nan_logits`` fault is due — the deterministic stand-in for a NaN
        logit row (an all-NaN row's argmax is indistinguishable from a
        legitimate token 0, so the injected evidence is an out-of-range
        sentinel the engine's validity check must catch; the device state
        is untouched, which is also what keeps the co-batched
        bit-identity claim falsifiable)."""
        due = self._due(tick, ("nan_logits",))
        if not due:
            return tokens
        import numpy as np

        tokens = np.array(tokens, copy=True)
        for f in due:
            slot = f.slot if f.slot is not None else 0
            tokens[slot] = np.iinfo(np.int32).min
            self._emit(f, slot=slot, target="sampled_token")
        return tokens

    def perturb_loss(self, step: int, loss: float) -> float:
        """Poison the step's (host-fetched) loss when a ``nan_spike`` is
        due — the cheap deterministic stand-in for a diverged device step:
        the loop must discard the step's outputs and roll back either way."""
        for f in self._due(step, ("nan_spike",)):
            self._emit(f, value=repr(f.value), target="loss")
            loss = f.value
        return loss

    def perturb_grads(self, step: int, grads: Any) -> Any:
        """Poison every leaf of a grad pytree when a ``nan_spike`` is due —
        for custom loops that hand grads to their optimizer themselves."""
        due = self._due(step, ("nan_spike",))
        if not due:
            return grads
        import jax
        import jax.numpy as jnp

        for f in due:
            self._emit(f, value=repr(f.value), target="grads")
            grads = jax.tree.map(
                lambda g: jnp.full_like(g, f.value)
                if hasattr(g, "dtype") and jnp.issubdtype(g.dtype, jnp.floating)
                else g,
                grads,
            )
        return grads
