"""End-to-end example: train the MoE GPT with EP + MoE-DP (+ optional TP).

The BASELINE.md MoE milestone: an 8-expert transformer trained with expert
parallelism (experts sharded over the 'moe_ep' sub-axis, token dispatch via
all_to_all) and MoE data parallelism (same-expert replicas average grads
over 'moe_dp' only — the reference's MoEDP hook split,
torchdistpackage/ddp/naive_ddp.py:233-441 + ddp/moe_dp.md — expressed here
as a grad-reduce override).

- real TPU chips:      python examples/train_moe.py
- 8-device CPU sim:    TDP_CPU_SIM=8 python examples/train_moe.py
"""

import os

if os.environ.get("TDP_CPU_SIM"):
    # XLA_FLAGS handling is centralized in dist/overlap.py (test_repo_lint
    # bans direct writes); cpu_sim also pins the cpu platform, replacing
    # the old post-import jax.config.update dance.
    from torchdistpackage_tpu.dist.overlap import cpu_sim

    cpu_sim(os.environ["TDP_CPU_SIM"])

import jax

import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from torchdistpackage_tpu import setup_distributed, tpc
from torchdistpackage_tpu.models import (
    GPTConfig,
    gpt_moe_loss,
    gpt_moe_param_specs,
    init_gpt_moe_params,
)
from torchdistpackage_tpu.models.gpt_moe import gpt_moe_forward
from torchdistpackage_tpu.obs import Telemetry, moe_load_stats
from torchdistpackage_tpu.parallel import DataParallel
from torchdistpackage_tpu.parallel.moe import moe_grad_reduce_overrides

SMOKE = bool(os.environ.get("TDP_SMOKE"))


def main():
    setup_distributed()
    ndev = len(jax.devices())
    # all devices on the data axis; the moe view splits it into
    # moe_dp x moe_ep with EP innermost (ICI-adjacent), the reference's
    # contiguous-EP layout (process_topo.py:118-143)
    tpc.setup_process_groups([("data", ndev)])
    ep = min(4, ndev) if ndev > 1 else 1
    tpc.build_moe_mesh(moe_ep_size=ep)
    mesh = tpc.get_view("moe")

    cfg = GPTConfig(
        vocab_size=512,
        dim=128,
        nheads=4,
        nlayers=4,
        max_seq=256,
        ffn_mult=2,
        moe_experts=8,
        moe_top_k=2,
        moe_every=2,  # expert FFN on blocks 1 and 3
        moe_aux_weight=1e-2,
    )
    steps = 3 if SMOKE else 20
    B = max(8, ndev)

    params = init_gpt_moe_params(jax.random.PRNGKey(0), cfg)
    specs = gpt_moe_param_specs(cfg, tp_axis=None, ep_axis="moe_ep")
    opt = optax.adam(1e-3)

    dp = DataParallel(
        mesh=mesh,
        axis=("moe_dp", "moe_ep"),
        grad_reduce_overrides=moe_grad_reduce_overrides(),
    )
    sharded = dp.broadcast_params(params, param_specs=specs)
    state = opt.init(sharded)
    step = dp.make_train_step(
        lambda p, b: gpt_moe_loss(p, b, cfg, ep_axis="moe_ep"),
        opt,
        param_specs=specs,
        batch_spec={
            "tokens": P(("moe_dp", "moe_ep")),
            "targets": P(("moe_dp", "moe_ep")),
        },
    )

    # mesh=the moe VIEW: the comm ledger classifies the EP all_to_all by
    # the ('moe_dp', 'moe_ep') axes, which the base ('data',) mesh can't see
    tel = Telemetry(run="train_moe", tokens_per_step=B * cfg.max_seq, mesh=mesh)
    step = tel.wrap_step(step)
    bsh = NamedSharding(mesh, P(("moe_dp", "moe_ep")))
    losses = []
    for i in range(steps):
        k1, _ = jax.random.split(jax.random.PRNGKey(100 + i))
        tokens = jax.random.randint(k1, (B, cfg.max_seq), 0, cfg.vocab_size)
        # copy task: target[i] = tokens[i-1] — needs attention through the
        # expert blocks, so the loss decrease exercises real routing
        targets = jnp.concatenate([tokens[:, :1], tokens[:, :-1]], axis=1)
        batch = jax.device_put({"tokens": tokens, "targets": targets}, bsh)
        sharded, state, loss = step(sharded, state, batch)
        rec = tel.end_step(step=i, loss=loss)
        losses.append(rec["loss"])
        print(f"step {i}: loss={losses[-1]:.4f}  (experts={cfg.moe_experts}, ep={ep})")

    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], "training must reduce the loss"

    # observability pass on the trained router: serial forward (global
    # arrays, ep_axis=None) collecting per-expert token counts -> the
    # expert-load imbalance counter in RUNREPORT.json
    _, _, router = jax.jit(
        lambda p, t: gpt_moe_forward(p, t, cfg, collect_metrics=True)
    )(sharded, tokens)
    stats = moe_load_stats(
        np.asarray(router["expert_tokens"]),
        dropped_rate=float(router["dropped_token_rate"]),
    )
    stats["router_entropy"] = float(router["router_entropy"])
    tel.record_counters(moe=stats)

    # --- auto-sharding planner phase (docs/autoplan.md, PR 18): the
    # hand-picked EP split above is exactly the decision the planner now
    # makes for MoE configs — ep arms over divisors of dp that divide E,
    # activated-FLOP pricing (top_k·cf/E per expert leaf), the ep-axis
    # all_to_all comm term, and the expert stacks' residency at each EP
    # sharding judged by MemoryModel before any compile.  Prove the
    # chosen plan compiles and trains via plain GSPMD (XLA derives the
    # dispatch all_to_all from the ep-sharded expert specs).
    from torchdistpackage_tpu.dist import autoplan

    presult = autoplan.plan(
        cfg, ndev, global_batch=B, seq_len=cfg.max_seq,
        executable_only=True, device_kind=jax.devices()[0].device_kind)
    chosen = presult["chosen"]
    assert chosen is not None, "no MoE plan fits this host's memory budget"
    eps = sorted({c.get("ep", 1) for c in presult["ranked"]})
    print(f"autoplan: chose {chosen['key']} of "
          f"{presult['n_candidates']} candidates (ep arms {eps}, "
          f"{presult['n_pruned_oom']} pruned OOM), modeled step "
          f"{chosen['step_s'] * 1e3:.3f} ms")
    pmesh = autoplan.build_mesh(chosen)
    pspecs = autoplan.plan_param_specs(chosen, cfg)
    pparams = jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(pmesh, s)),
        init_gpt_moe_params(jax.random.PRNGKey(7), cfg), pspecs)
    popt = optax.adam(1e-3)
    pstate = jax.device_put(popt.init(pparams), NamedSharding(pmesh, P()))
    pbatch = jax.device_put(
        {"tokens": tokens, "targets": targets},
        NamedSharding(pmesh, autoplan.batch_partition_spec(chosen)))

    @jax.jit
    def plan_step(p, s, b):
        loss, grads = jax.value_and_grad(
            lambda p_: gpt_moe_loss(p_, b, cfg))(p)
        updates, s = popt.update(grads, s)
        return jax.tree.map(jnp.add, p, updates), s, loss

    plosses = []
    for _ in range(3):
        pparams, pstate, ploss = plan_step(pparams, pstate, pbatch)
        plosses.append(float(ploss))
    assert np.isfinite(plosses).all(), plosses
    assert plosses[-1] < plosses[0], f"planned MoE layout failed to train: {plosses}"
    print(f"autoplan: plan {chosen['key']} trains "
          f"(loss {plosses[0]:.4f} -> {plosses[-1]:.4f})")
    tel.record_autoplan(presult)

    report = tel.finalize()
    assert report["autoplan"]["chosen"]["key"] == chosen["key"]
    print(
        f"expert load: imbalance={stats['imbalance']:.3f} "
        f"entropy={stats['load_entropy']:.3f} "
        f"dropped={stats['dropped_token_rate']:.3f}"
    )
    # each device holds only num_experts/ep experts' weights
    w1 = sharded["blocks"][1]["moe"]["experts"]["w1"]
    local_experts = w1.addressable_shards[0].data.shape[0]
    print(
        f"trained {cfg.moe_experts}-expert MoE GPT over moe_dp={ndev//ep} x "
        f"moe_ep={ep}: loss {losses[0]:.4f} -> {losses[-1]:.4f}; "
        f"experts resident per device: {local_experts}"
    )


if __name__ == "__main__":
    main()
