"""ZeRO-1/2 optimizer-state sharding — analogue of ``Bf16ZeroOptimizer``
(``torchdistpackage/ddp/zero_optim.py``, 318 LoC), including the hybrid
intra-node variant (``dist/node_group.py`` + Intro.md:69-77).

The reference greedily partitions params across the dp group
(zero_optim.py:19-41), keeps fp32 masters of the own shard only
(zero_optim.py:159-170), ``dist.reduce``-es each grad to its owner
(zero_optim.py:203) or flat-buckets + all-reduces on a side stream, and
"all-gathers" updated params as per-param broadcasts from the owner
(zero_optim.py:280-287 — its known perf weak point).

TPU-native design: **per-leaf sharding instead of greedy per-rank
partitioning.**  Every param leaf gets a *zero spec* — its TP PartitionSpec
with the shard axis inserted on the first free, divisible dimension.  The
compiled step then:

- ``psum_scatter``-s grads over the shard axis straight to their owner shard
  (one fused reduce+scatter vs the reference's per-param reduce-to-owner),
- updates the fp32 master shard and inner-optimizer state shard locally
  inside ``shard_map``,
- casts masters to the training dtype *then* reshards them to the param
  sharding via ``with_sharding_constraint`` — XLA emits the param all-gather
  (in bf16, half the bytes) and schedules/overlaps it, replacing the
  reference's per-param owner broadcasts.

ZeRO-2 grad sharding falls out: the post-reduce grad only exists as the local
shard, and the optimizer update touches 1/N of the state.  Hybrid ZeRO = pass
``shard_axis='data_intra'`` on a hybrid mesh view
(``tpc.build_hybrid_mesh``): state shards over the ICI-local sub-axis while
grads still average over the whole data group, exactly the reference's trick
that keeps the param all-gather off the slow cross-node links.

Composes with TP transparently: zero specs start from the TP specs, and all
shard-level math runs on local arrays.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple, Union

import jax

from ..compat import axis_size
import jax.numpy as jnp
from ..compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..dist.topology import DATA_AXIS, tpc
from .data_parallel import (
    _vaxes,
    _vma,
    local_value_and_grad,
    normalize_model_axis_grads,
    pvary_params,
)

PyTree = Any
AxisName = Union[str, Tuple[str, ...]]


def _norm_spec(spec: Optional[P], ndim: int) -> Tuple:
    entries = tuple(spec) if spec is not None else ()
    return entries + (None,) * (ndim - len(entries))


def zero_partition_spec(
    shape: Tuple[int, ...],
    spec: Optional[P],
    axis: str,
    axis_size: int,
) -> Tuple[P, Optional[int]]:
    """Insert ``axis`` into ``spec`` on the first free dim divisible by
    ``axis_size``.  Returns (new_spec, shard_dim) — shard_dim is ``-1`` when
    the leaf stays replicated (no divisible free dim; e.g. tiny LN params —
    the same leaves the reference's greedy numel partition would place whole,
    zero_optim.py:19-41)."""
    entries = list(_norm_spec(spec, len(shape)))
    for d, (size, used) in enumerate(zip(shape, entries)):
        if used is None and size % axis_size == 0 and size > 0:
            entries[d] = axis
            while entries and entries[-1] is None:
                entries.pop()
            return P(*entries), d
    return spec if spec is not None else P(), -1


class ZeroOptimizer:
    """Wrap an optax optimizer with ZeRO-style sharded state.

    Usage::

        zero = ZeroOptimizer(optax.adam(3e-4))          # shard over 'data'
        params = zero.place_params(params)               # bf16, TP/replicated
        state = zero.init(params)                        # fp32 masters, sharded
        step = zero.make_train_step(loss_fn)
        params, state, loss = step(params, state, batch)

    Hybrid: build ``tpc.build_hybrid_mesh(intra)`` and pass
    ``mesh=view, shard_axis='data_intra',
    grad_reduce_axes=('data_inter', 'data_intra')``.
    """

    def __init__(
        self,
        inner,
        mesh: Optional[Mesh] = None,
        shard_axis: str = DATA_AXIS,
        grad_reduce_axes: Optional[Tuple[str, ...]] = None,
        param_specs: Optional[PyTree] = None,
        param_dtype: Any = None,
        master_dtype: Any = jnp.float32,
        grad_reduce_overrides: Optional[dict] = None,
        grad_compress: Optional[str] = None,
        compress_min_size: int = 65536,
        comm_model: Optional[Any] = None,
        gather_compress: Union[str, None] = "follow",
    ) -> None:
        self.inner = inner
        self.mesh = mesh if mesh is not None else tpc.get_view()
        self.shard_axis = shard_axis
        if grad_reduce_axes is None:
            grad_reduce_axes = (shard_axis,)
        if shard_axis not in grad_reduce_axes:
            raise ValueError(
                f"shard_axis {shard_axis!r} must be one of grad_reduce_axes {grad_reduce_axes}"
            )
        self.grad_reduce_axes = tuple(grad_reduce_axes)
        # ``{name_substring: axes}`` like DataParallel's (reduce_gradients
        # docstring): matching leaves psum over THESE axes only, normalized
        # by the FULL data-group size (the MoE-DP expert semantics — the
        # all_to_all transpose already summed over EP).  ZeRO additionally
        # needs each override to still contain ``shard_axis`` so the grad
        # can psum_scatter to its owner master shard.
        self.grad_reduce_overrides = dict(grad_reduce_overrides or {})
        for tok, ax in self.grad_reduce_overrides.items():
            if shard_axis not in tuple(ax):
                raise ValueError(
                    f"grad_reduce_overrides[{tok!r}]={tuple(ax)} must contain "
                    f"shard_axis {shard_axis!r}: ZeRO owners are shards of "
                    f"that axis (for MoE, shard over 'moe_dp' — the axis "
                    f"expert grads reduce on)"
                )
            extra = set(ax) - set(self.grad_reduce_axes)
            if extra:
                raise ValueError(
                    f"grad_reduce_overrides[{tok!r}] axes {sorted(extra)} not "
                    f"in grad_reduce_axes {self.grad_reduce_axes}"
                )
        self.param_specs = param_specs
        self.param_dtype = param_dtype
        self.master_dtype = master_dtype
        # 'int8' swaps the f32 psum_scatter for the int8 ring reduce-scatter
        # (~4x fewer wire bytes on the shard axis; for hybrid layouts the
        # cross-node psum over the remaining grad_reduce_axes rides the int8
        # ring too) on leaves >= compress_min_size elements.  Small and
        # override (MoE expert) leaves keep the exact path.
        # 'int8_ef' additionally carries a per-leaf error-feedback residual
        # in the optimizer state (state['ef']): each step compresses
        # grad + residual and persists the quantization error, so the lossy
        # reduction's bias cancels over steps (dist.compressed.ef_compress).
        # 'auto' decides per leaf from CommModel.predict_compressed and
        # records a compress_policy event at step build.
        if grad_compress not in (None, "int8", "int8_ef", "auto"):
            raise ValueError(
                f"unknown grad_compress {grad_compress!r}; ZeroOptimizer "
                f"supports None, 'int8', 'int8_ef' or 'auto'")
        self.grad_compress = grad_compress
        self.compress_min_size = compress_min_size
        self.comm_model = comm_model
        # The updated masters travel BACK as a param all-gather every step
        # (the regroup below) — as many bytes as the grad reduction itself,
        # so compression that stops at grads caps out around 1.6x on the
        # axis.  'follow' (default) re-gathers the COMPRESSED leaves through
        # the invariance-typed int8 masked-psum gather
        # (dist.compressed.int8_psum_all_gather) whenever grad_compress is
        # active: the wire carries quantized params, masters stay full
        # precision (noise does not accumulate — QAT-style), and the parity
        # harness bounds the drift.  Pass None to keep the exact bf16/f32
        # re-gather.
        if gather_compress not in (None, "int8", "follow"):
            raise ValueError(
                f"unknown gather_compress {gather_compress!r}")
        self.gather_compress = gather_compress

    # ----------------------------------------------------------------- specs

    def _specs_for(self, params: PyTree) -> Tuple[PyTree, PyTree, PyTree]:
        """(param_specs, zero_specs, shard_dims) trees for a params tree."""
        n = self.mesh.shape[self.shard_axis]
        p_specs = (
            self.param_specs
            if self.param_specs is not None
            else jax.tree.map(lambda _: P(), params)
        )
        flat_p, treedef = jax.tree_util.tree_flatten(params)
        flat_s = treedef.flatten_up_to(p_specs)
        pairs = [zero_partition_spec(x.shape, s, self.shard_axis, n) for x, s in zip(flat_p, flat_s)]
        zero_specs = jax.tree_util.tree_unflatten(treedef, [sp for sp, _ in pairs])
        shard_dims = jax.tree_util.tree_unflatten(treedef, [d for _, d in pairs])
        return p_specs, zero_specs, shard_dims

    def _local_shape(self, x, spec) -> jax.ShapeDtypeStruct:
        entries = _norm_spec(spec, x.ndim)
        shp = list(x.shape)
        for d, e in enumerate(entries):
            if e is None:
                continue
            axes = (e,) if isinstance(e, str) else tuple(e)
            for a in axes:
                shp[d] //= self.mesh.shape[a]
        return jax.ShapeDtypeStruct(tuple(shp), self.master_dtype)

    def _state_specs_from(self, params: PyTree, zero_specs: PyTree) -> PyTree:
        """Specs for the inner optimizer state, resolved structurally via
        ``optax.tree_map_params``: param-shaped state leaves (adam's mu/nu...)
        inherit the corresponding master's zero spec; everything else (count
        scalars etc.) replicates."""
        import optax

        local_master = jax.tree.map(self._local_shape, params, zero_specs)
        state_shape = jax.eval_shape(self.inner.init, local_master)
        return optax.tree_map_params(
            self.inner,
            lambda _leaf, spec: spec,
            state_shape,
            zero_specs,
            transform_non_params=lambda _: P(),
        )

    # ------------------------------------------------------------- placement

    def place_params(self, params: PyTree) -> PyTree:
        """Cast to the training dtype (bf16 flow of zero_optim.py:7-13) and
        place with the param (TP) sharding."""
        p_specs, _, _ = self._specs_for(params)
        dt = self.param_dtype

        def put(x, s):
            x = x.astype(dt) if dt is not None else x
            return jax.device_put(x, NamedSharding(self.mesh, s))

        return jax.tree.map(put, params, p_specs)

    def _ef_specs(self, p_specs: PyTree) -> PyTree:
        """Specs for the error-feedback residuals: per-DEVICE-of-the-data-
        group values of the leaf's LOCAL (TP-sharded) shape — stored with a
        leading dim of the data-group size sharded over
        ``grad_reduce_axes`` (local view: ``[1, *local_leaf]``)."""
        axes = tuple(self.grad_reduce_axes)
        return jax.tree.map(
            lambda s: P(axes, *tuple(s)), p_specs,
            is_leaf=lambda x: isinstance(x, P))

    def init(self, params: PyTree) -> PyTree:
        """Create sharded fp32 masters + inner optimizer state
        (zero_optim.py:159-174 analogue, sharded by construction).  With
        ``grad_compress='int8_ef'`` the state additionally carries ``ef``
        — one zero-initialized f32 residual per leaf (full leaf shape per
        data-group member; the input-side error-feedback memory
        :meth:`reduce_grads_to_shard` updates every step)."""
        p_specs, zero_specs, _ = self._specs_for(params)
        mdt = self.master_dtype

        master = jax.jit(
            lambda p: jax.tree.map(lambda x: x.astype(mdt), p),
            out_shardings=jax.tree.map(lambda s: NamedSharding(self.mesh, s), zero_specs),
        )(params)

        # build the inner state on *local* shard shapes inside shard_map so
        # leaf shapes match what update() will see
        inner_state = jax.jit(
            shard_map(
                self.inner.init,
                mesh=self.mesh,
                in_specs=(zero_specs,),
                out_specs=self._state_specs_from(params, zero_specs),
            )
        )(master)
        state = {"master": master, "inner": inner_state}
        if self.grad_compress == "int8_ef":
            ndev = 1
            for a in self.grad_reduce_axes:
                ndev *= int(self.mesh.shape[a])
            ef = jax.tree.map(
                lambda x, s: jax.device_put(
                    jnp.zeros((ndev,) + tuple(jnp.shape(x)), jnp.float32),
                    NamedSharding(self.mesh, P(tuple(self.grad_reduce_axes),
                                               *tuple(s)))),
                params, p_specs,
            )
            state["ef"] = ef
        return state

    # ------------------------------------------------------------ traced core

    def _compress_decisions(self, params: PyTree, shard_dims: PyTree):
        """Host-side per-leaf compress/exact choices (shapes are static):
        ``(policy {name: bool}, auto records or None)``.  Override (MoE
        expert) and replicated (no divisible dim) leaves never compress;
        'int8'/'int8_ef' apply the size threshold; 'auto' scores the
        shard-axis reduce-scatter through ``CommModel.predict_compressed``
        (``dist.compressed.auto_compress_policy``)."""
        from .data_parallel import _key_str

        if self.grad_compress is None:
            return {}, None
        flat = jax.tree_util.tree_flatten_with_path(params)[0]
        d_flat = jax.tree_util.tree_leaves(shard_dims)
        itemsize = jnp.dtype(self.master_dtype).itemsize
        policy: dict = {}
        eligible = []
        for (path, x), d in zip(flat, d_flat):
            name = _key_str(path)
            matched = any(tok in name for tok in self.grad_reduce_overrides)
            if matched or d < 0:
                policy[name] = False
                continue
            eligible.append((name, tuple(jnp.shape(x)), itemsize))
        if self.grad_compress == "auto":
            from ..dist.compressed import auto_compress_policy

            pol, records = auto_compress_policy(
                eligible, "reduce_scatter", (self.shard_axis,), self.mesh,
                model=self.comm_model, min_size=self.compress_min_size)
            policy.update(pol)
            return policy, records
        for name, shape, _ in eligible:
            size = 1
            for s in shape:
                size *= int(s)
            policy[name] = size >= self.compress_min_size
        return policy, None

    def reduce_grads_to_shard(
        self,
        grads_local: PyTree,
        shard_dims: PyTree,
        policy: Optional[dict] = None,
        ef: Optional[PyTree] = None,
    ):
        """Traced: mean-reduce grads over ``grad_reduce_axes`` delivering only
        the owner shard (fused psum_scatter; the reference's reduce-to-owner,
        zero_optim.py:203).

        Override leaves (``grad_reduce_overrides``) psum over their override
        axes only, still normalized by the FULL data-group size — the MoE-DP
        expert semantics (see :func:`..data_parallel.reduce_gradients`).

        ``grad_compress``: compressed leaves (``policy`` — per-leaf choices
        from :meth:`_compress_decisions`; derived from the size threshold
        when None) replace the f32 ``psum_scatter`` with
        :func:`...dist.compressed.int8_ring_reduce_scatter` (1 int8
        byte/elem on the wire vs 4 — the reduction only ever moves grads
        TOWARD their owner, so no gather leg exists to pay for), and any
        remaining cross-axes (hybrid's ``data_inter`` — the DCN leg) ride
        :func:`...dist.compressed.int8_ring_pmean`.

        ``ef`` (the 'int8_ef' path): a per-leaf residual tree — each
        compressed leaf reduces ``Q(grad + residual)`` and the new
        residual (the quantization error, ``dist.compressed.ef_compress``)
        is returned: ``(grads_shard, new_ef)`` instead of the bare tree.
        """
        from .data_parallel import _key_str

        if policy is None:
            policy, _ = self._compress_decisions(grads_local, shard_dims)

        n = axis_size(self.shard_axis)
        total = n
        for a in self.grad_reduce_axes:
            if a != self.shard_axis:
                total *= axis_size(a)

        flat = jax.tree_util.tree_flatten_with_path(grads_local)
        paths_leaves, treedef = flat
        d_flat = jax.tree_util.tree_leaves(shard_dims)
        e_flat = (
            jax.tree_util.tree_leaves(ef) if ef is not None
            else [None] * len(d_flat)
        )

        out_leaves, ef_leaves = [], []
        for (path, g), d, e in zip(paths_leaves, d_flat, e_flat):
            g = g.astype(self.master_dtype)
            axes = self.grad_reduce_axes
            matched = False
            name = _key_str(path)
            for tok, ax in self.grad_reduce_overrides.items():
                if tok in name:
                    axes = tuple(ax)
                    matched = True
                    break
            other = tuple(a for a in axes if a != self.shard_axis)
            compress = bool(policy.get(name, False))
            if d < 0:  # replicated leaf
                vaxes = _vaxes(g, axes)
                if matched:
                    # override semantics: full-group mean (EP overcount)
                    g = (jax.lax.psum(g, vaxes) if vaxes else g) / total
                else:
                    g = jax.lax.pmean(g, vaxes) if vaxes else g
                out_leaves.append(g)
                ef_leaves.append(e)
                continue
            if compress:
                from ..dist.compressed import (
                    ef_compress,
                    int8_ring_pmean,
                    int8_ring_reduce_scatter,
                )

                if e is not None:
                    # input-side error feedback: compress grad + carried
                    # residual, persist this step's quantization error
                    g, e = ef_compress(g + e)
                g = int8_ring_reduce_scatter(g, self.shard_axis, d)
            else:
                g = jax.lax.psum_scatter(
                    g, self.shard_axis, scatter_dimension=d, tiled=True)
            o = _vaxes(g, other)
            if o:
                if compress:
                    for a in o:
                        # the ring pmean's mean * size == the psum, with the
                        # int8 wire (the hybrid DCN leg)
                        g = int8_ring_pmean(g, a) * axis_size(a)
                else:
                    g = jax.lax.psum(g, o)
            out_leaves.append(g / total)
            ef_leaves.append(e)

        out = jax.tree_util.tree_unflatten(treedef, out_leaves)
        if ef is None:
            return out
        return out, jax.tree_util.tree_unflatten(treedef, ef_leaves)

    def apply_gradients(
        self,
        grads_shard: PyTree,
        state_local: PyTree,
    ) -> Tuple[PyTree, PyTree]:
        """Traced: inner optimizer step on the local master shard.  Returns
        (new_master_local, new_state_local)."""
        master = state_local["master"]
        updates, inner_state = self.inner.update(grads_shard, state_local["inner"], master)
        master = jax.tree.map(jnp.add, master, updates)
        return master, {"master": master, "inner": inner_state}

    # ------------------------------------------------------------ train step

    def make_train_step(
        self,
        loss_fn: Optional[Callable[[PyTree, PyTree], jnp.ndarray]] = None,
        grad_accum_iters: int = 1,
        batch_spec: Optional[PyTree] = None,
        donate: bool = True,
        value_and_grad_fn: Optional[Callable] = None,
        accum_reduce: str = "final",
    ):
        """Jitted SPMD train step with the ZeRO update.  ``loss_fn`` sees the
        local batch shard, as in :class:`DataParallel`.

        ``value_and_grad_fn(params, batch) -> (loss, grads)`` replaces
        ``loss_fn`` for schedules whose backward cannot be expressed as outer
        AD — the 1F1B pipeline (``pipeline_parallel.pipeline_1f1b`` /
        ``gpt_pipeline_1f1b``) interleaves its backward with its forward
        inside one scan.  This is what makes the north-star composition
        (hybrid ZeRO × 1F1B × TP × DP, the reference's zero_optim.py:98-287
        under Readme.md:56's PP+DP recipe) buildable: the pipeline produces
        the local grads, ZeRO scatters them to owner shards and updates the
        sharded fp32 masters exactly as in the loss_fn path.

        ``accum_reduce='microbatch'`` (overlap path; loss_fn + grad_accum
        only): the owner psum_scatter runs per microbatch INSIDE the
        accumulation scan — ZeRO-2's per-bucket reduce-scatter during the
        backward, overlapping the next microbatch's compute — and the
        accumulator holds only the 1/N grad shard instead of the full
        tree (the grad-memory win that lets accumulation scale).  Exact
        (the scatter is linear); trades ``iters``× the scatter traffic
        for overlap + memory, and composes with ``overlap.configure()``'s
        async-collective presets."""
        if (loss_fn is None) == (value_and_grad_fn is None):
            raise ValueError("pass exactly one of loss_fn / value_and_grad_fn")
        if value_and_grad_fn is not None and grad_accum_iters != 1:
            raise ValueError(
                "grad_accum_iters applies to the loss_fn path only; a "
                "value_and_grad_fn (e.g. pipeline_1f1b) owns its own "
                "microbatching"
            )
        if accum_reduce not in ("final", "microbatch"):
            raise ValueError(
                f"accum_reduce must be 'final' or 'microbatch', got {accum_reduce!r}")
        if (
            self.grad_compress == "int8_ef"
            and accum_reduce == "microbatch"
            and grad_accum_iters > 1
        ):
            # the residual is one-per-STEP state; the microbatch path
            # reduces inside the accumulation scan where the reduce_fn is
            # stateless — silently dropping the feedback would defeat the
            # mode, so the combination is rejected by name
            raise ValueError(
                "grad_compress='int8_ef' does not compose with "
                "accum_reduce='microbatch': the error-feedback residual "
                "updates once per step, but 'microbatch' reduces inside "
                "the accumulation scan; use accum_reduce='final' or "
                "grad_compress='int8'")
        mesh = self.mesh
        data_axes = self.grad_reduce_axes
        ef_mode = self.grad_compress == "int8_ef"

        cache = {}

        def jit_for(params, state, batch):
            from .data_parallel import _key_str, step_cache_key

            key = step_cache_key(params, state, batch)
            if key not in cache:
                p_specs, zero_specs, shard_dims = self._specs_for(params)
                policy, records = self._compress_decisions(params, shard_dims)
                if records is not None:
                    # the 'auto' decision trail: one structured event per
                    # compiled signature (the compression RUNREPORT section
                    # reads it — obs.comm_model.compression_report)
                    from ..obs.events import emit_event

                    emit_event(
                        "compress_policy", family="zero", mode="auto",
                        op="reduce_scatter", axes=[self.shard_axis],
                        n_leaves=len(records),
                        n_compressed=sum(
                            1 for r in records if r["compress"]),
                        leaves=records)
                state_specs = {
                    "master": zero_specs,
                    "inner": self._state_specs_from(params, zero_specs),
                }
                if ef_mode:
                    state_specs["ef"] = self._ef_specs(p_specs)
                in_batch_specs = (
                    batch_spec
                    if batch_spec is not None
                    else jax.tree.map(lambda _: P(data_axes), batch)
                )

                in_scan = (
                    accum_reduce == "microbatch"
                    and value_and_grad_fn is None
                    and grad_accum_iters > 1
                )

                def core(params, state, batch):
                    """shard_map body: local grads -> scatter -> shard update.
                    With accum_reduce='microbatch' the scatter runs inside
                    the accumulation scan (per-bucket reduce-scatter during
                    the backward) and only the shard is accumulated; the
                    post-scan model-axis normalization is a pure scaling,
                    so applying it to the scattered grads is exact."""
                    p_local = pvary_params(params, data_axes)
                    if value_and_grad_fn is not None:
                        loss, grads = value_and_grad_fn(p_local, batch)
                    else:
                        loss, grads = local_value_and_grad(
                            loss_fn, p_local, batch, grad_accum_iters,
                            reduce_fn=(
                                (lambda g: self.reduce_grads_to_shard(
                                    g, shard_dims, policy=policy))
                                if in_scan else None
                            ),
                        )
                    grads, other = normalize_model_axis_grads(
                        loss, grads, mesh, data_axes
                    )
                    new_ef = None
                    if in_scan:
                        g_shard = grads
                    elif ef_mode:
                        # residual leaves are [1, *local_leaf] per device
                        # (leading dim = the data-group member)
                        e_loc = jax.tree.map(lambda r: r[0], state["ef"])
                        g_shard, new_ef = self.reduce_grads_to_shard(
                            grads, shard_dims, policy=policy, ef=e_loc)
                    else:
                        g_shard = self.reduce_grads_to_shard(
                            grads, shard_dims, policy=policy)
                    master, new_state = self.apply_gradients(g_shard, state)
                    if ef_mode:
                        new_state["ef"] = jax.tree.map(
                            lambda r: r[None], new_ef)

                    if other:
                        loss = jax.lax.pmean(loss, other)
                    dax = _vaxes(loss, data_axes)
                    if dax:
                        loss = jax.lax.pmean(loss, dax)
                    return master, new_state, loss

                sm = shard_map(
                    core,
                    mesh=mesh,
                    in_specs=(p_specs, state_specs, in_batch_specs),
                    out_specs=(zero_specs, state_specs, P()),
                )

                # --- the param re-gather: which leaves ride the int8 wire
                # back.  The masters' return trip moves as many bytes as
                # the grad reduction, so ``gather_compress`` (default
                # 'follow') re-gathers the COMPRESSED leaves through the
                # invariance-typed int8 masked-psum gather; masters stay
                # full precision (quantization noise does not accumulate).
                gather_mode = (
                    self.gather_compress if self.gather_compress != "follow"
                    else ("int8" if self.grad_compress is not None else None))
                flat_paths = jax.tree_util.tree_flatten_with_path(params)
                (pl, treedef) = flat_paths
                d_flat = jax.tree_util.tree_leaves(shard_dims)
                mask_leaves = [
                    gather_mode == "int8"
                    and policy.get(_key_str(path), False)
                    and d >= 0
                    for (path, _), d in zip(pl, d_flat)
                ]
                gmask = jax.tree_util.tree_unflatten(treedef, mask_leaves)
                dtype_tree = jax.tree.map(lambda x: x.dtype, params)
                regather_sm = None
                if any(mask_leaves):
                    regather_specs = jax.tree_util.tree_unflatten(
                        treedef,
                        [
                            ps if m else zs
                            for m, ps, zs in zip(
                                mask_leaves,
                                treedef.flatten_up_to(p_specs),
                                treedef.flatten_up_to(zero_specs),
                            )
                        ],
                    )

                    def regather_body(m_tree):
                        from ..dist.compressed import int8_psum_all_gather

                        def g1(m, d, msk, dt):
                            m = m.astype(dt)
                            if msk:
                                return int8_psum_all_gather(
                                    m, self.shard_axis, d)
                            return m

                        return jax.tree.map(
                            g1, m_tree, shard_dims, gmask, dtype_tree)

                    regather_sm = shard_map(
                        regather_body,
                        mesh=mesh,
                        in_specs=(zero_specs,),
                        out_specs=regather_specs,
                    )

                def step(params, state, batch):
                    master, new_state, loss = sm(params, state, batch)
                    # cast to training dtype on the shard, then reshard to the
                    # param placement — XLA emits the (bf16) all-gather, the
                    # analogue of the reference's param broadcast
                    # (zero_optim.py:280-287) as one overlappable collective;
                    # compressed leaves instead ride the explicit int8
                    # masked-psum gather built above.
                    gathered = (
                        regather_sm(master) if regather_sm is not None
                        else master)

                    def regroup(m, p, zs, ps, msk):
                        if msk:
                            return m  # already full + param-placed (int8)
                        m = m.astype(p.dtype)
                        m = jax.lax.with_sharding_constraint(m, NamedSharding(mesh, zs))
                        return jax.lax.with_sharding_constraint(m, NamedSharding(mesh, ps))

                    new_params = jax.tree.map(
                        regroup, gathered, params, zero_specs, p_specs, gmask)
                    return new_params, new_state, loss

                cache[key] = jax.jit(step, donate_argnums=(0, 1) if donate else ())
            return cache[key]

        def jitted(params, state, batch):
            return jit_for(params, state, batch)(params, state, batch)

        # AOT hook (the Telemetry/bench contract): lower the SAME cached
        # jit so ledgers/cost analysis see exactly the step being run
        jitted.lower = lambda p, s, b: jit_for(p, s, b).lower(p, s, b)
        return jitted
